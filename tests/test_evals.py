"""Eval drivers: linear probe mechanics/semantics + full kNN eval
(BASELINE config 4; `main_lincls.py` rebuild)."""

import jax
import numpy as np
import optax
import pytest

from moco_tpu.checkpoint import export_encoder_q
from moco_tpu.config import EvalConfig
from moco_tpu.evals.knn import run_knn
from moco_tpu.evals.lincls import load_frozen_backbone, train_lincls
from moco_tpu.models.resnet import ResNetTiny
from moco_tpu.train_state import create_train_state


@pytest.fixture(scope="module")
def exported_ckpt(tmp_path_factory):
    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    path = str(tmp_path_factory.mktemp("ckpt") / "encoder.safetensors")
    export_encoder_q(state, path)
    return path


def eval_config(path, **kw):
    base = dict(
        arch="resnet_tiny", pretrained=path, dataset="synthetic",
        image_size=16, cifar_stem=True, num_classes=10, batch_size=64,
        epochs=1, lr=1.0, print_freq=4,
    )
    base.update(kw)
    return EvalConfig().replace(**base)


def test_load_frozen_backbone_surgery(exported_ckpt):
    config = eval_config(exported_ckpt)
    model, params, stats = load_frozen_backbone(config)
    assert "fc" not in params
    assert "conv1" in params and "layer1_0" in params
    assert stats["bn1"]["mean"].shape == (16,)


def test_load_frozen_backbone_arch_mismatch(exported_ckpt):
    config = eval_config(exported_ckpt, arch="resnet18")
    with pytest.raises(ValueError, match="surgery mismatch"):
        load_frozen_backbone(config)


@pytest.mark.slow
def test_lincls_end_to_end(mesh8, exported_ckpt):
    """Probe on RANDOM frozen features of clusterable data still beats
    chance (random projections are linearly separable enough), proving the
    whole train/validate/sanity-check path."""
    config = eval_config(exported_ckpt)
    fc, best_acc1 = train_lincls(config, mesh8, max_steps=24)
    assert np.isfinite(best_acc1)
    assert best_acc1 > 15.0, f"probe top-1 {best_acc1} not above 10% chance"
    assert fc["w"].shape == (32, 10)


@pytest.mark.slow
def test_knn_eval_end_to_end(exported_ckpt):
    config = eval_config(exported_ckpt, knn_k=20)
    acc = run_knn(config)
    assert acc > 0.15, f"kNN top-1 {acc} not above chance"
