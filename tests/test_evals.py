"""Eval drivers: linear probe mechanics/semantics + full kNN eval
(BASELINE config 4; `main_lincls.py` rebuild)."""

import jax
import numpy as np
import optax
import pytest

from moco_tpu.checkpoint import export_encoder_q
from moco_tpu.config import EvalConfig
from moco_tpu.evals.knn import run_knn
from moco_tpu.evals.lincls import load_frozen_backbone, train_lincls
from moco_tpu.models.resnet import ResNetTiny
from moco_tpu.train_state import create_train_state


@pytest.fixture(scope="module")
def exported_ckpt(tmp_path_factory):
    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    path = str(tmp_path_factory.mktemp("ckpt") / "encoder.safetensors")
    export_encoder_q(state, path)
    return path


def eval_config(path, **kw):
    base = dict(
        arch="resnet_tiny", pretrained=path, dataset="synthetic",
        image_size=16, cifar_stem=True, num_classes=10, batch_size=64,
        epochs=1, lr=1.0, print_freq=4, ckpt_dir="",
    )
    base.update(kw)
    return EvalConfig().replace(**base)


def test_load_frozen_backbone_surgery(exported_ckpt):
    config = eval_config(exported_ckpt)
    model, params, stats = load_frozen_backbone(config)
    assert "fc" not in params
    assert "conv1" in params and "layer1_0" in params
    assert stats["bn1"]["mean"].shape == (16,)


def test_load_frozen_backbone_arch_mismatch(exported_ckpt):
    config = eval_config(exported_ckpt, arch="resnet18")
    with pytest.raises(ValueError, match="surgery mismatch"):
        load_frozen_backbone(config)


@pytest.mark.slow
def test_lincls_end_to_end(mesh8, exported_ckpt):
    """Probe on RANDOM frozen features of clusterable data still beats
    chance (random projections are linearly separable enough), proving the
    whole train/validate/sanity-check path."""
    config = eval_config(exported_ckpt)
    fc, best_acc1 = train_lincls(config, mesh8, max_steps=24)
    assert np.isfinite(best_acc1)
    assert best_acc1 > 15.0, f"probe top-1 {best_acc1} not above 10% chance"
    assert fc["w"].shape == (32, 10)


@pytest.mark.slow
def test_knn_eval_end_to_end(exported_ckpt):
    config = eval_config(exported_ckpt, knn_k=20)
    acc = run_knn(config)
    assert acc > 0.15, f"kNN top-1 {acc} not above chance"


def test_v3_backbone_dialect_roundtrip(tmp_path):
    """v3 export (backbone tree dialect, projector/predictor dropped) loads
    back through the same lincls surgery path — for ResNet AND ViT-style
    backbones (same code path; ResNetTiny keeps the test fast)."""
    from moco_tpu.checkpoint import export_v3_backbone, flatten_tree, unflatten_tree
    from moco_tpu.v3_step import V3Model, create_v3_train_state

    model = V3Model(
        ResNetTiny(num_classes=None, cifar_stem=True), embed_dim=16, hidden_dim=32
    )
    tx = optax.sgd(0.1)
    state = create_v3_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3))
    path = str(tmp_path / "v3_backbone.safetensors")
    flat = export_v3_backbone(state, path)
    assert all(k.startswith(("backbone/", "backbone_stats/")) for k in flat)
    assert not any("projector" in k or "predictor" in k for k in flat)

    config = eval_config(path)
    m, params, stats = load_frozen_backbone(config)
    for a, b in zip(
        jax.tree.leaves(params),
        jax.tree.leaves(state.params_q["backbone"]),
        strict=True,
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # unflatten(flatten(x)) == x
    tree = {"a": {"b": np.ones((2, 2)), "c": np.zeros(3)}, "d": np.arange(4)}
    back = unflatten_tree(flatten_tree(tree))
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(back),
        jax.tree_util.tree_leaves_with_path(tree),
    ):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_lincls_checkpoint_resume(mesh8, exported_ckpt, tmp_path):
    """Probe checkpointing + --resume auto (the reference's main_lincls
    saves fc/optimizer/epoch/best every epoch)."""
    cfg = eval_config(exported_ckpt, ckpt_dir=str(tmp_path / "probe"), epochs=2)
    fc1, best1 = train_lincls(cfg, mesh8, max_steps=32)
    import os

    steps = sorted(int(d) for d in os.listdir(tmp_path / "probe"))
    assert steps, "no probe checkpoints written"
    # resume: continues PAST the first run's last checkpoint (a restore
    # that silently restarted from scratch would stop at the same step)
    cfg2 = cfg.replace(resume="auto", epochs=3)
    fc2, best2 = train_lincls(cfg2, mesh8, max_steps=96)
    steps2 = sorted(int(d) for d in os.listdir(tmp_path / "probe"))
    assert max(steps2) > max(steps), (steps, steps2)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="requires a ckpt_dir"):
        train_lincls(cfg.replace(ckpt_dir="", resume="auto"), mesh8, max_steps=1)


@pytest.mark.slow
def test_lincls_evaluate_only(mesh8, exported_ckpt, tmp_path):
    """--evaluate (reference -e): validate the resumed probe, no training —
    the returned acc matches the training run's last validation, and the
    classifier is untouched."""
    cfg = eval_config(exported_ckpt, ckpt_dir=str(tmp_path / "probe"), epochs=1)
    fc_trained, best = train_lincls(cfg, mesh8, max_steps=32)
    fc_eval, acc = train_lincls(
        cfg.replace(resume="auto", evaluate=True), mesh8
    )
    assert acc == pytest.approx(best, abs=1e-6)
    for a, b in zip(jax.tree.leaves(fc_trained), jax.tree.leaves(fc_eval),
                    strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_val_split_preserves_synthetic_texture_kind():
    """The synthetic val split must be the SAME dataset kind as training:
    a synthetic_texture probe validated on SyntheticDataset images scores
    the head against labels from a different generator (below-chance val
    with near-perfect train — the on-chip r5 signature,
    runs/lincls_tpu_r5.log). Class tiles are fixed across seeds, so a
    held-out texture instance shares the train classes."""
    import numpy as np

    from moco_tpu.config import get_preset
    from moco_tpu.data.datasets import SyntheticTextureDataset
    from moco_tpu.evals.lincls import _val_split

    # the dangerous default: imagenet-lincls leaves num_classes at 1000,
    # but the train split is built with the dataset's own default class
    # count — the val label space must follow the TRAIN SET, not config
    cfg = get_preset("imagenet-lincls").replace(
        dataset="synthetic_texture", image_size=32)
    train = SyntheticTextureDataset(num_samples=64, image_size=32, seed=0)
    val = _val_split(cfg, train)
    assert isinstance(val, SyntheticTextureDataset)
    assert val.num_classes == train.num_classes == 16

    # same class tiles across seeds (the fixed-tile-seed contract)
    np.testing.assert_array_equal(
        np.asarray(train.class_tiles), np.asarray(val.class_tiles))

    # non-default class count follows the train set too
    train24 = SyntheticTextureDataset(num_samples=48, image_size=32,
                                      num_classes=24, seed=0)
    assert _val_split(cfg, train24).num_classes == 24
