import numpy as np

from moco_tpu.ops.schedules import cosine_lr, step_lr, warmup_cosine_lr


def test_cosine_endpoints():
    assert np.isclose(float(cosine_lr(0.03, 0, 200)), 0.03)
    assert np.isclose(float(cosine_lr(0.03, 100, 200)), 0.015)
    assert np.isclose(float(cosine_lr(0.03, 200, 200)), 0.0, atol=1e-9)


def test_step_schedule_reference_defaults():
    # reference defaults: --lr 0.03 --schedule 120 160
    assert np.isclose(float(step_lr(0.03, 0, (120, 160))), 0.03)
    assert np.isclose(float(step_lr(0.03, 119, (120, 160))), 0.03)
    assert np.isclose(float(step_lr(0.03, 120, (120, 160))), 0.003)
    assert np.isclose(float(step_lr(0.03, 160, (120, 160))), 0.0003)


def test_warmup_cosine():
    assert np.isclose(float(warmup_cosine_lr(1.0, 0, 300, 40)), 0.0)
    assert np.isclose(float(warmup_cosine_lr(1.0, 20, 300, 40)), 0.5)
    assert np.isclose(float(warmup_cosine_lr(1.0, 40, 300, 40)), 1.0)
    assert float(warmup_cosine_lr(1.0, 300, 300, 40)) < 1e-6
