"""Driver-level coverage for paths the main smoke test doesn't touch:
the v3 variant through train() (composite state, symmetric step, momentum
metric, backbone export) and the ImageFolder real-data path (JPEG decode →
staging → on-device aug → SPMD step)."""

import os

import numpy as np
import pytest

from moco_tpu.config import get_preset
from moco_tpu.train import train


@pytest.mark.slow
def test_v3_through_driver(mesh8, tmp_path):
    config = get_preset("imagenet-moco-v3-vits").replace(
        arch="resnet_tiny",            # v3 supports ResNet backbones (paper R50 recipe)
        cifar_stem=True,
        embed_dim=16,
        dataset="synthetic",
        image_size=16,
        batch_size=32,
        lr=1e-3,
        epochs=2,
        warmup_epochs=1,
        steps_per_epoch=8,
        compute_dtype="float32",
        knn_monitor=True,
        ckpt_dir=str(tmp_path / "ckpt"),
        export_path=str(tmp_path / "v3_backbone.safetensors"),
        print_freq=4,
        num_classes=10,
    )
    state, metrics = train(config, mesh8)
    assert int(state.step) == 16
    assert np.isfinite(metrics["loss"])
    assert "momentum" in metrics  # the v3 cosine ramp is live
    assert 0.0 < metrics["knn_train_top1"] <= 1.0
    assert state.queue is None
    assert os.path.exists(config.export_path)


@pytest.mark.slow
def test_midepoch_resume_no_replay(mesh8, tmp_path):
    """A checkpoint saved after a mid-epoch max_steps break must resume at
    the NEXT batch of that epoch, not replay the epoch from its start
    (ADVICE r1): an interrupted run continued to step 6 must be bit-identical
    to an uninterrupted 6-step run."""
    import jax

    base = dict(
        arch="resnet_tiny",
        dataset="synthetic",
        image_size=16,
        batch_size=32,
        num_negatives=64,
        embed_dim=16,
        epochs=2,
        steps_per_epoch=4,
        compute_dtype="float32",
        knn_monitor=False,
        print_freq=100,
    )
    uninterrupted = get_preset("cifar10-moco-v1").replace(**base, ckpt_dir="")
    state_a, _ = train(uninterrupted, mesh8, max_steps=6)

    interrupted = get_preset("cifar10-moco-v1").replace(
        **base, ckpt_dir=str(tmp_path / "ckpt")
    )
    state_mid, _ = train(interrupted, mesh8, max_steps=2)  # breaks mid-epoch 0
    assert int(state_mid.step) == 2
    state_b, _ = train(interrupted.replace(resume="auto"), mesh8, max_steps=6)

    assert int(state_a.step) == int(state_b.step) == 6
    for pa, pb in zip(
        jax.tree.leaves(state_a.params_q), jax.tree.leaves(state_b.params_q)
    ):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    np.testing.assert_array_equal(np.asarray(state_a.queue), np.asarray(state_b.queue))


@pytest.mark.slow
def test_imagefolder_through_driver(mesh8, tmp_path):
    """Real-data path: JPEG tree → (native or PIL) staging → device aug →
    step. Images are written per class from distinct base colors so the
    pipeline has real class signal."""
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    root = tmp_path / "data" / "train"
    rng = np.random.RandomState(0)
    colors = [(200, 40, 40), (40, 200, 40), (40, 40, 200)]
    for c, color in enumerate(colors):
        d = root / f"class{c}"
        d.mkdir(parents=True)
        for i in range(12):
            img = np.clip(
                np.array(color)[None, None] + rng.randint(-30, 30, (48, 48, 3)),
                0, 255,
            ).astype(np.uint8)
            Image.fromarray(img).save(str(d / f"{i}.jpg"), quality=90)

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny",
        dataset="imagefolder",
        data_dir=str(tmp_path / "data"),
        image_size=16,
        batch_size=32,
        num_negatives=64,
        embed_dim=16,
        epochs=2,
        steps_per_epoch=None,   # derived: 36 imgs // 32 = 1 step/epoch
        knn_monitor=False,
        ckpt_dir="",
        print_freq=1,
        num_classes=3,
    )
    state, metrics = train(config, mesh8)
    assert int(state.step) == 2
    assert np.isfinite(metrics["loss"])


@pytest.mark.slow
def test_steps_per_epoch_clamped_to_loader(mesh8):
    """A steps_per_epoch above what the dataset can yield used to silently
    truncate epochs (and stretch the lr schedule); it now clamps to the
    loader's real batch count, so configured epochs mean what they say."""
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16,
        batch_size=256, num_negatives=512, embed_dim=16,
        epochs=2, steps_per_epoch=10_000,   # >> 2048/256 = 8 available
        knn_monitor=False, ckpt_dir="", print_freq=100,
    )
    state, _ = train(config, mesh8)
    assert int(state.step) == 2 * 8  # 2 real epochs of the 8 real batches


@pytest.mark.slow
def test_knn_monitor_synthetic_texture_val_split(mesh8):
    """synthetic_texture gets a held-out-seed val split (fixed class tiles
    keep the label space aligned across seeds): the monitor reports real
    val tags plus the untrained baseline row (VERDICT r3 weak #3)."""
    from moco_tpu.data.datasets import SyntheticTextureDataset

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic_texture", image_size=16,
        batch_size=32, num_negatives=64, embed_dim=16, epochs=1,
        knn_monitor=True, knn_bank_size=64, ckpt_dir="", print_freq=1,
        num_classes=4,
    )
    data = SyntheticTextureDataset(num_samples=64, image_size=16,
                                   num_classes=4, seed=0)
    _, metrics = train(config, mesh8, dataset=data)
    assert "knn_val_top1" in metrics and "knn_train_top1" not in metrics
    assert "knn_val_top1_untrained" in metrics
    assert 0.0 <= metrics["knn_val_top1"] <= 1.0


def test_knn_monitor_uses_val_split_when_present(mesh8, tmp_path):
    """With an imagefolder val/ dir the monitor reports a REAL val metric
    (knn_val_top1); without one it holds out train data (knn_train_top1)."""
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    rng = np.random.RandomState(1)
    colors = [(220, 30, 30), (30, 220, 30), (30, 30, 220)]
    for split, count in (("train", 12), ("val", 6)):
        for c, color in enumerate(colors):
            d = tmp_path / "data" / split / f"class{c}"
            d.mkdir(parents=True)
            for i in range(count):
                img = np.clip(
                    np.array(color)[None, None] + rng.randint(-25, 25, (32, 32, 3)),
                    0, 255,
                ).astype(np.uint8)
                Image.fromarray(img).save(str(d / f"{i}.jpg"), quality=90)

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny",
        dataset="imagefolder",
        data_dir=str(tmp_path / "data"),
        image_size=16,
        batch_size=32,
        num_negatives=64,
        embed_dim=16,
        epochs=1,
        knn_monitor=True,
        knn_bank_size=36,
        ckpt_dir="",
        print_freq=1,
        num_classes=3,
    )
    _, metrics = train(config, mesh8)
    assert "knn_val_top1" in metrics and "knn_train_top1" not in metrics
    assert 0.0 <= metrics["knn_val_top1"] <= 1.0

    # a val/ whose class listing differs from train/ would shift every
    # label id — the monitor must refuse it and fall back to the train
    # hold-out (labeled accordingly)
    extra = tmp_path / "data" / "val" / "class_extra"
    extra.mkdir()
    img = np.full((32, 32, 3), 128, np.uint8)
    Image.fromarray(img).save(str(extra / "0.jpg"), quality=90)
    _, metrics = train(config, mesh8)
    assert "knn_train_top1" in metrics and "knn_val_top1" not in metrics
