"""Learning-health observability suite (ISSUE 13).

Layers, bottom-up:

  - unit: the in-graph diagnostic math (telemetry/health.py) —
    embedding std / participation ratio on known distributions, the
    neg-sim/logit-margin fold over both logit layouts, the chaos
    key-encoder crush really degenerating features;
  - sentinel: CollapseSentinel window semantics (full-window violation,
    one incident per excursion, clean-window re-arm, min_step, opt-in
    rollback raising CollapseError);
  - step level (8 fake devices): neg_sim/logit_margin as standard
    metrics in both step builders; health_stride gating (real values
    on-stride, exact zeros off); THE contract — the parameter/queue/
    optimizer trajectory with diagnostics on is BITWISE the trajectory
    with them off;
  - serve: the reload drift guard refusing a collapsed checkpoint
    (CollapsedCheckpointError), recording probe drift on good reloads;
  - acceptance (chaos drill): 30-step CPU train with collapse_at_step=20
    → the stride-sampled emb-std pins the injected collapse, the
    sentinel fires EXACTLY one `health` incident, obsd's shipped
    learning-health rules alert then recover over the run's own records,
    telemetry_report renders the `health:` section, and the collapsed
    final checkpoint is refused by the reload guard.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig, get_preset
from moco_tpu.resilience import (
    ChaosPlan,
    CollapseError,
    CollapseSentinel,
    NonFiniteLossError,
    chaos_context,
)
from moco_tpu.telemetry import health

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RULES_PATH = os.path.join(REPO, "tools", "slo_rules",
                          "learning_health.json")

GLOBAL_B, IMG, DIM, K = 16, 8, 16, 64


# ---------------------------------------------------------------------------
# unit: diagnostic math
# ---------------------------------------------------------------------------


def test_embedding_stats_isotropic_vs_collapsed():
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(size=(256, 16)).astype(np.float32))
    std, pr = health.embedding_stats(z)
    # isotropic gaussian: per-dim std ~1, participation ratio ~D
    assert 0.8 < float(std) < 1.2
    assert 12.0 < float(pr) <= 16.0
    # rank-one collapse: every row on ONE direction (varying magnitude)
    mags = rng.normal(size=(256, 1)).astype(np.float32)
    direction = rng.normal(size=(1, 16)).astype(np.float32)
    _, pr1 = health.embedding_stats(jnp.asarray(mags * direction))
    assert float(pr1) == pytest.approx(1.0, abs=1e-3)
    # rank-zero (constant batch): std exactly 0, pr degrades to 0
    stdc, prc = health.embedding_stats(jnp.ones((64, 16)))
    assert float(stdc) == 0.0 and float(prc) == 0.0


def test_neg_sim_mean_both_logit_layouts():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))
    # v1/v2 layout: positive at column 0
    labels = jnp.zeros((8,), jnp.int32)
    expected = float(np.mean(np.asarray(logits)[:, 1:])) * 0.07
    got = float(health.neg_sim_mean(logits, labels, 0.07))
    assert got == pytest.approx(expected, rel=1e-5)
    # v3 layout: positive on a (shifted) diagonal
    sq = jnp.asarray(rng.normal(size=(6, 6)).astype(np.float32))
    diag = jnp.arange(6, dtype=jnp.int32)
    m = np.asarray(sq)
    expected = float((m.sum() - np.trace(m)) / (6 * 5))
    assert float(health.neg_sim_mean(sq, diag, 1.0)) == pytest.approx(
        expected, rel=1e-5)


def test_grad_group_norms_first_and_last_group():
    grads = {
        "a_stem": {"w": jnp.full((3,), 2.0)},
        "z_head": {"w": jnp.full((4,), 1.0)},
    }
    out = health.grad_group_norms(grads)
    assert float(out["h_gnorm_first"]) == pytest.approx(np.sqrt(12.0))
    assert float(out["h_gnorm_last"]) == pytest.approx(2.0)
    assert float(out["h_gnorm"]) == pytest.approx(np.sqrt(16.0))


def test_crush_key_params_makes_features_input_independent():
    from moco_tpu.models import build_backbone

    model = build_backbone("resnet_tiny", cifar_stem=True)
    variables = model.init(jax.random.key(0), jnp.zeros((1, IMG, IMG, 3)),
                           train=False)
    crushed = health.crush_key_params(variables["params"])
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, IMG, IMG, 3)).astype(np.float32))
    out = model.apply(
        {"params": crushed,
         "batch_stats": variables.get("batch_stats", {})},
        x, train=False)
    # every input maps to ONE constant feature vector
    assert np.allclose(np.asarray(out), np.asarray(out)[0], atol=1e-6)
    std, _ = health.embedding_stats(out)
    assert float(std) < 1e-6


# ---------------------------------------------------------------------------
# CollapseSentinel window semantics
# ---------------------------------------------------------------------------


def _feed(sentinel, values, key="logit_margin", start=1):
    for i, v in enumerate(values):
        sentinel.observe(start + i, {key: v})
    sentinel.flush()


def test_sentinel_fires_once_per_excursion_and_rearms():
    s = CollapseSentinel(3, margin_eps=0.01)
    assert s.armed
    _feed(s, [1.0, 1.0, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0])
    assert len(s.fired) == 1
    (incident,) = s.fired
    assert incident["predicate"] == "margin"
    assert incident["step"] == 6  # the step completing the first bad window
    # a clean window re-arms; a second excursion fires a SECOND incident
    _feed(s, [1.0, 1.0, 1.0, 0.0, 0.0, 0.0], start=9)
    assert len(s.fired) == 2


def test_sentinel_one_healthy_sample_inside_window_rearms():
    s = CollapseSentinel(3, margin_eps=0.01)
    _feed(s, [0.0, 0.0, 1.0, 0.0, 0.0, 1.0, 0.0, 0.0])
    assert s.fired == []


def test_sentinel_min_step_suppresses_warmup():
    s = CollapseSentinel(2, acc1_floor=5.0, min_step=10)
    _feed(s, [0.1, 0.1, 0.1, 0.1], key="acc1", start=1)
    assert s.fired == []  # all inside warmup
    _feed(s, [0.1, 0.1, 0.1], key="acc1", start=11)
    assert len(s.fired) == 1


def test_sentinel_warmup_values_never_fill_the_window():
    """Grace-period observations are DISCARDED, not just muted: warmup
    violations plus ONE bad post-min_step value must not complete a
    window (the window starts filling only after min_step)."""
    s = CollapseSentinel(3, acc1_floor=5.0, min_step=10)
    _feed(s, [0.1] * 8, key="acc1", start=2)   # warmup-era "violations"
    _feed(s, [0.1], key="acc1", start=11)      # first real observation
    assert s.fired == []                       # window 1/3 full, no page


def test_sentinel_emb_std_takes_min_of_q_and_k():
    s = CollapseSentinel(2, emb_std_eps=1e-3)
    # query side healthy, key side collapsed: still collapse
    for i in range(4):
        s.observe(i + 1, {"h_emb_std_q": 0.5, "h_emb_std_k": 0.0})
    s.flush()
    assert len(s.fired) == 1 and s.fired[0]["predicate"] == "emb_std"


def test_sentinel_rollback_raises_collapse_error():
    s = CollapseSentinel(2, margin_eps=0.01, rollback=True)
    with pytest.raises(CollapseError) as e:
        _feed(s, [0.0, 0.0, 0.0])
    assert isinstance(e.value, NonFiniteLossError)  # rides the driver's
    assert e.value.predicate == "margin"            # bounded-rollback path


def test_sentinel_unarmed_when_no_thresholds():
    s = CollapseSentinel(5)
    assert not s.armed
    _feed(s, [0.0] * 20)
    assert s.fired == []


# ---------------------------------------------------------------------------
# step level: standard metrics, stride gating, bitwise trajectory
# ---------------------------------------------------------------------------


def _tiny_v1_config(**overrides):
    base = dict(variant="v1", num_negatives=K, embed_dim=DIM,
                temperature=0.07, lr=0.05, batch_size=GLOBAL_B, epochs=4,
                schedule=(2, 3))
    base.update(overrides)
    return PretrainConfig(**base)


def _build_v1(config, mesh):
    from moco_tpu.models.resnet import BasicBlock, ResNet
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import build_optimizer, build_train_step

    model = ResNet(stage_sizes=(1, 1), block_cls=BasicBlock, width=8,
                   cifar_stem=True, num_classes=DIM)
    tx, _ = build_optimizer(config, steps_per_epoch=4)
    state = create_train_state(
        jax.random.key(0), model, tx, (GLOBAL_B // 8, IMG, IMG, 3), K, DIM)
    raw = build_train_step(config, model, tx, mesh, steps_per_epoch=4)

    def step_fn(s, im_q, im_k):
        # the step donates its state; feed a copy, keep the original
        return raw(jax.tree.map(jnp.copy, s), im_q, im_k)

    return state, step_fn


def _batches(n):
    return [
        (jax.random.normal(jax.random.key(10 + i), (GLOBAL_B, IMG, IMG, 3)),
         jax.random.normal(jax.random.key(20 + i), (GLOBAL_B, IMG, IMG, 3)))
        for i in range(n)
    ]


def test_standard_metrics_present_and_consistent_v1(mesh8):
    config = _tiny_v1_config()  # health_stride=0: diagnostics OFF
    state, step_fn, = _build_v1(config, mesh8)
    _, metrics = step_fn(state, *_batches(1)[0])
    assert "neg_sim" in metrics and "logit_margin" in metrics
    assert float(metrics["logit_margin"]) == pytest.approx(
        float(metrics["pos_sim"]) - float(metrics["neg_sim"]), abs=1e-5)
    # diagnostics off: NO h_* keys in the step program's outputs
    assert not any(k.startswith("h_") for k in metrics)


def test_health_stride_gates_and_trajectory_bitwise_v1(mesh8):
    """THE contract: diagnostics are observational — the state trajectory
    with health_stride on is BITWISE the trajectory with it off; h_*
    scalars carry real values exactly on stride steps, zeros off."""
    batches = _batches(4)
    state_off, step_off = _build_v1(_tiny_v1_config(), mesh8)
    state_on, step_on = _build_v1(_tiny_v1_config(health_stride=2), mesh8)

    s_off, s_on = state_off, state_on
    for i, (im_q, im_k) in enumerate(batches):
        s_off, m_off = step_off(s_off, im_q, im_k)
        s_on, m_on = step_on(s_on, im_q, im_k)
        on_stride = i % 2 == 0  # the cond keys on state.step (starts 0)
        if on_stride:
            assert float(m_on["h_emb_std_q"]) > 1e-3
            assert float(m_on["h_emb_std_k"]) > 1e-3
            # the 2-row per-device shard is rank-1 by construction, so
            # the PR bottoms at exactly 1 here; real shards spread it
            assert float(m_on["h_emb_pr_q"]) >= 1.0
            assert float(m_on["h_gnorm"]) > 0.0
            assert float(m_on["h_qnorm_mean"]) >= 0.0
            assert float(m_on["h_pdrift"]) >= 0.0
        else:
            for key in ("h_emb_std_q", "h_emb_std_k", "h_emb_pr_q",
                        "h_gnorm", "h_qnorm_mean", "h_pdrift"):
                assert float(m_on[key]) == 0.0, key
        # identical losses step by step...
        assert float(m_on["loss"]) == float(m_off["loss"])
    # ...and a bitwise-identical final state (params, queue, optimizer)
    for a, b in zip(
            jax.tree.leaves(s_on.replace(rng=jax.random.key_data(s_on.rng))),
            jax.tree.leaves(s_off.replace(rng=jax.random.key_data(s_off.rng)))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_v3_standard_metrics_and_stride(mesh8):
    from moco_tpu.v3_step import build_v3_train_step, create_v3_train_state

    config = PretrainConfig(
        variant="v3", arch="vit_tiny", embed_dim=DIM, batch_size=GLOBAL_B,
        epochs=4, lr=1e-3, image_size=16, health_stride=2,
    )
    from moco_tpu.train_step import build_encoder, build_optimizer

    model = build_encoder(config)
    tx, sched = build_optimizer(config, steps_per_epoch=4)
    state = create_v3_train_state(
        jax.random.key(0), model, tx, (GLOBAL_B // 8, 16, 16, 3))
    raw = build_v3_train_step(config, model, tx, mesh8, 4, sched)

    def step_fn(s, a, b):
        return raw(jax.tree.map(jnp.copy, s), a, b)

    im = [(jax.random.normal(jax.random.key(30 + i), (GLOBAL_B, 16, 16, 3)),
           jax.random.normal(jax.random.key(40 + i), (GLOBAL_B, 16, 16, 3)))
          for i in range(2)]
    s = state
    s, m0 = step_fn(s, *im[0])  # state.step 0: on-stride
    assert "neg_sim" in m0 and "logit_margin" in m0
    assert float(m0["h_emb_std_q"]) > 0.0
    assert float(m0["h_pdrift"]) >= 0.0
    # v3 is queue-free: no queue diagnostics
    assert "h_qnorm_mean" not in m0
    s, m1 = step_fn(s, *im[1])  # state.step 1: off-stride
    assert float(m1["h_emb_std_q"]) == 0.0


# ---------------------------------------------------------------------------
# serve: the reload drift guard
# ---------------------------------------------------------------------------


def _engine_from_params(model, params, stats, buckets=(1, 4, 8)):
    from moco_tpu.serve import EmbeddingEngine

    return EmbeddingEngine(model, params, stats, image_size=IMG,
                           buckets=buckets)


@pytest.fixture(scope="module")
def tiny_backbone():
    from moco_tpu.models import build_backbone

    model = build_backbone("resnet_tiny", cifar_stem=True)
    variables = {
        seed: model.init(jax.random.key(seed),
                         jnp.zeros((1, IMG, IMG, 3)), train=False)
        for seed in (0, 1)
    }
    return model, variables


def test_reload_guard_refuses_collapsed_checkpoint(tiny_backbone):
    from moco_tpu.serve import CollapsedCheckpointError, EmbedService

    model, variables = tiny_backbone
    v0 = variables[0]
    service = EmbedService(
        _engine_from_params(model, v0["params"],
                            v0.get("batch_stats", {})),
        flush_ms=2.0, max_queue=32, request_deadline_ms=10_000.0)
    crushed = health.crush_key_params(v0["params"])
    service.set_engine_factory(
        lambda path: _engine_from_params(model, crushed,
                                         v0.get("batch_stats", {})))
    try:
        with pytest.raises(CollapsedCheckpointError) as e:
            service.reload("collapsed.npz", step=7)
        assert "degenerate" in str(e.value)
        assert service.reloads == 0  # never promoted
        # the OLD engine keeps serving
        img = np.random.RandomState(0).randint(
            0, 256, (IMG, IMG, 3)).astype(np.uint8)
        row, _ = service.embed(img)
        assert np.isfinite(row).all()
    finally:
        service.drain(timeout_s=10.0)


def test_reload_guard_records_drift_on_good_reload(tiny_backbone):
    from moco_tpu.serve import EmbedService

    model, variables = tiny_backbone
    v0, v1 = variables[0], variables[1]
    service = EmbedService(
        _engine_from_params(model, v0["params"],
                            v0.get("batch_stats", {})),
        flush_ms=2.0, max_queue=32, request_deadline_ms=10_000.0)
    service.set_engine_factory(
        lambda path: _engine_from_params(model, v1["params"],
                                         v1.get("batch_stats", {})))
    try:
        entry = service.reload("other.npz", step=8)
        assert service.reloads == 1
        # different weights: the space moved, and the probe says by how
        # much; dispersion stayed healthy
        assert entry["probe_drift"] > 0.0
        assert entry["probe_spread"] > service.reload_min_spread
    finally:
        service.drain(timeout_s=10.0)


def test_reload_guard_disabled_with_probe_zero(tiny_backbone):
    from moco_tpu.serve import EmbedService

    model, variables = tiny_backbone
    v0 = variables[0]
    service = EmbedService(
        _engine_from_params(model, v0["params"],
                            v0.get("batch_stats", {})),
        flush_ms=2.0, max_queue=32, request_deadline_ms=10_000.0,
        reload_probe=0)
    crushed = health.crush_key_params(v0["params"])
    service.set_engine_factory(
        lambda path: _engine_from_params(model, crushed,
                                         v0.get("batch_stats", {})))
    try:
        entry = service.reload("collapsed.npz")  # guard off: promoted
        assert service.reloads == 1
        assert "probe_spread" not in entry
    finally:
        service.drain(timeout_s=10.0)


def test_watcher_public_quarantine_moves_step_dir(tmp_path):
    from moco_tpu.serve import CheckpointWatcher

    watch = tmp_path / "watch"
    (watch / "5").mkdir(parents=True)
    (watch / "5" / "encoder.npz").write_bytes(b"payload")
    events = []
    w = CheckpointWatcher(str(watch),
                          emit=lambda ev, **f: events.append((ev, f)))
    w.quarantine(5, "reload drift guard: collapsed")
    assert not (watch / "5").exists()
    assert (watch / ".quarantine" / "5").exists()
    assert events and events[0][0] == "reload_quarantine"
    assert "drift guard" in events[0][1]["reason"]


# ---------------------------------------------------------------------------
# acceptance: the chaos collapse drill, end to end
# ---------------------------------------------------------------------------


def _drill_config(tmp_path, **overrides):
    base = dict(
        arch="resnet_tiny", dataset="synthetic", image_size=16,
        batch_size=16, num_negatives=64, embed_dim=32, lr=0.1, epochs=3,
        steps_per_epoch=10, ckpt_dir="", tb_dir="", print_freq=1000,
        num_classes=10, knn_monitor=False,
        telemetry_dir=str(tmp_path / "telemetry"),
        telemetry_flush_steps=10_000, heartbeat_secs=0.0,
        health_stride=2, collapse_window=3, collapse_emb_std=1e-4,
        collapse_min_step=4,
    )
    base.update(overrides)
    return get_preset("cifar10-moco-v1").replace(**base)


@pytest.mark.chaos
def test_collapse_drill_e2e(mesh8, tmp_path):
    """ISSUE 13 acceptance: 30-step CPU train with `collapse_at_step=20`
    — the in-graph diagnostics catch the injected collapse, the sentinel
    fires exactly ONE `health` incident, obsd's shipped learning-health
    rules alert then recover over the run's own records, the report
    renders `health:`, and the collapsed checkpoint is refused by the
    serve reload guard."""
    from moco_tpu.telemetry.aggregate import Aggregator, load_rules
    from moco_tpu.train import train
    from tools.telemetry_report import load_events, render, summarize

    config = _drill_config(tmp_path)
    with chaos_context(ChaosPlan(collapse_at_step=20)):
        state, _ = train(config, mesh8)
    assert int(state.step) == 30

    events_path = os.path.join(config.telemetry_dir, "events.jsonl")
    records, skipped = load_events(events_path)
    assert skipped == 0

    # (1) the stride-sampled diagnostics separate healthy from collapsed
    blocks = [(r["step"], r["health"]) for r in records
              if r.get("kind") == "step" and "health" in r]
    healthy = [h["emb_std_k"] for s, h in blocks if s <= 20]
    crushed = [h["emb_std_k"] for s, h in blocks if s > 22]
    assert healthy and min(healthy) > 1e-3
    assert crushed and max(crushed) <= 1e-4

    # (2) the sentinel fired exactly one health incident, on emb_std
    incidents = [r for r in records if r.get("kind") == "event"
                 and r.get("event") == "health"]
    assert len(incidents) == 1
    assert incidents[0]["predicate"] == "emb_std"
    assert incidents[0]["step"] > 20

    # (3) obsd with the SHIPPED rule file over the run's own records:
    # replay them time-compressed into a live stream (records that exist
    # before the tailer is created are catch-up by design), healthy
    # phase first, collapsed phase after both burn windows aged out
    replay = tmp_path / "replay"
    replay.mkdir()
    replay_events = str(replay / "events.jsonl")
    agg = Aggregator([str(replay)], rules=load_rules(RULES_PATH))
    assert agg.poll_once(now=900.0) == []

    def append(recs):
        with open(replay_events, "a", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")

    pre = [r for r in records if r not in incidents
           and (r.get("kind") != "step" or r.get("step", 0) <= 20)]
    post = [r for r in records
            if r.get("kind") == "step" and r.get("step", 0) > 20] \
        + incidents
    append(pre)
    transitions = agg.poll_once(now=1000.0)
    assert transitions == []  # healthy phase: nothing fires
    append(post)
    transitions = agg.poll_once(now=1400.0)
    fired = {t["rule"] for t in transitions}
    assert "collapse_emb_std" in fired  # the learning-health SLO alerts
    assert all(t["action"] == "alert" for t in transitions)
    # the stream drains -> the alert recovers (clear_s hysteresis)
    assert agg.poll_once(now=1500.0) == []
    recovered = agg.poll_once(now=1505.0)
    assert {t["rule"] for t in recovered} >= {"collapse_emb_std"}
    assert all(t["action"] == "recover" for t in recovered)

    # (4) the report renders the learning-health story — incl. the slo
    # transitions obsd appended into the replay stream
    replay_records, _ = load_events(replay_events)
    summary = summarize(replay_records)
    assert summary["health"]["incidents"]["fired"] == 1
    assert summary["health"]["min"]["emb_std_k"] <= 1e-4
    assert summary["slo"]["alerts"] >= 1 and summary["slo"]["recoveries"] >= 1
    text = render(summary)
    assert "health:" in text and "collapse incidents: 1 fired" in text

    # (5) the collapsed checkpoint is refused by the serve reload guard:
    # a healthy engine is live, the drilled run's final (crushed) key
    # encoder arrives as the reload candidate
    from moco_tpu.serve import CollapsedCheckpointError, EmbedService
    from moco_tpu.train_step import build_encoder

    model = build_encoder(config)
    healthy_vars = model.init(jax.random.key(3),
                              jnp.zeros((1, 16, 16, 3)), train=False)
    from moco_tpu.serve import EmbeddingEngine

    def engine(params, stats):
        return EmbeddingEngine(model, params, stats, image_size=16,
                               buckets=(1, 4, 8))

    service = EmbedService(
        engine(healthy_vars["params"],
               healthy_vars.get("batch_stats", {})),
        flush_ms=2.0, max_queue=32, request_deadline_ms=10_000.0)
    service.set_engine_factory(
        lambda path: engine(state.params_k, state.batch_stats_k))
    try:
        with pytest.raises(CollapsedCheckpointError):
            service.reload("collapsed-step-30.npz", step=30)
        assert service.reloads == 0
    finally:
        service.drain(timeout_s=10.0)


@pytest.mark.chaos
@pytest.mark.slow
def test_collapse_rollback_soak_exhausts_budget(mesh8, tmp_path):
    """The opt-in rollback path under a PERSISTENT collapse: every
    rollback restores a pre-collapse checkpoint, the wedged-momentum
    chaos re-crushes the key encoder, the sentinel fires again — the
    bounded budget must exhaust and abort for a human instead of
    rollback-looping forever (the NaN-rollback semantics, inherited by
    construction)."""
    from moco_tpu.resilience import RollbackExhaustedError
    from moco_tpu.train import train

    config = _drill_config(
        tmp_path, ckpt_dir=str(tmp_path / "ckpt"), ckpt_every_epochs=1,
        collapse_rollback=True, max_rollbacks=1,
    )
    with chaos_context(ChaosPlan(collapse_at_step=12)):
        with pytest.raises(RollbackExhaustedError):
            train(config, mesh8)
    events_path = os.path.join(config.telemetry_dir, "events.jsonl")
    from tools.telemetry_report import load_events

    records, _ = load_events(events_path)
    # each attempt's stream carries the sentinel firing with rollback
    # requested, and the data-window advance the restore performed
    incidents = [r for r in records if r.get("kind") == "event"
                 and r.get("event") == "health"]
    assert incidents and incidents[0]["predicate"] == "emb_std"
    assert "requesting rollback" in incidents[0]["msg"]
    rollbacks = [r for r in records if r.get("kind") == "event"
                 and r.get("event") == "rollback"]
    assert rollbacks  # the bounded restore actually ran before giving up
