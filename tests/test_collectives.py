"""ShuffleBN collective tests on the 8-fake-device mesh (SURVEY §4 item 1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from moco_tpu.parallel import DATA_AXIS, batch_shuffle, batch_unshuffle
from moco_tpu.parallel.collectives import all_gather_batch, ring_shuffle
from moco_tpu.utils.compat import shard_map


def _shard_map(fn, mesh, in_specs, out_specs):
    return jax.jit(
        shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_shuffle_unshuffle_is_identity(mesh8):
    x = np.arange(32 * 3, dtype=np.float32).reshape(32, 3)
    key = jax.random.key(0)

    def f(x, key):
        shuf, perm = batch_shuffle(x, key, DATA_AXIS)
        return batch_unshuffle(shuf, perm, DATA_AXIS)

    out = _shard_map(f, mesh8, (P(DATA_AXIS), P()), P(DATA_AXIS))(x, key)
    np.testing.assert_array_equal(np.asarray(out), x)


def test_shuffle_is_global_permutation(mesh8):
    x = np.arange(32, dtype=np.float32).reshape(32, 1)
    key = jax.random.key(1)

    def f(x, key):
        shuf, _ = batch_shuffle(x, key, DATA_AXIS)
        return shuf

    out = np.asarray(_shard_map(f, mesh8, (P(DATA_AXIS), P()), P(DATA_AXIS))(x, key))
    # same multiset of rows globally...
    assert sorted(out.ravel().tolist()) == sorted(x.ravel().tolist())
    # ...but the per-device grouping changed: at least one device must hold a
    # row that originated on a different device (BN decorrelation property).
    orig_groups = x.reshape(8, 4, 1)
    new_groups = out.reshape(8, 4, 1)
    assert not np.array_equal(orig_groups, new_groups)
    moved = sum(
        1
        for d in range(8)
        if set(new_groups[d].ravel()) != set(orig_groups[d].ravel())
    )
    assert moved >= 6  # with a random 32-perm, essentially all groups change


def test_all_gather_batch_concatenates_in_rank_order(mesh8):
    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    f = _shard_map(
        lambda x: all_gather_batch(x, DATA_AXIS), mesh8, (P(DATA_AXIS),), P(DATA_AXIS)
    )
    out = np.asarray(f(x))  # each device holds full copy; sharded out gives back x8 rows
    assert out.shape == (16 * 8, 1)
    np.testing.assert_array_equal(out[:16], x)


def test_ring_shuffle_roundtrip(mesh8):
    x = np.arange(32, dtype=np.float32).reshape(32, 1)

    def f(x):
        y = ring_shuffle(x, DATA_AXIS)
        return ring_shuffle(y, DATA_AXIS, inverse=True)

    out = _shard_map(f, mesh8, (P(DATA_AXIS),), P(DATA_AXIS))(x)
    np.testing.assert_array_equal(np.asarray(out), x)


def test_shuffle_roundtrip_on_2d_mesh(mesh8):
    """ShuffleBN generalized to arbitrary mesh shapes (ISSUE 15): the
    gather+permute shuffle runs over the combined (data, fsdp) group and
    roundtrips exactly, and the global row order matches the combined
    row-major device index."""
    from moco_tpu.parallel.mesh import create_mesh_2d

    mesh2d = create_mesh_2d(4, devices=list(mesh8.devices.flat))
    axes = ("data", "fsdp")
    x = np.arange(32 * 3, dtype=np.float32).reshape(32, 3)
    key = jax.random.key(0)

    def f(x, key):
        shuf, perm = batch_shuffle(x, key, axes)
        return batch_unshuffle(shuf, perm, axes)

    out = _shard_map(f, mesh2d, (P(axes), P()), P(axes))(x, key)
    np.testing.assert_array_equal(np.asarray(out), x)

    g = _shard_map(lambda v: all_gather_batch(v, axes), mesh2d,
                   (P(axes),), P(axes))
    gathered = np.asarray(g(x))
    np.testing.assert_array_equal(gathered[:32], x)


def test_chunked_gather_bitwise_equals_plain(mesh8):
    """The FAST-style chunked gather (ISSUE 15) restitches to exactly the
    monolithic gather's rows — pure scheduling, zero numerics."""
    x = np.asarray(
        jax.random.normal(jax.random.key(3), (32, 5)), np.float32)

    def plain(v):
        return all_gather_batch(v, DATA_AXIS)

    def chunked(v):
        return all_gather_batch(v, DATA_AXIS, chunks=2)

    a = np.asarray(_shard_map(plain, mesh8, (P(DATA_AXIS),), P(DATA_AXIS))(x))
    b = np.asarray(
        _shard_map(chunked, mesh8, (P(DATA_AXIS),), P(DATA_AXIS))(x))
    np.testing.assert_array_equal(a, b)


def test_batch_axis_index_matches_gather_order(mesh8):
    """The combined row-major index IS the position a device's tiled
    gather shard lands at — the invariant every v3 label offset and aug
    sample-key derivation rides on."""
    from moco_tpu.parallel.collectives import batch_axis_index
    from moco_tpu.parallel.mesh import create_mesh_2d

    mesh2d = create_mesh_2d(4, devices=list(mesh8.devices.flat))
    axes = ("data", "fsdp")
    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    def f(v):
        idx = batch_axis_index(axes)
        g = all_gather_batch(idx[None, None].astype(np.float32), axes)
        return g

    out = np.asarray(_shard_map(f, mesh2d, (P(axes),), P(axes))(x))
    np.testing.assert_array_equal(out[:8].ravel(), np.arange(8))


def test_ring_shuffle_mixes_group_membership(mesh8):
    """The point of ShuffleBN is changing group COMPOSITION, not which
    device computes a group: every post-shuffle BN group must contain
    samples from (at least) two different pre-shuffle groups — a whole-shard
    rotation would fail this (membership preserved ⇒ BN leak intact)."""
    x = np.arange(32, dtype=np.float32).reshape(32, 1)
    out = np.asarray(
        _shard_map(
            lambda x: ring_shuffle(x, DATA_AXIS), mesh8, (P(DATA_AXIS),), P(DATA_AXIS)
        )(x)
    )
    orig_groups = [set(g.ravel()) for g in x.reshape(8, 4)]
    for d in range(8):
        new_group = set(out.reshape(8, 4)[d].ravel())
        sources = {
            i for i, og in enumerate(orig_groups) if og & new_group
        }
        assert len(sources) >= 2, f"group {d} drawn from a single source {sources}"
        assert new_group != orig_groups[d]
