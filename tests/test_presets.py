"""Every named preset must construct a valid model + optimizer (shape-level
only — eval_shape keeps ResNet-50/ViT-S init free)."""

import jax
import jax.numpy as jnp
import pytest

from moco_tpu.config import PRESETS, PretrainConfig, get_preset
from moco_tpu.train_step import build_encoder, build_optimizer


@pytest.mark.parametrize(
    "name", [n for n, c in PRESETS.items() if isinstance(c, PretrainConfig)]
)
def test_pretrain_preset_builds(name):
    config = get_preset(name)
    model = build_encoder(config)
    tx, sched = build_optimizer(config, steps_per_epoch=100)
    s = config.image_size
    kwargs = {"predict": True} if config.variant == "v3" else {}
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, s, s, 3)), train=False, **kwargs
        )
    )
    assert "params" in shapes
    # schedule evaluates finitely at the start/end of training
    assert float(sched(0)) >= 0.0
    assert float(sched(100 * config.epochs - 1)) >= 0.0


def test_reference_v1_v2_deltas():
    """The entire v1→v2 delta is 3 flags + temperature (SURVEY §2.1)."""
    v1 = get_preset("imagenet-moco-v1")
    v2 = get_preset("imagenet-moco-v2")
    assert (v1.mlp_head, v1.aug_plus, v1.cos, v1.temperature) == (
        False, False, False, 0.07,
    )
    assert (v2.mlp_head, v2.aug_plus, v2.cos, v2.temperature) == (
        True, True, True, 0.2,
    )
    # everything else identical
    for field in ("arch", "num_negatives", "momentum_ema", "lr", "batch_size",
                  "epochs", "weight_decay", "sgd_momentum"):
        assert getattr(v1, field) == getattr(v2, field), field


def test_unknown_preset():
    with pytest.raises(ValueError, match="unknown preset"):
        get_preset("nope")
