"""Every named preset must construct a valid model + optimizer (shape-level
only — eval_shape keeps ResNet-50/ViT-S init free)."""

import jax
import jax.numpy as jnp
import pytest

from moco_tpu.config import PRESETS, PretrainConfig, get_preset
from moco_tpu.train_step import build_encoder, build_optimizer


@pytest.mark.parametrize(
    "name", [n for n, c in PRESETS.items() if isinstance(c, PretrainConfig)]
)
def test_pretrain_preset_builds(name):
    config = get_preset(name)
    model = build_encoder(config)
    tx, sched = build_optimizer(config, steps_per_epoch=100)
    s = config.image_size
    kwargs = {"predict": True} if config.variant == "v3" else {}
    shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.key(0), jnp.zeros((1, s, s, 3)), train=False, **kwargs
        )
    )
    assert "params" in shapes
    # schedule evaluates finitely at the start/end of training
    assert float(sched(0)) >= 0.0
    assert float(sched(100 * config.epochs - 1)) >= 0.0


def test_reference_v1_v2_deltas():
    """The entire v1→v2 delta is 3 flags + temperature (SURVEY §2.1)."""
    v1 = get_preset("imagenet-moco-v1")
    v2 = get_preset("imagenet-moco-v2")
    assert (v1.mlp_head, v1.aug_plus, v1.cos, v1.temperature) == (
        False, False, False, 0.07,
    )
    assert (v2.mlp_head, v2.aug_plus, v2.cos, v2.temperature) == (
        True, True, True, 0.2,
    )
    # everything else identical
    for field in ("arch", "num_negatives", "momentum_ema", "lr", "batch_size",
                  "epochs", "weight_decay", "sgd_momentum"):
        assert getattr(v1, field) == getattr(v2, field), field


def test_unknown_preset():
    with pytest.raises(ValueError, match="unknown preset"):
        get_preset("nope")


def test_v3_preset_lr_scales_with_batch():
    """The v3 presets follow the linear-scaling rule: a --batch-size override
    must rescale the effective lr (reference: `args.lr * args.batch_size/256`
    computed from the ACTUAL batch; VERDICT r2 weak #4)."""
    for name, base in (("imagenet-moco-v3-vits", 1.5e-4),
                       ("imagenet-moco-v3-r50", 0.3)):
        cfg = get_preset(name)
        assert cfg.effective_lr == pytest.approx(base * 4096 / 256)
        halved = cfg.replace(batch_size=1024)
        assert halved.effective_lr == pytest.approx(base * 1024 / 256)
        # an explicit lr still wins over the scaling rule
        assert cfg.replace(lr=0.5).effective_lr == 0.5


def test_v3_preset_lr_in_schedule():
    """build_optimizer's schedule must use the batch-resolved lr."""
    cfg = get_preset("imagenet-moco-v3-vits").replace(
        batch_size=512, warmup_epochs=0, cos=True
    )
    _, sched = build_optimizer(cfg, steps_per_epoch=10)
    assert float(sched(0)) == pytest.approx(1.5e-4 * 512 / 256)


def test_v3_lincls_preset():
    """The moco-v3 probe recipe: batch-scaled SGD lr 3/256-per-sample,
    90 epochs, cosine (VERDICT r2 missing #2)."""
    cfg = get_preset("imagenet-lincls-v3")
    assert cfg.epochs == 90 and cfg.cos
    assert cfg.effective_lr == pytest.approx(3.0 * 1024 / 256)
    assert cfg.replace(batch_size=256).effective_lr == pytest.approx(3.0)


def test_effective_lr_requires_some_lr():
    with pytest.raises(ValueError, match="lr or base_lr"):
        _ = PretrainConfig(lr=0.0, base_lr=0.0).effective_lr
