"""FastBatchNorm (Pallas-stat BN) equivalence with `nn.BatchNorm`, and the
streaming reduction kernels in interpret mode (SURVEY §2.10: the cuDNN
fused-BN equivalent must be provably identical to the graph-level math)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.models.fast_bn import FastBatchNorm
from moco_tpu.ops.pallas_stats import channel_grad_sums, channel_sums
from moco_tpu.utils.compat import shard_map


def _pair(dtype):
    flax_bn = nn.BatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5,
        dtype=dtype, param_dtype=jnp.float32,
    )
    fast_bn = FastBatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5,
        dtype=dtype, param_dtype=jnp.float32,
    )
    return flax_bn, fast_bn


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fast_bn_train_matches_flax(dtype):
    """Off-TPU the jnp path mirrors flax's op order exactly: forward output,
    running-stat updates, and (via the custom VJP's closed form) gradients."""
    flax_bn, fast_bn = _pair(dtype)
    x = jax.random.normal(jax.random.key(0), (8, 6, 6, 16)) * 2.0 + 1.0
    v1 = flax_bn.init(jax.random.key(1), x)
    v2 = fast_bn.init(jax.random.key(1), x)
    assert jax.tree.structure(v1) == jax.tree.structure(v2)
    # shared weights so outputs are comparable
    variables = {"params": v1["params"], "batch_stats": v1["batch_stats"]}

    ya, muta = flax_bn.apply(variables, x, mutable=["batch_stats"])
    yb, mutb = fast_bn.apply(variables, x, mutable=["batch_stats"])
    # off-TPU the fast module IS flax's graph — bit-identical in both dtypes
    np.testing.assert_array_equal(np.asarray(ya, np.float32), np.asarray(yb, np.float32))
    for a, b in zip(
        jax.tree.leaves(muta["batch_stats"]), jax.tree.leaves(mutb["batch_stats"]),
        strict=True,
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    def loss(bn):
        def f(params, x):
            y, _ = bn.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            return jnp.sum(y.astype(jnp.float32) ** 2)
        return f

    ga, gxa = jax.grad(loss(flax_bn), argnums=(0, 1))(variables["params"], x)
    gb, gxb = jax.grad(loss(fast_bn), argnums=(0, 1))(variables["params"], x)
    # grads agree to ~1 ulp (autodiff reassociates one mul differently vs
    # flax's in-place `mul *=` graph); the forward is bit-exact and the
    # training-trajectory pin is test_golden.py, which must stay unchanged
    np.testing.assert_allclose(
        np.asarray(gxa, np.float32), np.asarray(gxb, np.float32),
        rtol=3e-6, atol=5e-7,
    )
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-6, atol=5e-6)


def test_fast_bn_eval_matches_flax():
    flax_bn = nn.BatchNorm(use_running_average=True, epsilon=1e-5)
    fast_bn = FastBatchNorm(use_running_average=True, epsilon=1e-5)
    x = jax.random.normal(jax.random.key(2), (4, 5, 5, 8))
    v = flax_bn.init(jax.random.key(3), x)
    v["batch_stats"]["mean"] = jnp.linspace(-1, 1, 8)
    v["batch_stats"]["var"] = jnp.linspace(0.5, 2, 8)
    ya = flax_bn.apply(v, x)
    yb = fast_bn.apply(v, x)
    np.testing.assert_array_equal(np.asarray(ya), np.asarray(yb))


def test_fast_bn_sync_axis(mesh8):
    """SyncBN path: cross-device pmean statistics inside shard_map equal
    global-batch statistics."""
    from jax.sharding import PartitionSpec as P

    bn = FastBatchNorm(use_running_average=False, axis_name="data")
    x = jax.random.normal(jax.random.key(4), (16, 4, 4, 8))
    v = bn.init(jax.random.key(5), x[:2])

    def body(x):
        y, mut = bn.apply(v, x, mutable=["batch_stats"])
        return y, mut["batch_stats"]["mean"]

    y, mean = jax.jit(
        shard_map(
            body, mesh=mesh8, in_specs=P("data"), out_specs=(P("data"), P()),
        )
    )(x)
    xf = np.asarray(x, np.float64)
    np.testing.assert_allclose(
        np.asarray(mean), 0.1 * xf.mean(axis=(0, 1, 2)), rtol=1e-4, atol=1e-5
    )  # running update: 0.9*0 + 0.1*batch_mean


def test_channel_sums_interpret_matches_jnp():
    x = jax.random.normal(jax.random.key(6), (1024, 24)).astype(jnp.bfloat16)
    s, sq = channel_sums(x, interpret=True)
    xf = np.asarray(x, np.float32)
    np.testing.assert_allclose(np.asarray(s), xf.sum(0), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(sq), (xf * xf).sum(0), rtol=1e-2, atol=1e-2)


def test_channel_grad_sums_interpret_matches_jnp():
    key = jax.random.key(7)
    dy = jax.random.normal(key, (2048, 16)).astype(jnp.bfloat16)
    x = jax.random.normal(jax.random.key(8), (2048, 16)).astype(jnp.bfloat16)
    mean = jnp.linspace(-0.5, 0.5, 16)
    rstd = jnp.linspace(0.8, 1.2, 16)
    dsum, dxh = channel_grad_sums(dy, x, mean, rstd, interpret=True)
    dyf = np.asarray(dy, np.float32)
    xh = (np.asarray(x, np.float32) - np.asarray(mean)) * np.asarray(rstd)
    np.testing.assert_allclose(np.asarray(dsum), dyf.sum(0), rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(dxh), (dyf * xh).sum(0), rtol=1e-2, atol=2e-2)


def test_resnet_fast_bn_param_tree_unchanged():
    """ResNet with fast_bn on/off has identical param + batch_stats trees —
    checkpoints are interchangeable."""
    from moco_tpu.models.resnet import BasicBlock, ResNet

    kw = dict(stage_sizes=(1,), block_cls=BasicBlock, width=8,
              num_classes=16, cifar_stem=True)
    x = jnp.zeros((2, 16, 16, 3))
    va = ResNet(fast_bn=False, **kw).init(jax.random.key(0), x, train=False)
    vb = ResNet(fast_bn=True, **kw).init(jax.random.key(0), x, train=False)
    assert jax.tree.structure(va) == jax.tree.structure(vb)
    for a, b in zip(jax.tree.leaves(va), jax.tree.leaves(vb), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bn_train_custom_vjp_matches_autodiff(dtype):
    """The TPU path's custom VJP (closed-form dx, Pallas-shaped reductions —
    jnp fallback internals here) agrees with flax autodiff to float
    tolerance. On TPU this same code runs with the Pallas kernels."""
    from moco_tpu.models.fast_bn import _bn_train

    flax_bn = nn.BatchNorm(use_running_average=False, momentum=0.9,
                           epsilon=1e-5, dtype=dtype, param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(10), (8, 6, 6, 16)) * 1.7
    v = flax_bn.init(jax.random.key(11), x)
    scale, bias = v["params"]["scale"], v["params"]["bias"]

    def loss_custom(x, scale, bias):
        y, _, _ = _bn_train(x, scale, bias, 1e-5, dtype)
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    def loss_flax(x, params):
        y, _ = flax_bn.apply(
            {"params": params, "batch_stats": v["batch_stats"]},
            x, mutable=["batch_stats"])
        return jnp.sum(jnp.sin(y.astype(jnp.float32)))

    gx, gs, gb = jax.grad(loss_custom, argnums=(0, 1, 2))(x, scale, bias)
    gxa, ga = jax.grad(loss_flax, argnums=(0, 1))(x, v["params"])
    tol = dict(rtol=1e-4, atol=1e-5) if dtype == jnp.float32 else dict(rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(gx, np.float32), np.asarray(gxa, np.float32), **tol)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(ga["scale"]), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(ga["bias"]), rtol=1e-3, atol=1e-3)


def test_tile_rows_vmem_budget_and_override():
    """_tile_rows keeps every per-operand tile within the byte target (the
    r5 on-chip VMEM finding: the grad-sums kernel holds ~4 f32 tile-sized
    intermediates, so a 2 MB bf16 tile blew the 16 MB Mosaic scoped-VMEM
    limit at c=64), divides n exactly, floors at the f32 sublane count,
    and honors the tile-budget override (MOCO_TPU_STATS_TILE_KIB, read
    once at import — a mid-process change could never reach an
    already-jitted program, so the kib parameter is the testable seam)."""
    from moco_tpu.ops.pallas_stats import _tile_rows

    for n, c in [(128 * 56 * 56, 64), (128 * 7 * 7, 2048), (256, 512),
                 (8, 64), (12, 256)]:
        t = _tile_rows(n, c, kib=0)
        assert n % t == 0 or t == n
        # bf16 operand tile within the 1 MB default target (unless floored)
        assert t * c * 2 <= (1 << 20) or t == 8 or t == n
        assert t >= 1

    # the floor is 8, not 512: c=2048 must not get a 1M-element tile
    assert _tile_rows(128 * 7 * 7, 2048, kib=0) * 2048 * 2 <= (1 << 20)

    base = _tile_rows(1 << 16, 64, kib=0)
    # the row cap scales with the budget: a 2 MiB override must reach the
    # pre-fix 16384-row tile at c=64, not clamp back to the default tile
    assert _tile_rows(1 << 16, 64, kib=2048) == 2 * base
    assert _tile_rows(1 << 16, 64, kib=256) == base // 4

    # non-power-of-two budgets floor to a power-of-two tile instead of
    # degenerating to 1-row tiles (factor-3 target vs pow2 n) or silently
    # aliasing the default program
    n = 128 * 56 * 56
    assert _tile_rows(n, 64, kib=768) == 4096
    assert _tile_rows(n, 128, kib=1536) == 4096
    for kib in (3, 24, 768, 1536, 5000):
        t = _tile_rows(n, 64, kib=kib)
        assert t & (t - 1) == 0 and t >= 8, (kib, t)


def test_pallas_gates_are_decoupled(monkeypatch):
    """fast_bn's BN-stats kernels default OFF on TPU (r5 on-chip A/B:
    ~52 ms/step launch overhead) behind the MOCO_TPU_PALLAS_BN opt-in,
    while fused_block's separately-validated family stays reachable via
    its config switch — flipping one default must not silently gate the
    other (review, r5). "0" must mean off for the opt-in."""
    import unittest.mock as mock

    import moco_tpu.models.fast_bn as fbn
    import moco_tpu.models.fused_block as fb

    with mock.patch.object(jax, "default_backend", lambda: "tpu"):
        monkeypatch.delenv("MOCO_TPU_PALLAS_BN", raising=False)
        monkeypatch.delenv("MOCO_TPU_DISABLE_PALLAS", raising=False)
        assert not fbn._use_pallas()      # opt-in, default off
        assert fb._use_pallas()           # fused family: config gates it

        monkeypatch.setenv("MOCO_TPU_PALLAS_BN", "1")
        assert fbn._use_pallas()
        monkeypatch.setenv("MOCO_TPU_PALLAS_BN", "0")
        assert not fbn._use_pallas()      # "0" is off, not truthy-on

        monkeypatch.setenv("MOCO_TPU_PALLAS_BN", "1")
        monkeypatch.setenv("MOCO_TPU_DISABLE_PALLAS", "1")
        assert not fbn._use_pallas()      # global kill-switch wins
        assert not fb._use_pallas()


def test_custom_vjp_gate(monkeypatch):
    """_use_custom_vjp: ON for TPU (measured win, closed-form dx), OFF
    elsewhere (CPU goldens pin plain autodiff), MOCO_TPU_BN_VJP forces
    either way and "0" means off."""
    import unittest.mock as mock

    import moco_tpu.models.fast_bn as fbn

    monkeypatch.delenv("MOCO_TPU_BN_VJP", raising=False)
    assert not fbn._use_custom_vjp()  # cpu backend here
    with mock.patch.object(jax, "default_backend", lambda: "tpu"):
        assert fbn._use_custom_vjp()
        monkeypatch.setenv("MOCO_TPU_BN_VJP", "0")
        assert not fbn._use_custom_vjp()
    monkeypatch.setenv("MOCO_TPU_BN_VJP", "1")
    assert fbn._use_custom_vjp()      # forced on even off-TPU


def test_env_flag_zero_means_off_everywhere(monkeypatch):
    """Uniform '0'-means-off across ALL Pallas switches, including the
    DISABLE_* spellings: MOCO_TPU_DISABLE_PALLAS=0 must NOT kill the
    kernel families (review, r5)."""
    import unittest.mock as mock

    import moco_tpu.data.augment as aug
    import moco_tpu.models.fast_bn as fbn
    import moco_tpu.models.fused_block as fb

    with mock.patch.object(jax, "default_backend", lambda: "tpu"):
        monkeypatch.setenv("MOCO_TPU_DISABLE_PALLAS", "0")
        monkeypatch.setenv("MOCO_TPU_PALLAS_BN", "1")
        assert fbn._use_pallas()          # "0" disable = not disabled
        assert fb._use_pallas()
        cfg = aug.v2_aug_config(out_size=16)
        monkeypatch.setenv("MOCO_TPU_DISABLE_PALLAS_BLUR", "0")
        assert aug._use_pallas_blur(cfg)
        monkeypatch.setenv("MOCO_TPU_DISABLE_PALLAS", "1")
        assert not fbn._use_pallas()
        assert not fb._use_pallas()
        assert not aug._use_pallas_blur(cfg)
