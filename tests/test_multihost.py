"""True multi-process multi-host simulation (SURVEY §4 item 4): two JAX
processes x 4 fake CPU devices = one 8-device mesh across 2 "hosts",
exercising `jax.distributed` bootstrap, host-sharded input assembly
(`make_array_from_process_local_data`), the SPMD step's collectives across
process boundaries, and COLLECTIVE Orbax checkpointing. The parent asserts
both processes end with bit-identical replicated state."""

import os
import re
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_training_agrees(tmp_path):
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    ckpt_dir = str(tmp_path / "ckpt")
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.getcwd()
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/multihost_worker.py", coordinator, "2", str(pid), ckpt_dir],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        m = re.search(
            r"RESULT pid=(\d+) steps=(\d+) loss=([\d.]+) queue=(\w+) ptr=(\d+) conv1=(\w+)",
            out,
        )
        assert m, f"no RESULT line in:\n{out[-3000:]}"
        results[int(m.group(1))] = m.groups()[1:]
    assert results[0] == results[1], f"process state diverged: {results}"
    # 3 steps of global batch 16 into a 64-slot queue
    assert results[0][0] == "3"
    assert results[0][3] == "48"
    # collective checkpoint landed
    assert os.path.isdir(os.path.join(ckpt_dir, "3"))
