"""True multi-process multi-host simulation (SURVEY §4 item 4; VERDICT r1
#7): two JAX processes x 4 fake CPU devices = one 8-device mesh across 2
"hosts", driving the REAL train driver — `jax.distributed` bootstrap,
host-sharded input assembly, the SHARDED two-crop augmentation, the SPMD
step's collectives across process boundaries, and COLLECTIVE Orbax
checkpointing — for both the v2 (queue + ShuffleBN) and v3 (symmetric,
queue-free) paths. A separate FRESH 2-process session then restores the v2
checkpoint and must reproduce the saved state bit-for-bit."""

import os
import re
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pair(ckpt_dir: str, mode: str, phase: str) -> dict[int, tuple]:
    """Launch 2 workers, return {pid: (steps, loss, digest)}."""
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.getcwd()
    procs = [
        subprocess.Popen(
            [sys.executable, "tests/multihost_worker.py", coordinator, "2",
             str(pid), ckpt_dir, mode, phase],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{mode}/{phase} worker {pid} failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        m = re.search(
            r"RESULT pid=(\d+) steps=(\d+) loss=([\d.nan]+) digest=(\w+)", out
        )
        assert m, f"no RESULT line in:\n{out[-3000:]}"
        results[int(m.group(1))] = (m.group(2), m.group(3), m.group(4))
    return results


@pytest.mark.slow
def test_two_process_v2_train_restore_bitfaithful(tmp_path):
    """v2 (sharded aug + queue + ShuffleBN): replicas agree bit-for-bit after
    6 driver steps, and a FRESH 2-process session restores the checkpoint to
    exactly the trained state. The train pair also exercises pod telemetry
    (ISSUE 2): process 0 must write events.jsonl containing `pod` records
    aggregated from BOTH hosts at the resilience_sync_steps cadence."""
    import json

    ckpt_dir = str(tmp_path / "ckpt_v2")
    trained = _run_pair(ckpt_dir, "v2", "train")
    assert trained[0] == trained[1], f"process state diverged: {trained}"
    assert trained[0][0] == "6"  # 2 epochs x 3 steps through the real driver
    assert os.path.isdir(os.path.join(ckpt_dir, "6"))

    events_path = os.path.join(ckpt_dir + "_telemetry", "events.jsonl")
    assert os.path.exists(events_path), "process 0 wrote no telemetry events"
    with open(events_path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    pods = [r for r in records if r.get("kind") == "pod"]
    assert pods, f"no pod records in {sorted({r.get('kind') for r in records})}"
    assert all(p["hosts"] == 2 for p in pods), pods
    assert all(p["step_s_max"] >= p["step_s_min"] >= 0.0 for p in pods)
    steps = [r for r in records if r.get("kind") == "step"]
    assert len(steps) == 6, f"expected 6 step records, got {len(steps)}"
    assert os.path.exists(
        os.path.join(ckpt_dir + "_telemetry", "heartbeat.json"))

    restored = _run_pair(ckpt_dir, "v2", "restore")
    assert restored[0] == restored[1], f"restore diverged: {restored}"
    assert restored[0][0] == "6"
    assert restored[0][2] == trained[0][2], (
        f"restored digest {restored[0][2]} != trained digest {trained[0][2]}"
    )


@pytest.mark.slow
def test_two_process_v3_train_agrees(tmp_path):
    """v3 (asymmetric sharded aug pair, symmetric queue-free loss, AdamW +
    warmup + momentum ramp) across a real process boundary."""
    ckpt_dir = str(tmp_path / "ckpt_v3")
    results = _run_pair(ckpt_dir, "v3", "train")
    assert results[0] == results[1], f"process state diverged: {results}"
    assert results[0][0] == "6"
