"""ResNet/head structure tests. Param counts are pinned against torchvision's
published totals (the reference's backbone source) so the flax rebuild is
structurally identical: torchvision resnet18/50 with a 1000-way fc have
11,689,512 / 25,557,032 parameters; swapping fc for a 128-d head changes only
the fc term (512·128+128 / 2048·128+128)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.models import ResNet18, ResNet50, V3Predictor, V3Projector, build_resnet


def _count(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


@pytest.fixture(scope="module")
def r18_vars():
    model = ResNet18(num_classes=128, cifar_stem=True)
    v = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)), train=False)
    return model, v


def test_resnet18_param_count_matches_torchvision():
    # ImageNet stem so the structure matches torchvision exactly
    v = jax.eval_shape(
        lambda: ResNet18(num_classes=128).init(
            jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False
        )
    )
    expected = 11_689_512 - (512 * 1000 + 1000) + (512 * 128 + 128)
    assert _count(v["params"]) == expected


def test_resnet50_param_count_matches_torchvision():
    v = jax.eval_shape(
        lambda: ResNet50(num_classes=128).init(
            jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False
        )
    )
    expected = 25_557_032 - (2048 * 1000 + 1000) + (2048 * 128 + 128)
    assert _count(v["params"]) == expected


def test_mlp_head_param_count():
    # v2 head: Linear(2048,2048)+ReLU+Linear(2048,128) replaces Linear(2048,128)
    plain = jax.eval_shape(
        lambda: ResNet50(num_classes=128).init(
            jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False
        )
    )
    mlp = jax.eval_shape(
        lambda: ResNet50(num_classes=128, mlp_head=True).init(
            jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False
        )
    )
    assert _count(mlp["params"]) - _count(plain["params"]) == 2048 * 2048 + 2048


def test_forward_shapes_and_feature_mode(r18_vars):
    model, v = r18_vars
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    out = model.apply(v, x, train=False)
    assert out.shape == (2, 128)
    feat_model = ResNet18(num_classes=None, cifar_stem=True)
    fv = feat_model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)), train=False)
    feats = feat_model.apply(fv, x, train=False)
    assert feats.shape == (2, 512)


def test_batch_stats_update_in_train_mode(r18_vars):
    model, v = r18_vars
    x = jax.random.normal(jax.random.key(2), (4, 32, 32, 3)) * 5 + 3
    out, mutated = model.apply(v, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(v["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))
    # eval mode must NOT touch stats and must be deterministic
    out1 = model.apply(v, x, train=False)
    out2 = model.apply(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_bfloat16_activations_f32_params():
    model = ResNet18(num_classes=64, cifar_stem=True, dtype=jnp.bfloat16)
    v = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)), train=False)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(v["params"]))
    out = model.apply(v, jnp.zeros((2, 32, 32, 3)), train=False)
    assert out.dtype == jnp.float32  # head math promoted back to f32


def test_build_resnet_registry():
    with pytest.raises(ValueError, match="unknown arch"):
        build_resnet("resnet1337")
    m = build_resnet("resnet34", num_classes=10)
    assert m.stage_sizes == (3, 4, 6, 3)


def test_v3_heads_shapes():
    proj = V3Projector()
    pv = proj.init(jax.random.key(0), jnp.zeros((2, 384)), train=False)
    out = proj.apply(pv, jnp.ones((2, 384)), train=False)
    assert out.shape == (2, 256)
    pred = V3Predictor()
    qv = pred.init(jax.random.key(0), jnp.zeros((2, 256)), train=False)
    out2 = pred.apply(qv, out, train=False)
    assert out2.shape == (2, 256)


def test_s2d_stem_equals_plain_conv_stem():
    """The space-to-depth stem computes the SAME convolution as the plain
    7x7/2 conv (products regrouped only): same param tree, matching outputs,
    matching gradients — so checkpoints and training dynamics are unchanged
    while the MXU contracts over 12 channels instead of 3."""
    from moco_tpu.models.resnet import BasicBlock, ResNet

    kw = dict(stage_sizes=(1,), block_cls=BasicBlock, width=8, num_classes=16)
    plain = ResNet(s2d_stem=False, **kw)
    s2d = ResNet(s2d_stem=True, **kw)
    x = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    v = plain.init(jax.random.key(1), x, train=False)
    # identical param trees (s2d re-tiles at trace time, not in the params)
    v2 = s2d.init(jax.random.key(1), x, train=False)
    assert jax.tree.structure(v) == jax.tree.structure(v2)
    assert v["params"]["conv1"]["kernel"].shape == (7, 7, 3, 8)

    out_a = plain.apply(v, x, train=False)
    out_b = s2d.apply(v, x, train=False)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=2e-5, atol=2e-5)

    def loss(params, model):
        return jnp.sum(model.apply({"params": params,
                                    "batch_stats": v["batch_stats"]},
                                   x, train=False) ** 2)

    ga = jax.grad(loss)(v["params"], plain)
    gb = jax.grad(loss)(v["params"], s2d)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_s2d_stem_falls_back_on_odd_sizes():
    from moco_tpu.models.resnet import BasicBlock, ResNet

    model = ResNet(stage_sizes=(1,), block_cls=BasicBlock, width=8,
                   num_classes=16, s2d_stem=True)
    x = jnp.zeros((2, 33, 33, 3))
    v = model.init(jax.random.key(0), x, train=False)
    out = model.apply(v, x, train=False)
    assert out.shape == (2, 16)


def test_remat_blocks_identical_outputs_and_grads():
    """Per-block rematerialization is a pure memory/compute trade: the same
    ops re-executed in the backward — outputs and gradients must be
    IDENTICAL to the unrematted model (param tree included)."""
    from moco_tpu.models.resnet import BasicBlock, ResNet

    kw = dict(stage_sizes=(1, 1), block_cls=BasicBlock, width=8,
              num_classes=16, cifar_stem=True)
    plain = ResNet(remat=False, **kw)
    rm = ResNet(remat=True, **kw)
    x = jax.random.normal(jax.random.key(0), (2, 16, 16, 3))
    v = plain.init(jax.random.key(1), x, train=False)
    assert jax.tree.structure(v) == jax.tree.structure(
        rm.init(jax.random.key(1), x, train=False)
    )
    out_a = plain.apply(v, x, train=False)
    out_b = rm.apply(v, x, train=False)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))

    def loss(params, model):
        out, _ = model.apply(
            {"params": params, "batch_stats": v["batch_stats"]},
            x, train=True, mutable=["batch_stats"],
        )
        return jnp.sum(out ** 2)

    ga = jax.grad(loss)(v["params"], plain)
    gb = jax.grad(loss)(v["params"], rm)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
