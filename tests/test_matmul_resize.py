"""Dense-matmul crop/resize op: agreement with jax.image.scale_and_translate
and basic filter properties."""

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.ops.matmul_resize import crop_resize, interp_matrix


def test_matches_scale_and_translate():
    rng = np.random.RandomState(0)
    img = jnp.asarray(rng.rand(60, 60, 3).astype(np.float32))
    s = 32
    for i in range(4):
        ch, cw = rng.uniform(12, 60), rng.uniform(12, 60)
        y0, x0 = rng.uniform(0, 60 - ch), rng.uniform(0, 60 - cw)
        ref = jax.image.scale_and_translate(
            img, (s, s, 3), (0, 1),
            jnp.array([s / ch, s / cw]), jnp.array([-y0 * s / ch, -x0 * s / cw]),
            method="linear", antialias=True,
        )
        got = crop_resize(img, y0, x0, ch, cw, s)
        # small boundary-normalization/convention differences are fine for an
        # augmentation resampler; the bulk must agree closely
        assert float(jnp.abs(ref - got).max()) < 2e-2
        assert float(jnp.abs(ref - got).mean()) < 2e-3


def test_interp_matrix_row_stochastic():
    m = np.asarray(interp_matrix(60, 32, 10.0, 37.5))
    np.testing.assert_allclose(m.sum(axis=1), 1.0, rtol=1e-5)
    assert (m >= 0).all()


def test_identity_crop_is_near_identity():
    """Full-image crop at the same resolution ≈ identity mapping."""
    rng = np.random.RandomState(1)
    img = jnp.asarray(rng.rand(32, 32, 3).astype(np.float32))
    out = crop_resize(img, 0.0, 0.0, 32.0, 32.0, 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(img), atol=1e-5)


def test_upscale_and_downscale_ranges():
    """Resampling must stay within the input's convex hull (weights are a
    convex combination) for both minification and magnification."""
    img = jnp.asarray(np.random.RandomState(2).rand(40, 40, 3).astype(np.float32))
    for ch in (8.0, 40.0):
        out = np.asarray(crop_resize(img, 0.0, 0.0, ch, ch, 24))
        assert out.min() >= float(img.min()) - 1e-5
        assert out.max() <= float(img.max()) + 1e-5
