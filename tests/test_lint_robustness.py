"""tools/lint_robustness.py in tier-1: the package must stay free of bare
`except:` / broad silent swallowing (they would quietly defeat the
resilience subsystem's typed-error routing), and the linter itself must
keep catching both patterns."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "lint_robustness.py")

spec = importlib.util.spec_from_file_location("lint_robustness", LINT)
lint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(lint)


def test_package_is_clean():
    assert lint.check_tree(os.path.join(REPO, "moco_tpu")) == []


def test_cli_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, LINT, os.path.join(REPO, "moco_tpu")],
        capture_output=True, text=True,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    (tmp_path / "dirty.py").write_text(
        "try:\n    x = 1\nexcept:\n    pass\n"
    )
    dirty = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True,
    )
    assert dirty.returncode == 1
    assert "bare `except:`" in dirty.stdout


def test_detects_broad_silent_swallow(tmp_path):
    (tmp_path / "swallow.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
        "try:\n    y = 2\nexcept (ValueError, OSError):\n    pass\n"  # legal
        "try:\n    z = 3\nexcept Exception as e:\n    log(e)\n"       # legal
    )
    found = lint.check_file(str(tmp_path / "swallow.py"))
    assert len(found) == 1
    assert ":3:" in found[0] and "silently swallows" in found[0]


def test_detects_bare_print_outside_logging(tmp_path):
    """R3 (ISSUE 2): bare print() bypasses the structured channel."""
    (tmp_path / "chatty.py").write_text(
        "print('hello')\n"
        "info('fine: the sanctioned channel')\n"
        "x.print('fine: a method, not the builtin')\n"
    )
    found = lint.check_file(str(tmp_path / "chatty.py"))
    assert len(found) == 1
    assert ":1:" in found[0] and "bare `print(" in found[0]


def test_print_allowed_in_logging_and_meters(tmp_path):
    """The channels themselves (log_event/info, console meters) must stay
    allowed — they ARE the sanctioned print sites."""
    for allowed in ("utils/logging.py", "utils/meters.py"):
        path = tmp_path / "pkg" / allowed
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text("print('the channel itself')\n")
        assert lint.check_file(str(path)) == []


def test_r4_detects_unclosed_loader(tmp_path):
    """R4 (ISSUE 3): a Prefetcher/epoch_loader construction with no
    close()/close_quietly() in a finally leaks staging threads."""
    (tmp_path / "leaky.py").write_text(
        "def run(ds, mesh):\n"
        "    loader = epoch_loader(ds, 0, 0, 16, mesh)\n"
        "    for b in loader:\n"
        "        pass\n"
    )
    found = lint.check_file(str(tmp_path / "leaky.py"))
    assert len(found) == 1
    assert ":2:" in found[0] and "finally" in found[0]


def test_r4_accepts_closed_loader_and_factory_return(tmp_path):
    (tmp_path / "clean.py").write_text(
        "def run(ds, mesh):\n"
        "    loader = epoch_loader(ds, 0, 0, 16, mesh)\n"
        "    try:\n"
        "        for b in loader:\n"
        "            pass\n"
        "    finally:\n"
        "        loader.close_quietly()\n"
        "\n"
        "def factory(ds, idx, mesh):\n"
        "    return Prefetcher(ds, idx, 16, mesh)\n"
    )
    assert lint.check_file(str(tmp_path / "clean.py")) == []


def test_r4_flags_unbound_construction(tmp_path):
    (tmp_path / "unbound.py").write_text(
        "def run(ds, idx, mesh):\n"
        "    return list(Prefetcher(ds, idx, 16, mesh))\n"
    )
    found = lint.check_file(str(tmp_path / "unbound.py"))
    assert len(found) == 1
    assert "without binding a name" in found[0]


def test_r4_close_in_wrong_scope_still_flagged(tmp_path):
    """A finally in a DIFFERENT function does not discharge the
    construction site's obligation."""
    (tmp_path / "cross.py").write_text(
        "def make(ds, mesh):\n"
        "    loader = epoch_loader(ds, 0, 0, 16, mesh)\n"
        "    return loader\n"
        "\n"
        "def other(loader):\n"
        "    try:\n"
        "        pass\n"
        "    finally:\n"
        "        loader.close()\n"
    )
    found = lint.check_file(str(tmp_path / "cross.py"))
    assert len(found) == 1 and ":2:" in found[0]


def test_r5_detects_numeric_literal_exits(tmp_path):
    """R5 (ISSUE 4): a magic-number process exit silently forks the
    supervisor's classification protocol — every flavor is flagged."""
    (tmp_path / "exits.py").write_text(
        "import os, sys\n"
        "sys.exit(43)\n"                      # the core violation
        "os._exit(1)\n"
        "raise SystemExit(3)\n"
    )
    found = lint.check_file(str(tmp_path / "exits.py"))
    assert len(found) == 3
    assert all("named constants" in v for v in found)


def test_r5_accepts_named_constants_and_bare_exits(tmp_path):
    (tmp_path / "ok.py").write_text(
        "import sys\n"
        "from moco_tpu.resilience.exitcodes import EXIT_PREEMPTED\n"
        "sys.exit(EXIT_PREEMPTED)\n"          # the protocol
        "sys.exit()\n"                        # bare: plain success
        "sys.exit('message')\n"               # message form: not a code
        "raise SystemExit(EXIT_PREEMPTED)\n"
        "parser.exit(2)\n"                    # argparse's API, not ours
        "pool.exit(0)\n"                      # any method named exit
    )
    assert lint.check_file(str(tmp_path / "ok.py")) == []


def _serve_file(tmp_path, body: str):
    """A file positioned under a moco_tpu/serve/ tree (R6's scope)."""
    path = tmp_path / "moco_tpu" / "serve" / "mod.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(body)
    return str(path)


def test_r6_detects_train_imports_under_serve(tmp_path):
    """R6 (ISSUE 5): the serving runtime must stay train-free — every
    import spelling of the forbidden modules is flagged, including lazy
    (function-body) imports."""
    found = lint.check_file(_serve_file(
        tmp_path,
        "import optax\n"
        "import moco_tpu.train_step\n"
        "from moco_tpu.train import main\n"
        "from moco_tpu import train_state\n"
        "from moco_tpu.ops.schedules import cosine_lr\n"
        "def lazy():\n"
        "    from moco_tpu.v3_step import build_v3_step\n"
    ))
    assert len(found) == 6
    assert all("train-free" in v for v in found)


def test_r6_allows_inference_imports_under_serve(tmp_path):
    assert lint.check_file(_serve_file(
        tmp_path,
        "import numpy as np\n"
        "from moco_tpu.checkpoint import load_for_inference\n"
        "from moco_tpu.ops.knn import knn_predict\n"
        "from moco_tpu.telemetry.registry import Histogram\n"
        "from moco_tpu.serve.batcher import MicroBatcher\n"
    )) == []


def test_r6_scoped_to_serve_tree(tmp_path):
    """The SAME import outside moco_tpu/serve/ is legal — R6 protects the
    serving runtime, not the whole package."""
    path = tmp_path / "moco_tpu" / "evals" / "mod.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("import optax\n")
    assert lint.check_file(str(path)) == []


def test_r6_holds_for_the_real_serve_package():
    """Tier-1 gate: the shipped moco_tpu/serve/ is train-free."""
    serve_dir = os.path.join(REPO, "moco_tpu", "serve")
    r6 = [v for v in lint.check_tree(serve_dir) if "train-free" in v]
    assert r6 == [], r6


def test_r7_detects_grad_collective_outside_parallel(tmp_path):
    """R7 (ISSUE 6): an inline pmean/psum on grads outside parallel/
    silently reverts the step to the fused reduce — flagged; collectives on
    non-gradient values stay legal."""
    path = tmp_path / "moco_tpu" / "stepish.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        "from jax import lax\n"
        "def region(grads, new_stats_q, metrics, g_grads):\n"
        "    grads = lax.pmean(grads, 'data')\n"          # violation
        "    out = psum(g_grads, 'data')\n"               # violation (bare)
        "    new_stats_q = lax.pmean(new_stats_q, 'data')\n"  # legal
        "    metrics = lax.pmean(metrics, 'data')\n"          # legal
        "    one = lax.psum(1, 'data')\n"                     # legal
        "    return grads, out, new_stats_q, metrics, one\n"
    )
    found = lint.check_file(str(path))
    assert len(found) == 2
    assert all("gradsync API" in v for v in found)
    assert ":3:" in found[0] and ":4:" in found[1]


def test_r7_allows_grad_collectives_under_parallel(tmp_path):
    """The gradsync layer itself IS the sanctioned home for gradient
    collectives."""
    path = tmp_path / "moco_tpu" / "parallel" / "gradsyncish.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(
        "from jax import lax\n"
        "def reduce(grads):\n"
        "    return lax.pmean(grads, 'data')\n"
    )
    assert lint.check_file(str(path)) == []


def test_r7_holds_for_the_real_step_builders():
    """Tier-1 gate: train_step/v3_step route grads through gradsync."""
    for rel in ("moco_tpu/train_step.py", "moco_tpu/v3_step.py"):
        r7 = [v for v in lint.check_file(os.path.join(REPO, rel))
              if "gradsync API" in v]
        assert r7 == [], r7


def test_r4_holds_for_bench_and_package_call_sites():
    """The real construction sites (train driver, lincls, bench.py — the
    latter outside the package tree, held to R4 here) stay clean."""
    for rel in ("moco_tpu/train.py", "moco_tpu/evals/lincls.py", "bench.py"):
        path = os.path.join(REPO, rel)
        r4_only = [v for v in lint.check_file(path) if "finally" in v
                   or "without binding" in v]
        assert r4_only == [], r4_only
