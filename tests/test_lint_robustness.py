"""tools/lint_robustness.py in tier-1: the package must stay free of bare
`except:` / broad silent swallowing (they would quietly defeat the
resilience subsystem's typed-error routing), and the linter itself must
keep catching both patterns."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "lint_robustness.py")

spec = importlib.util.spec_from_file_location("lint_robustness", LINT)
lint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(lint)


def test_package_is_clean():
    assert lint.check_tree(os.path.join(REPO, "moco_tpu")) == []


def test_cli_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, LINT, os.path.join(REPO, "moco_tpu")],
        capture_output=True, text=True,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    (tmp_path / "dirty.py").write_text(
        "try:\n    x = 1\nexcept:\n    pass\n"
    )
    dirty = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True,
    )
    assert dirty.returncode == 1
    assert "bare `except:`" in dirty.stdout


def test_detects_broad_silent_swallow(tmp_path):
    (tmp_path / "swallow.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
        "try:\n    y = 2\nexcept (ValueError, OSError):\n    pass\n"  # legal
        "try:\n    z = 3\nexcept Exception as e:\n    log(e)\n"       # legal
    )
    found = lint.check_file(str(tmp_path / "swallow.py"))
    assert len(found) == 1
    assert ":3:" in found[0] and "silently swallows" in found[0]


def test_detects_bare_print_outside_logging(tmp_path):
    """R3 (ISSUE 2): bare print() bypasses the structured channel."""
    (tmp_path / "chatty.py").write_text(
        "print('hello')\n"
        "info('fine: the sanctioned channel')\n"
        "x.print('fine: a method, not the builtin')\n"
    )
    found = lint.check_file(str(tmp_path / "chatty.py"))
    assert len(found) == 1
    assert ":1:" in found[0] and "bare `print(" in found[0]


def test_print_allowed_in_logging_and_meters(tmp_path):
    """The channels themselves (log_event/info, console meters) must stay
    allowed — they ARE the sanctioned print sites."""
    for allowed in ("utils/logging.py", "utils/meters.py"):
        path = tmp_path / "pkg" / allowed
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text("print('the channel itself')\n")
        assert lint.check_file(str(path)) == []
