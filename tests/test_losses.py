"""InfoNCE / v3 loss property tests (SURVEY §4 item 2)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from moco_tpu.ops.losses import (
    contrastive_accuracy,
    infonce_logits,
    l2_normalize,
    softmax_cross_entropy,
    v3_contrastive_loss,
)
from moco_tpu.parallel import DATA_AXIS
from moco_tpu.utils.compat import shard_map


def _rand_unit(key, shape):
    return l2_normalize(jax.random.normal(key, shape))


def test_l2_normalize_unit_rows():
    x = jax.random.normal(jax.random.key(0), (5, 7)) * 10
    n = np.linalg.norm(np.asarray(l2_normalize(x)), axis=-1)
    np.testing.assert_allclose(n, 1.0, rtol=1e-5)


def test_logits_column0_is_positive_similarity():
    kq, kk, kqueue = jax.random.split(jax.random.key(1), 3)
    q = _rand_unit(kq, (4, 8))
    k = _rand_unit(kk, (4, 8))
    queue = _rand_unit(kqueue, (32, 8))
    logits, labels = infonce_logits(q, k, queue, temperature=0.2)
    assert logits.shape == (4, 33)
    np.testing.assert_allclose(
        np.asarray(logits[:, 0]), np.sum(np.asarray(q * k), -1) / 0.2, rtol=1e-5
    )
    np.testing.assert_array_equal(np.asarray(labels), 0)


def test_loss_at_init_is_log_Kplus1():
    """With random unit q, k, queue and T=1 the expected loss ≈ log(K+1)."""
    K, dim, B = 4096, 128, 64
    kq, kk, kqueue = jax.random.split(jax.random.key(2), 3)
    q = _rand_unit(kq, (B, dim))
    k = _rand_unit(kk, (B, dim))
    queue = _rand_unit(kqueue, (K, dim))
    logits, labels = infonce_logits(q, k, queue, temperature=1.0)
    loss = float(softmax_cross_entropy(logits, labels))
    assert abs(loss - np.log(K + 1)) < 0.1


def test_no_gradient_reaches_queue_or_keys():
    kq, kk, kqueue = jax.random.split(jax.random.key(3), 3)
    q = _rand_unit(kq, (4, 8))
    k = _rand_unit(kk, (4, 8))
    queue = _rand_unit(kqueue, (16, 8))

    def loss_wrt_k_and_queue(k, queue):
        logits, labels = infonce_logits(q, jax.lax.stop_gradient(k), queue, 0.2)
        return softmax_cross_entropy(logits, labels)

    gk, gqueue = jax.grad(loss_wrt_k_and_queue, argnums=(0, 1))(k, queue)
    np.testing.assert_array_equal(np.asarray(gk), 0.0)
    np.testing.assert_array_equal(np.asarray(gqueue), 0.0)


def test_contrastive_accuracy_perfect_and_zero():
    logits = jnp.array([[10.0, 0.0, 0.0], [9.0, 1.0, 0.0]])
    labels = jnp.zeros(2, jnp.int32)
    acc1, acc5 = contrastive_accuracy(logits, labels)
    assert float(acc1) == 100.0
    logits_bad = jnp.array([[0.0, 10.0, 5.0, 4.0, 3.0, 2.0, 1.0]])
    acc1b, acc5b = contrastive_accuracy(logits_bad, jnp.zeros(1, jnp.int32))
    assert float(acc1b) == 0.0
    assert float(acc5b) == 0.0  # positive ranked 7th of 7


def test_v3_loss_single_device_matches_manual():
    kq, kk = jax.random.split(jax.random.key(4))
    q = _rand_unit(kq, (8, 16))
    k = _rand_unit(kk, (8, 16))
    loss = v3_contrastive_loss(q, k, temperature=0.5, axis_name=None)
    logits = np.asarray(q) @ np.asarray(k).T / 0.5
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    manual = -np.mean(np.diag(logp)) * 2 * 0.5
    np.testing.assert_allclose(float(loss), manual, rtol=1e-5)


def test_v3_loss_sharded_matches_single_device(mesh8):
    """The sharded v3 loss (all-gathered negatives + rank-offset labels) must
    equal the single-device computation on the same global batch."""
    kq, kk = jax.random.split(jax.random.key(5))
    q = _rand_unit(kq, (32, 16))
    k = _rand_unit(kk, (32, 16))
    ref = float(v3_contrastive_loss(q, k, 0.2, axis_name=None))

    def f(q, k):
        loss = v3_contrastive_loss(q, k, 0.2, axis_name=DATA_AXIS)
        return jax.lax.pmean(loss, DATA_AXIS)

    sharded = jax.jit(
        shard_map(f, mesh=mesh8, in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P())
    )(q, k)
    np.testing.assert_allclose(float(sharded), ref, rtol=1e-5)
