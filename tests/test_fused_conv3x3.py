"""Equivalence pins for the fused bn→relu→3x3-conv (stride 1) interior
fusion (ops/pallas_fused_conv3x3.py + models/fused_block.py).

Same proof ladder as the 1x1 tail (test_fused_conv.py): interpret-mode
kernel equivalence (incl. batch boundaries — zero padding must happen at
IMAGE edges, never leak across the folded batch), custom-VJP vs autodiff,
and hardware-free TPU (Mosaic) lowering at the real R50 conv2 shapes.
The module-level integration (param-tree identity, grads, running stats,
shard_map composition) is covered by test_fused_conv.py's Bottleneck tests,
which exercise BOTH fusions on stride-1 blocks.
"""

import jax
import jax.export  # noqa: F401  (binds the lazy submodule on 0.4.x)
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.models.fused_block import _bn_relu_conv3x3_train
from moco_tpu.ops.pallas_fused_conv3x3 import bn_relu_conv3x3, conv3x3_dw


def _ref(x, a, b, w):
    z = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0)
    return jax.lax.conv_general_dilated(
        z, w.astype(jnp.float32), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@pytest.mark.parametrize(
    "shape", [(2, 8, 8, 16, 32), (3, 12, 10, 8, 16), (1, 4, 16, 32, 8)]
)
def test_kernel_matches_conv_interpret(shape):
    bsz, h, w_, k, n = shape
    x = jax.random.normal(jax.random.key(0), (bsz, h, w_, k), jnp.float32)
    a = 1.0 + 0.1 * jax.random.normal(jax.random.key(1), (k,))
    b = 0.1 * jax.random.normal(jax.random.key(2), (k,))
    w = 0.1 * jax.random.normal(jax.random.key(3), (3, 3, k, n))
    got = bn_relu_conv3x3(x, a, b, w, out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_ref(x, a, b, w)), rtol=1e-4, atol=1e-4
    )


def test_batch_boundary_no_halo_leak_interpret():
    """Two images whose edge rows are wildly different: each image's output
    must equal its own single-image conv — any halo leak across the folded
    batch dimension shows up immediately."""
    k, n = 8, 8
    x0 = jnp.full((1, 4, 4, k), 100.0, jnp.float32)
    x1 = jnp.full((1, 4, 4, k), -100.0, jnp.float32)
    a = jnp.ones((k,))
    b = jnp.zeros((k,))
    w = 0.1 * jax.random.normal(jax.random.key(4), (3, 3, k, n))
    both = bn_relu_conv3x3(
        jnp.concatenate([x0, x1]), a, b, w, out_dtype=jnp.float32,
        interpret=True,
    )
    solo0 = bn_relu_conv3x3(x0, a, b, w, out_dtype=jnp.float32, interpret=True)
    solo1 = bn_relu_conv3x3(x1, a, b, w, out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(both[0]), np.asarray(solo0[0]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(both[1]), np.asarray(solo1[0]),
                               rtol=1e-5, atol=1e-5)


def _ref_s2(x, a, b, w):
    z = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0)
    return jax.lax.conv_general_dilated(
        z, w.astype(jnp.float32), (2, 2), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@pytest.mark.parametrize(
    "shape", [(2, 8, 8, 16, 32), (3, 12, 10, 8, 16), (1, 4, 16, 32, 8)]
)
def test_s2_kernel_matches_conv_interpret(shape):
    from moco_tpu.ops.pallas_fused_conv3x3 import bn_relu_conv3x3_s2

    bsz, h, w_, k, n = shape
    x = jax.random.normal(jax.random.key(40), (bsz, h, w_, k), jnp.float32)
    a = 1.0 + 0.1 * jax.random.normal(jax.random.key(41), (k,))
    b = 0.1 * jax.random.normal(jax.random.key(42), (k,))
    w = 0.1 * jax.random.normal(jax.random.key(43), (3, 3, k, n))
    got = bn_relu_conv3x3_s2(x, a, b, w, out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_ref_s2(x, a, b, w)), rtol=1e-4, atol=1e-4
    )


def test_s2_batch_boundary_no_halo_leak_interpret():
    """Stride-2 variant of the halo-leak probe: the di=-1 taps of each
    image's first output row must read PADDING (zero), not the previous
    image's last row."""
    from moco_tpu.ops.pallas_fused_conv3x3 import bn_relu_conv3x3_s2

    k, n = 8, 8
    x0 = jnp.full((1, 4, 4, k), 100.0, jnp.float32)
    x1 = jnp.full((1, 4, 4, k), -100.0, jnp.float32)
    a = jnp.ones((k,))
    b = jnp.zeros((k,))
    w = 0.1 * jax.random.normal(jax.random.key(44), (3, 3, k, n))
    both = bn_relu_conv3x3_s2(
        jnp.concatenate([x0, x1]), a, b, w, out_dtype=jnp.float32,
        interpret=True,
    )
    for i, xi in enumerate((x0, x1)):
        solo = bn_relu_conv3x3_s2(xi, a, b, w, out_dtype=jnp.float32,
                                  interpret=True)
        np.testing.assert_allclose(np.asarray(both[i]), np.asarray(solo[0]),
                                   rtol=1e-5, atol=1e-5)


def test_s2_custom_vjp_matches_autodiff():
    from moco_tpu.models.fused_block import _bn_relu_conv3x3s2_train

    eps = 1e-5
    x = jax.random.normal(jax.random.key(46), (2, 8, 8, 16), jnp.float32)
    scale = 1.0 + 0.1 * jax.random.normal(jax.random.key(47), (16,))
    bias = 0.1 * jax.random.normal(jax.random.key(48), (16,))
    w = 0.1 * jax.random.normal(jax.random.key(49), (3, 3, 16, 8))

    def unfused(x, scale, bias, w):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.mean(xf * xf, axis=(0, 1, 2)) - mean * mean
        z = jnp.maximum(
            (xf - mean) * (jax.lax.rsqrt(var + eps) * scale) + bias, 0.0
        )
        return jax.lax.conv_general_dilated(
            z, w, (2, 2), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def loss_fused(args):
        y, _, _ = _bn_relu_conv3x3s2_train(*args, eps, jnp.float32)
        return jnp.sum(y * jnp.sin(y))

    def loss_ref(args):
        return jnp.sum(unfused(*args) * jnp.sin(unfused(*args)))

    args = (x, scale, bias, w)
    lf, gf = jax.value_and_grad(loss_fused)(args)
    lr_, gr = jax.value_and_grad(loss_ref)(args)
    np.testing.assert_allclose(float(lf), float(lr_), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(gf), jax.tree.leaves(gr), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-4
        )


def test_s2_kernel_lowers_for_tpu_at_r50_shapes():
    from moco_tpu.ops.pallas_fused_conv3x3 import bn_relu_conv3x3_s2

    # the three stage-first conv2 sites of R50@224
    for (bsz, h, w_, k) in [(128, 56, 56, 128), (128, 28, 28, 256),
                            (128, 14, 14, 512)]:
        x = jax.ShapeDtypeStruct((bsz, h, w_, k), jnp.bfloat16)
        a = jax.ShapeDtypeStruct((k,), jnp.float32)
        b = jax.ShapeDtypeStruct((k,), jnp.float32)
        w = jax.ShapeDtypeStruct((3, 3, k, k), jnp.bfloat16)
        fn = lambda x, a, b, w: bn_relu_conv3x3_s2(x, a, b, w,
                                                   out_dtype=jnp.bfloat16)
        exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(x, a, b, w)
        assert "tpu_custom_call" in exp.mlir_module(), (bsz, h, w_, k)


@pytest.mark.parametrize(
    "shape", [(2, 8, 8, 16, 32), (3, 12, 10, 8, 16), (1, 4, 16, 32, 8)]
)
def test_dw_kernel_matches_conv_filter_grad_interpret(shape):
    """conv3x3_dw == autodiff's filter gradient of relu(x·a+b) ⊛ w."""
    bsz, h, w_, k, n = shape
    x = jax.random.normal(jax.random.key(10), (bsz, h, w_, k), jnp.float32)
    a = 1.0 + 0.1 * jax.random.normal(jax.random.key(11), (k,))
    b = 0.1 * jax.random.normal(jax.random.key(12), (k,))
    w = 0.1 * jax.random.normal(jax.random.key(13), (3, 3, k, n))
    dy = jax.random.normal(jax.random.key(14), (bsz, h, w_, n), jnp.float32)
    _, vjp = jax.vjp(lambda w_: _ref(x, a, b, w_), w)
    (want,) = vjp(dy)
    got = conv3x3_dw(x, a, b, dy, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_dw_kernel_batch_boundary_no_halo_leak_interpret():
    """The tap gradients must pair z and dy WITHIN an image only — summing
    per-image filter grads of wildly different images equals the batched
    call iff no halo leaks across the folded batch dimension."""
    k, n = 8, 8
    x0 = jax.random.normal(jax.random.key(15), (1, 4, 4, k)) * 100.0
    x1 = -x0 + jax.random.normal(jax.random.key(16), (1, 4, 4, k))
    a = jnp.ones((k,))
    b = jnp.zeros((k,))
    dy = jax.random.normal(jax.random.key(17), (2, 4, 4, n), jnp.float32)
    both = conv3x3_dw(jnp.concatenate([x0, x1]), a, b, dy, interpret=True)
    solo = (conv3x3_dw(x0, a, b, dy[:1], interpret=True)
            + conv3x3_dw(x1, a, b, dy[1:], interpret=True))
    np.testing.assert_allclose(np.asarray(both), np.asarray(solo),
                               rtol=1e-5, atol=1e-4)


def test_dw_kernel_lowers_for_tpu_at_r50_shapes():
    for (bsz, h, w_, k) in [
        (128, 56, 56, 64), (128, 28, 28, 128),
        (128, 14, 14, 256), (128, 7, 7, 512),
    ]:
        x = jax.ShapeDtypeStruct((bsz, h, w_, k), jnp.bfloat16)
        a = jax.ShapeDtypeStruct((k,), jnp.float32)
        b = jax.ShapeDtypeStruct((k,), jnp.float32)
        dy = jax.ShapeDtypeStruct((bsz, h, w_, k), jnp.bfloat16)
        fn = lambda x, a, b, dy: conv3x3_dw(x, a, b, dy)
        exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(x, a, b, dy)
        assert "tpu_custom_call" in exp.mlir_module(), (bsz, h, w_, k)


def test_custom_vjp_matches_autodiff():
    eps = 1e-5
    x = jax.random.normal(jax.random.key(6), (2, 6, 6, 16), jnp.float32)
    scale = 1.0 + 0.1 * jax.random.normal(jax.random.key(7), (16,))
    bias = 0.1 * jax.random.normal(jax.random.key(8), (16,))
    w = 0.1 * jax.random.normal(jax.random.key(9), (3, 3, 16, 8))

    def unfused(x, scale, bias, w):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.mean(xf * xf, axis=(0, 1, 2)) - mean * mean
        z = jnp.maximum(
            (xf - mean) * (jax.lax.rsqrt(var + eps) * scale) + bias, 0.0
        )
        return jax.lax.conv_general_dilated(
            z, w, (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def loss_fused(args):
        y, _, _ = _bn_relu_conv3x3_train(*args, eps, jnp.float32)
        return jnp.sum(y * jnp.sin(y))

    def loss_ref(args):
        y = unfused(*args)
        return jnp.sum(y * jnp.sin(y))

    args = (x, scale, bias, w)
    lf, gf = jax.value_and_grad(loss_fused)(args)
    lr_, gr = jax.value_and_grad(loss_ref)(args)
    np.testing.assert_allclose(float(lf), float(lr_), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(gf), jax.tree.leaves(gr), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-4
        )


def test_kernel_lowers_for_tpu_at_r50_shapes():
    for (bsz, h, w_, k) in [
        (128, 56, 56, 64), (128, 28, 28, 128),
        (128, 14, 14, 256), (128, 7, 7, 512),
    ]:
        x = jax.ShapeDtypeStruct((bsz, h, w_, k), jnp.bfloat16)
        a = jax.ShapeDtypeStruct((k,), jnp.float32)
        b = jax.ShapeDtypeStruct((k,), jnp.float32)
        w = jax.ShapeDtypeStruct((3, 3, k, k), jnp.bfloat16)
        fn = lambda x, a, b, w: bn_relu_conv3x3(x, a, b, w, out_dtype=jnp.bfloat16)
        exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(x, a, b, w)
        assert "tpu_custom_call" in exp.mlir_module(), (bsz, h, w_, k)


@pytest.mark.parametrize("train", [True, False])
def test_bottleneck_stride2_fused_equivalent(train):
    """The stage-first (stride-2) Bottleneck with fused_tail: identical
    param/stat tree, matching outputs/grads/running stats vs unfused —
    the r4 fusion site (previously these blocks kept the unfused path)."""
    from functools import partial

    import flax.linen as nn

    from moco_tpu.models.resnet import Bottleneck

    conv = partial(nn.Conv, use_bias=False, dtype=jnp.float32,
                   param_dtype=jnp.float32)
    norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                   epsilon=1e-5, dtype=jnp.float32, param_dtype=jnp.float32)
    kw = dict(filters=8, strides=2, conv=conv, norm=norm)
    plain = Bottleneck(**kw)
    fused = Bottleneck(fused_tail=True, bn_momentum=0.9, dtype=jnp.float32,
                       **kw)
    x = jax.random.normal(jax.random.key(50), (2, 8, 8, 16), jnp.float32)
    v = plain.init(jax.random.key(51), x)
    v2 = fused.init(jax.random.key(51), x)
    assert jax.tree.structure(v) == jax.tree.structure(v2)

    if train:
        out_a, mut_a = plain.apply(v, x, mutable=["batch_stats"])
        out_b, mut_b = fused.apply(v, x, mutable=["batch_stats"])
        for a, b_ in zip(jax.tree.leaves(mut_a), jax.tree.leaves(mut_b),
                         strict=True):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-5, atol=1e-6)

        def loss(params, model):
            out, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            return jnp.sum(out ** 2)

        ga = jax.grad(loss)(v["params"], plain)
        gb = jax.grad(loss)(v["params"], fused)
        for (pa, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ga),
            jax.tree_util.tree_leaves_with_path(gb),
            strict=True,
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=3e-4, atol=3e-4, err_msg=str(pa))
    else:
        out_a = plain.apply(v, x)
        out_b = fused.apply(v, x)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("train", [True, False])
def test_basicblock_fused_equivalent(train):
    """BasicBlock's bn1→relu→conv2 fusion (R18/34 path): identical
    param/stat tree, matching outputs/grads/running stats vs unfused."""
    from functools import partial

    import flax.linen as nn

    from moco_tpu.models.resnet import BasicBlock

    conv = partial(nn.Conv, use_bias=False, dtype=jnp.float32,
                   param_dtype=jnp.float32)
    norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                   epsilon=1e-5, dtype=jnp.float32, param_dtype=jnp.float32)
    kw = dict(filters=16, strides=1, conv=conv, norm=norm)
    plain = BasicBlock(**kw)
    fused = BasicBlock(fused_tail=True, bn_momentum=0.9, dtype=jnp.float32, **kw)
    x = jax.random.normal(jax.random.key(30), (2, 8, 8, 16), jnp.float32)
    v = plain.init(jax.random.key(31), x)
    v2 = fused.init(jax.random.key(31), x)
    assert jax.tree.structure(v) == jax.tree.structure(v2)

    if train:
        out_a, mut_a = plain.apply(v, x, mutable=["batch_stats"])
        out_b, mut_b = fused.apply(v, x, mutable=["batch_stats"])
        for a, b_ in zip(jax.tree.leaves(mut_a), jax.tree.leaves(mut_b),
                         strict=True):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-5, atol=1e-6)

        def loss(params, model):
            out, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            return jnp.sum(out ** 2)

        ga = jax.grad(loss)(v["params"], plain)
        gb = jax.grad(loss)(v["params"], fused)
        for (pa, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ga),
            jax.tree_util.tree_leaves_with_path(gb),
            strict=True,
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=3e-4, atol=3e-4, err_msg=str(pa))
    else:
        out_a = plain.apply(v, x)
        out_b = fused.apply(v, x)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)
