"""Full-resolution staging fidelity (VERDICT r2 missing #3).

torchvision's RandomResizedCrop samples from the ORIGINAL photo
(`main_moco.py:≈L232`); our host stages the whole image into a fixed canvas
and the device crops from that. These tests pin the two guarantees that make
the pipelines equivalent:

1. no-upsample staging: an image that fits the canvas is staged PIXEL-EXACT
   (so on-device crops read original pixels, and a crop from the staged
   canvas IS the crop from the original);
2. for images larger than the canvas (fit-downscaled), a small-scale crop
   taken from the staged canvas matches the same crop taken from the
   original within interpolation tolerance.
"""

import os

import numpy as np
import pytest

from moco_tpu.data.datasets import ImageFolder, build_dataset


def _png_tree(tmp_path, arrays):
    from PIL import Image

    root = tmp_path / "tree"
    d = root / "class0"
    os.makedirs(d, exist_ok=True)
    for i, arr in enumerate(arrays):
        Image.fromarray(arr).save(d / f"{i:03d}.png")
    return str(root)


def test_staging_is_pixel_exact_when_image_fits(tmp_path):
    rng = np.random.RandomState(0)
    orig = rng.randint(0, 256, (300, 400, 3), dtype=np.uint8)  # landscape
    root = _png_tree(tmp_path, [orig])
    folder = ImageFolder(root, stage_size=512, backend="pil")
    imgs, _, extents = folder.get_batch(np.array([0]))
    h, w, rot = extents[0]
    assert (h, w, rot) == (300, 400, 0)
    np.testing.assert_array_equal(imgs[0, :300, :400], orig)
    # edge-replicated padding, not black
    np.testing.assert_array_equal(imgs[0, :300, 400], orig[:, -1])
    np.testing.assert_array_equal(imgs[0, 300, :], imgs[0, 299, :])


def test_staging_portrait_transposed_pixel_exact(tmp_path):
    rng = np.random.RandomState(1)
    orig = rng.randint(0, 256, (400, 300, 3), dtype=np.uint8)  # portrait
    root = _png_tree(tmp_path, [orig])
    folder = ImageFolder(root, stage_size=512, backend="pil")
    imgs, _, extents = folder.get_batch(np.array([0]))
    h, w, rot = extents[0]
    assert (h, w, rot) == (300, 400, 1)
    np.testing.assert_array_equal(imgs[0, :300, :400], orig.swapaxes(0, 1))


def test_crop_from_staged_matches_crop_from_original(tmp_path):
    """The VERDICT-prescribed pin: a small-scale crop resampled from the
    staged canvas vs the SAME crop resampled from the original photo.

    Case A (fits the canvas): bit-identical, because staging is a paste.
    Case B (downscaled 800x1100 -> 512-canvas): equal within interpolation
    tolerance on the uint8 scale."""
    import jax.numpy as jnp

    from moco_tpu.ops.matmul_resize import crop_resize

    rng = np.random.RandomState(2)
    # smooth-ish content: pure noise makes resample-order differences look
    # large; real photos are low-frequency dominated
    small = rng.randint(0, 256, (12, 16, 3)).astype(np.uint8)
    from PIL import Image

    big = np.asarray(
        Image.fromarray(small).resize((1100, 800), Image.BILINEAR), np.uint8
    )
    orig_a = big[:375, :500]  # 375x500: fits a 512x1024 canvas
    root = _png_tree(tmp_path, [orig_a, big])
    folder = ImageFolder(root, stage_size=512, backend="pil")
    imgs, _, extents = folder.get_batch(np.array([0, 1]))

    # --- case A: staged pixel-exact -> identical interpolation inputs ---
    y0, x0, ch, cw = 40.0, 60.0, 150.0, 200.0
    got = crop_resize(
        jnp.asarray(imgs[0], jnp.float32), y0, x0, ch, cw, 64,
        valid_h=extents[0, 0], valid_w=extents[0, 1],
    )
    want = crop_resize(jnp.asarray(orig_a, jnp.float32), y0, x0, ch, cw, 64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)

    # --- case B: 800x1100 downscaled by 0.64 into the canvas ---
    h, w, rot = extents[1]
    assert rot == 0 and h < 800  # really downscaled
    s = h / 800.0
    y0, x0, ch, cw = 100.0, 150.0, 400.0, 520.0  # in ORIGINAL coordinates
    got = crop_resize(
        jnp.asarray(imgs[1], jnp.float32),
        y0 * s, x0 * s, ch * s, cw * s, 64,
        valid_h=extents[1, 0], valid_w=extents[1, 1],
    )
    want = crop_resize(jnp.asarray(big, jnp.float32), y0, x0, ch, cw, 64)
    err = np.abs(np.asarray(got) - np.asarray(want))
    assert err.mean() < 2.5, f"mean abs err {err.mean():.2f} on uint8 scale"
    assert np.percentile(err, 99) < 12.0


def test_build_dataset_plumbs_staging_knobs(tmp_path):
    """stage_size / num_workers reach ImageFolder through build_dataset
    (they were dead config surface in r2 — VERDICT weak #6)."""
    rng = np.random.RandomState(3)
    root = _png_tree(tmp_path, [rng.randint(0, 256, (64, 80, 3), dtype=np.uint8)])
    ds = build_dataset("imagefolder", root, image_size=224,
                       stage_size=96, num_workers=2, backend="pil")
    assert ds.stage_h == 96 and ds.stage_w == 192
    assert ds._pool._max_workers == 2
    # 0 = class defaults
    ds = build_dataset("imagefolder", root, image_size=224, backend="pil")
    assert ds.stage_h == 512
    # synthetic ignores the knobs without error
    ds = build_dataset("synthetic", image_size=32, stage_size=96, num_workers=2)
    assert len(ds) > 0
