"""Disaggregated input service (ISSUE 14).

  - protocol: frame round-trips, bounds/garbage rejection, endpoint
    parsing, structured error surfacing
  - prestage: decode-once mmap format round-trips bit-identical; every
    incomplete/drifted directory is refused loudly
  - ServiceClient vs in-process Prefetcher: BIT-IDENTICAL batches on the
    same seed/epoch (the ISSUE acceptance pin), including when the rows
    come from a pre-staged epoch cache
  - failure contract: retry-on-another-server for dead peers, immediate
    surfacing of non-retryable remote errors, loud config-drift refusal
  - chaos: kill_at_shard / stall_at_shard knobs parse and fire once
  - resilience plumbing: EXIT_STAGING_BIND from both CLI halves,
    classify_exit -> CLASS_STAGING_BIND (fatal: reschedule, don't race)
  - telemetry: per-server stats fold into telemetry_report; the obsd
    input_credit_stall_rate objective; cross-process serve_shard spans
    continue the coordinator's stage_batch trace
  - THE tier-1 drill: SIGKILL one of two real staging servers mid-epoch
    -> every shard re-lands on the survivor, the epoch is bit-identical,
    zero lost batches, and the supervisor relaunches the dead worker

Fast tests run DecodeWorker in-thread (real sockets, no subprocess);
only the drill and the slow soak spawn real server processes.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.data.datasets import SyntheticDataset
from moco_tpu.data.loader import epoch_loader
from moco_tpu.data.service import protocol
from moco_tpu.data.service.client import (
    ServiceClient,
    ServiceConfigError,
    service_epoch_loader,
)
from moco_tpu.data.service.fleet import LocalServerPool
from moco_tpu.data.service.prestage import (
    PrestageError,
    PrestagedDataset,
    write_prestage,
)
from moco_tpu.data.service.worker import DecodeWorker
from moco_tpu.data.service.worker import main as worker_main
from moco_tpu.data.stats import InputPipelineStats
from moco_tpu.resilience.chaos import ChaosPlan, parse_chaos_spec
from moco_tpu.resilience.exitcodes import (
    EXIT_CODE_NAMES,
    EXIT_STAGING_BIND,
)
from moco_tpu.resilience.supervisor import (
    CLASS_STAGING_BIND,
    FATAL_CLASSES,
    classify_exit,
)

N_SAMPLES = 64
GLOBAL_BATCH = 16  # 8 fake devices x 2 rows; 4 batches per epoch


def _dataset(**kw):
    kw.setdefault("num_samples", N_SAMPLES)
    kw.setdefault("image_size", 32)
    kw.setdefault("seed", 0)
    return SyntheticDataset(**kw)


def _start_worker(dataset, **kw):
    """One in-thread DecodeWorker on an auto port (real sockets, real
    protocol, no subprocess)."""
    worker = DecodeWorker(dataset, "127.0.0.1", 0, **kw)
    t = threading.Thread(target=worker.serve_forever, daemon=True,
                         name="test-worker")
    t.start()
    return worker


def _drain(loader):
    """[(imgs, labels, extents) as numpy] for every yielded batch."""
    return [(np.asarray(i), np.asarray(l), np.asarray(e))
            for i, l, e in loader]


def _assert_batches_equal(got, want):
    assert len(got) == len(want)
    for (gi, gl, ge), (wi, wl, we) in zip(got, want):
        np.testing.assert_array_equal(gi, wi)
        np.testing.assert_array_equal(gl, wl)
        np.testing.assert_array_equal(ge, we)


def _reference_epoch(mesh8, epoch=1, dataset=None):
    loader = epoch_loader(dataset if dataset is not None else _dataset(),
                          epoch, 0, GLOBAL_BATCH, mesh8, workers=2)
    try:
        return _drain(loader)
    finally:
        loader.close_quietly()


# ---------------------------------------------------------------------------
# protocol
# ---------------------------------------------------------------------------


def test_frame_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        payload = np.arange(16, dtype="<i8").tobytes()
        protocol.send_frame(a, {"op": "shard", "batch": 3}, payload)
        header, got = protocol.recv_frame(b)
        assert header == {"op": "shard", "batch": 3}
        assert got == payload
        protocol.send_frame(b, {"op": "pong", "stats": {}})  # empty payload
        header, got = protocol.recv_frame(a)
        assert header["op"] == "pong" and got == b""
    finally:
        a.close()
        b.close()


def test_frame_bounds_and_garbage_rejected():
    a, b = socket.socketpair()
    try:
        with pytest.raises(protocol.FrameError, match="bounds"):
            protocol.send_frame(
                a, {"op": "x"}, b"\0" * (protocol.MAX_PAYLOAD_BYTES + 1))
        # a foreign/corrupt prefix must refuse, not allocate gigabytes
        a.sendall(b"\xff\xff\xff\xff\xff\xff\xff\xff")
        with pytest.raises(protocol.FrameError, match="not this protocol"):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()
    # a peer hanging up mid-frame is a ConnectionError (retry food)
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00\x08")  # half a prefix, then gone
        a.close()
        with pytest.raises(ConnectionError):
            protocol.recv_frame(b)
    finally:
        b.close()


def test_parse_endpoints_forms_and_errors():
    assert protocol.parse_endpoints("h1:1, h2:2;h3:3,") == [
        ("h1", 1), ("h2", 2), ("h3", 3)]
    with pytest.raises(ValueError, match="not host:port"):
        protocol.parse_endpoints("just-a-host")
    with pytest.raises(ValueError, match="non-integer port"):
        protocol.parse_endpoints("h:eighty")
    with pytest.raises(ValueError, match="no endpoints"):
        protocol.parse_endpoints(" , ")


def test_raise_if_error_surfaces_remote_shard_error():
    with pytest.raises(protocol.RemoteShardError) as exc:
        protocol.raise_if_error({"op": "error", "code": "transient",
                                 "detail": "flaky read",
                                 "retryable": True})
    assert exc.value.retryable and exc.value.code == "transient"
    protocol.raise_if_error({"op": "data"})  # not an error: no raise


# ---------------------------------------------------------------------------
# prestage format
# ---------------------------------------------------------------------------


def test_prestage_roundtrip_bit_identical(tmp_path):
    ds = _dataset()
    root = str(tmp_path / "pre")
    meta = write_prestage(ds, root, chunk=10)
    assert meta["n"] == N_SAMPLES
    pre = PrestagedDataset(root)
    assert len(pre) == N_SAMPLES
    idx = np.asarray([0, 5, 63, 7])
    want_i, want_l, want_e = ds.get_batch(idx)
    got_i, got_l, got_e = pre.get_batch(idx)
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_array_equal(got_l, want_l)
    np.testing.assert_array_equal(got_e, want_e)
    # the staging-canvas protocol: memcpy into caller-owned rows
    out_i = np.zeros_like(want_i)
    out_e = np.zeros_like(want_e)
    labels = pre.get_batch_into(idx, out_i, out_e)
    np.testing.assert_array_equal(out_i, want_i)
    np.testing.assert_array_equal(out_e, want_e)
    np.testing.assert_array_equal(np.asarray(labels), want_l)


def test_prestage_refuses_incomplete_and_drifted(tmp_path):
    ds = _dataset(num_samples=8)
    root = str(tmp_path / "pre")
    write_prestage(ds, root)
    # never silently overwrite a whole-cluster artifact
    with pytest.raises(PrestageError, match="already holds"):
        write_prestage(ds, root)
    # missing meta == killed writer == not a prestage
    incomplete = str(tmp_path / "torn")
    os.makedirs(incomplete)
    with pytest.raises(PrestageError, match="no meta.json"):
        PrestagedDataset(incomplete)
    # meta/payload drift is refused loudly
    meta_path = os.path.join(root, "meta.json")
    with open(meta_path, encoding="utf-8") as f:
        meta = json.load(f)
    meta["n"] = 9
    with open(meta_path, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    with pytest.raises(PrestageError, match="disagrees with meta"):
        PrestagedDataset(root)
    # a future format version is refused, not misread
    meta["n"] = 8
    meta["v"] = 999
    with open(meta_path, "w", encoding="utf-8") as f:
        json.dump(meta, f)
    with pytest.raises(PrestageError, match="v999"):
        PrestagedDataset(root)


# ---------------------------------------------------------------------------
# ServiceClient vs in-process Prefetcher: THE bit-identity pin
# ---------------------------------------------------------------------------


def test_service_bit_identical_to_inprocess(mesh8):
    """Two in-thread staging servers, same dataset code: every service-fed
    batch equals the in-process Prefetcher batch bit-for-bit, same order,
    none lost."""
    want = _reference_epoch(mesh8)
    w1 = _start_worker(_dataset())
    w2 = _start_worker(_dataset())
    client = None
    try:
        client = service_epoch_loader(
            [(w1.host, w1.port), (w2.host, w2.port)], N_SAMPLES, 1, 0,
            GLOBAL_BATCH, mesh8, streams=2, backoff_secs=0.05)
        got = _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        w1.stop(timeout_s=1.0)
        w2.stop(timeout_s=1.0)
    _assert_batches_equal(got, want)
    # both servers actually served (streams round-robin the endpoints)
    assert w1.stats.shards + w2.stats.shards >= 4


def test_service_bit_identical_from_prestage(mesh8, tmp_path):
    """The degenerate cache-everything case: a server answering from the
    pre-staged epoch cache yields the same bits as in-process decode."""
    want = _reference_epoch(mesh8)
    root = str(tmp_path / "pre")
    write_prestage(_dataset(), root)
    worker = _start_worker(PrestagedDataset(root), prestaged=True)
    client = None
    try:
        client = service_epoch_loader(
            f"{worker.host}:{worker.port}", N_SAMPLES, 1, 0,
            GLOBAL_BATCH, mesh8, streams=2)
        assert client.meta["prestaged"] is True
        got = _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        worker.stop(timeout_s=1.0)
    _assert_batches_equal(got, want)


def test_chunked_shards_bit_identical(mesh8):
    """The frame payload bound means a big per-host batch must split
    into multiple shard requests (client.MAX_SHARD_ROWS math); pin that
    a tiny forced cap — every fetch chunked, including the whole-batch
    shape-discovery path — still yields bit-identical epochs."""
    from moco_tpu.data.loader import epoch_permutation, host_shard

    want = _reference_epoch(mesh8)
    indices = host_shard(epoch_permutation(N_SAMPLES, 1, 0, GLOBAL_BATCH),
                         GLOBAL_BATCH)
    worker = _start_worker(_dataset())
    client = None
    try:
        client = ServiceClient(
            [(worker.host, worker.port)], indices, GLOBAL_BATCH, mesh8,
            streams=2, max_shard_rows=3)
        got = _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        worker.stop(timeout_s=1.0)
    _assert_batches_equal(got, want)
    # the cap really forced chunking: 4 batches x 16 rows / <=3 rows
    assert worker.stats.shards >= 4 * 6


def test_inprocess_prefetcher_over_prestage_bit_identical(mesh8, tmp_path):
    """The OTHER prestage consumer: the plain Prefetcher pointed at the
    mmap (config.input_prestage) matches fresh decode bit-for-bit."""
    want = _reference_epoch(mesh8)
    root = str(tmp_path / "pre")
    write_prestage(_dataset(), root)
    got = _reference_epoch(mesh8, dataset=PrestagedDataset(root))
    _assert_batches_equal(got, want)


# ---------------------------------------------------------------------------
# failure contract
# ---------------------------------------------------------------------------


def test_client_retries_shards_on_another_server(mesh8):
    """One endpoint is a peer that accepts and instantly hangs up: every
    shard it was offered re-lands on the healthy server, the epoch stays
    bit-identical and complete."""
    want = _reference_epoch(mesh8)
    stop = threading.Event()
    refuser = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    refuser.bind(("127.0.0.1", 0))
    refuser.listen(8)
    refuser.settimeout(0.1)
    dead_port = refuser.getsockname()[1]

    def _refuse():
        while not stop.is_set():
            try:
                conn, _ = refuser.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                return

    t = threading.Thread(target=_refuse, daemon=True)
    t.start()
    worker = _start_worker(_dataset())
    client = None
    try:
        client = service_epoch_loader(
            [("127.0.0.1", dead_port), (worker.host, worker.port)],
            N_SAMPLES, 1, 0, GLOBAL_BATCH, mesh8, streams=2,
            backoff_secs=0.05)
        got = _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        worker.stop(timeout_s=1.0)
        stop.set()
        refuser.close()
    _assert_batches_equal(got, want)
    assert worker.stats.shards >= 4  # the survivor carried the epoch


def test_client_surfaces_nonretryable_error_immediately(mesh8):
    """A non-retryable remote error must NOT burn the retry budget — it
    is a programming/config error, surfaced as-is."""
    stop = threading.Event()
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    lsock.settimeout(0.1)
    port = lsock.getsockname()[1]
    meta = {"op": protocol.OP_META, "n": N_SAMPLES,
            "img_shape": [32, 32, 3], "img_dtype": "uint8",
            "label_dtype": "int32", "server_id": 7}

    def _serve_one(conn):
        # one thread per connection: the client's handshake link stays
        # open (and silent) while its fetch thread opens another
        try:
            conn.settimeout(10.0)
            header, _ = protocol.recv_frame(conn)
            if header.get("op") == protocol.OP_HELLO:
                protocol.send_frame(conn, meta)
                header, _ = protocol.recv_frame(conn)
            if header.get("op") == protocol.OP_SHARD:
                protocol.send_frame(conn, {
                    "op": protocol.OP_ERROR,
                    "code": protocol.ERR_BAD_REQUEST,
                    "detail": "dataset drift", "retryable": False})
        except (ConnectionError, protocol.FrameError, OSError):
            pass
        finally:
            conn.close()

    def _serve():
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=_serve_one, args=(conn,),
                             daemon=True).start()

    t = threading.Thread(target=_serve, daemon=True)
    t.start()
    client = None
    try:
        client = ServiceClient(
            [("127.0.0.1", port)], np.arange(GLOBAL_BATCH), GLOBAL_BATCH,
            # retries=50: proof the non-retryable error skips the budget
            mesh8, retries=50, backoff_secs=0.01, streams=1)
        with pytest.raises(protocol.RemoteShardError, match="drift"):
            _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        stop.set()
        lsock.close()


def test_worker_answers_error_on_garbage_shard_payload():
    """A shard payload that is not a whole number of <i8 indices answers
    a non-retryable bad_request ERROR frame — and the connection thread
    survives to serve the next (well-formed) request."""
    worker = _start_worker(_dataset())
    try:
        with socket.create_connection((worker.host, worker.port),
                                      timeout=5.0) as sock:
            sock.settimeout(5.0)
            protocol.send_frame(sock, {"op": protocol.OP_HELLO,
                                       "role": "client",
                                       "proto": protocol.PROTO_VERSION})
            protocol.recv_frame(sock)  # meta
            protocol.send_frame(sock, {"op": protocol.OP_SHARD,
                                       "batch": 0, "lo": 0, "hi": 1},
                                b"1234567")
            header, _ = protocol.recv_frame(sock)
            assert header["op"] == protocol.OP_ERROR
            assert header["code"] == protocol.ERR_BAD_REQUEST
            assert header["retryable"] is False
            idx = np.zeros(1, dtype="<i8").tobytes()
            protocol.send_frame(sock, {"op": protocol.OP_SHARD,
                                       "batch": 0, "lo": 0, "hi": 1},
                                idx)
            header, _ = protocol.recv_frame(sock)
            assert header["op"] == protocol.OP_DATA
    finally:
        worker.stop(timeout_s=1.0)


def test_client_retries_malformed_data_answer_on_another_server(mesh8):
    """A data answer with garbage/missing shapes is a peer speaking
    garbage — the SAME retry-on-another-server class as a torn frame
    (FrameError), not a run-killing KeyError: the epoch completes
    bit-identically off the healthy server."""
    worker = _start_worker(_dataset())
    stop = threading.Event()
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(8)
    lsock.settimeout(0.1)
    bad_port = lsock.getsockname()[1]
    meta = {"op": protocol.OP_META, "n": N_SAMPLES,
            "img_shape": [32, 32, 3], "img_dtype": "uint8",
            "label_dtype": "int32", "server_id": 9}

    def _serve_one(conn):
        try:
            conn.settimeout(10.0)
            while True:
                header, _ = protocol.recv_frame(conn)
                if header.get("op") == protocol.OP_HELLO:
                    protocol.send_frame(conn, meta)
                elif header.get("op") == protocol.OP_SHARD:
                    # well-framed, wrong content: no shapes/dtypes keys
                    protocol.send_frame(conn, {"op": protocol.OP_DATA},
                                        b"")
                else:
                    return
        except (ConnectionError, protocol.FrameError, OSError):
            pass
        finally:
            conn.close()

    def _serve():
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=_serve_one, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=_serve, daemon=True).start()
    client = None
    try:
        client = service_epoch_loader(
            f"127.0.0.1:{bad_port},{worker.host}:{worker.port}",
            N_SAMPLES, 1, 0, GLOBAL_BATCH, mesh8, streams=2,
            backoff_secs=0.01)
        got = _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        stop.set()
        lsock.close()
        worker.stop(timeout_s=1.0)
    _assert_batches_equal(got, _reference_epoch(mesh8))


def test_client_refuses_unreachable_and_drifted_config(mesh8):
    # nothing listening: a configuration error, not a silent stall
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()  # bound-then-closed: connection refused
    client = None
    try:
        with pytest.raises(ServiceConfigError, match="no staging server"):
            client = ServiceClient(
                [("127.0.0.1", dead_port)], np.arange(GLOBAL_BATCH),
                GLOBAL_BATCH, mesh8, connect_timeout_s=0.5)
    finally:
        if client is not None:  # ctor raised: nothing to close
            client.close_quietly()
    # a server whose dataset length disagrees with the run's is refused
    worker = _start_worker(_dataset(num_samples=32))
    try:
        with pytest.raises(ServiceConfigError, match="32 samples"):
            client = ServiceClient(
                [(worker.host, worker.port)], np.arange(GLOBAL_BATCH),
                GLOBAL_BATCH, mesh8, expected_len=N_SAMPLES)
    finally:
        if client is not None:
            client.close_quietly()
        worker.stop(timeout_s=1.0)
    # EVERY server is validated, not just the first reachable one: a
    # same-length server with drifted canvas geometry is refused the
    # moment a fetch thread connects to it — never silently-wrong rows
    w_a = _start_worker(_dataset())
    w_b = _start_worker(_dataset(image_size=16))  # same n, 16x16 canvas
    client = None
    try:
        with pytest.raises(ServiceConfigError, match="disagrees on"):
            client = service_epoch_loader(
                [(w_a.host, w_a.port), (w_b.host, w_b.port)], N_SAMPLES,
                1, 0, GLOBAL_BATCH, mesh8, streams=2)
            _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        w_a.stop(timeout_s=1.0)
        w_b.stop(timeout_s=1.0)


def test_config_knob_validation():
    with pytest.raises(ValueError, match="not host:port"):
        PretrainConfig(input_service="garbage")
    with pytest.raises(ValueError, match="mutually exclusive"):
        PretrainConfig(input_service="127.0.0.1:4000", h2d_trim=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        PretrainConfig(input_service="127.0.0.1:4000",
                       input_prestage="/some/prestage")
    with pytest.raises(ValueError, match="input_request_timeout_s"):
        PretrainConfig(input_request_timeout_s=0)
    # valid spec + the in-process default both construct fine
    assert PretrainConfig(input_service="h1:4000,h2:4000").input_service
    assert PretrainConfig().input_service == ""


# ---------------------------------------------------------------------------
# chaos knobs
# ---------------------------------------------------------------------------


def test_client_retries_injected_transient_faults(mesh8):
    """The PR 1 contract on the CLIENT side: a chaos-injected
    TransientDataError inside _fetch_rows re-enters the retry budget —
    the service twin of test_prefetcher_retries_transient_reads."""
    from moco_tpu.resilience.chaos import chaos_context

    want = _reference_epoch(mesh8)
    worker = _start_worker(_dataset())
    client = None
    try:
        with chaos_context(ChaosPlan(loader_error_at_batch=1,
                                     loader_error_count=2)):
            client = service_epoch_loader(
                f"{worker.host}:{worker.port}", N_SAMPLES, 1, 0,
                GLOBAL_BATCH, mesh8, streams=2, retries=3,
                backoff_secs=0.01)
            got = _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        worker.stop(timeout_s=1.0)
    _assert_batches_equal(got, want)


def test_chaos_shard_knobs_parse_and_stall_fires_once(tmp_path):
    plan = parse_chaos_spec("kill_at_shard=3,stall_at_shard=2,stall_ms=40")
    assert plan.kill_at_shard == 3 and plan.stall_at_shard == 2
    plan.state_dir = str(tmp_path)
    t0 = time.perf_counter()
    plan.maybe_stall_shard(2)
    assert time.perf_counter() - t0 >= 0.04  # it really stalled
    assert os.path.exists(tmp_path / "fired_stall_shard")
    # fire-once ACROSS processes: a fresh plan sharing the state dir
    # (the supervisor-relaunched worker) must not re-fire
    relaunched = ChaosPlan(stall_at_shard=2, stall_ms=40,
                           state_dir=str(tmp_path))
    t0 = time.perf_counter()
    relaunched.maybe_stall_shard(2)
    assert time.perf_counter() - t0 < 0.04


# ---------------------------------------------------------------------------
# resilience plumbing
# ---------------------------------------------------------------------------


def test_worker_bind_failure_exits_staging_bind(tmp_path):
    blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        rc = worker_main(["--dataset", "synthetic", "--num-samples", "8",
                          "--image-size", "16", "--port", str(port)])
    finally:
        blocker.close()
    assert rc == EXIT_STAGING_BIND


def test_worker_misconfigured_data_dir_exits_config_error(tmp_path):
    """--data-dir at a file (NotADirectoryError — an OSError that is NOT
    FileNotFoundError) is a config-class death: EXIT_CONFIG_ERROR, so
    the supervisor abandons instead of relaunch-looping the budget."""
    from moco_tpu.resilience.exitcodes import EXIT_CONFIG_ERROR

    not_a_dir = tmp_path / "data"
    not_a_dir.write_text("not a directory")
    rc = worker_main(["--dataset", "imagefolder",
                      "--data-dir", str(not_a_dir / "train")])
    assert rc == EXIT_CONFIG_ERROR


def test_staging_server_cli_health_bind_failure(tmp_path):
    from tools.staging_server import main as cli_main

    blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        # health binds FIRST in the supervisor ctor: the CLI fails with
        # EXIT_STAGING_BIND before any worker subprocess exists
        rc = cli_main(["--health-port", str(port), "--telemetry-dir",
                       str(tmp_path), "--dataset", "synthetic"])
    finally:
        blocker.close()
    assert rc == EXIT_STAGING_BIND


def test_probe_decode_fault_is_not_a_bind_failure():
    """A transient read fault on the row-0 meta probe must NOT exit
    EXIT_STAGING_BIND — that class is fatal (the supervisor abandons);
    a storage blip has to surface as a plain restartable crash."""
    from moco_tpu.data.service.worker import ProbeDecodeError

    class _FlakyProbe:
        def __len__(self):
            return 8

        def get_batch(self, indices):
            raise OSError("EIO: storage blip")

    with pytest.raises(ProbeDecodeError):
        DecodeWorker(_FlakyProbe(), "127.0.0.1", 0)


def test_staging_bind_classification_is_fatal():
    cls, _detail = classify_exit(EXIT_STAGING_BIND)
    assert cls == CLASS_STAGING_BIND
    assert CLASS_STAGING_BIND in FATAL_CLASSES  # reschedule, don't race
    assert EXIT_CODE_NAMES[EXIT_STAGING_BIND] == "staging_bind"


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


def test_worker_stats_and_credit_stall_accounting():
    stats = InputPipelineStats()
    stats.note_workers(2)
    stats.note_credit_stall(0.5)
    stats.note_credit_stall(0.25)
    time.sleep(0.002)  # wall_s rounds to ms: give it one tick
    snap = stats.snapshot()
    assert snap["credit_stall_s"] == 0.75
    assert snap["wall_s"] > 0


def test_obsd_input_credit_stall_rate_objective():
    from moco_tpu.telemetry.aggregate import RunWindow

    w = RunWindow("r1")
    for i, (stall, wall) in enumerate([(0.0, 10.0), (2.0, 20.0)]):
        w.ingest({"kind": "step", "step": i, "step_s": 0.1,
                  "input": {"credit_stall_s": stall, "wall_s": wall}},
                 "src", "p", now=100.0 + i)
    # delta: 2.0 s stalled over 10.0 s of wall
    assert w.metric("input_credit_stall_rate", 60.0, 102.0) == \
        pytest.approx(0.2)


def test_report_folds_staging_server_dirs(tmp_path):
    from tools.telemetry_report import (
        expand_events_arg,
        render,
        summarize,
    )

    sdir = tmp_path / "staging_server0"
    sdir.mkdir()
    records = [
        {"v": 1, "t": 1.0, "kind": "input_server", "event": "launch",
         "server_id": 0, "pid": 123},
        {"v": 1, "t": 2.0, "kind": "input_server", "event": "stats",
         "server_id": 0, "shards": 40, "streamed_mb": 128.5,
         "shard_s_p50": 0.004, "shard_s_p95": 0.011, "decode_s": 1.5,
         "credit_stall_s": 3.0, "wall_s": 60.0, "errors": 1,
         "connections": 2, "cache_hit_rate": 0.75},
        {"v": 1, "t": 3.0, "kind": "input_server", "event": "worker_exit",
         "server_id": 0, "returncode": -9,
         "classification": "native_crash"},
    ]
    with open(sdir / "events.jsonl", "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    pairs = expand_events_arg(str(tmp_path))
    assert [label for label, _ in pairs] == ["staging_server0"]
    summary = summarize(records)
    isv = summary["input_servers"]
    assert isv["n_servers"] == 1
    assert isv["totals"] == {"shards": 40, "streamed_mb": 128.5,
                             "errors": 1}
    server = isv["servers"]["0"]
    assert server["stats"]["cache_hit_rate"] == 0.75
    assert server["events"] == {"launch": 1, "worker_exit": 1}
    assert server["death_classes"] == ["native_crash"]
    text = render(summary)
    assert "input service: 1 staging server(s)" in text
    assert "cache 75.0% hit" in text


def test_report_sums_stats_across_worker_lives():
    """A decode-worker relaunch restarts WorkerStats from zero; the
    report detects the counter reset and SUMS additive counters across
    lives — the kill-drill report must still count every shard the
    pre-kill life served. Latency window / hit rate stay the last
    life's (percentiles don't merge)."""
    from tools.telemetry_report import summarize

    records = [
        {"v": 1, "kind": "input_server", "event": "stats", "server_id": 0,
         "shards": 5, "streamed_mb": 10.0, "wall_s": 30.0, "errors": 1,
         "shard_s_p50": 0.01, "credit_stall_s": 2.0, "decode_s": 1.0},
        {"v": 1, "kind": "input_server", "event": "worker_exit",
         "server_id": 0, "returncode": -9,
         "classification": "native_crash"},
        {"v": 1, "kind": "input_server", "event": "launch", "server_id": 0},
        {"v": 1, "kind": "input_server", "event": "stats", "server_id": 0,
         "shards": 3, "streamed_mb": 6.0, "wall_s": 4.0, "errors": 0,
         "shard_s_p50": 0.02, "credit_stall_s": 0.5, "decode_s": 0.4},
    ]
    isv = summarize(records)["input_servers"]
    stats = isv["servers"]["0"]["stats"]
    assert stats["shards"] == 8
    assert stats["streamed_mb"] == 16.0
    assert stats["errors"] == 1
    assert stats["wall_s"] == 34.0
    assert stats["shard_s_p50"] == 0.02
    assert isv["totals"] == {"shards": 8, "streamed_mb": 16.0,
                             "errors": 1}

    # pid-stamped records detect the relaunch EXACTLY: here the new
    # life's first snapshot already exceeds the old life's last (no
    # counter ever decreases), which the legacy heuristic would miss
    pid_records = [
        {"v": 1, "kind": "input_server", "event": "stats", "server_id": 1,
         "pid": 100, "shards": 1, "streamed_mb": 2.0, "wall_s": 2.0,
         "errors": 0},
        {"v": 1, "kind": "input_server", "event": "stats", "server_id": 1,
         "pid": 200, "shards": 1, "streamed_mb": 2.0, "wall_s": 8.0,
         "errors": 0},
    ]
    stats = summarize(pid_records)["input_servers"]["servers"]["1"]["stats"]
    assert stats["shards"] == 2
    assert stats["wall_s"] == 10.0
    assert "_stats_pid" not in summarize(pid_records)[
        "input_servers"]["servers"]["1"]


def test_service_dataset_len_from_meta_probe():
    """input_service without the kNN monitor must not need a local
    dataset build: the length comes from one handshake meta probe (the
    remote-decode topology's train host may not even mount the data
    tree); an unreachable pool refuses loudly."""
    from moco_tpu.train import _service_dataset_len

    worker = _start_worker(_dataset())
    try:
        meta = protocol.fetch_meta(worker.host, worker.port)
        assert meta is not None and meta["n"] == N_SAMPLES
        assert _service_dataset_len(
            f"{worker.host}:{worker.port}") == N_SAMPLES
    finally:
        worker.stop(timeout_s=1.0)
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    free_port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(ServiceConfigError, match="meta probe"):
        _service_dataset_len([("127.0.0.1", free_port)])


def test_serve_shard_spans_continue_coordinator_trace(mesh8, tmp_path):
    """The cross-process critical-path story: the worker's serve_shard
    spans parent under the SAME trace as the client coordinator's
    stage_batch spans — what lets trace_report show decode on/off the
    train host's critical path across the process edge."""
    from moco_tpu.telemetry.trace import Tracer

    client_tracer = Tracer(str(tmp_path / "driver"), "full", proc="driver")
    worker_tracer = Tracer(str(tmp_path / "staging0"), "full",
                           proc="staging0")
    worker = _start_worker(_dataset(), tracer=worker_tracer)
    client = None
    try:
        client = service_epoch_loader(
            f"{worker.host}:{worker.port}", N_SAMPLES, 1, 0,
            GLOBAL_BATCH, mesh8, streams=2, tracer=client_tracer)
        _drain(client)
    finally:
        if client is not None:
            client.close_quietly()
        worker.stop(timeout_s=1.0)
        client_tracer.close()
        worker_tracer.close()
    spans = []
    with open(tmp_path / "staging0" / "spans.jsonl",
              encoding="utf-8") as f:
        for line in f:
            spans.append(json.loads(line))
    served = [s for s in spans if s["name"] == "serve_shard"]
    assert served, "worker recorded no serve_shard spans"
    assert all(s.get("parent") for s in served)
    # the trace id IS the client tracer's: one merged timeline
    assert {s["trace"] for s in served} == {client_tracer.trace_id}


# ---------------------------------------------------------------------------
# the tier-1 drill: SIGKILL one of two real servers mid-epoch
# ---------------------------------------------------------------------------


def test_kill_one_server_drill_epoch_bit_identical(mesh8, tmp_path):
    """The ISSUE 14 acceptance drill, on real server processes: poison
    server 0 with kill_at_shard (self-SIGKILL before answering its 2nd
    shard), run a full epoch -> every shard re-lands on server 1, the
    epoch output is bit-identical to in-process staging, zero batches
    lost — and the staging supervisor relaunches the dead worker without
    re-firing the drill (fire-once chaos state)."""
    want = _reference_epoch(mesh8)
    chaos_state = tmp_path / "chaos_state"
    from moco_tpu.serve.fleet import FleetPolicy

    pool = LocalServerPool(
        2,
        ["--dataset", "synthetic", "--num-samples", str(N_SAMPLES),
         "--image-size", "32", "--seed", "0"],
        telemetry_root=str(tmp_path),
        policy=FleetPolicy(probe_secs=0.2, startup_grace_secs=60.0,
                           backoff_base_secs=0.1, backoff_max_secs=0.5),
        per_server_env={0: {"MOCO_TPU_CHAOS": "kill_at_shard=2",
                            "MOCO_TPU_CHAOS_STATE": str(chaos_state)}},
    )
    client = None
    try:
        pool.start()
        assert pool.wait_healthy(60.0), "pool never became healthy"
        client = service_epoch_loader(
            pool.endpoints_spec(), N_SAMPLES, 1, 0, GLOBAL_BATCH, mesh8,
            streams=2, backoff_secs=0.05, request_timeout_s=10.0)
        got = _drain(client)
        _assert_batches_equal(got, want)  # bit-identical, zero lost
        # the drill really fired (fire-once marker persisted) ...
        assert os.path.exists(chaos_state / "fired_kill_shard")
        # ... and the supervisor relaunches the SIGKILLed worker; the
        # chaos marker keeps the relaunch from crash-looping. (Wait for
        # launches >= 2, not worker_healthy alone: right after the kill
        # the probe state is still the STALE pre-kill healthy.)
        server0 = pool.servers[0]
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if server0.worker.launches >= 2 and server0.worker_healthy():
                break
            time.sleep(0.1)
        assert server0.worker.launches >= 2, \
            "server 0 never relaunched after the chaos SIGKILL"
        assert server0.worker_healthy(), "relaunched worker never probed ok"
        events = [json.loads(line) for line in open(
            tmp_path / "staging_server0" / "events.jsonl",
            encoding="utf-8")]
        exits = [e for e in events if e["event"] == "worker_exit"]
        assert any(e["returncode"] == -9 for e in exits)  # the SIGKILL
        assert any(e["event"] == "launch" and e["attempt"] >= 1
                   for e in events)  # the relaunch
    finally:
        if client is not None:
            client.close_quietly()
        pool.close_quietly()


@pytest.mark.slow
def test_prestage_served_pool_soak(mesh8, tmp_path):
    """Multi-process soak (slow): a 2-server pool answering from a shared
    pre-staged epoch cache serves TWO bit-identical epochs; /stats on the
    health endpoint reports the shards served; a stall drill on one
    server is absorbed by the request timeout + retry path."""
    import urllib.request

    root = str(tmp_path / "pre")
    write_prestage(_dataset(), root)
    want1 = _reference_epoch(mesh8, epoch=1)
    want2 = _reference_epoch(mesh8, epoch=2)
    from moco_tpu.serve.fleet import FleetPolicy

    pool = LocalServerPool(
        2, ["--prestage", root],
        telemetry_root=str(tmp_path),
        policy=FleetPolicy(probe_secs=0.2, startup_grace_secs=60.0),
        per_server_env={1: {"MOCO_TPU_CHAOS":
                            "stall_at_shard=1,stall_ms=1500",
                            "MOCO_TPU_CHAOS_STATE":
                            str(tmp_path / "chaos_state")}},
    )
    client = None
    try:
        pool.start()
        assert pool.wait_healthy(60.0), "pool never became healthy"
        for epoch, want in ((1, want1), (2, want2)):
            client = service_epoch_loader(
                pool.endpoints_spec(), N_SAMPLES, epoch, 0, GLOBAL_BATCH,
                mesh8, streams=2, backoff_secs=0.05,
                request_timeout_s=1.0)
            try:
                got = _drain(client)
            finally:
                client.close_quietly()
                client = None
            _assert_batches_equal(got, want)
        with urllib.request.urlopen(
                "http://127.0.0.1:"
                f"{pool.servers[0].health_port}/stats",
                timeout=5.0) as resp:
            stats = json.load(resp)
        assert stats["worker_stats"].get("shards", 0) >= 1
    finally:
        if client is not None:
            client.close_quietly()
        pool.close_quietly()
