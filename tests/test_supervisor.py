"""Out-of-process supervisor suite (ISSUE 4).

Three layers:
  - pure unit tests: exit classification, backoff, restart-budget refund,
    events-tail forensics, resume preflight, chaos kill/freeze parsing and
    cross-process fire-once state — no child processes, no jax;
  - stub-child e2e: the REAL Supervisor loop driving tiny python stub
    children (hang → SIGTERM→grace→SIGKILL escalation + restart, crash
    loop → budget exhaustion, fatal classes → no restart, preemption →
    immediate relaunch) in a couple of seconds, tier-1 friendly;
  - the full chaos soak (slow+chaos): a real CPU training run supervised
    through kill@step + freeze@step faults, final state bit-identical to
    an uninterrupted supervised run, incidents rendered by
    tools/telemetry_report.py.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from moco_tpu.resilience.chaos import ChaosPlan, parse_chaos_spec
from moco_tpu.resilience.exitcodes import (
    EXIT_PREEMPTED,
    EXIT_ROLLBACK_EXHAUSTED,
)
from moco_tpu.resilience.supervisor import (
    CLASS_CLEAN,
    CLASS_CRASH,
    CLASS_HANG,
    CLASS_KILLED,
    CLASS_NATIVE_CRASH,
    CLASS_OOM,
    CLASS_PREEMPTED,
    CLASS_ROLLBACK_EXHAUSTED,
    QUARANTINE_DIRNAME,
    RestartPolicy,
    Supervisor,
    classify_exit,
    preflight_resume,
    read_events_tail,
    read_heartbeat,
    tail_rss_bytes,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def test_classify_named_exit_codes():
    assert classify_exit(0)[0] == CLASS_CLEAN
    assert classify_exit(EXIT_PREEMPTED)[0] == CLASS_PREEMPTED
    assert classify_exit(EXIT_ROLLBACK_EXHAUSTED)[0] == CLASS_ROLLBACK_EXHAUSTED
    assert classify_exit(45)[0] == "config_error"
    assert classify_exit(2)[0] == "config_error"  # argparse usage error
    assert classify_exit(46)[0] == "data_quality"
    # ISSUE 5: a bind failure must never restart-loop against the same
    # occupied socket — fatal class, matching the README table
    from moco_tpu.resilience.supervisor import FATAL_CLASSES

    assert classify_exit(47)[0] == "serve_bind"
    assert "serve_bind" in FATAL_CLASSES
    assert classify_exit(1)[0] == CLASS_CRASH
    assert classify_exit(77)[0] == CLASS_CRASH  # unknown positive code


def test_classify_signal_deaths():
    assert classify_exit(-int(signal.SIGSEGV))[0] == CLASS_NATIVE_CRASH
    assert classify_exit(-int(signal.SIGABRT))[0] == CLASS_NATIVE_CRASH
    assert classify_exit(-int(signal.SIGBUS))[0] == CLASS_NATIVE_CRASH
    assert classify_exit(-int(signal.SIGKILL))[0] == CLASS_KILLED
    assert classify_exit(-int(signal.SIGTERM))[0] == CLASS_KILLED


def test_classify_hang_wins_over_exit_code():
    """A SIGTERM-responsive hang exits EXIT_PREEMPTED on the way down —
    the supervisor's own kill decision must still classify it as a hang
    (it gets the restart, but the record says why it died)."""
    cls, detail = classify_exit(EXIT_PREEMPTED, hang_killed=True)
    assert cls == CLASS_HANG
    assert "staleness" in detail


def test_classify_oom_from_events_tail():
    tail = [
        {"kind": "step", "step": 9, "host_rss_bytes": 2e9},
        {"kind": "step", "step": 10, "host_rss_bytes": 9e9},
        {"kind": "event", "event": "watchdog"},
    ]
    assert classify_exit(-9, events_tail=tail, oom_rss_bytes=8e9)[0] == CLASS_OOM
    # below the threshold, or with no threshold configured: external kill
    assert classify_exit(-9, events_tail=tail, oom_rss_bytes=1e10)[0] == CLASS_KILLED
    assert classify_exit(-9, events_tail=tail)[0] == CLASS_KILLED
    assert tail_rss_bytes(tail) == 9e9
    assert tail_rss_bytes([]) == 0.0


def test_read_events_tail_skips_torn_lines(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "step", "step": 1}\n')
        f.write('{"kind": "step", "step": 2}\n')
        f.write('{"kind": "step", "ste')  # torn tail: SIGKILL mid-flush
    records = read_events_tail(path)
    assert [r["step"] for r in records] == [1, 2]
    assert read_events_tail(str(tmp_path / "missing.jsonl")) == []


def test_read_heartbeat_absent_or_torn(tmp_path):
    path = str(tmp_path / "heartbeat.json")
    assert read_heartbeat(path) is None
    with open(path, "w") as f:
        f.write('{"step": 4')
    assert read_heartbeat(path) is None
    with open(path, "w") as f:
        json.dump({"step": 4, "pid": 123}, f)
    assert read_heartbeat(path) == {"step": 4, "pid": 123}


# ---------------------------------------------------------------------------
# backoff + budget
# ---------------------------------------------------------------------------


def test_backoff_exponential_capped_jittered():
    import random

    p = RestartPolicy(backoff_base_secs=1.0, backoff_max_secs=8.0,
                      backoff_jitter=0.0)
    rng = random.Random(0)
    assert [p.backoff_secs(n, rng) for n in (1, 2, 3, 4, 5)] == \
        [1.0, 2.0, 4.0, 8.0, 8.0]
    jittered = RestartPolicy(backoff_base_secs=1.0, backoff_max_secs=8.0,
                             backoff_jitter=0.5)
    vals = [jittered.backoff_secs(1, random.Random(s)) for s in range(32)]
    assert all(1.0 <= v <= 1.5 for v in vals)
    assert len(set(vals)) > 1  # jitter actually varies


def _bare_supervisor(tmp_path, **policy_kw):
    return Supervisor(
        ["true"], telemetry_dir=str(tmp_path),
        policy=RestartPolicy(**policy_kw),
    )


def test_budget_consumed_by_no_progress_refunded_by_progress(tmp_path):
    sup = _bare_supervisor(tmp_path, max_restarts=2)
    assert sup._note_exit(progressed=False)   # budget 2 -> 1
    assert sup._note_exit(progressed=False)   # budget 1 -> 0
    assert not sup._note_exit(progressed=False)  # exhausted: crash loop
    sup = _bare_supervisor(tmp_path, max_restarts=2)
    assert sup._note_exit(progressed=False)
    assert sup._note_exit(progressed=True)    # progress refunds the budget
    assert sup._note_exit(progressed=False)
    assert sup._note_exit(progressed=False)
    assert not sup._note_exit(progressed=False)


def test_zero_budget_never_restarts(tmp_path):
    sup = _bare_supervisor(tmp_path, max_restarts=0)
    assert not sup._note_exit(progressed=True)


def test_progress_marker_prefers_heartbeat_falls_back_to_ckpt(tmp_path):
    ckpt = tmp_path / "ckpt"
    (ckpt / "8").mkdir(parents=True)
    sup = Supervisor(["true"], telemetry_dir=str(tmp_path),
                     ckpt_dir=str(ckpt))
    assert sup._progress_marker() == 8  # no heartbeat yet: newest ckpt step
    with open(tmp_path / "heartbeat.json", "w") as f:
        json.dump({"step": 11, "pid": 1}, f)
    assert sup._progress_marker() == 11


# ---------------------------------------------------------------------------
# resume-integrity preflight
# ---------------------------------------------------------------------------


def _fake_ckpt_step(ckpt_dir, step, manifest=True):
    d = ckpt_dir / str(step)
    d.mkdir(parents=True)
    (d / "payload.bin").write_bytes(b"x" * 2048)
    if manifest:
        from moco_tpu.resilience.integrity import write_manifest

        write_manifest(str(ckpt_dir), step)


def test_preflight_quarantines_corrupt_newest_stops_at_survivor(tmp_path):
    ckpt = tmp_path / "ckpt"
    _fake_ckpt_step(ckpt, 4, manifest=False)  # pre-manifest: never touched
    _fake_ckpt_step(ckpt, 8)
    _fake_ckpt_step(ckpt, 12)
    _fake_ckpt_step(ckpt, 16)
    (ckpt / "16" / "payload.bin").write_bytes(b"y" * 1024)  # corrupt newest
    (ckpt / "12" / "payload.bin").write_bytes(b"z" * 1024)  # and the next
    emitted = []
    gone = preflight_resume(str(ckpt), emit=lambda e, **f: emitted.append((e, f)))
    # newest-first: 16 and 12 quarantined, the walk STOPS at verifying 8 —
    # resume only ever reads the newest survivor, so older steps are not
    # re-hashed on every relaunch
    assert gone == [16, 12]
    assert sorted(n for n in os.listdir(ckpt) if n.isdigit()) == ["4", "8"]
    assert os.path.isdir(ckpt / QUARANTINE_DIRNAME / "16")
    assert os.path.isdir(ckpt / QUARANTINE_DIRNAME / "12")
    # the corrupt steps' sidecars must not survive as dangling references
    assert not os.path.exists(ckpt / ".integrity" / "16.json")
    assert [e for e, _ in emitted] == ["preflight_quarantine"] * 2
    assert [f["step"] for _, f in emitted] == [16, 12]
    # second pass: newest (8) verifies immediately, nothing to do
    assert preflight_resume(str(ckpt)) == []
    assert preflight_resume(str(tmp_path / "missing")) == []


def test_preflight_manifestless_newest_stops_walk(tmp_path):
    """A manifest-less newest step verifies vacuously (restore is then the
    gate) and ends the walk — a corrupt step behind it is unreachable
    except through the child's own per-candidate walk-back."""
    ckpt = tmp_path / "ckpt"
    _fake_ckpt_step(ckpt, 8)
    (ckpt / "8" / "payload.bin").write_bytes(b"y" * 1024)  # corrupt, behind
    _fake_ckpt_step(ckpt, 12, manifest=False)
    assert preflight_resume(str(ckpt)) == []
    assert sorted(n for n in os.listdir(ckpt) if n.isdigit()) == ["12", "8"]


# ---------------------------------------------------------------------------
# chaos kill/freeze plumbing
# ---------------------------------------------------------------------------


def test_parse_chaos_spec_kill_and_freeze():
    plan = parse_chaos_spec("kill_at_step=6,freeze_at_step=9")
    assert plan.kill_at_step == 6
    assert plan.freeze_at_step == 9


def test_chaos_fire_once_persists_across_processes(tmp_path):
    """A kill/freeze fault must fire once per SCENARIO, not once per
    process: the restarted child re-traverses the fault's step and would
    otherwise crash-loop the drill. The marker is written BEFORE the fault
    executes (a SIGKILL leaves no later chance)."""
    state = str(tmp_path / "chaos_state")
    first = ChaosPlan(kill_at_step=5, state_dir=state)
    assert first._fire_once("kill")
    assert os.path.exists(os.path.join(state, "fired_kill"))
    assert not first._fire_once("kill")
    # a fresh plan (the restarted process) sees the marker and stays quiet
    second = ChaosPlan(kill_at_step=5, state_dir=state)
    assert not second._fire_once("kill")
    assert second._fire_once("freeze")  # other faults unaffected


def test_env_chaos_state_dir_wired(tmp_path, monkeypatch):
    from moco_tpu.resilience.chaos import active_chaos, clear_chaos

    monkeypatch.setenv("MOCO_TPU_CHAOS", "kill_at_step=3")
    monkeypatch.setenv("MOCO_TPU_CHAOS_STATE", str(tmp_path))
    clear_chaos()
    try:
        plan = active_chaos()
        assert plan.kill_at_step == 3
        assert plan.state_dir == str(tmp_path)
    finally:
        clear_chaos()


# ---------------------------------------------------------------------------
# stub-child e2e: the real Supervisor loop, seconds-cheap children
# ---------------------------------------------------------------------------

_STUB = textwrap.dedent("""\
    import json, os, sys, time
    tdir, state_path = sys.argv[1], sys.argv[2]
    plan = sys.argv[3].split(",")
    extra = sys.argv[4:]  # e.g. the supervisor-appended `--resume auto`
    n = 0
    if os.path.exists(state_path):
        n = int(open(state_path).read())
    open(state_path, "w").write(str(n + 1))
    with open(os.path.join(tdir, "argv_%d.json" % n), "w") as f:
        json.dump(extra, f)
    behavior = plan[min(n, len(plan) - 1)]
    def beat(step, phase="step"):
        p = os.path.join(tdir, "heartbeat.json")
        with open(p + ".tmp", "w") as f:
            json.dump({"v": 1, "t": round(time.time(), 3), "step": step,
                       "pid": os.getpid(), "phase": phase}, f)
        os.replace(p + ".tmp", p)
    kind, _, arg = behavior.partition(":")
    if kind == "hang":
        beat(int(arg or 1))
        time.sleep(300)
    elif kind == "ok":
        beat(int(arg or 5))
        sys.exit(0)
    elif kind == "eval_pause":
        # step beats, then a declared eval phase whose silence outlives
        # the tight window, then back to stepping — must NOT be killed
        beat(3)
        beat(3, phase="eval")
        time.sleep(float(arg or 1.5))
        beat(5)
        sys.exit(0)
    elif kind == "silent_ok":
        # never beats at all (telemetry off / wrong dir) — must not be
        # kill-looped; exits fine on its own
        time.sleep(float(arg or 1.0))
        sys.exit(0)
    elif kind == "preempt":
        beat(int(arg or 3), phase="preempt_exit")
        sys.exit(43)
    elif kind == "exit":
        sys.exit(int(arg))
    else:
        raise SystemExit("unknown stub behavior %r" % behavior)
""")


def _stub_supervisor(tmp_path, plan, **policy_kw):
    stub = tmp_path / "stub.py"
    stub.write_text(_STUB)
    tdir = tmp_path / "telemetry"
    tdir.mkdir(exist_ok=True)
    defaults = dict(
        max_restarts=3, heartbeat_stale_secs=0.5, startup_grace_secs=10.0,
        term_grace_secs=1.0, backoff_base_secs=0.05, backoff_max_secs=0.2,
        backoff_jitter=0.0, poll_secs=0.1,
    )
    defaults.update(policy_kw)
    return Supervisor(
        [sys.executable, str(stub), str(tdir), str(tmp_path / "attempts"),
         plan],
        telemetry_dir=str(tdir),
        policy=RestartPolicy(**defaults),
        seed=0,
    ), tdir


def test_e2e_hang_killed_within_window_then_restarted(tmp_path):
    """A child that beats once then goes silent is killed within 2x the
    staleness window and the relaunch finishes the run."""
    sup, tdir = _stub_supervisor(tmp_path, "hang:1,ok:5")
    t0 = time.monotonic()
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert result.classifications == [CLASS_HANG, CLASS_CLEAN]
    assert result.restarts == 1 and not result.gave_up
    # detection latency: the kill incident lands within 2x the staleness
    # window (+ the SIGTERM grace) of the child's last beat
    kills = [r for r in sup.incidents if r["event"] == "kill"]
    assert kills and kills[0]["reason"] == "heartbeat_stale"
    # 2x the window, plus fixed slack for scheduler noise at this tiny
    # (0.5 s) window — the soak pins the strict 2x bound at a real scale
    assert kills[0]["stale_secs"] <= 2 * sup.policy.heartbeat_stale_secs + 1.0
    assert time.monotonic() - t0 < 30.0
    # the whole story is one JSONL stream, supervisor records included
    records = read_events_tail(os.path.join(str(tdir), "events.jsonl"))
    events = [r["event"] for r in records if r.get("kind") == "supervisor"]
    assert "launch" in events and "kill" in events and "done" in events


def test_e2e_crash_loop_exhausts_budget(tmp_path):
    sup, _ = _stub_supervisor(tmp_path, "exit:1", max_restarts=2)
    result = sup.run()
    assert result.gave_up
    assert result.final_class == CLASS_CRASH
    assert result.launches == 3  # initial + max_restarts
    assert all(c == CLASS_CRASH for c in result.classifications)
    give_up = [r for r in sup.incidents if r["event"] == "give_up"]
    assert give_up and "budget exhausted" in give_up[0]["reason"]


def test_e2e_fatal_class_never_restarts(tmp_path):
    sup, _ = _stub_supervisor(tmp_path, "exit:44")
    result = sup.run()
    assert result.final_class == CLASS_ROLLBACK_EXHAUSTED
    assert result.launches == 1 and not result.gave_up
    assert [r["event"] for r in sup.incidents if r["event"] == "restart"] == []


def test_e2e_preempt_relaunches_without_backoff_and_forces_resume(tmp_path):
    sup, tdir = _stub_supervisor(tmp_path, "preempt:3,ok:7")
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert result.classifications == [CLASS_PREEMPTED, CLASS_CLEAN]
    # preemption: the machine is healthy, no backoff before the relaunch
    assert [r for r in sup.incidents if r["event"] == "backoff"] == []
    # EVERY launch carries --resume auto (attempt 0 included: a restarted
    # supervisor over an existing ckpt_dir must continue, not retrain)
    for attempt in (0, 1):
        with open(tdir / f"argv_{attempt}.json") as f:
            assert json.load(f) == ["--resume", "auto"]


def test_e2e_eval_phase_widens_staleness_window(tmp_path):
    """A declared non-step phase (the kNN eval's "eval" beat) suspends the
    tight window — the supervisor-side analogue of watchdog.suspended().
    The pause here (1.5 s) is 3x the stale window; only the startup grace
    (10 s) applies while the newest beat says "eval"."""
    sup, _ = _stub_supervisor(tmp_path, "eval_pause:1.5")
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert result.restarts == 0
    assert [r for r in sup.incidents if r["event"] == "kill"] == []


def test_e2e_never_any_heartbeat_disables_kill_not_loops(tmp_path):
    """A child that never writes a heartbeat (telemetry off, mismatched
    --telemetry-dir) must NOT be kill-restarted on a cycle — the channel
    is missing, not the child. Hang detection disables with a loud
    incident and the child finishes on its own."""
    sup, _ = _stub_supervisor(tmp_path, "silent_ok:1.2",
                              startup_grace_secs=0.3)
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert result.restarts == 0
    assert [r for r in sup.incidents if r["event"] == "kill"] == []
    warns = [r for r in sup.incidents if r["event"] == "no_heartbeat"]
    assert len(warns) == 1


def test_e2e_stale_zero_disables_hang_detection(tmp_path):
    """heartbeat_stale_secs <= 0: no kill ever (non-main pod hosts never
    write a heartbeat — they must not be killed as 'hung' on a cycle).
    The child here beats once then exits on its own; with a live window
    this same shape gets killed (see the hang test above)."""
    sup, _ = _stub_supervisor(tmp_path, "ok:5", heartbeat_stale_secs=0.0,
                              startup_grace_secs=0.01)
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert [r for r in sup.incidents if r["event"] == "kill"] == []


def test_launch_respects_equals_form_resume(tmp_path):
    """`--resume=latest` in the child argv must suppress the appended
    `--resume auto` exactly like the space-separated form — argparse
    last-wins would silently override the operator's pinned choice."""
    sup = Supervisor(
        ["python", "-m", "moco_tpu.train", "--resume=7"],
        telemetry_dir=str(tmp_path),
    )
    # reach into the argv assembly without launching a process
    argv_out = {}

    class _FakePopen:
        pid = 1

        def __init__(self, argv, **kw):
            argv_out["argv"] = argv

    import moco_tpu.resilience.supervisor as supmod

    orig = supmod.subprocess.Popen
    supmod.subprocess.Popen = _FakePopen
    try:
        sup._launch(attempt=1)
    finally:
        supmod.subprocess.Popen = orig
    assert argv_out["argv"].count("--resume") == 0
    assert "--resume=7" in argv_out["argv"]
    assert "auto" not in argv_out["argv"]


def test_e2e_progress_refunds_budget(tmp_path):
    """Three deaths, each after fresh step progress, on a budget of 1: a
    crash loop would die at the second, a progressing run keeps going."""
    sup, _ = _stub_supervisor(
        tmp_path, "preempt:3,preempt:6,preempt:9,ok:12", max_restarts=1,
    )
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert result.restarts == 3 and not result.gave_up


# ---------------------------------------------------------------------------
# the full chaos soak: real training, kill@ + freeze@, bit-identical result
# ---------------------------------------------------------------------------


def _train_child_argv(tdir, ckpt_dir):
    return [
        sys.executable, "-m", "moco_tpu.train",
        "--preset", "cifar10-moco-v1", "--fake-devices", "8",
        "--arch", "resnet_tiny", "--dataset", "synthetic",
        "--image-size", "16", "--batch-size", "16",
        "--num-negatives", "64", "--embed-dim", "32", "--lr", "0.1",
        "--epochs", "3", "--steps-per-epoch", "4", "--print-freq", "1000",
        "--knn-monitor", "false", "--num-classes", "10",
        "--watchdog-secs", "0",
        "--telemetry-dir", str(tdir), "--telemetry-flush-steps", "4",
        "--heartbeat-secs", "0.05", "--ckpt-dir", str(ckpt_dir),
    ]


def _soak_env(tmp_path, chaos="", chaos_state=""):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # NO persistent compile cache: a SIGKILL-grade fault can poison this
    # jax build's cache (a child dying around a cache write left an entry
    # whose load heap-corrupts every later process — glibc "corrupted
    # double-linked list" at startup), converting restarts into a
    # native-crash loop. The supervisor's budget contained it exactly as
    # designed (give_up after max_restarts no-progress deaths), but the
    # soak needs the run to COMPLETE. See README "Run supervision".
    env["MOCO_TPU_NO_CACHE"] = "1"
    env.pop("MOCO_TPU_CACHE_DIR", None)
    if chaos:
        env["MOCO_TPU_CHAOS"] = chaos
        env["MOCO_TPU_CHAOS_STATE"] = chaos_state
    else:
        env.pop("MOCO_TPU_CHAOS", None)
        env.pop("MOCO_TPU_CHAOS_STATE", None)
    return env


def _restore_leaves(ckpt_dir, step):
    """Final checkpoint's raw leaves, loaded WITHOUT building a model —
    the bit-identity comparison must not depend on reconstruction."""
    import numpy as np
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(os.path.join(str(ckpt_dir), str(step), "default"))
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_chaos_soak_bitidentical(tmp_path):
    """ISSUE 4 acceptance: a supervised CPU run through a SIGKILL at step 6
    and a wedged-collective freeze at step 9 completes within the restart
    budget, the hang is detected and killed within 2x the staleness
    window, the final checkpoint is bit-identical to an uninterrupted
    run's, and the supervisor's incidents render in telemetry_report."""
    import numpy as np

    # uninterrupted reference, same subprocess environment
    ref_t = tmp_path / "ref_telemetry"
    ref_ckpt = tmp_path / "ref_ckpt"
    proc = subprocess.run(
        _train_child_argv(ref_t, ref_ckpt), env=_soak_env(tmp_path),
        capture_output=True, text=True, timeout=900, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]

    # supervised run with process-level faults injected via the env plan
    sup_t = tmp_path / "sup_telemetry"
    sup_ckpt = tmp_path / "sup_ckpt"
    sup_t.mkdir()
    stale = 3.0
    sup = Supervisor(
        _train_child_argv(sup_t, sup_ckpt),
        telemetry_dir=str(sup_t),
        ckpt_dir=str(sup_ckpt),
        env=_soak_env(tmp_path, chaos="kill_at_step=6,freeze_at_step=9",
                      chaos_state=str(tmp_path / "chaos_state")),
        policy=RestartPolicy(
            max_restarts=4, heartbeat_stale_secs=stale,
            startup_grace_secs=600.0, term_grace_secs=3.0,
            backoff_base_secs=0.1, backoff_max_secs=1.0, poll_secs=0.25,
        ),
        seed=0,
    )
    result = sup.run()
    assert result.final_class == CLASS_CLEAN, result
    assert not result.gave_up
    assert result.restarts == 2, result
    assert result.classifications == [CLASS_KILLED, CLASS_HANG, CLASS_CLEAN]

    # hang detected within 2x the staleness window
    kills = [r for r in sup.incidents if r["event"] == "kill"]
    assert kills and kills[0]["stale_secs"] <= 2 * stale

    # bit-identical final state: every leaf of the step-12 checkpoint
    ref_leaves = _restore_leaves(ref_ckpt, 12)
    sup_leaves = _restore_leaves(sup_ckpt, 12)
    assert len(ref_leaves) == len(sup_leaves)
    for a, b in zip(ref_leaves, sup_leaves):
        np.testing.assert_array_equal(a, b)

    # incidents present in the stream and rendered by the report tool
    report = os.path.join(REPO, "tools", "telemetry_report.py")
    events = os.path.join(str(sup_t), "events.jsonl")
    out = subprocess.run([sys.executable, report, events],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "supervisor:" in out.stdout and "death classifications" in out.stdout
    as_json = subprocess.run([sys.executable, report, events, "--json"],
                             capture_output=True, text=True)
    summary = json.loads(as_json.stdout)
    assert summary["supervisor"]["restarts"] == 2
    assert summary["supervisor"]["outcome"] == "done"
    assert sorted(summary["supervisor"]["classifications"]) == \
        sorted(["killed", "hang", "clean"])
