"""moco_tpu/serve/ — the online embedding service (ISSUE 5).

Pins the batching semantics the tentpole promises:
  - bit-identical embeddings regardless of batch composition (solo vs
    coalesced into a full bucket, and vs a direct jitted `model.apply`);
  - a FIXED compile set: warmup compiles exactly the bucket ladder and
    load never adds a program;
  - deadline-flush ordering (FIFO; a partial bucket flushes when the
    oldest request's coalesce window closes);
  - shed-not-stall under synthetic overload (bounded admission queue,
    immediate structured rejection, queued work still completes);
  - drain completing every in-flight request while rejecting new work;
plus the HTTP front end's wire contract, the content-hash embedding LRU,
the kNN endpoint, the telemetry `serve:` report section, and the ISSUE 5
CPU-smoke acceptance run (32 concurrent clients, >= 200 requests, zero
lost, p95 within deadline, mean occupancy >= 50%, bit-identical rows).
"""

from __future__ import annotations

import base64
import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


serve_bench = _load_tool("serve_bench")
telemetry_report = _load_tool("telemetry_report")


# ---------------------------------------------------------------------------
# batcher semantics (stub executor — no jax anywhere near these)
# ---------------------------------------------------------------------------


def _mk_batcher(run_batch=None, **kw):
    from moco_tpu.serve.batcher import MicroBatcher

    return MicroBatcher(run_batch or (lambda x: x * 2.0), **kw)


def test_bucket_for_picks_smallest_fitting():
    from moco_tpu.serve.batcher import bucket_for

    assert [bucket_for(n, (1, 8, 32)) for n in (1, 2, 8, 9, 32)] == \
        [1, 8, 8, 32, 32]
    with pytest.raises(ValueError):
        bucket_for(33, (1, 8, 32))


def test_bucket_validation():
    from moco_tpu.serve.batcher import validate_buckets

    assert validate_buckets([1, 8]) == (1, 8)
    for bad in ((), (0, 4), (8, 1), (4, 4)):
        with pytest.raises(ValueError):
            validate_buckets(bad)


def test_deadline_flush_ordering_fifo():
    """A partial bucket flushes when the OLDEST request's window closes,
    and rows come back in arrival order (each request gets ITS OWN row)."""
    seen = []

    def run(batch):
        seen.append(batch.copy())
        return batch * 2.0

    b = _mk_batcher(run, buckets=(1, 4, 8), flush_ms=40.0, max_queue=16)
    try:
        pendings = [b.submit(np.array([float(i)])) for i in range(3)]
        results = [p.wait(timeout=5.0) for p in pendings]
        for i, r in enumerate(results):
            assert r[0] == 2.0 * i  # FIFO row mapping survived coalescing
        assert len(seen) == 1 and seen[0].shape[0] == 3  # one deadline flush
        assert b.batches == 1 and b.occupancy_sum == pytest.approx(3 / 4)
    finally:
        b.close()


def test_flush_on_full_bucket_before_deadline():
    b = _mk_batcher(buckets=(1, 4), flush_ms=10_000.0, max_queue=8)
    try:
        t0 = time.monotonic()
        pendings = [b.submit(np.array([float(i)])) for i in range(4)]
        for p in pendings:
            p.wait(timeout=5.0)
        # a 10 s coalesce window did NOT gate the full bucket
        assert time.monotonic() - t0 < 5.0
        assert b.batches == 1 and b.occupancy_mean == pytest.approx(1.0)
    finally:
        b.close()


class _Gate:
    """An executor the test can hold closed to build synthetic overload."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def __call__(self, batch):
        self.calls += 1
        if not self.release.wait(timeout=10.0):
            raise RuntimeError("test gate never released")
        return batch * 2.0


def test_overload_sheds_immediately_not_stalls():
    from moco_tpu.serve.batcher import OverloadedError

    gate = _Gate()
    b = _mk_batcher(gate, buckets=(1, 2), flush_ms=1.0, max_queue=4,
                    default_deadline_ms=30_000.0)
    try:
        first = b.submit(np.array([0.0]))  # flusher picks it up, blocks
        time.sleep(0.1)
        queued = [b.submit(np.array([float(i)])) for i in range(1, 5)]
        t0 = time.monotonic()
        with pytest.raises(OverloadedError) as exc:
            b.submit(np.array([99.0]))
        assert time.monotonic() - t0 < 1.0  # shed at the door, no waiting
        assert exc.value.fields["retry_after_ms"] > 0
        assert b.shed_overload == 1
        gate.release.set()
        # everything ACCEPTED still completes (shed, never dropped)
        for p in [first] + queued:
            assert p.wait(timeout=10.0)[0] == 2.0 * p.payload[0]
    finally:
        b.close()


def test_expired_in_queue_shed_with_structured_error():
    from moco_tpu.serve.batcher import DeadlineExceededError

    gate = _Gate()
    b = _mk_batcher(gate, buckets=(1,), flush_ms=1.0, max_queue=8)
    try:
        first = b.submit(np.array([0.0]), deadline_s=30.0)
        time.sleep(0.05)
        doomed = b.submit(np.array([1.0]), deadline_s=0.01)
        time.sleep(0.1)  # its deadline passes while the gate is closed
        gate.release.set()
        assert first.wait(timeout=10.0)[0] == 0.0
        with pytest.raises(DeadlineExceededError):
            doomed.wait(timeout=10.0)
        assert b.shed_deadline == 1
    finally:
        b.close()


def test_drain_completes_inflight_rejects_new():
    from moco_tpu.serve.batcher import DrainingError

    gate = _Gate()
    b = _mk_batcher(gate, buckets=(1, 4), flush_ms=5.0, max_queue=16,
                    default_deadline_ms=30_000.0)
    pendings = [b.submit(np.array([float(i)])) for i in range(6)]
    done = threading.Event()
    drained = []

    def drainer():
        drained.append(b.drain(timeout_s=20.0))
        done.set()

    threading.Thread(target=drainer, daemon=True).start()
    time.sleep(0.1)
    with pytest.raises(DrainingError):
        b.submit(np.array([99.0]))  # new work rejected the moment drain starts
    gate.release.set()
    assert done.wait(timeout=20.0)
    assert drained == [True]
    for i, p in enumerate(pendings):  # every accepted request completed
        assert p.wait(timeout=1.0)[0] == 2.0 * i
    b.close()


def test_close_without_drain_rejects_leftovers():
    from moco_tpu.serve.batcher import DrainingError

    gate = _Gate()
    b = _mk_batcher(gate, buckets=(1,), flush_ms=1.0, max_queue=8)
    first = b.submit(np.array([0.0]))
    time.sleep(0.05)
    leftover = b.submit(np.array([1.0]))
    gate.release.set()
    b.close(drain=False)
    first.wait(timeout=10.0)  # the in-flight one still resolved
    with pytest.raises(DrainingError):
        leftover.wait(timeout=1.0)  # structured rejection, never a hang


def test_batch_error_propagates_to_every_rider():
    def boom(batch):
        raise RuntimeError("device on fire")

    b = _mk_batcher(boom, buckets=(1, 4), flush_ms=5.0, max_queue=8)
    try:
        pendings = [b.submit(np.array([float(i)])) for i in range(3)]
        for p in pendings:
            with pytest.raises(RuntimeError, match="device on fire"):
                p.wait(timeout=5.0)
        assert b.batch_errors == 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# engine: bucketed compiles + bit-identical embeddings
# ---------------------------------------------------------------------------

BUCKETS = (1, 4, 16)
SIZE = 32


@pytest.fixture(scope="module")
def tiny_setup():
    import jax
    import jax.numpy as jnp

    from moco_tpu.models import build_backbone
    from moco_tpu.serve import EmbeddingEngine

    model = build_backbone("resnet_tiny", cifar_stem=True)
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, SIZE, SIZE, 3)), train=False
    )
    params = variables["params"]
    stats = variables.get("batch_stats", {})
    engine = EmbeddingEngine(model, params, stats, image_size=SIZE,
                             buckets=BUCKETS)
    engine.warmup()

    @jax.jit
    def direct_apply(p, s, u8):
        """The reference computation: a direct jitted `model.apply` with
        params as ARGUMENTS (how every step program in this repo runs;
        closed-over params constant-fold differently at 1-ulp scale)."""
        from moco_tpu.data.augment import IMAGENET_INV_STD, IMAGENET_MEAN

        x = u8.astype(jnp.float32) / 255.0
        x = (x - IMAGENET_MEAN) * IMAGENET_INV_STD
        return model.apply({"params": p, "batch_stats": s}, x, train=False)

    def direct(u8_batch):
        return np.asarray(direct_apply(params, stats, u8_batch))

    return engine, direct


def _imgs(n, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, SIZE, SIZE, 3)
    ).astype(np.uint8)


def test_engine_fixed_compile_set_under_load(tiny_setup):
    engine, _ = tiny_setup
    before = engine.compiled_programs()
    for n in (1, 2, 3, 4, 5, 9, 16, 1, 7):  # every bucket + odd sizes
        out = engine.embed(_imgs(n, seed=n))
        assert out.shape == (n, engine.feat_dim)
    after = engine.compiled_programs()
    if before is not None:  # introspection available on this jax build
        assert before == after == len(BUCKETS)  # zero recompiles under load


def test_embeddings_bit_identical_across_batch_composition(tiny_setup):
    """The same image embeds BIT-identically: solo (1-bucket), coalesced
    among strangers into a full bucket, zero-padded into a partial
    bucket, and vs the direct jitted model.apply."""
    engine, direct = tiny_setup
    imgs = _imgs(16, seed=42)
    ref = direct(imgs)
    solo = engine.embed(imgs[:1])[0]
    full = engine.embed(imgs)
    partial = engine.embed(imgs[:3])  # padded 3 -> 4-bucket
    assert np.array_equal(solo, ref[0])
    assert np.array_equal(full, ref)
    assert np.array_equal(partial, ref[:3])
    # composition-independence directly: same row, different neighbors
    reordered = engine.embed(imgs[::-1].copy())
    assert np.array_equal(reordered[-1], full[0])


def test_engine_validates_shape_and_dtype(tiny_setup):
    engine, _ = tiny_setup
    with pytest.raises(ValueError):
        engine.embed(_imgs(1).astype(np.float32))
    with pytest.raises(ValueError):
        engine.embed(np.zeros((1, SIZE, SIZE + 1, 3), np.uint8))
    with pytest.raises(ValueError):
        engine.embed(_imgs(BUCKETS[-1] + 1))  # beyond the largest bucket


# ---------------------------------------------------------------------------
# embedding cache
# ---------------------------------------------------------------------------


def test_embedding_cache_content_keyed_lru():
    from moco_tpu.serve.cache import EmbeddingCache

    cache = EmbeddingCache(1)  # 1 MiB
    a, b = _imgs(2, seed=7)
    ka, kb = EmbeddingCache.key_for(a), EmbeddingCache.key_for(b)
    assert ka != kb
    assert ka == EmbeddingCache.key_for(a.copy())  # content, not identity
    assert cache.get(ka) is None and cache.misses == 1
    cache.put(ka, np.arange(4, dtype=np.float32))
    got = cache.get(ka)
    assert np.array_equal(got, [0, 1, 2, 3]) and cache.hits == 1
    # stored row is a private copy: caller mutation can't corrupt it
    src = np.ones(4, np.float32)
    cache.put(kb, src)
    src[:] = 99.0
    assert np.array_equal(cache.get(kb), np.ones(4))


def test_embedding_cache_byte_budget_evicts_lru():
    from moco_tpu.serve.cache import EmbeddingCache

    cache = EmbeddingCache(1)  # 1 MiB budget
    row = np.zeros(65536, np.float32)  # 256 KiB each -> 4 fit
    for i in range(5):
        cache.put(f"k{i}", row)
    assert cache.entries == 4
    assert cache.get("k0") is None       # LRU victim
    assert cache.get("k4") is not None
    assert cache.cached_bytes <= 2**20
    # an entry larger than the whole budget is never cached
    cache.put("huge", np.zeros(2**19, np.float64))
    assert cache.get("huge") is None


# ---------------------------------------------------------------------------
# service + HTTP front end
# ---------------------------------------------------------------------------


def _post(url, body, timeout=15.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _b64_body(img, **extra):
    return {"image_b64": base64.b64encode(img.tobytes()).decode("ascii"),
            "shape": list(img.shape), **extra}


@pytest.fixture()
def served(tiny_setup, tmp_path):
    """A full service + frontend on an ephemeral port, with telemetry and
    a kNN bank, torn down cleanly."""
    from moco_tpu.serve import EmbedService, ServeFrontend
    from moco_tpu.telemetry.registry import MetricsRegistry

    engine, direct = tiny_setup
    bank_imgs = _imgs(32, seed=5)
    bank = direct(bank_imgs)
    labels = np.arange(32) % 4
    events = str(tmp_path / "events.jsonl")
    registry = MetricsRegistry(events, flush_every=1)
    service = EmbedService(
        engine, flush_ms=5.0, max_queue=64, request_deadline_ms=10_000.0,
        cache_mb=4, registry=registry, snapshot_every=1,
        knn_bank=bank, knn_labels=labels, knn_k=5,
    )
    frontend = ServeFrontend(service, port=0)
    frontend.start()
    try:
        yield service, frontend, direct, (bank, labels), events
    finally:
        service.drain(timeout_s=10.0)
        frontend.shutdown()
        registry.close()


def test_http_embed_knn_health_stats(served):
    from moco_tpu.ops.knn import knn_predict

    service, frontend, direct, (bank, labels), _ = served
    img = _imgs(1, seed=11)[0]

    status, resp = _post(frontend.url + "/v1/embed", _b64_body(img))
    assert status == 200 and resp["cached"] is False
    emb = np.asarray(resp["embedding"], np.float32)
    assert np.array_equal(emb, direct(img[None])[0])  # wire fidelity

    status, resp = _post(frontend.url + "/v1/embed", _b64_body(img))
    assert status == 200 and resp["cached"] is True  # content-hash hit

    status, resp = _post(frontend.url + "/v1/knn",
                         _b64_body(img, return_embedding=True))
    assert status == 200
    expected = int(np.asarray(knn_predict(
        emb[None], bank, labels.astype(np.int32), 4, k=5,
    ))[0])
    assert resp["class"] == expected
    assert np.array_equal(np.asarray(resp["embedding"], np.float32), emb)

    with urllib.request.urlopen(frontend.url + "/healthz", timeout=5) as r:
        assert json.loads(r.read())["status"] == "ok"
    with urllib.request.urlopen(frontend.url + "/stats", timeout=5) as r:
        stats = json.loads(r.read())
    assert stats["requests"] >= 3 and stats["served"] >= 3
    assert stats["cache"]["hits"] >= 1


def test_http_structured_errors(served):
    service, frontend, _, _, _ = served
    # malformed: missing shape
    status, resp = _post(frontend.url + "/v1/embed",
                         {"image_b64": "AAAA"})
    assert status == 400 and resp["error"] == "bad_request"
    # wrong resolution for this model
    bad = np.zeros((8, 8, 3), np.uint8)
    status, resp = _post(frontend.url + "/v1/embed", _b64_body(bad))
    assert status == 400 and resp["error"] == "bad_request"
    # byte-count mismatch
    status, resp = _post(frontend.url + "/v1/embed",
                         {"image_b64": "AAAA", "shape": [SIZE, SIZE, 3]})
    assert status == 400
    # unknown route
    status, resp = _post(frontend.url + "/v1/nope", {})
    assert status == 404


def test_draining_service_rejects_over_http(tiny_setup):
    from moco_tpu.serve import EmbedService, ServeFrontend

    engine, _ = tiny_setup
    service = EmbedService(engine, flush_ms=2.0, max_queue=32,
                           request_deadline_ms=5_000.0)
    frontend = ServeFrontend(service, port=0)
    frontend.start()
    try:
        service.drain(timeout_s=5.0)
        img = _imgs(1)[0]
        status, resp = _post(frontend.url + "/v1/embed", _b64_body(img))
        assert status == 503 and resp["error"] == "draining"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(frontend.url + "/healthz", timeout=5)
        assert exc.value.code == 503
    finally:
        frontend.shutdown()


def test_serve_telemetry_report_section(served):
    service, frontend, _, _, events = served
    for i in range(4):
        _post(frontend.url + "/v1/embed", _b64_body(_imgs(1, seed=100 + i)[0]))
    service.registry.flush()
    records, skipped = telemetry_report.load_events(events)
    assert skipped == 0
    summary = telemetry_report.summarize(records)
    srv = summary["serve"]
    assert srv["requests"] >= 4 and srv["batches"] >= 1
    assert "p95" in srv["latency_ms"]
    rendered = telemetry_report.render(summary)
    assert "serve:" in rendered and "occupancy mean" in rendered
    starts = [r for r in records if r.get("kind") == "serve_start"]
    assert starts and starts[0]["buckets"] == list(BUCKETS)


# ---------------------------------------------------------------------------
# shared checkpoint loader + ServeConfig
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_export(tiny_setup, tmp_path_factory):
    """The tiny encoder exported in the reference's torchvision dialect —
    what tools/serve.py actually loads."""
    import jax

    from moco_tpu.checkpoint import _save_flat, resnet_to_torchvision

    engine, _ = tiny_setup
    flat = resnet_to_torchvision(
        jax.tree.map(np.asarray, engine.params),
        jax.tree.map(np.asarray, engine.batch_stats),
        prefix="module.encoder_q.",
    )
    path = str(tmp_path_factory.mktemp("export") / "tiny.npz")
    _save_flat(flat, path)
    return path


def test_load_for_inference_roundtrip(tiny_setup, tiny_export):
    import jax

    from moco_tpu.checkpoint import load_for_inference

    engine, direct = tiny_setup
    model, params, stats = load_for_inference(
        tiny_export, "resnet_tiny", image_size=SIZE, cifar_stem=True
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(engine.params),
        strict=True,
    ):
        assert pa == pb
        assert np.array_equal(np.asarray(a), np.asarray(b)), pa


def test_load_for_inference_rejects_wrong_arch(tiny_export):
    from moco_tpu.checkpoint import load_for_inference

    with pytest.raises(ValueError, match="surgery mismatch"):
        load_for_inference(tiny_export, "resnet18", image_size=SIZE,
                           cifar_stem=True)


def test_detect_dialect_table():
    from moco_tpu.checkpoint import detect_dialect

    assert detect_dialect({"module.encoder_q.conv1.weight": 0}) == \
        "torchvision_encoder_q"
    assert detect_dialect({"patch_embed.proj.weight": 0}) == "timm_vit"
    assert detect_dialect({"backbone/conv1/kernel": 0}) == "v3_tree"
    with pytest.raises(ValueError, match="no known dialect"):
        detect_dialect({"mystery.weight": 0})


def test_serve_config_validation_and_flags():
    import argparse

    from moco_tpu.config import ServeConfig, add_config_flags, collect_overrides

    with pytest.raises(ValueError):
        ServeConfig(buckets=(8, 1))
    with pytest.raises(ValueError):
        ServeConfig(max_queue=4)  # smaller than the largest bucket
    with pytest.raises(ValueError):
        ServeConfig(request_deadline_ms=0)
    parser = argparse.ArgumentParser()
    add_config_flags(parser, ServeConfig)
    args = parser.parse_args(["--buckets", "1", "4", "16",
                              "--max-queue", "64", "--flush-ms", "7.5"])
    config = ServeConfig().replace(**collect_overrides(args, ServeConfig))
    assert config.buckets == (1, 4, 16)  # retupled, validated
    assert config.max_queue == 64 and config.flush_ms == 7.5


# ---------------------------------------------------------------------------
# ISSUE 5 acceptance: the CPU smoke under real concurrency
# ---------------------------------------------------------------------------


def test_smoke_serve_bench_32_clients(tiny_setup):
    """serve_bench drives >= 32 concurrent clients for >= 200 requests
    against the stdlib front end: zero requests lost (every one resolves
    to a result or a structured rejection), p95 within the configured
    deadline budget, mean batch occupancy >= 50% under full load, and
    served embeddings bit-identical to a direct model.apply."""
    from moco_tpu.serve import EmbeddingEngine, EmbedService, ServeFrontend

    engine0, direct = tiny_setup
    # smoke-sized ladder: 32 concurrent clients against a max bucket of 32
    engine = EmbeddingEngine(
        engine0.model, engine0.params, engine0.batch_stats,
        image_size=SIZE, buckets=(1, 8, 32),
    )
    deadline_ms = 10_000.0
    service = EmbedService(engine, flush_ms=20.0, max_queue=128,
                           request_deadline_ms=deadline_ms, cache_mb=0)
    frontend = ServeFrontend(service, port=0)
    frontend.start()
    try:
        captured: dict[int, list] = {}
        pool, seed = 16, 3
        summary = serve_bench.run_load(
            frontend.url, concurrency=32, total_requests=256,
            image_size=SIZE, pool=pool, timeout_s=30.0, seed=seed,
            capture=captured,
        )
        stats = service.stats()
    finally:
        assert service.drain(timeout_s=30.0)
        frontend.shutdown()
    # zero lost: every request resolved (result or structured rejection)
    assert summary["lost"] == 0, summary["lost_detail"]
    assert summary["resolved"] == summary["sent"] == 256
    assert summary["ok"] >= 200
    # p95 within the deadline budget
    assert summary["latency_ms"]["p95"] <= deadline_ms
    # real coalescing under full load
    assert stats["batches"] >= 1
    assert stats["occupancy_mean"] >= 0.5, stats
    # served rows bit-identical to the direct jitted apply on the same
    # inputs (run_load generates its pool with this seed/size)
    images = np.random.RandomState(seed).randint(
        0, 256, (pool, SIZE, SIZE, 3)
    ).astype(np.uint8)
    ref = direct(images)
    assert captured, "no embeddings captured"
    for k, emb in captured.items():
        assert np.array_equal(np.asarray(emb, np.float32), ref[k]), k


def test_serve_import_is_transitively_train_free():
    """Lint R6 checks DIRECT imports; this pins the transitive claim: a
    fresh process importing the serve package AND its sanctioned loader
    module never pulls the optimizer stack (optax/orbax/train_state)."""
    import subprocess
    import sys as _sys

    code = (
        "import sys\n"
        "import moco_tpu.serve, moco_tpu.checkpoint\n"
        "bad = [m for m in sys.modules\n"
        "       for f in ('optax', 'orbax', 'moco_tpu.train_state',\n"
        "                 'moco_tpu.train', 'moco_tpu.train_step')\n"
        "       if m == f or m.startswith(f + '.')]\n"
        "assert not bad, bad\n"
    )
    r = subprocess.run([_sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_sigterm_drains_cleanly_end_to_end(tiny_export, tmp_path):
    """tools/serve.py under a real SIGTERM: serve, answer one request,
    drain on signal, exit EXIT_OK — the wire-level drain contract an
    orchestrator sees."""
    import signal
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MOCO_TPU_NO_CACHE="1")
    proc = subprocess.Popen(
        [_sys.executable, "-u", os.path.join(REPO, "tools", "serve.py"),
         "--pretrained", tiny_export, "--arch", "resnet_tiny",
         "--image-size", str(SIZE), "--cifar-stem", "true",
         "--port", "0", "--buckets", "1", "4",
         "--telemetry-dir", str(tmp_path / "telemetry")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )
    try:
        url = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if "serving" in line and "http://" in line:
                url = line.split("http://")[1].split()[0].rstrip("/")
                break
        assert url, "server never announced its url"
        img = _imgs(1, seed=21)[0]
        status, resp = _post(f"http://{url}/v1/embed", _b64_body(img),
                             timeout=60.0)
        assert status == 200 and len(resp["embedding"]) > 0
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, out
        assert "drained cleanly" in out
        events = tmp_path / "telemetry" / "events.jsonl"
        assert events.exists()
        kinds = [json.loads(ln).get("kind")
                 for ln in events.read_text().splitlines() if ln.strip()]
        assert "serve_start" in kinds and "serve" in kinds
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=10)
