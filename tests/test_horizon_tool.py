"""The horizon tool's honesty-gate plumbing (review, r5): the untrained-
baseline sidecar must survive preemption (atomic write, corrupt-tolerant
restore) and a resume must be provably the SAME run (flag fingerprint) —
otherwise the gate compares against a baseline nobody measured, or gates a
spliced cosine schedule nobody ran.

The fail-fast paths run the tool as a subprocess: both exit 4 BEFORE any
training step, which is the point (discovering a dead sidecar after the
remaining epochs wastes the whole run).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "_horizon_run.py")


def _run_tool(ckpt_dir, extra=()):
    env = dict(os.environ, MOCO_TPU_FORCE_CPU="1")
    return subprocess.run(
        [sys.executable, TOOL, "--steps", "4", "--batch", "16",
         "--samples", "16", "--ckpt-dir", ckpt_dir, *extra],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)


def _fake_ckpt(tmp_path, run_args=None):
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "100").mkdir()  # orbax step dir: marks "a checkpoint exists"
    if run_args is not None:
        (ck / "horizon_args.json").write_text(json.dumps(run_args))
    return str(ck)


# the tool's own fingerprint for --steps 4 --batch 16 --samples 16:
# samples=16, steps_per_epoch=1, epochs=4, total=4 (cpu: the subprocess
# runs under MOCO_TPU_FORCE_CPU=1)
ARGS_4_16 = {"steps": 4, "batch": 16, "samples": 16,
             "arch": "resnet18", "image_size": 32, "lr": 0.03,
             "momentum_ema": 0.99, "backend": "cpu",
             "compute_dtype": "float32"}


def test_resume_refuses_changed_flags(tmp_path):
    ck = _fake_ckpt(tmp_path, dict(ARGS_4_16, steps=4608))
    r = _run_tool(ck)
    assert r.returncode == 4, r.stdout + r.stderr
    assert "resume refused: flags changed" in r.stdout


def test_resume_refuses_missing_args_fingerprint(tmp_path):
    ck = _fake_ckpt(tmp_path, run_args=None)
    r = _run_tool(ck)
    assert r.returncode == 4, r.stdout + r.stderr
    assert "horizon_args.json missing/corrupt" in r.stdout


def test_resume_refuses_dead_baseline_sidecar(tmp_path):
    ck = _fake_ckpt(tmp_path, ARGS_4_16)
    (tmp_path / "ck" / "untrained_baseline.json").write_text('{"knn_val')
    r = _run_tool(ck)
    assert r.returncode == 4, r.stdout + r.stderr
    assert "untrained_baseline.json missing/corrupt" in r.stdout


@pytest.mark.slow
def test_baseline_sidecar_roundtrip(tmp_path):
    """train()-level: fresh run writes the sidecar atomically; a corrupt
    sidecar on resume yields NO baseline key (the tool then refuses to
    gate); a healthy one restores the recorded value verbatim."""
    from moco_tpu.config import get_preset
    from moco_tpu.data.datasets import SyntheticTextureDataset
    from moco_tpu.train import train

    ck = str(tmp_path / "sck")
    cfg = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", cifar_stem=True, dataset="synthetic_texture",
        image_size=16, batch_size=16, num_negatives=32, embed_dim=32,
        lr=0.03, epochs=1, steps_per_epoch=None, knn_monitor=True,
        knn_every_epochs=1, knn_bank_size=32, num_classes=16,
        ckpt_dir=ck, ckpt_every_epochs=1, resume="", tb_dir="",
        print_freq=100, num_workers=0, compute_dtype="float32",
    )
    data = SyntheticTextureDataset(num_samples=32, image_size=16,
                                   num_classes=16)
    state, metrics = train(cfg, dataset=data)
    side = os.path.join(ck, "untrained_baseline.json")
    assert os.path.exists(side) and not os.path.exists(side + ".tmp")
    tag = ("knn_val_top1_untrained"
           if "knn_val_top1_untrained" in metrics else
           "knn_train_top1_untrained")
    assert json.load(open(side))[tag] == pytest.approx(metrics[tag])

    # corrupt -> resumed metrics carry NO baseline (no fabrication)
    with open(side, "w") as f:
        f.write('{"knn_val_top1_untr')
    _, m2 = train(cfg.replace(resume="auto", epochs=2), dataset=data)
    assert "knn_val_top1_untrained" not in m2
    assert "knn_train_top1_untrained" not in m2

    # healthy -> restored verbatim
    with open(side, "w") as f:
        json.dump({"knn_val_top1_untrained": 0.123}, f)
    _, m3 = train(cfg.replace(resume="auto", epochs=3), dataset=data)
    assert m3["knn_val_top1_untrained"] == pytest.approx(0.123)


def test_resume_accepts_pre_arch_fingerprint(tmp_path):
    """Fingerprints written before the --arch/--image-size flags lack the
    two keys; those runs WERE resnet18@32, so the migration must default
    them rather than refuse (review, r5). Proven by reaching the NEXT
    refusal (corrupt sidecar) instead of 'flags changed'."""
    old = {k: v for k, v in ARGS_4_16.items()
           if k not in ("arch", "image_size")}
    ck = _fake_ckpt(tmp_path, old)
    (tmp_path / "ck" / "untrained_baseline.json").write_text('{"knn_val')
    r = _run_tool(ck)
    assert r.returncode == 4, r.stdout + r.stderr
    assert "untrained_baseline.json missing/corrupt" in r.stdout
    assert "flags changed" not in r.stdout
