"""Worker for the 2-process multi-host simulation test (SURVEY §4 item 4).

Launched by tests/test_multihost.py as:
    python tests/multihost_worker.py <coordinator> <num_procs> <pid> <ckpt_dir>

Each process owns 4 fake CPU devices → a global 8-device data mesh across 2
"hosts". Runs 3 steps of the real v1 train step with the real host-sharded
input path, saves a collective Orbax checkpoint, and prints digests of the
replicated state — the parent asserts both processes agree bit-for-bit.
"""

import hashlib
import sys

import numpy as np


def main():
    coordinator, num_procs, pid, ckpt_dir = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
    )
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from moco_tpu.parallel.mesh import distributed_init

    distributed_init(coordinator, num_procs, pid)
    assert jax.process_count() == num_procs
    assert len(jax.devices()) == 4 * num_procs, jax.devices()

    import jax.numpy as jnp

    from moco_tpu.checkpoint import checkpoint_manager, save_checkpoint
    from moco_tpu.config import PretrainConfig
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.data.loader import epoch_loader
    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step

    GLOBAL_B, IMG, DIM, K = 16, 8, 16, 64
    config = PretrainConfig(
        variant="v1", arch="resnet_tiny", cifar_stem=True, num_negatives=K,
        embed_dim=DIM, batch_size=GLOBAL_B, epochs=1, lr=0.1, seed=0,
    )
    mesh = create_mesh()
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 4)
    state = create_train_state(
        jax.random.key(0), model, tx, (GLOBAL_B // 8, IMG, IMG, 3), K, DIM
    )
    step_fn = build_train_step(config, model, tx, mesh, 4, sched)

    dataset = SyntheticDataset(num_samples=64, image_size=IMG, seed=0)
    loader = epoch_loader(dataset, epoch=0, seed=0, global_batch=GLOBAL_B, mesh=mesh)
    steps = 0
    try:
        for imgs, _labels, _extents in loader:
            imgs_f32 = imgs.astype(jnp.float32)
            state, metrics = step_fn(state, imgs_f32, imgs_f32)
            steps += 1
            if steps == 3:
                break
    finally:
        loader.close()

    mgr = checkpoint_manager(ckpt_dir)
    save_checkpoint(mgr, state, steps)  # collective: every process calls it
    mgr.wait_until_finished()

    # digest the fully-replicated state from THIS process's local shard
    def digest(x):
        local = np.asarray(x.addressable_shards[0].data)
        return hashlib.sha256(np.ascontiguousarray(local).tobytes()).hexdigest()[:16]

    print(
        f"RESULT pid={pid} steps={steps} loss={float(metrics['loss']):.6f} "
        f"queue={digest(state.queue)} ptr={int(state.queue_ptr)} "
        f"conv1={digest(state.params_q['conv1']['kernel'])}",
        flush=True,
    )


if __name__ == "__main__":
    main()
