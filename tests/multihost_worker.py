"""Worker for the 2-process multi-host simulation tests (SURVEY §4 item 4).

Launched by tests/test_multihost.py as:
    python tests/multihost_worker.py <coordinator> <num_procs> <pid> \
        <ckpt_dir> <mode> <phase>

Each process owns 4 fake CPU devices → a global 8-device data mesh across 2
"hosts". Unlike round 1's hand-rolled loop, this drives the REAL train
driver (`moco_tpu.train.train`): host-sharded epoch loader, the SHARDED
two-crop augmentation (`build_two_crops_sharded` inside the fused step),
the SPMD train step's collectives across the process boundary, and
COLLECTIVE Orbax checkpointing.

Modes (VERDICT r1 #7):
    v2       — MoCo-v2 path: aug_plus two-crop aug, MLP head, queue + ShuffleBN
    v3       — MoCo-v3 path: asymmetric aug pair, symmetric loss, AdamW +
               warmup + momentum ramp (no queue)
Phases:
    train    — run 6 driver steps, save a collective checkpoint, print the
               full-state digest
    restore  — FRESH session: rebuild an (differently-seeded) state, restore
               the checkpoint, print the digest — the parent asserts it is
               bit-identical to what the train phase saved
"""

import hashlib
import sys

import numpy as np


def full_state_digest(state) -> str:
    """sha256 over every leaf of the state (rng as raw key data), using this
    process's local shard of each (replicated) array."""
    import jax

    st = state.replace(rng=jax.random.key_data(state.rng))
    h = hashlib.sha256()
    for path, leaf in sorted(
        jax.tree_util.tree_leaves_with_path(st),
        key=lambda kv: jax.tree_util.keystr(kv[0]),
    ):
        h.update(jax.tree_util.keystr(path).encode())
        arr = leaf.addressable_shards[0].data if hasattr(leaf, "addressable_shards") else leaf
        h.update(np.ascontiguousarray(np.asarray(arr)).tobytes())
    return h.hexdigest()[:16]


def make_config(mode: str, ckpt_dir: str):
    from moco_tpu.config import PretrainConfig

    common = dict(
        arch="resnet_tiny", cifar_stem=True, embed_dim=16, batch_size=16,
        image_size=8, epochs=2, steps_per_epoch=3, seed=0, ckpt_dir=ckpt_dir,
        ckpt_every_epochs=2, num_workers=1,
        # pod telemetry across the REAL process boundary (ISSUE 2): the
        # allgather piggybacks on resilience_sync_steps, so the cadence
        # must divide the 6-step run; proc 0 writes events.jsonl with
        # `pod` records the parent test parses
        telemetry_dir=ckpt_dir + "_telemetry",
        telemetry_flush_steps=4, telemetry_stride=2,
        resilience_sync_steps=2, peak_flops_per_chip=1e12,
    )
    if mode == "v2":
        return PretrainConfig(
            variant="v2", aug_plus=True, mlp_head=True, num_negatives=64,
            temperature=0.2, lr=0.1, cos=True, **common,
        )
    if mode == "v3":
        return PretrainConfig(
            variant="v3", optimizer="adamw", lr=1e-3, warmup_epochs=1,
            momentum_ramp=True, momentum_ema=0.99, temperature=1.0,
            weight_decay=0.1, **common,
        )
    raise ValueError(mode)


def main():
    coordinator, num_procs, pid, ckpt_dir, mode, phase = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5], sys.argv[6],
    )
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from moco_tpu.parallel.mesh import distributed_init

    distributed_init(coordinator, num_procs, pid)
    assert jax.process_count() == num_procs
    assert len(jax.devices()) == 4 * num_procs, jax.devices()

    config = make_config(mode, ckpt_dir)

    if phase == "train":
        from moco_tpu.train import train

        state, metrics = train(config)
        steps = int(state.step)
        loss = float(metrics.get("loss", float("nan")))
        print(
            f"RESULT pid={pid} steps={steps} loss={loss:.6f} "
            f"digest={full_state_digest(state)}",
            flush=True,
        )
        return

    # phase == "restore": a fresh session restores the checkpoint the train
    # phase saved; digest must match what train printed (bit-faithful resume
    # across a NEW 2-process incarnation, VERDICT r1 #7)
    from moco_tpu.checkpoint import checkpoint_manager, maybe_resume
    from moco_tpu.parallel.mesh import create_mesh, replicated
    from moco_tpu.train_step import build_encoder, build_optimizer
    from moco_tpu.train_state import create_train_state

    mesh = create_mesh()
    model = build_encoder(config)
    tx, _ = build_optimizer(config, config.steps_per_epoch)
    local_b = config.batch_size // 8
    shape = (local_b, config.image_size, config.image_size, 3)
    if config.variant == "v3":
        from moco_tpu.v3_step import create_v3_train_state

        fresh = create_v3_train_state(jax.random.key(999), model, tx, shape)
    else:
        fresh = create_train_state(
            jax.random.key(999), model, tx, shape, config.num_negatives,
            config.embed_dim,
        )
    mgr = checkpoint_manager(ckpt_dir)
    # restore straight into the replicated sharding (host-local shard reads)
    state = maybe_resume(mgr, fresh, "auto", sharding=replicated(mesh))
    assert int(state.step) > 0, "restore phase found no checkpoint"
    print(
        f"RESULT pid={pid} steps={int(state.step)} loss=0.0 "
        f"digest={full_state_digest(state)}",
        flush=True,
    )


if __name__ == "__main__":
    main()
