"""Queue FIFO property tests (SURVEY §4 item 2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.ops.queue import dequeue_and_enqueue, init_queue


def test_init_queue_normalized():
    q, ptr = init_queue(jax.random.key(0), 128, 16)
    assert q.shape == (128, 16)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(q), axis=1), 1.0, rtol=1e-5)
    assert int(ptr) == 0


def test_enqueue_fifo_and_wraparound():
    k_slots, dim, b = 16, 4, 4
    queue = jnp.zeros((k_slots, dim))
    ptr = jnp.zeros((), jnp.int32)
    # fill exactly K/b batches, then one more to test wraparound overwrite
    for i in range(k_slots // b):
        keys = jnp.full((b, dim), float(i + 1))
        queue, ptr = dequeue_and_enqueue(queue, ptr, keys)
    assert int(ptr) == 0  # wrapped exactly at ptr+bs == K
    q = np.asarray(queue)
    for i in range(k_slots // b):
        np.testing.assert_array_equal(q[i * b : (i + 1) * b], float(i + 1))
    # one more batch overwrites the OLDEST slots (rows 0:b)
    queue, ptr = dequeue_and_enqueue(queue, ptr, jnp.full((b, dim), 99.0))
    q = np.asarray(queue)
    np.testing.assert_array_equal(q[:b], 99.0)
    np.testing.assert_array_equal(q[b : 2 * b], 2.0)
    assert int(ptr) == b


def test_enqueue_requires_divisibility():
    queue = jnp.zeros((10, 4))
    with pytest.raises(ValueError, match="divisible"):
        dequeue_and_enqueue(queue, jnp.zeros((), jnp.int32), jnp.zeros((3, 4)))


def test_enqueue_under_jit_donation():
    """The queue update must be expressible with the state buffer donated
    (the north-star's in-place HBM queue)."""
    queue = jnp.zeros((8, 2))
    ptr = jnp.zeros((), jnp.int32)
    f = jax.jit(dequeue_and_enqueue, donate_argnums=(0,))
    queue2, ptr2 = f(queue, ptr, jnp.ones((2, 2)))
    assert int(ptr2) == 2
    np.testing.assert_array_equal(np.asarray(queue2)[:2], 1.0)
