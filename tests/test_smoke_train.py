"""Integration smoke (SURVEY §4 item 3, BASELINE config-1 criterion): run the
REAL train() driver end-to-end on clusterable synthetic data, then feed its
exported checkpoint through the real linear-probe and kNN eval drivers — the
complete user journey. Uses the micro arch so the single-core CPU sandbox
finishes in a couple of minutes."""

import os

import numpy as np
import pytest

from moco_tpu.config import EvalConfig, get_preset
from moco_tpu.train import train


@pytest.fixture(scope="module")
def trained(mesh8, tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("smoke")
    export = str(tmp_path / "encoder_q.safetensors")
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny",
        dataset="synthetic",
        image_size=16,
        batch_size=32,
        num_negatives=128,
        embed_dim=32,
        lr=0.12,
        epochs=3,
        steps_per_epoch=16,
        knn_monitor=True,
        ckpt_dir=str(tmp_path / "ckpt"),
        tb_dir=str(tmp_path / "tb"),
        export_path=export,
        print_freq=8,
        num_classes=10,
    )
    state, metrics = train(config, mesh8)
    return config, state, metrics, export, tmp_path


@pytest.mark.slow
def test_moco_v1_smoke_loss_falls_knn_above_chance(trained):
    config, state, metrics, export, tmp_path = trained
    assert int(state.step) == 48
    assert np.isfinite(metrics["loss"])
    # 10-class synthetic data, chance = 10%. Healthy runs measure kNN
    # 0.95-0.99 here across seeds (runs/README.md; 3-seed r2 measurement),
    # so 0.9 catches subtle algorithmic regressions (aug order, EMA rate)
    # that the old above-chance bar (0.2) would have passed
    assert metrics["knn_train_top1"] > 0.9, f"kNN top-1 {metrics['knn_train_top1']} below healthy range"
    assert os.path.exists(export)
    try:
        import tensorboardX  # noqa: F401  (optional dep; writer no-ops without it)
    except ImportError:
        pass
    else:
        tb_files = os.listdir(tmp_path / "tb")
        assert any("tfevents" in f for f in tb_files), tb_files


@pytest.mark.slow
def test_lincls_on_trained_export(trained, mesh8):
    """Probe on PRETRAINED features must beat chance comfortably — the full
    pretrain→export→surgery→probe pipeline (config 4 on config 1's output)."""
    from moco_tpu.evals.lincls import train_lincls

    config, state, metrics, export, tmp_path = trained
    eval_cfg = EvalConfig().replace(
        arch="resnet_tiny", pretrained=export, dataset="synthetic",
        image_size=16, cifar_stem=True, num_classes=10, batch_size=64,
        epochs=2, lr=0.03, print_freq=32, ckpt_dir="",
    )
    fc, best_acc1 = train_lincls(eval_cfg, mesh8, max_steps=64)
    # probe recipe re-derived after the symmetric-padding parity fix shifted
    # micro-scale feature magnitudes (lr 1.0 diverged): lr 0.03 x 64 steps
    # measures 67-76% across 3 seeds (runs/README.md)
    assert best_acc1 > 50.0, f"probe on pretrained features only {best_acc1}%"


@pytest.mark.slow
def test_texture_learning_detector(mesh8):
    """Frozen-encoder regression detector on the honest (non-separable)
    dataset — VERDICT r4 #5: the plain-synthetic smoke above cannot notice
    an encoder that silently stops learning.

    Thresholds are MEASURED, not aspirational (tools/_texture_smoke_measure
    .py, 3 seeds x {live lr=0.12, frozen-null lr=1e-9}, 256 steps,
    runs/texture_smoke_r5.jsonl): positive-pair alignment `pos_sim` ends in
    [0.955, 0.970] live vs [0.650, 0.821] frozen → assert > 0.88 (worst-gap
    midpoint); loss ends 6.14-6.18 live vs 6.97-8.74 frozen → assert < 6.6.
    Class-level kNN is deliberately NOT asserted here: at CI scale the live
    delta is NEGATIVE (the clustering dip the r5 horizon sweep shows at 320
    steps), while the frozen null's kNN RISES +6-11 pts from BN running-
    stat calibration alone — kNN-vs-baseline becomes the criterion only at
    horizon scale (tools/_horizon_run.py), judged against the BN-calibrated
    null (runs/horizon_frozen_null_r5.log)."""
    from moco_tpu.data.datasets import SyntheticTextureDataset

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", cifar_stem=True, dataset="synthetic_texture",
        image_size=32, batch_size=32, num_negatives=512, embed_dim=64,
        lr=0.12, momentum_ema=0.99, cos=True, epochs=8,
        knn_monitor=True, knn_every_epochs=8, knn_bank_size=768,
        num_classes=16, ckpt_dir="", tb_dir="", print_freq=31, seed=0,
    )
    data = SyntheticTextureDataset(num_samples=1024, image_size=32,
                                   num_classes=16, seed=0)
    state, metrics = train(config, mesh8, dataset=data)
    assert int(state.step) == 256
    # both sides of the learning evidence must have been computed
    assert 0.0 <= metrics["knn_val_top1_untrained"] <= 1.0
    assert 0.0 <= metrics["knn_val_top1"] <= 1.0
    # the two measured detectors: alignment and queue-hardened loss
    assert metrics["pos_sim"] > 0.88, (
        f"pos_sim {metrics['pos_sim']:.3f} is in the frozen-encoder band "
        "(measured frozen max 0.821, live min 0.955)")
    assert metrics["loss"] < 6.6, (
        f"loss {metrics['loss']:.3f} is in the frozen-encoder band "
        "(measured frozen min 6.97, live max 6.18)")


def test_knn_every_epochs_zero_rejected(mesh8):
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16,
        batch_size=32, num_negatives=128, knn_monitor=True,
        knn_every_epochs=0, ckpt_dir="", tb_dir="",
    )
    with pytest.raises(ValueError, match="knn_every_epochs"):
        train(config, mesh8)


@pytest.mark.slow
def test_knn_on_trained_export(trained):
    from moco_tpu.evals.knn import run_knn

    config, state, metrics, export, tmp_path = trained
    eval_cfg = EvalConfig().replace(
        arch="resnet_tiny", pretrained=export, dataset="synthetic",
        image_size=16, cifar_stem=True, num_classes=10, knn_k=20, ckpt_dir="",
    )
    acc = run_knn(eval_cfg)
    # healthy runs measure 100% here (runs/README.md)
    assert acc > 0.9, f"kNN on pretrained features only {acc}"
