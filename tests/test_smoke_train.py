"""Integration smoke (SURVEY §4 item 3, BASELINE config-1 criterion): run the
REAL train() driver end-to-end on clusterable synthetic data and assert the
contrastive loss falls and kNN beats chance. Uses the micro arch so the
single-core CPU sandbox finishes in ~a minute."""

import numpy as np
import pytest

from moco_tpu.config import get_preset
from moco_tpu.train import train


@pytest.mark.slow
def test_moco_v1_smoke_loss_falls_knn_above_chance(mesh8, tmp_path):
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny",
        dataset="synthetic",
        image_size=16,
        batch_size=32,
        num_negatives=128,
        embed_dim=32,
        lr=0.12,
        epochs=3,
        steps_per_epoch=16,
        knn_monitor=True,
        ckpt_dir=str(tmp_path / "ckpt"),
        tb_dir=str(tmp_path / "tb"),
        print_freq=8,
        num_classes=10,
    )
    state, metrics = train(config, mesh8)
    assert int(state.step) == 48
    try:
        import tensorboardX  # noqa: F401  (optional dep; writer no-ops without it)
    except ImportError:
        pass
    else:
        import os

        tb_files = os.listdir(tmp_path / "tb")
        assert any("tfevents" in f for f in tb_files), tb_files
    # loss fell below the trivial-collapse plateau and is finite
    assert np.isfinite(metrics["loss"])
    # 10-class synthetic data: chance = 10%; the features must beat it well
    assert metrics["knn_top1"] > 0.2, f"kNN top-1 {metrics['knn_top1']} not above chance"
