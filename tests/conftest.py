"""Test configuration: run everything on 8 fake CPU devices.

This is the framework's replacement for the reference's "validate on 8 real
V100s" story (SURVEY.md §4): `--xla_force_host_platform_device_count=8`
provides real XLA CPU devices with real all_gather/psum/ppermute semantics,
so every collective path (ShuffleBN, enqueue gather, grad psum, v3 in-batch
negatives) is exercised without hardware. Must run before JAX initializes a
backend — hence module scope, before any jax-importing test module loads.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from moco_tpu.parallel.mesh import create_mesh

    return create_mesh(8)
