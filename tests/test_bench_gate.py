"""bench_gate (ISSUE 12 satellite): perf regressions fail loudly.

  - flatten: metric-bearing lines from wrappers, stdout text, nested
    input/e2e folds, detail rows deliberately not gated
  - gate_record: tolerance semantics both directions, newest-baseline
    selection, metric-name isolation (a degraded CPU-proxy round never
    compares against an 8-chip one)
  - infra-failed rounds (parsed null / rc!=0) contribute no baselines
  - CLI exit codes: 0 pass / 1 regression / 2 usage
  - THE tier-1 pin: --self-test replays the committed BENCH_r01→r05
    trajectory with the DEFAULT tolerances and finds zero false
    regressions — the guard that keeps the defaults honest
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from tools.bench_gate import (
    flatten,
    gate_record,
    load_trajectory,
    self_test,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "tools", "bench_gate.py")


def _wrapper(parsed=None, tail_records=(), rc=0):
    tail = "".join(json.dumps(r) + "\n" for r in tail_records)
    return {"n": 1, "cmd": "python bench.py", "rc": rc, "tail": tail,
            "parsed": parsed}


# ---------------------------------------------------------------------------
# flatten
# ---------------------------------------------------------------------------


def test_flatten_wrapper_with_nested_folds():
    rec = {"metric": "m_step", "value": 100.0, "unit": "imgs/sec/chip",
           "vs_baseline": 1.0, "final_loss": 4.2,
           "input": {"value": 500.0, "unit": "imgs/sec",
                     "detail": {"a": 1.0, "b": 2.0}},
           "e2e": {"metric": "m_e2e", "value": 50.0}}
    flat, details = flatten(_wrapper(parsed=rec, tail_records=[rec]))
    assert flat == {"m_step": 100.0, "m_step/final_loss": 4.2,
                    "m_step/input": 500.0, "m_e2e": 50.0}
    assert details == 2  # noted, never gated


def test_flatten_service_and_prestage_rows_gate_detail_excluded():
    """ISSUE 14: the e2e child's service/prestage rows gate under their
    own metric names; noisy per-server detail rows are counted, never
    gated — the per-thread-row rule."""
    svc = {"metric": "m_e2e_service", "value": 60.0, "servers": 2,
           "detail": {"server0_shards": 8, "server1_shards": 8,
                      "server0_shard_s_p95": 0.01}}
    pre = {"metric": "m_e2e_prestage", "value": 90.0,
           "vs_device_bound": 0.95}
    # shape 1: the orchestrator nests the child's record under "e2e"
    rec = {"metric": "m_step", "value": 100.0,
           "e2e": {"metric": "m_e2e", "value": 50.0,
                   "service": svc, "prestage": pre}}
    flat, details = flatten(_wrapper(parsed=rec, tail_records=[rec]))
    assert flat == {"m_step": 100.0, "m_e2e": 50.0,
                    "m_e2e_service": 60.0, "m_e2e_prestage": 90.0}
    assert details == 3  # the per-server rows, noted but not gated
    # shape 2: the e2e CHILD's own stdout record carries them top-level
    child = {"metric": "m_e2e", "value": 50.0,
             "service": svc, "prestage": pre}
    flat, details = flatten(_wrapper(parsed=child, tail_records=[child]))
    assert flat == {"m_e2e": 50.0, "m_e2e_service": 60.0,
                    "m_e2e_prestage": 90.0}
    assert details == 3
    # a dead pool degrades to an error row — no value, no gate, no crash
    rec = {"metric": "m_e2e", "value": 50.0,
           "service": {"metric": "m_e2e_service",
                       "error": "RuntimeError: pool never healthy"}}
    flat, _ = flatten(_wrapper(parsed=rec, tail_records=[rec]))
    assert flat == {"m_e2e": 50.0}


def test_flatten_sharding_rows_gate_by_name():
    """ISSUE 15: the step child's per-sharding-mode v3 rows gate under
    their own metric names; degraded rows (skipped/error) fold to
    nothing instead of poisoning the gate."""
    rec = {"metric": "m_step", "value": 100.0,
           "sharding": {
               "dp": {"imgs_per_sec_per_chip": 12.5,
                      "state_bytes_per_device": 513544},
               "fsdp": {"imgs_per_sec_per_chip": 11.0,
                        "state_bytes_per_device": 128392},
               "fsdp_tp": {"skipped": "sweep budget exhausted"},
           }}
    flat, _ = flatten(_wrapper(parsed=rec, tail_records=[rec]))
    assert flat == {"m_step": 100.0,
                    "m_step/sharding/dp": 12.5,
                    "m_step/sharding/fsdp": 11.0}
    # the rows gate like any named metric: a slower fresh fsdp row fails
    verdict = gate_record({"m_step/sharding/fsdp": 8.0}, [("r1", flat)])
    assert [r["metric"] for r in verdict["regressions"]] == [
        "m_step/sharding/fsdp"]


def test_flatten_takes_last_record_per_metric_and_skips_garbage():
    text = "\n".join([
        "not json",
        json.dumps({"metric": "m", "value": 10.0}),  # provisional line
        json.dumps({"no_metric": True}),
        json.dumps({"metric": "m", "value": 30.0}),  # consolidated: wins
    ])
    flat, _ = flatten(text)
    assert flat == {"m": 30.0}


def test_flatten_failed_round_is_empty():
    flat, _ = flatten(_wrapper(parsed=None, rc=1))
    assert flat == {}
    # a zero/fallback value record carries no perf claim either
    flat, _ = flatten(_wrapper(parsed={"metric": "m", "value": 0.0}))
    assert flat == {}


# ---------------------------------------------------------------------------
# gate semantics
# ---------------------------------------------------------------------------

_TRAJ = [
    ("r1", {"m": 100.0, "m/final_loss": 4.0}),
    ("r2", {"m": 120.0}),  # newest baseline for m
]


def test_gate_pass_improvement_and_regression():
    ok = gate_record({"m": 115.0}, _TRAJ, tolerance=0.25)
    assert not ok["regressions"]
    assert ok["passes"][0]["baseline_round"] == "r2"  # newest wins
    up = gate_record({"m": 130.0}, _TRAJ, tolerance=0.25)
    assert up["improvements"][0]["ratio"] == 1.0833
    bad = gate_record({"m": 80.0}, _TRAJ, tolerance=0.25)
    (reg,) = bad["regressions"]
    assert reg["baseline"] == 120.0 and reg["tolerance"] == 0.25
    # exactly at the tolerance edge: not a regression
    edge = gate_record({"m": 90.0}, _TRAJ, tolerance=0.25)
    assert not edge["regressions"]


def test_gate_loss_is_lower_better():
    ok = gate_record({"m/final_loss": 4.3}, _TRAJ, loss_tolerance=0.10)
    assert not ok["regressions"]
    bad = gate_record({"m/final_loss": 4.5}, _TRAJ, loss_tolerance=0.10)
    assert bad["regressions"][0]["metric"] == "m/final_loss"
    better = gate_record({"m/final_loss": 3.5}, _TRAJ, loss_tolerance=0.10)
    assert better["improvements"]


def test_gate_new_metric_has_no_baseline():
    out = gate_record({"m_new": 5.0}, _TRAJ)
    assert out["new_metrics"] == ["m_new"]
    assert out["compared"] == 0


def test_gate_per_metric_override():
    out = gate_record({"m": 110.0}, _TRAJ, tolerance=0.25,
                      overrides={"m": 0.05})
    (reg,) = out["regressions"]  # 110 < 120 * 0.95
    assert reg["tolerance"] == 0.05


# ---------------------------------------------------------------------------
# trajectory loading + the committed-history self-test (tier-1 pin)
# ---------------------------------------------------------------------------


def test_load_trajectory_is_round_ordered():
    names = [name for name, _ in load_trajectory()]
    assert names == sorted(names)
    assert names[0].startswith("BENCH_r0")


def test_self_test_replays_committed_history_zero_false_regressions():
    verdict = self_test()
    # the committed trajectory must gate clean under DEFAULT tolerances —
    # this is the pin that keeps the defaults honest against real history
    assert verdict["regressions"] == 0
    assert verdict["compared"] >= 1  # and it actually compared something
    # the infra-failed rounds (r02 dead backend, r03 rc=124) were skipped
    assert "BENCH_r02.json" in verdict["skipped"]
    assert "BENCH_r03.json" in verdict["skipped"]
    assert verdict["usable_rounds"] >= 3


def test_self_test_cli_exit_codes(tmp_path):
    out = subprocess.run([sys.executable, GATE, "--self-test"],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert "0 regression(s)" in out.stdout
    # a doctored trajectory WITH a real regression makes the self-test
    # fail — the zero above is not vacuous
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        _wrapper(parsed={"metric": "m", "value": 100.0})))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        _wrapper(parsed={"metric": "m", "value": 10.0})))
    out = subprocess.run(
        [sys.executable, GATE, "--self-test", "--trajectory",
         str(tmp_path / "BENCH_r*.json")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1


def test_cli_gates_fresh_record(tmp_path):
    fresh = tmp_path / "fresh.txt"
    fresh.write_text(json.dumps(
        {"metric": "moco_v2_r50_pretrain_throughput_per_chip",
         "value": 1800.0}) + "\n")
    out = subprocess.run([sys.executable, GATE, str(fresh), "--json"],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    verdict = json.loads(out.stdout)
    assert verdict["compared"] == 1 and not verdict["regressions"]
    # a 10× drop fails the gate loudly
    fresh.write_text(json.dumps(
        {"metric": "moco_v2_r50_pretrain_throughput_per_chip",
         "value": 180.0}) + "\n")
    out = subprocess.run([sys.executable, GATE, str(fresh)],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1
    assert "REGRESSION" in out.stdout and "FAIL" in out.stdout


def test_cli_failed_fresh_bench_and_usage(tmp_path):
    empty = tmp_path / "empty.txt"
    empty.write_text("no metrics here\n")
    out = subprocess.run([sys.executable, GATE, str(empty)],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1  # a metric-less fresh bench IS a failure
    out = subprocess.run([sys.executable, GATE, str(empty),
                          "--allow-failed"],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0
    out = subprocess.run([sys.executable, GATE],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2
    out = subprocess.run([sys.executable, GATE, str(empty),
                          "--tolerance-for", "garbage"],
                         capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2
