"""Equivalence pins for the fused bn→relu→1x1-conv tail
(ops/pallas_fused_conv.py + models/fused_block.py; VERDICT r2 #2 lever).

Three layers of proof, all CPU-runnable:
1. the Pallas kernel (interpret mode) against the plain jnp math;
2. the custom VJP (closed-form BN chain + recomputed-z matmuls) against
   autodiff of the unfused composition;
3. the Bottleneck module with `fused_tail=True`: identical param/stat tree
   and matching outputs/grads/running-stat updates vs the unfused block.
"""

import flax.linen as nn
import jax
import jax.export  # noqa: F401  (binds the lazy submodule on 0.4.x)
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.models.fused_block import _bn_relu_conv_train
from moco_tpu.ops.pallas_fused_conv import bn_relu_matmul


def _ref_math(x, a, b, w):
    z = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0)
    return z @ w.astype(jnp.float32)


def test_kernel_matches_reference_interpret():
    key = jax.random.key(0)
    m, k, n = 128, 64, 256
    x = jax.random.normal(jax.random.key(1), (m, k), jnp.float32)
    a = jax.random.normal(jax.random.key(2), (k,)) * 0.5 + 1.0
    b = jax.random.normal(jax.random.key(3), (k,)) * 0.1
    w = jax.random.normal(key, (k, n)) * 0.05
    got = bn_relu_matmul(x, a, b, w, out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_ref_math(x, a, b, w)), rtol=1e-5, atol=1e-5
    )


def test_kernel_ragged_tiles_interpret():
    """Tile pickers must handle non-power-of-two dims (fall back to full)."""
    x = jax.random.normal(jax.random.key(4), (96, 24), jnp.float32)
    a = jnp.ones((24,))
    b = jnp.zeros((24,))
    w = jax.random.normal(jax.random.key(5), (24, 40)) * 0.1
    got = bn_relu_matmul(x, a, b, w, out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_ref_math(x, a, b, w)), rtol=1e-5, atol=1e-5
    )


def test_custom_vjp_matches_autodiff():
    """The closed-form backward (BN chain + recomputed-z matmuls) equals
    autodiff of the unfused normalize→relu→conv composition."""
    eps = 1e-5
    x = jax.random.normal(jax.random.key(6), (4, 6, 6, 16), jnp.float32)
    scale = 1.0 + 0.1 * jax.random.normal(jax.random.key(7), (16,))
    bias = 0.1 * jax.random.normal(jax.random.key(8), (16,))
    w = 0.1 * jax.random.normal(jax.random.key(9), (1, 1, 16, 32))

    def unfused(x, scale, bias, w):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.mean(xf * xf, axis=(0, 1, 2)) - mean * mean
        z = nn.relu((xf - mean) * (jax.lax.rsqrt(var + eps) * scale) + bias)
        return jax.lax.conv_general_dilated(
            z, w, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )

    def loss_fused(args):
        y, _, _ = _bn_relu_conv_train(*args, eps, jnp.float32)
        return jnp.sum(y * jnp.cos(y))  # non-trivial cotangent

    def loss_ref(args):
        y = unfused(*args)
        return jnp.sum(y * jnp.cos(y))

    args = (x, scale, bias, w)
    lf, gf = jax.value_and_grad(loss_fused)(args)
    lr_, gr = jax.value_and_grad(loss_ref)(args)
    np.testing.assert_allclose(float(lf), float(lr_), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(gf), jax.tree.leaves(gr), strict=True):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4
        )


@pytest.mark.parametrize("train", [True, False])
def test_bottleneck_fused_tail_equivalent(train):
    """Same param/stat tree, same outputs, same grads, same running-stat
    updates as the unfused Bottleneck (CPU: plain fwd + closed-form bwd)."""
    from moco_tpu.models.resnet import Bottleneck

    from functools import partial

    conv = partial(nn.Conv, use_bias=False, dtype=jnp.float32,
                   param_dtype=jnp.float32)
    norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                   epsilon=1e-5, dtype=jnp.float32, param_dtype=jnp.float32)
    kw = dict(filters=8, strides=1, conv=conv, norm=norm)
    plain = Bottleneck(**kw)
    fused = Bottleneck(fused_tail=True, bn_momentum=0.9, dtype=jnp.float32, **kw)
    x = jax.random.normal(jax.random.key(10), (2, 8, 8, 32), jnp.float32)
    v = plain.init(jax.random.key(11), x)
    v2 = fused.init(jax.random.key(11), x)
    assert jax.tree.structure(v) == jax.tree.structure(v2)
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(v),
        jax.tree_util.tree_leaves_with_path(v2),
        strict=True,
    ):
        assert la.shape == lb.shape, (pa, la.shape, lb.shape)

    if train:
        out_a, mut_a = plain.apply(v, x, mutable=["batch_stats"])
        out_b, mut_b = fused.apply(v, x, mutable=["batch_stats"])
        for a, b_ in zip(
            jax.tree.leaves(mut_a), jax.tree.leaves(mut_b), strict=True
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-5, atol=1e-6)
    else:
        out_a = plain.apply(v, x)
        out_b = fused.apply(v, x)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               rtol=1e-5, atol=1e-5)

    if train:
        def loss(params, model):
            out, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            return jnp.sum(out ** 2)

        ga = jax.grad(loss)(v["params"], plain)
        gb = jax.grad(loss)(v["params"], fused)
        for (pa, a), (pb, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ga),
            jax.tree_util.tree_leaves_with_path(gb),
            strict=True,
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=3e-4, atol=3e-4,
                err_msg=str(pa),
            )


def test_fused_tail_inside_shard_map_step(mesh8):
    """The fused custom-VJP tail composes with the full hybrid jit/shard_map
    v2 training step (manual params + running-stat updates + donation +
    pmean'd grads). The backend gate is patched so the fused DECLARATION
    path runs here with the jnp fallback math (the Pallas lowering itself is
    TPU-only and covered by interpret-mode tests above)."""
    import unittest.mock as mock

    import moco_tpu.models.fast_bn as fbn
    import moco_tpu.models.fused_block as fb
    from moco_tpu.config import PretrainConfig
    from moco_tpu.models.resnet import Bottleneck, ResNet
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import build_optimizer, build_train_step

    B, IMG, DIM, K = 16, 16, 16, 64
    config = PretrainConfig(variant="v1", arch="resnet_tiny", cifar_stem=True,
                            num_negatives=K, embed_dim=DIM, batch_size=B, lr=0.1)
    model = ResNet(stage_sizes=(1, 1), block_cls=Bottleneck, width=8,
                   num_classes=DIM, cifar_stem=True, fused_bn_conv=True)
    tx, sched = build_optimizer(config, 8)
    with mock.patch.object(jax, "default_backend", lambda: "tpu"), \
         mock.patch.object(fb, "_use_pallas", lambda: False), \
         mock.patch.object(fbn, "_use_pallas", lambda: False):
        state = create_train_state(
            jax.random.key(0), model, tx, (2, IMG, IMG, 3), K, DIM
        )
        step = build_train_step(config, model, tx, mesh8, 8, sched)
        im_q = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
        im_k = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
        state, metrics = step(state, im_q, im_k)
        state, metrics = step(state, im_q, im_k)
    assert np.isfinite(float(metrics["loss"]))
    # the fused tail's running stats live exactly where bn2's would
    assert "bn2" in state.batch_stats_q["layer1_0"]
    assert int(state.step) == 2


def test_kernel_lowers_for_tpu_at_r50_shapes():
    """Cross-platform export compiles the Pallas kernel to Mosaic IR (the
    stage where block/tile errors surface) for every R50 bottleneck-tail
    shape at per-chip batch 128 — hardware-free assurance that the TPU path
    will build. (The bench orchestrator's retry still covers the residual
    Mosaic→binary stage.)"""
    shapes = [
        (128 * 56 * 56, 64, 256),
        (128 * 28 * 28, 128, 512),
        (128 * 14 * 14, 256, 1024),
        (128 * 7 * 7, 512, 2048),
    ]
    for m, k, n in shapes:
        x = jax.ShapeDtypeStruct((m, k), jnp.bfloat16)
        a = jax.ShapeDtypeStruct((k,), jnp.float32)
        b = jax.ShapeDtypeStruct((k,), jnp.float32)
        w = jax.ShapeDtypeStruct((k, n), jnp.bfloat16)
        fn = lambda x, a, b, w: bn_relu_matmul(x, a, b, w, out_dtype=jnp.bfloat16)
        exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(x, a, b, w)
        mod = exp.mlir_module()
        assert "tpu_custom_call" in mod or "mosaic" in mod.lower(), (m, k, n)


@pytest.mark.slow
def test_full_benchmark_step_lowers_for_tpu():
    """The ENTIRE benchmark program — uint8 staging input → two-crop bf16
    augmentation (Pallas blur) → both R50 forwards (Pallas BN stats, fused
    bn→relu→conv3 tails) → backward → SGD → donated queue update — exports
    for the TPU platform from CPU. Every Pallas kernel reaches Mosaic IR
    (33 custom calls), so the driver's benchmark chip meets a program that
    is known to lower."""
    import unittest.mock as mock

    import moco_tpu.models.fast_bn as fbn
    import moco_tpu.models.fused_block as fb
    from moco_tpu.config import get_preset
    from moco_tpu.data.augment import build_two_crops_sharded, v2_aug_config, with_dtype
    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import (
        build_encoder, build_fused_step, build_optimizer, build_train_step,
    )

    B = 128
    # fused ON explicitly: the census pins the CANDIDATE fused program's
    # lowering (the shipping default is OFF until _fused_validate proves it
    # on a chip — config.py::fused_bn_conv)
    config = get_preset("imagenet-moco-v2").replace(
        batch_size=B, fused_bn_conv=True)
    mesh = create_mesh(1)
    with mock.patch.object(jax, "default_backend", lambda: "tpu"), \
         mock.patch.object(fbn, "_use_pallas", lambda: True), \
         mock.patch.object(fb, "_use_pallas", lambda: True):
        model = build_encoder(config)
        tx, sched = build_optimizer(config, 1000)
        state = jax.eval_shape(lambda: create_train_state(
            jax.random.key(0), model, tx, (B, 224, 224, 3),
            config.num_negatives, config.embed_dim))
        step_fn = build_train_step(config, model, tx, mesh, 1000, sched)
        two = build_two_crops_sharded(with_dtype(v2_aug_config(224), "bfloat16"), mesh)
        fused = build_fused_step(step_fn, two, jax.random.key(1))
        imgs = jax.ShapeDtypeStruct((B, 252, 252, 3), jnp.uint8)
        ext = jax.ShapeDtypeStruct((B, 3), jnp.int32)
        exp = jax.export.export(fused, platforms=["tpu"])(
            state, imgs, ext, jax.ShapeDtypeStruct((), jnp.int32)
        )
        # per-kernel-name census (post-CSE unique call sites): a drop in any
        # row means a kernel gate silently fell back to jnp and a perf lever
        # quietly disappeared from the benchmark
        import re
        from collections import Counter

        mod = exp.mlir_module()
        names = Counter(re.findall(r'kernel_name = "([^"]+)"', mod))
        assert names["_blur_kernel"] >= 1, names          # Pallas blur
        assert names["_sums_kernel"] >= 12, names         # BN fwd stats
        assert names["_grad_sums_kernel"] >= 12, names    # BN bwd reductions
        assert names["_kernel"] >= 4, names               # fused conv3 tails
        assert names["_conv3x3_kernel"] >= 4, names       # fused conv2 mids
        assert names["_conv3x3s2_kernel"] >= 3, names     # stride-2 conv2s
        assert names["_dw_kernel"] >= 4, names            # fused-tail dW bwd
        assert names["_dw3x3_kernel"] >= 4, names         # fused-mid dW bwd
        assert mod.count("tpu_custom_call") >= 44


def test_dw_kernel_matches_reference_interpret():
    """The backward twin: dW = relu(x·a+b)ᵀ @ dy, ẑ recomputed in VMEM."""
    from moco_tpu.ops.pallas_fused_conv import bn_relu_matmul_dw

    x = jax.random.normal(jax.random.key(20), (256, 64), jnp.float32)
    a = 1.0 + 0.1 * jax.random.normal(jax.random.key(21), (64,))
    b = 0.1 * jax.random.normal(jax.random.key(22), (64,))
    dy = jax.random.normal(jax.random.key(23), (256, 128), jnp.float32)
    got = bn_relu_matmul_dw(x, a, b, dy, interpret=True)
    z = jnp.maximum(x * a + b, 0.0)
    want = z.T @ dy
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_dw_kernel_lowers_for_tpu_at_r50_shapes():
    from moco_tpu.ops.pallas_fused_conv import bn_relu_matmul_dw

    for m, k, n in [(128 * 56 * 56, 64, 256), (128 * 7 * 7, 512, 2048)]:
        x = jax.ShapeDtypeStruct((m, k), jnp.bfloat16)
        a = jax.ShapeDtypeStruct((k,), jnp.float32)
        b = jax.ShapeDtypeStruct((k,), jnp.float32)
        dy = jax.ShapeDtypeStruct((m, n), jnp.bfloat16)
        exp = jax.export.export(
            jax.jit(lambda x, a, b, dy: bn_relu_matmul_dw(x, a, b, dy)),
            platforms=["tpu"],
        )(x, a, b, dy)
        assert "tpu_custom_call" in exp.mlir_module(), (m, k, n)
