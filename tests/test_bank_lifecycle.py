"""Versioned bank lifecycle suite (ISSUE 16).

Five layers:
  - builder units (no jax): shard→merge bit-identical for any shard
    count, resume-from-completed-shards after a crash, retry-on-another
    -shard, manifest schema + atomicity, probe agreement roundtrip;
  - CLI: tools/bank_build.py config-error exits (45) and the jax-free
    batch-lane build through a stub /v1/embed fleet, with kind:"bank"
    telemetry;
  - service dual swap on jax-free stub engines: the HTTP wire contract
    (409 reload_refused with the serving bank's step, 503 for an
    in-flight bank, 409 reload_bank_mismatch for a doctored pair,
    GET /admin/bank), and the closed-loop generation-consistency drill
    — every served row matches the engine generation that produced it;
  - fleet promotion units (stub backends, no jax): pair gating
    (bank_waiting), the dual-swap POST carrying (bank, bank_step), and
    the mismatch drill — pair quarantined as a unit, last-known-good
    restored, half-swapped replicas rolled back;
  - in-process jax: a verified (checkpoint, bank) pair swaps with
    embeddings bit-identical to a cold start, a doctored manifest is
    refused by the space-agreement probe; plus the full promotion soak
    (slow) over real tools/serve.py replicas.

obsd/report satellites ride along: bank event normalization,
bank_age_steps, the shipped SLO rules, and the report's bank section.
"""

from __future__ import annotations

import base64
import importlib.util
import json
import os
import shutil
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from moco_tpu.resilience.integrity import manifest_path, write_manifest
from moco_tpu.serve.bankbuild import (
    BankBuildError,
    build_bank,
    load_bank,
    probe_agreement,
    read_bank_meta,
    shard_ranges,
    verify_bank,
)
from moco_tpu.serve.fleet import FleetPolicy, FleetSupervisor, ReplicaState

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


D = 6  # stub embedding dim


def _embed_stub(batch, scale=1.0):
    flat = np.asarray(batch, np.float32).reshape(len(batch), -1)
    return (flat[:, :D] / 255.0 * scale).astype(np.float32)


def _corpus(n=13, seed=3, size=8):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    labels = (np.arange(n) % 3).astype(np.int64)
    return images, labels


def _ckpt(tmp_path, step, payload=b"weights " * 64):
    d = tmp_path / "export" / str(step)
    d.mkdir(parents=True, exist_ok=True)
    path = d / "encoder.npz"
    path.write_bytes(payload)
    return str(path)


def _post(url, body, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _wait(cond, timeout_s=20.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# builder: deterministic shard -> merge, resume, retry (no jax)
# ---------------------------------------------------------------------------


def test_shard_ranges_partition_exactly():
    for n, shards in ((13, 3), (4, 4), (7, 1), (5, 9)):
        ranges = shard_ranges(n, shards)
        covered = [i for (s, e) in ranges for i in range(s, e)]
        assert covered == list(range(n))
    with pytest.raises(ValueError, match="shards"):
        shard_ranges(4, 0)


def test_build_bytes_identical_across_shard_counts(tmp_path):
    """ISSUE 16 acceptance: a 1-shard and a 3-shard build of the same
    corpus produce byte-identical bank.npz files and manifests equal
    modulo the recorded shard topology — merge order is dataset-index
    order, never worker-completion order."""
    images, labels = _corpus(13)
    ck = _ckpt(tmp_path, 7)
    events = []
    m1 = build_bank(str(tmp_path / "b1"), 7, images, labels, _embed_stub,
                    checkpoint_path=ck, image_size=8, shards=1)
    m3 = build_bank(str(tmp_path / "b3"), 7, images, labels, _embed_stub,
                    checkpoint_path=ck, image_size=8, shards=3, workers=2,
                    emit=lambda e, **f: events.append((e, f)))
    p1 = tmp_path / "b1" / "7" / "bank.npz"
    p3 = tmp_path / "b3" / "7" / "bank.npz"
    assert p1.read_bytes() == p3.read_bytes()
    assert m1["shards"] == 1 and m3["shards"] == 3
    strip = lambda m: {k: v for k, v in m.items() if k != "shards"}  # noqa: E731
    assert strip(m1) == strip(m3)
    assert m1["files"]["bank.npz"]["sha256"] == \
        m3["files"]["bank.npz"]["sha256"]
    # telemetry: one build_start, one shard_done per shard, one build_done
    names = [e for e, _ in events]
    assert names[0] == "build_start" and names[-1] == "build_done"
    assert names.count("shard_done") == 3
    assert events[0][1]["checkpoint_sha256"] == m3["checkpoint"]["sha256"]
    # the artifact is complete: integrity-verifiable, loadable, probed
    assert verify_bank(str(tmp_path / "b3"), 7) is None
    feats, lab, meta = load_bank(str(p3))
    assert feats.shape == (13, D) and np.array_equal(lab, labels)
    assert meta["step"] == 7 and meta["rows"] == 13
    assert probe_agreement(_embed_stub, meta) == pytest.approx(1.0)
    # .build scratch is gone; the manifest was written last
    assert not (tmp_path / "b3" / ".build" / "7").exists()


def test_build_resumes_from_completed_shards(tmp_path):
    """Killed-mid-build acceptance: a build that dies on one shard keeps
    its completed shard files; the rerun re-embeds ONLY the missing
    shard and lands byte-identical to a never-crashed build."""
    images, labels = _corpus(12)
    ck = _ckpt(tmp_path, 9)
    poison = images[4]  # first row of shard 1 of 3

    def dying(batch):
        if np.array_equal(np.asarray(batch)[0], poison):
            raise RuntimeError("worker died")
        return _embed_stub(batch)

    with pytest.raises(BankBuildError, match=r"shard 1 rows \[4:8\)"):
        build_bank(str(tmp_path / "b"), 9, images, labels, dying,
                   checkpoint_path=ck, image_size=8, shards=3,
                   max_shard_retries=2)
    work = tmp_path / "b" / ".build" / "9"
    assert sorted(os.listdir(work)) == [
        "shard_00000000_00000004.npz", "shard_00000008_00000012.npz",
    ]
    assert not os.path.exists(manifest_path(str(tmp_path / "b"), 9))

    calls = []

    def counting(batch):
        calls.append(len(batch))
        return _embed_stub(batch)

    events = []
    build_bank(str(tmp_path / "b"), 9, images, labels, counting,
               checkpoint_path=ck, image_size=8, shards=3,
               emit=lambda e, **f: events.append((e, f)))
    reused = [f for e, f in events if e == "shard_done" and f["reused"]]
    fresh = [f for e, f in events if e == "shard_done" and not f["reused"]]
    assert len(reused) == 2 and len(fresh) == 1 and fresh[0]["shard"] == 1
    # only the missing shard (1 batch) + the probe batch were embedded
    assert len(calls) == 2
    clean = build_bank(str(tmp_path / "clean"), 9, images, labels,
                       _embed_stub, checkpoint_path=ck, image_size=8,
                       shards=3)
    assert (tmp_path / "b" / "9" / "bank.npz").read_bytes() == \
        (tmp_path / "clean" / "9" / "bank.npz").read_bytes()
    with open(manifest_path(str(tmp_path / "b"), 9)) as f:
        resumed_manifest = json.load(f)
    assert resumed_manifest == clean  # byte-identical artifact, same binding


def test_build_retries_shard_on_transient_failure(tmp_path):
    images, labels = _corpus(8)
    ck = _ckpt(tmp_path, 5)
    failed = []

    def flaky(batch):
        if np.asarray(batch).shape[0] == 4 and not failed:
            failed.append(1)
            raise OSError("connection reset")  # a dead batch-lane worker
        return _embed_stub(batch)

    manifest = build_bank(str(tmp_path / "b"), 5, images, labels, flaky,
                          checkpoint_path=ck, image_size=8, shards=2,
                          workers=2)
    assert manifest["rows"] == 8 and failed  # it DID fail once
    assert verify_bank(str(tmp_path / "b"), 5) is None


def test_build_input_validation_and_legacy_load(tmp_path):
    images, labels = _corpus(4)
    ck = _ckpt(tmp_path, 3)
    with pytest.raises(BankBuildError, match="corpus shape mismatch"):
        build_bank(str(tmp_path / "b"), 3, images, labels[:2],
                   _embed_stub, checkpoint_path=ck, image_size=8)
    with pytest.raises(BankBuildError, match="empty corpus"):
        build_bank(str(tmp_path / "b"), 3, images[:0], labels[:0],
                   _embed_stub, checkpoint_path=ck, image_size=8)
    with pytest.raises(BankBuildError, match=r"\[N, D\]|rows"):
        build_bank(str(tmp_path / "b"), 3, images, labels,
                   lambda b: np.zeros(3, np.float32),
                   checkpoint_path=ck, image_size=8, max_shard_retries=1)
    # a plain npz (pre-ISSUE-16 --knn-bank) loads with meta=None
    legacy = tmp_path / "legacy.npz"
    np.savez(legacy, features=np.ones((4, D), np.float32),
             labels=np.arange(4))
    feats, lab, meta = load_bank(str(legacy))
    assert feats.shape == (4, D) and meta is None
    with pytest.raises(ValueError, match="features"):
        np.savez(tmp_path / "bad.npz", nope=np.ones(3))
        load_bank(str(tmp_path / "bad.npz"))
    # a versioned layout WITHOUT its manifest is "still in flight"
    step_dir = tmp_path / "b2" / "11"
    step_dir.mkdir(parents=True)
    np.savez(step_dir / "bank.npz", features=np.ones((2, D), np.float32),
             labels=np.arange(2))
    assert read_bank_meta(str(step_dir / "bank.npz")) is None


# ---------------------------------------------------------------------------
# tools/bank_build.py CLI (config errors + the jax-free batch lane)
# ---------------------------------------------------------------------------


def test_bank_build_cli_config_errors(tmp_path):
    bank_build = _load_tool("bank_build")
    images, labels = _corpus(4)
    corpus = tmp_path / "corpus.npz"
    np.savez(corpus, images=images, labels=labels)
    ck = _ckpt(tmp_path, 7)
    base = ["--bank-dir", str(tmp_path / "b"), "--corpus", str(corpus)]
    # missing checkpoint file
    assert bank_build.main(
        ["--checkpoint", str(tmp_path / "nope.npz"), "--step", "1"] + base
    ) == 45
    # --step -1 with a non-step parent dir
    loose = tmp_path / "loose.npz"
    loose.write_bytes(b"w")
    assert bank_build.main(["--checkpoint", str(loose)] + base) == 45
    # corpus without labels
    np.savez(tmp_path / "bad_corpus.npz", images=images)
    assert bank_build.main(
        ["--checkpoint", ck, "--bank-dir", str(tmp_path / "b"),
         "--corpus", str(tmp_path / "bad_corpus.npz")]
    ) == 45


def test_bank_build_cli_batch_lane_with_telemetry(tmp_path):
    """The jax-free lane: the CLI embeds through a (stub) serve fleet's
    POST /v1/embed, derives --step from the export layout, and lands
    kind:"bank" build events in events.jsonl."""
    bank_build = _load_tool("bank_build")

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(n))
            row = _embed_stub(np.asarray(req["pixels"], np.uint8)[None])[0]
            body = json.dumps({"embedding": row.tolist()}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class S(ThreadingHTTPServer):
        daemon_threads = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        images, labels = _corpus(6)
        corpus = tmp_path / "corpus.npz"
        np.savez(corpus, images=images, labels=labels)
        ck = _ckpt(tmp_path, 7000)
        tdir = tmp_path / "t"
        rc = bank_build.main([
            "--checkpoint", ck, "--bank-dir", str(tmp_path / "bank"),
            "--corpus", str(corpus), "--shards", "2",
            "--fleet-url", f"http://127.0.0.1:{srv.server_address[1]}",
            "--telemetry-dir", str(tdir),
        ])
        assert rc == 0
        assert verify_bank(str(tmp_path / "bank"), 7000) is None
        feats, _, meta = load_bank(
            str(tmp_path / "bank" / "7000" / "bank.npz"))
        assert np.array_equal(feats, _embed_stub(images))
        assert meta["step"] == 7000  # derived from the export layout
        with open(tdir / "events.jsonl") as f:
            recs = [json.loads(line) for line in f if line.strip()]
        bank_events = [r["event"] for r in recs if r.get("kind") == "bank"]
        assert bank_events[0] == "build_start"
        assert bank_events[-1] == "build_done"
        assert bank_events.count("shard_done") == 2
        assert len({r["run_id"] for r in recs if r.get("kind") == "bank"}) == 1
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# service dual swap on stub engines: wire contract + generation drill
# ---------------------------------------------------------------------------


class _SpaceStubEngine:
    """A jax-free engine whose embedding space is a scaled pixel
    projection: scale 1.0 and 2.0 are distinguishable spaces with
    cosine 1.0 — the space-agreement probe passes, while every served
    row still reveals WHICH engine generation computed it."""

    image_size = 8
    buckets = (1, 4)

    def __init__(self, scale):
        self.scale = float(scale)

    def warmup(self):
        return D

    def embed(self, images_u8):
        return _embed_stub(images_u8, scale=self.scale)


def _stub_pair(tmp_path, step, scale, name):
    """A (checkpoint file, versioned bank) pair for _SpaceStubEngine."""
    ck = _ckpt(tmp_path / name, step, payload=name.encode() * 100)
    images, labels = _corpus(8, seed=step)
    build_bank(str(tmp_path / name / "bank"), step, images, labels,
               lambda b: _embed_stub(b, scale=scale),
               checkpoint_path=ck, image_size=8)
    return ck, str(tmp_path / name / "bank" / str(step) / "bank.npz")


def _stub_service(ck1_bank, scale=1.0, **kw):
    from moco_tpu.serve import EmbedService

    feats, labels, meta = load_bank(ck1_bank)
    service = EmbedService(
        _SpaceStubEngine(scale), flush_ms=1.0, max_queue=64,
        request_deadline_ms=30_000.0, knn_bank=feats, knn_labels=labels,
        knn_k=3, knn_bank_meta=meta, **kw,
    )
    return service


def test_dual_swap_http_contract_and_admin_bank(tmp_path):
    """The wire satellites: 409 reload_refused names tools/bank_build.py
    and carries the serving bank's step; a manifest-less bank is 503
    (retryable, build in flight); a wrong-checkpoint pair is 409
    reload_bank_mismatch; a verified pair swaps and GET /admin/bank +
    /stats report the new bank version."""
    from moco_tpu.serve import ServeFrontend

    ck1, bank1 = _stub_pair(tmp_path, 1, 1.0, "one")
    ck2, bank2 = _stub_pair(tmp_path, 2, 2.0, "two")
    service = _stub_service(bank1)
    service.set_engine_factory(lambda path: _SpaceStubEngine(2.0))
    frontend = ServeFrontend(service, port=0)
    frontend.start()
    try:
        status, resp = _get(frontend.url + "/admin/bank")
        assert status == 200 and resp["configured"]
        assert resp["bank_step"] == 1 and resp["rows"] == 8
        assert resp["generation"] == 0 and resp["swaps"] == 0

        # bank-less reload under a configured bank: terminal 409 that
        # tells the operator exactly what to build
        status, resp = _post(frontend.url + "/admin/reload",
                             {"pretrained": ck2})
        assert status == 409 and resp["error"] == "reload_refused"
        assert "tools/bank_build.py" in resp["detail"]
        assert resp["bank_step"] == 1  # the space still being served

        # manifest-less bank: the build may still be in flight -> 503
        inflight_dir = tmp_path / "inflight" / "2"
        inflight_dir.mkdir(parents=True)
        shutil.copy(bank2, inflight_dir / "bank.npz")
        status, resp = _post(
            frontend.url + "/admin/reload",
            {"pretrained": ck2, "bank": str(inflight_dir / "bank.npz"),
             "bank_step": 2})
        assert status == 503 and resp["error"] == "reload_failed"
        assert "in flight" in resp["detail"]

        # bank1 is bound to checkpoint 1's hash: offering it with
        # checkpoint 2 is NOT a pair -> 409 reload_bank_mismatch
        status, resp = _post(
            frontend.url + "/admin/reload",
            {"pretrained": ck2, "bank": bank1, "bank_step": 1})
        assert status == 409 and resp["error"] == "reload_bank_mismatch"
        assert "not a pair" in resp["detail"]

        # the verified pair swaps in one generation bump
        status, resp = _post(
            frontend.url + "/admin/reload",
            {"pretrained": ck2, "step": 2, "bank": bank2,
             "bank_step": 2})
        assert status == 200 and resp["status"] == "reloaded"
        assert resp["bank_step"] == 2 and resp["bank_rows"] == 8
        assert resp["bank_agreement"] == pytest.approx(1.0)

        img = np.full((8, 8, 3), 100, np.uint8)
        body = {"image_b64": base64.b64encode(img.tobytes()).decode(),
                "shape": list(img.shape)}
        status, resp = _post(frontend.url + "/v1/embed", body)
        assert status == 200
        assert np.allclose(resp["embedding"],
                           _embed_stub(img[None], scale=2.0)[0])
        status, resp = _post(frontend.url + "/v1/knn", body)
        assert status == 200 and resp["class"] in (0, 1, 2)

        status, resp = _get(frontend.url + "/admin/bank")
        assert resp["bank_step"] == 2 and resp["swaps"] == 1
        assert resp["generation"] == 1
        status, stats = _get(frontend.url + "/stats")
        assert stats["bank"]["bank_step"] == 2
    finally:
        service.drain(timeout_s=10.0)
        frontend.shutdown()


def test_dual_swap_closed_loop_generation_consistent(tmp_path):
    """The acceptance drill, deterministically: under closed-loop load
    across a dual swap, zero requests are lost and EVERY returned row
    matches the engine generation that computed it — no cross-space
    answers, ever. The scaled stub spaces make a violation visible in
    the row values themselves."""
    ck1, bank1 = _stub_pair(tmp_path, 1, 1.0, "one")
    ck2, bank2 = _stub_pair(tmp_path, 2, 2.0, "two")
    service = _stub_service(bank1)
    service.set_engine_factory(lambda path: _SpaceStubEngine(2.0))
    try:
        stop = threading.Event()
        results, errors = [], []
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (64, 8, 8, 3), dtype=np.uint8)

        def client(seed):
            i = seed
            while not stop.is_set():
                img = imgs[i % len(imgs)]
                i += 1
                try:
                    row, _ = service.embed(img)
                except Exception as e:  # pragma: no cover - fails the test
                    errors.append(e)
                    return
                results.append((img, np.asarray(row, np.float32),
                                getattr(row, "gen", 0)))

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        entry = service.reload(ck2, step=2, bank=bank2, bank_step=2)
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not errors
        assert entry["bank_agreement"] == pytest.approx(1.0)
        by_gen = {0: 0, 1: 0}
        for img, row, gen in results:
            scale = {0: 1.0, 1: 2.0}[gen]
            assert np.allclose(row, _embed_stub(img[None], scale=scale)[0]), \
                f"generation {gen} row does not match its engine's space"
            by_gen[gen] += 1
        # the loop really straddled the swap: both generations answered
        assert by_gen[0] > 0 and by_gen[1] > 0
        # classify resolves post-swap rows against the NEW bank
        cls_id, _, _ = service.classify(imgs[0])
        assert cls_id in (0, 1, 2)
    finally:
        service.drain(timeout_s=10.0)


def test_doctored_manifest_refused_by_space_agreement(tmp_path):
    """A bank whose manifest LIES about its probe features (right
    checkpoint hash, wrong recorded space) is exactly what the
    agreement probe exists for: BankMismatchError, factory cost only,
    old pair untouched."""
    from moco_tpu.serve import BankMismatchError

    ck1, bank1 = _stub_pair(tmp_path, 1, 1.0, "one")
    ck2, bank2 = _stub_pair(tmp_path, 2, 2.0, "two")
    mpath = manifest_path(str(tmp_path / "two" / "bank"), 2)
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["probe"]["features"] = [
        [-x for x in row] for row in manifest["probe"]["features"]
    ]
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    # the doctored manifest still passes FILE integrity (bank.npz is
    # untouched) — only the probe can catch it
    assert verify_bank(str(tmp_path / "two" / "bank"), 2) is None

    service = _stub_service(bank1)
    service.set_engine_factory(lambda path: _SpaceStubEngine(2.0))
    try:
        before, _ = service.embed(np.zeros((8, 8, 3), np.uint8))
        with pytest.raises(BankMismatchError,
                           match="space-agreement"):
            service.reload(ck2, step=2, bank=bank2, bank_step=2)
        after, _ = service.embed(np.full((8, 8, 3), 10, np.uint8))
        assert service.reloads == 0  # old pair keeps serving
        assert service.bank_info()["bank_step"] == 1

        # offered bank_step contradicting the manifest: refused before
        # the factory ever runs
        service.set_engine_factory(
            lambda path: (_ for _ in ()).throw(AssertionError("no factory")))
        fixed_ck, fixed_bank = _stub_pair(tmp_path, 4, 2.0, "four")
        with pytest.raises(BankMismatchError, match="recorded step"):
            service.reload(fixed_ck, bank=fixed_bank, bank_step=999)
    finally:
        service.drain(timeout_s=10.0)


# ---------------------------------------------------------------------------
# fleet promotion: pair gating, dual-swap POST, quarantine + rollback
# ---------------------------------------------------------------------------


FAST_POLICY = dict(
    probe_secs=0.1, probe_timeout_s=0.5, health_stale_secs=1.0,
    startup_grace_secs=15.0, term_grace_secs=1.0,
    backoff_base_secs=0.05, backoff_max_secs=0.2, backoff_jitter=0.0,
    request_timeout_s=10.0, watch_poll_secs=0.1, stats_every_secs=1.0,
)


class _FakeProc:
    pid = 4242

    def poll(self):
        return None

    def terminate(self):
        pass


def _capture_backend(decide):
    """An in-thread replica stub: records every POST body, answers with
    decide(body) -> (status, payload)."""
    bodies = []

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n)
            try:
                req = json.loads(raw) if raw else {}
            except ValueError:
                req = {}
            bodies.append(dict(req, _path=self.path))
            status, payload = decide(req)
            body = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class S(ThreadingHTTPServer):
        daemon_threads = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, bodies


def _bank_fleet(tmp_path, ports, bank_dir):
    fleet = FleetSupervisor(
        lambda *a: ["true"], replicas=len(ports),
        telemetry_dir=str(tmp_path / "fleet_t"),
        policy=FleetPolicy(**FAST_POLICY), bank_dir=bank_dir,
    )
    for i, port in enumerate(ports):
        r = ReplicaState(i, "127.0.0.1", port,
                         str(tmp_path / f"r{i}"), budget=3)
        r.proc = _FakeProc()
        r.healthy = True
        fleet.replicas.append(r)
    return fleet


def _fleet_bank(bank_dir, step, rows=6):
    """A verified bank artifact in the fleet's bank_dir layout."""
    step_dir = os.path.join(bank_dir, str(step))
    os.makedirs(step_dir)
    np.savez(os.path.join(step_dir, "bank.npz"),
             features=np.full((rows, D), float(step), np.float32),
             labels=np.arange(rows) % 2)
    write_manifest(bank_dir, step)
    return os.path.join(step_dir, "bank.npz")


def test_fleet_pair_gating_waits_for_bank_then_dual_swaps(tmp_path):
    """With --bank-dir, a manifested checkpoint WAITS (deduped
    bank_waiting) until its paired bank lands; the reload POST then
    carries (bank, bank_step) so the replica rolls both together."""
    srv, bodies = _capture_backend(
        lambda b: (200, {"status": "reloaded"}))
    bank_dir = str(tmp_path / "bank")
    os.makedirs(bank_dir)
    fleet = _bank_fleet(tmp_path, [srv.server_address[1]], bank_dir)
    try:
        with fleet._lock:
            fleet._target_step, fleet._target_path = 7, "/x/7/encoder.npz"
        fleet._reload_sync()
        fleet._reload_sync()  # the converge loop coming around again
        assert bodies == []  # no replica was asked to half-swap
        assert fleet.replicas[0].deployed_step == -1
        waiting = [e for e in fleet.incidents
                   if e["event"] == "bank_waiting"]
        assert len(waiting) == 1  # announced once, not every pass
        assert waiting[0]["step"] == 7

        bank_path = _fleet_bank(bank_dir, 7)
        fleet._reload_sync()
        assert fleet.replicas[0].deployed_step == 7
        assert bodies[-1]["bank"] == bank_path
        assert bodies[-1]["bank_step"] == 7
        st = fleet.stats()["bank"]
        assert st["good_step"] == 7 and st["good_bank"] == bank_path
        # a corrupt LATER bank quarantines itself without touching the
        # serving pair
        bank9 = _fleet_bank(bank_dir, 9)
        with open(bank9, "ab") as f:
            f.write(b"torn")
        with fleet._lock:
            fleet._target_step, fleet._target_path = 9, "/x/9/encoder.npz"
        fleet._reload_sync()
        assert fleet.replicas[0].deployed_step == 7
        assert os.path.isdir(os.path.join(bank_dir, ".quarantine", "9"))
        assert fleet.stats()["bank"]["quarantined"] == [9]
    finally:
        srv.shutdown()


def test_fleet_mismatch_quarantines_pair_and_rolls_back(tmp_path):
    """The mismatch drill: replica 0 swaps onto the new pair, replica 1
    refuses it (space-agreement). The pair is quarantined as a unit,
    known-good rolls back to the previous pair, and the half-swapped
    replica is reloaded back — the fleet converges on the old space."""
    srv0, bodies0 = _capture_backend(
        lambda b: (200, {"status": "reloaded"}))

    def judge(b):
        if b.get("bank_step") == 5:
            return 200, {"status": "reloaded"}
        return 409, {"error": "reload_bank_mismatch",
                     "detail": "space-agreement probe cosine 0.01"}

    srv1, bodies1 = _capture_backend(judge)
    bank_dir = str(tmp_path / "bank")
    os.makedirs(bank_dir)
    bank5 = _fleet_bank(bank_dir, 5)
    _fleet_bank(bank_dir, 7)
    fleet = _bank_fleet(
        tmp_path, [srv0.server_address[1], srv1.server_address[1]],
        bank_dir)
    try:
        with fleet._lock:
            fleet._target_step, fleet._target_path = 5, "/x/5/encoder.npz"
        fleet._reload_sync()
        assert all(r.deployed_step == 5 for r in fleet.replicas)
        assert fleet.stats()["bank"]["good_step"] == 5

        with fleet._lock:
            fleet._target_step, fleet._target_path = 7, "/x/7/encoder.npz"
        fleet._reload_sync()
        # replica 0 half-swapped onto 7, then was rolled back to the
        # restored known-good pair
        assert fleet.replicas[0].deployed_step == 5
        assert fleet.replicas[1].deployed_step == 5
        assert bodies0[-1]["pretrained"] == "/x/5/encoder.npz"
        assert bodies0[-1]["bank"] == bank5 and bodies0[-1]["bank_step"] == 5
        # the pair died as a unit
        assert os.path.isdir(os.path.join(bank_dir, ".quarantine", "7"))
        assert not os.path.exists(manifest_path(bank_dir, 7))
        st = fleet.stats()["bank"]
        assert st["good_step"] == 5 and st["good_bank"] == bank5
        assert st["quarantined"] == [7]
        # the refusal is terminal for step 7 and the target was reset:
        # the converge loop must not churn on the condemned pair
        assert fleet.replicas[1].reload_refused_step == 7
        with fleet._lock:
            assert fleet._target_path is None
        n_posts = len(bodies0) + len(bodies1)
        fleet._reload_sync()
        assert len(bodies0) + len(bodies1) == n_posts
        events = [e["event"] for e in fleet.incidents]
        assert "quarantine" in events and "bank_quarantine" in events
        rollbacks = [e for e in fleet.incidents if e["event"] == "rollback"]
        assert rollbacks and rollbacks[0]["mode"] == "reload"
        assert rollbacks[0]["from_step"] == 7
        assert rollbacks[0]["to_step"] == 5
        assert all(e["kind"] == "bank" for e in fleet.incidents
                   if e["event"] in ("quarantine", "bank_quarantine",
                                     "rollback", "bank_waiting"))
    finally:
        srv0.shutdown()
        srv1.shutdown()


def test_fleet_launch_argv_pins_bank_and_tolerates_legacy_signature(
        tmp_path):
    """A replica relaunch pins the known-good BANK into the child argv
    alongside the weights (a dying replica reboots onto the pair, never
    new weights over an old bank); a legacy 4-arg child_argv still
    launches (bank-free fleets, older stubs)."""
    calls = []

    def argv5(index, port, tdir, pretrained, bank):
        calls.append((pretrained, bank))
        return ["true"]

    def argv4(index, port, tdir, pretrained):
        calls.append((pretrained, None))
        return ["true"]

    for i, fn in enumerate((argv5, argv4)):
        fleet = FleetSupervisor(fn, replicas=1,
                                telemetry_dir=str(tmp_path / f"t{i}"),
                                policy=FleetPolicy(**FAST_POLICY))
        with fleet._lock:
            fleet._current_pretrained = "/good/encoder.npz"
            fleet._good_bank = "/good/bank.npz"
        r = ReplicaState(0, "127.0.0.1", 1234, str(tmp_path / f"r{i}"),
                         budget=3)
        os.makedirs(r.telemetry_dir, exist_ok=True)
        fleet._launch(r)
        r.proc.wait(timeout=10.0)
    assert calls == [("/good/encoder.npz", "/good/bank.npz"),
                     ("/good/encoder.npz", None)]


# ---------------------------------------------------------------------------
# obsd + SLO rules + telemetry report satellites
# ---------------------------------------------------------------------------


def _bank_rec(event, **fields):
    return dict({"v": 1, "kind": "bank", "event": event}, **fields)


def test_run_window_bank_events_and_age():
    from moco_tpu.telemetry.aggregate import RunWindow

    w = RunWindow("r1")
    w.ingest(_bank_rec("build_start", step=7), "s", "p", 10.0)
    w.ingest(_bank_rec("shard_done", step=7, shard=0), "s", "p", 10.5)
    w.ingest(_bank_rec("swap", step=7, bank_step=5, rows=8,
                       generation=1, agreement=0.995), "s", "p", 11.0)
    # event names normalize to a bank_ prefix; shard_done stays out of
    # the incident ledger (it is progress, not an incident)
    assert w.incidents.get("bank_build_start") == 1
    assert w.incidents.get("bank_swap") == 1
    assert "bank_shard_done" not in w.incidents
    assert w.metric("event:bank_swap", 60.0, 12.0) == 1.0
    # bank age: promoted checkpoint step minus serving bank step
    assert w.metric("bank_age_steps", 60.0, 12.0) == 2.0
    w.ingest(_bank_rec("bank_waiting", step=9, age_steps=4), "s", "p", 12.0)
    assert w.metric("bank_age_steps", 60.0, 13.0) == 4.0
    w.ingest(_bank_rec("quarantine", step=9), "s", "p", 13.0)
    w.ingest(_bank_rec("rollback", replica=0, from_step=9, to_step=5),
             "s", "p", 14.0)
    assert w.metric("event:bank_quarantine", 60.0, 15.0) == 1.0
    assert w.metric("event:bank_rollback", 60.0, 15.0) == 1.0
    # a quarantined pair counts as a reload failure for the default rule
    assert w.metric("reload_failures", 60.0, 15.0) == 1.0
    snap = w.snapshot(15.0)
    assert snap["bank"]["event"] == "bank_waiting"
    assert snap["bank"]["age_steps"] == 4
    # no bank records ever seen -> no fabricated age
    w2 = RunWindow("r2")
    assert w2.metric("bank_age_steps", 60.0, 15.0) is None


def test_shipped_bank_slo_rules_fire():
    from moco_tpu.telemetry.aggregate import RunWindow, SLOEngine, load_rules

    rules = load_rules(
        os.path.join(REPO, "tools", "slo_rules", "bank_lifecycle.json"))
    assert [r.name for r in rules] == [
        "bank_age", "bank_pair_quarantine", "bank_rollback"]
    w = RunWindow("r1")
    w.ingest(_bank_rec("bank_waiting", step=9000, age_steps=3000),
             "s", "p", 100.0)
    w.ingest(_bank_rec("quarantine", step=9000), "s", "p", 100.5)
    w.ingest(_bank_rec("rollback", replica=1, from_step=9000, to_step=5),
             "s", "p", 101.0)
    engine = SLOEngine(rules)
    fired = {t["rule"] for t in engine.evaluate({"r1": w}, 102.0)}
    assert fired == {"bank_age", "bank_pair_quarantine", "bank_rollback"}


def test_report_bank_section(tmp_path):
    report = _load_tool("telemetry_report")
    records = [
        _bank_rec("build_start", step=7, rows=128, shards=2),
        _bank_rec("shard_done", step=7, shard=0),
        _bank_rec("shard_done", step=7, shard=1),
        _bank_rec("build_done", step=7, rows=128, feat_dim=64, shards=2,
                  manifest_sha256="ab" * 32),
        _bank_rec("swap", step=9, bank_step=7, rows=128, generation=2,
                  agreement=0.998),
        _bank_rec("quarantine", step=11, detail="space mismatch"),
        _bank_rec("rollback", replica=0, from_step=11, to_step=9),
    ]
    summary = report.summarize(records)
    bank = summary["bank"]
    assert bank["builds"] == 1 and bank["swaps"] == 1
    assert bank["quarantines"] == 1 and bank["rollbacks"] == 1
    assert bank["events"]["bank_shard_done"] == 2
    assert bank["last_build"]["rows"] == 128
    assert bank["last_swap"]["bank_step"] == 7
    assert bank["age_steps"] == 2
    rendered = report.render(summary)
    assert "bank:" in rendered
    assert "128 rows" in rendered and "generation 2" in rendered
    # --follow line rendering
    line = report.render_record(records[4])
    assert line.startswith("bank: swap") and "bank_step=7" in line


# ---------------------------------------------------------------------------
# in-process jax: the verified pair swaps bit-identically
# ---------------------------------------------------------------------------


J_SIZE = 32
J_BUCKETS = (1, 4)


@pytest.fixture(scope="module")
def pair_exports(tmp_path_factory):
    """Two DIFFERENT tiny encoders in the torchvision dialect — the
    (checkpoint, bank) pair for A serves first, the pair for B rolls
    over it."""
    import jax
    import jax.numpy as jnp

    from moco_tpu.checkpoint import _save_flat, resnet_to_torchvision
    from moco_tpu.models import build_backbone

    model = build_backbone("resnet_tiny", cifar_stem=True)
    root = tmp_path_factory.mktemp("bank_exports")
    paths = []
    for seed in (0, 1):
        variables = model.init(
            jax.random.key(seed), jnp.zeros((1, J_SIZE, J_SIZE, 3)),
            train=False,
        )
        flat = resnet_to_torchvision(
            jax.tree.map(np.asarray, variables["params"]),
            jax.tree.map(np.asarray, variables.get("batch_stats", {})),
            prefix="module.encoder_q.",
        )
        path = str(root / f"encoder_{seed}.npz")
        _save_flat(flat, path)
        paths.append(path)
    return paths


def _jax_engine(path):
    from moco_tpu.serve import EmbeddingEngine

    return EmbeddingEngine.from_checkpoint(
        path, "resnet_tiny", image_size=J_SIZE, cifar_stem=True,
        buckets=J_BUCKETS,
    )


def _jax_embed_fn(engine):
    cap = J_BUCKETS[-1]

    def embed(batch):
        return np.concatenate(
            [engine.embed(batch[lo:lo + cap])
             for lo in range(0, len(batch), cap)], axis=0)

    return embed


def test_jax_dual_swap_refusal_then_verified_pair_bit_identical(
        pair_exports, tmp_path):
    """The PR 10/13 refusal contract under the new lifecycle: a bank-
    less reload under a versioned bank still 409s (now naming the
    builder and the serving bank step) — and the path the refusal
    points at WORKS: a tools/bank_build.py pair for the new checkpoint
    swaps, with served embeddings bit-identical to a cold start."""
    from moco_tpu.serve import EmbedService, ReloadRefusedError

    path_a, path_b = pair_exports
    imgs = np.random.RandomState(5).randint(
        0, 256, (6, J_SIZE, J_SIZE, 3)).astype(np.uint8)
    labels = np.arange(6) % 2

    engine_a = _jax_engine(path_a)
    engine_a.warmup()
    build_bank(str(tmp_path / "bank"), 1, imgs, labels,
               _jax_embed_fn(engine_a), checkpoint_path=path_a,
               image_size=J_SIZE)
    bank1 = str(tmp_path / "bank" / "1" / "bank.npz")
    feats, lab, meta = load_bank(bank1)
    service = EmbedService(engine_a, flush_ms=2.0, max_queue=32,
                           request_deadline_ms=10_000.0,
                           knn_bank=feats, knn_labels=lab, knn_k=3,
                           knn_bank_meta=meta)
    service.set_engine_factory(_jax_engine)
    try:
        with pytest.raises(ReloadRefusedError,
                           match="tools/bank_build.py") as e:
            service.reload(path_b)
        assert e.value.bank_step == 1
        assert service.reloads == 0

        cold_b = _jax_engine(path_b)
        cold_b.warmup()
        build_bank(str(tmp_path / "bank"), 2, imgs, labels,
                   _jax_embed_fn(cold_b), checkpoint_path=path_b,
                   image_size=J_SIZE)
        bank2 = str(tmp_path / "bank" / "2" / "bank.npz")
        entry = service.reload(path_b, step=2, bank=bank2, bank_step=2)
        assert entry["bank_step"] == 2
        # same deterministic engine construction on both sides of the
        # build/verify boundary: agreement is exactly 1.0
        assert entry["bank_agreement"] == pytest.approx(1.0)

        img = imgs[0]
        row, cached = service.embed(img)
        assert cached is False  # cache cleared at the swap
        assert np.array_equal(row, cold_b.embed(img[None])[0])
        cls_id, _, _ = service.classify(imgs[1])
        assert cls_id in (0, 1)
        assert service.bank_info()["bank_step"] == 2

        # bank1 was built against checkpoint A: offering it for another
        # reload of B is refused by the hash binding, factory never runs
        from moco_tpu.serve import BankMismatchError

        service.set_engine_factory(
            lambda path: (_ for _ in ()).throw(AssertionError("factory")))
        with pytest.raises(BankMismatchError, match="not a pair"):
            service.reload(path_b, bank=bank1, bank_step=1)
    finally:
        service.drain(timeout_s=10.0)


# ---------------------------------------------------------------------------
# the full promotion soak: real serve.py replicas + --bank-dir
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_bank_promotion_soak_real_replicas(pair_exports, tmp_path):
    """ISSUE 16 acceptance, full stack: 2 REAL tools/serve.py replicas
    booted on the (checkpoint A, bank A) pair under a --bank-dir fleet.
    A manifested checkpoint B WAITS until its paired bank lands, then
    the fleet dual-swaps both replicas under closed-loop load with zero
    lost; post-swap /v1/embed is bit-identical to a cold start on B and
    /v1/knn answers from the new bank."""
    import subprocess
    import sys as _sys

    path_a, path_b = pair_exports
    serve_bench = _load_tool("serve_bench")
    watch = tmp_path / "export"
    watch.mkdir()
    bank_dir = tmp_path / "bank"
    bank_dir.mkdir()
    serve_py = os.path.join(REPO, "tools", "serve.py")
    bank_build_py = os.path.join(REPO, "tools", "bank_build.py")

    imgs = np.random.RandomState(6).randint(
        0, 256, (6, J_SIZE, J_SIZE, 3)).astype(np.uint8)
    corpus = tmp_path / "corpus.npz"
    np.savez(corpus, images=imgs, labels=np.arange(6) % 2)

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MOCO_TPU_NO_CACHE="1")

    def cli_build(checkpoint, step):
        subprocess.run(
            [_sys.executable, bank_build_py, "--checkpoint", checkpoint,
             "--step", str(step), "--bank-dir", str(bank_dir),
             "--corpus", str(corpus), "--arch", "resnet_tiny",
             "--cifar-stem", "--image-size", str(J_SIZE),
             "--buckets", "1,4", "--shards", "2"],
            env=env, check=True, timeout=300,
        )

    cli_build(path_a, 1)
    boot_bank = str(bank_dir / "1" / "bank.npz")

    def child_argv(index, port, tdir, pretrained, bank=None):
        return [_sys.executable, "-u", serve_py,
                "--pretrained", pretrained or path_a,
                "--knn-bank", bank or boot_bank,
                "--arch", "resnet_tiny", "--image-size", str(J_SIZE),
                "--cifar-stem", "true", "--buckets", "1", "4",
                "--flush-ms", "5.0", "--port", str(port),
                "--telemetry-dir", tdir, "--snapshot-every", "5"]

    fleet = FleetSupervisor(
        child_argv, replicas=2, telemetry_dir=str(tmp_path / "fleet_t"),
        watch_dir=str(watch), bank_dir=str(bank_dir), env=env,
        policy=FleetPolicy(
            probe_secs=0.2, probe_timeout_s=2.0, health_stale_secs=10.0,
            startup_grace_secs=240.0, term_grace_secs=5.0,
            backoff_base_secs=0.2, backoff_max_secs=1.0,
            watch_poll_secs=0.2, reload_timeout_s=240.0,
        ), seed=0,
    )
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, timeout_s=240.0,
              msg="2 real replicas healthy")
        # checkpoint B lands WITHOUT its bank: the fleet waits
        step_dir = watch / "60"
        step_dir.mkdir()
        shutil.copy(path_b, step_dir / "encoder.npz")
        write_manifest(str(watch), 60)
        _wait(lambda: any(e["event"] == "bank_waiting"
                          for e in fleet.incidents), timeout_s=60.0,
              msg="fleet announced the missing paired bank")
        assert all(r.deployed_step == -1 for r in fleet.replicas)

        # the paired bank lands -> dual swap under closed-loop load
        cli_build(str(step_dir / "encoder.npz"), 60)
        result = {}

        def load():
            result.update(serve_bench.run_load(
                fleet.router.url, concurrency=8, total_requests=128,
                image_size=J_SIZE, pool=8, timeout_s=60.0,
            ))

        loader = threading.Thread(target=load)
        loader.start()
        _wait(lambda: all(r.deployed_step == 60 for r in fleet.replicas),
              timeout_s=240.0, msg="dual swap rolled across the fleet")
        loader.join(timeout=120.0)
        assert result["lost"] == 0, result["lost_detail"]

        # bit-identity + the new bank answers /v1/knn
        img = imgs[0]
        body = {"image_b64": base64.b64encode(img.tobytes()).decode(),
                "shape": list(img.shape)}
        status, resp = _post(fleet.router.url + "/v1/embed", body,
                             timeout=60.0)
        assert status == 200
        cold = _jax_engine(path_b)
        cold.warmup()
        assert np.array_equal(
            np.asarray(resp["embedding"], np.float32),
            cold.embed(img[None])[0],
        )
        status, resp = _post(fleet.router.url + "/v1/knn", body,
                             timeout=60.0)
        assert status == 200 and resp["class"] in (0, 1)
        assert fleet.stats()["bank"]["good_step"] == 60
        events = [e["event"] for e in fleet.incidents]
        assert "reload_done" in events
    finally:
        fleet.stop()
