"""moco_tpu.utils.cache: the persistent XLA compile cache helper the bench
children and train driver call (VERDICT r4 #2a). The helper must point JAX
at the dir, honor the opt-out, and never raise."""

import os

import jax

from moco_tpu.utils.cache import enable_persistent_cache


_MIN_COMPILE_DEFAULT = jax.config.jax_persistent_cache_min_compile_time_secs


def _reset():
    jax.config.update("jax_compilation_cache_dir", None)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      _MIN_COMPILE_DEFAULT)


def test_enable_points_jax_at_dir(tmp_path):
    try:
        d = str(tmp_path / "cache")
        out = enable_persistent_cache(d)
        assert out == d and os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
    finally:
        _reset()


def test_env_dir_override(tmp_path, monkeypatch):
    try:
        d = str(tmp_path / "env_cache")
        monkeypatch.setenv("MOCO_TPU_CACHE_DIR", d)
        assert enable_persistent_cache() == d
    finally:
        _reset()


def test_no_cache_opt_out(tmp_path, monkeypatch):
    monkeypatch.setenv("MOCO_TPU_NO_CACHE", "1")
    before = jax.config.jax_compilation_cache_dir
    assert enable_persistent_cache(str(tmp_path / "x")) is None
    assert jax.config.jax_compilation_cache_dir == before
    assert not os.path.exists(tmp_path / "x")


def test_per_run_cache_dir_isolated_and_created(tmp_path):
    """ISSUE 5 satellite (PR 4 finding): kill-risk processes get a cache
    dir no other process shares, under <base>/per_run, created eagerly."""
    from moco_tpu.utils.cache import per_run_cache_dir

    a = per_run_cache_dir(str(tmp_path), tag="drill")
    b = per_run_cache_dir(str(tmp_path), tag="drill")
    assert a != b  # two calls, two runs: never shared
    for d in (a, b):
        assert os.path.isdir(d)
        assert os.path.dirname(d) == str(tmp_path / "per_run")
        assert os.path.basename(d).startswith("drill-")


def test_per_run_cache_dir_honors_cache_root_env(tmp_path, monkeypatch):
    from moco_tpu.utils.cache import per_run_cache_dir

    monkeypatch.setenv("MOCO_TPU_CACHE_ROOT", str(tmp_path / "root"))
    d = per_run_cache_dir(tag="serve")
    assert d.startswith(str(tmp_path / "root"))
    assert os.path.isdir(d)
