"""obsd: fleet-wide aggregation + SLO/burn-rate engine (ISSUE 12).

  - pure units: PercentileWindow ring math, StreamTailer partial-line /
    truncation discipline, RunWindow objective folds, SLORule validation
  - burn-rate engine: fast+slow gating, for_s arming, clear_s recovery
    hysteresis, one alert per sustained incident (no flapping)
  - HTTP contract: /metrics is valid Prometheus text exposition 0.0.4,
    /slo and /runs are schema-stable JSON — probed over real HTTP
  - heartbeat monotonic pair (satellite): seq/mono_s written by every
    beat; the supervisor's freshness/change checks prefer them, so a
    wall-clock step reads as neither hang nor freshness
  - router_stats schema (satellite): the autoscaler input record carries
    cumulative per-code sheds, outstanding depth, latency percentiles
  - import diet: aggregate.py + tools/obsd.py run with jax/numpy blocked
    (subprocess, like trace.py's — mocolint R11 obsd-stdlib-only)
  - THE acceptance smoke: 30-step CPU train with chaos slow_at_step
    while a 2-replica stub fleet serves load, ONE obsd tailing both →
    the step-time SLO fires exactly one alert then one recovery,
    /metrics + /slo stay valid during the run, the slo records land
    under the producing run_ids, and telemetry_report renders `slo:`
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import pytest

from moco_tpu.telemetry.aggregate import (
    Aggregator,
    ObsServer,
    PercentileWindow,
    RunWindow,
    SLOEngine,
    SLORule,
    StreamTailer,
    discover_streams,
    load_rules,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "tools", "telemetry_report.py")


# ---------------------------------------------------------------------------
# percentile window
# ---------------------------------------------------------------------------


def test_percentile_window_nearest_rank_and_ring():
    w = PercentileWindow(size=4)
    assert w.percentile(95) == 0.0  # empty: 0, never raises
    for v in (0.010, 0.020, 0.030, 0.040):
        w.observe(v)
    assert w.percentile(50) == pytest.approx(0.030)
    assert w.percentile(99) == pytest.approx(0.040)
    # ring: a 5th observation evicts the oldest
    w.observe(0.050)
    assert w.count == 4
    assert w.percentile(0) == pytest.approx(0.020)
    pct = w.percentiles_ms()
    assert set(pct) == {"p50", "p95", "p99"}
    assert pct["p50"] <= pct["p95"] <= pct["p99"] == 50.0


def test_percentile_window_rejects_bad_size():
    with pytest.raises(ValueError):
        PercentileWindow(size=0)


# ---------------------------------------------------------------------------
# stream tailing
# ---------------------------------------------------------------------------


def test_tailer_partial_line_and_truncation(tmp_path):
    path = str(tmp_path / "events.jsonl")
    t = StreamTailer(path)
    assert t.poll() == []  # missing file: "not yet", never an error
    with open(path, "w") as f:
        f.write('{"kind": "step", "step": 1}\n{"kind": "st')
        f.flush()
    recs = t.poll()
    assert [r["step"] for r in recs] == [1]  # torn tail stays buffered
    with open(path, "a") as f:
        f.write('ep", "step": 2}\n')
    recs = t.poll()
    assert [r["step"] for r in recs] == [2]  # completed across two polls
    # truncation resets the offset and re-reads from the top
    with open(path, "w") as f:
        f.write('{"kind": "step", "step": 9}\nnot json at all\n')
    recs = t.poll()
    assert [r["step"] for r in recs] == [9]
    assert t.skipped == 1  # the garbage line counted, not fatal


def test_discover_streams_fleet_layout(tmp_path):
    fleet = tmp_path / "fleet"
    (fleet / "replica0").mkdir(parents=True)
    (fleet / "replica1").mkdir()
    (fleet / "not_a_replica").mkdir()
    (fleet / "events.jsonl").write_text("")
    (fleet / "replica0" / "events.jsonl").write_text("")
    (fleet / "replica1" / "events.jsonl").write_text("")
    (fleet / "not_a_replica" / "events.jsonl").write_text("")
    lone = tmp_path / "train.jsonl"
    lone.write_text("")
    streams = discover_streams([str(fleet), str(lone)])
    labels = sorted(os.path.basename(k.rstrip("/")) for k in streams)
    assert labels == ["fleet", "replica0", "replica1", "train.jsonl"]


# ---------------------------------------------------------------------------
# run-window objective folds
# ---------------------------------------------------------------------------


def _step(step, step_s, data_s=0.0, mfu=None, run="r1"):
    rec = {"v": 1, "t": time.time(), "kind": "step", "run_id": run,
           "step": step, "step_s": step_s, "data_s": data_s}
    if mfu is not None:
        rec["mfu"] = mfu
    return rec


def test_run_window_step_metrics_and_min_step():
    w = RunWindow("r1")
    w.ingest(_step(1, 5.0), "src", "p", now=100.0)  # the compile step
    for i in range(2, 12):
        w.ingest(_step(i, 0.1, data_s=0.05, mfu=0.2), "src", "p",
                 now=100.0 + i)
    # min_step=0 sees the compile blowout; min_step=3 drops it AND the
    # early steps (the SlowSampleDetector `skip` lesson)
    assert w.metric("step_time_ms_max", 1000.0, 120.0) == 5000.0
    assert w.metric("step_time_ms_max", 1000.0, 120.0, 3) == \
        pytest.approx(100.0)
    assert w.metric("step_time_ms_p50", 1000.0, 120.0, 3) == \
        pytest.approx(100.0)
    assert w.metric("data_share", 1000.0, 120.0, 3) == pytest.approx(0.5)
    assert w.metric("mfu_mean", 1000.0, 120.0, 3) == pytest.approx(0.2)
    # the TIME window is on the aggregator's observation clock: a narrow
    # window sees only the recent steps
    assert w.metric("step_time_ms_max", 3.0, 112.0) == pytest.approx(100.0)
    # and an empty window answers None, never 0 (silence != healthy)
    assert w.metric("step_time_ms_p95", 1.0, 500.0) is None
    with pytest.raises(ValueError):
        w.metric("no_such_objective", 10.0, 0.0)


def test_run_window_shed_rate_from_router_deltas():
    w = RunWindow("r1")

    def router(now, requests, sheds):
        w.ingest({"kind": "fleet", "event": "router_stats",
                  "requests": requests, "shed_no_backend": sheds,
                  "outstanding": 3,
                  "latency_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0}},
                 "fleet", "p", now)

    assert w.metric("shed_rate", 60.0, 100.0) is None  # < 2 snapshots
    router(100.0, 100, 0)
    assert w.metric("shed_rate", 60.0, 100.0) is None
    router(110.0, 300, 10)
    # delta: 10 sheds / 200 requests inside the window
    assert w.metric("shed_rate", 60.0, 115.0) == pytest.approx(0.05)
    assert w.metric("outstanding", 60.0, 115.0) == 3.0
    assert w.metric("router_latency_ms_p95", 60.0, 115.0) == 2.0
    # counters are cumulative: a window covering only the LAST snapshot
    # has one point -> None, not a fabricated rate
    assert w.metric("shed_rate", 4.0, 115.0) is None


def test_run_window_event_counts_and_slo_feedback_guard():
    w = RunWindow("r1")
    w.ingest({"kind": "event", "event": "rollback"}, "s", "p", 10.0)
    w.ingest({"kind": "event", "event": "sentinel"}, "s", "p", 11.0)
    w.ingest({"kind": "fleet", "event": "reload_quarantine"}, "s", "p", 12.0)
    w.ingest({"kind": "supervisor", "event": "resize_relaunch"},
             "s", "p", 13.0)
    assert w.metric("rollback_events", 60.0, 20.0) == 2.0
    assert w.metric("reload_failures", 60.0, 20.0) == 1.0
    assert w.metric("resize_relaunches", 60.0, 20.0) == 1.0
    assert w.metric("event:resize_relaunch", 60.0, 20.0) == 1.0
    # time-windowed: far in the future they're gone
    assert w.metric("rollback_events", 5.0, 1000.0) == 0.0
    # kind:"slo" records NEVER feed back into the windows they were
    # computed from — only the counter moves
    w.ingest({"kind": "slo", "action": "alert", "rule": "x"}, "s", "p", 14.0)
    assert w.slo_events == 1
    assert "slo" not in w.kinds


# ---------------------------------------------------------------------------
# SLO rules + burn-rate engine
# ---------------------------------------------------------------------------


def test_slo_rule_validation():
    with pytest.raises(ValueError):
        SLORule({"name": "x", "objective": "step_time_ms_p95"})  # no threshold
    with pytest.raises(ValueError):
        SLORule({"name": "x", "objective": "o", "threshold": 1,
                 "op": "!="})
    with pytest.raises(ValueError):
        SLORule({"name": "x", "objective": "o", "threshold": 1,
                 "fast_window_s": 60, "slow_window_s": 30})
    r = SLORule({"name": "x", "objective": "step_time_ms_p95",
                 "threshold": 100})
    assert r.op == ">" and r.slow_window_s == 5 * r.fast_window_s
    assert r.min_step == 3  # compile steps excluded by default
    assert r.clear_s == 2.0  # default hysteresis EXISTS: a metric at
    # its threshold must not flap one alert/recovery pair per tick


def test_default_clear_s_suppresses_tick_flap():
    engine, windows = _engine_with_steps(
        [(100.0, 2.0)], {"fast_window_s": 3, "slow_window_s": 6})
    assert [t["action"] for t in engine.evaluate(windows, 101.0)] \
        == ["alert"]
    # the stall ages out at 103; with the 2 s default clear_s the very
    # next clean tick must NOT already recover
    assert engine.evaluate(windows, 103.5) == []
    assert [t["action"] for t in engine.evaluate(windows, 106.0)] \
        == ["recover"]


def test_load_rules_default_set_and_file(tmp_path):
    rules = load_rules(None)
    names = {r.name for r in rules}
    # the documented default set: step-time p95, data-stall share, shed
    # rate, input credit stall (ISSUE 14), reload failure, NaN/rollback,
    # resize loop
    assert names == {"step_time_p95", "data_stall_share", "shed_rate",
                     "input_credit_stall", "reload_failure",
                     "nonfinite_loss", "resize_loop"}
    path = tmp_path / "rules.json"
    path.write_text(json.dumps({"rules": [
        {"name": "a", "objective": "step_time_ms_p95", "threshold": 5},
    ]}))
    assert [r.name for r in load_rules(str(path))] == ["a"]
    path.write_text(json.dumps([
        {"name": "a", "objective": "o", "threshold": 1},
        {"name": "a", "objective": "o", "threshold": 2},
    ]))
    with pytest.raises(ValueError, match="duplicate"):
        load_rules(str(path))
    path.write_text("{}")
    with pytest.raises(ValueError):
        load_rules(str(path))


def _engine_with_steps(step_s_by_time, rule_kw):
    """One window fed with (now, step_s) samples + one rule engine."""
    w = RunWindow("r1")
    for i, (now, step_s) in enumerate(step_s_by_time):
        w.ingest(_step(i + 10, step_s), "src", "p", now)
    rule = SLORule({"name": "st", "objective": "step_time_ms_max",
                    "op": ">", "threshold": 1000.0, **rule_kw})
    return SLOEngine([rule]), {"r1": w}


def test_burn_rate_needs_both_windows():
    # fast window violated, slow window CLEAN -> no alert (a blip the
    # slow window absorbs). Achieved via a steeper slow threshold.
    engine, windows = _engine_with_steps(
        [(100.0 + i, 0.1) for i in range(10)] + [(111.0, 2.0)],
        {"fast_window_s": 5, "slow_window_s": 50,
         "slow_threshold": 5000.0},
    )
    assert engine.evaluate(windows, 112.0) == []
    st = engine.state_for("st", "r1")
    assert not st.alerting
    assert st.last_fast == pytest.approx(2000.0)


def test_burn_rate_alert_for_s_and_recovery_hysteresis():
    engine, windows = _engine_with_steps(
        [(100.0, 2.0)],  # one 2 s stall
        {"fast_window_s": 10, "slow_window_s": 20,
         "for_s": 3.0, "clear_s": 4.0},
    )
    # violating but not yet sustained for for_s: armed, silent
    assert engine.evaluate(windows, 101.0) == []
    assert engine.evaluate(windows, 102.0) == []
    out = engine.evaluate(windows, 104.5)  # 3.5 s sustained -> alert
    assert [t["action"] for t in out] == ["alert"]
    assert out[0]["rule"] == "st" and out[0]["run_id"] == "r1"
    assert out[0]["value_fast"] == pytest.approx(2000.0)
    # still violating: no re-alert
    assert engine.evaluate(windows, 106.0) == []
    # stall ages out of the fast window at t=110; clear_s=4 holds the
    # recovery until the clean stretch is sustained
    assert engine.evaluate(windows, 111.0) == []
    assert engine.evaluate(windows, 113.0) == []
    out = engine.evaluate(windows, 115.5)
    assert [t["action"] for t in out] == ["recover"]
    # fully drained: nothing else ever fires
    assert engine.evaluate(windows, 200.0) == []
    st = engine.state_for("st", "r1")
    assert st.alerts == 1 and st.recoveries == 1


def test_burn_rate_flap_within_for_s_rearms():
    # a violation that clears before for_s elapses never alerts
    engine, windows = _engine_with_steps(
        [(100.0, 2.0)],
        {"fast_window_s": 2, "slow_window_s": 4, "for_s": 5.0},
    )
    assert engine.evaluate(windows, 101.0) == []  # violating, arming
    assert engine.evaluate(windows, 107.0) == []  # aged out before for_s
    assert engine.evaluate(windows, 200.0) == []
    assert engine.state_for("st", "r1").alerts == 0


def test_engine_snapshot_shape():
    engine, windows = _engine_with_steps(
        [(100.0, 2.0)], {"fast_window_s": 10, "slow_window_s": 20})
    engine.evaluate(windows, 101.0)
    snap = engine.snapshot(windows)
    (rule,) = snap["rules"]
    assert rule["name"] == "st"
    assert rule["runs"]["r1"]["state"] == "alert"
    assert rule["runs"]["r1"]["alerts"] == 1
    assert "since" in rule["runs"]["r1"]


# ---------------------------------------------------------------------------
# aggregator: multi-stream ingest + slo emission
# ---------------------------------------------------------------------------


def _write_lines(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_aggregator_emits_slo_into_producing_stream(tmp_path):
    train = tmp_path / "train"
    train.mkdir()
    ev = str(train / "events.jsonl")
    _write_lines(ev, [_step(i, 0.05) for i in range(4, 10)])
    rules = [SLORule({"name": "st", "objective": "step_time_ms_max",
                      "threshold": 1000.0, "fast_window_s": 5,
                      "slow_window_s": 10})]
    agg = Aggregator([str(train)], rules=rules)
    assert agg.poll_once() == []
    _write_lines(ev, [_step(11, 2.0)])
    (transition,) = agg.poll_once()
    assert transition["action"] == "alert"
    # the record landed in the PRODUCING run's own stream, kind:"slo",
    # under the producing run_id
    slo_lines = [json.loads(line) for line in open(ev)
                 if '"slo"' in line]
    assert len(slo_lines) == 1
    assert slo_lines[0]["kind"] == "slo"
    assert slo_lines[0]["run_id"] == "r1"
    assert slo_lines[0]["rule"] == "st"
    # the appended line reads back without disturbing the alert state
    assert agg.poll_once() == []
    assert agg.windows["r1"].slo_events == 1


def test_aggregator_no_emit_mode(tmp_path):
    train = tmp_path / "train"
    train.mkdir()
    ev = str(train / "events.jsonl")
    rules = [SLORule({"name": "st", "objective": "step_time_ms_max",
                      "threshold": 1000.0, "fast_window_s": 5,
                      "slow_window_s": 10})]
    agg = Aggregator([str(train)], rules=rules, emit_slo=False)
    agg.poll_once()  # tailer exists before the stall lands (live data)
    _write_lines(ev, [_step(11, 2.0)])
    (transition,) = agg.poll_once()
    assert transition["action"] == "alert"
    assert not [line for line in open(ev) if '"slo"' in line]
    assert agg.windows["r1"].slo_events == 1  # still counted


def test_aggregator_restart_does_not_replay_history(tmp_path):
    """The restart story (review finding): a stream already containing
    an incident AND its alert/recover pair is catch-up for a fresh
    obsd — counters/meta fold, but the windows stay empty, no duplicate
    alert is appended, and a NEW incident still fires."""
    train = tmp_path / "train"
    train.mkdir()
    ev = str(train / "events.jsonl")
    _write_lines(ev, [_step(i, 0.05) for i in range(4, 10)]
                 + [_step(10, 2.0)]  # yesterday's stall
                 + [{"kind": "slo", "action": "alert", "rule": "st",
                     "run_id": "r1"},
                    {"kind": "slo", "action": "recover", "rule": "st",
                     "run_id": "r1"}])
    rules = [SLORule({"name": "st", "objective": "step_time_ms_max",
                      "threshold": 1000.0, "fast_window_s": 5,
                      "slow_window_s": 10})]
    agg = Aggregator([str(train)], rules=rules)
    assert agg.poll_once() == []  # catch-up: NO duplicate alert
    assert agg.poll_once() == []
    window = agg.windows["r1"]
    assert window.steps_total == 7          # history still counted
    assert window.slo_events == 2
    assert window.metric("step_time_ms_max", 1e9, time.monotonic()) \
        is None                             # ...but not windowed
    assert len([line for line in open(ev) if '"slo"' in line]) == 2
    # a LIVE stall after the restart still alerts exactly once
    _write_lines(ev, [_step(20, 2.0)])
    (transition,) = agg.poll_once()
    assert transition["action"] == "alert"


def test_aggregator_discovers_late_replica_dirs(tmp_path):
    fleet = tmp_path / "fleet"
    fleet.mkdir()
    _write_lines(str(fleet / "events.jsonl"),
                 [{"kind": "fleet", "event": "fleet_start",
                   "run_id": "f1", "replicas": 2}])
    agg = Aggregator([str(fleet)], rules=[])
    agg.poll_once()
    assert agg.runs_snapshot()["streams"] == 1
    # a replica dir that appears AFTER the aggregator started is tailed
    _write_lines(str(fleet / "replica0" / "events.jsonl"),
                 [{"kind": "serve", "run_id": "f1", "requests": 5,
                   "served": 5, "latency_ms": {"p95": 3.0}}])
    agg.poll_once()
    snap = agg.runs_snapshot()
    assert snap["streams"] == 2
    (run,) = snap["runs"]
    assert run["kinds"] == {"fleet": 1, "serve": 1}


def test_aggregator_retires_dead_runs(tmp_path):
    """Bounded state for an always-on daemon: an ended (or long-silent)
    run's window AND rule state are dropped once nothing is alerting —
    run_ids churn with every relaunch, and a watcher that only ever
    gains windows degrades for its whole (long) life."""
    train = tmp_path / "train"
    train.mkdir()
    ev = str(train / "events.jsonl")
    rules = [SLORule({"name": "st", "objective": "step_time_ms_max",
                      "threshold": 1000.0, "fast_window_s": 5,
                      "slow_window_s": 10})]
    agg = Aggregator([str(train)], rules=rules, retire_after_s=100.0)
    now = time.monotonic()
    agg.poll_once(now)
    _write_lines(ev, [_step(5, 0.05), {"kind": "run_end", "run_id": "r1",
                                       "steps": 5}])
    agg.poll_once(now + 1.0)
    assert "r1" in agg.windows
    # ended + past the post-end grace -> retired, state gone
    agg.poll_once(now + 70.0)
    assert "r1" not in agg.windows
    assert agg.retired == 1
    assert not agg.engine._state
    # a silent-but-never-ended run retires on retire_after_s
    _write_lines(ev, [_step(6, 0.05, run="r2")])
    agg.poll_once(now + 71.0)
    assert "r2" in agg.windows
    agg.poll_once(now + 180.0)
    assert "r2" not in agg.windows
    # an ALERTING run is never retired out from under its recovery
    _write_lines(ev, [_step(7, 2.0, run="r3")])
    agg.poll_once(now + 181.0)
    assert agg.engine.state_for("st", "r3").alerting
    agg.poll_once(now + 400.0)  # silent way past retire_after_s
    assert "r3" in agg.windows  # still held: recovery must land first


# ---------------------------------------------------------------------------
# HTTP contract
# ---------------------------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? -?[0-9.e+-]+$"
)


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read().decode("utf-8")


def validate_prometheus(text: str) -> dict:
    """Assert `text` is well-formed exposition; return {metric: samples}."""
    metrics: dict[str, int] = {}
    typed: set[str] = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            assert len(parts) >= 4, line
            if parts[1] == "TYPE":
                assert parts[3] in ("gauge", "counter"), line
                typed.add(parts[2])
            continue
        assert _PROM_SAMPLE.match(line), f"bad exposition line: {line!r}"
        name = line.split("{", 1)[0].split(" ", 1)[0]
        assert name in typed, f"sample before TYPE: {line!r}"
        metrics[name] = metrics.get(name, 0) + 1
    assert text.endswith("\n")
    return metrics


@pytest.fixture()
def obs_http(tmp_path):
    train = tmp_path / "train"
    train.mkdir()
    rules = [SLORule({"name": "st", "objective": "step_time_ms_max",
                      "threshold": 1000.0, "fast_window_s": 5,
                      "slow_window_s": 10})]
    agg = Aggregator([str(train)], rules=rules)
    agg.poll_once()  # tailer exists first: the records below are LIVE
    _write_lines(str(train / "events.jsonl"),
                 [{"v": 1, "t": time.time(), "kind": "run_start",
                   "run_id": "r1", "name": "smoke", "arch": "tiny"}]
                 + [_step(i, 0.05, data_s=0.01, mfu=0.3)
                    for i in range(4, 10)]
                 + [{"kind": "event", "event": "rollback", "run_id": "r1"},
                    {"kind": "fleet", "event": "router_stats",
                     "run_id": "r1", "requests": 10, "ok": 9,
                     "shed_no_backend": 1, "outstanding": 2,
                     "latency_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0}}])
    agg.poll_once()
    server = ObsServer(agg)
    server.start()
    try:
        yield server, agg, str(train / "events.jsonl")
    finally:
        server.shutdown()


def test_metrics_endpoint_valid_exposition(obs_http):
    server, agg, _ = obs_http
    status, headers, body = _get(server.url + "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
    metrics = validate_prometheus(body)
    assert metrics["moco_tpu_steps_total"] == 1
    assert metrics["moco_tpu_step_time_ms"] == 3  # p50/p95/p99
    assert metrics["moco_tpu_events_total"] >= 1
    assert metrics["moco_tpu_router_outstanding"] == 1
    assert metrics["moco_tpu_router_requests_total"] == 1
    assert metrics["moco_tpu_router_latency_ms"] == 3
    assert metrics["moco_tpu_obsd_streams"] == 1
    assert 'run_id="r1"' in body


def test_slo_and_runs_endpoints_json(obs_http):
    server, agg, _ = obs_http
    status, headers, body = _get(server.url + "/slo")
    assert status == 200
    assert headers["Content-Type"] == "application/json"
    slo = json.loads(body)
    assert slo["v"] == 1
    (rule,) = slo["rules"]
    assert rule["name"] == "st"
    assert rule["runs"]["r1"]["state"] == "ok"
    status, _, body = _get(server.url + "/runs")
    runs = json.loads(body)
    assert runs["records"] == 9
    (run,) = runs["runs"]
    assert run["run_id"] == "r1"
    assert run["run"]["name"] == "smoke"
    assert run["steps"] == 6
    assert "stale_s" in run
    status, _, _ = _get(server.url + "/healthz")
    assert status == 200
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(server.url + "/nope")
    assert exc.value.code == 404


# ---------------------------------------------------------------------------
# heartbeat monotonic pair (satellite)
# ---------------------------------------------------------------------------


def test_heartbeat_writes_seq_and_mono(tmp_path):
    from moco_tpu.telemetry.registry import Heartbeat

    hb = Heartbeat(str(tmp_path / "heartbeat.json"))
    hb.beat(1, phase="step")
    first = json.load(open(tmp_path / "heartbeat.json"))
    hb.beat(2, phase="step")
    second = json.load(open(tmp_path / "heartbeat.json"))
    assert first["seq"] == 1 and second["seq"] == 2
    assert second["mono_s"] >= first["mono_s"] > 0
    assert second["pid"] == os.getpid()


def test_supervisor_staleness_prefers_monotonic_pair():
    from moco_tpu.resilience.supervisor import beat_is_fresh, beat_marker

    now_wall, now_mono = time.time(), time.monotonic()
    # BACKWARD wall jump since our launch: the launch's wall stamp sits
    # 100 s in the (new) future, so the wall comparison would call a
    # LIVE child's current beat stale — the mono pair (same boot: the
    # beat's t−mono_s offset matches ours) must win
    launched_wall, launched_mono = now_wall + 100.0, now_mono - 10.0
    live = {"t": now_wall, "mono_s": now_mono, "seq": 7}
    assert beat_is_fresh(live, launched_wall, launched_mono)
    # same boot, genuinely stale (previous incarnation, written 50 s
    # before our launch): mono says stale even if a forward wall jump
    # at launch time would confuse the wall comparison
    stale = {"t": now_wall - 50.0, "mono_s": now_mono - 50.0}
    assert not beat_is_fresh(stale, now_wall - 60.0, now_mono - 10.0)
    # CROSS-HOST beat (srun wrapper on another node, shared FS): the
    # writer's clock offset disagrees wildly, so CLOCK_MONOTONIC is
    # incomparable — wall semantics (the pre-pair behavior) apply
    foreign = {"t": now_wall + 1.0, "mono_s": 1234.5}
    assert beat_is_fresh(foreign, now_wall, now_mono - 10.0)
    foreign_stale = {"t": now_wall - 99.0, "mono_s": 1234.5}
    assert not beat_is_fresh(foreign_stale, now_wall, now_mono - 10.0)
    # no mono pair (old payload): wall fallback unchanged
    assert beat_is_fresh({"t": now_wall + 1.0}, now_wall, now_mono)
    assert not beat_is_fresh({"t": now_wall - 1.0}, now_wall, now_mono)
    # change detection keys on seq when present (equal wall stamps from
    # a coarse clock can no longer mask progress)
    a = {"t": 100.0, "seq": 1}
    b = {"t": 100.0, "seq": 2}
    assert beat_marker(a) != beat_marker(b)
    assert beat_marker({"t": 100.0}) == ("t", 100.0)
    # a seq marker can never collide with a t marker
    assert beat_marker({"seq": 100}) != beat_marker({"t": 100})


# ---------------------------------------------------------------------------
# router_stats schema (satellite): the stable autoscaler input
# ---------------------------------------------------------------------------


def test_router_stats_record_schema(tmp_path):
    from moco_tpu.serve.fleet import FleetPolicy, FleetSupervisor

    fleet = FleetSupervisor(
        lambda *a: ["true"], replicas=1,
        telemetry_dir=str(tmp_path / "fleet_t"),
        policy=FleetPolicy(stats_every_secs=0.1), seed=0,
    )
    # no .start(): drive the counters directly and emit
    fleet.r_requests, fleet.r_ok = 100, 90
    fleet.r_shed_no_backend, fleet.r_upstream_timeout = 4, 3
    fleet.r_upstream_error, fleet.r_deadline_router = 2, 1
    for v in (0.010, 0.020, 0.030):
        fleet._router_latency.observe(v)
    fleet._emit_router_stats(final=True)
    (rec,) = [json.loads(line)
              for line in open(tmp_path / "fleet_t" / "events.jsonl")]
    assert rec["kind"] == "fleet" and rec["event"] == "router_stats"
    # the stable schema obsd + the autoscaler key on
    for key in ("requests", "ok", "retries", "retry_ok",
                "shed_no_backend", "upstream_timeout", "upstream_error",
                "shed_deadline_router", "passthrough_non_200",
                "outstanding", "healthy", "replicas", "interval_s",
                "run_id"):
        assert key in rec, key
    assert rec["requests"] == 100 and rec["shed_deadline_router"] == 1
    assert rec["latency_ms"]["p50"] == pytest.approx(20.0)
    assert rec["window"] == 3
    # report folds the new fields into the router section
    from tools.telemetry_report import summarize

    flt = summarize([rec])["fleet"]
    assert flt["router"]["outstanding"] == 0
    assert flt["router"]["latency_ms"]["p95"] == pytest.approx(30.0)
    assert flt["router"]["shed_rate"] == pytest.approx(0.1)


# ---------------------------------------------------------------------------
# report: slo section + follow line
# ---------------------------------------------------------------------------


def test_report_renders_slo_section(tmp_path):
    from tools.telemetry_report import render, render_record, summarize

    records = [
        _step(5, 0.05),
        {"v": 1, "t": 1.0, "kind": "slo", "action": "alert",
         "rule": "step_time_p95", "objective": "step_time_ms_p95",
         "op": ">", "threshold": 500.0, "run_id": "r1",
         "value_fast": 2000.0, "value_slow": 1500.0},
        {"v": 1, "t": 2.0, "kind": "slo", "action": "recover",
         "rule": "step_time_p95", "objective": "step_time_ms_p95",
         "run_id": "r1", "value_fast": 50.0},
    ]
    summary = summarize(records)
    assert summary["slo"]["alerts"] == 1
    assert summary["slo"]["recoveries"] == 1
    assert summary["slo"]["active"] == []
    rule = summary["slo"]["by_rule"]["step_time_p95"]
    assert rule["alerts"] == 1 and not rule["active"]
    text = render(summary)
    assert "slo: 1 alert(s), 1 recovery(ies) — all clear" in text
    assert "step_time_p95: 1 alert(s) / 1 recovery(ies)" in text
    # an unrecovered alert shows ACTIVE
    summary2 = summarize(records[:2])
    assert summary2["slo"]["active"] == ["step_time_p95"]
    assert "ACTIVE: step_time_p95" in render(summary2)
    # --follow renders slo lines like fleet/resize ones
    line = render_record(records[1])
    assert line.startswith("slo: ALERT step_time_p95")
    assert "step_time_ms_p95=2000.0" in line and "run=r1" in line


# ---------------------------------------------------------------------------
# import diet: aggregate + obsd without jax/numpy (subprocess)
# ---------------------------------------------------------------------------


def test_obsd_imports_without_jax_or_numpy(tmp_path):
    events = tmp_path / "t" / "events.jsonl"
    events.parent.mkdir()
    events.write_text(json.dumps(
        {"v": 1, "t": 0.0, "kind": "step", "run_id": "r", "step": 5,
         "step_s": 0.05, "data_s": 0.01}) + "\n")
    code = textwrap.dedent(f"""
        import sys
        class Block:
            def find_module(self, name, path=None):
                root = name.split('.')[0]
                if root in ('jax', 'jaxlib', 'numpy', 'flax', 'optax',
                            'orbax', 'scipy'):
                    raise ImportError('blocked heavy import: ' + name)
        sys.meta_path.insert(0, Block())
        from moco_tpu.telemetry.aggregate import Aggregator, load_rules
        agg = Aggregator([{str(tmp_path / 't')!r}], rules=load_rules(None))
        agg.poll_once()
        assert agg.runs_snapshot()['records'] == 1
        assert 'moco_tpu_steps_total' in agg.prometheus()
        import tools.obsd
        print('CLEAN')
    """)
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "CLEAN" in out.stdout


def test_obsd_cli_once_mode(tmp_path):
    events = tmp_path / "t" / "events.jsonl"
    events.parent.mkdir()
    events.write_text(json.dumps(
        {"v": 1, "t": 0.0, "kind": "step", "run_id": "r", "step": 5,
         "step_s": 0.05}) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsd.py"),
         str(tmp_path / "t"), "--once"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    snap = json.loads(out.stdout)
    assert snap["records"] == 1
    # a bad rule file is a config error (45), not a traceback
    bad = tmp_path / "rules.json"
    bad.write_text("{}")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obsd.py"),
         str(tmp_path / "t"), "--once", "--rules", str(bad)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 45


# ---------------------------------------------------------------------------
# THE acceptance smoke (ISSUE 12): train + stub fleet under ONE obsd
# ---------------------------------------------------------------------------

_STUB_REPLICA = textwrap.dedent("""\
    import argparse, json, threading, time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--telemetry-dir", required=True)
    p.add_argument("--pretrained", default="boot")
    args, _ = p.parse_known_args()

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        def log_message(self, *a):
            pass
        def _send(self, status, obj):
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        def do_GET(self):
            self._send(200, {"status": "ok"})
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length") or 0))
            self._send(200, {"embedding": [1.0], "cached": False})

    class S(ThreadingHTTPServer):
        daemon_threads = True

    S(("127.0.0.1", args.port), H).serve_forever()
""")


@pytest.fixture(scope="module")
def obsd_smoke(mesh8, tmp_path_factory):
    """30-step CPU train with a chaos slow step at 20, a 2-replica stub
    fleet taking load, and ONE obsd tailing both telemetry dirs with a
    step-time SLO sized so the 2 s stall (and nothing else) trips it.
    obsd is a pure reader: the producers never know it exists."""
    from moco_tpu.config import get_preset
    from moco_tpu.serve.fleet import FleetPolicy, FleetSupervisor
    from moco_tpu.train import train

    tmp_path = tmp_path_factory.mktemp("obsd_smoke")
    train_dir = tmp_path / "train_telemetry"
    fleet_dir = tmp_path / "fleet_telemetry"

    # --- the stub fleet under load -------------------------------------
    stub = tmp_path / "stub_replica.py"
    stub.write_text(_STUB_REPLICA)

    def child_argv(index, port, tdir, pretrained):
        return [sys.executable, str(stub), "--port", str(port),
                "--telemetry-dir", tdir]

    fleet = FleetSupervisor(
        child_argv, replicas=2, telemetry_dir=str(fleet_dir),
        policy=FleetPolicy(
            probe_secs=0.1, probe_timeout_s=1.0, startup_grace_secs=30.0,
            term_grace_secs=1.0, stats_every_secs=0.4,
        ),
        seed=0,
    )
    fleet.start()
    # load starts only against a healthy fleet: startup sheds would
    # (correctly!) fire the shed-rate SLO and muddy the exactly-one
    # step-time story this smoke pins
    deadline = time.monotonic() + 30.0
    while fleet.healthy_count() < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert fleet.healthy_count() == 2

    # --- one obsd over BOTH dirs ---------------------------------------
    rules = [
        SLORule({"name": "step_time", "objective": "step_time_ms_max",
                 "op": ">", "threshold": 1500.0, "min_step": 3,
                 "fast_window_s": 8.0, "slow_window_s": 60.0,
                 "clear_s": 1.0, "severity": "page"}),
        SLORule({"name": "shed_rate", "objective": "shed_rate",
                 "op": ">", "threshold": 0.05,
                 "fast_window_s": 8.0, "slow_window_s": 60.0}),
    ]
    agg = Aggregator([str(train_dir), str(fleet_dir)], rules=rules)
    server = ObsServer(agg)
    server.start()
    stop = threading.Event()
    collector = threading.Thread(
        target=agg.run, kwargs=dict(tick_secs=0.2, stop=stop), daemon=True)
    collector.start()

    # --- live probes: /metrics + /slo must answer DURING the run -------
    probes = {"metrics": [], "slo": [], "errors": []}

    def probe_loop():
        while not stop.is_set():
            try:
                _, _, metrics_body = _get(server.url + "/metrics")
                _, _, slo_body = _get(server.url + "/slo")
                probes["metrics"].append(metrics_body)
                probes["slo"].append(json.loads(slo_body))
            except Exception as e:  # noqa: BLE001 - recorded for assert
                probes["errors"].append(repr(e))
            stop.wait(0.5)

    prober = threading.Thread(target=probe_loop, daemon=True)
    prober.start()

    def load_loop():
        body = json.dumps({"pixels": [[[0, 0, 0]]]}).encode()
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    fleet.router.url + "/v1/embed", data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST")
                urllib.request.urlopen(req, timeout=5.0).read()
            except Exception:  # noqa: BLE001 - load gen best-effort
                pass
            stop.wait(0.05)

    loader = threading.Thread(target=load_loop, daemon=True)
    loader.start()

    # --- the 30-step chaos train (blocking) ----------------------------
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16,
        batch_size=16, num_negatives=64, embed_dim=32, lr=0.1, epochs=2,
        steps_per_epoch=15, ckpt_dir="", tb_dir="", print_freq=10,
        num_classes=10, knn_monitor=False,
        telemetry_dir=str(train_dir), telemetry_flush_steps=2,
        telemetry_stride=5, peak_flops_per_chip=1e12,
        chaos="slow_at_step=20,slow_ms=2000",
    )
    state, metrics = train(config, mesh8)

    # --- drain: keep ticking until the stall ages out and recovery
    # fires (fast window 8 s + clear 1 s; generous deadline, tight poll)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        snap = agg.slo_snapshot()
        st = next((r for r in snap["rules"] if r["name"] == "step_time"),
                  None)
        runs = (st or {}).get("runs", {})
        if runs and all(r["state"] == "ok" and r["recoveries"] >= 1
                        for r in runs.values()):
            break
        time.sleep(0.2)
    stop.set()
    collector.join(timeout=10.0)
    prober.join(timeout=10.0)
    loader.join(timeout=10.0)
    agg.poll_once()
    fleet.stop(timeout_s=10.0)
    server.shutdown()
    return dict(config=config, state=state, agg=agg, fleet=fleet,
                probes=probes, train_dir=str(train_dir),
                fleet_dir=str(fleet_dir))


def test_smoke_exactly_one_alert_then_recovery(obsd_smoke):
    assert int(obsd_smoke["state"].step) == 30
    # the engine's final word: one alert, one recovery, state ok
    snap = obsd_smoke["agg"].slo_snapshot()
    st = next(r for r in snap["rules"] if r["name"] == "step_time")
    (run_state,) = st["runs"].values()
    assert run_state["alerts"] == 1
    assert run_state["recoveries"] == 1
    assert run_state["state"] == "ok"
    # and the stream agrees: alert then recover, in order, kind:"slo"
    events = os.path.join(obsd_smoke["train_dir"], "events.jsonl")
    slo = [json.loads(line) for line in open(events) if '"slo"' in line]
    slo = [r for r in slo if r.get("kind") == "slo"]
    assert [r["action"] for r in slo] == ["alert", "recover"]
    assert all(r["rule"] == "step_time" for r in slo)
    assert slo[0]["value_fast"] >= 1500.0
    # under the PRODUCING run id (the train driver's)
    run_ids = {json.loads(line).get("run_id") for line in open(events)}
    assert {r["run_id"] for r in slo} <= run_ids
    # the fleet stream got NO step-time slo records (wrong run)
    fleet_events = os.path.join(obsd_smoke["fleet_dir"], "events.jsonl")
    assert not [line for line in open(fleet_events)
                if '"kind": "slo"' in line]


def test_smoke_endpoints_valid_during_run(obsd_smoke):
    probes = obsd_smoke["probes"]
    assert not probes["errors"], probes["errors"]
    assert len(probes["metrics"]) >= 3  # actually sampled during the run
    for body in probes["metrics"]:
        validate_prometheus(body)
    # the last mid-run scrapes carry both producers' series
    assert any("moco_tpu_router_requests_total" in body
               and "moco_tpu_steps_total" in body
               for body in probes["metrics"][-3:])
    for snap in probes["slo"]:
        assert {r["name"] for r in snap["rules"]} == {"step_time",
                                                      "shed_rate"}
    # the alert was OBSERVABLE live on /slo at some point
    assert any(
        any(run.get("state") == "alert"
            for run in next(r for r in snap["rules"]
                            if r["name"] == "step_time")["runs"].values())
        for snap in probes["slo"]
    )


def test_smoke_fleet_served_and_router_stats_flowed(obsd_smoke):
    agg = obsd_smoke["agg"]
    fleet = obsd_smoke["fleet"]
    stats = fleet.stats()
    assert stats["router"]["requests"] > 0
    assert stats["router"]["ok"] > 0
    # obsd folded the fleet's router_stats cadence records
    fleet_run = agg.windows.get(fleet.run_id)
    assert fleet_run is not None
    assert fleet_run.last_router is not None
    assert fleet_run.last_router["requests"] > 0
    assert "latency_ms" in fleet_run.last_router
    # and the shed-rate rule saw data without firing (healthy fleet)
    st = next(r for r in agg.slo_snapshot()["rules"]
              if r["name"] == "shed_rate")
    run_state = st["runs"].get(fleet.run_id)
    assert run_state is not None and run_state["alerts"] == 0


def test_smoke_report_renders_slo_section(obsd_smoke):
    events = os.path.join(obsd_smoke["train_dir"], "events.jsonl")
    proc = subprocess.run(
        [sys.executable, REPORT, events], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "slo: 1 alert(s), 1 recovery(ies) — all clear" in proc.stdout
    assert "step_time:" in proc.stdout
    as_json = subprocess.run(
        [sys.executable, REPORT, events, "--json"],
        capture_output=True, text=True)
    summary = json.loads(as_json.stdout)
    assert summary["slo"]["alerts"] == 1
    assert summary["slo"]["by_rule"]["step_time"]["severity"] == "page"


def test_smoke_obsd_is_a_pure_reader(obsd_smoke):
    """The overhead bound, structurally: obsd never writes producer
    files except the slo lines, and the producers' own record streams
    parse cleanly after a full run of concurrent tailing (no torn
    lines, no interleave corruption)."""
    from tools.telemetry_report import load_events

    for dirname in (obsd_smoke["train_dir"], obsd_smoke["fleet_dir"]):
        records, skipped = load_events(
            os.path.join(dirname, "events.jsonl"))
        assert skipped == 0
        assert records
    # every non-slo record in the train stream was written by the run's
    # own producers (driver pid): obsd added nothing but slo lines
    train_records, _ = load_events(
        os.path.join(obsd_smoke["train_dir"], "events.jsonl"))
    foreign = [r for r in train_records
               if r.get("kind") not in (
                   "run_start", "step", "event", "run_end", "pod", "slo")]
    assert foreign == []
