"""Detectron2 converter tests (SURVEY §2.6 transfer-export parity)."""

import pickle

import jax
import numpy as np
import optax
import pytest

from moco_tpu.checkpoint import export_encoder_q
from moco_tpu.export_detectron2 import convert, torchvision_flat_to_detectron2
from moco_tpu.models.resnet import ResNetTiny
from moco_tpu.train_state import create_train_state


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    model = ResNetTiny(num_classes=32, cifar_stem=True)
    state = create_train_state(
        jax.random.key(0), model, optax.sgd(0.1), (2, 16, 16, 3), 64, 32
    )
    path = str(tmp_path_factory.mktemp("exp") / "enc.safetensors")
    flat = export_encoder_q(state, path)
    return path, flat, state


def test_convert_writes_loadable_pickle(exported, tmp_path):
    path, flat, state = exported
    out = str(tmp_path / "d2.pkl")
    model = convert(path, out)
    with open(out, "rb") as f:
        obj = pickle.load(f)
    assert obj["matching_heuristics"] is True
    assert set(obj["model"]) == set(model)


def test_name_mapping(exported):
    path, flat, state = exported
    model = torchvision_flat_to_detectron2(flat)
    assert "stem.conv1.weight" in model
    assert "stem.conv1.norm.running_mean" in model
    # layer1 → res2, block 0
    assert "res2.0.conv1.weight" in model
    assert "res2.0.conv1.norm.weight" in model
    # layer2 has a downsample in ResNetTiny → shortcut names
    assert "res3.0.shortcut.weight" in model
    assert "res3.0.shortcut.norm.running_var" in model
    # no classifier head survives
    assert not any(k.startswith("fc") for k in model)
    # tensor values pass through untouched
    np.testing.assert_array_equal(
        model["stem.conv1.weight"], flat["module.encoder_q.conv1.weight"]
    )


def test_wrong_prefix_errors(exported):
    path, flat, state = exported
    with pytest.raises(ValueError, match="no nope"):
        torchvision_flat_to_detectron2(flat, prefix="nope")
