"""Pallas blur kernel: equivalence with the portable shifted-add blur
(interpret mode on CPU), weight semantics, per-sample independence."""

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.data.augment import augment_batch, v2_aug_config
from moco_tpu.ops.pallas_blur import blur_weights, gaussian_blur_batch


def test_identity_kernel_is_noop():
    imgs = jax.random.normal(jax.random.key(0), (2, 16, 16, 3))
    radius = 2
    identity = jnp.zeros((2, 2 * radius + 1)).at[:, radius].set(1.0)
    out = gaussian_blur_batch(imgs, identity, radius, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(imgs), atol=1e-6)


def test_blur_weights_semantics():
    radius = 3
    w_on = blur_weights(jax.random.key(1), radius, (0.5, 1.5), prob=1.0)
    w_off = blur_weights(jax.random.key(1), radius, (0.5, 1.5), prob=0.0)
    np.testing.assert_allclose(float(jnp.sum(w_on)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(w_on), np.asarray(w_on[::-1]), rtol=1e-5)
    assert float(w_on[radius]) < 1.0  # actually blurs
    np.testing.assert_allclose(
        np.asarray(w_off), np.eye(2 * radius + 1)[radius], atol=1e-7
    )


def test_per_sample_sigmas_differ():
    imgs = jnp.broadcast_to(
        jax.random.normal(jax.random.key(2), (1, 16, 16, 3)), (3, 16, 16, 3)
    )
    radius = 2
    keys = jax.random.split(jax.random.key(3), 3)
    weights = jax.vmap(lambda k: blur_weights(k, radius, (0.1, 2.0), 1.0))(keys)
    out = np.asarray(gaussian_blur_batch(imgs, weights, radius, interpret=True))
    assert not np.allclose(out[0], out[1])


def test_pallas_pipeline_matches_portable_blur():
    """Full v2 augmentation with pallas_blur='on' (interpret) must match the
    portable shifted-add path bit-for-tolerance: same PRNG stream, and the
    blur commutes with flip/normalize as documented."""
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randint(0, 256, (4, 40, 40, 3), dtype=np.uint8))
    key = jax.random.key(4)
    cfg_off = v2_aug_config(out_size=32)._replace(pallas_blur="off")
    cfg_on = v2_aug_config(out_size=32)._replace(pallas_blur="on")
    a = np.asarray(augment_batch(imgs, key, cfg_off))
    b = np.asarray(augment_batch(imgs, key, cfg_on))
    np.testing.assert_allclose(
        a, b, atol=2e-4, err_msg=f"max abs diff {np.abs(a - b).max()}"
    )


def test_sharded_two_crops_matches_unsharded(mesh8):
    """build_two_crops_sharded derives per-sample keys from GLOBAL indices,
    so its output must equal plain two_crops on the same global batch (the
    multichip path loses no semantics — and the Pallas blur stays local)."""
    from moco_tpu.data.augment import build_two_crops_sharded, two_crops

    rng = np.random.RandomState(1)
    imgs = jnp.asarray(rng.randint(0, 256, (16, 24, 24, 3), dtype=np.uint8))
    key = jax.random.key(5)
    cfg = v2_aug_config(out_size=16)._replace(pallas_blur="on")
    q_ref, k_ref = two_crops(imgs, key, cfg)
    fn = build_two_crops_sharded(cfg, mesh8)
    q_sh, k_sh = fn(imgs, key)
    np.testing.assert_allclose(np.asarray(q_sh), np.asarray(q_ref), atol=2e-4)
    np.testing.assert_allclose(np.asarray(k_sh), np.asarray(k_ref), atol=2e-4)
