"""CIFAR-10 pickle-layout reader test against generated batch files."""

import os
import pickle

import numpy as np
import pytest

from moco_tpu.data.datasets import CIFAR10


@pytest.fixture(scope="module")
def cifar_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("cifar")
    d = root / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.RandomState(0)
    for name, n in [(f"data_batch_{i}", 20) for i in range(1, 6)] + [("test_batch", 10)]:
        data = rng.randint(0, 256, (n, 3072), dtype=np.uint8)
        labels = rng.randint(0, 10, n).tolist()
        with open(d / name, "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
    return str(root)


def test_train_split_concatenates_batches(cifar_dir):
    ds = CIFAR10(cifar_dir, train=True)
    assert len(ds) == 100
    imgs, labels, extents = ds.get_batch(np.arange(8))
    np.testing.assert_array_equal(extents, np.tile([32, 32, 0], (8, 1)))
    assert imgs.shape == (8, 32, 32, 3) and imgs.dtype == np.uint8
    assert labels.shape == (8,)
    assert ds.num_classes == 10


def test_chw_to_hwc_layout(cifar_dir):
    """CIFAR stores rows as [3072] = [3, 32, 32] planar; reader must emit HWC."""
    ds = CIFAR10(cifar_dir, train=True)
    with open(os.path.join(cifar_dir, "cifar-10-batches-py", "data_batch_1"), "rb") as f:
        raw = pickle.load(f, encoding="bytes")[b"data"][0].reshape(3, 32, 32)
    np.testing.assert_array_equal(ds.images[0], raw.transpose(1, 2, 0))


def test_test_split(cifar_dir):
    ds = CIFAR10(cifar_dir, train=False)
    assert len(ds) == 10


def test_missing_batch_errors(tmp_path):
    with pytest.raises(FileNotFoundError, match="cifar-10-batches-py"):
        CIFAR10(str(tmp_path))
