"""Data layer tests: on-device augmentation, datasets, loader."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.data import (
    SyntheticDataset,
    augment_batch,
    epoch_loader,
    epoch_permutation,
    eval_aug_config,
    host_shard,
    two_crops,
    v1_aug_config,
    v2_aug_config,
)
from moco_tpu.data.augment import _hsv_to_rgb, _rgb_to_hsv


@pytest.fixture(scope="module")
def batch_u8():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randint(0, 256, (4, 32, 32, 3), dtype=np.uint8))


def test_augment_shapes_and_dtype(batch_u8):
    cfg = v1_aug_config(out_size=16)
    out = augment_batch(batch_u8, jax.random.key(0), cfg)
    assert out.shape == (4, 16, 16, 3)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()


def test_two_crops_independent(batch_u8):
    cfg = v2_aug_config(out_size=16)
    q, k = two_crops(batch_u8, jax.random.key(1), cfg)
    assert q.shape == k.shape == (4, 16, 16, 3)
    assert not np.allclose(np.asarray(q), np.asarray(k))


def test_augment_deterministic_per_key(batch_u8):
    cfg = v2_aug_config(out_size=16)
    a = augment_batch(batch_u8, jax.random.key(2), cfg)
    b = augment_batch(batch_u8, jax.random.key(2), cfg)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = augment_batch(batch_u8, jax.random.key(3), cfg)
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_per_sample_randomness(batch_u8):
    """Identical images in a batch must receive DIFFERENT crops."""
    same = jnp.broadcast_to(batch_u8[:1], batch_u8.shape)
    cfg = v1_aug_config(out_size=16)
    out = np.asarray(augment_batch(same, jax.random.key(4), cfg))
    assert not np.allclose(out[0], out[1])


def test_eval_aug_deterministic(batch_u8):
    cfg = eval_aug_config(out_size=16)
    a = augment_batch(batch_u8, jax.random.key(5), cfg)
    b = augment_batch(batch_u8, jax.random.key(6), cfg)  # different keys!
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_hsv_roundtrip():
    rgb = jnp.asarray(np.random.RandomState(1).rand(8, 8, 3).astype(np.float32))
    back = _hsv_to_rgb(_rgb_to_hsv(rgb))
    np.testing.assert_allclose(np.asarray(back), np.asarray(rgb), atol=1e-5)


def test_synthetic_dataset_clusterable():
    ds = SyntheticDataset(num_samples=64, image_size=16, num_classes=4, seed=1)
    imgs, labels = ds.get_batch(np.arange(64))
    assert imgs.shape == (64, 16, 16, 3) and imgs.dtype == np.uint8
    # same-class images more similar than cross-class on average
    f = imgs.reshape(64, -1).astype(np.float32)
    same, diff = [], []
    for i in range(0, 32):
        for j in range(i + 1, 32):
            d = np.linalg.norm(f[i] - f[j])
            (same if labels[i] == labels[j] else diff).append(d)
    assert np.mean(same) < np.mean(diff)


def test_epoch_permutation_drops_last():
    p = epoch_permutation(103, epoch=0, seed=0, global_batch=10)
    assert len(p) == 100
    assert len(set(p.tolist())) == 100
    p2 = epoch_permutation(103, epoch=1, seed=0, global_batch=10)
    assert not np.array_equal(p, p2)  # set_epoch reshuffles
    p3 = epoch_permutation(103, epoch=0, seed=0, global_batch=10)
    np.testing.assert_array_equal(p, p3)  # deterministic


def test_host_shard_single_process_identity():
    idx = np.arange(40)
    np.testing.assert_array_equal(host_shard(idx, 8), idx)


def test_epoch_loader_yields_sharded_batches(mesh8):
    ds = SyntheticDataset(num_samples=70, image_size=16, num_classes=3)
    loader = epoch_loader(ds, epoch=0, seed=0, global_batch=16, mesh=mesh8)
    batches = list(loader)
    assert len(batches) == len(loader) == 70 // 16
    imgs, labels = batches[0]
    assert imgs.shape == (16, 16, 16, 3)
    assert labels.shape == (16,)
    # sharded over the 8 devices, 2 rows each
    assert len(imgs.sharding.device_set) == 8


def test_prefetcher_propagates_dataset_error(mesh8):
    """A dataset error (corrupt/missing file) must raise in the consumer,
    not kill the staging thread and hang the q.get()."""

    class BadDataset:
        num_classes = 2

        def __len__(self):
            return 64

        def get_batch(self, indices):
            raise ValueError("corrupt file: synthetic test failure")

    loader = epoch_loader(BadDataset(), epoch=0, seed=0, global_batch=16, mesh=mesh8)
    try:
        with pytest.raises(ValueError, match="corrupt file"):
            list(loader)
    finally:
        loader.close()


def test_prefetcher_error_after_good_batches(mesh8):
    """Errors mid-epoch surface after the already-staged batches drain."""

    class FlakyDataset:
        num_classes = 2

        def __init__(self):
            self.calls = 0

        def __len__(self):
            return 64

        def get_batch(self, indices):
            self.calls += 1
            if self.calls > 2:
                raise OSError("decode failed")
            return (
                np.zeros((len(indices), 8, 8, 3), np.uint8),
                np.zeros((len(indices),), np.int32),
            )

    loader = epoch_loader(FlakyDataset(), epoch=0, seed=0, global_batch=16, mesh=mesh8)
    try:
        seen = 0
        with pytest.raises(OSError, match="decode failed"):
            for _batch in loader:
                seen += 1
        assert seen == 2
    finally:
        loader.close()


def test_solarize_semantics():
    from moco_tpu.data.augment import AugConfig, _random_solarize
    import jax as _jax

    img = jnp.asarray([[[0.2, 0.6, 0.9]]])
    cfg_on = AugConfig(solarize_prob=1.0)
    out = np.asarray(_random_solarize(img, _jax.random.key(0), cfg_on))
    np.testing.assert_allclose(out[0, 0], [0.2, 0.4, 0.1], atol=1e-6)
    cfg_off = AugConfig(solarize_prob=0.0)
    out2 = np.asarray(_random_solarize(img, _jax.random.key(0), cfg_off))
    np.testing.assert_allclose(out2[0, 0], [0.2, 0.6, 0.9], atol=1e-6)


def test_v3_asymmetric_two_crops(mesh8):
    """v3's view pair uses different configs (blur p=1.0 vs p=0.1+solarize);
    the sharded builder must accept the pair and produce valid crops."""
    from moco_tpu.data.augment import build_two_crops_sharded, v3_aug_configs

    rng = np.random.RandomState(3)
    imgs = jnp.asarray(rng.randint(0, 256, (16, 24, 24, 3), dtype=np.uint8))
    cfg1, cfg2 = v3_aug_configs(out_size=16)
    assert cfg1.blur_prob == 1.0 and cfg2.blur_prob == 0.1
    assert cfg1.solarize_prob == 0.0 and cfg2.solarize_prob == 0.2
    fn = build_two_crops_sharded((cfg1, cfg2), mesh8)
    q, k = fn(imgs, jax.random.key(0))
    assert q.shape == k.shape == (16, 16, 16, 3)
    assert np.isfinite(np.asarray(q)).all() and np.isfinite(np.asarray(k)).all()
    assert not np.allclose(np.asarray(q), np.asarray(k))
