"""Data layer tests: on-device augmentation, datasets, loader."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.data import (
    SyntheticDataset,
    augment_batch,
    epoch_loader,
    epoch_permutation,
    eval_aug_config,
    host_shard,
    two_crops,
    v1_aug_config,
    v2_aug_config,
)
from moco_tpu.data.augment import _hsv_to_rgb, _rgb_to_hsv


@pytest.fixture(scope="module")
def batch_u8():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randint(0, 256, (4, 32, 32, 3), dtype=np.uint8))


def test_augment_shapes_and_dtype(batch_u8):
    cfg = v1_aug_config(out_size=16)
    out = augment_batch(batch_u8, jax.random.key(0), cfg)
    assert out.shape == (4, 16, 16, 3)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()


def test_two_crops_independent(batch_u8):
    cfg = v2_aug_config(out_size=16)
    q, k = two_crops(batch_u8, jax.random.key(1), cfg)
    assert q.shape == k.shape == (4, 16, 16, 3)
    assert not np.allclose(np.asarray(q), np.asarray(k))


def test_augment_deterministic_per_key(batch_u8):
    cfg = v2_aug_config(out_size=16)
    a = augment_batch(batch_u8, jax.random.key(2), cfg)
    b = augment_batch(batch_u8, jax.random.key(2), cfg)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = augment_batch(batch_u8, jax.random.key(3), cfg)
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_per_sample_randomness(batch_u8):
    """Identical images in a batch must receive DIFFERENT crops."""
    same = jnp.broadcast_to(batch_u8[:1], batch_u8.shape)
    cfg = v1_aug_config(out_size=16)
    out = np.asarray(augment_batch(same, jax.random.key(4), cfg))
    assert not np.allclose(out[0], out[1])


def test_eval_aug_deterministic(batch_u8):
    cfg = eval_aug_config(out_size=16)
    a = augment_batch(batch_u8, jax.random.key(5), cfg)
    b = augment_batch(batch_u8, jax.random.key(6), cfg)  # different keys!
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_hsv_roundtrip():
    rgb = jnp.asarray(np.random.RandomState(1).rand(8, 8, 3).astype(np.float32))
    back = _hsv_to_rgb(_rgb_to_hsv(rgb))
    np.testing.assert_allclose(np.asarray(back), np.asarray(rgb), atol=1e-5)


def test_synthetic_dataset_clusterable():
    ds = SyntheticDataset(num_samples=64, image_size=16, num_classes=4, seed=1)
    imgs, labels, _extents = ds.get_batch(np.arange(64))
    assert imgs.shape == (64, 16, 16, 3) and imgs.dtype == np.uint8
    # same-class images more similar than cross-class on average
    f = imgs.reshape(64, -1).astype(np.float32)
    same, diff = [], []
    for i in range(0, 32):
        for j in range(i + 1, 32):
            d = np.linalg.norm(f[i] - f[j])
            (same if labels[i] == labels[j] else diff).append(d)
    assert np.mean(same) < np.mean(diff)


def test_synthetic_texture_dataset_pixel_hard():
    """The horizon dataset's defining property (VERDICT r3 weak #3): class
    identity must NOT be recoverable from raw pixel distance — the color
    cast dominates — while the channel-mean-removed residual (what an
    aug-invariant encoder can isolate) IS class-informative."""
    from moco_tpu.data.datasets import SyntheticTextureDataset

    ds = SyntheticTextureDataset(num_samples=256, image_size=16, num_classes=4,
                                 seed=1, cast_strength=1.0)
    imgs, labels, extents = ds.get_batch(np.arange(256))
    assert imgs.shape == (256, 16, 16, 3) and imgs.dtype == np.uint8
    assert extents.shape == (256, 3)
    f = imgs.reshape(256, -1).astype(np.float32)

    def knn1_acc(feats):
        d = ((feats[:, None] - feats[None]) ** 2).sum(-1)
        np.fill_diagonal(d, np.inf)
        return float(np.mean(labels[d.argmin(1)] == labels))

    # raw pixels: near chance (0.25). cast-normalized (per-sample,
    # per-channel standardize — a crude stand-in for learned cast
    # invariance): well above chance
    raw = knn1_acc(f)
    x = imgs.astype(np.float32)
    x = (x - x.mean(axis=(1, 2), keepdims=True)) / (
        x.std(axis=(1, 2), keepdims=True) + 1e-6)
    normed = knn1_acc(x.reshape(256, -1))
    assert raw < 0.45, f"raw-pixel kNN should hover near chance, got {raw}"
    assert normed > raw + 0.2, (raw, normed)
    # determinism + split convention: same fixed class tiles across seeds
    ds2 = SyntheticTextureDataset(num_samples=256, image_size=16,
                                  num_classes=4, seed=1, cast_strength=1.0)
    np.testing.assert_array_equal(ds.images, ds2.images)

    # the default (cast 0.5, horizon scale 32px/16-class): raw-pixel 1-NN
    # measures ~0.28 — class-informative but nowhere near separable (the
    # predecessor dataset measured ~1.0). The operative honesty metric is
    # the random-FEATURE baseline the horizon PRINTS as its Epoch[-1] row
    # (measured 8.3%, chance 6.25% — datasets.py docstring); this bound
    # just pins the pixel statistics from regressing toward separable
    dsd = SyntheticTextureDataset(num_samples=512, image_size=32,
                                  num_classes=16, seed=2)
    imgs_d, labels_d, _ = dsd.get_batch(np.arange(512))
    fd = imgs_d.reshape(512, -1).astype(np.float32)
    d = ((fd[:, None] - fd[None]) ** 2).sum(-1)
    np.fill_diagonal(d, np.inf)
    raw_default = float(np.mean(labels_d[d.argmin(1)] == labels_d))
    assert raw_default < 0.35, f"default-config raw kNN {raw_default}"


def test_epoch_permutation_drops_last():
    p = epoch_permutation(103, epoch=0, seed=0, global_batch=10)
    assert len(p) == 100
    assert len(set(p.tolist())) == 100
    p2 = epoch_permutation(103, epoch=1, seed=0, global_batch=10)
    assert not np.array_equal(p, p2)  # set_epoch reshuffles
    p3 = epoch_permutation(103, epoch=0, seed=0, global_batch=10)
    np.testing.assert_array_equal(p, p3)  # deterministic


def test_host_shard_single_process_identity():
    idx = np.arange(40)
    np.testing.assert_array_equal(host_shard(idx, 8), idx)


def test_epoch_loader_yields_sharded_batches(mesh8):
    ds = SyntheticDataset(num_samples=70, image_size=16, num_classes=3)
    loader = epoch_loader(ds, epoch=0, seed=0, global_batch=16, mesh=mesh8)
    batches = list(loader)
    assert len(batches) == len(loader) == 70 // 16
    imgs, labels, extents = batches[0]
    assert imgs.shape == (16, 16, 16, 3)
    assert labels.shape == (16,)
    assert extents.shape == (16, 3)
    # sharded over the 8 devices, 2 rows each
    assert len(imgs.sharding.device_set) == 8


def test_v1_applies_grayscale_before_jitter():
    """v1 (`main_moco.py:≈L232-244`) orders RandomGrayscale BEFORE
    ColorJitter; v2 the reverse. With hue jitter the orders differ (hue does
    not preserve luma), so a wiring mistake shows up as equal outputs."""
    cfg_v1 = v1_aug_config(out_size=16)
    assert cfg_v1.grayscale_first
    assert not v2_aug_config(out_size=16).grayscale_first
    rng = np.random.RandomState(7)
    imgs = jnp.asarray(rng.randint(0, 256, (8, 24, 24, 3), dtype=np.uint8))
    force = cfg_v1._replace(grayscale_prob=1.0, flip_prob=0.0)
    out_gray_first = np.asarray(augment_batch(imgs, jax.random.key(0), force))
    out_jit_first = np.asarray(
        augment_batch(imgs, jax.random.key(0), force._replace(grayscale_first=False))
    )
    assert not np.allclose(out_gray_first, out_jit_first)
    # grayscale(p=1) output is gray regardless of order: un-normalize and
    # check channel equality
    from moco_tpu.data.augment import IMAGENET_MEAN, IMAGENET_STD

    raw = out_gray_first * IMAGENET_STD + IMAGENET_MEAN
    np.testing.assert_allclose(raw[..., 0], raw[..., 1], atol=1e-5)
    np.testing.assert_allclose(raw[..., 1], raw[..., 2], atol=1e-5)


def test_color_jitter_randomizes_op_order():
    """torchvision ColorJitter permutes its 4 sub-ops per call; pin that
    `_color_jitter` consumes a randperm(4) from its key and applies the ops
    in that order (replicate the internal key splits and compare against the
    exposed `_apply_jitter_ops`)."""
    from moco_tpu.data.augment import AugConfig, _apply_jitter_ops, _color_jitter

    cfg = AugConfig(
        brightness=0.4, contrast=0.4, saturation=0.8, hue=0.4, jitter_prob=1.0
    )
    img = jnp.asarray(np.random.RandomState(0).rand(12, 12, 3).astype(np.float32))
    perms = set()
    for seed in range(12):
        key = jax.random.key(seed)
        kb, kc, ks, kh, kp, kperm = jax.random.split(key, 6)

        def factor(k, x):
            return jax.random.uniform(k, (), minval=max(0.0, 1.0 - x), maxval=1.0 + x)

        factors = (factor(kb, 0.4), factor(kc, 0.4), factor(ks, 0.8))
        shift = jax.random.uniform(kh, (), minval=-0.4, maxval=0.4)
        perm = jax.random.permutation(kperm, 4)
        perms.add(tuple(np.asarray(perm).tolist()))
        expected = _apply_jitter_ops(img, factors, shift, perm, use_hue=True)
        got = _color_jitter(img, key, cfg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-6)
    assert len(perms) >= 3  # the order genuinely varies across keys


def test_fast_jitter_matches_switch_form():
    """The production jitter (`_apply_jitter_ops_fast`: single hue eval,
    unified cheap-op blend) must equal the reference switch-chain form for
    every one of the 24 permutations."""
    import itertools

    from moco_tpu.data.augment import _apply_jitter_ops, _apply_jitter_ops_fast

    base = np.random.RandomState(2).rand(10, 10, 3).astype(np.float32)
    factors = (jnp.float32(1.25), jnp.float32(0.8), jnp.float32(1.6))
    shift = jnp.float32(0.22)
    for dtype, atol in ((jnp.float32, 2e-6), (jnp.bfloat16, 2e-2)):
        img = jnp.asarray(base, dtype)
        for perm in itertools.permutations(range(4)):
            p = jnp.asarray(perm)
            for use_hue in (True, False):
                ref = _apply_jitter_ops(img, factors, shift, p, use_hue)
                fast = _apply_jitter_ops_fast(img, factors, shift, p, use_hue)
                assert fast.dtype == img.dtype
                diff = np.abs(
                    np.asarray(fast, np.float32) - np.asarray(ref, np.float32)
                )
                if dtype == jnp.float32:
                    assert diff.max() <= atol, (perm, use_hue, diff.max())
                else:
                    # bf16: hue is discontinuous at sector boundaries, so a
                    # rare quantized pixel may land in a different sector —
                    # demand near-total agreement, not sup-norm equality
                    assert (diff > atol).mean() < 0.01, (perm, use_hue, diff.max())


def test_jitter_op_order_matters():
    from moco_tpu.data.augment import _apply_jitter_ops

    img = jnp.asarray(np.random.RandomState(1).rand(8, 8, 3).astype(np.float32))
    factors = (jnp.float32(1.3), jnp.float32(0.7), jnp.float32(1.8))
    shift = jnp.float32(0.3)
    a = _apply_jitter_ops(img, factors, shift, jnp.asarray([0, 1, 2, 3]), True)
    b = _apply_jitter_ops(img, factors, shift, jnp.asarray([3, 2, 1, 0]), True)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_rrc_params_torchvision_semantics():
    """10-trial rejection sampling: crops stay in bounds, realized aspect
    stays in [3/4, 4/3] (single-draw clipping violated this on elongated
    images), and the fallback is the centered aspect-clamped crop."""
    from moco_tpu.data.augment import AugConfig, _rrc_params

    cfg = AugConfig(min_scale=0.2, max_scale=1.0)
    # elongated valid region: most draws reject, fallback must clamp ratio
    h, w = 40.0, 160.0
    ratios, fallbacks = [], 0
    for seed in range(200):
        y0, x0, ch, cw = map(
            float, _rrc_params(jax.random.key(seed), h, w, cfg)
        )
        assert y0 >= -1e-4 and x0 >= -1e-4
        assert y0 + ch <= h + 1e-3 and x0 + cw <= w + 1e-3
        r = cw / ch
        assert 0.75 - 1e-3 <= r <= 4.0 / 3.0 + 1e-3, r
        ratios.append(r)
        if abs(r - 4.0 / 3.0) < 1e-5 and abs(ch - h) < 1e-4:
            fallbacks += 1
    assert fallbacks > 0  # the elongated region exercises the fallback
    # square region with scale (0.2, 1): trials almost always accept
    accepted_ratios = [
        float(_rrc_params(jax.random.key(s), 64.0, 64.0, cfg)[3])
        / float(_rrc_params(jax.random.key(s), 64.0, 64.0, cfg)[2])
        for s in range(50)
    ]
    assert np.std(accepted_ratios) > 0.01  # ratio genuinely varies


def test_rrc_deterministic_center_crop_frac():
    from moco_tpu.data.augment import AugConfig, _rrc_params

    cfg = AugConfig(deterministic=True, crop_frac=0.875)
    y0, x0, ch, cw = map(float, _rrc_params(jax.random.key(0), 200.0, 300.0, cfg))
    assert ch == cw == pytest.approx(0.875 * 200.0)
    assert y0 == pytest.approx((200.0 - ch) / 2)
    assert x0 == pytest.approx((300.0 - cw) / 2)
    full = AugConfig(deterministic=True, crop_frac=1.0)
    y0, x0, ch, cw = map(float, _rrc_params(jax.random.key(0), 32.0, 32.0, full))
    assert ch == cw == pytest.approx(32.0) and y0 == x0 == pytest.approx(0.0)


def test_extent_rotated_center_crop_roundtrip():
    """A portrait image staged TRANSPOSED (rot=1) must come back in original
    orientation: deterministic full-extent crop of the staged canvas equals
    resizing the original directly."""
    from moco_tpu.data.augment import eval_aug_config
    from moco_tpu.ops.matmul_resize import crop_resize

    rng = np.random.RandomState(3)
    orig = rng.randint(0, 256, (48, 20, 3)).astype(np.uint8)  # portrait
    staged = np.swapaxes(orig, 0, 1)  # [20, 48, 3] landscape
    canvas = np.zeros((24, 64, 3), np.uint8)
    canvas[:20, :48] = staged
    canvas[:20, 48:] = staged[:, -1:]
    canvas[20:, :] = canvas[19:20, :]
    cfg = eval_aug_config(out_size=16, crop_frac=1.0)
    extents = np.asarray([[20, 48, 1]], np.int32)
    out = augment_batch(canvas[None], jax.random.key(0), cfg, jnp.asarray(extents))
    from moco_tpu.data.augment import IMAGENET_MEAN, IMAGENET_STD

    got = np.asarray(out[0]) * IMAGENET_STD + IMAGENET_MEAN
    # expected: center crop (full min side = 20 wide) of the STAGED image,
    # resampled then transposed back
    expected = crop_resize(
        jnp.asarray(staged, jnp.float32) / 255.0, 0.0, (48 - 20) / 2.0, 20.0, 20.0, 16
    )
    expected = np.swapaxes(np.asarray(expected), 0, 1)
    np.testing.assert_allclose(got, expected, atol=1e-4)


def test_augment_extent_equals_tight_image():
    """Augmenting an edge-replicated canvas restricted to `extent` must equal
    augmenting the tightly-sized content image directly: crops never read the
    padding (boundary filter taps land on replicated pixels, which is exactly
    the clamp semantics a tight image gives)."""
    rng = np.random.RandomState(5)
    content = rng.randint(0, 256, (4, 16, 24, 3)).astype(np.uint8)
    canvas = np.zeros((4, 32, 64, 3), np.uint8)
    canvas[:, :16, :24] = content
    canvas[:, :16, 24:] = content[:, :, -1:]
    canvas[:, 16:, :] = canvas[:, 15:16, :]
    extents = jnp.asarray(np.tile([16, 24, 0], (4, 1)), np.int32)
    cfg = v2_aug_config(out_size=16)._replace(blur_prob=0.0)
    for seed in range(5):
        key = jax.random.key(seed)
        from_canvas = np.asarray(augment_batch(jnp.asarray(canvas), key, cfg, extents))
        from_tight = np.asarray(augment_batch(jnp.asarray(content), key, cfg))
        np.testing.assert_allclose(from_canvas, from_tight, atol=1e-5)


def test_flip_folded_into_crop_matrix():
    """The horizontal flip lives in the resample matrix: with flip forced on,
    the output is exactly the W-reverse of the flip-off output (same key →
    same crop box; every later op is pixelwise or a symmetric blur)."""
    rng = np.random.RandomState(9)
    imgs = jnp.asarray(rng.randint(0, 256, (4, 28, 28, 3), dtype=np.uint8))
    base = v1_aug_config(out_size=16)._replace(
        jitter_prob=0.0, grayscale_prob=0.0
    )
    on = np.asarray(augment_batch(imgs, jax.random.key(3), base._replace(flip_prob=1.0)))
    off = np.asarray(augment_batch(imgs, jax.random.key(3), base._replace(flip_prob=0.0)))
    np.testing.assert_allclose(on, off[:, :, ::-1], atol=1e-5)


def test_flip_folded_respects_rotation():
    """For rot-staged (transposed) samples the fold must reverse the staged
    H axis so the FINAL image is still flipped along W."""
    rng = np.random.RandomState(10)
    canvas = rng.randint(0, 256, (2, 16, 32, 3)).astype(np.uint8)
    extents = jnp.asarray([[16, 20, 1], [16, 20, 1]], np.int32)
    base = v1_aug_config(out_size=12)._replace(jitter_prob=0.0, grayscale_prob=0.0)
    on = np.asarray(
        augment_batch(jnp.asarray(canvas), jax.random.key(4), base._replace(flip_prob=1.0), extents)
    )
    off = np.asarray(
        augment_batch(jnp.asarray(canvas), jax.random.key(4), base._replace(flip_prob=0.0), extents)
    )
    np.testing.assert_allclose(on, off[:, :, ::-1], atol=1e-5)


def test_bfloat16_pipeline_close_to_float32():
    """dtype='bfloat16' (the TPU fast path) must match the f32 pipeline
    within quantization tolerance (~2^-8 on [0,1] pixels, ~3/255 after the
    1/std≈4.4 normalize scaling)."""
    rng = np.random.RandomState(11)
    imgs = jnp.asarray(rng.randint(0, 256, (4, 32, 32, 3), dtype=np.uint8))
    cfg32 = v2_aug_config(out_size=16)
    cfg16 = cfg32._replace(dtype="bfloat16")
    a = np.asarray(augment_batch(imgs, jax.random.key(5), cfg32))
    b = np.asarray(augment_batch(imgs, jax.random.key(5), cfg16)).astype(np.float32)
    assert b.dtype == np.float32 and np.isfinite(b).all()
    assert np.abs(a - b).mean() < 0.02
    assert np.abs(a - b).max() < 0.2


def test_prefetcher_propagates_dataset_error(mesh8):
    """A dataset error (corrupt/missing file) must raise in the consumer,
    not kill the staging thread and hang the q.get()."""

    class BadDataset:
        num_classes = 2

        def __len__(self):
            return 64

        def get_batch(self, indices):
            raise ValueError("corrupt file: synthetic test failure")

    loader = epoch_loader(BadDataset(), epoch=0, seed=0, global_batch=16, mesh=mesh8)
    try:
        with pytest.raises(ValueError, match="corrupt file"):
            list(loader)
    finally:
        loader.close()


def test_prefetcher_error_after_good_batches(mesh8):
    """Errors mid-epoch surface after the already-staged batches drain."""

    class FlakyDataset:
        num_classes = 2

        def __init__(self):
            self.calls = 0

        def __len__(self):
            return 64

        def get_batch(self, indices):
            self.calls += 1
            if self.calls > 2:
                raise OSError("decode failed")
            return (
                np.zeros((len(indices), 8, 8, 3), np.uint8),
                np.zeros((len(indices),), np.int32),
            )

    loader = epoch_loader(FlakyDataset(), epoch=0, seed=0, global_batch=16, mesh=mesh8)
    try:
        seen = 0
        with pytest.raises(OSError, match="decode failed"):
            for _batch in loader:
                seen += 1
        assert seen == 2
    finally:
        loader.close()


def test_solarize_semantics():
    from moco_tpu.data.augment import AugConfig, _random_solarize
    import jax as _jax

    img = jnp.asarray([[[0.2, 0.6, 0.9]]])
    cfg_on = AugConfig(solarize_prob=1.0)
    out = np.asarray(_random_solarize(img, _jax.random.key(0), cfg_on))
    np.testing.assert_allclose(out[0, 0], [0.2, 0.4, 0.1], atol=1e-6)
    cfg_off = AugConfig(solarize_prob=0.0)
    out2 = np.asarray(_random_solarize(img, _jax.random.key(0), cfg_off))
    np.testing.assert_allclose(out2[0, 0], [0.2, 0.6, 0.9], atol=1e-6)


def test_v3_asymmetric_two_crops(mesh8):
    """v3's view pair uses different configs (blur p=1.0 vs p=0.1+solarize);
    the sharded builder must accept the pair and produce valid crops."""
    from moco_tpu.data.augment import build_two_crops_sharded, v3_aug_configs

    rng = np.random.RandomState(3)
    imgs = jnp.asarray(rng.randint(0, 256, (16, 24, 24, 3), dtype=np.uint8))
    cfg1, cfg2 = v3_aug_configs(out_size=16)
    assert cfg1.blur_prob == 1.0 and cfg2.blur_prob == 0.1
    assert cfg1.solarize_prob == 0.0 and cfg2.solarize_prob == 0.2
    fn = build_two_crops_sharded((cfg1, cfg2), mesh8)
    q, k = fn(imgs, jax.random.key(0))
    assert q.shape == k.shape == (16, 16, 16, 3)
    assert np.isfinite(np.asarray(q)).all() and np.isfinite(np.asarray(k)).all()
    assert not np.allclose(np.asarray(q), np.asarray(k))


def test_aug_config_for_matches_variant():
    """The shared variant->recipe selector (train driver AND benchkit —
    review, r5): v1 presets must get the v1 recipe (grayscale-first, no
    blur), not a silently-substituted v2 stack; v3 gets the asymmetric
    pair with crop_min plumbed."""
    from moco_tpu.config import get_preset
    from moco_tpu.data.augment import aug_config_for

    v1 = aug_config_for(get_preset("imagenet-moco-v1"))
    assert v1.grayscale_first and v1.blur_prob == 0.0

    v2 = aug_config_for(get_preset("imagenet-moco-v2"))
    assert not v2.grayscale_first and v2.blur_prob == 0.5

    pair = aug_config_for(get_preset("imagenet-moco-v3-vits"))
    assert isinstance(pair, tuple) and len(pair) == 2
    a, b = pair
    assert a.blur_prob == 1.0 and b.solarize_prob == 0.2
    # crop_min plumbing, both directions: the vits preset leaves crop_min
    # at 0 ("variant default") which must resolve to the ViT 0.08 — NOT
    # propagate the raw 0.0 (degenerate zero-area crops); an explicit
    # override must win
    assert a.min_scale == 0.08
    a20, _ = aug_config_for(
        get_preset("imagenet-moco-v3-vits").replace(crop_min=0.2))
    assert a20.min_scale == 0.2
