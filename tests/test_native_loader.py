"""Native C++ staging loader: build, decode correctness vs PIL, failure
handling, and ImageFolder integration."""

import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from moco_tpu.data.datasets import ImageFolder  # noqa: E402
from moco_tpu.data.native_loader import NativeStagingLoader  # noqa: E402


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    """Tiny ImageFolder tree of JPEGs with deterministic gradient content."""
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir()
        for i in range(3):
            h, w = rng.randint(40, 90), rng.randint(40, 90)
            yy, xx = np.mgrid[0:h, 0:w]
            img = np.stack(
                [255 * yy / h, 255 * xx / w, np.full((h, w), (i * 40) % 255)], -1
            ).astype(np.uint8)
            Image.fromarray(img).save(str(d / f"{i}.jpg"), quality=95)
    return str(root)


@pytest.fixture(scope="module")
def native(jpeg_tree):
    try:
        return NativeStagingLoader(stage_h=32, stage_w=64, num_threads=2)
    except RuntimeError as e:
        pytest.skip(f"native loader unavailable: {e}")


def test_native_decode_matches_pil(jpeg_tree, native):
    folder = ImageFolder(jpeg_tree, stage_size=32, backend="pil")
    paths = [e.path for e in folder.entries]
    out, extents, failures = native.load_batch(paths)
    assert failures == 0
    assert out.shape == (len(paths), 32, 64, 3)
    pil_imgs, _, pil_extents = folder.get_batch(np.arange(len(paths)))
    # staged geometry must agree EXACTLY (same fit math, same rounding)
    np.testing.assert_array_equal(extents, pil_extents)
    # different bilinear implementations: require close agreement, not equality
    diff = np.abs(out.astype(np.int32) - pil_imgs.astype(np.int32))
    assert diff.mean() < 12.0, f"native vs PIL mean abs diff {diff.mean():.1f}"


def test_native_stages_whole_image_with_extent(jpeg_tree, native):
    """The canvas holds the WHOLE image top-left (portrait staged transposed)
    with edge-replicated padding — not a center crop."""
    folder = ImageFolder(jpeg_tree, stage_size=32, backend="pil")
    paths = [e.path for e in folder.entries]
    out, extents, failures = native.load_batch(paths)
    assert failures == 0
    from PIL import Image

    for i, p in enumerate(paths):
        w, h = Image.open(p).size
        nh, nw, rot = extents[i]
        assert rot == (1 if h > w else 0)
        src_h, src_w = (w, h) if rot else (h, w)  # staged orientation
        assert nh == min(32, max(1, int(src_h * min(32 / src_h, 64 / src_w) + 0.5)))
        assert 1 <= nw <= 64 and 1 <= nh <= 32
        # edge replication: padding column equals the last content column
        if nw < 64:
            np.testing.assert_array_equal(out[i, :nh, nw], out[i, :nh, nw - 1])
        if nh < 32:
            np.testing.assert_array_equal(out[i, nh], out[i, nh - 1])


def test_native_handles_corrupt_file(tmp_path, native):
    bad = tmp_path / "bad.jpg"
    bad.write_bytes(b"not a jpeg at all")
    out, extents, failures = native.load_batch([str(bad)])
    assert failures == 1
    np.testing.assert_array_equal(out[0], 0)
    np.testing.assert_array_equal(extents[0], [32, 64, 0])


def test_imagefolder_uses_native_backend(jpeg_tree):
    folder = ImageFolder(jpeg_tree, stage_size=32, backend="auto")
    imgs, labels, extents = folder.get_batch(np.arange(4))
    assert imgs.shape == (4, 32, 64, 3)
    assert extents.shape == (4, 3)
    assert folder.num_classes == 2
    if folder._native is None:
        pytest.skip("native backend not built in this environment")


def test_imagefolder_pil_fallback_matches_shapes(jpeg_tree):
    a = ImageFolder(jpeg_tree, stage_size=32, backend="pil")
    imgs, labels, extents = a.get_batch(np.arange(6))
    assert imgs.shape == (6, 32, 64, 3)
    assert extents.shape == (6, 3)
    assert sorted(set(labels.tolist())) == [0, 1]
