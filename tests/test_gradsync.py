"""Communication-efficient gradient sync (ISSUE 6, parallel/gradsync.py).

Parity gates on the tiny CPU proxy, over the 8-fake-device mesh (the
single-process stand-in for pod math — the 2-proc multihost harness is dead
at seed in this container):

- `bucketed` is BITWISE-pinned against the fused exact-DP reduce (same adds
  in the same element order; only the issue schedule differs);
- `quantized` and `demo` pass bounded loss-divergence gates over N steps —
  compressed DP is approximate by design, so the gate is a band, not
  equality;
- the per-leaf dtype policy handles integer and None leaves (the
  `_pmean_grads` regression the ISSUE calls out);
- the per-device accumulators checkpoint and resume exactly, and a
  dialect-1 checkpoint (no gradsync leaves) restores with fresh zeros.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from moco_tpu.config import PretrainConfig
from moco_tpu.parallel.gradsync import GradSync, leaf_wire_dtype
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step
from moco_tpu.utils.compat import shard_map

B, IMG, DIM, K = 16, 16, 16, 64


def _config(**kw):
    base = dict(
        variant="v1", arch="resnet_tiny", cifar_stem=True, num_negatives=K,
        embed_dim=DIM, batch_size=B, epochs=2, lr=0.1,
    )
    base.update(kw)
    return PretrainConfig(**base)


def _build(mesh, config):
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_train_state(
        jax.random.key(0), model, tx, (B // mesh.size, IMG, IMG, 3), K, DIM
    )
    state = GradSync(config, mesh.size).attach(state, mesh)
    step = build_train_step(config, model, tx, mesh, 8, sched)
    return state, step


def _run(mesh, config, steps=1):
    state, step = _build(mesh, config)
    losses = []
    for i in range(steps):
        im_q = jax.random.normal(jax.random.key(100 + i), (B, IMG, IMG, 3))
        im_k = jax.random.normal(jax.random.key(200 + i), (B, IMG, IMG, 3))
        state, metrics = step(state, im_q, im_k)
        losses.append(float(metrics["loss"]))
    return state, losses, metrics


# ---------------------------------------------------------------------------
# bucketed: bitwise parity with exact DP
# ---------------------------------------------------------------------------


def test_bucketed_bitwise_parity_with_fused(mesh8):
    sf, lf, mf = _run(mesh8, _config(grad_sync="fused"), steps=2)
    sb, lb, mb = _run(
        mesh8, _config(grad_sync="bucketed", grad_sync_bucket_mb=0.05), steps=2
    )
    assert lf == lb
    for a, b in zip(jax.tree.leaves(sf.params_q), jax.tree.leaves(sb.params_q),
                    strict=True):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(sf.queue), np.asarray(sb.queue))


def test_bucketed_bf16_matches_fused_bf16(mesh8):
    """The legacy grad_allreduce_dtype policy rides through both dense
    modes identically (wire casts happen per leaf, before concatenation)."""
    sf, lf, _ = _run(
        mesh8, _config(grad_sync="fused", grad_allreduce_dtype="bfloat16"),
        steps=2,
    )
    sb, lb, _ = _run(
        mesh8,
        _config(grad_sync="bucketed", grad_allreduce_dtype="bfloat16",
                grad_sync_bucket_mb=0.05),
        steps=2,
    )
    assert lf == lb
    for a, b in zip(jax.tree.leaves(sf.params_q), jax.tree.leaves(sb.params_q),
                    strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_bucket_plan_respects_budget_and_covers_all_leaves(mesh8):
    config = _config(grad_sync="bucketed", grad_sync_bucket_mb=0.01)
    gs = GradSync(config, mesh8.size)
    model = build_encoder(config)
    variables = model.init(jax.random.key(0), jnp.zeros((1, IMG, IMG, 3)),
                           train=False)
    gs.plan(variables["params"])
    buckets = gs._buckets()
    planned = sorted(p.index for b in buckets for p in b)
    assert planned == list(range(len(jax.tree.leaves(variables["params"]))))
    budget = 0.01 * 2**20
    for b in buckets:
        nbytes = sum(p.size * 4 for p in b)
        # a single oversized leaf may exceed the budget alone; multi-leaf
        # buckets must not
        assert len(b) == 1 or nbytes <= budget


# ---------------------------------------------------------------------------
# quantized: bounded divergence + error feedback
# ---------------------------------------------------------------------------

N_DIVERGENCE_STEPS = 5


def test_quantized_int8_bounded_divergence(mesh8):
    sf, lf, _ = _run(mesh8, _config(grad_sync="fused"),
                     steps=N_DIVERGENCE_STEPS)
    sq, lq, _ = _run(
        mesh8,
        _config(grad_sync="quantized", grad_sync_bucket_mb=0.05),
        steps=N_DIVERGENCE_STEPS,
    )
    assert all(np.isfinite(lq))
    # loss curves track exact DP within a band (int8 + shared scale + EF)
    for a, b in zip(lf, lq):
        assert abs(a - b) <= 0.05 * max(abs(a), 1.0), (lf, lq)
    # ...but the compression really happened: params are NOT bitwise equal
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(sf.params_q),
                        jax.tree.leaves(sq.params_q))
    )
    # and the error-feedback accumulator carries a nonzero residual with the
    # per-device leading axis
    acc = jax.tree.leaves(sq.gradsync["acc"])
    assert all(a.shape[0] == mesh8.size for a in acc)
    assert any(float(jnp.max(jnp.abs(a))) > 0 for a in acc)


def test_quantized_per_leaf_scales_avoid_starvation(mesh8):
    """Leaves whose gradients are orders of magnitude below the bucket's
    absmax must still transmit: scales are per LEAF (pmax-shared), not per
    bucket — a bucket-wide scale would round the small leaf to all-zeros on
    the wire every step."""
    config = _config(grad_sync="quantized", grad_sync_bucket_mb=64.0)
    gs = GradSync(config, mesh8.size)
    tree = {"big": jnp.full((64,), 0.1, jnp.float32),
            "small": jnp.full((64,), 1e-5, jnp.float32)}
    acc = {"acc": jax.tree.map(
        lambda x: jnp.zeros((mesh8.size,) + x.shape, jnp.float32), tree)}

    def region(t, a, step):
        payload, new_acc, _ = gs.region_reduce(t, a, step)
        return payload

    fn = shard_map(region, mesh=mesh8,
                   in_specs=(P(), P(DATA_AXIS), P()), out_specs=P())
    out = jax.jit(fn)(tree, acc, jnp.int32(0))
    # both leaves share one bucket (64 MiB budget), yet the small leaf's
    # reduced value is nonzero and within int8 tolerance of its true mean
    np.testing.assert_allclose(np.asarray(out["small"]), 1e-5, rtol=0.02)
    np.testing.assert_allclose(np.asarray(out["big"]), 0.1, rtol=0.02)


def test_quantized_bf16_bounded_divergence(mesh8):
    _, lf, _ = _run(mesh8, _config(grad_sync="fused"), steps=3)
    _, lq, _ = _run(
        mesh8,
        _config(grad_sync="quantized", grad_sync_quant_dtype="bfloat16"),
        steps=3,
    )
    assert all(np.isfinite(lq))
    for a, b in zip(lf, lq):
        assert abs(a - b) <= 0.02 * max(abs(a), 1.0), (lf, lq)


# ---------------------------------------------------------------------------
# demo: decoupled momentum, sparse sync, cadence
# ---------------------------------------------------------------------------


def test_demo_bounded_divergence(mesh8):
    _, lf, _ = _run(mesh8, _config(grad_sync="fused"),
                    steps=N_DIVERGENCE_STEPS)
    sd, ld, _ = _run(
        mesh8,
        _config(grad_sync="demo", grad_sync_topk=0.25,
                grad_sync_demo_beta=0.9),
        steps=N_DIVERGENCE_STEPS,
    )
    assert all(np.isfinite(ld))
    # demo is NOT an approximation of SGD — the gate is a band around the
    # exact-DP curve wide enough for the decoupled update, tight enough to
    # catch a frozen or exploding encoder
    for a, b in zip(lf, ld):
        assert abs(a - b) <= 0.5 * max(abs(a), 1.0), (lf, ld)
    # the local momentum carries the untransmitted residue
    acc = jax.tree.leaves(sd.gradsync["acc"])
    assert any(float(jnp.max(jnp.abs(a))) > 0 for a in acc)


def test_demo_cadence_skips_sync_on_off_steps(mesh8):
    """With cadence=2 and a memoryless optimizer the off-step hands the
    optimizer an all-zero delta: params must not move, while the sync step
    must move them — pinned this way because byte savings are invisible on
    the CPU backend but a zero update is not."""
    config = _config(
        grad_sync="demo", grad_sync_cadence=2, grad_sync_topk=0.25,
        sgd_momentum=0.0, weight_decay=0.0,
    )
    state, step = _build(mesh8, config)
    im = lambda k: jax.random.normal(jax.random.key(k), (B, IMG, IMG, 3))
    s1, _ = step(state, im(1), im(2))        # step 0: sync
    p0 = [np.asarray(x) for x in jax.tree.leaves(s1.params_q)]
    s2, _ = step(s1, im(3), im(4))           # step 1: off — no sync, no move
    p1 = [np.asarray(x) for x in jax.tree.leaves(s2.params_q)]
    for a, b in zip(p0, p1, strict=True):
        np.testing.assert_array_equal(a, b)
    s3, _ = step(s2, im(5), im(6))           # step 2: sync again
    assert any(
        not np.array_equal(np.asarray(a), b)
        for a, b in zip(jax.tree.leaves(s3.params_q), p1)
    )


def test_demo_params_stay_replicated_consistent(mesh8):
    """The DP-safety invariant: after sparse merges every device applies
    the identical update (the merge is an outer-level replicated
    computation), so a fully-addressable param leaf has identical shards."""
    sd, _, _ = _run(mesh8, _config(grad_sync="demo", grad_sync_topk=0.25),
                    steps=2)
    leaf = jax.tree.leaves(sd.params_q)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


# ---------------------------------------------------------------------------
# per-leaf dtype policy (the `_pmean_grads` regression)
# ---------------------------------------------------------------------------


def test_wire_dtype_policy():
    assert leaf_wire_dtype(jnp.dtype(jnp.float32), "float32") == jnp.float32
    assert leaf_wire_dtype(jnp.dtype(jnp.bfloat16), "float32") == jnp.bfloat16
    assert leaf_wire_dtype(jnp.dtype(jnp.float32), "bfloat16") == jnp.bfloat16
    assert leaf_wire_dtype(jnp.dtype(jnp.int32), "bfloat16") == jnp.int32
    with pytest.raises(ValueError, match="grad_allreduce_dtype"):
        leaf_wire_dtype(jnp.dtype(jnp.float32), "float16")


@pytest.mark.parametrize("mode", ["fused", "bucketed"])
@pytest.mark.parametrize("allreduce_dtype", ["float32", "bfloat16"])
def test_integer_and_none_leaves_reduce_exactly(mesh8, mode, allreduce_dtype):
    """Integer leaves are SUMMED exactly (never averaged, never cast) and
    None leaves pass through structurally; a bf16 float leaf keeps its own
    dtype after the reduce (the old code silently widened it to f32)."""
    config = _config(grad_sync=mode, grad_allreduce_dtype=allreduce_dtype,
                     grad_sync_bucket_mb=0.001)
    gs = GradSync(config, mesh8.size)

    def region(tree, step):
        payload, state, probe = gs.region_reduce(tree, {}, step)
        return payload

    fn = shard_map(
        region, mesh=mesh8,
        in_specs=(P(), P()), out_specs=P(),
    )
    tree = {
        "w": jnp.full((8, 3), 2.0, jnp.float32),
        "h": jnp.full((4,), 1.5, jnp.bfloat16),
        "count": jnp.asarray([3, 7], jnp.int32),
        "none": None,
    }
    out = jax.jit(fn)(tree, jnp.int32(0))
    assert out["none"] is None
    assert out["count"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out["count"]),
                                  np.asarray([24, 56]))  # 8 devices × exact
    assert out["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0, rtol=1e-6)
    assert out["h"].dtype == jnp.bfloat16  # NOT widened to f32
    np.testing.assert_allclose(np.asarray(out["h"], np.float32), 1.5,
                               rtol=1e-2)


# ---------------------------------------------------------------------------
# config validation + byte accounting
# ---------------------------------------------------------------------------


def test_config_rejects_bad_gradsync_knobs():
    with pytest.raises(ValueError, match="grad_sync"):
        _config(grad_sync="turbo")
    with pytest.raises(ValueError, match="grad_sync_quant_dtype"):
        _config(grad_sync_quant_dtype="int4")
    with pytest.raises(ValueError, match="grad_sync_cadence"):
        _config(grad_sync_cadence=0)
    with pytest.raises(ValueError, match="grad_sync_topk"):
        _config(grad_sync_topk=0.0)
    with pytest.raises(ValueError, match="grad_sync_bucket_mb"):
        _config(grad_sync_bucket_mb=0)


def test_sync_bytes_accounting(mesh8):
    params = {"a": jnp.zeros((100,), jnp.float32),
              "b": jnp.zeros((10, 10), jnp.float32)}
    fused = GradSync(_config(grad_sync="fused"), 8).describe(params)
    assert fused["sync_bytes_per_step"] == 200 * 4
    q = GradSync(_config(grad_sync="quantized"), 8).describe(params)
    assert q["sync_bytes_per_step"] == 200 * 1 + 4 * 2  # 1 B/elem + scale/leaf
    demo_cfg = _config(grad_sync="demo", grad_sync_topk=0.05,
                       grad_sync_cadence=4)
    demo = GradSync(demo_cfg, 8).describe(params)
    assert demo["sync_bytes_per_step"] == 2 * int(5 * 8 / 4)  # k=5 per leaf
    # the compressed modes really cut the wire payload
    assert q["sync_bytes_per_step"] < fused["sync_bytes_per_step"] / 3
    assert demo["sync_bytes_per_step"] < q["sync_bytes_per_step"] / 5


# ---------------------------------------------------------------------------
# checkpoint: dialect 2 roundtrip + dialect-1 upgrade
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_gradsync_state_checkpoint_roundtrip(mesh8, tmp_path):
    from moco_tpu.checkpoint import (
        checkpoint_manager,
        restore_checkpoint,
        save_checkpoint,
    )
    from moco_tpu.parallel.mesh import replicated

    config = _config(grad_sync="quantized")
    state, step = _build(mesh8, config)
    im_q = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
    im_k = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
    state, _ = step(state, im_q, im_k)
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state, 1)
    fresh, _ = _build(mesh8, config)
    restored = restore_checkpoint(mgr, fresh, 1, sharding=replicated(mesh8))
    for a, b in zip(jax.tree.leaves(state.gradsync["acc"]),
                    jax.tree.leaves(restored.gradsync["acc"]), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == 1


@pytest.mark.slow
def test_dialect1_checkpoint_restores_with_fresh_accumulators(mesh8, tmp_path):
    """A pre-gradsync (dialect 1) checkpoint — simulated by saving the
    TrainState WITHOUT the gradsync field — restores into a quantized-mode
    target: the shim strips the accumulator leaves, the restore succeeds,
    and the accumulators restart from the caller's fresh zeros."""
    import orbax.checkpoint as ocp

    from moco_tpu.checkpoint import checkpoint_manager, restore_checkpoint
    from moco_tpu.parallel.mesh import replicated

    config = _config(grad_sync="quantized")
    state, _ = _build(mesh8, config)
    old_tree = {
        "step": state.step, "params_q": state.params_q,
        "params_k": state.params_k, "batch_stats_q": state.batch_stats_q,
        "batch_stats_k": state.batch_stats_k, "opt_state": state.opt_state,
        "queue": state.queue, "queue_ptr": state.queue_ptr,
        "rng": jax.random.key_data(state.rng),
    }
    mgr = checkpoint_manager(str(tmp_path / "old"))
    mgr.save(0, args=ocp.args.StandardSave(old_tree))
    mgr.wait_until_finished()
    fresh, _ = _build(mesh8, config)
    restored = restore_checkpoint(mgr, fresh, 0, sharding=replicated(mesh8))
    for a, b in zip(jax.tree.leaves(restored.params_q),
                    jax.tree.leaves(state.params_q), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a in jax.tree.leaves(restored.gradsync["acc"]):
        assert float(jnp.max(jnp.abs(a))) == 0.0  # fresh zeros


@pytest.mark.slow
def test_mode_switch_downgrade_drops_accumulators(mesh8, tmp_path):
    """A quantized checkpoint (accumulator leaves on disk) restored by a
    fused-mode run: the shim's stripped retry ignores the on-disk
    accumulators and the run proceeds exact-DP."""
    from moco_tpu.checkpoint import (
        checkpoint_manager,
        restore_checkpoint,
        save_checkpoint,
    )
    from moco_tpu.parallel.mesh import replicated

    state_q, step_q = _build(mesh8, _config(grad_sync="quantized"))
    im_q = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
    im_k = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
    state_q, _ = step_q(state_q, im_q, im_k)
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state_q, 1)
    fresh_fused, step_f = _build(mesh8, _config(grad_sync="fused"))
    restored = restore_checkpoint(mgr, fresh_fused, 1,
                                  sharding=replicated(mesh8))
    assert restored.gradsync == {}
    for a, b in zip(jax.tree.leaves(restored.params_q),
                    jax.tree.leaves(state_q.params_q), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    restored, metrics = step_f(restored, im_q, im_k)
    assert np.isfinite(float(metrics["loss"]))


# ---------------------------------------------------------------------------
# v3 path + telemetry plumbing
# ---------------------------------------------------------------------------


def test_v3_demo_step_runs(mesh8):
    config = _config(
        variant="v3", grad_sync="demo", grad_sync_topk=0.25,
        optimizer="sgd", num_negatives=K,
    )
    from moco_tpu.v3_step import create_v3_train_state

    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_v3_train_state(
        jax.random.key(0), model, tx, (B // mesh8.size, IMG, IMG, 3)
    )
    state = GradSync(config, mesh8.size).attach(state, mesh8)
    step = build_train_step(config, model, tx, mesh8, 8, sched)
    x1 = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
    x2 = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
    state, metrics = step(state, x1, x2)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1
    acc = jax.tree.leaves(state.gradsync["acc"])
    assert acc and all(a.shape[0] == mesh8.size for a in acc)


def test_step_emits_comm_probes(mesh8):
    _, _, metrics = _run(mesh8, _config(grad_sync="bucketed"), steps=1)
    assert np.isfinite(float(metrics["gs_comm_pre"]))
    assert np.isfinite(float(metrics["gs_comm_post"]))


def test_timer_comm_phase():
    from moco_tpu.telemetry.timing import StepPhaseTimer

    timer = StepPhaseTimer(stride=2)
    timer.epoch_start()
    timer.mark_data()
    timer.mark_dispatch()
    # off-stride: no fence, no comm sample
    assert timer.maybe_fence(1, 1.0, comm_pre=0.5, comm_post=0.7) is None
    assert "comm_s" not in timer.finish_step()
    timer.mark_data()
    timer.mark_dispatch()
    assert timer.maybe_fence(2, 1.0, comm_pre=0.5, comm_post=0.7) is not None
    phases = timer.finish_step()
    assert "comm_s" in phases and phases["comm_s"] >= 0.0
    assert "device_s" in phases
    # probes absent (a non-gradsync caller): fence still works, no comm key
    timer.mark_data()
    timer.mark_dispatch()
    assert timer.maybe_fence(4, 1.0) is not None
    assert "comm_s" not in timer.finish_step()


def test_report_renders_comm_share_and_sync_bytes(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "telemetry_report.py"),
    )
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)

    gs = {"mode": "quantized", "sync_bytes_per_step": 5 * 2**20,
          "quant_dtype": "int8", "bucket_mb": 4.0, "buckets": 3}
    records = [
        {"kind": "run_start", "name": "t", "variant": "v2", "arch": "r50",
         "batch_size": 256, "n_chips": 8, "n_procs": 1},
        {"kind": "event", "event": "grad_sync", **gs},
    ]
    for s in range(1, 9):
        rec = {"kind": "step", "step": s, "step_s": 0.1, "data_s": 0.01,
               "host_s": 0.005}
        if s % 4 == 0:
            rec["comm_s"] = 0.02
            rec["grad_sync"] = gs
        records.append(rec)
    summary = report.summarize(records)
    assert summary["comm"]["samples"] == 2
    assert summary["comm"]["share_mean"] == pytest.approx(0.2)
    assert summary["grad_sync"]["mode"] == "quantized"
    text = report.render(summary)
    assert "grad sync: quantized" in text
    assert "5.00 MiB/step/device" in text
    assert "comm phase" in text and "share 20.0%" in text
    # grad_sync is a routine event, not an incident
    assert summary["incidents_total"] == 0


@pytest.mark.slow
def test_driver_emits_grad_sync_records(mesh8, tmp_path):
    """End-to-end: a short quantized driver run lands a `grad_sync` event
    (mode + analytic bytes) and step records at the sampling stride carry
    the grad_sync stamp; the report renders the section."""
    from moco_tpu.config import get_preset
    from moco_tpu.train import train

    tel_dir = str(tmp_path / "tel")
    os.makedirs(tel_dir, exist_ok=True)
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=32,
        num_negatives=64, embed_dim=16, epochs=1, steps_per_epoch=6,
        grad_sync="quantized", knn_monitor=False, ckpt_dir="", print_freq=2,
        telemetry_dir=tel_dir, telemetry_stride=2, telemetry_flush_steps=2,
    )
    state, metrics = train(config, mesh8)
    assert int(state.step) == 6
    assert np.isfinite(metrics["loss"])
    events = [json.loads(line) for line in
              open(os.path.join(tel_dir, "events.jsonl"))]
    gs_events = [e for e in events
                 if e.get("kind") == "event" and e.get("event") == "grad_sync"]
    assert gs_events and gs_events[0]["mode"] == "quantized"
    assert gs_events[0]["sync_bytes_per_step"] > 0
    stamped = [e for e in events
               if e.get("kind") == "step" and "grad_sync" in e]
    assert stamped, "no step record carried the grad_sync stamp"
