"""REAL torch consumers for every export dialect (upgrade over the r2 numpy
emulations — torch-cpu is in the image, so the dialects are verified against
genuine torch module semantics: Conv2d/BatchNorm2d/LayerNorm/Linear NCHW
forward passes).

- torchvision dialect (`module.encoder_q.*`): a from-scratch torch ResNet
  with torchvision's exact module names consumes `export`ed weights
  `strict=True` and reproduces the flax forward.
- timm ViT dialect: a from-scratch torch ViT with timm's fused-qkv layout
  consumes a `vit_to_timm` export and reproduces the flax class-token
  feature (pos_embed consumed the timm way: added AFTER cls concat).
- Detectron2 pkl: renamed back to torchvision names, consumed by the torch
  backbone, features match.

These pin the reference consumer contracts: `main_lincls.py:≈L176-200`
surgery expects torchvision names; `detection/convert-pretrain-to-
detectron2.py:≈L1-40` names; moco-v3's lincls consumes timm ViTs.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")


# ---------------------------------------------------------------------------
# minimal torch ResNet with torchvision's exact state_dict names
# ---------------------------------------------------------------------------


class TBasic(torch.nn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(cout)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = torch.nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = torch.nn.Sequential(
                torch.nn.Conv2d(cin, cout, 1, stride, bias=False),
                torch.nn.BatchNorm2d(cout),
            )

    def forward(self, x):
        r = x if self.downsample is None else self.downsample(x)
        y = torch.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return torch.relu(r + y)


class TBottleneck(torch.nn.Module):
    def __init__(self, cin, width, stride):
        super().__init__()
        cout = width * 4
        self.conv1 = torch.nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(width)
        self.conv2 = torch.nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = torch.nn.BatchNorm2d(width)
        self.conv3 = torch.nn.Conv2d(width, cout, 1, bias=False)
        self.bn3 = torch.nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = torch.nn.Sequential(
                torch.nn.Conv2d(cin, cout, 1, stride, bias=False),
                torch.nn.BatchNorm2d(cout),
            )

    def forward(self, x):
        r = x if self.downsample is None else self.downsample(x)
        y = torch.relu(self.bn1(self.conv1(x)))
        y = torch.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return torch.relu(r + y)


class TResNet(torch.nn.Module):
    def __init__(self, stages, block, width=64, num_classes=16, mlp=False):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(3, width, 7, 2, 3, bias=False)
        self.bn1 = torch.nn.BatchNorm2d(width)
        self.maxpool = torch.nn.MaxPool2d(3, 2, 1)
        cin = width
        for i, n in enumerate(stages):
            blocks = []
            for j in range(n):
                stride = 2 if i > 0 and j == 0 else 1
                if block is TBasic:
                    blocks.append(TBasic(cin, width * 2**i, stride))
                    cin = width * 2**i
                else:
                    blocks.append(TBottleneck(cin, width * 2**i, stride))
                    cin = width * 2**i * 4
            setattr(self, f"layer{i + 1}", torch.nn.Sequential(*blocks))
        self.nstages = len(stages)
        if num_classes is None:
            self.fc = None
        elif mlp:
            self.fc = torch.nn.Sequential(
                torch.nn.Linear(cin, cin), torch.nn.ReLU(),
                torch.nn.Linear(cin, num_classes),
            )
        else:
            self.fc = torch.nn.Linear(cin, num_classes)

    def forward(self, x):
        x = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        for i in range(self.nstages):
            x = getattr(self, f"layer{i + 1}")(x)
        x = x.mean(dim=(2, 3))
        return x if self.fc is None else self.fc(x)


def _randomized_stats(stats, seed=5):
    """Non-trivial running stats so a mean/var swap can't hide."""
    rng = np.random.RandomState(seed)

    def f(path, leaf):
        name = jax.tree_util.keystr(path)
        arr = 0.5 * rng.rand(*leaf.shape).astype(np.float32)
        return arr + (1.0 if "var" in name else 0.0)

    return jax.tree_util.tree_map_with_path(f, stats)


def _load_torch(model, flat):
    sd = {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in flat.items()}
    missing, unexpected = model.load_state_dict(sd, strict=False)
    # torch tracks num_batches_tracked per BN; everything else must match
    assert not unexpected, unexpected
    assert all("num_batches_tracked" in m for m in missing), missing
    return model.eval()


@pytest.mark.slow
def test_torch_resnet18_consumes_export():
    """`module.encoder_q.`-style export → real torch ResNet-18, strict names,
    matching eval forward (the lincls surgery consumer contract)."""
    from moco_tpu.checkpoint import resnet_to_torchvision
    from moco_tpu.models import build_resnet

    model = build_resnet("resnet18", num_classes=16, s2d_stem=False)
    x = jax.random.normal(jax.random.key(0), (2, 64, 64, 3), jnp.float32)
    v = model.init(jax.random.key(1), x, train=False)
    stats = _randomized_stats(v["batch_stats"])
    ours = np.asarray(
        model.apply({"params": v["params"], "batch_stats": stats}, x, train=False)
    )
    flat = resnet_to_torchvision(
        jax.tree.map(np.asarray, v["params"]), jax.tree.map(np.asarray, stats)
    )
    tmodel = _load_torch(TResNet((2, 2, 2, 2), TBasic, num_classes=16), flat)
    with torch.no_grad():
        theirs = tmodel(torch.from_numpy(
            np.asarray(x).transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_torch_bottleneck_mlp_consumes_export():
    """Bottleneck + v2 MLP head (fc.0/fc.2) through the same contract."""
    from moco_tpu.checkpoint import resnet_to_torchvision
    from moco_tpu.models.resnet import Bottleneck, ResNet

    model = ResNet(stage_sizes=(1, 1), block_cls=Bottleneck, width=8,
                   num_classes=12, mlp_head=True, s2d_stem=False)
    x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3), jnp.float32)
    v = model.init(jax.random.key(3), x, train=False)
    stats = _randomized_stats(v["batch_stats"], seed=6)
    ours = np.asarray(
        model.apply({"params": v["params"], "batch_stats": stats}, x, train=False)
    )
    flat = resnet_to_torchvision(
        jax.tree.map(np.asarray, v["params"]), jax.tree.map(np.asarray, stats)
    )
    tmodel = _load_torch(
        TResNet((1, 1), TBottleneck, width=8, num_classes=12, mlp=True), flat
    )
    with torch.no_grad():
        theirs = tmodel(torch.from_numpy(
            np.asarray(x).transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_torch_consumes_detectron2_pkl():
    """pkl → rename Detectron2 names back to torchvision → torch backbone
    forward matches the flax feature output (value-level consumer check the
    r2 round recorded as impossible without torch)."""
    import pickle

    from moco_tpu.checkpoint import resnet_to_torchvision
    from moco_tpu.export_detectron2 import torchvision_flat_to_detectron2
    from moco_tpu.models import build_resnet

    model = build_resnet("resnet18", num_classes=None, s2d_stem=False)
    x = jax.random.normal(jax.random.key(4), (1, 64, 64, 3), jnp.float32)
    v = model.init(jax.random.key(5), x, train=False)
    stats = _randomized_stats(v["batch_stats"], seed=7)
    ours = np.asarray(
        model.apply({"params": v["params"], "batch_stats": stats}, x, train=False)
    )
    flat = resnet_to_torchvision(
        jax.tree.map(np.asarray, v["params"]), jax.tree.map(np.asarray, stats)
    )
    det2 = torchvision_flat_to_detectron2(
        {f"module.encoder_q.{k}": v_ for k, v_ in flat.items()}
    )
    blob = pickle.loads(pickle.dumps(det2))  # round-trip like the real pkl

    # invert the naming: stem.conv1{,.norm} → conv1/bn1; resN.M.convK{,.norm}
    # → layer(N-1).M.{convK,bnK}; shortcut{,.norm} → downsample.0/1
    back = {}
    bn_leaves = {"weight": "weight", "bias": "bias",
                 "running_mean": "running_mean", "running_var": "running_var"}
    for k, arr in blob.items():
        parts = k.split(".")
        if parts[0] == "stem":
            if parts[2] == "norm":
                back[f"bn1.{bn_leaves[parts[3]]}"] = arr
            else:
                back[f"conv1.{parts[2]}"] = arr
        else:
            stage = int(parts[0][len("res"):]) - 1
            base = f"layer{stage}.{parts[1]}"
            if parts[2] == "shortcut":
                if parts[3] == "norm":
                    back[f"{base}.downsample.1.{bn_leaves[parts[4]]}"] = arr
                else:
                    back[f"{base}.downsample.0.{parts[3]}"] = arr
            elif parts[3] == "norm":
                back[f"{base}.bn{parts[2][len('conv'):]}.{bn_leaves[parts[4]]}"] = arr
            else:
                back[f"{base}.{parts[2]}.{parts[3]}"] = arr
    tmodel = _load_torch(TResNet((2, 2, 2, 2), TBasic, num_classes=None), back)
    with torch.no_grad():
        theirs = tmodel(torch.from_numpy(
            np.asarray(x).transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# minimal torch ViT with timm's fused-qkv layout and names
# ---------------------------------------------------------------------------


class TBlock(torch.nn.Module):
    def __init__(self, d, heads):
        super().__init__()
        # timm's LayerNorm eps is 1e-6 (torch default 1e-5 visibly diverges
        # on the near-zero cls row)
        self.norm1 = torch.nn.LayerNorm(d, eps=1e-6)
        self.attn = torch.nn.Module()
        self.attn.qkv = torch.nn.Linear(d, 3 * d)
        self.attn.proj = torch.nn.Linear(d, d)
        self.norm2 = torch.nn.LayerNorm(d, eps=1e-6)
        self.mlp = torch.nn.Module()
        self.mlp.fc1 = torch.nn.Linear(d, 4 * d)
        self.mlp.fc2 = torch.nn.Linear(4 * d, d)
        self.h = heads
        self.d = d

    def forward(self, x):
        b, n, d = x.shape
        y = self.norm1(x)
        qkv = self.attn.qkv(y).reshape(b, n, 3, self.h, d // self.h)
        q, k, v = qkv.unbind(2)  # [b, n, h, hd]
        q = q.transpose(1, 2)
        k = k.transpose(1, 2)
        v = v.transpose(1, 2)
        a = torch.softmax(q @ k.transpose(-2, -1) / math.sqrt(d // self.h), -1)
        y = (a @ v).transpose(1, 2).reshape(b, n, d)
        x = x + self.attn.proj(y)
        y = self.norm2(x)
        y = self.mlp.fc2(torch.nn.functional.gelu(self.mlp.fc1(y)))
        return x + y


class TViT(torch.nn.Module):
    def __init__(self, d, depth, heads, patch):
        super().__init__()
        self.patch_embed = torch.nn.Module()
        self.patch_embed.proj = torch.nn.Conv2d(3, d, patch, patch)
        self.cls_token = torch.nn.Parameter(torch.zeros(1, 1, d))
        self.pos_embed = None  # set from the export (timm consumes it)
        self.blocks = torch.nn.Sequential(*[TBlock(d, heads) for _ in range(depth)])
        self.norm = torch.nn.LayerNorm(d, eps=1e-6)

    def forward(self, x):
        b = x.shape[0]
        x = self.patch_embed.proj(x).flatten(2).transpose(1, 2)  # [b, n, d]
        x = torch.cat([self.cls_token.expand(b, -1, -1), x], dim=1)
        x = x + self.pos_embed  # timm order: pos added AFTER cls concat
        x = self.blocks(x)
        return self.norm(x)[:, 0]


@pytest.mark.slow
def test_torch_vit_consumes_timm_export():
    """vit_to_timm export → real torch fused-qkv ViT (timm layout) → class
    token feature matches the flax forward (moco-v3 lincls consumer)."""
    from moco_tpu.checkpoint import vit_to_timm
    from moco_tpu.models.vit import build_vit

    model = build_vit("vit_tiny", num_classes=None)
    x = jax.random.normal(jax.random.key(6), (2, 32, 32, 3), jnp.float32)
    v = model.init(jax.random.key(7), x, train=False)
    ours = np.asarray(model.apply(v, x, train=False))
    flat = vit_to_timm(jax.tree.map(np.asarray, v["params"]), grid=(2, 2))

    tmodel = TViT(64, 2, 2, 16)
    pos = torch.from_numpy(np.ascontiguousarray(flat.pop("pos_embed")))
    sd = {k: torch.from_numpy(np.ascontiguousarray(a)) for k, a in flat.items()}
    missing, unexpected = tmodel.load_state_dict(sd, strict=False)
    assert not unexpected, unexpected
    assert missing == [], missing
    tmodel.pos_embed = pos
    tmodel.eval()
    with torch.no_grad():
        theirs = tmodel(torch.from_numpy(
            np.asarray(x).transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
