"""Serve fleet suite (ISSUE 10).

Four layers:
  - pure units: router pick/retry/shed semantics against in-thread stub
    backends (no child processes, no jax), checkpoint-watcher
    verify/quarantine, exit-code 48 classification;
  - stub-replica e2e: the REAL FleetSupervisor loop driving tiny python
    stub replicas — restart policy, the accepting-but-not-answering
    wedge kill, drain-aware rolling restart under load, the
    32-client SIGKILL drill with the zero-lost contract, and the
    watcher's reload roll + relaunch convergence — seconds-cheap,
    tier-1;
  - in-process jax: hot reload swap bit-identical to a cold start on
    the new checkpoint, cache invalidation, /admin/reload wire
    contract, reload-failure leaves the old weights serving;
  - the full soak (slow): 2 REAL tools/serve.py replicas under the
    fleet, closed-loop load through a replica SIGKILL and a
    watcher-driven hot reload, embeddings verified against a fresh
    engine on the new checkpoint.
"""

from __future__ import annotations

import base64
import importlib.util
import json
import os
import signal
import socket
import textwrap
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from moco_tpu.resilience.chaos import truncate_checkpoint
from moco_tpu.resilience.integrity import write_manifest
from moco_tpu.serve.fleet import (
    CheckpointWatcher,
    FleetPolicy,
    FleetSupervisor,
    ReplicaState,
    pick_free_port,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


serve_bench = _load_tool("serve_bench")
telemetry_report = _load_tool("telemetry_report")

FAST_POLICY = dict(
    probe_secs=0.1, probe_timeout_s=0.5, health_stale_secs=1.0,
    startup_grace_secs=15.0, term_grace_secs=1.0,
    backoff_base_secs=0.05, backoff_max_secs=0.2, backoff_jitter=0.0,
    request_timeout_s=10.0, watch_poll_secs=0.1, stats_every_secs=1.0,
)


# ---------------------------------------------------------------------------
# router semantics (in-thread stub backends, no child processes)
# ---------------------------------------------------------------------------


class _FakeProc:
    """Stands in for a live Popen in router-only tests."""

    pid = 4242

    def poll(self):
        return None


def _stub_backend(response=None, status=200):
    """One in-thread HTTP backend answering every POST with `response`."""
    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length") or 0))
            body = json.dumps(
                response if response is not None
                else {"embedding": [float(self.server.server_address[1])]}
            ).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class S(ThreadingHTTPServer):
        daemon_threads = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _router_fleet(tmp_path, ports, healthy=None):
    """A FleetSupervisor with hand-built replica states (no start(), no
    monitor thread): exactly the router logic under test."""
    fleet = FleetSupervisor(
        lambda *a: ["true"], replicas=len(ports),
        telemetry_dir=str(tmp_path / "fleet_t"),
        policy=FleetPolicy(**FAST_POLICY),
    )
    for i, port in enumerate(ports):
        r = ReplicaState(i, "127.0.0.1", port,
                         str(tmp_path / f"r{i}"), budget=3)
        r.proc = _FakeProc()
        r.healthy = True if healthy is None else healthy[i]
        fleet.replicas.append(r)
    return fleet


def test_router_least_outstanding_pick(tmp_path):
    fleet = _router_fleet(tmp_path, [1001, 1002, 1003])
    fleet.replicas[0].outstanding = 2
    fleet.replicas[1].outstanding = 0
    fleet.replicas[2].outstanding = 1
    picked = fleet.pick_backend()
    assert picked.index == 1
    assert picked.outstanding == 1  # pick reserves a slot
    fleet.release_backend(picked)
    assert picked.outstanding == 0
    # draining/ejected/excluded replicas never picked
    fleet.replicas[1].draining = True
    assert fleet.pick_backend(exclude=(2,)).index == 0


def test_router_retries_once_on_dead_replica_then_succeeds(tmp_path):
    live = _stub_backend()
    dead_port = pick_free_port()  # nothing listening: connection refused
    fleet = _router_fleet(
        tmp_path, [dead_port, live.server_address[1]]
    )
    try:
        # force the dead replica to be picked first
        fleet.replicas[1].outstanding = 5
        status, body = fleet.router_proxy("/v1/embed", b"{}")
        assert status == 200
        assert json.loads(body)["embedding"] == [live.server_address[1]]
        assert fleet.r_retries == 1 and fleet.r_retry_ok == 1
        # the dead replica was ejected: re-admission is the probe's job
        assert fleet.replicas[0].healthy is False
        assert [e["event"] for e in fleet.incidents].count("eject") == 1
    finally:
        live.shutdown()


def test_router_both_attempts_fail_structured_502(tmp_path):
    fleet = _router_fleet(
        tmp_path, [pick_free_port(), pick_free_port()]
    )
    status, body = fleet.router_proxy("/v1/embed", b"{}")
    resp = json.loads(body)
    assert status == 502 and resp["error"] == "upstream_error"
    assert resp["retry_after_ms"] > 0
    assert fleet.r_upstream_error == 1


def test_router_sheds_structured_503_when_no_healthy_backend(tmp_path):
    fleet = _router_fleet(tmp_path, [1001], healthy=[False])
    t0 = time.monotonic()
    status, body = fleet.router_proxy("/v1/embed", b"{}")
    resp = json.loads(body)
    assert time.monotonic() - t0 < 1.0  # shed immediately, never stalls
    assert status == 503 and resp["error"] == "no_healthy_backend"
    assert resp["retry_after_ms"] > 0
    assert fleet.r_shed_no_backend == 1
    assert any(e["event"] == "no_backend" for e in fleet.incidents)


def test_router_passes_replica_rejections_through(tmp_path):
    shed = _stub_backend(response={"error": "overloaded",
                                   "retry_after_ms": 5.0}, status=503)
    fleet = _router_fleet(tmp_path, [shed.server_address[1]])
    try:
        status, body = fleet.router_proxy("/v1/embed", b"{}")
        assert status == 503
        assert json.loads(body)["error"] == "overloaded"
        # a structured ANSWER from a live replica is not a router failure:
        # no retry, no ejection
        assert fleet.r_retries == 0
        assert fleet.replicas[0].healthy is True
        assert fleet.r_passthrough_error == 1
    finally:
        shed.shutdown()


def test_router_deadline_from_body_wins(tmp_path):
    fleet = _router_fleet(tmp_path, [1001])
    assert fleet._deadline_s(b'{"pixels": [1]}') == \
        fleet.policy.request_timeout_s
    assert fleet._deadline_s(b'{"deadline_ms": 250}') == 0.25
    # malformed body: default deadline, the replica answers the 400
    assert fleet._deadline_s(b'{"deadline_ms": oops') == \
        fleet.policy.request_timeout_s


# ---------------------------------------------------------------------------
# checkpoint watcher (verify -> deploy / quarantine)
# ---------------------------------------------------------------------------


def _export_step(watch_dir, step, payload=b"w" * 4096, manifest=True,
                 name="encoder.npz"):
    d = watch_dir / str(step)
    d.mkdir(parents=True)
    (d / name).write_bytes(payload)
    if manifest:
        write_manifest(str(watch_dir), step)
    return str(d / name)


def test_watcher_deploys_only_manifested_verified_steps(tmp_path):
    watch = tmp_path / "export"
    watch.mkdir()
    events = []
    w = CheckpointWatcher(str(watch),
                          emit=lambda e, **f: events.append((e, f)))
    assert w.poll_once() is None  # empty dir
    _export_step(watch, 10, manifest=False)
    # manifest-less = still being written: NOT deployable yet
    assert w.poll_once() is None
    write_manifest(str(watch), 10)
    step, payload = w.poll_once()
    assert step == 10 and payload.endswith("encoder.npz")
    assert w.poll_once() is None  # nothing new
    # newest verified wins; older undeployed steps are skipped
    _export_step(watch, 20)
    _export_step(watch, 30)
    step, _ = w.poll_once()
    assert step == 30
    assert w.poll_once() is None


def test_watcher_quarantines_truncated_checkpoint(tmp_path):
    """The acceptance drill: a truncated export is quarantined loudly and
    NEVER deployed; a later valid step still deploys."""
    watch = tmp_path / "export"
    watch.mkdir()
    events = []
    w = CheckpointWatcher(str(watch),
                          emit=lambda e, **f: events.append((e, f)))
    _export_step(watch, 10)
    assert w.poll_once()[0] == 10
    _export_step(watch, 20)
    truncate_checkpoint(str(watch), 20)  # torn mid-write
    assert w.poll_once() is None  # nothing deployable
    assert [e for e, _ in events] == ["reload_quarantine"]
    assert events[0][1]["step"] == 20
    assert not (watch / "20").exists()
    assert os.path.isdir(str(watch / ".quarantine" / "20"))
    _export_step(watch, 21)
    assert w.poll_once()[0] == 21


def test_watcher_payload_selection(tmp_path):
    watch = tmp_path / "export"
    watch.mkdir()
    d = watch / "5"
    d.mkdir()
    (d / "notes.txt").write_bytes(b"x")
    (d / "encoder.safetensors").write_bytes(b"w" * 512)
    write_manifest(str(watch), 5)
    w = CheckpointWatcher(str(watch))
    step, payload = w.poll_once()
    assert step == 5 and payload.endswith("encoder.safetensors")


# ---------------------------------------------------------------------------
# exit-code protocol
# ---------------------------------------------------------------------------


def test_fleet_bind_exit_code_is_fatal():
    from moco_tpu.resilience.exitcodes import EXIT_FLEET_BIND
    from moco_tpu.resilience.supervisor import FATAL_CLASSES, classify_exit

    assert EXIT_FLEET_BIND == 48
    cls, detail = classify_exit(48)
    assert cls == "fleet_bind"
    assert "fleet_bind" in FATAL_CLASSES


def test_serve_fleet_cli_bind_failure_exits_48(tmp_path):
    serve_fleet = _load_tool("serve_fleet")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        s.listen(1)
        taken = s.getsockname()[1]
        rc = serve_fleet.main([
            "--replicas", "1", "--port", str(taken),
            "--telemetry-dir", str(tmp_path / "t"), "--", "true",
        ])
    assert rc == 48
    # and the config error path: no replica command at all
    assert serve_fleet.main(
        ["--replicas", "1", "--telemetry-dir", str(tmp_path / "t2")]
    ) == 45


def test_serve_fleet_cli_unspawnable_replica_exits_45_not_48(tmp_path):
    """A replica command that can never exec is a CONFIG error (45), not
    the reschedule-semantics bind failure (48) — and a partial start
    must not leak the replicas that did spawn."""
    serve_fleet = _load_tool("serve_fleet")
    rc = serve_fleet.main([
        "--replicas", "2", "--port", "0",
        "--telemetry-dir", str(tmp_path / "t"), "--",
        str(tmp_path / "no_such_binary"),
    ])
    assert rc == 45


def test_fleet_import_is_stdlib_only():
    """The R11 boundary's runtime twin: a fresh process importing the
    fleet module (and the CLI's imports) must pull neither numpy nor
    jax — the routing tier survives what kills the replicas."""
    import subprocess
    import sys as _sys

    code = (
        "import sys\n"
        "import moco_tpu.serve.fleet\n"
        "bad = sorted({m.split('.')[0] for m in sys.modules} & "
        "{'numpy', 'jax', 'optax', 'orbax', 'flax'})\n"
        "assert not bad, bad\n"
    )
    r = subprocess.run([_sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# stub-replica e2e: the real fleet loop, seconds-cheap children
# ---------------------------------------------------------------------------

_STUB_REPLICA = textwrap.dedent("""\
    import argparse, json, os, signal, sys, threading, time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--telemetry-dir", required=True)
    p.add_argument("--pretrained", default="boot")
    p.add_argument("--behavior", default="ok")
    args, _ = p.parse_known_args()

    state = {"draining": False, "wedged": False, "requests": 0,
             "pretrained": args.pretrained, "reloads": 0}

    if args.behavior == "exit1":
        sys.exit(1)
    wedge_after = None
    if args.behavior.startswith("wedge_after="):
        wedge_after = int(args.behavior.split("=")[1])
        # a truly wedged process doesn't honor SIGTERM either: force the
        # fleet's SIGTERM -> grace -> SIGKILL escalation
        signal.signal(signal.SIGTERM, signal.SIG_IGN)

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        def log_message(self, *a):
            pass
        def _send(self, status, obj):
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        def _wedge(self):
            while state["wedged"]:
                time.sleep(3600.0)
        def do_GET(self):
            self._wedge()
            if self.path == "/healthz":
                if state["draining"]:
                    self._send(503, {"status": "draining"})
                else:
                    self._send(200, {"status": "ok"})
            elif self.path == "/stats":
                self._send(200, dict(state, pid=os.getpid()))
            else:
                self._send(404, {"error": "not_found"})
        def do_POST(self):
            self._wedge()
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n)
            if self.path == "/admin/reload":
                req = json.loads(body or b"{}")
                if not req.get("pretrained"):
                    self._send(400, {"error": "bad_request"})
                    return
                state["pretrained"] = req["pretrained"]
                state["reloads"] += 1
                self._send(200, {"status": "reloaded",
                                 "step": req.get("step")})
                return
            if self.path in ("/v1/embed", "/v1/knn"):
                state["requests"] += 1
                if wedge_after is not None and \\
                        state["requests"] >= wedge_after:
                    state["wedged"] = True
                if state["draining"]:
                    self._send(503, {"error": "draining"})
                    return
                self._send(200, {"embedding": [1.0, float(args.port)],
                                 "cached": False,
                                 "pretrained": state["pretrained"]})
                return
            self._send(404, {"error": "not_found"})

    class S(ThreadingHTTPServer):
        daemon_threads = True
        request_queue_size = 128

    srv = S(("127.0.0.1", args.port), H)
    stop = threading.Event()
    def term(signum, frame):
        state["draining"] = True
        stop.set()
    if wedge_after is None:
        signal.signal(signal.SIGTERM, term)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    while not stop.is_set():
        time.sleep(0.02)
    time.sleep(0.05)  # "drain" the in-flight work
    srv.shutdown()
    sys.exit(0)
""")


def _stub_fleet(tmp_path, n=2, behavior="ok", watch_dir="", **policy_kw):
    import sys as _sys

    stub = tmp_path / "stub_replica.py"
    stub.write_text(_STUB_REPLICA)
    kw = dict(FAST_POLICY)
    kw.update(policy_kw)

    def child_argv(index, port, tdir, pretrained):
        argv = [_sys.executable, str(stub), "--port", str(port),
                "--telemetry-dir", tdir, "--behavior",
                behavior if index == 0 else "ok"]
        if pretrained:
            argv += ["--pretrained", pretrained]
        return argv

    return FleetSupervisor(
        child_argv, replicas=n, telemetry_dir=str(tmp_path / "fleet_t"),
        policy=FleetPolicy(**kw), watch_dir=watch_dir, seed=0,
    )


def _wait(cond, timeout_s=20.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _post(url, body, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_e2e_crash_loop_exhausts_budget_and_fleet_fails(tmp_path):
    """A replica that dies at every launch is abandoned after
    max_restarts consecutive never-healthy deaths; a 1-replica fleet is
    then FAILED (the CLI exits nonzero)."""
    fleet = _stub_fleet(tmp_path, n=1, behavior="exit1", max_restarts=2)
    fleet.start()
    try:
        _wait(lambda: fleet.failed, msg="fleet_give_up")
        r = fleet.replicas[0]
        assert r.abandoned and r.launches == 3  # initial + 2 restarts
        events = [e["event"] for e in fleet.incidents]
        assert "give_up" in events and "fleet_give_up" in events
        assert all(c == "crash" for c in r.classifications)
    finally:
        fleet.stop()


def test_e2e_wedge_is_probe_detected_killed_and_restarted(tmp_path):
    """The accepting-but-not-answering drill: after the wedge, probes
    stop answering; the fleet ejects, escalates SIGTERM (ignored) →
    SIGKILL, classifies the death as a hang, and restores the replica —
    while the other replica keeps serving the whole time."""
    fleet = _stub_fleet(tmp_path, n=2, behavior="wedge_after=3",
                        term_grace_secs=0.5)
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, msg="fleet healthy")
        url = fleet.router.url
        wedge_port = fleet.replicas[0].port
        # drive requests AT the wedged replica's own port to trip the
        # wedge deterministically (the router would balance away)
        for _ in range(3):
            _post(f"http://127.0.0.1:{wedge_port}/v1/embed",
                  {"pixels": [1]})
        # the router keeps answering through replica 1 throughout
        status, _ = _post(url + "/v1/embed", {"pixels": [1]})
        assert status == 200
        _wait(lambda: "hang" in fleet.replicas[0].classifications,
              msg="wedge killed + classified hang")
        _wait(lambda: fleet.healthy_count() == 2,
              msg="wedged replica restored")
        events = [e["event"] for e in fleet.incidents]
        assert "eject" in events and "kill" in events
        kills = [e for e in fleet.incidents if e["event"] == "kill"]
        assert any(k.get("phase") == "sigkill" for k in kills)  # escalated
    finally:
        fleet.stop()


def test_e2e_rolling_restart_keeps_capacity_under_load(tmp_path):
    """Drain-aware rolling restart: every replica's pid changes, yet a
    closed loop running THROUGH the roll loses nothing and the router
    never sheds for lack of a backend — capacity stayed >= N-1."""
    fleet = _stub_fleet(tmp_path, n=2)
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, msg="fleet healthy")
        pids_before = [r.pid for r in fleet.replicas]
        result = {}

        def load():
            result.update(serve_bench.run_load(
                fleet.router.url, concurrency=8, total_requests=400,
                image_size=8, pool=4, timeout_s=15.0,
            ))

        loader = threading.Thread(target=load)
        loader.start()
        assert fleet.rolling_restart(timeout_s=60.0)
        loader.join(timeout=60.0)
        assert not loader.is_alive()
        assert result["lost"] == 0, result["lost_detail"]
        assert fleet.r_shed_no_backend == 0  # capacity never hit zero
        pids_after = [r.pid for r in fleet.replicas]
        assert all(a != b for a, b in zip(pids_before, pids_after))
        assert fleet.healthy_count() == 2
        events = [e["event"] for e in fleet.incidents]
        assert events.count("roll_replica") >= 4  # drain+done per replica
        assert "roll_end" in events
    finally:
        fleet.stop()


def test_e2e_kill_drill_32_clients_zero_lost(tmp_path):
    """THE acceptance drill: 32 closed-loop clients, SIGKILL one of two
    replicas mid-load → zero lost requests (the router's single retry
    absorbs the in-flight failures), the fleet restores N replicas, and
    every transition is a `kind:"fleet"` event under ONE run_id."""
    fleet = _stub_fleet(tmp_path, n=2)
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, msg="fleet healthy")
        victim_pid = fleet.replicas[0].pid
        killed = {}

        def killer():
            time.sleep(0.15)  # mid-load, not before it
            os.kill(victim_pid, signal.SIGKILL)
            killed["pid"] = victim_pid

        kt = threading.Thread(target=killer)
        kt.start()
        summary = serve_bench.run_load(
            fleet.router.url, concurrency=32, total_requests=1024,
            image_size=8, pool=4, timeout_s=15.0,
        )
        kt.join(timeout=5.0)
        assert killed["pid"] == victim_pid
        assert summary["lost"] == 0, summary["lost_detail"]
        assert summary["resolved"] == summary["sent"] == 1024
        assert summary["ok"] >= 1000  # at most a few structured sheds
        _wait(lambda: "killed" in fleet.replicas[0].classifications,
              msg="death observed and classified")
        _wait(lambda: fleet.healthy_count() == 2,
              msg="fleet restored to N replicas")
    finally:
        fleet.stop()

    # the whole story is one events.jsonl under one run_id, and the
    # report tool renders it from the DIRECTORY (telemetry satellite)
    events_path = os.path.join(str(tmp_path / "fleet_t"), "events.jsonl")
    records = [json.loads(ln) for ln in open(events_path)
               if ln.strip()]
    fleet_records = [r for r in records if r.get("kind") == "fleet"]
    assert {r["run_id"] for r in fleet_records} == {fleet.run_id}
    events = [r["event"] for r in fleet_records]
    for expected in ("fleet_start", "launch", "replica_exit",
                     "replica_healthy", "router_stats", "fleet_stop"):
        assert expected in events, expected
    pairs = telemetry_report.expand_events_arg(str(tmp_path / "fleet_t"))
    assert ("fleet", events_path) in pairs
    records, _ = telemetry_report.load_events_multi(pairs)
    summary = telemetry_report.summarize(records)
    flt = summary["fleet"]
    assert flt["size"] == 2
    assert flt["replicas"][0]["restarts"] >= 1
    assert "killed" in flt["replicas"][0]["classifications"]
    assert flt["router"]["requests"] >= 1024
    rendered = telemetry_report.render(summary)
    assert "fleet:" in rendered and "replica 0:" in rendered


def test_e2e_reload_roll_and_relaunch_convergence(tmp_path):
    """Watcher e2e against stub replicas: a verified step rolls across
    every replica via /admin/reload; a truncated later step is
    quarantined and never deployed; a replica KILLED after the roll
    comes back booted on the deployed payload (argv convergence)."""
    watch = tmp_path / "export"
    watch.mkdir()
    fleet = _stub_fleet(tmp_path, n=2, watch_dir=str(watch))
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, msg="fleet healthy")
        payload = _export_step(watch, 100)
        _wait(lambda: all(r.deployed_step == 100 for r in fleet.replicas),
              msg="reload rolled to both replicas")
        events = [e["event"] for e in fleet.incidents]
        assert "reload_detected" in events and "reload_done" in events
        assert events.count("reload_replica") == 2
        # each stub really swapped: /v1/embed now reports the new payload
        seen = set()
        for _ in range(8):
            _, resp = _post(fleet.router.url + "/v1/embed",
                            {"pixels": [1]})
            seen.add(resp["pretrained"])
        assert seen == {payload}

        # truncated later step: quarantined, target unchanged
        _export_step(watch, 200)
        truncate_checkpoint(str(watch), 200)
        _wait(lambda: any(e["event"] == "reload_quarantine"
                          for e in fleet.incidents),
              msg="truncated step quarantined")
        assert fleet._target_step == 100
        assert os.path.isdir(str(watch / ".quarantine" / "200"))

        # SIGKILL a replica: its relaunch must boot on the DEPLOYED
        # payload, not the boot-time weights
        os.kill(fleet.replicas[1].pid, signal.SIGKILL)
        _wait(lambda: "killed" in fleet.replicas[1].classifications,
              msg="death observed")
        _wait(lambda: fleet.healthy_count() == 2, msg="replica restored")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{fleet.replicas[1].port}/stats", timeout=5
        ) as r:
            stats = json.loads(r.read())
        assert stats["pretrained"] == payload
        assert fleet.replicas[1].deployed_step == 100
    finally:
        fleet.stop()


def test_serve_bench_fleet_mode_with_kill_drill(tmp_path):
    """The serve_bench satellite end to end: --fleet spawns
    tools/serve_fleet.py per replica count, parses the router url,
    SIGKILLs a replica via the router's /stats pids, and reports
    rps/p99/lost rows — lost stays 0 through the drill."""
    import sys as _sys

    stub = tmp_path / "stub_replica.py"
    stub.write_text(_STUB_REPLICA)
    rows = serve_bench.run_fleet_bench(
        [_sys.executable, str(stub)], counts=(2,),
        concurrency=16, total_requests=512, image_size=8, pool=4,
        timeout_s=15.0, kill_drill=True, kill_after_s=0.1,
        boot_timeout_s=60.0,
        fleet_args=["--health-stale-secs", "2",
                    "--term-grace-secs", "1"],
    )
    assert len(rows) == 1
    row = rows[0]
    assert "error" not in row, row
    assert row["replicas"] == 2
    assert row["lost"] == 0, row["lost_detail"]
    assert row["killed_pid"]  # the drill really fired
    assert row["throughput_rps"] > 0
    assert "p99" in row["latency_ms"]


# ---------------------------------------------------------------------------
# hot reload: in-process jax — swap bit-identical to a cold start
# ---------------------------------------------------------------------------

BUCKETS = (1, 4, 16)
SIZE = 32


@pytest.fixture(scope="module")
def two_exports(tmp_path_factory):
    """Two DIFFERENT tiny encoders exported in the torchvision dialect —
    checkpoint A serves first, checkpoint B hot-reloads over it."""
    import jax
    import jax.numpy as jnp

    from moco_tpu.checkpoint import _save_flat, resnet_to_torchvision
    from moco_tpu.models import build_backbone

    model = build_backbone("resnet_tiny", cifar_stem=True)
    root = tmp_path_factory.mktemp("exports")
    paths = []
    for seed in (0, 1):
        variables = model.init(
            jax.random.key(seed), jnp.zeros((1, SIZE, SIZE, 3)),
            train=False,
        )
        flat = resnet_to_torchvision(
            jax.tree.map(np.asarray, variables["params"]),
            jax.tree.map(np.asarray, variables.get("batch_stats", {})),
            prefix="module.encoder_q.",
        )
        path = str(root / f"encoder_{seed}.npz")
        _save_flat(flat, path)
        paths.append(path)
    return paths


def _engine_from(path):
    from moco_tpu.serve import EmbeddingEngine

    return EmbeddingEngine.from_checkpoint(
        path, "resnet_tiny", image_size=SIZE, cifar_stem=True,
        buckets=BUCKETS,
    )


def _imgs(n, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, SIZE, SIZE, 3)
    ).astype(np.uint8)


def test_reload_swap_bit_identical_to_cold_start(two_exports):
    """ISSUE 10 acceptance: after reload(B), served embeddings are
    BIT-identical to a freshly cold-started engine on checkpoint B; the
    content-hash cache is invalidated at the swap (old-weight rows must
    never answer for the new weights)."""
    from moco_tpu.serve import EmbedService

    path_a, path_b = two_exports
    service = EmbedService(_engine_from(path_a), flush_ms=2.0,
                           max_queue=32, request_deadline_ms=10_000.0,
                           cache_mb=4)
    service.set_engine_factory(_engine_from)
    try:
        img = _imgs(1, seed=7)[0]
        before, cached = service.embed(img)
        assert cached is False
        _, cached = service.embed(img)
        assert cached is True  # warmed the cache on the OLD weights

        entry = service.reload(path_b, step=123)
        assert entry["step"] == 123 and entry["warm_s"] >= 0.0

        after, cached = service.embed(img)
        assert cached is False  # cache cleared at the swap
        cold = _engine_from(path_b)
        cold.warmup()
        expected = cold.embed(img[None])[0]
        assert np.array_equal(after, expected)  # bit-identical
        assert not np.array_equal(after, before)  # weights really changed
        assert service.stats()["reloads"] == 1
        assert service.stats()["reload_history"][0]["step"] == 123
    finally:
        service.drain(timeout_s=10.0)


def test_reload_failure_keeps_old_weights_serving(two_exports):
    from moco_tpu.serve import EmbedService

    path_a, _ = two_exports
    service = EmbedService(_engine_from(path_a), flush_ms=2.0,
                           max_queue=32, request_deadline_ms=10_000.0)
    service.set_engine_factory(_engine_from)
    try:
        img = _imgs(1, seed=9)[0]
        before, _ = service.embed(img)
        with pytest.raises(ValueError, match="cannot load"):
            service.reload(path_a + ".does_not_exist")
        after, _ = service.embed(img)
        assert np.array_equal(before, after)  # old engine untouched
        assert service.reloads == 0
    finally:
        service.drain(timeout_s=10.0)


def test_reload_refused_on_ladder_change_and_knn_bank(two_exports):
    """Guards the swap's contracts: a factory producing a DIFFERENT
    bucket ladder would overflow live coalesced batches (the batcher
    still coalesces to the old ladder), and a configured kNN bank was
    computed by the OLD encoder — both refuse, old weights keep
    serving."""
    from moco_tpu.serve import EmbeddingEngine, EmbedService

    path_a, path_b = two_exports
    service = EmbedService(_engine_from(path_a), flush_ms=2.0,
                           max_queue=32, request_deadline_ms=10_000.0)

    def smaller_ladder(path):
        return EmbeddingEngine.from_checkpoint(
            path, "resnet_tiny", image_size=SIZE, cifar_stem=True,
            buckets=(1, 4),
        )

    service.set_engine_factory(smaller_ladder)
    try:
        with pytest.raises(ValueError, match="bucket ladder"):
            service.reload(path_b)
        assert service.reloads == 0
    finally:
        service.drain(timeout_s=10.0)

    engine = _engine_from(path_a)
    engine.warmup()
    bank = engine.embed(_imgs(8, seed=1))
    service = EmbedService(engine, flush_ms=2.0, max_queue=32,
                           request_deadline_ms=10_000.0,
                           knn_bank=bank, knn_labels=np.arange(8) % 2,
                           knn_k=3)
    service.set_engine_factory(_engine_from)
    try:
        # since ISSUE 16 the refusal is "never WITHOUT a verified paired
        # bank" and tells the operator what to build (the dual-swap path
        # itself is pinned in test_bank_lifecycle.py)
        with pytest.raises(ValueError, match="kNN bank") as e:
            service.reload(path_b)
        assert "tools/bank_build.py" in str(e.value)
        assert e.value.bank_step is None  # plain npz bank: no version
        # old weights (and the matching bank) still serve
        cls_id, _, _ = service.classify(_imgs(1, seed=2)[0])
        assert cls_id in (0, 1)
    finally:
        service.drain(timeout_s=10.0)


class _GatedStubEngine:
    """A jax-free engine stand-in whose embed() can be held closed —
    deterministic interleavings for the swap-vs-in-flight races."""

    image_size = 8
    buckets = (1, 4)

    def __init__(self, value, gate=None):
        self.value = float(value)
        self.gate = gate

    def warmup(self):
        return 2

    def embed(self, images_u8):
        if self.gate is not None and not self.gate.wait(timeout=10.0):
            raise RuntimeError("test gate never released")
        return np.full((len(images_u8), 2), self.value, np.float32)


def test_reload_does_not_let_inflight_old_rows_repopulate_cache():
    """A request whose batch executed on the OLD engine resolves AFTER
    the swap cleared the cache: its stale row must not be cached (a
    content-hash hit would then serve old-model embeddings forever)."""
    import threading as _threading

    from moco_tpu.serve import EmbedService

    gate = _threading.Event()
    old = _GatedStubEngine(1.0, gate=gate)
    # reload_probe=0: the drift guard (ISSUE 13) would block on the gated
    # old engine inside reload() and break this test's interleaving (and
    # the constant-row stub IS "collapsed" by construction)
    service = EmbedService(old, flush_ms=1.0, max_queue=16,
                           request_deadline_ms=30_000.0, cache_mb=4,
                           reload_probe=0)
    service.set_engine_factory(
        lambda path: _GatedStubEngine(2.0))
    try:
        img = np.zeros((8, 8, 3), np.uint8)
        result = {}

        def request():
            result["row"], result["cached"] = service.embed(img)

        t = _threading.Thread(target=request)
        t.start()
        time.sleep(0.3)  # the batch is now blocked INSIDE the old engine
        reloader = _threading.Thread(
            target=lambda: result.update(swap=service.reload("new")))
        reloader.start()
        time.sleep(0.3)
        gate.set()  # old-engine batch completes AFTER the swap
        t.join(timeout=10.0)
        reloader.join(timeout=10.0)
        assert result["row"][0] == 1.0  # the in-flight answer is honest
        # ... but the NEXT request must not hit a stale cache entry
        row, cached = service.embed(img)
        assert cached is False
        assert row[0] == 2.0  # new engine, not the old cached row
    finally:
        service.drain(timeout_s=10.0)


def test_reload_refusals_are_cheap_factory_never_called(two_exports):
    """The kNN-bank refusal must fire BEFORE the factory: a fleet's
    converge loop may re-attempt, and each late refusal would cost a
    full checkpoint load + ladder warmup on the serving replica."""
    from moco_tpu.serve import EmbedService

    path_a, path_b = two_exports
    engine = _engine_from(path_a)
    engine.warmup()
    bank = engine.embed(_imgs(4, seed=1))
    service = EmbedService(engine, flush_ms=2.0, max_queue=16,
                           request_deadline_ms=5_000.0,
                           knn_bank=bank, knn_labels=np.arange(4) % 2,
                           knn_k=3)

    def exploding_factory(path):
        raise AssertionError("factory must not run for a refused reload")

    service.set_engine_factory(exploding_factory)
    try:
        with pytest.raises(ValueError, match="kNN bank") as e:
            service.reload(path_b)
        # the 409 body's bank_step comes from the serving bank's
        # manifest; a plain npz bank has none
        assert e.value.bank_step is None
    finally:
        service.drain(timeout_s=5.0)


def test_fleet_409_refusal_is_terminal_not_retried(tmp_path):
    """A replica that answers 409 to /admin/reload (kNN bank, ladder
    change) must not be re-asked every pass — each attempt would make it
    load + warm a checkpoint just to refuse again."""
    refuse = _stub_backend(response={"error": "reload_refused",
                                     "detail": "kNN bank"}, status=409)
    fleet = _router_fleet(tmp_path, [refuse.server_address[1]])
    try:
        with fleet._lock:
            fleet._target_step, fleet._target_path = 7, "/x/encoder.npz"
        fleet._reload_sync()
        fleet._reload_sync()  # the converge loop coming around again
        r = fleet.replicas[0]
        assert r.reload_refused_step == 7
        assert r.deployed_step == -1
        fails = [e for e in fleet.incidents
                 if e["event"] == "reload_failed"]
        assert len(fails) == 1  # announced once, then terminal
        # the monitor's need_sync predicate now excludes it
        assert not (r.deployed_step < fleet._target_step
                    and r.reload_refused_step < fleet._target_step)
    finally:
        refuse.shutdown()

    # a TRANSIENT failure (503 reload_failed) must stay retryable: no
    # terminal mark, so the converge loop keeps trying
    flaky = _stub_backend(response={"error": "reload_failed",
                                    "detail": "NFS blip"}, status=503)
    fleet2 = _router_fleet(tmp_path / "f2", [flaky.server_address[1]])
    try:
        with fleet2._lock:
            fleet2._target_step, fleet2._target_path = 9, "/x/e.npz"
        fleet2._reload_sync()
        r = fleet2.replicas[0]
        assert r.reload_refused_step == -1  # NOT terminal
        assert (r.deployed_step < fleet2._target_step
                and r.reload_refused_step < fleet2._target_step)
    finally:
        flaky.shutdown()


def test_roll_skips_abandoned_replica_instead_of_wedging(tmp_path):
    """A replica abandoned after roll-begin will never come alive: the
    roll must skip it (and finish), not wait on it forever."""
    fleet = _stub_fleet(tmp_path, n=2)
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, msg="fleet healthy")
        pid1 = fleet.replicas[1].pid
        # the hazard is abandonment AFTER roll-begin (roll-begin already
        # filters): inject a roll whose queue still holds replica 0 and
        # abandon it — the monitor thread advances the roll from here
        with fleet._lock:
            fleet.replicas[0].abandoned = True
            fleet._roll = {"queue": [0, 1], "idx": None,
                           "phase": "await", "t": 0.0}
        _wait(lambda: any(e["event"] == "roll_end"
                          for e in fleet.incidents),
              timeout_s=30.0, msg="roll completed despite abandonment")
        assert fleet.replicas[1].pid != pid1  # replica 1 really rolled
        skipped = [e for e in fleet.incidents
                   if e["event"] == "roll_replica"
                   and e.get("phase") == "skipped"]
        assert skipped and skipped[0]["replica"] == 0
    finally:
        fleet.stop()


def test_reload_unconfigured_raises():
    import jax
    import jax.numpy as jnp

    from moco_tpu.models import build_backbone
    from moco_tpu.serve import EmbeddingEngine, EmbedService

    model = build_backbone("resnet_tiny", cifar_stem=True)
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, SIZE, SIZE, 3)), train=False
    )
    engine = EmbeddingEngine(model, variables["params"],
                             variables.get("batch_stats", {}),
                             image_size=SIZE, buckets=(1, 4))
    service = EmbedService(engine, flush_ms=2.0, max_queue=16,
                           request_deadline_ms=5_000.0)
    try:
        with pytest.raises(ValueError, match="not configured"):
            service.reload("whatever.npz")
    finally:
        service.drain(timeout_s=5.0)


def test_admin_reload_http_contract(two_exports):
    """POST /admin/reload over the wire: 400 on a bad body, 409 with the
    reason on a bad checkpoint, 200 + swapped weights on a good one —
    and the swap is visible in served embeddings immediately."""
    from moco_tpu.serve import EmbedService, ServeFrontend

    path_a, path_b = two_exports
    service = EmbedService(_engine_from(path_a), flush_ms=2.0,
                           max_queue=32, request_deadline_ms=10_000.0)
    service.set_engine_factory(_engine_from)
    frontend = ServeFrontend(service, port=0)
    frontend.start()
    try:
        status, resp = _post(frontend.url + "/admin/reload", {})
        assert status == 400 and resp["error"] == "bad_request"
        # a malformed step is the CLIENT's bug: 400, never mis-bucketed
        # as a 409 checkpoint failure
        status, resp = _post(frontend.url + "/admin/reload",
                             {"pretrained": path_b, "step": "abc"})
        assert status == 400 and resp["error"] == "bad_request"
        # a load failure is possibly TRANSIENT: 503 reload_failed (the
        # fleet retries), never the terminal 409
        status, resp = _post(frontend.url + "/admin/reload",
                             {"pretrained": "/nope.npz"})
        assert status == 503 and resp["error"] == "reload_failed"
        status, resp = _post(frontend.url + "/admin/reload",
                             {"pretrained": path_b, "step": 7})
        assert status == 200 and resp["status"] == "reloaded"
        assert resp["step"] == 7

        img = _imgs(1, seed=11)[0]
        body = {"image_b64": base64.b64encode(img.tobytes()).decode(),
                "shape": list(img.shape)}
        status, resp = _post(frontend.url + "/v1/embed", body)
        assert status == 200
        cold = _engine_from(path_b)
        cold.warmup()
        assert np.array_equal(
            np.asarray(resp["embedding"], np.float32),
            cold.embed(img[None])[0],
        )
    finally:
        service.drain(timeout_s=10.0)
        frontend.shutdown()


# ---------------------------------------------------------------------------
# the full soak: real serve.py replicas, kill drill + watcher hot reload
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_soak_real_replicas_kill_and_hot_reload(two_exports,
                                                      tmp_path):
    """ISSUE 10 acceptance, full stack: 2 REAL tools/serve.py replicas
    under the fleet; closed-loop load survives a replica SIGKILL with
    zero lost; a new manifested checkpoint dropped into the watch dir
    rolls across the fleet with zero dropped requests and embeddings
    bit-identical to a fresh engine on it; a truncated checkpoint is
    quarantined and never loaded."""
    import sys as _sys

    path_a, path_b = two_exports
    watch = tmp_path / "export"
    watch.mkdir()
    serve_py = os.path.join(REPO, "tools", "serve.py")

    def child_argv(index, port, tdir, pretrained):
        argv = [_sys.executable, "-u", serve_py,
                "--pretrained", pretrained or path_a,
                "--arch", "resnet_tiny", "--image-size", str(SIZE),
                "--cifar-stem", "true", "--buckets", "1", "4", "16",
                "--flush-ms", "5.0",
                "--port", str(port), "--telemetry-dir", tdir,
                "--snapshot-every", "5"]
        return argv

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MOCO_TPU_NO_CACHE="1")
    fleet = FleetSupervisor(
        child_argv, replicas=2, telemetry_dir=str(tmp_path / "fleet_t"),
        watch_dir=str(watch), env=env,
        policy=FleetPolicy(
            probe_secs=0.2, probe_timeout_s=2.0, health_stale_secs=10.0,
            startup_grace_secs=240.0, term_grace_secs=5.0,
            backoff_base_secs=0.2, backoff_max_secs=1.0,
            watch_poll_secs=0.2, reload_timeout_s=240.0,
        ), seed=0,
    )
    fleet.start()
    try:
        _wait(lambda: fleet.healthy_count() == 2, timeout_s=240.0,
              msg="2 real replicas healthy")
        # 1) kill drill under 32-client closed loop
        victim = fleet.replicas[0].pid

        def killer():
            time.sleep(0.5)
            os.kill(victim, signal.SIGKILL)

        kt = threading.Thread(target=killer)
        kt.start()
        summary = serve_bench.run_load(
            fleet.router.url, concurrency=32, total_requests=256,
            image_size=SIZE, pool=8, timeout_s=60.0,
        )
        kt.join()
        assert summary["lost"] == 0, summary["lost_detail"]
        _wait(lambda: fleet.healthy_count() == 2, timeout_s=240.0,
              msg="killed replica restored")

        # 2) truncated checkpoint: quarantined, never loaded
        step_dir = watch / "50"
        step_dir.mkdir()
        import shutil
        shutil.copy(path_b, step_dir / "encoder.npz")
        write_manifest(str(watch), 50)
        truncate_checkpoint(str(watch), 50)
        _wait(lambda: any(e["event"] == "reload_quarantine"
                          for e in fleet.incidents), timeout_s=30.0,
              msg="truncated step quarantined")
        assert all(r.deployed_step == -1 for r in fleet.replicas)

        # 3) valid checkpoint: detected, verified, rolled — zero dropped
        step_dir = watch / "60"
        step_dir.mkdir()
        shutil.copy(path_b, step_dir / "encoder.npz")
        write_manifest(str(watch), 60)
        result = {}

        def load():
            result.update(serve_bench.run_load(
                fleet.router.url, concurrency=8, total_requests=128,
                image_size=SIZE, pool=8, timeout_s=60.0,
            ))

        loader = threading.Thread(target=load)
        loader.start()
        _wait(lambda: all(r.deployed_step == 60 for r in fleet.replicas),
              timeout_s=240.0, msg="reload rolled across the fleet")
        loader.join(timeout=120.0)
        assert result["lost"] == 0, result["lost_detail"]

        # 4) bit-identity: the fleet now answers exactly like a fresh
        # engine cold-started on checkpoint B
        img = _imgs(1, seed=3)[0]
        body = {"image_b64": base64.b64encode(img.tobytes()).decode(),
                "shape": list(img.shape)}
        status, resp = _post(fleet.router.url + "/v1/embed", body,
                             timeout=60.0)
        assert status == 200
        cold = _engine_from(path_b)
        cold.warmup()
        assert np.array_equal(
            np.asarray(resp["embedding"], np.float32),
            cold.embed(img[None])[0],
        )
        events = [e["event"] for e in fleet.incidents]
        assert "reload_done" in events
    finally:
        fleet.stop()
