"""progcheck: the jaxpr-level program auditor (ISSUE 9).

Three layers, mirroring the acceptance criteria:

- every shipped check has a SEEDED-VIOLATION fixture proving it fires
  (incl. the removed key-encoder stop_gradient and a double-reduced
  gradient), plus a clean negative;
- golden invariant-summary snapshots for train/v3 across all four
  grad_sync modes: a refactor that changes collective count/payload or
  the donation contract diffs loudly against the committed file;
- THE tier-1 gate: `python -m tools.progcheck --json` runs clean over
  the full surface (train/v3 all modes + serve buckets + probes +
  gradsync + trim variants + evals) on the CPU backend inside the 60 s
  budget.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from moco_tpu.parallel.mesh import DATA_AXIS  # noqa: E402
from moco_tpu.utils.compat import shard_map  # noqa: E402
from tools.progcheck.engine import Engine  # noqa: E402
from tools.progcheck.inventory import (  # noqa: E402
    golden_json,
    inventory_json,
)
from tools.progcheck.surface import build_surface  # noqa: E402

MESHMETA = {"mesh_axes": ("data",)}


def _record(name, closed, family="train", donated=None, meta=None):
    from tools.progcheck.inventory import make_record

    return make_record(name, family, None, closed, donated=donated,
                       meta={**MESHMETA, **(meta or {})})


def _run(rec, check_id):
    return Engine(select=(check_id,)).run(
        rec if isinstance(rec, list) else [rec]).findings


@pytest.fixture(scope="module")
def probe_records(mesh8):
    return build_surface(mesh=mesh8, families=("probe",), with_cost=False)


@pytest.fixture(scope="module")
def gradsync_records(mesh8):
    return build_surface(mesh=mesh8, families=("gradsync",), with_cost=False)


# ---------------------------------------------------------------------------
# P1: gradient flow into the key encoder / queue
# ---------------------------------------------------------------------------


def test_p1_clean_on_real_probes(probe_records):
    assert [r.name for r in probe_records] == ["probe/train", "probe/v3"]
    for rec in probe_records:
        assert _run(rec, "P1") == [], rec.name


def test_p1_fires_when_key_stop_gradient_removed(mesh8, monkeypatch):
    """THE seeded violation the ISSUE names: delete the key-branch
    stop_gradient (via a patched key path — the production helper minus
    its last stop_gradient) and the auditor must see gradient flow into
    params_k AND the queue."""
    import moco_tpu.train_step as ts
    from moco_tpu.ops.losses import l2_normalize
    from moco_tpu.parallel.collectives import batch_shuffle, batch_unshuffle

    def broken_key_path(config, model):
        def key_path(params_k, stats_k, im_k, key):
            im_k_shuf, perm = batch_shuffle(im_k, key, DATA_AXIS)
            k, mut_k = model.apply(
                {"params": params_k, "batch_stats": stats_k},
                im_k_shuf, train=True, mutable=["batch_stats"],
            )
            k = l2_normalize(k)
            k = batch_unshuffle(k, perm, DATA_AXIS)
            return k, mut_k["batch_stats"]  # stop_gradient DELETED

        return key_path

    monkeypatch.setattr(ts, "_build_key_path", broken_key_path)
    from tools.progcheck.surface import _probe_records

    rec = [r for r in _probe_records(mesh8) if r.name == "probe/train"][0]
    findings = _run(rec, "P1")
    assert findings, "P1 missed the removed stop_gradient"
    msgs = " ".join(f.message for f in findings)
    assert "params_k" in msgs
    # the queue grads stay zero: infonce_logits stop-grads the queue
    # ITSELF (defense in depth) — only the key-encoder path leaks here
    assert "queue" not in msgs


def test_p1_fires_when_v3_momentum_stop_gradient_removed(mesh8, monkeypatch):
    """v3 has TWO stop_gradients on the key path — one in _build_momentum_
    keys, one inside v3_contrastive_loss (defense in depth). The seeded
    violation removes both; P1 must still catch the leak."""
    import moco_tpu.v3_step as v3
    from moco_tpu.ops import losses

    def broken_momentum_keys(model):
        apply = v3._build_apply(model)

        def momentum_keys(params_k, stats_k, x1, x2):
            k1, stats_k = apply(params_k, stats_k, x1, predict=False)
            k2, stats_k = apply(params_k, stats_k, x2, predict=False)
            return k1, k2, stats_k  # stop_gradients DELETED

        return momentum_keys

    def broken_v3_loss(q, k, temperature, axis_name, chunks=1):
        # v3_contrastive_loss minus its own `k = stop_gradient(k)`
        from moco_tpu.parallel.collectives import all_gather_batch

        if axis_name is not None:
            k_all = all_gather_batch(k, axis_name)
            offset = lax.axis_index(axis_name) * q.shape[0]
        else:
            k_all, offset = k, 0
        logits = jnp.einsum("nc,mc->nm", q, k_all,
                            preferred_element_type=jnp.float32) / temperature
        labels = jnp.arange(q.shape[0], dtype=jnp.int32) + offset
        return losses.softmax_cross_entropy(logits, labels) * (
            2.0 * temperature)

    monkeypatch.setattr(v3, "_build_momentum_keys", broken_momentum_keys)
    monkeypatch.setattr(v3, "v3_contrastive_loss", broken_v3_loss)
    from tools.progcheck.surface import _probe_records

    rec = [r for r in _probe_records(mesh8) if r.name == "probe/v3"][0]
    findings = _run(rec, "P1")
    assert findings and "params_k" in findings[0].message


def test_p1_flags_vacuous_probe(mesh8):
    """A probe whose 'flow' grads are constants is auditing nothing."""
    def region(x):
        return lax.pmean(jnp.zeros((4,)), DATA_AXIS)

    fn = shard_map(region, mesh=mesh8, in_specs=(P(DATA_AXIS),),
                   out_specs=P())
    rec = _record("fix/vacuous", jax.make_jaxpr(fn)(jnp.zeros((16, 4))),
                  family="probe",
                  meta={"flow_groups": [("params_q", 0, 1)],
                        "zero_groups": []})
    findings = _run(rec, "P1")
    assert findings and "vacuous" in findings[0].message


# ---------------------------------------------------------------------------
# P2/P3: collective axis hygiene
# ---------------------------------------------------------------------------


def test_p2_flags_axis_missing_from_mesh(mesh8):
    def region(x):
        return lax.pmean(x, DATA_AXIS)

    fn = shard_map(region, mesh=mesh8, in_specs=(P(DATA_AXIS),),
                   out_specs=P())
    rec = _record("fix/axis", jax.make_jaxpr(fn)(jnp.zeros((16, 4))),
                  meta={"mesh_axes": ("model",)})  # program/mesh forked
    findings = _run(rec, "P2")
    assert findings and "'data'" in findings[0].message


def test_p2_p7_clean_on_resized_mesh_programs(mesh8):
    """ISSUE 11 satellite: the elastic relaunch's 2-device step programs
    are part of the audited surface — their collectives bind to the
    RESIZED mesh (P2) and the donation contract survives the rebuild
    (P7). The quantized record carries the [2, ...] accumulator leaves
    the dialect shim rebuilds fresh-zero at a mesh hop."""
    records = build_surface(mesh=mesh8, families=("resize",),
                            with_cost=False)
    assert [r.name for r in records] == ["resize/fused@2dev",
                                         "resize/quantized@2dev"]
    for rec in records:
        assert rec.meta["mesh_size"] == 2
        assert _run(rec, "P2") == [], rec.name
        assert _run(rec, "P7") == [], rec.name


def test_p3_fires_on_double_reduced_gradient(mesh8):
    """The ISSUE's second named fixture: grads pmean'd inline BEFORE the
    gradsync reduce — the classic silently-rescaled-gradient regression."""
    from moco_tpu.config import PretrainConfig
    from moco_tpu.parallel.gradsync import GradSync

    gs = GradSync(PretrainConfig(arch="resnet_tiny", cifar_stem=True,
                                 batch_size=16, epochs=1, lr=0.1), 8)

    def region(params, x, step):
        grads = jax.grad(lambda p: jnp.sum((x @ p) ** 2))(params)
        grads = lax.pmean(grads, DATA_AXIS)        # seeded double reduce
        reduced, _, _ = gs.region_reduce({"w": grads}, {}, step)
        return reduced

    fn = shard_map(region, mesh=mesh8,
                   in_specs=(P(), P(DATA_AXIS), P()), out_specs=P())
    closed = jax.make_jaxpr(fn)(jnp.zeros((4, 4)), jnp.zeros((16, 4)),
                                jnp.int32(0))
    findings = _run(_record("fix/double_grad", closed), "P3")
    assert findings and "reduced" in findings[0].message


def test_p3_clean_on_single_reduce_and_real_steps(mesh8, gradsync_records):
    def region(x):
        return lax.pmean(x, DATA_AXIS)

    fn = shard_map(region, mesh=mesh8, in_specs=(P(DATA_AXIS),),
                   out_specs=P())
    rec = _record("fix/single", jax.make_jaxpr(fn)(jnp.zeros((16, 4))))
    assert _run(rec, "P3") == []
    for rec in gradsync_records:  # chained/quantized psums are NOT double
        assert _run(rec, "P3") == [], rec.name


# ---------------------------------------------------------------------------
# P4/P5: dtype policy
# ---------------------------------------------------------------------------


def test_p4_flags_averaged_integer_reduce(mesh8):
    def region(x):
        return lax.psum(x, DATA_AXIS) / 8

    fn = shard_map(region, mesh=mesh8, in_specs=(P(DATA_AXIS),),
                   out_specs=P())
    closed = jax.make_jaxpr(fn)(jnp.zeros((16, 4), jnp.int32))
    findings = _run(_record("fix/intavg", closed), "P4")
    assert findings and "never averaged" in findings[0].message


def test_p5_flags_bf16_widened_before_reduce(mesh8):
    def region(x):
        return lax.psum(x.astype(jnp.float32), DATA_AXIS)

    fn = shard_map(region, mesh=mesh8, in_specs=(P(DATA_AXIS),),
                   out_specs=P())
    closed = jax.make_jaxpr(fn)(jnp.zeros((16, 4), jnp.bfloat16))
    findings = _run(_record("fix/widen", closed), "P5")
    assert findings and "bfloat16 -> float32" in findings[0].message


def test_p4_p5_clean_on_real_gradsync(gradsync_records):
    for rec in gradsync_records:
        assert _run(rec, "P4") == [], rec.name
        assert _run(rec, "P5") == [], rec.name


# ---------------------------------------------------------------------------
# P6: host callbacks
# ---------------------------------------------------------------------------


def test_p6_flags_debug_print_in_step():
    @jax.jit
    def step(x):
        jax.debug.print("loss={x}", x=x[0])
        return x * 2

    closed = jax.make_jaxpr(step)(jnp.zeros((4,)))
    findings = _run(_record("fix/callback", closed), "P6")
    assert findings and "debug_callback" in findings[0].message


# ---------------------------------------------------------------------------
# P7: donation aliasing
# ---------------------------------------------------------------------------


def test_p7_flags_unaliasable_donation():
    import functools
    import warnings

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(x):
        return jnp.concatenate([x, x])  # no [4]-shaped output to alias

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed = jax.make_jaxpr(step)(jnp.zeros((4,)))
    donated = closed.jaxpr.eqns[0].params["donated_invars"]
    findings = _run(_record("fix/donate", closed, donated=donated), "P7")
    assert findings and "degrades to a copy" in findings[0].message


# ---------------------------------------------------------------------------
# P8: gradsync wire bytes vs telemetry claim
# ---------------------------------------------------------------------------


def test_p8_clean_on_all_modes(gradsync_records):
    # "quantized@2d" (ISSUE 15) is the DynamiQ multi-hop reduce over the
    # 2-D mesh — P8 verifies its per-hop bytes sum to the analytic claim
    assert sorted(r.mode for r in gradsync_records) == [
        "bucketed", "demo", "fused", "quantized", "quantized@2d"]
    for rec in gradsync_records:
        assert _run(rec, "P8") == [], rec.name


def test_p8_fires_when_program_moves_extra_bytes(mesh8):
    """Sabotage: the region psums a tensor the analytic accounting does
    not know about — the jaxpr payload and the telemetry claim diverge."""
    from moco_tpu.config import PretrainConfig
    from moco_tpu.parallel.gradsync import GradSync

    gs = GradSync(PretrainConfig(arch="resnet_tiny", cifar_stem=True,
                                 batch_size=16, epochs=1, lr=0.1), 8)
    params = {"w": jnp.zeros((64,), jnp.float32)}
    fn, args, payload = gs.audit_region_program(params, mesh8)

    def smuggling(grads, state, step):
        reduced, new_state = fn(grads, state, step)
        extra = shard_map(lambda z: lax.psum(z, DATA_AXIS), mesh=mesh8,
                          in_specs=(P(DATA_AXIS),), out_specs=P())(
            jnp.zeros((16, 4)))
        return jax.tree.map(lambda g: g + 0 * extra.sum(), reduced), new_state

    closed = jax.make_jaxpr(smuggling)(*args)
    rec = _record("gradsync/fused", closed, family="gradsync",
                  meta={"gradsync": gs, "payload_shape": payload,
                        "mesh_size": mesh8.size})
    findings = _run(rec, "P8")
    assert findings and "drifted" in findings[0].message


# ---------------------------------------------------------------------------
# P9: bounded compile set
# ---------------------------------------------------------------------------


def test_p9_flags_shape_outside_the_ladder(mesh8):
    def make(n):
        closed = jax.make_jaxpr(lambda x: x * 2)(jnp.zeros((n, 4)))
        return _record(f"serve/bucket{n}", closed, family="serve",
                       meta={"max_programs": 2})

    clean = [make(1), make(8)]
    assert _run(clean, "P9") == []
    findings = _run(clean + [make(32)], "P9")
    assert findings and "no longer closed" in findings[0].message


# ---------------------------------------------------------------------------
# golden invariant snapshots (satellite)
# ---------------------------------------------------------------------------


GOLDEN_PATH = os.path.join(REPO, "tools", "progcheck",
                           "golden_invariants.json")


def test_golden_invariant_summaries_match_committed(mesh8):
    """Collective count/shape/payload and the donation contract of the
    train and v3 steps, across ALL FOUR grad_sync modes, pinned against
    tools/progcheck/golden_invariants.json. A refactor that changes any
    of it must regenerate the golden deliberately:

        python -m tools.progcheck --families train,v3 --no-flops \\
            --write-golden tools/progcheck/golden_invariants.json
    """
    records = build_surface(mesh=mesh8, families=("train", "v3"),
                            with_cost=False)
    # JSON-normalize (tuples -> lists) so current compares to committed
    current = json.loads(json.dumps(golden_json(records, mesh8.size)))
    with open(GOLDEN_PATH, encoding="utf-8") as f:
        committed = json.load(f)
    assert sorted(current["programs"]) == sorted(committed["programs"])
    for name in sorted(current["programs"]):
        assert current["programs"][name] == committed["programs"][name], (
            f"{name}: program invariants drifted from the golden — if the "
            "change is intentional, regenerate (see docstring)"
        )


# ---------------------------------------------------------------------------
# THE tier-1 gate + inventory/report fold (satellite)
# ---------------------------------------------------------------------------


def test_repo_gate_full_surface_clean_within_budget(tmp_path):
    """ISSUE 9 acceptance: the gate runs clean over train/v3 (all four
    grad_sync modes) + serve bucket programs (+ probes, gradsync, trim
    variants, evals) on the CPU backend in < 60 s, and the inventory it
    writes feeds telemetry_report's --programs fold."""
    inv_path = str(tmp_path / "inventory.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.progcheck", "--json",
         "--inventory", inv_path],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert elapsed < 60.0, f"progcheck gate took {elapsed:.1f}s"
    out = json.loads(proc.stdout)
    assert out["findings"] == []
    names = {p["name"] for p in out["inventory"]["programs"]}
    for fam in ("train", "v3"):
        for mode in ("fused", "bucketed", "quantized", "demo"):
            assert f"{fam}/{mode}" in names
    assert {"serve/bucket1", "serve/bucket8", "serve/bucket32",
            "serve/bucket128"} <= names
    assert {"probe/train", "probe/v3"} <= names
    # ISSUE 11: the resized-mesh step programs (the elastic 1→2 relaunch's
    # compiles) are part of the audited surface, so P2 pins their
    # collectives to the 2-device mesh
    assert {"resize/fused@2dev", "resize/quantized@2dev"} <= names

    inv = json.load(open(inv_path))
    assert inv["program_count"] == len(names)
    # the fold telemetry_report --programs performs
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(REPO, "tools",
                                         "telemetry_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)
    summary = report.fold_programs({"steps": 0}, inv)
    assert summary["programs"]["count"] == inv["program_count"]
    assert set(summary["programs"]["gradsync_bytes_per_step"]) == {
        "fused", "bucketed", "quantized", "demo", "quantized@2d"}
    cross = summary["programs"].get("mfu_cross_check", [])
    assert cross, "no mfu_cross_check rows (cost_analysis unavailable?)"
    # v1 proxy: the backbone the analytic model counts IS the program's
    # dominant compute — the two counts must agree within 2x. The v3
    # proxy's 4096-wide projector/predictor MLPs (which mfu.py documents
    # as uncounted) dwarf the tiny backbone, so its ratio only has to be
    # finite and positive here; at real scale the backbone dominates.
    for row in cross:
        assert row["ratio"] > 0, row
        if row["name"].startswith("train/"):
            assert 0.5 < row["ratio"] < 2.0, row


def test_inventory_json_shape(gradsync_records, mesh8):
    inv = inventory_json(gradsync_records, mesh8.size)
    assert inv["version"] == 1 and inv["by_family"] == {"gradsync": 5}
    rec = inv["programs"][0]
    assert {"name", "family", "collectives", "collective_bytes",
            "in_avals"} <= set(rec)
    assert all(c["axes"] == ["data"] or c["axes"] == ("data",)
               for c in rec["collectives"])


def test_cli_list_checks():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.progcheck", "--list-checks"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0
    for cid in ("P1", "P2", "P3", "P4", "P5", "P6", "P7", "P8", "P9"):
        assert cid in proc.stdout
