"""Telemetry subsystem suite (ISSUE 2): schema round-trip through the real
offline report, MFU against hand-computed ResNet-18 FLOPs, phase-timer
monotonicity + stride fencing, the 30-step acceptance smoke through the
real train() driver, and a chaos scenario asserting a rollback lands a
structured incident in events.jsonl."""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from moco_tpu.config import get_preset
from moco_tpu.telemetry import (
    SCHEMA_VERSION,
    Heartbeat,
    MetricsRegistry,
    MFUEstimator,
    StepPhaseTimer,
    detect_peak_flops,
    model_fwd_flops,
    percentiles_ms,
    resnet_fwd_flops,
    train_step_flops,
    vit_fwd_flops,
)
from moco_tpu.utils import logging as mlog
from moco_tpu.utils.meters import Throughput

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "tools", "telemetry_report.py")

_spec = importlib.util.spec_from_file_location("telemetry_report", REPORT)
report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(report)


# ---------------------------------------------------------------------------
# registry / sink
# ---------------------------------------------------------------------------


def test_registry_instruments_typed(tmp_path):
    reg = MetricsRegistry(str(tmp_path / "events.jsonl"))
    c = reg.counter("incidents")
    c.inc()
    c.inc(2)
    assert c.value == 3
    assert reg.counter("incidents") is c  # get-or-create
    g = reg.gauge("hbm")
    g.set(10)
    g.set(4)
    assert g.value == 4.0 and g.high_water == 10.0
    h = reg.histogram("step_s")
    for v in (5.0, 1.0, 3.0, 2.0, 4.0):
        h.observe(v)
    assert h.count == 5 and h.max == 5.0 and h.mean == 3.0
    assert h.percentile(0) == 1.0 and h.percentile(50) == 3.0
    assert h.percentile(100) == 5.0
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("incidents")
    reg.close()


def test_jsonl_roundtrip_through_report(tmp_path):
    """write → flush → tools/telemetry_report parse: the full schema loop."""
    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=3)
    reg.emit("run_start", name="t", variant="v2", arch="resnet18",
             batch_size=32, n_chips=8, n_procs=1,
             peak_flops_per_chip=1e12, flops_per_step=1e9)
    for step in range(1, 11):
        reg.emit("step", step=step, step_s=0.1 * step, data_s=0.01,
                 host_s=0.02, imgs_per_sec=100.0, mfu=0.5)
    reg.emit("event", event="rollback", msg="injected")
    reg.close()

    # a torn tail (SIGKILL mid-flush) must be skipped, not fatal
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "step", "trunc')

    records, skipped = report.load_events(path)
    assert skipped == 1
    assert all(r["v"] == SCHEMA_VERSION for r in records)
    summary = report.summarize(records, skipped)
    assert summary["steps"] == 10
    assert summary["incidents"] == {"rollback": 1}
    # nearest-rank over 0.1..1.0
    assert summary["step_time_ms"]["p50"] == pytest.approx(500.0)
    assert summary["step_time_ms"]["p99"] == pytest.approx(1000.0)
    assert summary["mfu"]["mean"] == pytest.approx(0.5)
    rendered = report.render(summary)
    assert "p50" in rendered and "MFU" in rendered and "rollback" in rendered


def test_registry_flush_cadence(tmp_path):
    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=4)
    flushes = [reg.emit("step", step=i) for i in range(6)]
    # 4th record flushes; the 2 after it sit in the buffer until close
    assert flushes == [False, False, False, True, False, False]
    records, _ = report.load_events(path)
    assert len(records) == 4
    reg.close()
    records, _ = report.load_events(path)
    assert len(records) == 6


def test_null_sink_registry_aggregates_without_writing(tmp_path):
    """Non-main pod hosts: instruments work, nothing lands on disk, and the
    record buffer stays bounded (dropped at the flush cadence)."""
    reg = MetricsRegistry(None, flush_every=2)
    for i in range(100):
        reg.emit("step", step=i)
    reg.histogram("step_s").observe(1.0)
    assert len(reg._buffer) < 2
    reg.close()


def test_reopen_after_torn_tail_starts_fresh_line(tmp_path):
    """A resumed run appending to an events.jsonl whose last line was torn
    by a SIGKILL mid-flush must not weld its run_start onto the fragment —
    only the torn fragment may be lost, never the new record."""
    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=1)
    reg.emit("step", step=1)
    reg.close()
    with open(path, "a") as f:
        f.write('{"v": 1, "kind": "step", "tor')  # no trailing newline

    resumed = MetricsRegistry(path, flush_every=1)
    resumed.emit("run_start", name="resumed")
    resumed.close()
    records, skipped = report.load_events(path)
    assert skipped == 1  # the fragment, and ONLY the fragment
    assert [r["kind"] for r in records] == ["step", "run_start"]


def test_nonfinite_and_foreign_scalars_stay_valid_json(tmp_path):
    """A diverged loss (the record that documents an incident!) must not
    produce a bare `NaN` line that RFC-8259 consumers reject; numpy
    scalars (not `float` subclasses) go through the same check."""
    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=1)
    reg.emit("step", step=1, loss=float("nan"), lr=np.float32("inf"),
             n=np.int64(7), nested={"x": [float("-inf"), 2.0]})
    reg.close()
    with open(path) as f:
        line = f.read().strip()
    rec = json.loads(line)  # strict json: parse must succeed
    assert "NaN" not in line and "Infinity" not in line
    assert rec["loss"] == "nan" and rec["lr"] == "inf" and rec["n"] == 7
    assert rec["nested"]["x"] == ["-inf", 2.0]


def test_registry_emit_is_thread_safe(tmp_path):
    """log_event sinks fire from the watchdog/prefetcher threads while the
    step loop emits: no record may be lost or torn across a flush race."""
    import threading

    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=3)  # frequent buffer swaps

    def spam(tid):
        for i in range(200):
            reg.emit("event", event="stress", tid=tid, i=i)

    threads = [threading.Thread(target=spam, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    reg.close()
    records, skipped = report.load_events(path)
    assert skipped == 0
    assert len(records) == 800
    seen = {(r["tid"], r["i"]) for r in records}
    assert len(seen) == 800  # nothing lost, nothing duplicated


def test_heartbeat_atomic_and_parseable(tmp_path):
    hb = Heartbeat(str(tmp_path / "telemetry" / "heartbeat.json"))
    hb.beat(7, phase="run_start")
    with open(hb.path) as f:
        payload = json.load(f)
    assert payload["step"] == 7 and payload["pid"] == os.getpid()
    assert payload["v"] == SCHEMA_VERSION
    t_first = payload["t"]
    hb.beat(9)
    with open(hb.path) as f:
        payload = json.load(f)
    assert payload["step"] == 9 and payload["t"] >= t_first
    assert not os.path.exists(hb.path + ".tmp")


def test_heartbeat_maybe_beat_time_gated(tmp_path):
    """maybe_beat honors min_interval_secs (the per-step call must not pay
    an atomic replace per 100 ms step); beat() always writes (lifecycle
    transitions are never elided)."""
    hb = Heartbeat(str(tmp_path / "heartbeat.json"), min_interval_secs=60.0)
    assert hb.maybe_beat(1, phase="step")      # first write always lands
    assert not hb.maybe_beat(2, phase="step")  # gated: way inside 60 s
    with open(hb.path) as f:
        assert json.load(f)["step"] == 1
    hb.beat(3, phase="run_end")                # forced lifecycle write
    with open(hb.path) as f:
        assert json.load(f)["step"] == 3
    ungated = Heartbeat(str(tmp_path / "hb2.json"), min_interval_secs=0.0)
    assert ungated.maybe_beat(1) and ungated.maybe_beat(2)


def test_on_step_beats_every_step_decoupled_from_flush(tmp_path, mesh8):
    """ISSUE 4 satellite: the heartbeat used to advance only when the sink
    flushed, making hang-detection granularity an accident of
    telemetry_flush_steps. It now beats every step (time-gated), with the
    step + phase fields the supervisor's progress check reads."""
    from moco_tpu.telemetry import RunTelemetry

    config = get_preset("cifar10-moco-v1").replace(
        telemetry_dir=str(tmp_path), telemetry_flush_steps=10_000,
        heartbeat_secs=0.0, telemetry_stride=0,
    )
    tel = RunTelemetry(config, n_chips=8, n_procs=1, process_index=0,
                       steps_per_epoch=4)
    try:
        thr = Throughput(8, window=4)
        thr.update(16)
        phases = {"step_s": 0.01, "data_s": 0.001, "host_s": 0.001}
        hb_path = os.path.join(str(tmp_path), "heartbeat.json")
        for step in (1, 2, 3):
            flushed = tel.on_step(step, dict(phases), thr)
            assert not flushed  # flush cadence never reached …
            with open(hb_path) as f:
                payload = json.load(f)
            assert payload["step"] == step  # … yet every step beat
            assert payload["phase"] == "step"
            assert payload["pid"] == os.getpid()
    finally:
        tel.close(last_step=3)
    with open(hb_path) as f:
        final = json.load(f)
    assert final["phase"] == "run_end" and final["step"] == 3


def test_close_preempted_marks_heartbeat_phase(tmp_path, mesh8):
    """The emergency-exit path stamps phase=preempt_exit with the last
    completed step + pid, so the supervisor can tell 'relaunch me' from a
    natural end without scraping logs (ISSUE 4 satellite)."""
    from moco_tpu.telemetry import RunTelemetry

    config = get_preset("cifar10-moco-v1").replace(
        telemetry_dir=str(tmp_path), heartbeat_secs=0.0)
    tel = RunTelemetry(config, n_chips=8, n_procs=1, process_index=0,
                       steps_per_epoch=4)
    tel.close(last_step=7, preempted=True)
    with open(os.path.join(str(tmp_path), "heartbeat.json")) as f:
        payload = json.load(f)
    assert payload["phase"] == "preempt_exit"
    assert payload["step"] == 7 and payload["pid"] == os.getpid()


# ---------------------------------------------------------------------------
# MFU / analytic FLOPs
# ---------------------------------------------------------------------------


def test_resnet18_flops_hand_computed():
    """Independent layer-by-layer arithmetic for ResNet-18 @224 (torch
    BasicBlock structure), down to the exact FLOP."""
    def conv(hw, k, cin, cout):
        return 2 * hw * hw * k * k * cin * cout

    expected = conv(112, 7, 3, 64)            # stem 7x7/2: 224 -> 112
    # stage 1 @56 (after 3x3/2 maxpool), 64ch, 2 blocks, no downsample
    expected += 4 * conv(56, 3, 64, 64)
    # stage 2 @28, 64 -> 128, downsample 1x1 in block 0
    expected += conv(28, 3, 64, 128) + conv(28, 3, 128, 128) + conv(28, 1, 64, 128)
    expected += 2 * conv(28, 3, 128, 128)
    # stage 3 @14, 128 -> 256
    expected += conv(14, 3, 128, 256) + conv(14, 3, 256, 256) + conv(14, 1, 128, 256)
    expected += 2 * conv(14, 3, 256, 256)
    # stage 4 @7, 256 -> 512
    expected += conv(7, 3, 256, 512) + conv(7, 3, 512, 512) + conv(7, 1, 256, 512)
    expected += 2 * conv(7, 3, 512, 512)

    assert resnet_fwd_flops("resnet18", 224) == expected
    # cross-check vs the literature number (1.814 GMACs backbone @224)
    assert expected / 2e9 == pytest.approx(1.814, abs=0.01)
    # head accounting: +2*512*128 for the default fc
    assert model_fwd_flops("resnet18", 224, embed_dim=128) == expected + 2 * 512 * 128


def test_resnet50_and_vit_flops_literature_band():
    assert resnet_fwd_flops("resnet50", 224) / 2e9 == pytest.approx(4.09, abs=0.05)
    # DeiT-S / moco-v3 vit_small: ~4.6 GMACs @224
    assert vit_fwd_flops("vit_small", 224) / 2e9 == pytest.approx(4.6, abs=0.1)


def test_train_step_flops_variant_multipliers():
    v2 = get_preset("imagenet-moco-v2")
    per_image = model_fwd_flops("resnet50", 224, embed_dim=v2.embed_dim,
                                mlp_head=True)
    # v1/v2: query fwd+bwd (3) + key fwd (1)
    assert train_step_flops(v2) == per_image * 4 * v2.batch_size
    v3 = get_preset("imagenet-moco-v3-vits")
    per_image3 = model_fwd_flops("vit_small", 224, embed_dim=v3.embed_dim)
    # v3: both crops through query fwd+bwd (6) + momentum fwd (2)
    assert train_step_flops(v3) == per_image3 * 8 * v3.batch_size


def test_mfu_estimator_arithmetic_and_peak_table():
    est = MFUEstimator(flops_per_step=4e12, n_chips=8, peak_flops_per_chip=1e12)
    # 4e12 FLOPs in 1 s on 8 chips of 1 TFLOP/s = 50%
    assert est.mfu(1.0) == pytest.approx(0.5)
    assert est.mfu(0.0) is None
    assert MFUEstimator(1e9, 1, None).mfu(1.0) is None  # never fabricate
    assert detect_peak_flops("TPU v5e") == 197e12
    assert detect_peak_flops("TPU v5p") == 459e12  # v5p must not match "v5e"
    assert detect_peak_flops("TPU v4") == 275e12
    assert detect_peak_flops("cpu") is None
    config = get_preset("imagenet-moco-v2").replace(peak_flops_per_chip=2e12)
    est2 = MFUEstimator.for_config(config, n_chips=4, device_kind="TPU v4")
    assert est2.peak_flops_per_chip == 2e12  # explicit override wins


# ---------------------------------------------------------------------------
# phase timer
# ---------------------------------------------------------------------------


def test_phase_timer_monotonic_and_stride_fencing():
    import jax.numpy as jnp

    timer = StepPhaseTimer(stride=3)
    sync = jnp.ones(())
    records = []
    timer.epoch_start()
    for step in range(1, 10):
        timer.mark_data()
        timer.mark_dispatch()
        fenced = timer.maybe_fence(step, sync)
        phases = timer.finish_step()
        records.append((step, fenced, phases))
    # fences land ONLY on stride multiples: 3, 6, 9
    assert [s for s, fenced, _ in records if fenced is not None] == [3, 6, 9]
    assert timer.fences == 3
    for _, fenced, p in records:
        assert p["data_s"] >= 0.0 and p["host_s"] >= 0.0 and p["step_s"] > 0.0
        # phases partition the iteration: the split never exceeds the whole
        assert p["data_s"] + p["host_s"] <= p["step_s"] + 1e-9
        assert ("device_s" in p) == (fenced is not None)
        if fenced is not None:
            assert p["device_s"] == fenced >= 0.0


def test_phase_timer_stride_zero_never_fences():
    timer = StepPhaseTimer(stride=0)
    timer.epoch_start()
    timer.mark_data()
    timer.mark_dispatch()
    # sync object deliberately un-blockable: stride 0 must never touch it
    assert timer.maybe_fence(1, object()) is None
    assert timer.fences == 0
    assert "device_s" not in timer.finish_step()


# ---------------------------------------------------------------------------
# meters satellite: rolling throughput
# ---------------------------------------------------------------------------


def test_throughput_rolling_window_sheds_compile_stall(monkeypatch):
    from moco_tpu.utils import meters

    clock = {"t": 100.0}
    monkeypatch.setattr(meters.time, "perf_counter", lambda: clock["t"])
    tp = Throughput(num_chips=1, window=4)
    # first step: 10 s compile stall, then steady 0.1 s/step at 32 imgs
    clock["t"] += 10.0
    tp.update(32)
    for _ in range(8):
        clock["t"] += 0.1
        tp.update(32)
    cumulative = tp.imgs_per_sec
    rolling = tp.rolling_imgs_per_sec
    assert cumulative == pytest.approx(9 * 32 / 10.8)   # stall-polluted: ~27
    assert rolling == pytest.approx(32 / 0.1)           # steady state: 320
    # window=0 keeps the old cumulative-only behavior
    tp0 = Throughput(num_chips=1, window=0)
    clock["t"] += 1.0
    tp0.update(10)
    assert tp0.rolling_imgs_per_sec == tp0.imgs_per_sec


# ---------------------------------------------------------------------------
# logging satellites: event sinks + ScalarWriter drops
# ---------------------------------------------------------------------------


def test_log_event_sink_receives_structured_fields(capsys):
    seen = []
    sink = lambda kind, msg, fields: seen.append((kind, msg, fields))  # noqa: E731
    mlog.add_event_sink(sink)
    try:
        mlog.log_event("rollback", "restoring", step=12, rollback=1)
    finally:
        mlog.remove_event_sink(sink)
    assert seen == [("rollback", "restoring", {"step": 12, "rollback": 1})]
    assert "[rollback] restoring" in capsys.readouterr().out
    mlog.log_event("after", "sink removed")  # no sink, no error
    assert seen == [("rollback", "restoring", {"step": 12, "rollback": 1})]


def test_log_event_broken_sink_does_not_raise(capsys):
    def bad_sink(kind, msg, fields):
        raise RuntimeError("sink broke")

    mlog.add_event_sink(bad_sink)
    try:
        mlog.log_event("kind", "msg")
    finally:
        mlog.remove_event_sink(bad_sink)
    out = capsys.readouterr().out
    assert "[kind] msg" in out and "event sink failed" in out


class _FakeTBWriter:
    def __init__(self):
        self.written = []

    def add_scalar(self, name, value, step):
        self.written.append((name, float(value), step))

    def flush(self):
        self.flushed = True

    def close(self):
        pass


def test_scalar_writer_counts_and_surfaces_drops(capsys):
    w = mlog.ScalarWriter("")
    w._writer = _FakeTBWriter()  # bypass the tensorboardX import
    seen = []
    sink = lambda kind, msg, fields: seen.append((kind, fields))  # noqa: E731
    mlog.add_event_sink(sink)
    try:
        w.write(1, {"ok": 1.0, "bad": "not-a-number", "worse": object()})
        w.write(2, {"bad": "still-bad"})
    finally:
        mlog.remove_event_sink(sink)
    assert w.dropped == 3
    assert w._writer.written == [("ok", 1.0, 1)]
    # surfaced ONCE through log_event, not once per drop
    assert len(seen) == 1 and seen[0][0] == "scalar_writer"
    assert seen[0][1]["name"] == "bad"
    w.flush()
    assert w._writer.flushed


def test_scalar_writer_disabled_flush_and_write_noop():
    w = mlog.ScalarWriter("")
    w.write(0, {"x": 1})
    w.flush()
    w.close()
    assert w.dropped == 0


def test_percentiles_ms_shape():
    pct = percentiles_ms([0.001 * (i + 1) for i in range(100)])
    assert set(pct) == {"p50", "p95", "p99"}
    assert pct["p50"] <= pct["p95"] <= pct["p99"] <= 100.0


# ---------------------------------------------------------------------------
# acceptance: 30-step CPU smoke through the real driver
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def telemetry_run(mesh8, tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("telemetry_smoke")
    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=16,
        num_negatives=64, embed_dim=32, lr=0.1, epochs=2, steps_per_epoch=15,
        ckpt_dir="", tb_dir="", print_freq=5, num_classes=10,
        knn_monitor=False,
        telemetry_dir=str(tmp_path / "telemetry"),
        telemetry_flush_steps=8, telemetry_stride=5,
        peak_flops_per_chip=1e12,  # CPU has no table entry; MFU needs a basis
        # ISSUE 3: the smoke also exercises the parallel staging pipeline +
        # decode-once cache, so input metrics land in the same stream
        staging_workers=2, input_cache_mb=64,
    )
    from moco_tpu.train import train

    state, metrics = train(config, mesh8)
    return config, state, metrics


def test_train_30_steps_writes_parseable_events(telemetry_run):
    config, state, metrics = telemetry_run
    assert int(state.step) == 30
    events_path = os.path.join(config.telemetry_dir, "events.jsonl")
    records, skipped = report.load_events(events_path)
    assert skipped == 0
    assert all(r["v"] == SCHEMA_VERSION for r in records)

    starts = [r for r in records if r["kind"] == "run_start"]
    assert len(starts) == 1
    assert starts[0]["arch"] == "resnet_tiny"
    assert starts[0]["flops_per_step"] > 0
    assert starts[0]["peak_flops_per_chip"] == 1e12

    steps = [r for r in records if r["kind"] == "step"]
    assert [r["step"] for r in steps] == list(range(1, 31))
    for r in steps:
        assert r["step_s"] > 0 and r["data_s"] >= 0 and r["host_s"] >= 0
        assert r["imgs_per_sec"] >= 0 and r["imgs_per_sec_cum"] >= 0
        assert 0 <= r["mfu"] < 1.0  # tiny model on CPU: tiny but present
    # device fences exactly on the stride (5, 10, ..., 30)
    fenced = [r["step"] for r in steps if "device_s" in r]
    assert fenced == [5, 10, 15, 20, 25, 30]
    # HBM/RSS sampling shares the stride; CPU backends may omit HBM keys
    # but host RSS is always reported
    assert all("host_rss_bytes" in r and r["host_rss_bytes"] > 0
               for r in steps if r["step"] % 5 == 0)
    # loss rides the records where the print cadence synced it anyway
    assert any("loss" in r for r in steps)

    ends = [r for r in records if r["kind"] == "run_end"]
    assert len(ends) == 1
    assert ends[0]["steps"] == 30 and ends[0]["scalar_drops"] == 0
    assert ends[0]["step_s_p50"] > 0


def test_input_pipeline_metrics_in_events(telemetry_run):
    """ISSUE 3 acceptance: queue depth, cache hit rate, and staged-batch
    latency appear in events.jsonl (step records at the sampling stride +
    the run_end summary)."""
    config, _, _ = telemetry_run
    events_path = os.path.join(config.telemetry_dir, "events.jsonl")
    records, _ = report.load_events(events_path)
    steps = [r for r in records if r["kind"] == "step"]
    snaps = [r["input"] for r in steps if "input" in r]
    assert snaps, "no step record carried an input snapshot"
    for snap in snaps:
        assert snap["staged_batches"] > 0
        assert snap["workers"] == 2
        assert snap["queue_depth"] >= 0 and snap["queue_depth_mean"] >= 0
        assert snap["staged_batch_s_p95"] >= snap["staged_batch_s_p50"] > 0
        assert 0 <= snap["worker_busy_frac"] <= 1
        assert "cache_hit_rate" in snap  # the cache wrap was active
    end = [r for r in records if r["kind"] == "run_end"][-1]
    assert end["input"]["staged_batches"] >= snaps[-1]["staged_batches"]


def test_report_renders_input_pipeline(telemetry_run):
    config, _, _ = telemetry_run
    events_path = os.path.join(config.telemetry_dir, "events.jsonl")
    proc = subprocess.run(
        [sys.executable, REPORT, events_path], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    assert "input:" in proc.stdout
    assert "staged-batch latency" in proc.stdout
    assert "decode-once cache" in proc.stdout
    as_json = subprocess.run(
        [sys.executable, REPORT, events_path, "--json"],
        capture_output=True, text=True,
    )
    summary = json.loads(as_json.stdout)
    assert summary["input"]["staged_batches"] > 0
    assert "cache_hit_rate" in summary["input"]


def test_heartbeat_written(telemetry_run):
    config, _, _ = telemetry_run
    hb_path = os.path.join(config.telemetry_dir, "heartbeat.json")
    with open(hb_path) as f:
        payload = json.load(f)
    assert payload["phase"] == "run_end"
    assert payload["pid"] == os.getpid()


def test_report_cli_renders_percentiles_and_mfu(telemetry_run):
    config, _, _ = telemetry_run
    events_path = os.path.join(config.telemetry_dir, "events.jsonl")
    proc = subprocess.run(
        [sys.executable, REPORT, events_path], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    assert "p50" in proc.stdout and "p95" in proc.stdout
    assert "MFU: mean" in proc.stdout

    as_json = subprocess.run(
        [sys.executable, REPORT, events_path, "--json"],
        capture_output=True, text=True,
    )
    summary = json.loads(as_json.stdout)
    assert summary["steps"] == 30
    assert summary["step_time_ms"]["p50"] > 0
    assert summary["step_time_ms"]["p95"] >= summary["step_time_ms"]["p50"]
    assert summary["mfu"]["mean"] > 0


# ---------------------------------------------------------------------------
# pod aggregation
# ---------------------------------------------------------------------------


def test_pod_aggregator_folds_gathered_matrix(tmp_path):
    """The exact fold the driver performs on the allgathered per-host
    vectors (the 2-process harness exercises the wire path in
    tests/test_multihost.py where the environment supports multiprocess
    CPU; the fold math is pinned here either way)."""
    from moco_tpu.telemetry import POD_FIELDS, PodAggregator

    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=1)
    agg = PodAggregator(reg, n_procs=2, process_index=0)
    agg.update(step_s=0.2, data_s=0.01, imgs_per_sec=100.0,
               hbm_peak_bytes=1e9, host_rss_bytes=2e9, incidents=1)
    vec = agg.local_vector()
    assert vec.shape == (len(POD_FIELDS),)
    # host 1's vector: slower step, less memory, no incidents
    other = vec.copy()
    other[POD_FIELDS.index("step_s")] = 0.5
    other[POD_FIELDS.index("imgs_per_sec")] = 80.0
    other[POD_FIELDS.index("hbm_peak_bytes")] = 5e8
    other[POD_FIELDS.index("incidents")] = 0
    agg.record(16, np.stack([vec, other]))
    reg.close()

    records, _ = report.load_events(path)
    (pod,) = [r for r in records if r["kind"] == "pod"]
    assert pod["hosts"] == 2 and pod["step"] == 16
    assert pod["step_s_max"] == pytest.approx(0.5)
    assert pod["step_s_min"] == pytest.approx(0.2)
    assert pod["imgs_per_sec_sum"] == pytest.approx(180.0)
    assert pod["hbm_peak_bytes_max"] == int(1e9)
    assert pod["incidents_total"] == 1


def test_pod_aggregator_nonmain_is_silent(tmp_path):
    from moco_tpu.telemetry import PodAggregator

    reg = MetricsRegistry(None)
    agg = PodAggregator(reg, n_procs=2, process_index=1)
    agg.update(step_s=0.1)
    agg.record(4, np.stack([agg.local_vector()] * 2))  # no emit, no error
    assert reg.records_written == 0


# ---------------------------------------------------------------------------
# resilience integration: incidents land in the stream
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_rollback_emits_structured_incident(mesh8, tmp_path):
    """A NaN rollback must be visible to an external monitor: the sentinel
    detection and the retry's data-window advance both land as structured
    `event` records in the SAME events.jsonl the step records go to."""
    from moco_tpu.resilience import ChaosPlan, chaos_context
    from moco_tpu.train import train

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=16,
        num_negatives=64, embed_dim=32, lr=0.1, epochs=3, steps_per_epoch=4,
        ckpt_dir=str(tmp_path / "ckpt"), tb_dir="", print_freq=1000,
        num_classes=10, knn_monitor=False, max_rollbacks=3,
        telemetry_dir=str(tmp_path / "telemetry"),
        telemetry_flush_steps=4, telemetry_stride=0,
    )
    with chaos_context(ChaosPlan(nan_at_step=6)):
        state, metrics = train(config, mesh8)
    assert int(state.step) == 10 and np.isfinite(metrics["loss"])

    records, skipped = report.load_events(
        os.path.join(config.telemetry_dir, "events.jsonl"))
    assert skipped == 0
    incident_kinds = {r["event"] for r in records if r["kind"] == "event"}
    assert "sentinel" in incident_kinds, incident_kinds
    assert "rollback" in incident_kinds, incident_kinds
    # the retry appended to the SAME stream: two run_start records
    assert sum(r["kind"] == "run_start" for r in records) == 2
    summary = report.summarize(records, skipped)
    assert summary["incidents_total"] >= 2
    assert summary["runs"] == 2
