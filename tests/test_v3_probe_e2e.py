"""End-to-end MoCo-v3 eval journey (VERDICT r2 missing #2): ViT pretrain →
timm-dialect backbone export → linear probe at the v3 recipe
(`imagenet-lincls-v3` preset: batch-scaled SGD lr, 90 epochs, cosine —
the sibling repo's `main_lincls.py` settings) beating chance on synthetic
data. Config 5's eval story, fully plumbed."""

import numpy as np
import pytest

from moco_tpu.config import get_preset
from moco_tpu.evals.lincls import train_lincls
from moco_tpu.train import train


@pytest.mark.slow
def test_v3_vit_pretrain_export_probe(mesh8, tmp_path):
    export = str(tmp_path / "v3_vit_backbone.safetensors")
    pretrain = get_preset("imagenet-moco-v3-vits").replace(
        arch="vit_tiny",
        embed_dim=16,
        dataset="synthetic",
        image_size=32,
        batch_size=32,
        lr=1e-3,
        epochs=2,
        warmup_epochs=1,
        steps_per_epoch=8,
        compute_dtype="float32",
        knn_monitor=False,
        ckpt_dir="",
        export_path=export,
        print_freq=8,
        num_classes=10,
    )
    state, metrics = train(pretrain, mesh8)
    assert int(state.step) == 16
    assert np.isfinite(metrics["loss"])

    probe = get_preset("imagenet-lincls-v3").replace(
        arch="vit_tiny",
        pretrained=export,
        dataset="synthetic",
        image_size=32,
        batch_size=32,
        epochs=2,
        num_classes=10,
        ckpt_dir="",
        print_freq=32,
    )
    # the preset's linear-scaling rule is live on the probe side too
    assert probe.effective_lr == pytest.approx(3.0 * 32 / 256)
    _, best_acc1 = train_lincls(probe, mesh8)
    # synthetic classes are strongly separable; even a near-random frozen
    # ViT-tiny linearly beats 10-way chance by a wide margin
    assert best_acc1 > 25.0, f"probe top-1 {best_acc1:.1f}% not above chance"
