"""EMA math property tests (SURVEY §4 item 2: `p_k' = m·p_k + (1-m)·p_q` exactly)."""

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.ops.ema import ema_update, momentum_schedule


def test_ema_exact():
    pk = {"w": jnp.full((3,), 2.0), "nested": {"b": jnp.full((2, 2), -1.0)}}
    pq = {"w": jnp.full((3,), 4.0), "nested": {"b": jnp.full((2, 2), 3.0)}}
    out = ema_update(pk, pq, 0.999)
    np.testing.assert_allclose(np.asarray(out["w"]), 2.0 * 0.999 + 4.0 * 0.001, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(out["nested"]["b"]), -1.0 * 0.999 + 3.0 * 0.001, rtol=1e-6
    )


def test_ema_momentum_one_freezes():
    pk = {"w": jnp.ones(3)}
    pq = {"w": jnp.zeros(3)}
    np.testing.assert_array_equal(np.asarray(ema_update(pk, pq, 1.0)["w"]), 1.0)


def test_momentum_schedule_ramp():
    m0 = momentum_schedule(0.99, 0, 100)
    m_half = momentum_schedule(0.99, 50, 100)
    m_end = momentum_schedule(0.99, 100, 100)
    assert np.isclose(float(m0), 0.99, atol=1e-6)
    assert np.isclose(float(m_half), 0.995, atol=1e-6)
    assert np.isclose(float(m_end), 1.0, atol=1e-6)
    assert float(m0) < float(m_half) < float(m_end)
