"""bf16 gradient all-reduce (quantized collective, PAPERS.md EQuARX-style):
half the ICI bytes, bounded quantization error, default-off parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step

B, IMG, DIM, K = 16, 16, 16, 64


def _one_step(mesh, dtype):
    config = PretrainConfig(
        variant="v1", arch="resnet_tiny", cifar_stem=True, num_negatives=K,
        embed_dim=DIM, batch_size=B, epochs=2, lr=0.1,
        grad_allreduce_dtype=dtype,
    )
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_train_state(
        jax.random.key(0), model, tx, (B // mesh.size, IMG, IMG, 3), K, DIM
    )
    step = build_train_step(config, model, tx, mesh, 8, sched)
    im_q = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
    im_k = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
    return step(state, im_q, im_k)


def test_bf16_allreduce_close_to_f32(mesh8):
    s32, m32 = _one_step(mesh8, "float32")
    s16, m16 = _one_step(mesh8, "bfloat16")
    assert np.isfinite(float(m16["loss"]))
    # same forward → identical loss; the updates differ only by bf16
    # quantization of the reduced gradients
    np.testing.assert_allclose(float(m32["loss"]), float(m16["loss"]), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s32.params_q), jax.tree.leaves(s16.params_q),
                    strict=True):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        np.testing.assert_allclose(a, b, rtol=0.02, atol=2e-4)
    # and they are NOT bit-identical (the cast really happened)
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(s32.params_q),
                        jax.tree.leaves(s16.params_q))
    )


def test_unknown_allreduce_dtype_rejected(mesh8):
    with pytest.raises(ValueError, match="grad_allreduce_dtype"):
        _one_step(mesh8, "float16")
