"""Golden-value regression pinning (SURVEY §4 item 3): fixed seed, fixed
data, fixed arch → the first steps' losses are pinned so any silent change
to the algorithm (EMA order, queue semantics, shuffle stream, LR, optimizer
chain, augmentation RNG) shows up as a diff here.

CPU XLA is deterministic, so tolerances are tight. If a DELIBERATE semantic
change moves these values, update the constants in the same commit and say
why in its message.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step

GLOBAL_B, IMG, DIM, K = 16, 8, 16, 64


def _run_steps(config, mesh, n=3):
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_train_state(
        jax.random.key(0), model, tx,
        (GLOBAL_B // mesh.size, IMG, IMG, 3), K, DIM,
    )
    step_fn = build_train_step(config, model, tx, mesh, 8, sched)
    losses = []
    for i in range(n):
        im_q = jax.random.normal(jax.random.key(100 + i), (GLOBAL_B, IMG, IMG, 3))
        im_k = jax.random.normal(jax.random.key(200 + i), (GLOBAL_B, IMG, IMG, 3))
        state, metrics = step_fn(state, im_q, im_k)
        losses.append(float(metrics["loss"]))
    return losses, state


@pytest.fixture(scope="module")
def config():
    return PretrainConfig(
        variant="v1", arch="resnet_tiny", cifar_stem=True, num_negatives=K,
        embed_dim=DIM, batch_size=GLOBAL_B, epochs=2, lr=0.1, seed=0,
    )


def test_golden_losses_8dev(config, mesh8):
    losses, state = _run_steps(config, mesh8)
    # pinned 2026-07-29 (jax 0.9.0, CPU): update deliberately, never casually
    # re-pinned same day: stride-2 3x3 convs moved from SAME (0,1) padding to
    # torchvision's symmetric (1,1) — the torch-consumer parity fix
    golden = [0.016187, 2.8706696, 3.7958486]
    np.testing.assert_allclose(losses, golden, rtol=2e-4, err_msg=str(losses))
    assert int(state.queue_ptr) == (3 * GLOBAL_B) % K


def test_golden_losses_1dev(config):
    """Separate pin for the 1-device mesh: per-DEVICE BatchNorm makes the
    numbers legitimately mesh-size-dependent (16-sample BN groups here vs
    8x2 on the 8-device mesh — exactly as per-GPU BN behaves in the
    reference), so each mesh size gets its own golden values."""
    from moco_tpu.parallel.mesh import create_mesh

    losses, _ = _run_steps(config, create_mesh(1))
    # re-pinned with the symmetric-padding parity fix (see 8dev note)
    golden = [0.0279795, 2.8311126, 3.4929943]
    np.testing.assert_allclose(losses, golden, rtol=2e-4, err_msg=str(losses))
