"""Checkpoint tests: Orbax bit-faithful resume (queue included, SURVEY §5.4)
and the torchvision-dialect export/import roundtrip (SURVEY §2.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from moco_tpu.checkpoint import (
    checkpoint_manager,
    export_encoder_q,
    import_encoder_q,
    maybe_resume,
    restore_checkpoint,
    resnet_to_torchvision,
    save_checkpoint,
    torchvision_to_resnet,
)
from moco_tpu.models.resnet import ResNetTiny
from moco_tpu.train_state import create_train_state


@pytest.fixture(scope="module")
def tiny_state():
    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1, momentum=0.9)
    return model, create_train_state(
        jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32
    ), tx


def test_orbax_roundtrip_bit_faithful(tiny_state, tmp_path):
    model, state, tx = tiny_state
    state = state.replace(queue_ptr=jnp.asarray(32, jnp.int32))
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state, 7)
    mgr.wait_until_finished()
    fresh = create_train_state(jax.random.key(1), model, tx, (2, 16, 16, 3), 64, 32)
    restored = restore_checkpoint(mgr, fresh, 7)
    assert int(restored.queue_ptr) == 32
    ra = restored.replace(rng=jax.random.key_data(restored.rng))
    sa = state.replace(rng=jax.random.key_data(state.rng))
    for a, b in zip(jax.tree.leaves(ra), jax.tree.leaves(sa)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_maybe_resume_auto_and_empty(tiny_state, tmp_path):
    model, state, tx = tiny_state
    mgr = checkpoint_manager(str(tmp_path / "empty"))
    out = maybe_resume(mgr, state, "auto")  # no checkpoint yet → fresh state
    assert out is state
    out = maybe_resume(mgr, state, "")
    assert out is state
    with pytest.raises(ValueError, match="step directory"):
        maybe_resume(mgr, state, "/no/such/path")


def test_export_import_roundtrip(tiny_state, tmp_path):
    model, state, tx = tiny_state
    path = str(tmp_path / "encoder.safetensors")
    flat = export_encoder_q(state, path)
    assert any(k.startswith("module.encoder_q.conv1") for k in flat)
    assert any(".running_mean" in k for k in flat)
    params, stats = torchvision_to_resnet(import_encoder_q(path))
    # fc dropped (checkpoint surgery), backbone identical
    assert "fc" not in params
    orig = {k: v for k, v in state.params_q.items() if k != "fc"}
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(orig),
    ):
        assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # running stats preserved too
    assert stats["bn1"]["mean"].shape == (16,)


def test_export_npz_and_mlp_head_names(tmp_path):
    model = ResNetTiny(num_classes=32, mlp_head=True, cifar_stem=True)
    tx = optax.sgd(0.1)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    path = str(tmp_path / "enc.npz")
    flat = export_encoder_q(state, path, mlp_head=True)
    assert "module.encoder_q.fc.0.weight" in flat  # Sequential index names
    assert "module.encoder_q.fc.2.weight" in flat
    params, _ = torchvision_to_resnet(import_encoder_q(path))
    assert "fc" not in params and "fc_hidden" not in params


def test_conv_layout_transposed():
    """flax [kh,kw,cin,cout] ↔ torch [cout,cin,kh,kw]."""
    kernel = np.arange(3 * 3 * 4 * 8, dtype=np.float32).reshape(3, 3, 4, 8)
    flat = resnet_to_torchvision({"conv1": {"kernel": kernel}}, {}, prefix="")
    assert flat["conv1.weight"].shape == (8, 4, 3, 3)
    back, _ = torchvision_to_resnet({"x.conv1.weight": flat["conv1.weight"]}, "x.")
    np.testing.assert_array_equal(back["conv1"]["kernel"], kernel)
