"""Checkpoint tests: Orbax bit-faithful resume (queue included, SURVEY §5.4)
and the torchvision-dialect export/import roundtrip (SURVEY §2.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from moco_tpu.checkpoint import (
    checkpoint_manager,
    export_encoder_q,
    import_encoder_q,
    maybe_resume,
    restore_checkpoint,
    resnet_to_torchvision,
    save_checkpoint,
    torchvision_to_resnet,
)
from moco_tpu.models.resnet import ResNetTiny
from moco_tpu.train_state import create_train_state


@pytest.fixture(scope="module")
def tiny_state():
    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1, momentum=0.9)
    return model, create_train_state(
        jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32
    ), tx


def test_orbax_roundtrip_bit_faithful(tiny_state, tmp_path):
    model, state, tx = tiny_state
    state = state.replace(queue_ptr=jnp.asarray(32, jnp.int32))
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state, 7)
    mgr.wait_until_finished()
    fresh = create_train_state(jax.random.key(1), model, tx, (2, 16, 16, 3), 64, 32)
    restored = restore_checkpoint(mgr, fresh, 7)
    assert int(restored.queue_ptr) == 32
    ra = restored.replace(rng=jax.random.key_data(restored.rng))
    sa = state.replace(rng=jax.random.key_data(state.rng))
    for a, b in zip(jax.tree.leaves(ra), jax.tree.leaves(sa)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_maybe_resume_auto_and_empty(tiny_state, tmp_path):
    model, state, tx = tiny_state
    mgr = checkpoint_manager(str(tmp_path / "empty"))
    out = maybe_resume(mgr, state, "auto")  # no checkpoint yet → fresh state
    assert out is state
    out = maybe_resume(mgr, state, "")
    assert out is state
    with pytest.raises(ValueError, match="step directory"):
        maybe_resume(mgr, state, "/no/such/path")


def test_export_import_roundtrip(tiny_state, tmp_path):
    model, state, tx = tiny_state
    path = str(tmp_path / "encoder.safetensors")
    flat = export_encoder_q(state, path)
    assert any(k.startswith("module.encoder_q.conv1") for k in flat)
    assert any(".running_mean" in k for k in flat)
    params, stats = torchvision_to_resnet(import_encoder_q(path))
    # fc dropped (checkpoint surgery), backbone identical
    assert "fc" not in params
    orig = {k: v for k, v in state.params_q.items() if k != "fc"}
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(orig),
    ):
        assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # running stats preserved too
    assert stats["bn1"]["mean"].shape == (16,)


def test_export_npz_and_mlp_head_names(tmp_path):
    model = ResNetTiny(num_classes=32, mlp_head=True, cifar_stem=True)
    tx = optax.sgd(0.1)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    path = str(tmp_path / "enc.npz")
    flat = export_encoder_q(state, path, mlp_head=True)
    assert "module.encoder_q.fc.0.weight" in flat  # Sequential index names
    assert "module.encoder_q.fc.2.weight" in flat
    params, _ = torchvision_to_resnet(import_encoder_q(path))
    assert "fc" not in params and "fc_hidden" not in params


def test_conv_layout_transposed():
    """flax [kh,kw,cin,cout] ↔ torch [cout,cin,kh,kw]."""
    kernel = np.arange(3 * 3 * 4 * 8, dtype=np.float32).reshape(3, 3, 4, 8)
    flat = resnet_to_torchvision({"conv1": {"kernel": kernel}}, {}, prefix="")
    assert flat["conv1.weight"].shape == (8, 4, 3, 3)
    back, _ = torchvision_to_resnet({"x.conv1.weight": flat["conv1.weight"]}, "x.")
    np.testing.assert_array_equal(back["conv1"]["kernel"], kernel)


# ---------------------------------------------------------------------------
# timm-dialect ViT export (VERDICT r1 #6: public v3 checkpoint dialect)
# ---------------------------------------------------------------------------

from moco_tpu.checkpoint import (  # noqa: E402
    load_pretrained_backbone,
    timm_to_vit,
    vit_to_timm,
)
from moco_tpu.models.vit import ViT  # noqa: E402


@pytest.fixture(scope="module")
def tiny_vit_params():
    model = ViT(patch_size=4, width=16, depth=2, num_heads=4, num_classes=None)
    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    params = model.init(jax.random.key(3), x, train=False)["params"]
    return model, params


def test_vit_timm_name_set(tiny_vit_params):
    _, params = tiny_vit_params
    flat = vit_to_timm(jax.tree.map(np.asarray, params), grid=(2, 2))
    expected = {"cls_token", "pos_embed", "patch_embed.proj.weight",
                "patch_embed.proj.bias", "norm.weight", "norm.bias"}
    for i in range(2):
        for n in ("norm1.weight", "norm1.bias", "attn.qkv.weight",
                  "attn.qkv.bias", "attn.proj.weight", "attn.proj.bias",
                  "norm2.weight", "norm2.bias", "mlp.fc1.weight",
                  "mlp.fc1.bias", "mlp.fc2.weight", "mlp.fc2.bias"):
            expected.add(f"blocks.{i}.{n}")
    assert set(flat) == expected
    assert flat["blocks.0.attn.qkv.weight"].shape == (48, 16)
    assert flat["blocks.0.attn.qkv.bias"].shape == (48,)
    assert flat["patch_embed.proj.weight"].shape == (16, 3, 4, 4)
    assert flat["pos_embed"].shape == (1, 5, 16)
    np.testing.assert_array_equal(flat["pos_embed"][0, 0], 0.0)  # cls row


def test_vit_timm_roundtrip_and_apply(tiny_vit_params, tmp_path):
    model, params = tiny_vit_params
    flat = vit_to_timm(jax.tree.map(np.asarray, params), grid=(2, 2))
    back = timm_to_vit(flat, num_heads=4)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(back),
        jax.tree_util.tree_leaves_with_path(params),
    ):
        assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    x = jax.random.normal(jax.random.key(4), (2, 8, 8, 3))
    np.testing.assert_allclose(
        model.apply({"params": back}, x, train=False),
        model.apply({"params": params}, x, train=False),
        rtol=1e-6,
    )


def _timm_consumer_forward(flat, img):
    """Emulate a timm-style torch consumer forward in numpy: patchify via the
    [D,3,p,p] conv weight, +pos_embed, pre-norm blocks with fused qkv, exact
    GELU, final norm, cls feature. Verifies the exported tensor LAYOUTS, not
    just converter self-consistency."""
    from scipy.special import erf  # via numpy: exact gelu

    def ln(x, w, b, eps=1e-6):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + eps) * w + b

    W = flat["patch_embed.proj.weight"]  # [D, C, p, p]
    D, C, p, _ = W.shape
    B, H, Wd, _ = img.shape
    gh, gw = H // p, Wd // p
    patches = img.reshape(B, gh, p, gw, p, C).transpose(0, 1, 3, 5, 2, 4)
    patches = patches.reshape(B, gh * gw, C * p * p)  # torch (c, ph, pw) order
    x = patches @ W.reshape(D, C * p * p).T + flat["patch_embed.proj.bias"]
    cls = np.broadcast_to(flat["cls_token"], (B, 1, D))
    x = np.concatenate([cls, x], axis=1) + flat["pos_embed"]
    n_blocks = 1 + max(int(k.split(".")[1]) for k in flat if k.startswith("blocks."))
    heads = 4
    hd = D // heads
    for i in range(n_blocks):
        bp = f"blocks.{i}"
        y = ln(x, flat[f"{bp}.norm1.weight"], flat[f"{bp}.norm1.bias"])
        qkv = y @ flat[f"{bp}.attn.qkv.weight"].T + flat[f"{bp}.attn.qkv.bias"]
        q, k, v = np.split(qkv, 3, axis=-1)
        N = q.shape[1]

        def split_heads(t):
            return t.reshape(B, N, heads, hd).transpose(0, 2, 1, 3)

        q, k, v = split_heads(q), split_heads(k), split_heads(v)
        att = q @ k.transpose(0, 1, 3, 2) / np.sqrt(hd)
        att = np.exp(att - att.max(-1, keepdims=True))
        att = att / att.sum(-1, keepdims=True)
        o = (att @ v).transpose(0, 2, 1, 3).reshape(B, N, D)
        o = o @ flat[f"{bp}.attn.proj.weight"].T + flat[f"{bp}.attn.proj.bias"]
        x = x + o
        y = ln(x, flat[f"{bp}.norm2.weight"], flat[f"{bp}.norm2.bias"])
        y = y @ flat[f"{bp}.mlp.fc1.weight"].T + flat[f"{bp}.mlp.fc1.bias"]
        y = 0.5 * y * (1.0 + erf(y / np.sqrt(2.0)))
        y = y @ flat[f"{bp}.mlp.fc2.weight"].T + flat[f"{bp}.mlp.fc2.bias"]
        x = x + y
    x = ln(x, flat["norm.weight"], flat["norm.bias"])
    return x[:, 0]


def test_vit_timm_export_matches_external_consumer(tiny_vit_params):
    """A torch/timm consumer computing from the exported tensors gets the
    same features our model computes — the layout (transposes, qkv fusion,
    head packing, patch order, pos_embed) is externally correct."""
    model, params = tiny_vit_params
    flat = vit_to_timm(jax.tree.map(np.asarray, params), grid=(2, 2))
    img = np.asarray(jax.random.normal(jax.random.key(5), (2, 8, 8, 3)))
    ours = np.asarray(model.apply({"params": params}, jnp.asarray(img), train=False))
    theirs = _timm_consumer_forward(flat, img.astype(np.float64))
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-5)


def test_v3_vit_export_is_timm_dialect(tmp_path):
    from moco_tpu.checkpoint import export_v3_backbone
    from moco_tpu.v3_step import V3Model

    model = V3Model(
        ViT(patch_size=4, width=16, depth=2, num_heads=4, num_classes=None),
        embed_dim=8,
        hidden_dim=16,
    )
    state = create_train_state(jax.random.key(0), model, optax.sgd(0.1),
                               (2, 8, 8, 3), None, 8)
    path = str(tmp_path / "v3_vit.safetensors")
    flat = export_v3_backbone(state, path, image_size=8)
    assert "blocks.0.attn.qkv.weight" in flat
    assert "backbone/patch_embed/kernel" not in flat
    # pos_embed follows the MODEL's grid (8px / patch 4 -> 2x2 + cls)
    assert flat["pos_embed"].shape == (1, 5, 16)
    params, stats = load_pretrained_backbone(path, num_heads=4)
    assert stats == {}
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(state.params_q["backbone"]),
    ):
        assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
