"""MoCo v3 tests: ViT structure, frozen patch embed, symmetric step on the
8-device mesh (BASELINE config 5; SURVEY §2.9/§3.5)."""

import jax
import jax.export  # noqa: F401  (binds the lazy submodule on 0.4.x)
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.models.vit import ViT, sincos_2d_position_embedding
from moco_tpu.ops.ema import ema_update
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step
from moco_tpu.v3_step import (
    V3Model,
    create_v3_train_state,
    encoder_subtree,
    patch_embed_trainable_mask,
)

IMG, B = 16, 16  # 16x16 imgs, patch 8 → 2x2=4 tokens + cls


def tiny_vit(**kw):
    return ViT(patch_size=8, width=32, depth=2, num_heads=2, **kw)


def tiny_config(**kw):
    base = dict(
        variant="v3", arch="vit_small", embed_dim=16, momentum_ema=0.99,
        momentum_ramp=True, temperature=0.2, optimizer="adamw", lr=1e-3,
        weight_decay=0.1, batch_size=B, epochs=2, warmup_epochs=1,
    )
    base.update(kw)
    return PretrainConfig(**base)


def test_sincos_embedding_shape_and_determinism():
    e1 = sincos_2d_position_embedding(4, 4, 32)
    e2 = sincos_2d_position_embedding(4, 4, 32)
    assert e1.shape == (1, 16, 32)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))


def test_vit_forward_shapes():
    model = tiny_vit(num_classes=None)
    v = model.init(jax.random.key(0), jnp.zeros((2, IMG, IMG, 3)), train=False)
    out = model.apply(v, jnp.ones((2, IMG, IMG, 3)), train=False)
    assert out.shape == (2, 32)


def test_patch_embed_gets_no_gradient():
    model = tiny_vit(num_classes=16, frozen_patch_embed=True)
    v = model.init(jax.random.key(0), jnp.zeros((2, IMG, IMG, 3)), train=False)

    def loss(params):
        out = model.apply({"params": params}, jnp.ones((2, IMG, IMG, 3)), train=False)
        return jnp.sum(out**2)

    g = jax.grad(loss)(v["params"])
    np.testing.assert_array_equal(np.asarray(g["patch_embed"]["kernel"]), 0.0)
    # other layers DO get gradient
    assert float(jnp.abs(g["block0"]["mlp_fc1"]["kernel"]).max()) > 0


def test_patch_embed_mask_marks_only_patch_embed():
    model = tiny_vit(num_classes=None)
    v = model.init(jax.random.key(0), jnp.zeros((2, IMG, IMG, 3)), train=False)
    mask = patch_embed_trainable_mask(v["params"])
    flat = jax.tree_util.tree_leaves_with_path(mask)
    frozen = [jax.tree_util.keystr(p) for p, m in flat if not m]
    assert frozen and all("patch_embed" in f for f in frozen)


@pytest.fixture(scope="module")
def v3_setup(mesh8):
    config = tiny_config()
    model = V3Model(tiny_vit(num_classes=None), embed_dim=16, hidden_dim=32)
    tx, sched = build_optimizer(config, steps_per_epoch=4)
    state = create_v3_train_state(
        jax.random.key(0), model, tx, (B // 8, IMG, IMG, 3)
    )
    step_raw = build_train_step(config, model, tx, mesh8, steps_per_epoch=4, sched=sched)

    def step(s, x1, x2):
        return step_raw(jax.tree.map(jnp.copy, s), x1, x2)

    x1 = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
    x2 = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
    return config, state, step, (x1, x2)


def test_vit_large_huge_geometry():
    """The paper's scaling-study archs (moco-v3 Table 3): ViT-L/16 and
    ViT-H/14 build with the standard timm geometry and the sin-cos grid
    matches the patch count — checked shape-only (eval_shape; a real L/H
    forward is too heavy for the 1-core sandbox)."""
    import jax

    from moco_tpu.models.vit import VIT_FEATURE_DIMS, build_vit

    for arch, width, depth, heads, patch, grid in (
        ("vit_large", 1024, 24, 16, 16, 14),
        ("vit_huge", 1280, 32, 16, 14, 16),
    ):
        model = build_vit(arch, num_classes=None)
        assert model.width == width and model.depth == depth
        assert model.num_heads == heads and model.patch_size == patch
        assert VIT_FEATURE_DIMS[arch] == width
        shapes = jax.eval_shape(
            lambda m=model: m.init(
                jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False
            )
        )
        pos = shapes["params"]["pos_embed"] if "pos_embed" in shapes["params"] else None
        # feature output is [1, width]
        out = jax.eval_shape(
            lambda v, m=model: m.apply(v, jnp.zeros((1, 224, 224, 3)),
                                       train=False),
            shapes,
        )
        assert out.shape == (1, width), (arch, out.shape)
        n_blocks = sum(1 for k in shapes["params"] if k.startswith("block"))
        assert n_blocks == depth, (arch, n_blocks)
        del pos, grid


def test_v3_state_has_no_queue_and_no_predictor_in_k(v3_setup):
    _, state, _, _ = v3_setup
    assert state.queue is None and state.queue_ptr is None
    assert "predictor" in state.params_q
    assert "predictor" not in state.params_k
    assert set(state.params_k) == set(encoder_subtree(state.params_q))


def test_v3_step_runs_and_updates(v3_setup):
    config, state, step, (x1, x2) = v3_setup
    s, metrics = step(state, x1, x2)
    assert int(s.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["acc1"]) <= 100.0
    # momentum at step 0 equals base (ramp starts at 0.99)
    assert np.isclose(float(metrics["momentum"]), 0.99, atol=1e-6)
    # linear warmup: lr is exactly 0 at step 0 (faithful to the reference's
    # per-iteration warmup), so params move only from step 2 on
    assert float(metrics["lr"]) == 0.0
    s, metrics = step(s, x1, x2)
    assert float(metrics["lr"]) > 0.0
    # params moved (except frozen patch embed)
    pe_before = np.asarray(state.params_q["backbone"]["patch_embed"]["kernel"])
    pe_after = np.asarray(s.params_q["backbone"]["patch_embed"]["kernel"])
    np.testing.assert_array_equal(pe_before, pe_after)
    proj_before = np.asarray(state.params_q["projector"]["mlp"]["fc0"]["kernel"])
    proj_after = np.asarray(s.params_q["projector"]["mlp"]["fc0"]["kernel"])
    assert not np.allclose(proj_before, proj_after)


def test_v3_key_params_move_only_by_ema(v3_setup):
    config, state, step, (x1, x2) = v3_setup
    s, _ = step(state, x1, x2)
    expected = ema_update(state.params_k, encoder_subtree(state.params_q), 0.99)
    for a, b in zip(jax.tree.leaves(s.params_k), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_remat_vit_same_params_and_grads():
    """remat=True must not change the parameter tree or the math — only the
    memory/recompute trade (it made v3 ViT-S batch 512 compile on the v5e
    where the non-remat version exhausted compile resources)."""
    x = jnp.ones((2, IMG, IMG, 3))
    plain = tiny_vit(num_classes=16)
    rem = tiny_vit(num_classes=16, remat=True)
    v = plain.init(jax.random.key(0), x, train=False)
    v2 = rem.init(jax.random.key(0), x, train=False)
    assert jax.tree.structure(v) == jax.tree.structure(v2)

    def loss(m, params):
        return jnp.sum(m.apply({"params": params}, x, train=False) ** 2)

    g1 = jax.grad(lambda p: loss(plain, p))(v["params"])
    g2 = jax.grad(lambda p: loss(rem, p))(v2["params"])
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_v3_resnet_backbone_via_build_encoder(mesh8):
    """v3 also supports ResNet backbones (paper's MoCo v3 R50 recipe)."""
    config = tiny_config(arch="resnet18", cifar_stem=True)
    model = build_encoder(config)
    assert isinstance(model, V3Model)
    v = model.init(
        jax.random.key(0), jnp.zeros((2, IMG, IMG, 3)), train=False, predict=True
    )
    assert "predictor" in v["params"]


def test_v3_r50_lars_step_on_mesh(mesh8):
    """The v3-ResNet/LARS leg (imagenet-moco-v3-r50 preset shape): one step
    runs on the 8-device mesh, and the LARS trust-ratio scaling produces a
    genuinely different update than SGD with the same lr/grads."""
    from moco_tpu.config import get_preset

    preset = get_preset("imagenet-moco-v3-r50")
    assert preset.optimizer == "lars" and preset.variant == "v3"
    assert preset.weight_decay == 1.5e-6 and preset.crop_min == 0.2
    # lr follows the linear-scaling rule from the ACTUAL batch (base_lr)
    assert preset.effective_lr == pytest.approx(0.3 * preset.batch_size / 256)

    def run(optimizer):
        config = preset.replace(
            arch="resnet_tiny", cifar_stem=True, embed_dim=16, batch_size=B,
            compute_dtype="float32", optimizer=optimizer,
            lr=0.1, warmup_epochs=0, epochs=2,
        )
        model = build_encoder(config)
        tx, sched = build_optimizer(config, steps_per_epoch=4)
        state = create_v3_train_state(
            jax.random.key(0), model, tx, (B // 8, IMG, IMG, 3)
        )
        step = build_train_step(config, model, tx, mesh8, steps_per_epoch=4, sched=sched)
        x1 = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
        x2 = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
        # the step donates its input state — keep a live copy for comparison
        s, metrics = step(jax.tree.map(jnp.copy, state), x1, x2)
        return state, s, metrics

    init_lars, s_lars, m_lars = run("lars")
    init_sgd, s_sgd, m_sgd = run("sgd")
    assert np.isfinite(float(m_lars["loss"]))
    assert int(s_lars.step) == 1 and s_lars.queue is None
    # identical init (same seed) but different step direction: the trust
    # ratio rescales per-layer updates
    before = np.asarray(init_lars.params_q["backbone"]["conv1"]["kernel"])
    after_lars = np.asarray(s_lars.params_q["backbone"]["conv1"]["kernel"])
    after_sgd = np.asarray(s_sgd.params_q["backbone"]["conv1"]["kernel"])
    d_lars = after_lars - before
    d_sgd = after_sgd - before
    assert np.abs(d_lars).max() > 0  # LARS actually moved the params
    assert not np.allclose(d_lars, d_sgd)
    # LARS normalizes the update to ~trust_coefficient * ||w|| / ||u|| * lr:
    # the scale of the two updates must differ materially, not just noise
    ratio = np.linalg.norm(d_lars) / max(np.linalg.norm(d_sgd), 1e-12)
    assert ratio < 0.5 or ratio > 2.0, ratio


@pytest.mark.slow
def test_v3_vits_full_step_lowers_for_tpu():
    """Config 5's whole benchmark program (asymmetric v3 aug pair with the
    Pallas blur, ViT-S with remat, symmetric loss, AdamW) exports for the
    TPU platform from CPU — hardware-free lowering assurance like the v2
    pin in test_fused_conv."""
    import unittest.mock as mock

    from moco_tpu.config import get_preset
    from moco_tpu.data.augment import build_two_crops_sharded, v3_aug_configs, with_dtype
    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.train_step import (
        build_encoder, build_fused_step, build_optimizer, build_train_step,
    )
    from moco_tpu.v3_step import create_v3_train_state

    Bv = 256
    config = get_preset("imagenet-moco-v3-vits").replace(batch_size=Bv, remat=True)
    mesh = create_mesh(1)
    # the backend patch routes the aug's blur gate onto the Pallas path;
    # fast_bn is not part of the ViT program (LayerNorm backbone)
    with mock.patch.object(jax, "default_backend", lambda: "tpu"):
        model = build_encoder(config)
        tx, sched = build_optimizer(config, 1000)
        state = jax.eval_shape(lambda: create_v3_train_state(
            jax.random.key(0), model, tx, (Bv, 224, 224, 3)))
        step_fn = build_train_step(config, model, tx, mesh, 1000, sched)
        two = build_two_crops_sharded(
            with_dtype(v3_aug_configs(224), "bfloat16"), mesh
        )
        fused = build_fused_step(step_fn, two, jax.random.key(1))
        imgs = jax.ShapeDtypeStruct((Bv, 252, 252, 3), jnp.uint8)
        ext = jax.ShapeDtypeStruct((Bv, 3), jnp.int32)
        exp = jax.export.export(fused, platforms=["tpu"])(
            state, imgs, ext, jax.ShapeDtypeStruct((), jnp.int32)
        )
        # the Pallas blur is the one custom kernel on the ViT path
        assert exp.mlir_module().count("tpu_custom_call") >= 1
