"""Distributed-tracing suite (ISSUE 8): span parenting and mode
filtering, capture-window lifecycle (trigger file / SIGUSR1 / anomaly
detectors, budgeted), cross-PROCESS id propagation supervisor → child →
staging worker under one trace_id, Chrome-trace schema validation of
tools/trace_report.py, the live-tail --follow mode, the StepPhaseTimer
`telemetry` sub-phase fix, R12 lint fixtures, and the acceptance smoke: a
30-step CPU train with chaos slow-step injection whose anomaly detector
auto-captures exactly once within budget."""

import importlib.util
import io
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from moco_tpu.telemetry.registry import MetricsRegistry
from moco_tpu.telemetry.timing import StepPhaseTimer
from moco_tpu.telemetry.trace import (
    ENV_RUN_ID,
    ENV_TRACE_PARENT,
    NULL_SPAN,
    SPANS_FILENAME,
    TRIGGER_FILENAME,
    SlowSampleDetector,
    SpikeDetector,
    Tracer,
    null_tracer,
    parse_parent,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_report = _load_tool("trace_report")
telemetry_report = _load_tool("telemetry_report")


def read_spans(telemetry_dir):
    path = os.path.join(str(telemetry_dir), SPANS_FILENAME)
    spans = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                spans.append(json.loads(line))
    return spans


# ---------------------------------------------------------------------------
# span basics: parenting, modes, retroactive recording
# ---------------------------------------------------------------------------


def test_span_nesting_parents_and_flush(tmp_path):
    t = Tracer(str(tmp_path), "steps", proc="driver")
    with t.span("outer", cat="test", k=1) as outer:
        with t.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id == t.trace_id
    t.flush()
    spans = read_spans(tmp_path)
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent"] == by_name["outer"]["span"]
    assert by_name["outer"]["attrs"] == {"k": 1}
    assert by_name["outer"]["run"] == t.run_id
    assert by_name["outer"]["proc"] == "driver"
    assert by_name["outer"]["dur"] >= by_name["inner"]["dur"] >= 0
    assert t.spans_recorded == t.spans_written == 2


def test_modes_filter_detail_spans(tmp_path):
    off = Tracer(str(tmp_path / "off"), "off")
    assert off.span("x") is NULL_SPAN
    assert off.record_step(1, {"step_s": 0.1}) is None

    steps = Tracer(str(tmp_path / "steps"), "steps")
    assert steps.span("fine", detail=True) is NULL_SPAN
    with steps.span("coarse"):
        pass
    assert steps.record_span("retro", time.time(), 0.01, detail=True) is None
    steps.flush()
    assert [s["name"] for s in read_spans(tmp_path / "steps")] == ["coarse"]

    full = Tracer(str(tmp_path / "full"), "full")
    with full.span("fine", detail=True):
        pass
    full.flush()
    assert [s["name"] for s in read_spans(tmp_path / "full")] == ["fine"]


def test_record_step_emits_phase_children_at_full(tmp_path):
    t = Tracer(str(tmp_path), "full")
    phases = {"step_s": 0.1, "data_s": 0.03, "host_s": 0.02,
              "telemetry_s": 0.01, "device_s": 0.05}
    sid = t.record_step(7, phases, loss=1.5)
    t.flush()
    spans = read_spans(tmp_path)
    step = next(s for s in spans if s["cat"] == "step")
    assert step["span"] == sid
    assert step["attrs"]["step"] == 7 and step["attrs"]["loss"] == 1.5
    children = {s["name"]: s for s in spans if s["cat"] == "phase"}
    # device_s is a fenced drain sample, not a wall segment: attr only
    assert set(children) == {"telemetry", "data", "host"}
    assert all(c["parent"] == sid for c in children.values())
    # at `steps` level the children are filtered, the step span remains
    t2 = Tracer(str(tmp_path / "s"), "steps")
    t2.record_step(8, phases)
    t2.flush()
    assert [s["cat"] for s in read_spans(tmp_path / "s")] == ["step"]


def test_null_tracer_is_inert():
    t = null_tracer()
    assert t.span("x", detail=True) is NULL_SPAN
    assert t.tick(3) is None and t.capture_state() is None
    assert not t.maybe_autocapture("slow_step")
    assert t.child_env() == {}
    assert NULL_SPAN.context() is None


def test_parse_parent():
    assert parse_parent("abc:def") == ("abc", "def")
    assert parse_parent("") is None
    assert parse_parent(None) is None
    assert parse_parent("malformed") is None
    assert parse_parent(":") is None


# ---------------------------------------------------------------------------
# capture windows: trigger file, SIGUSR1, budget
# ---------------------------------------------------------------------------


def test_capture_window_lifecycle_and_budget(tmp_path):
    t = Tracer(str(tmp_path), "off", capture_steps=3, capture_budget=1,
               trigger_poll_secs=0.0)
    assert t.tick(0) is None  # idle: no transitions
    t.request_capture("manual")
    evt = t.tick(1)
    assert evt["action"] == "start" and evt["reason"] == "manual"
    assert t.capture_state() == {
        "capturing": True, "window_steps_left": 3,
        "captures_used": 1, "capture_budget": 1,
    }
    # capture elevates an OFF tracer to full detail
    with t.span("detail_during_capture", detail=True):
        pass
    assert t.tick(2) is None
    assert t.tick(3) is None
    evt = t.tick(4)
    assert evt["action"] == "end"
    assert not t.capture_state()["capturing"]
    # budget spent: the detector entry point still ROUTES the request (a
    # budget-exhausted anomaly must stay visible, not vanish) and the
    # next tick answers with ONE visible denial
    assert t.maybe_autocapture("slow_step")
    assert t.tick(5)["action"] == "denied"
    assert not t.capture_state()["capturing"]
    t.request_capture("manual3")
    assert t.tick(6) is None  # denial reported once, not per request
    assert t.captures_used == 1  # the denied requests never started
    spans = read_spans(tmp_path)
    names = [s["name"] for s in spans]
    assert "capture_start" in names and "capture_end" in names
    assert "detail_during_capture" in names


def test_trigger_file_arms_capture(tmp_path):
    t = Tracer(str(tmp_path), "off", trigger_poll_secs=0.0,
               capture_steps=2, capture_budget=3)
    trigger = tmp_path / TRIGGER_FILENAME
    trigger.write_text("")
    evt = t.tick(10)
    assert evt["action"] == "start" and evt["reason"] == "trigger_file"
    assert not trigger.exists()  # consumed: re-touch re-arms
    # a touch DURING the active window queues (the file is consumed either
    # way — dropping the request would make the operator's touch vanish):
    # the next capture starts on the first tick after this window ends
    trigger.write_text("")
    assert t.tick(11) is None           # window step 1; request queued
    assert not trigger.exists()
    assert t.tick(12)["action"] == "end"
    evt = t.tick(13)
    assert evt["action"] == "start" and evt["reason"] == "trigger_file"
    assert t.captures_used == 2


def test_sigusr1_arms_capture(tmp_path):
    t = Tracer(str(tmp_path), "off")
    prev = signal.getsignal(signal.SIGUSR1)
    assert t.install_signal()
    try:
        signal.raise_signal(signal.SIGUSR1)
        evt = t.tick(1)
        assert evt["action"] == "start" and evt["reason"] == "sigusr1"
    finally:
        t.close()
    assert signal.getsignal(signal.SIGUSR1) is prev


def test_detectors():
    det = SlowSampleDetector(k=3.0, min_samples=4, floor_s=0.01)
    for _ in range(4):
        assert not det.observe(0.1)  # builds the window
    assert not det.observe(0.2)      # 2x: not anomalous
    assert det.observe(1.0)          # >3x p95
    # last_p95 is the PRE-append threshold the anomaly violated (p95 of
    # [0.1 x4, 0.2]) — the post-append p95 could be the anomaly itself
    assert det.last_p95 == pytest.approx(0.2)
    assert not det.observe(0.005)    # below floor regardless of window
    det2 = SlowSampleDetector(k=3.0, min_samples=8)
    assert not det2.observe(100.0)   # too few samples: never fires

    # warmup skip: compile-scale samples are discarded, not windowed —
    # without it two warmup steps put k*p95 at compile scale forever
    det3 = SlowSampleDetector(k=3.0, min_samples=4, skip=2)
    assert not det3.observe(5.0) and not det3.observe(3.0)  # skipped
    for _ in range(4):
        assert not det3.observe(0.02)
    assert det3.p95() == pytest.approx(0.02)  # warmup never entered
    assert det3.observe(1.0)

    spike = SpikeDetector(min_events=3, window_s=60.0)
    now = 1000.0
    assert not spike.note(now) and not spike.note(now + 1)
    assert spike.note(now + 2)       # 3 within the window
    assert not spike.note(now + 3)   # cleared after firing
    assert not SpikeDetector(min_events=0).note()  # disabled


# ---------------------------------------------------------------------------
# events.jsonl joins the timeline (registry stamp)
# ---------------------------------------------------------------------------


def test_registry_stamp_lands_on_every_record(tmp_path):
    path = str(tmp_path / "events.jsonl")
    reg = MetricsRegistry(path, flush_every=1,
                          stamp={"run_id": "r1", "trace_id": "t1"})
    reg.emit("step", step=1)
    reg.emit("event", event="x", run_id="explicit-wins")
    reg.close()
    records = [json.loads(l) for l in open(path)]
    assert records[0]["run_id"] == "r1" and records[0]["trace_id"] == "t1"
    assert records[1]["run_id"] == "explicit-wins"


# ---------------------------------------------------------------------------
# StepPhaseTimer: explicit telemetry sub-phase (satellite fix)
# ---------------------------------------------------------------------------


def test_timer_books_telemetry_subphase_out_of_data():
    timer = StepPhaseTimer(stride=0)
    timer.epoch_start()
    time.sleep(0.03)           # the "telemetry + loader wait" window
    timer.note_telemetry(0.01)  # what the span layer says it spent of it
    timer.mark_data()
    timer.mark_dispatch()
    phases = timer.finish_step()
    assert phases["telemetry_s"] == pytest.approx(0.01)
    assert phases["data_s"] >= 0.015  # the wait minus the telemetry share
    assert phases["data_s"] + phases["telemetry_s"] <= phases["step_s"] + 1e-6
    # next step: booking reset
    timer.mark_data()
    timer.mark_dispatch()
    assert "telemetry_s" not in timer.finish_step()


def test_timer_telemetry_subphase_clamped_to_window():
    timer = StepPhaseTimer(stride=0)
    timer.epoch_start()
    timer.note_telemetry(10.0)  # absurd claim: clamp to the real window
    timer.mark_data()
    timer.mark_dispatch()
    phases = timer.finish_step()
    assert phases["data_s"] == 0.0
    assert phases["telemetry_s"] <= phases["step_s"]


# ---------------------------------------------------------------------------
# import diet: trace.py (and the supervisor through it) without jax/numpy
# ---------------------------------------------------------------------------


def test_trace_and_supervisor_import_without_jax_or_numpy():
    code = textwrap.dedent("""
        import sys
        class Block:
            def find_module(self, name, path=None):
                root = name.split('.')[0]
                if root in ('jax', 'jaxlib', 'numpy', 'flax', 'optax',
                            'orbax', 'scipy'):
                    raise ImportError('blocked heavy import: ' + name)
        sys.meta_path.insert(0, Block())
        import moco_tpu.telemetry.trace as trace
        import moco_tpu.resilience.supervisor as sup
        t = trace.Tracer(None, 'off')
        assert t.span('x') is trace.NULL_SPAN
        print('CLEAN')
    """)
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "CLEAN" in out.stdout


# ---------------------------------------------------------------------------
# cross-process propagation: supervisor -> child (driver) -> staging worker
# ---------------------------------------------------------------------------

# The child is a REAL consumer of the staging pipeline: it builds a
# Prefetcher (full trace mode) over a synthetic dataset, so its staging
# WORKER threads write decode_slice spans continuing the coordinator's
# stage_batch spans — which parent under the child root span, which
# parents under the supervisor's per-launch span via the env stamp.
_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, sys.argv[1])
    tdir = sys.argv[2]
    from moco_tpu.telemetry.trace import Tracer
    import numpy as np
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.data.loader import Prefetcher
    from moco_tpu.parallel.mesh import create_mesh

    tracer = Tracer(tdir, "full", proc="driver")  # env ids from supervisor
    mesh = create_mesh(1)
    ds = SyntheticDataset(num_samples=64, image_size=8)
    with tracer.span("driver_root", cat="driver") as root:
        pf = Prefetcher(ds, np.arange(32), 8, mesh, workers=2,
                        tracer=tracer)
        try:
            batches = list(pf)
        finally:
            pf.close_quietly()
        assert len(batches) == 4
    tracer.close()
""")


@pytest.fixture(scope="module")
def supervised_trace_run(tmp_path_factory):
    from moco_tpu.resilience.supervisor import RestartPolicy, Supervisor

    tmp_path = tmp_path_factory.mktemp("trace_prop")
    tdir = tmp_path / "telemetry"
    child_py = tmp_path / "child.py"
    child_py.write_text(_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(ENV_RUN_ID, None)
    env.pop(ENV_TRACE_PARENT, None)
    sup = Supervisor(
        [sys.executable, str(child_py), REPO, str(tdir)],
        telemetry_dir=str(tdir),
        env=env,
        force_resume=False,
        # the stub writes no heartbeat: hang detection off
        policy=RestartPolicy(heartbeat_stale_secs=0.0, poll_secs=0.1),
        seed=0,
    )
    result = sup.run()
    return sup, result, tdir


def test_trace_propagation_one_run_one_parent_chain(supervised_trace_run):
    sup, result, tdir = supervised_trace_run
    assert result.final_class == "clean", result
    spans = read_spans(tdir)
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # ONE run_id and ONE trace_id across supervisor, driver and workers
    assert {s["run"] for s in spans} == {sup.run_id}
    assert len({s["trace"] for s in spans}) == 1
    launch = by_name["child"][0]          # supervisor's per-launch span
    root = by_name["driver_root"][0]      # child process root
    stage = by_name["stage_batch"]        # coordinator, per batch
    slices = by_name["decode_slice"]      # staging workers (full detail)
    assert launch["proc"] == "supervisor"
    assert root["proc"] == "driver"
    # the parent CHAIN: worker slice -> stage_batch -> driver_root ->
    # supervisor launch span
    assert root["parent"] == launch["span"]
    assert all(s["parent"] == root["span"] for s in stage)
    stage_ids = {s["span"] for s in stage}
    assert slices and all(sl["parent"] in stage_ids for sl in slices)
    # worker spans really came from the worker threads
    assert any(sl["thread"].startswith("staging-w") for sl in slices)
    assert len(stage) == 4
    # supervisor lifecycle records carry the same run id
    events, _ = telemetry_report.load_events(
        os.path.join(str(tdir), "events.jsonl"))
    sup_records = [r for r in events if r.get("kind") == "supervisor"]
    assert sup_records and all(
        r.get("run_id") == sup.run_id for r in sup_records)


def test_trace_report_chrome_schema(supervised_trace_run, tmp_path):
    sup, _result, tdir = supervised_trace_run
    out = tmp_path / "trace.json"
    rc = trace_report.main([str(tdir), "-o", str(out), "--json"])
    assert rc == 0
    # the summary object is the last stdout line — re-run capturing it via
    # the module API instead
    data = trace_report.filter_run(
        trace_report.collect([str(tdir)]), sup.run_id)
    summary = trace_report.summarize(data)
    assert summary["run_ids"] == [sup.run_id]
    assert summary["spans_by_proc"]["supervisor"] >= 1
    assert summary["spans_by_proc"]["driver"] >= 5
    chrome = json.loads(out.read_text())
    events = chrome["traceEvents"]
    assert isinstance(events, list) and events
    phs = {e["ph"] for e in events}
    assert phs <= {"X", "i", "M"}
    spans = [e for e in events if e["ph"] == "X"]
    assert spans
    for e in spans:
        assert isinstance(e["name"], str)
        assert isinstance(e["ts"], float) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["args"]["run_id"] == sup.run_id
    # instants from events.jsonl (supervisor lifecycle) made it in
    assert any(e["ph"] == "i" and e["cat"] == "supervisor" for e in events)
    # every pid got a process_name metadata track
    meta_pids = {e["pid"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
    assert {e["pid"] for e in spans} <= meta_pids
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"supervisor", "driver"} <= names


# ---------------------------------------------------------------------------
# telemetry_report --follow (satellite)
# ---------------------------------------------------------------------------


def test_follow_renders_lines_and_survives_partial_writes(tmp_path):
    path = str(tmp_path / "events.jsonl")
    out = io.StringIO()
    stop = threading.Event()
    th = threading.Thread(
        target=telemetry_report.follow,
        args=(path, out, 0.02, stop), daemon=True)
    th.start()
    try:
        time.sleep(0.1)  # starts before the file exists
        with open(path, "w") as f:
            f.write(json.dumps({"v": 1, "kind": "step", "step": 3,
                                "step_s": 0.025, "data_s": 0.005,
                                "imgs_per_sec": 640.0, "loss": 2.5}) + "\n")
            f.write(json.dumps({"v": 1, "kind": "supervisor",
                                "event": "launch", "pid": 7}) + "\n")
            f.flush()
            # a PARTIAL line: must not be rendered (or crash) until its
            # newline lands
            f.write('{"v": 1, "kind": "event", "eve')
            f.flush()
            deadline = time.time() + 5.0
            while out.getvalue().count("\n") < 2 and time.time() < deadline:
                time.sleep(0.02)
            rendered = out.getvalue()
            assert "step      3" in rendered and "loss 2.5" in rendered
            assert "supervisor: launch pid=7" in rendered
            assert rendered.count("\n") == 2  # partial line still buffered
            f.write('nt": "rollback", "msg": "boom"}\n')
            f.flush()
        deadline = time.time() + 5.0
        while "[rollback]" not in out.getvalue() and time.time() < deadline:
            time.sleep(0.02)
        assert "[rollback] boom" in out.getvalue()
    finally:
        stop.set()
        th.join(timeout=5.0)


def test_follow_render_record_shapes():
    assert telemetry_report.render_record({"kind": "pod"}) is None
    line = telemetry_report.render_record(
        {"kind": "run_start", "name": "x", "arch": "r18",
         "batch_size": 8, "run_id": "abc"})
    assert "run_id=abc" in line
    line = telemetry_report.render_record(
        {"kind": "serve", "requests": 10, "served": 9,
         "latency_ms": {"p95": 12.0}, "queue_depth": 1})
    assert "9/10 served" in line


# ---------------------------------------------------------------------------
# R12 lint fixtures (satellite)
# ---------------------------------------------------------------------------

sys.path.insert(0, REPO)
from tools.mocolint.config import DEFAULT_CONFIG  # noqa: E402
from tools.mocolint.engine import Engine  # noqa: E402


def _lint(tmp_path, rel, body):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return Engine(DEFAULT_CONFIG, select=("R12",)).run([str(path)]).findings


def test_r12_flags_bare_span_open(tmp_path):
    findings = _lint(tmp_path, "moco_tpu/serve/thing.py", """
        def f(tracer):
            sp = tracer.span("x")
            do_work()
    """)
    assert len(findings) == 1 and findings[0].rule == "R12"
    assert "context-manager" in findings[0].message


def test_r12_accepts_with_and_retroactive(tmp_path):
    findings = _lint(tmp_path, "moco_tpu/serve/thing.py", """
        import time
        def f(tracer):
            with tracer.span("x") as sp:
                do_work()
            tracer.record_span("retro", time.time(), 0.1)
            tracer.instant("marker")
    """)
    assert findings == []


def test_r12_flags_nonstdlib_import_in_trace_py(tmp_path):
    findings = _lint(tmp_path, "moco_tpu/telemetry/trace.py", """
        import os

        def f():
            import numpy as np
            return np.zeros(3)
    """)
    assert len(findings) == 1
    assert "numpy" in findings[0].message and "(lazy)" in findings[0].message
    # and the real trace.py is clean under the full default gate (the
    # repo-wide tier-1 gate test in test_mocolint covers the rest)
    real = Engine(DEFAULT_CONFIG, select=("R12",)).run(
        [os.path.join(REPO, "moco_tpu", "telemetry", "trace.py")])
    assert real.findings == []


# ---------------------------------------------------------------------------
# RunTelemetry heartbeat surfacing (satellite)
# ---------------------------------------------------------------------------


def test_heartbeat_carries_trace_state_and_last_step_ms(tmp_path, mesh8):
    from moco_tpu.config import get_preset
    from moco_tpu.telemetry import RunTelemetry
    from moco_tpu.utils.meters import Throughput

    config = get_preset("cifar10-moco-v1").replace(
        telemetry_dir=str(tmp_path), trace_mode="steps",
        heartbeat_secs=0.0, peak_flops_per_chip=1e12,
    )
    tel = RunTelemetry(config, n_chips=1, n_procs=1, process_index=0,
                       steps_per_epoch=10)
    try:
        tel.timer.epoch_start()
        tel.timer.mark_data()
        tel.timer.mark_dispatch()
        phases = tel.timer.finish_step()
        tel.on_step(1, phases, Throughput(1))
        hb = json.load(open(tmp_path / "heartbeat.json"))
        assert hb["phase"] == "step"
        assert hb["last_step_ms"] >= 0
        assert hb["trace"] == {"capturing": False, "window_steps_left": 0,
                               "captures_used": 0, "capture_budget": 3}
    finally:
        tel.close()
    # the final run_end beat keeps the trace state too
    hb = json.load(open(tmp_path / "heartbeat.json"))
    assert hb["phase"] == "run_end" and "trace" in hb


# ---------------------------------------------------------------------------
# serve: batcher spans + shed-spike arming
# ---------------------------------------------------------------------------


def test_batcher_records_flush_and_request_spans(tmp_path):
    import numpy as np

    from moco_tpu.serve.batcher import MicroBatcher

    tracer = Tracer(str(tmp_path), "full", proc="serve")
    mb = MicroBatcher(lambda x: np.asarray(x, np.float32).sum(axis=(1,)),
                      buckets=(1, 4), flush_ms=5.0, max_queue=16,
                      tracer=tracer)
    try:
        pending = [mb.submit(np.full((3,), i, np.uint8)) for i in range(3)]
        for p in pending:
            p.wait(timeout=5.0)
    finally:
        mb.close()
    tracer.flush()
    spans = read_spans(tmp_path)
    flushes = [s for s in spans if s["name"] == "flush_batch"]
    requests = [s for s in spans if s["name"] == "request"]
    engines = [s for s in spans if s["name"] == "engine"]
    assert flushes and engines and len(requests) == 3
    assert all(r["attrs"]["outcome"] == "ok" for r in requests)
    # requests correlate to their flush via the shared seq attr
    seqs = {f["attrs"]["seq"] for f in flushes}
    assert {r["attrs"]["seq"] for r in requests} <= seqs
    # the engine span nests inside its flush span
    assert all(e["parent"] in {f["span"] for f in flushes} for e in engines)


def test_batcher_shed_spike_arms_capture(tmp_path):
    import numpy as np

    from moco_tpu.serve.batcher import MicroBatcher, OverloadedError

    tracer = Tracer(str(tmp_path), "off", capture_budget=1,
                    trigger_poll_secs=1e9)
    release = threading.Event()

    def slow_batch(x):
        release.wait(10.0)
        return np.zeros((len(x), 2), np.float32)

    mb = MicroBatcher(slow_batch, buckets=(1,), flush_ms=0.0, max_queue=1,
                      tracer=tracer, shed_spike_min=3)
    try:
        mb.submit(np.zeros(2, np.uint8))   # occupies the flusher
        time.sleep(0.1)
        mb.submit(np.zeros(2, np.uint8))   # fills the queue
        sheds = 0
        for _ in range(4):
            with pytest.raises(OverloadedError):
                mb.submit(np.zeros(2, np.uint8))
            sheds += 1
        assert sheds == 4
        # the spike (>= 3 sheds in the window) armed a pending capture
        assert tracer.tick(1)["reason"] == "shed_spike"
    finally:
        release.set()
        mb.close(drain=False)


# ---------------------------------------------------------------------------
# acceptance smoke: 30-step CPU train, chaos slow step, one auto-capture
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_chaos_run(mesh8, tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("trace_smoke")
    from moco_tpu.config import get_preset
    from moco_tpu.train import train

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=16,
        num_negatives=64, embed_dim=32, lr=0.1, epochs=2, steps_per_epoch=15,
        ckpt_dir="", tb_dir="", print_freq=5, num_classes=10,
        knn_monitor=False, staging_workers=2,
        telemetry_dir=str(tmp_path / "telemetry"),
        telemetry_flush_steps=8, telemetry_stride=5,
        peak_flops_per_chip=1e12,
        trace_mode="steps", trace_capture_steps=4, trace_capture_budget=1,
        # a 2 s stall at step 20: a blowout no honest p95 multiple misses
        chaos="slow_at_step=20,slow_ms=2000",
    )
    state, metrics = train(config, mesh8)
    return config, state, metrics


def _events(config):
    records, skipped = telemetry_report.load_events(
        os.path.join(config.telemetry_dir, "events.jsonl"))
    assert skipped == 0
    return records


def test_chaos_slow_step_auto_captures_once_within_budget(traced_chaos_run):
    config, state, _metrics = traced_chaos_run
    assert int(state.step) == 30
    records = _events(config)
    anomalies = [r for r in records if r.get("event") == "trace_anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["anomaly"] == "slow_step"
    assert anomalies[0]["step"] == 20
    captures = [r for r in records if r.get("event") == "trace_capture"]
    actions = [c["action"] for c in captures]
    assert actions == ["start", "end"]  # exactly ONE window, within budget
    assert captures[0]["reason"] == "slow_step"
    assert captures[0]["captures_used"] == 1
    ends = [r for r in records if r.get("kind") == "run_end"]
    assert ends[0]["trace"]["captures_used"] == 1
    assert ends[0]["trace"]["capture_budget"] == 1
    # every record joined the timeline: one run_id stream-wide
    run_ids = {r.get("run_id") for r in records}
    assert len(run_ids) == 1 and None not in run_ids
    # the slow step is visible in the record itself
    slow = next(r for r in records
                if r.get("kind") == "step" and r.get("step") == 20)
    assert slow["step_s"] >= 2.0
    # the telemetry sub-phase rides the stream (booked every step)
    assert any("telemetry_s" in r for r in records
               if r.get("kind") == "step")


def test_chaos_run_spans_elevate_during_capture(traced_chaos_run):
    config, _state, _metrics = traced_chaos_run
    spans = read_spans(config.telemetry_dir)
    steps = [s for s in spans if s["cat"] == "step"]
    assert len(steps) == 30  # trace_mode=steps: one span per step
    stage = [s for s in spans if s["name"] == "stage_batch"]
    assert stage  # coordinator spans at the coarse level
    # the capture window (steps ~21-24) recorded FULL detail: staging
    # worker decode slices appear only there
    slices = [s for s in spans if s["name"] == "decode_slice"]
    assert slices
    assert any(s["thread"].startswith("staging-w") for s in slices)
    cap_names = [s["name"] for s in spans if s["cat"] == "capture"]
    assert cap_names.count("capture_start") == 1
    assert cap_names.count("capture_end") == 1


def test_chaos_run_trace_report_merges_and_summarizes(traced_chaos_run,
                                                      tmp_path):
    config, _state, _metrics = traced_chaos_run
    out = tmp_path / "trace.json"
    rc = trace_report.main([config.telemetry_dir, "-o", str(out)])
    assert rc == 0
    chrome = json.loads(out.read_text())
    assert {e["ph"] for e in chrome["traceEvents"]} <= {"X", "i", "M"}
    data = trace_report.collect([config.telemetry_dir])
    summary = trace_report.summarize(data)
    assert summary["steps"] == 30
    assert summary["step_time_ms"]["p95"] > 0
    share = summary["phase_share"]
    assert "data" in share and "host" in share and "telemetry" in share
    assert "critical_path" in summary
    assert summary["captures"]
    assert summary["anomalies"][0]["anomaly"] == "slow_step"
    rendered = trace_report.render(summary)
    assert "critical path" in rendered and "capture: slow_step" in rendered


def test_chaos_run_heartbeat_final_state(traced_chaos_run):
    config, _state, _metrics = traced_chaos_run
    hb = json.load(open(os.path.join(config.telemetry_dir,
                                     "heartbeat.json")))
    assert hb["phase"] == "run_end"
    assert hb["trace"]["captures_used"] == 1
    assert not hb["trace"]["capturing"]


# ---------------------------------------------------------------------------
# full acceptance scenario, end to end out of process (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_train_chaos_slow_step_full_timeline(tmp_path):
    """ISSUE 8 acceptance, the whole sentence at once: a 30-step CPU train
    UNDER THE REAL SUPERVISOR with chaos slow-step injection; the anomaly
    detector auto-captures within budget, and trace_report emits a single
    valid Chrome-trace JSON merging supervisor, driver and staging-worker
    spans under the supervisor's one run_id."""
    from moco_tpu.resilience.supervisor import RestartPolicy, Supervisor

    tdir = tmp_path / "telemetry"
    env = dict(os.environ, JAX_PLATFORMS="cpu", MOCO_TPU_NO_CACHE="1")
    env.pop(ENV_RUN_ID, None)
    env.pop(ENV_TRACE_PARENT, None)
    child = [
        sys.executable, "-m", "moco_tpu.train",
        "--preset", "cifar10-moco-v1", "--fake-devices", "8",
        "--arch", "resnet_tiny", "--dataset", "synthetic",
        "--image-size", "16", "--batch-size", "16",
        "--num-negatives", "64", "--embed-dim", "32", "--lr", "0.1",
        "--epochs", "2", "--steps-per-epoch", "15", "--print-freq", "1000",
        "--knn-monitor", "false", "--num-classes", "10",
        "--watchdog-secs", "0", "--staging-workers", "2", "--ckpt-dir", "",
        "--telemetry-dir", str(tdir), "--telemetry-flush-steps", "8",
        "--heartbeat-secs", "0.05",
        # the window outlives the run (30 steps): the final run_end
        # heartbeat still says capturing=True, so the supervisor's
        # post-exit read surfaces it deterministically even though the
        # post-anomaly steps take milliseconds
        "--trace-mode", "steps", "--trace-capture-steps", "2000",
        "--trace-capture-budget", "1",
        "--chaos", "slow_at_step=20,slow_ms=3000",
    ]
    sup = Supervisor(
        child, telemetry_dir=str(tdir), env=env, force_resume=False,
        policy=RestartPolicy(heartbeat_stale_secs=60.0,
                             startup_grace_secs=600.0, poll_secs=0.2),
        seed=0,
    )
    result = sup.run()
    assert result.final_class == "clean", result
    spans = read_spans(tdir)
    assert {s["run"] for s in spans} == {sup.run_id}
    procs = {s["proc"] for s in spans}
    assert {"supervisor", "driver"} <= procs
    threads = {s["thread"] for s in spans}
    assert any(t.startswith("staging-") for t in threads)
    records, _ = telemetry_report.load_events(
        os.path.join(str(tdir), "events.jsonl"))
    captures = [r for r in records if r.get("event") == "trace_capture"]
    # the window was still open at run end (capture_steps > run length):
    # one start, and close() truncates it via a capture_end span
    assert [c["action"] for c in captures] == ["start"]
    assert captures[0]["reason"] == "slow_step"
    assert any(s["name"] == "capture_end"
               and (s.get("attrs") or {}).get("truncated") for s in spans)
    # the supervisor saw "currently profiling" from the heartbeat alone
    child_trace = [r for r in records if r.get("event") == "child_trace"]
    assert any(r.get("capturing") for r in child_trace)
    # one merged, valid Chrome trace
    out = tmp_path / "trace.json"
    assert trace_report.main([str(tdir), "-o", str(out),
                              "--run", sup.run_id]) == 0
    chrome = json.loads(out.read_text())
    span_events = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert {e["args"]["run_id"] for e in span_events} == {sup.run_id}
    assert len({e["pid"] for e in span_events}) >= 2  # supervisor + driver
