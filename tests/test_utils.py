"""Meters + logging utilities (the reference's AverageMeter/ProgressMeter
semantics, `main_moco.py:≈L330-375`)."""

import time

from moco_tpu.utils.logging import ProfilerWindow, ScalarWriter
from moco_tpu.utils.meters import AverageMeter, ProgressMeter, Throughput


def test_average_meter_running_average():
    m = AverageMeter("Loss", ":.2f")
    m.update(2.0, n=2)
    m.update(4.0, n=2)
    assert m.val == 4.0
    assert m.avg == 3.0
    assert str(m) == "Loss 4.00 (3.00)"
    m.reset()
    assert m.avg == 0.0


def test_progress_meter_format(capsys):
    m = AverageMeter("Loss", ":.1f")
    m.update(1.5)
    p = ProgressMeter(100, [m], prefix="Epoch: [3]")
    p.display(7)
    out = capsys.readouterr().out
    assert "Epoch: [3][  7/100]" in out
    assert "Loss 1.5 (1.5)" in out


def test_throughput_per_chip():
    t = Throughput(num_chips=8)
    t._t0 = time.perf_counter() - 2.0  # pretend 2 s elapsed
    t.update(1000)
    assert 400 < t.imgs_per_sec < 600
    # the two properties sample the clock independently — compare loosely
    assert abs(t.imgs_per_sec_per_chip - t.imgs_per_sec / 8) < 1.0


def test_scalar_writer_noop_without_dir(tmp_path):
    w = ScalarWriter("")
    w.write(0, {"loss": 1.0})  # must not raise
    w.close()


def test_scalar_writer_skips_unconvertible(tmp_path):
    try:
        import tensorboardX  # noqa: F401
    except ImportError:
        return
    w = ScalarWriter(str(tmp_path / "tb"))
    w.write(1, {"ok": 2.0, "bad": object()})  # bad value skipped, no raise
    w.close()


def test_profiler_window_inactive_without_dir():
    p = ProfilerWindow("", start=5, stop=10)
    for step in range(20):
        p.maybe_toggle(step)  # must never start a trace
    assert p._active is False
    p.close()
