"""ZeRO-1 optimizer-state sharding (parallel/zero.py): identical numerics,
a real footprint cut, and placement that survives the step (no silent
re-replication by the partitioner)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from moco_tpu.config import PretrainConfig, get_preset
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.parallel.zero import opt_state_shardings, shard_opt_state
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step

B, IMG, DIM, K = 16, 16, 16, 64


def _setup(mesh):
    config = PretrainConfig(
        variant="v2", arch="resnet_tiny", cifar_stem=True, mlp_head=True,
        num_negatives=K, embed_dim=DIM, batch_size=B, epochs=2, lr=0.1,
    )
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_train_state(
        jax.random.key(0), model, tx, (B // mesh.size, IMG, IMG, 3), K, DIM
    )
    step = build_train_step(config, model, tx, mesh, 8, sched)
    return state, step


def test_sharding_specs_pick_divisible_axes(mesh8):
    state, _ = _setup(mesh8)
    specs = opt_state_shardings(state.opt_state, mesh8)
    sharded = [
        (jax.tree_util.keystr(p), s.spec)
        for (p, s) in jax.tree_util.tree_leaves_with_path(specs)
        if s.spec != P()
    ]
    assert sharded, "no optimizer leaf got sharded"
    for path, spec in sharded:
        assert DATA_AXIS in tuple(spec), (path, spec)
    # a [3,3,16,16] conv momentum shards its channel axis (16 % 8 == 0),
    # never the kernel axes (3 % 8 != 0)
    leaves = dict(
        (jax.tree_util.keystr(p), (l.shape, s.spec))
        for (p, l), (_, s) in zip(
            jax.tree_util.tree_leaves_with_path(state.opt_state),
            jax.tree_util.tree_leaves_with_path(specs),
            strict=True,
        )
    )
    conv_rows = [(shape, spec) for shape, spec in leaves.values()
                 if len(shape) == 4 and shape[:2] == (3, 3)]
    assert conv_rows
    for shape, spec in conv_rows:
        assert spec[0] is None and spec[1] is None, (shape, spec)


def test_zero_step_identical_numerics_and_smaller_footprint(mesh8):
    """One step from identical inits, ZeRO placement vs replicated: params
    and queue equal to float-reduction tolerance (the partition boundary
    changes XLA fusion order by ~1e-7 relative); per-device optimizer bytes
    cut ~mesh-fold; the output opt_state KEEPS the ZeRO placement."""
    state_a, step = _setup(mesh8)
    state_b, _ = _setup(mesh8)
    state_b = state_b.replace(opt_state=shard_opt_state(state_b.opt_state, mesh8))

    im_q = jax.random.normal(jax.random.key(1), (B, IMG, IMG, 3))
    im_k = jax.random.normal(jax.random.key(2), (B, IMG, IMG, 3))
    # two steps so momentum (built in step 1) feeds the step-2 update
    sa, _ = step(state_a, im_q, im_k)
    sa, ma = step(sa, im_q, im_k)
    sb, _ = step(state_b, im_q, im_k)
    sb, mb = step(sb, im_q, im_k)

    np.testing.assert_allclose(np.asarray(ma["loss"]), np.asarray(mb["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(sa.params_q), jax.tree.leaves(sb.params_q),
                    strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sa.queue), np.asarray(sb.queue),
                               rtol=1e-5, atol=1e-6)

    def device0_bytes(opt_state):
        total = 0
        for leaf in jax.tree.leaves(opt_state):
            if hasattr(leaf, "addressable_shards"):
                shard = leaf.addressable_shards[0]
                total += np.prod(shard.data.shape) * leaf.dtype.itemsize
        return total

    assert device0_bytes(sb.opt_state) < 0.4 * device0_bytes(sa.opt_state)
    # placement survives the jitted step: no silent re-replication
    specs = opt_state_shardings(state_b.opt_state, mesh8)
    for (path, leaf), (_, want) in zip(
        jax.tree_util.tree_leaves_with_path(sb.opt_state),
        jax.tree_util.tree_leaves_with_path(specs),
        strict=True,
    ):
        if want.spec != P() and hasattr(leaf, "sharding"):
            def _norm(spec):  # XLA may drop trailing Nones
                t = tuple(spec)
                while t and t[-1] is None:
                    t = t[:-1]
                return t

            assert _norm(leaf.sharding.spec) == _norm(want.spec), (
                jax.tree_util.keystr(path), leaf.sharding.spec, want.spec)


@pytest.mark.slow
def test_zero_through_driver(mesh8):
    from moco_tpu.train import train

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=32,
        num_negatives=64, embed_dim=16, epochs=1, steps_per_epoch=4,
        zero_sharding=True, knn_monitor=False, ckpt_dir="", print_freq=2,
    )
    state, metrics = train(config, mesh8)
    assert int(state.step) == 4
    assert np.isfinite(metrics["loss"])


@pytest.mark.slow
def test_zero_checkpoint_roundtrip(mesh8, tmp_path):
    """A ZeRO run checkpoints its sharded opt_state and resumes bit-faithful:
    Orbax saves the sharded arrays, maybe_resume restores replicated, and the
    driver re-shards after resume (train() ordering) — end to end through the
    real driver."""
    from moco_tpu.train import train

    base = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=32,
        num_negatives=64, embed_dim=16, epochs=2, steps_per_epoch=4,
        zero_sharding=True, knn_monitor=False, print_freq=100,
        ckpt_dir=str(tmp_path / "ckpt"),
    )
    state_a, _ = train(base.replace(ckpt_dir=""), mesh8)           # 8 steps straight
    state_mid, _ = train(base, mesh8, max_steps=4)                  # epoch 1 + save
    assert int(state_mid.step) == 4
    import os

    # the save really happened — otherwise run 3 retrains from scratch and
    # the roundtrip assertions pass vacuously
    assert sorted(int(d) for d in os.listdir(tmp_path / "ckpt")) == [4]
    state_b, _ = train(base.replace(resume="auto"), mesh8)          # resume to 8

    assert int(state_a.step) == int(state_b.step) == 8
    for a, b in zip(jax.tree.leaves(state_a.params_q),
                    jax.tree.leaves(state_b.params_q), strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # the resumed run's opt state is back in the ZeRO placement
    sharded = [l for l in jax.tree.leaves(state_b.opt_state)
               if hasattr(l, "sharding") and l.sharding.spec != P()]
    assert sharded, "resume dropped the ZeRO placement"
