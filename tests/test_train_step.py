"""End-to-end SPMD train-step tests on the 8-fake-device mesh (SURVEY §4
items 1-2): collectives + EMA + queue + optimizer composed exactly as the
real driver composes them, on a tiny ResNet so CPU compile stays fast."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.models.resnet import BasicBlock, ResNet
from moco_tpu.ops.ema import ema_update
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_optimizer, build_train_step

GLOBAL_B, IMG, DIM, K = 16, 8, 16, 64


def tiny_model():
    return ResNet(
        stage_sizes=(1, 1), block_cls=BasicBlock, width=8,
        cifar_stem=True, num_classes=DIM,
    )


@pytest.fixture(scope="module")
def setup(mesh8):
    config = PretrainConfig(
        variant="v1", num_negatives=K, embed_dim=DIM, temperature=0.07,
        lr=0.05, batch_size=GLOBAL_B, epochs=4, schedule=(2, 3),
    )
    model = tiny_model()
    tx, _ = build_optimizer(config, steps_per_epoch=4)
    state = create_train_state(
        jax.random.key(0), model, tx,
        (GLOBAL_B // 8, IMG, IMG, 3), K, DIM,
    )
    raw_step_fn = build_train_step(config, model, tx, mesh8, steps_per_epoch=4)

    def step_fn(s, im_q, im_k):
        # the step donates its input state (by design); tests reuse states, so
        # feed a copy and keep the original alive
        return raw_step_fn(jax.tree.map(jnp.copy, s), im_q, im_k)

    batches = [
        (
            jax.random.normal(jax.random.key(10 + i), (GLOBAL_B, IMG, IMG, 3)),
            jax.random.normal(jax.random.key(20 + i), (GLOBAL_B, IMG, IMG, 3)),
        )
        for i in range(3)
    ]
    return config, model, tx, state, step_fn, batches


def test_step_advances_and_metrics_finite(setup):
    config, model, tx, state, step_fn, batches = setup
    s = state
    for i, (im_q, im_k) in enumerate(batches):
        s, metrics = step_fn(s, im_q, im_k)
        assert int(s.step) == i + 1
        assert int(s.queue_ptr) == ((i + 1) * GLOBAL_B) % K
        assert np.isfinite(float(metrics["loss"]))
        assert 0.0 <= float(metrics["acc1"]) <= 100.0
    # Bounded sanity: CE over K+1 classes lies in [0, log(K+1)+slack]. (The
    # exact loss≈log(K+1) property needs INDEPENDENT random embeddings and is
    # pinned in test_losses; a fresh encoder's q/k are highly correlated, so
    # the positive dominates and the loss starts near zero.)
    _, m0 = step_fn(state, *batches[0])
    assert 0.0 <= float(m0["loss"]) <= np.log(K + 1) + 1.0


def test_key_params_move_only_by_ema(setup):
    """After one step, params_k must equal EMA(old_k, old_q) EXACTLY — no
    gradient may leak into the key encoder (`moco/builder.py` no_grad path)."""
    config, model, tx, state, step_fn, batches = setup
    new_state, _ = step_fn(state, *batches[0])
    expected = ema_update(state.params_k, state.params_q, config.momentum_ema)
    for a, b in zip(jax.tree.leaves(new_state.params_k), jax.tree.leaves(expected)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_query_params_change_and_queue_filled(setup):
    config, model, tx, state, step_fn, batches = setup
    new_state, _ = step_fn(state, *batches[0])
    changed = [
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(new_state.params_q), jax.tree.leaves(state.params_q)
        )
    ]
    assert all(changed)  # every tensor received gradient signal
    q = np.asarray(new_state.queue)
    # first GLOBAL_B rows replaced by fresh unit-norm keys, rest untouched
    np.testing.assert_allclose(np.linalg.norm(q[:GLOBAL_B], axis=1), 1.0, rtol=1e-4)
    np.testing.assert_array_equal(q[GLOBAL_B:], np.asarray(state.queue)[GLOBAL_B:])
    assert not np.allclose(q[:GLOBAL_B], np.asarray(state.queue)[:GLOBAL_B])


def test_determinism(setup):
    config, model, tx, state, step_fn, batches = setup
    s1, m1 = step_fn(state, *batches[0])
    s2, m2 = step_fn(state, *batches[0])
    assert float(m1["loss"]) == float(m2["loss"])
    for a, b in zip(jax.tree.leaves(s1.params_q), jax.tree.leaves(s2.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bn_stats_update_and_replicated(setup):
    config, model, tx, state, step_fn, batches = setup
    new_state, _ = step_fn(state, *batches[0])
    before = jax.tree.leaves(state.batch_stats_q)
    after = jax.tree.leaves(new_state.batch_stats_q)
    assert any(not np.allclose(a, b) for a, b in zip(before, after))
    after_k = jax.tree.leaves(new_state.batch_stats_k)
    before_k = jax.tree.leaves(state.batch_stats_k)
    assert any(not np.allclose(a, b) for a, b in zip(before_k, after_k))


def test_single_device_mesh_same_program(setup):
    """BASELINE config 1 is single-process: the SAME step program must run on
    a 1-device mesh (collectives degenerate to identity)."""
    from moco_tpu.parallel.mesh import create_mesh

    config, model, tx, state, step_fn, batches = setup
    mesh1 = create_mesh(1)
    fn1 = build_train_step(config, model, tx, mesh1, steps_per_epoch=4)
    s = jax.tree.map(jnp.copy, state)
    s, metrics = fn1(s, *batches[0])
    assert int(s.step) == 1
    assert int(s.queue_ptr) == GLOBAL_B % K
    assert np.isfinite(float(metrics["loss"]))


def test_ring_shuffle_mode(setup, mesh8):
    """shuffle_mode='ring' (SURVEY §2.11 ppermute variant) must run the full
    step with finite loss and keep the queue semantics identical."""
    config, model, tx, state, step_fn, batches = setup
    ring_cfg = config.replace(shuffle_mode="ring")
    fn = build_train_step(ring_cfg, model, tx, mesh8, steps_per_epoch=4)
    s, metrics = fn(jax.tree.map(jnp.copy, state), *batches[0])
    assert np.isfinite(float(metrics["loss"]))
    assert int(s.queue_ptr) == GLOBAL_B % K
    import pytest

    with pytest.raises(ValueError, match="unknown shuffle_mode"):
        build_train_step(config.replace(shuffle_mode="nope"), model, tx, mesh8, 4)


def test_lr_follows_step_schedule(setup):
    """Milestone schedule (2,3) with 4 steps/epoch: lr drops x0.1 at epoch 2."""
    config, model, tx, state, step_fn, batches = setup
    s = state
    lrs = []
    for i in range(12):
        s, metrics = step_fn(s, *batches[i % 3])
        lrs.append(float(metrics["lr"]))
    assert np.allclose(lrs[0], 0.05)
    assert np.allclose(lrs[8], 0.005)  # step 8 = epoch 2 → first milestone
