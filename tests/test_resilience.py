"""Fault-tolerance suite (ISSUE 1): every recovery path exercised by
INJECTED faults on CPU instead of trusted on faith.

The headline scenarios ride the real train() driver on 8 fake devices:
SIGTERM mid-epoch lands an emergency checkpoint whose resumed run is
bit-identical to the uninterrupted trajectory; a truncated latest
checkpoint falls back to the next-older verifiable step; an injected NaN
triggers a bounded rollback and the run completes unattended; a
structural NaN (one the data-window advance cannot fix) exhausts the
rollback budget and aborts for a human. Unit tests below pin each
resilience primitive in isolation.
"""

import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from moco_tpu.checkpoint import (
    checkpoint_manager,
    maybe_resume,
    restore_checkpoint,
    save_checkpoint,
)
from moco_tpu.config import get_preset
from moco_tpu.data.loader import Prefetcher
from moco_tpu.resilience import (
    ChaosPlan,
    DataQualityError,
    NaNSentinel,
    NonFiniteLossError,
    PreemptionHandler,
    RollbackExhaustedError,
    StepWatchdog,
    TransientDataError,
    chaos_context,
    parse_chaos_spec,
    truncate_checkpoint,
)
from moco_tpu.resilience.integrity import manifest_path, verify_step, write_manifest
from moco_tpu.train import train
from moco_tpu.train_state import create_train_state
from moco_tpu.utils.meters import RateMeter


def micro_config(tmp_path, **overrides):
    """Smallest config the real driver accepts on the 8-device CPU mesh."""
    base = dict(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=16,
        num_negatives=64, embed_dim=32, lr=0.1, epochs=3, steps_per_epoch=4,
        ckpt_dir=str(tmp_path / "ckpt"), tb_dir="", print_freq=1000,
        num_classes=10, knn_monitor=False,
    )
    base.update(overrides)
    return get_preset("cifar10-moco-v1").replace(**base)


def state_leaves(state):
    return jax.tree.leaves(state.replace(rng=jax.random.key_data(state.rng)))


# ---------------------------------------------------------------------------
# headline chaos scenarios (real driver, injected faults)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_sigterm_emergency_checkpoint_then_bitidentical_resume(mesh8, tmp_path):
    """Preemption mid-epoch loses ZERO progress: the emergency checkpoint +
    the mid-epoch resume_skip path reproduce the uninterrupted trajectory
    bit for bit (the resume-determinism contract of train.py, previously
    claimed but untested)."""
    ref = micro_config(tmp_path / "a")
    ref_state, ref_metrics = train(ref, mesh8)
    assert int(ref_state.step) == 12

    cfg = micro_config(tmp_path / "b")
    with chaos_context(ChaosPlan(sigterm_at_step=6)):
        mid_state, _ = train(cfg, mesh8)
    # step 6 is mid-epoch (epoch 1, batch 2 of 4): only the emergency path
    # can have checkpointed it
    assert int(mid_state.step) == 6
    assert "6" in os.listdir(cfg.ckpt_dir)
    assert os.path.exists(manifest_path(cfg.ckpt_dir, 6))

    resumed_state, resumed_metrics = train(cfg.replace(resume="auto"), mesh8)
    assert int(resumed_state.step) == 12
    for a, b in zip(state_leaves(resumed_state), state_leaves(ref_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert resumed_metrics["loss"] == ref_metrics["loss"]


@pytest.mark.chaos
def test_truncated_latest_checkpoint_falls_back(mesh8, tmp_path):
    """A partial/corrupt latest step (preempted writer) must not brick
    `--resume auto`: the restore walks back to the newest step that verifies
    against its integrity manifest."""
    from moco_tpu.models.resnet import ResNetTiny

    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state.replace(queue_ptr=jnp.asarray(3, jnp.int32)), 3)
    save_checkpoint(mgr, state.replace(queue_ptr=jnp.asarray(7, jnp.int32)), 7)
    truncate_checkpoint(str(tmp_path / "ckpt"), 7)

    fresh = create_train_state(jax.random.key(1), model, tx, (2, 16, 16, 3), 64, 32)
    restored = restore_checkpoint(mgr, fresh)  # step=None: newest verifiable
    assert int(restored.queue_ptr) == 3

    restored = maybe_resume(mgr, fresh, "auto")
    assert int(restored.queue_ptr) == 3

    # an EXPLICIT step still fails hard — the caller asked for that step,
    # silently substituting another would be worse than the crash
    with pytest.raises(Exception):
        restore_checkpoint(mgr, fresh, 7)


@pytest.mark.chaos
def test_all_checkpoints_corrupt_raises(mesh8, tmp_path):
    from moco_tpu.models.resnet import ResNetTiny

    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state, 5)
    truncate_checkpoint(str(tmp_path / "ckpt"), 5)
    with pytest.raises(FileNotFoundError, match="no restorable checkpoint"):
        restore_checkpoint(mgr, state)


@pytest.mark.chaos
def test_nan_rollback_completes_without_intervention(mesh8, tmp_path):
    """One poisoned step: the sentinel catches it the NEXT step, the driver
    restores the last good checkpoint, the data stream advances past the
    poisoned window, and the run finishes on its own."""
    cfg = micro_config(tmp_path, max_rollbacks=3)
    with chaos_context(ChaosPlan(nan_at_step=6)):
        state, metrics = train(cfg, mesh8)
    # restored at step 4 (epoch-0 checkpoint), epoch 1's poisoned window of
    # 2 batches skipped -> epoch 1 contributes 2 steps instead of 4
    assert int(state.step) == 10
    assert np.isfinite(metrics["loss"])


@pytest.mark.chaos
def test_nan_rollback_spans_epoch_boundaries(mesh8, tmp_path):
    """A poison in a LATER epoch than the restored checkpoint
    (ckpt_every_epochs > 1, or an integrity walk-back): the data-window
    advance must cross the epoch boundary — an advance clamped to the
    restored epoch would replay the poisoned batch on every retry. The
    window here is [step 4, step 7]: epoch 2 is skipped wholesale, epoch 3
    resumes AFTER its poisoned batch 0, so the run ends at step 5."""
    cfg = micro_config(tmp_path, epochs=4, steps_per_epoch=2,
                       ckpt_every_epochs=2, max_rollbacks=3, print_freq=1)
    with chaos_context(ChaosPlan(nan_at_step=7)):
        state, metrics = train(cfg, mesh8)
    assert int(state.step) == 5
    assert np.isfinite(metrics["loss"])


@pytest.mark.chaos
def test_cli_chaos_plan_cleared_after_train(mesh8, tmp_path):
    """A --chaos/config-installed plan must not outlive its train() call: a
    stale plan would make the next call's own spec silently vacuous (or fire
    this run's unspent faults into it)."""
    from moco_tpu.resilience import active_chaos

    cfg = micro_config(tmp_path, ckpt_dir="", epochs=1, chaos="nan_at_step=99")
    train(cfg, mesh8)  # the fault never fires (only 4 steps)
    assert active_chaos() is None


@pytest.mark.chaos
def test_cli_chaos_plan_gets_state_dir_from_env(mesh8, tmp_path, monkeypatch):
    """A --chaos plan under a supervisor must persist fire-once state via
    MOCO_TPU_CHAOS_STATE exactly like an env-installed plan — otherwise a
    supervised kill/freeze drill re-fires on every restart and crash-loops
    (ISSUE 4). Captured at clear time: the plan is scoped to train()."""
    import moco_tpu.train as train_mod
    from moco_tpu.resilience import active_chaos

    captured = {}
    real_clear = train_mod.clear_chaos

    def spy_clear():
        captured["plan"] = active_chaos()
        real_clear()

    monkeypatch.setattr(train_mod, "clear_chaos", spy_clear)
    monkeypatch.setenv("MOCO_TPU_CHAOS_STATE", str(tmp_path / "markers"))
    cfg = micro_config(tmp_path, ckpt_dir="", epochs=1, chaos="nan_at_step=99")
    train(cfg, mesh8)
    assert captured["plan"].state_dir == str(tmp_path / "markers")
    assert active_chaos() is None


@pytest.mark.chaos
def test_resume_after_rollback_drift_is_bitidentical(mesh8, tmp_path):
    """A NaN rollback's data-window skip permanently drifts the step↔batch
    mapping, so a LATER preemption must resume from the checkpoint's
    position sidecar — step arithmetic would replay already-consumed batches
    and silently diverge from the pre-preemption trajectory."""
    a = micro_config(tmp_path / "a", epochs=2)
    with chaos_context(ChaosPlan(nan_at_step=3)):
        ref_state, _ = train(a, mesh8)  # rollback at 3, drifts, ends at 5
    assert int(ref_state.step) == 5

    b = micro_config(tmp_path / "b", epochs=2)
    with chaos_context(ChaosPlan(nan_at_step=3, sigterm_at_step=4)):
        mid_state, _ = train(b, mesh8)  # same rollback, then preempted at 4
    assert int(mid_state.step) == 4
    res_state, _ = train(b.replace(resume="auto"), mesh8)
    assert int(res_state.step) == 5
    for x, y in zip(state_leaves(res_state), state_leaves(ref_state)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.chaos
def test_structural_nan_exhausts_rollbacks(mesh8, tmp_path):
    """A divergence that re-appears after the data-window advance is NOT a
    poisoned batch — after max_rollbacks consecutive rollbacks with no net
    progress the run aborts for a human instead of looping forever."""
    cfg = micro_config(tmp_path, steps_per_epoch=2, epochs=2, max_rollbacks=1)
    with chaos_context(ChaosPlan(nan_at_step=3, nan_count=10)):
        with pytest.raises(RollbackExhaustedError):
            train(cfg, mesh8)


@pytest.mark.chaos
def test_nan_without_checkpointing_raises_directly(mesh8, tmp_path):
    """No ckpt_dir means nothing to roll back to: the sentinel's error
    surfaces as-is instead of pretending recovery happened."""
    cfg = micro_config(tmp_path, ckpt_dir="", epochs=1)
    with chaos_context(ChaosPlan(nan_at_step=2)):
        with pytest.raises(NonFiniteLossError) as exc:
            train(cfg, mesh8)
    assert exc.value.step == 2


@pytest.mark.chaos
def test_loader_fault_retried_through_train(mesh8, tmp_path):
    """A transient read fault inside the Prefetcher worker is retried with
    backoff and the run completes — the full driver path, not just the
    loader unit test below."""
    cfg = micro_config(tmp_path, ckpt_dir="", epochs=1,
                       loader_retries=3, loader_backoff_secs=0.01)
    with chaos_context(ChaosPlan(loader_error_at_batch=1, loader_error_count=2)):
        state, metrics = train(cfg, mesh8)
    assert int(state.step) == 4
    assert np.isfinite(metrics["loss"])


class _PoisonedDataset:
    """Synthetic data whose decode telemetry reports every image failed —
    the systemic zero-canvas case the abort threshold exists for."""

    def __init__(self, inner):
        self._inner = inner
        self.num_classes = inner.num_classes
        self.decode_failures = 0
        self.decode_total = 0

    def __len__(self):
        return len(self._inner)

    def get_batch(self, indices):
        self.decode_total += len(indices)
        self.decode_failures += len(indices)
        return self._inner.get_batch(indices)


@pytest.mark.chaos
def test_decode_failure_rate_aborts(mesh8, tmp_path):
    from moco_tpu.data.datasets import SyntheticDataset

    cfg = micro_config(tmp_path, ckpt_dir="", epochs=1, decode_abort_rate=0.5)
    data = _PoisonedDataset(
        SyntheticDataset(num_samples=64, image_size=16, num_classes=10)
    )
    with pytest.raises(DataQualityError, match="decode-failure rate"):
        train(cfg, mesh8, dataset=data)


# ---------------------------------------------------------------------------
# integrity manifests
# ---------------------------------------------------------------------------


def _fake_step(tmp_path, step=5):
    d = tmp_path / str(step) / "inner"
    d.mkdir(parents=True)
    (d / "payload.bin").write_bytes(b"x" * 4096)
    (tmp_path / str(step) / "meta.json").write_text("{}")
    return str(tmp_path)


def test_async_save_defers_manifest_to_finalize(mesh8, tmp_path):
    """wait=False keeps the epoch save async (serialization overlaps the
    next epoch's compute): the manifest — which would certify an in-flight
    save — is only written by finalize_checkpoints, after Orbax commits."""
    from moco_tpu.checkpoint import finalize_checkpoints
    from moco_tpu.models.resnet import ResNetTiny

    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state, 3, wait=False)
    assert not os.path.exists(manifest_path(str(tmp_path / "ckpt"), 3))
    finalize_checkpoints(mgr)
    assert os.path.exists(manifest_path(str(tmp_path / "ckpt"), 3))
    assert verify_step(str(tmp_path / "ckpt"), 3) is None
    finalize_checkpoints(mgr)  # idempotent


def test_position_sidecar_roundtrip(tmp_path):
    from moco_tpu.checkpoint import read_position, write_position

    assert read_position(str(tmp_path), 7) is None
    write_position(str(tmp_path), 7, (2, 3))
    assert read_position(str(tmp_path), 7) == (2, 3)
    (tmp_path / ".position" / "7.json").write_text("null")  # corrupt
    assert read_position(str(tmp_path), 7) is None


def test_sidecar_pruning_follows_checkpoint_gc(mesh8, tmp_path):
    """Manifests/positions for steps the manager garbage-collected
    (max_to_keep) must be pruned — nothing reads them again, and a
    multi-day run would accumulate them without bound."""
    from moco_tpu.models.resnet import ResNetTiny

    model = ResNetTiny(num_classes=32, cifar_stem=True)
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(jax.random.key(0), model, tx, (2, 16, 16, 3), 64, 32)
    ckpt = str(tmp_path / "ckpt")
    mgr = checkpoint_manager(ckpt)  # max_to_keep=3
    for s in (1, 2, 3, 4, 5):
        save_checkpoint(mgr, state.replace(step=jnp.asarray(s, jnp.int32)), s,
                        position=(s, 0))
    kept = {str(s) for s in mgr.all_steps()}
    assert kept == {"3", "4", "5"}
    for sub in (".integrity", ".position"):
        names = {os.path.splitext(n)[0] for n in os.listdir(os.path.join(ckpt, sub))}
        assert names == kept, (sub, names)


def test_manifest_roundtrip_and_mismatch(tmp_path):
    root = _fake_step(tmp_path)
    manifest = write_manifest(root, 5)
    assert set(manifest["files"]) == {"inner/payload.bin", "meta.json"}
    assert verify_step(root, 5) is None
    # same-size corruption: only the digest can catch it
    (tmp_path / "5" / "inner" / "payload.bin").write_bytes(b"y" * 4096)
    assert "digest mismatch" in verify_step(root, 5)
    # truncation: caught by size before any hashing
    (tmp_path / "5" / "inner" / "payload.bin").write_bytes(b"y" * 10)
    assert "size mismatch" in verify_step(root, 5)
    os.remove(tmp_path / "5" / "inner" / "payload.bin")
    assert "missing file" in verify_step(root, 5)


def test_manifest_absent_means_unverified_not_invalid(tmp_path):
    root = _fake_step(tmp_path)
    # pre-manifest checkpoints must stay restorable
    assert verify_step(root, 5) is None


def test_unreadable_manifest_fails_verification(tmp_path):
    root = _fake_step(tmp_path)
    write_manifest(root, 5)
    with open(manifest_path(root, 5), "w") as f:
        f.write('{"step": 5, "files"')  # half-written sidecar
    assert "unreadable manifest" in verify_step(root, 5)


def test_truncate_checkpoint_hits_largest_file(tmp_path):
    root = _fake_step(tmp_path)
    mangled = truncate_checkpoint(root, 5)
    assert mangled.endswith("payload.bin")
    assert os.path.getsize(mangled) == 2048
    with pytest.raises(FileNotFoundError):
        truncate_checkpoint(root, 99)


# ---------------------------------------------------------------------------
# chaos plan
# ---------------------------------------------------------------------------


def test_parse_chaos_spec():
    plan = parse_chaos_spec("sigterm_at_step=11, nan_at_step=3,nan_count=2")
    assert plan.sigterm_at_step == 11
    assert plan.nan_at_step == 3
    assert plan.nan_count == 2
    assert parse_chaos_spec("  ") is None
    with pytest.raises(ValueError, match="unknown chaos fault"):
        parse_chaos_spec("sigterm_at=11")


def test_chaos_faults_fire_exactly_as_configured():
    plan = ChaosPlan(nan_at_step=4, nan_count=2,
                     loader_error_at_batch=1, loader_error_count=2)
    assert not plan.maybe_nan(3)
    assert plan.maybe_nan(4)
    assert plan.maybe_nan(4)      # second traversal still poisoned
    assert not plan.maybe_nan(4)  # nan_count exhausted
    for _ in range(2):
        with pytest.raises(TransientDataError):
            plan.maybe_loader_error(1)
    plan.maybe_loader_error(1)  # count exhausted: no raise
    plan.maybe_loader_error(0)  # other batches never fault


# ---------------------------------------------------------------------------
# preemption handler
# ---------------------------------------------------------------------------


def test_preemption_flag_and_second_signal_chains():
    before = signal.getsignal(signal.SIGINT)
    with PreemptionHandler(signums=(signal.SIGINT,)) as h:
        assert not h.triggered
        signal.raise_signal(signal.SIGINT)
        assert h.triggered  # first signal: flag only, no exception
        # second signal chains to the original disposition (here python's
        # default KeyboardInterrupt) — the operator's double Ctrl-C works
        with pytest.raises(KeyboardInterrupt):
            signal.raise_signal(signal.SIGINT)
    assert signal.getsignal(signal.SIGINT) is before


def test_preemption_second_signal_chains_to_callable_handler():
    """Second-signal chaining with a CALLABLE previous disposition (a
    custom handler, not python's default): the handler must be invoked
    directly — re-raising through signal.signal would lose it (ISSUE 4
    satellite: this branch was previously pinned only indirectly)."""
    calls = []

    def custom(signum, frame):
        calls.append(signum)

    before = signal.signal(signal.SIGTERM, custom)
    try:
        with PreemptionHandler(signums=(signal.SIGTERM,)) as h:
            signal.raise_signal(signal.SIGTERM)
            assert h.triggered and not calls  # first: flag only
            signal.raise_signal(signal.SIGTERM)
            assert calls == [signal.SIGTERM]  # second: chained to custom
        # exit restores the pre-handler disposition, not SIG_DFL
        assert signal.getsignal(signal.SIGTERM) is custom
    finally:
        signal.signal(signal.SIGTERM, before)


def test_preemption_inert_off_main_thread():
    out = {}

    def body():
        with PreemptionHandler(signums=(signal.SIGINT,)) as h:
            out["triggered"] = h.triggered

    t = threading.Thread(target=body)
    t.start()
    t.join()
    assert out == {"triggered": False}


# ---------------------------------------------------------------------------
# NaN sentinel / watchdog / meters
# ---------------------------------------------------------------------------


def test_sentinel_detects_with_one_step_lag():
    s = NaNSentinel()
    s.observe(1, jnp.asarray(2.5))
    s.observe(2, float("inf"))  # step 1 checked here; 2 held
    with pytest.raises(NonFiniteLossError) as exc:
        s.observe(3, 1.0)  # step 2's inf surfaces exactly one step late
    assert exc.value.step == 2
    s2 = NaNSentinel()
    s2.observe(7, float("nan"))
    with pytest.raises(NonFiniteLossError):
        s2.flush()  # the run's final step is never left unverified
    s2.flush()  # idempotent once drained


def test_watchdog_suspended_scope_no_false_positive():
    """Known-long epoch-boundary work (kNN eval) runs under suspended():
    no stall flags inside, fresh re-arm on exit, real stalls still flagged."""
    with StepWatchdog(0.05) as w:
        w.beat(1)
        with w.suspended():
            time.sleep(0.3)
        assert w.stalls == 0
        time.sleep(0.3)
        assert w.stalls >= 1


def test_watchdog_flags_stall_and_rearms_on_beat():
    with StepWatchdog(0.05) as w:
        time.sleep(0.3)
        assert w.stalls >= 1
        w.beat(3)
        seen = w.stalls
        time.sleep(0.02)
        assert w.stalls == seen  # beat re-armed the window
    assert w._thread is None


def test_watchdog_rearm_spacing_one_flag_per_interval():
    """During CONTINUED silence the watchdog flags once per further full
    interval, not once per poll — the re-arm threshold ratchets (ISSUE 4
    satellite: the ratchet was previously untested)."""
    with StepWatchdog(0.2) as w:
        time.sleep(0.3)   # one interval elapsed: exactly one flag
        assert w.stalls == 1
        time.sleep(0.1)   # still within the second interval window
        assert w.stalls == 1
        time.sleep(0.2)   # second full interval of silence: second flag
        assert w.stalls == 2
        w.beat(9)         # beat resets the ratchet to ONE interval again
        time.sleep(0.3)
        assert w.stalls == 3


def test_watchdog_nested_suspended_scopes():
    """suspended() nests: the inner exit must NOT un-suspend the outer
    scope (an epoch-boundary eval that itself wraps a blocking save), and
    the watchdog re-arms fresh only when the outermost scope exits."""
    with StepWatchdog(0.05) as w:
        with w.suspended():
            with w.suspended():
                time.sleep(0.15)
            assert w._suspend == 1   # inner exit: still suspended
            time.sleep(0.15)
            assert w.stalls == 0     # outer scope still protects
        assert w._suspend == 0
        time.sleep(0.3)              # real silence after full exit: flags
        assert w.stalls >= 1


def test_watchdog_disabled_is_inert():
    with StepWatchdog(0.0) as w:
        w.beat(1)
        assert w._thread is None and w.stalls == 0


def test_rate_meter_format():
    m = RateMeter("DecFail")
    assert m.rate == 0.0
    m.update(3, 60)
    assert m.rate == pytest.approx(0.05)
    assert str(m) == "DecFail 3 (5.00%)"


# ---------------------------------------------------------------------------
# Prefetcher fault paths
# ---------------------------------------------------------------------------


class _ArrayDataset:
    def __init__(self, n=32, fail_at=None, exc=ValueError, block_on=None):
        self.imgs = np.zeros((n, 4, 4, 3), np.uint8)
        self.labels = np.zeros(n, np.int32)
        self.extents = np.tile(np.asarray([4, 4, 0], np.int32), (n, 1))
        self.fail_at = fail_at
        self.exc = exc
        self.block_on = block_on
        self.calls = []

    def get_batch(self, indices):
        b = int(indices[0]) // 8
        self.calls.append(b)
        if self.block_on is not None:
            self.block_on.wait()
        if self.fail_at is not None and b == self.fail_at:
            raise self.exc(f"injected at batch {b}")
        return self.imgs[indices], self.labels[indices], self.extents[indices]


def test_prefetcher_retries_transient_reads(mesh8):
    data = _ArrayDataset(fail_at=None)
    with chaos_context(ChaosPlan(loader_error_at_batch=1, loader_error_count=2)):
        pf = Prefetcher(data, np.arange(32), 8, mesh8,
                        retries=3, backoff_secs=0.01)
        batches = list(pf)
        pf.close()
    assert len(batches) == 4


def test_prefetcher_exhausted_retries_raise(mesh8):
    data = _ArrayDataset()
    with chaos_context(ChaosPlan(loader_error_at_batch=0, loader_error_count=9)):
        pf = Prefetcher(data, np.arange(32), 8, mesh8,
                        retries=2, backoff_secs=0.01)
        with pytest.raises(TransientDataError):
            list(pf)
        pf.close()  # already delivered via the iterator: close() won't re-raise


def test_prefetcher_nonretryable_error_fails_fast(mesh8):
    data = _ArrayDataset(fail_at=2, exc=ValueError)
    pf = Prefetcher(data, np.arange(32), 8, mesh8, backoff_secs=0.01)
    with pytest.raises(ValueError, match="injected at batch 2"):
        list(pf)
    assert data.calls.count(2) == 1  # no retry for programming errors
    pf.close()


def test_prefetcher_close_mid_backoff_is_silent(mesh8):
    """close() while the worker sits in retry backoff on a TRANSIENT read:
    the fault was still within its retry budget, so recording it as a worker
    error would crash a run that finished all its steps (close() runs in the
    driver's unwind path even on success)."""
    data = _ArrayDataset(fail_at=0, exc=TransientDataError)
    pf = Prefetcher(data, np.arange(32), 8, mesh8,
                    retries=9, backoff_secs=30.0)
    deadline = time.monotonic() + 5.0
    while not data.calls and time.monotonic() < deadline:
        time.sleep(0.01)  # wait for the worker to enter the retry backoff
    time.sleep(0.05)
    pf.close()  # wakes the 30 s backoff immediately; must NOT raise
    assert pf._err is None
    assert not pf._thread.is_alive()


def test_prefetcher_close_propagates_pending_error(mesh8):
    """A worker error the consumer never iterated to must surface at
    close() — data corruption must not vanish because the consumer left
    early. Exactly once: a second close() is a no-op."""
    data = _ArrayDataset(fail_at=0, exc=ValueError)
    pf = Prefetcher(data, np.arange(32), 8, mesh8, backoff_secs=0.01)
    deadline = time.monotonic() + 5.0
    while pf._err is None and time.monotonic() < deadline:
        time.sleep(0.01)  # worker fails on its very first batch
    with pytest.raises(ValueError, match="injected at batch 0"):
        pf.close()
    pf.close()


def test_prefetcher_close_warns_on_wedged_worker(mesh8, capsys):
    gate = threading.Event()
    data = _ArrayDataset(block_on=gate)
    pf = Prefetcher(data, np.arange(32), 8, mesh8, join_timeout=0.2)
    try:
        pf.close()
        assert pf._thread.is_alive()
        assert "staging thread still alive" in capsys.readouterr().out
    finally:
        gate.set()  # unwedge so the daemon thread exits


# ---------------------------------------------------------------------------
# ImageFolder decode tolerance
# ---------------------------------------------------------------------------


def test_imagefolder_tolerates_corrupt_file(tmp_path):
    PIL = pytest.importorskip("PIL")  # noqa: F841
    from PIL import Image

    from moco_tpu.data.datasets import ImageFolder

    d = tmp_path / "cls"
    d.mkdir()
    img = np.full((40, 40, 3), 128, np.uint8)
    Image.fromarray(img).save(str(d / "good.jpg"), quality=95)
    (d / "bad.jpg").write_bytes(b"not a jpeg")
    folder = ImageFolder(str(tmp_path), stage_size=32, backend="pil")
    imgs, labels, extents = folder.get_batch(np.arange(len(folder.entries)))
    assert folder.decode_total == 2
    assert folder.decode_failures == 1  # one corrupt file in a million-image
    bad_idx = [i for i, e in enumerate(folder.entries) if "bad" in e.path][0]
    np.testing.assert_array_equal(imgs[bad_idx], 0)  # zero canvas, not a crash
    np.testing.assert_array_equal(extents[bad_idx], [32, 64, 0])
    good_idx = 1 - bad_idx
    assert imgs[good_idx].max() > 0
