import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.ops.knn import knn_accuracy, knn_predict


def _clusters(key, n_per_class, num_classes, dim, spread=0.1):
    keys = jax.random.split(key, num_classes)
    centers = jax.random.normal(jax.random.key(123), (num_classes, dim)) * 3
    feats, labels = [], []
    for c in range(num_classes):
        pts = centers[c] + spread * jax.random.normal(keys[c], (n_per_class, dim))
        feats.append(pts)
        labels.append(jnp.full((n_per_class,), c, jnp.int32))
    return jnp.concatenate(feats), jnp.concatenate(labels)


def test_knn_separable_clusters_perfect():
    bank, bank_labels = _clusters(jax.random.key(0), 50, 4, 16)
    queries, qlabels = _clusters(jax.random.key(1), 10, 4, 16)
    pred = knn_predict(queries, bank, bank_labels, num_classes=4, k=20)
    np.testing.assert_array_equal(np.asarray(pred), np.asarray(qlabels))


def test_knn_accuracy_batched_matches():
    bank, bank_labels = _clusters(jax.random.key(2), 40, 3, 8)
    queries, qlabels = _clusters(jax.random.key(3), 30, 3, 8)
    acc = knn_accuracy(queries, qlabels, bank, bank_labels, num_classes=3, k=10, batch=7)
    assert acc == 1.0


def test_knn_k_larger_than_bank_clamps():
    bank, bank_labels = _clusters(jax.random.key(4), 5, 2, 8)
    queries, qlabels = _clusters(jax.random.key(5), 4, 2, 8)
    pred = knn_predict(queries, bank, bank_labels, num_classes=2, k=200)
    assert pred.shape == (8,)


def test_knn_chunked_matches_unchunked():
    """Bank-streamed top-k merge (VERDICT r1 #8) is exact: same predictions
    as the single-shot [B, N] path, including a ragged final chunk."""
    key = jax.random.key(6)
    bank = jax.random.normal(key, (1037, 32))  # not a multiple of the chunk
    bank_labels = jax.random.randint(jax.random.key(7), (1037,), 0, 10)
    queries = jax.random.normal(jax.random.key(8), (64, 32))
    ref = knn_predict(queries, bank, bank_labels, num_classes=10, k=50)
    for chunk in (64, 100, 512, 1037, 4096):
        got = knn_predict(queries, bank, bank_labels, num_classes=10, k=50,
                          bank_chunk=chunk)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_knn_chunked_k_exceeding_chunk_is_exact():
    """k > bank_chunk used to silently clamp to the chunk width (ADVICE r2);
    the merge now carries the full k, so the chunked path agrees with the
    unchunked protocol for any k ≤ N."""
    key = jax.random.key(9)
    bank = jax.random.normal(key, (300, 16))
    bank_labels = jax.random.randint(jax.random.key(10), (300,), 0, 7)
    queries = jax.random.normal(jax.random.key(11), (16, 16))
    for k in (64, 100, 250):
        ref = knn_predict(queries, bank, bank_labels, num_classes=7, k=k)
        got = knn_predict(queries, bank, bank_labels, num_classes=7, k=k,
                          bank_chunk=32)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_knn_imagenet_scale_bank():
    """Sizing proof for the full-scale eval (VERDICT r1 #8): a 200k x 128
    bank (structured so the true protocol answer is known) through the
    streaming path with the production chunk never materializes more than
    [batch, 65536] sims; accuracy is exact. The 1.28M ImageNet bank is the
    same program with 20 scan steps instead of 4 (bank 655 MB, sims chunk
    134 MB — HBM budget documented in ops/knn.py)."""
    n, dim, classes = 200_000, 128, 100
    rng = np.random.default_rng(0)
    bank_labels = rng.integers(0, classes, n).astype(np.int32)
    centers = rng.normal(size=(classes, dim)).astype(np.float32)
    bank = centers[bank_labels] + 0.1 * rng.normal(size=(n, dim)).astype(np.float32)
    qlabels = rng.integers(0, classes, 256).astype(np.int32)
    queries = centers[qlabels] + 0.1 * rng.normal(size=(256, dim)).astype(np.float32)
    acc = knn_accuracy(queries, qlabels, bank, bank_labels, num_classes=classes,
                       k=200, batch=128)
    assert acc == 1.0
