import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.ops.knn import knn_accuracy, knn_predict


def _clusters(key, n_per_class, num_classes, dim, spread=0.1):
    keys = jax.random.split(key, num_classes)
    centers = jax.random.normal(jax.random.key(123), (num_classes, dim)) * 3
    feats, labels = [], []
    for c in range(num_classes):
        pts = centers[c] + spread * jax.random.normal(keys[c], (n_per_class, dim))
        feats.append(pts)
        labels.append(jnp.full((n_per_class,), c, jnp.int32))
    return jnp.concatenate(feats), jnp.concatenate(labels)


def test_knn_separable_clusters_perfect():
    bank, bank_labels = _clusters(jax.random.key(0), 50, 4, 16)
    queries, qlabels = _clusters(jax.random.key(1), 10, 4, 16)
    pred = knn_predict(queries, bank, bank_labels, num_classes=4, k=20)
    np.testing.assert_array_equal(np.asarray(pred), np.asarray(qlabels))


def test_knn_accuracy_batched_matches():
    bank, bank_labels = _clusters(jax.random.key(2), 40, 3, 8)
    queries, qlabels = _clusters(jax.random.key(3), 30, 3, 8)
    acc = knn_accuracy(queries, qlabels, bank, bank_labels, num_classes=3, k=10, batch=7)
    assert acc == 1.0


def test_knn_k_larger_than_bank_clamps():
    bank, bank_labels = _clusters(jax.random.key(4), 5, 2, 8)
    queries, qlabels = _clusters(jax.random.key(5), 4, 2, 8)
    pred = knn_predict(queries, bank, bank_labels, num_classes=2, k=200)
    assert pred.shape == (8,)
