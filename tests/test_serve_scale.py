"""Planet-scale kNN serving suite (ISSUE 20).

Four layers, mirroring tests/test_fleet.py:
  - index units (no jax): build determinism across bank shard counts
    (byte-identical ann.npz), manifest pairing + torn/drifted-artifact
    rejection, the recall@1 >= 0.95 acceptance gate, shard-union ==
    full-index search, and the numpy-vote vs router-python-vote
    tie-break equivalence;
  - router fan-out against in-thread stub shards serving REAL AnnShard
    candidates: merged fan-out class == the single full-index classify,
    dead-shard partial flagging, 1-shard fleets never fan out, per-tier
    router accounting;
  - admission tiers: a batch-lane flood sheds batch work only — the
    interactive lane admits through saturation (the starvation drill);
  - autoscaling: AutoscaleController hysteresis/cooldown as a pure
    unit, config validation (constructor + serve_fleet CLI exit 45),
    stub-replica scale-up/drain-reap mechanics, and a load-driven
    surge -> scale-up -> idle -> reap e2e; the full CLI drill
    (serve_bench --autoscale-drill) runs as the slow soak.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from moco_tpu.config import ServeConfig
from moco_tpu.serve import ann as annmod
from moco_tpu.serve.ann import AnnIndexError, AnnShard, build_ann_index
from moco_tpu.serve.bankbuild import build_bank
from moco_tpu.serve.batcher import MicroBatcher, OverloadedError
from moco_tpu.serve.fleet import (
    AutoscaleController,
    FleetPolicy,
    FleetSupervisor,
    ReplicaState,
    pick_free_port,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_POLICY = dict(
    probe_secs=0.1, probe_timeout_s=0.5, health_stale_secs=1.0,
    startup_grace_secs=15.0, term_grace_secs=1.0,
    backoff_base_secs=0.05, backoff_max_secs=0.2, backoff_jitter=0.0,
    request_timeout_s=10.0, watch_poll_secs=0.1, stats_every_secs=1.0,
)

D = 8  # stub embedding dim


def _embed_stub(batch):
    flat = np.asarray(batch, np.float32).reshape(len(batch), -1)
    return (flat[:, :D] / 255.0).astype(np.float32)


def _corpus(n=256, seed=3, size=8):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    labels = (np.arange(n) % 5).astype(np.int64)
    return images, labels


def _bank(tmp_path, name="bank", step=7, n=256, shards=1):
    """A real versioned bank on disk (the artifact ANN indexes pair
    with); returns its root dir."""
    images, labels = _corpus(n)
    ck_dir = tmp_path / "export" / str(step)
    ck_dir.mkdir(parents=True, exist_ok=True)
    ck = ck_dir / "encoder.npz"
    if not ck.exists():
        ck.write_bytes(b"weights " * 512)
    bank_dir = tmp_path / name
    build_bank(str(bank_dir), step, images, labels, _embed_stub,
               checkpoint_path=str(ck), image_size=8, shards=shards)
    return str(bank_dir)


def _load_index(bank_dir, step=7, cells=16):
    if not os.path.exists(annmod.ann_manifest_path(bank_dir, step)):
        build_ann_index(bank_dir, step, cells=cells)
    feats = np.load(os.path.join(bank_dir, str(step), "bank.npz"))
    arrays, manifest = annmod.load_ann(
        os.path.join(bank_dir, str(step), "bank.npz"))
    return feats["features"], feats["labels"], arrays, manifest


def _wait(cond, timeout_s=20.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# index build: determinism, pairing, recall
# ---------------------------------------------------------------------------


def test_ann_build_byte_identical_across_bank_shard_counts(tmp_path):
    """ISSUE 20 acceptance: bank bytes are already shard-count
    invariant (ISSUE 16) and the index build is a pure function of
    those bytes + (cells, seed) — so 1-shard and 3-shard builds yield
    byte-identical ann.npz files and equal manifests."""
    b1 = _bank(tmp_path, "b1", shards=1)
    b3 = _bank(tmp_path, "b3", shards=3)
    m1 = build_ann_index(b1, 7, cells=16)
    m3 = build_ann_index(b3, 7, cells=16)
    p1 = annmod.ann_index_path(b1, 7)
    p3 = annmod.ann_index_path(b3, 7)
    assert open(p1, "rb").read() == open(p3, "rb").read()
    assert m1 == m3
    # and a REBUILD over the same bank is a byte-level no-op
    build_ann_index(b1, 7, cells=16)
    assert open(p1, "rb").read() == open(p3, "rb").read()


def test_ann_manifest_pairs_and_rejects_torn_or_drifted(tmp_path):
    bank_dir = _bank(tmp_path)
    bank_npz = os.path.join(bank_dir, "7", "bank.npz")
    # no index built yet: load_ann is None (exact fallback), no error
    assert annmod.load_ann(bank_npz) is None
    manifest = build_ann_index(bank_dir, 7, cells=8)
    assert annmod.verify_ann(bank_dir, 7) is None
    assert manifest["bank"]["sha256"] and manifest["checkpoint_sha256"]
    arrays, loaded = annmod.load_ann(bank_npz)
    assert loaded["cells"] == 8 and set(arrays) == {
        "centroids", "row_order", "cell_offsets"}
    # torn index: present-but-wrong bytes must raise, never silently
    # fall back to exact over a corrupt artifact
    with open(annmod.ann_index_path(bank_dir, 7), "ab") as f:
        f.write(b"torn")
    assert "size mismatch" in annmod.verify_ann(bank_dir, 7)
    with pytest.raises(AnnIndexError, match="rejected"):
        annmod.load_ann(bank_npz)


def test_ann_rejects_bank_drift_under_index(tmp_path):
    bank_dir = _bank(tmp_path)
    build_ann_index(bank_dir, 7, cells=8)
    bank_npz = os.path.join(bank_dir, "7", "bank.npz")
    data = dict(np.load(bank_npz))
    data["features"] = data["features"] + 1.0
    np.savez(bank_npz, **data)  # simulated out-of-band drift
    assert "drifted" in annmod.verify_ann(bank_dir, 7)


def test_ann_recall_probe_gate(tmp_path):
    """The acceptance pin: seeded ANN-vs-exact recall@1 >= 0.95 with a
    REAL approximation in play (nprobe 4 of 16 cells)."""
    bank_dir = _bank(tmp_path)
    features, labels, arrays, _ = _load_index(bank_dir)
    shard = AnnShard(features, labels, arrays, nprobe=4, rerank=50)
    assert shard.recall_probe() >= 0.95
    # deterministic: same index + seed => same score
    assert shard.recall_probe() == shard.recall_probe()
    # true shards measure against their OWN partition
    for s in range(2):
        half = AnnShard(features, labels, arrays, shard=s, shards=2,
                        nprobe=4, rerank=50)
        assert half.recall_probe() >= 0.95
        assert half.owned_rows < features.shape[0]


def test_shard_union_reproduces_full_index_search(tmp_path):
    """Cell partitioning is a pure split: with every owned cell probed,
    merging per-shard candidates by the router's (-sim, label) order
    reproduces the full-index top-k row set exactly."""
    bank_dir = _bank(tmp_path)
    features, labels, arrays, manifest = _load_index(bank_dir)
    cells = manifest["cells"]
    full = AnnShard(features, labels, arrays, nprobe=cells, rerank=10)
    shards = [AnnShard(features, labels, arrays, shard=s, shards=3,
                       nprobe=cells, rerank=10) for s in range(3)]
    assert sum(s.owned_rows for s in shards) == features.shape[0]
    rng = np.random.default_rng(11)
    for q in rng.standard_normal((8, D)).astype(np.float32):
        sims_f, _labels_f, rows_f = full.search(q, k=10)
        merged = []
        for s in shards:
            sims, labs, rows = s.search(q, k=10)
            merged += list(zip(sims.tolist(), rows.tolist()))
        merged.sort(key=lambda c: (-c[0], c[1]))
        assert [r for _s, r in merged[:10]] == rows_f.tolist()


def test_vote_tie_breaks_to_lowest_label():
    # the np.argmax semantics the router's pure-python max(sorted(...))
    # merge must reproduce
    assert annmod.vote([(0.5, 3), (0.5, 1)], 0.07, 5) == 1
    assert annmod.vote([(0.9, 4), (0.1, 0)], 0.07, 5) == 4
    # two candidates of one class outweigh one slightly-better one
    assert annmod.vote([(0.50, 2), (0.49, 2), (0.52, 0)], 1.0, 3) == 2


def test_ann_manifest_records_the_full_pairing_chain(tmp_path):
    """The index manifest binds index sha -> bank sha -> checkpoint
    sha: the chain a replica walks before trusting the artifact."""
    bank_dir = _bank(tmp_path, "bank2", n=32)
    manifest = build_ann_index(bank_dir, 7, cells=4)
    assert manifest["cells"] == 4 and manifest["rows"] == 32
    assert os.path.exists(annmod.ann_manifest_path(bank_dir, 7))
    with open(os.path.join(bank_dir, ".integrity", "7.json")) as f:
        bank_manifest = json.load(f)
    assert (manifest["checkpoint_sha256"]
            == bank_manifest["checkpoint"]["sha256"])
    assert (manifest["bank"]["sha256"]
            == bank_manifest["files"]["bank.npz"]["sha256"])


# ---------------------------------------------------------------------------
# router fan-out (in-thread stub shards, no child processes)
# ---------------------------------------------------------------------------


class _FakeProc:
    pid = 4242

    def poll(self):
        return None


def _shard_backend(embedding, shard_obj=None, answer=None):
    """A stub replica: /v1/embed answers `embedding`; a candidates
    probe answers its REAL AnnShard's search (or a canned `answer`)."""
    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(n) or b"{}")
            if self.path == "/v1/knn" and req.get("candidates"):
                if answer is not None:
                    resp = answer
                else:
                    sims, labs, _rows = shard_obj.search(
                        np.asarray(req["embedding"], np.float32))
                    resp = {
                        "candidates": [[float(s), int(lab)]
                                       for s, lab in zip(sims, labs)],
                        "temperature": shard_obj.temperature,
                        "k": shard_obj.rerank,
                        "num_classes": shard_obj.num_classes,
                    }
            elif self.path == "/v1/knn":
                resp = {"class": 42, "cached": False}  # exact-path stub
            else:
                resp = {"embedding": list(embedding)}
            body = json.dumps(resp).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class S(ThreadingHTTPServer):
        daemon_threads = True

    srv = S(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _router_fleet(tmp_path, ports, ann_shards=0):
    fleet = FleetSupervisor(
        lambda *a: ["true"], replicas=len(ports),
        telemetry_dir=str(tmp_path / "fleet_t"),
        policy=FleetPolicy(**FAST_POLICY), ann_shards=ann_shards,
    )
    for i, port in enumerate(ports):
        r = ReplicaState(i, "127.0.0.1", port,
                         str(tmp_path / f"r{i}"), budget=3)
        r.proc = _FakeProc()
        r.healthy = True
        if ann_shards:
            r.shard = i % ann_shards
        fleet.replicas.append(r)
    return fleet


def test_fanout_merge_matches_full_index_classify(tmp_path):
    """The tentpole correctness pin: a 2-shard fan-out through the
    stdlib-only router — real AnnShard candidates, pure-python merge +
    vote — answers EXACTLY what a single full-index replica answers."""
    bank_dir = _bank(tmp_path)
    features, labels, arrays, manifest = _load_index(bank_dir)
    cells = manifest["cells"]
    full = AnnShard(features, labels, arrays, nprobe=cells, rerank=50)
    halves = [AnnShard(features, labels, arrays, shard=s, shards=2,
                       nprobe=cells, rerank=50) for s in range(2)]
    rng = np.random.default_rng(5)
    q = annmod._l2(features[17] + 0.1 * rng.standard_normal(D)
                   .astype(np.float32))
    backends = [_shard_backend(q.tolist(), halves[0]),
                _shard_backend(q.tolist(), halves[1])]
    fleet = _router_fleet(
        tmp_path, [b.server_address[1] for b in backends], ann_shards=2)
    try:
        status, body = fleet.router_proxy("/v1/knn", b'{"pixels": [0]}')
        resp = json.loads(body)
        assert status == 200
        assert resp["partial"] is False and resp["shards_answered"] == 2
        assert resp["class"] == full.classify(q)[0]
        assert fleet.r_knn_fanout == 1 and fleet.r_knn_partial == 0
        assert fleet.r_ok == 1  # the embed leg did NOT double-count
        assert fleet.r_requests == 1
    finally:
        for b in backends:
            b.shutdown()


def test_fanout_dead_shard_flags_partial(tmp_path):
    live = _shard_backend([0.5] * D, answer={
        "candidates": [[0.9, 3], [0.2, 1]],
        "temperature": 0.07, "k": 10, "num_classes": 5,
    })
    dead_port = pick_free_port()
    fleet = _router_fleet(
        tmp_path, [live.server_address[1], dead_port], ann_shards=2)
    try:
        status, body = fleet.router_proxy(
            "/v1/knn", b'{"pixels": [0], "deadline_ms": 3000}')
        resp = json.loads(body)
        assert status == 200
        assert resp["partial"] is True and resp["shards_answered"] == 1
        assert resp["class"] == 3  # shard 0's candidates still vote
        assert fleet.r_knn_partial == 1
        # the dead shard owner was ejected for the probe to readmit
        assert fleet.replicas[1].healthy is False
    finally:
        live.shutdown()


def test_single_shard_fleet_never_fans_out(tmp_path):
    """ann_shards <= 1: /v1/knn routes like any request — the replica's
    own (exact or local-ANN) answer passes through bit-untouched, the
    exact-fallback acceptance contract at the router layer."""
    stub = _shard_backend([0.0] * D)
    fleet = _router_fleet(tmp_path, [stub.server_address[1]],
                          ann_shards=1)
    try:
        status, body = fleet.router_proxy("/v1/knn", b'{"pixels": [0]}')
        assert status == 200
        assert json.loads(body) == {"class": 42, "cached": False}
        assert fleet.r_knn_fanout == 0
    finally:
        stub.shutdown()


def test_router_counts_tiers(tmp_path):
    stub = _shard_backend([0.0] * D)
    fleet = _router_fleet(tmp_path, [stub.server_address[1]])
    try:
        fleet.router_proxy("/v1/embed", b'{"pixels": [0]}')
        fleet.router_proxy("/v1/embed", b'{"tier": "batch"}')
        fleet.router_proxy("/v1/embed", b'{"tier": "interactive"}')
        assert fleet.r_tier == {"interactive": 2, "batch": 1}
        counters = fleet._router_counters()
        assert counters["requests_interactive"] == 2
        assert counters["requests_batch"] == 1
    finally:
        stub.shutdown()


# ---------------------------------------------------------------------------
# admission tiers: the starvation drill
# ---------------------------------------------------------------------------


def test_batch_flood_never_sheds_interactive():
    """Saturate the batch lane past its admission depth while the
    device is gated: batch work sheds, the interactive lane admits
    through the whole flood."""
    gate = threading.Event()

    def run_batch(payloads):
        gate.wait(10.0)
        return [p for p in payloads]

    b = MicroBatcher(run_batch, buckets=(1, 4), max_queue=8,
                     batch_max_queue=4, flush_ms=5.0,
                     default_deadline_ms=5000.0)
    try:
        shed = 0
        for i in range(12):  # 3x the batch lane's depth
            try:
                b.submit(i, tier="batch")
            except OverloadedError:
                shed += 1
        assert shed > 0
        assert b.shed_overload_by_tier["batch"] == shed
        # the flood is invisible to the interactive lane
        pending = [b.submit(100 + i) for i in range(4)]
        assert b.shed_overload_by_tier["interactive"] == 0
        assert len(pending) == 4
        gate.set()
        for p in pending:
            assert p.wait(10.0) >= 100
    finally:
        gate.set()
        b.close()


def test_interactive_drains_before_batch():
    """Under contention the flusher picks the interactive queue first:
    people ride ahead of bulk re-embeds."""
    order = []
    gate = threading.Event()

    def run_batch(payloads):
        gate.wait(10.0)
        order.append(list(payloads))
        return list(payloads)

    b = MicroBatcher(run_batch, buckets=(1, 2), max_queue=8,
                     flush_ms=2.0, default_deadline_ms=5000.0)
    try:
        batch_p = [b.submit(("b", i), tier="batch") for i in range(2)]
        time.sleep(0.05)  # let the batch flush start and block on gate
        inter_p = [b.submit(("i", i)) for i in range(2)]
        time.sleep(0.05)
        gate.set()
        for p in batch_p + inter_p:
            p.wait(10.0)
        # the FIRST flush after the gate holds interactive work even
        # though batch work enqueued earlier
        later = [tag for flush in order[1:] for tag, _ in flush]
        if later:
            first_after = order[1][0][0]
            assert first_after == "i", order
    finally:
        gate.set()
        b.close()


# ---------------------------------------------------------------------------
# config validation: ServeConfig, constructor, CLI exit 45
# ---------------------------------------------------------------------------


def test_serve_config_validates_ann_and_tier_knobs():
    ok = ServeConfig(ann_cells=64, knn_bank="bank.npz", ann_shard=1,
                     ann_shards=4)
    assert ok.ann_nprobe == 8
    with pytest.raises(ValueError, match="ann_cells"):
        ServeConfig(ann_cells=-1)
    with pytest.raises(ValueError, match="ann_shard"):
        ServeConfig(ann_shard=4, ann_shards=4)
    with pytest.raises(ValueError, match="knn-bank"):
        ServeConfig(ann_cells=16)
    with pytest.raises(ValueError, match="batch_max_queue"):
        ServeConfig(batch_max_queue=2)
    with pytest.raises(ValueError, match="batch_deadline_ms"):
        ServeConfig(batch_deadline_ms=0)


def test_fleet_constructor_validates_shards_and_autoscale(tmp_path):
    def mk(**kw):
        return FleetSupervisor(
            lambda *a: ["true"], replicas=kw.pop("replicas", 2),
            telemetry_dir=str(tmp_path / "t"),
            policy=FleetPolicy(**FAST_POLICY, **kw.pop("policy", {})),
            **kw,
        )

    with pytest.raises(ValueError, match="ann_shards"):
        mk(ann_shards=-1)
    with pytest.raises(ValueError, match="ann_shards"):
        mk(replicas=2, ann_shards=3)  # shard cover needs >= N replicas
    with pytest.raises(ValueError, match="autoscale_min"):
        mk(policy=dict(autoscale_max=4, autoscale_min=0))
    with pytest.raises(ValueError, match="autoscale_max"):
        mk(replicas=3, policy=dict(autoscale_max=2))
    mk(replicas=2, ann_shards=2, policy=dict(autoscale_max=4))  # clean


@pytest.mark.parametrize("flags", [
    ("--ann-shards", "-1"),
    ("--replicas", "2", "--ann-shards", "4"),
    ("--autoscale-max", "1", "--replicas", "2"),
    ("--autoscale-max", "2", "--autoscale-min", "0"),
    ("--autoscale-max", "2", "--autoscale-up-after", "0"),
])
def test_serve_fleet_cli_bad_scale_flags_exit_45(tmp_path, flags):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_fleet.py"),
         "--telemetry-dir", str(tmp_path / "t"), "--port", "0",
         *flags, "--", "true"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 45, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# autoscaler: pure-unit hysteresis, then the fleet mechanics
# ---------------------------------------------------------------------------


def _stats(requests=0, sheds=0, outstanding=0, healthy=1, p99=0.0):
    return {"requests": requests, "upstream_timeout": sheds,
            "outstanding": outstanding, "healthy": healthy,
            "latency_ms": {"p99": p99} if p99 else {}}


def _policy(**kw):
    base = dict(FAST_POLICY)
    base.update(autoscale_max=4, autoscale_cooldown_s=10.0,
                autoscale_up_after=2, autoscale_down_after=2,
                autoscale_shed_high=0.02, autoscale_outstanding_high=4.0,
                autoscale_idle_low=0.25)
    base.update(kw)
    return FleetPolicy(**base)


def test_autoscale_shed_breach_needs_consecutive_windows():
    c = AutoscaleController(_policy())
    assert c.observe(_stats(100), now=0.0) is None  # no deltas yet
    assert c.observe(_stats(200, sheds=10), now=1.0) is None  # streak 1
    action = c.observe(_stats(300, sheds=20), now=2.0)
    assert action is not None and action[0] == "up"
    assert "shed_rate" in action[1]


def test_autoscale_mixed_window_resets_streaks():
    c = AutoscaleController(_policy())
    c.observe(_stats(100), now=0.0)
    c.observe(_stats(200, sheds=10), now=1.0)          # breach 1
    c.observe(_stats(300, sheds=10, outstanding=1), now=2.0)  # mixed
    assert c.breach_streak == 0 and c.idle_streak == 0
    assert c.observe(_stats(400, sheds=20), now=3.0) is None  # breach 1


def test_autoscale_cooldown_defers_but_keeps_streak():
    c = AutoscaleController(_policy(autoscale_cooldown_s=100.0))
    c.observe(_stats(100), now=0.0)
    c.observe(_stats(200, sheds=10), now=1.0)
    assert c.observe(_stats(300, sheds=20), now=2.0)[0] == "up"
    # breaches KEEP accumulating through the cooldown...
    c.observe(_stats(400, sheds=30), now=3.0)
    assert c.observe(_stats(500, sheds=40), now=4.0) is None
    assert c.breach_streak >= 2
    # ...and fire the moment the window reopens
    assert c.observe(_stats(600, sheds=50), now=200.0)[0] == "up"


def test_autoscale_depth_and_p99_breaches():
    c = AutoscaleController(_policy())
    c.observe(_stats(100), now=0.0)
    c.observe(_stats(200, outstanding=10, healthy=2), now=1.0)
    action = c.observe(_stats(300, outstanding=12, healthy=2), now=2.0)
    assert action[0] == "up" and "outstanding/healthy" in action[1]
    # p99 off by default (0.0); armed, it breaches alone
    c2 = AutoscaleController(_policy(autoscale_p99_high_ms=50.0))
    c2.observe(_stats(100), now=0.0)
    c2.observe(_stats(200, p99=80.0), now=1.0)
    assert c2.observe(_stats(300, p99=90.0), now=2.0)[0] == "up"


def test_autoscale_idle_scales_down_zero_sheds_only():
    c = AutoscaleController(_policy())
    c.observe(_stats(100), now=0.0)
    c.observe(_stats(110), now=1.0)                    # idle 1
    action = c.observe(_stats(120), now=2.0)           # idle 2
    assert action is not None and action[0] == "down"
    # ANY shed in the window blocks the idle path
    c.observe(_stats(130, sheds=21), now=3.0)
    assert c.idle_streak == 0


# -- stub-replica fleet mechanics -------------------------------------------

_SCALE_STUB = textwrap.dedent("""\
    import argparse, json, os, signal, sys, threading, time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--telemetry-dir", required=True)
    p.add_argument("--pretrained", default="boot")
    p.add_argument("--sleep-s", type=float, default=0.0)
    args, _ = p.parse_known_args()

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        def log_message(self, *a):
            pass
        def _send(self, status, obj):
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        def do_GET(self):
            if self.path == "/healthz":
                self._send(200, {"status": "ok"})
            else:
                self._send(404, {"error": "not_found"})
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length") or 0))
            if args.sleep_s:
                time.sleep(args.sleep_s)
            self._send(200, {"embedding": [1.0, float(args.port)],
                             "cached": False})

    class S(ThreadingHTTPServer):
        daemon_threads = True
        request_queue_size = 128

    srv = S(("127.0.0.1", args.port), H)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda s, f: stop.set())
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    stop.wait()
    time.sleep(0.05)
    srv.shutdown()
    sys.exit(0)
""")


def _scale_fleet(tmp_path, n=1, sleep_s=0.0, **policy_kw):
    stub = tmp_path / "scale_stub.py"
    stub.write_text(_SCALE_STUB)
    kw = dict(FAST_POLICY)
    kw.update(policy_kw)

    def child_argv(index, port, tdir, pretrained, bank=None, shard=None):
        return [sys.executable, str(stub), "--port", str(port),
                "--telemetry-dir", tdir, "--sleep-s", str(sleep_s)]

    return FleetSupervisor(
        child_argv, replicas=n, telemetry_dir=str(tmp_path / "fleet_t"),
        policy=FleetPolicy(**kw), seed=0,
    )


def _healthy(fleet):
    return sum(1 for r in fleet.replicas if r.healthy and not r.draining)


def test_scale_up_then_drain_reap_mechanics(tmp_path):
    """_scale_up spawns a replica on a fresh monotonic index;
    _scale_down drain-reaps the highest-index one and it is NEVER
    relaunched — the replica table shrinks for good."""
    # autoscale_down_after=50: the AUTO idle path must stay quiet so
    # this test owns every transition it asserts on
    fleet = _scale_fleet(tmp_path, n=1, autoscale_max=3,
                         autoscale_cooldown_s=0.1,
                         autoscale_down_after=50)
    fleet.start()
    try:
        _wait(lambda: _healthy(fleet) == 1, msg="boot replica healthy")
        fleet._scale_up("test breach")
        _wait(lambda: _healthy(fleet) == 2, msg="scaled-up replica")
        assert [r.index for r in fleet.replicas] == [0, 1]
        fleet._scale_down("test idle")
        _wait(lambda: len(fleet.replicas) == 1, msg="victim reaped")
        assert fleet.replicas[0].index == 0
        time.sleep(0.5)  # a reaped replica must NOT come back
        assert len(fleet.replicas) == 1
        events = [e["event"] for e in fleet.incidents]
        assert "autoscale_up" in events and "autoscale_down" in events
        assert "autoscale_reaped" in events
        # indices are never reused: the next spawn is index 2
        fleet._scale_up("again")
        _wait(lambda: _healthy(fleet) == 2, msg="third replica")
        assert [r.index for r in fleet.replicas] == [0, 2]
    finally:
        fleet.stop()


def test_scale_down_respects_floor_and_shard_cover(tmp_path):
    fleet = _router_fleet(tmp_path, [1001, 1002], ann_shards=2)
    # 2 replicas over 2 shards: floor = max(min=1, shards=2) — no reap
    fleet._scale_down("idle")
    assert not any(r.reaping for r in fleet.replicas)
    # 3 replicas, shards (0, 1, 0): replica 2 shares shard 0 — reapable
    r = ReplicaState(2, "127.0.0.1", 1003, str(tmp_path / "r2"), budget=3)
    r.proc = _FakeProc()
    r.healthy = True
    r.shard = 0
    fleet.replicas.append(r)
    fleet._scale_down("idle")
    assert fleet.replicas[2].reaping and fleet.replicas[2].draining
    # but replica 1 (sole owner of shard 1) would never have been picked
    assert not fleet.replicas[1].reaping


def test_e2e_load_driven_scale_up_and_down(tmp_path):
    """The step drill against a live stub fleet: a closed-loop surge
    drives outstanding/healthy over the breach line — capacity follows;
    the load stops — the fleet reaps back to its floor. Every request
    resolves structured (zero lost) through both transitions."""
    fleet = _scale_fleet(
        tmp_path, n=1, sleep_s=0.2, stats_every_secs=0.25,
        autoscale_max=2, autoscale_cooldown_s=0.5,
        autoscale_up_after=2, autoscale_down_after=2,
        autoscale_outstanding_high=2.0, autoscale_idle_low=0.5,
    )
    fleet.start()
    outcomes = {"ok": 0, "structured": 0, "lost": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            status, body = fleet.router_proxy(
                "/v1/embed", b'{"pixels": [0], "tier": "batch"}')
            try:
                resp = json.loads(body)
            except ValueError:
                resp = None
            with lock:
                if status == 200 and isinstance(resp, dict):
                    outcomes["ok"] += 1
                elif isinstance(resp, dict) and "error" in resp:
                    outcomes["structured"] += 1
                else:
                    outcomes["lost"] += 1

    try:
        _wait(lambda: _healthy(fleet) == 1, msg="boot replica healthy")
        clients = [threading.Thread(target=client, daemon=True)
                   for _ in range(6)]
        for t in clients:
            t.start()
        _wait(lambda: _healthy(fleet) == 2, timeout_s=15.0,
              msg="load-driven scale-up")
        stop.set()
        for t in clients:
            t.join(timeout=10.0)
        _wait(lambda: len(fleet.replicas) == 1, timeout_s=20.0,
              msg="idle-driven drain-reap")
        assert outcomes["lost"] == 0, outcomes
        assert outcomes["ok"] > 0
        events = [e["event"] for e in fleet.incidents]
        assert "autoscale_up" in events and "autoscale_reaped" in events
    finally:
        stop.set()
        fleet.stop()


# ---------------------------------------------------------------------------
# the full CLI drill (slow): serve_bench --autoscale-drill
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_autoscale_drill_cli_soak(tmp_path):
    """serve_bench.run_autoscale_drill end-to-end through the
    serve_fleet CLI: surge -> scale-up within the cooldown ->
    interactive probes unshedded -> idle -> drain-reap to the floor,
    zero lost. The acceptance drill, automated."""
    spec = importlib.util.spec_from_file_location(
        "serve_bench", os.path.join(REPO, "tools", "serve_bench.py"))
    serve_bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_bench)

    stub = tmp_path / "scale_stub.py"
    stub.write_text(_SCALE_STUB)
    out = serve_bench.run_autoscale_drill(
        [sys.executable, "-u", str(stub), "--sleep-s", "0.15"],
        base_replicas=1, concurrency=16, total_requests=600,
        image_size=8, pool=4, timeout_s=30.0,
        drill_timeout_s=120.0,
        fleet_args=[
            "--autoscale-max", "2", "--autoscale-min", "1",
            "--autoscale-cooldown-s", "1",
            "--autoscale-up-after", "2", "--autoscale-down-after", "2",
            "--autoscale-outstanding-high", "2",
            "--autoscale-idle-low", "0.5",
        ],
    )
    assert out.get("pass"), out
    assert out["healthy_peak"] == 2 and out["healthy_end"] == 1
    assert out["surge"]["lost"] == 0
    assert out["interactive_probes"]["shed"] == 0
    assert out["interactive_probes"]["lost"] == 0
