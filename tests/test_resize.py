"""Elastic training suite (ISSUE 11): checkpoint–resize–relaunch.

Layers:
  - unit: resize-request parse/claim, the chaos `resize_at_step` fault,
    exit-49 classification, argv rewrite + recorded-devices sidecar,
    controller arming, R5 coverage of the new exit path, report folds;
  - driver: the real train() honors a chaos resize — elastic checkpoint,
    `resized` metric, devices-stamped position sidecar, `resize_exit`
    heartbeat;
  - dialect shim: a quantized checkpoint saved under a 4-device mesh
    restores onto a 2-device mesh with fresh-zero [2, ...] accumulators —
    the restore every elastic relaunch performs;
  - stub-child e2e: the REAL Supervisor loop resizing stub children
    (request file consumed, SIGUSR2 delivered, argv rewritten, fresh
    compile-cache dir, mesh_change preflight incident, `resize` span
    under the child span, report fold) in a couple of seconds;
  - the slow soak: a supervised real-CPU 1→2→1 device drill with zero
    manual steps, loss-curve continuity pinned against an uninterrupted
    run at the gradsync dialect-shim tolerance.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from moco_tpu.resilience.chaos import ChaosPlan, chaos_context, parse_chaos_spec
from moco_tpu.resilience.exitcodes import EXIT_RESIZE
from moco_tpu.resilience.resize import (
    ResizeController,
    ResizeListener,
    ResizeRequest,
    argv_device_count,
    consume_resize_request,
    parse_resize_request,
    pick_device_flag,
    read_honored_request,
    read_recorded_devices,
    write_resize_request,
)
from moco_tpu.resilience.supervisor import (
    CLASS_CLEAN,
    CLASS_RESIZE,
    FATAL_CLASSES,
    RestartPolicy,
    Supervisor,
    classify_exit,
    read_events_tail,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# request file protocol
# ---------------------------------------------------------------------------


def test_parse_resize_request_forms():
    req = parse_resize_request("devices=2 grad_sync_cadence=4")
    assert (req.devices, req.grad_sync_cadence, req.slow) == (2, 4, False)
    assert parse_resize_request("devices=2,slow=1").slow is True
    empty = parse_resize_request("")  # "resize to whatever is visible"
    assert empty.devices is None and empty.grad_sync_cadence is None
    with pytest.raises(ValueError, match="unknown resize request key"):
        parse_resize_request("device=2")  # the typo'd key must be loud
    with pytest.raises(ValueError, match="devices must be >= 1"):
        parse_resize_request("devices=0")
    with pytest.raises(ValueError, match="key=value"):
        parse_resize_request("devices")
    # ISSUE 15: a resize can flip the sharding mode for the relaunch
    assert parse_resize_request("devices=8 sharding=fsdp").sharding == "fsdp"
    assert parse_resize_request("devices=2").sharding is None
    with pytest.raises(ValueError, match="sharding"):
        parse_resize_request("sharding=zero3")


def test_resize_apply_carries_sharding_mode(tmp_path):
    """The relaunch argv carries the requested sharding mode (argparse
    last-wins append, like the device count) — a grow onto a pod can flip
    dp→fsdp in the same resize."""
    d = str(tmp_path)
    ctl = ResizeController(d)
    write_resize_request(d, devices=8, sharding="fsdp")
    req = ctl.poll()
    assert req is not None and req.sharding == "fsdp"
    req = ctl.take()  # the child exited EXIT_RESIZE; claim + disarm
    argv = ["python", "-m", "moco_tpu.train", "--fake-devices", "1"]
    env = {}
    summary = ctl.apply(req, argv, env)
    assert argv[-4:] == ["--fake-devices", "8", "--sharding", "fsdp"]
    assert summary["sharding"] == "fsdp"
    # a mode-less request appends nothing: the original argv's own
    # --sharding (if any) keeps winning
    write_resize_request(d, devices=2)
    req2 = ctl.poll(now=time.monotonic() + 1.0)  # past the poll gate
    assert req2 is not None
    req2 = ctl.take()
    argv2 = ["python", "-m", "moco_tpu.train", "--sharding", "fsdp",
             "--fake-devices", "8"]
    ctl.apply(req2, argv2, env)
    assert "--sharding" not in argv2[-2:]
    assert argv2.count("--sharding") == 1


def test_request_claimed_exactly_once(tmp_path):
    d = str(tmp_path)
    write_resize_request(d, devices=2, grad_sync_cadence=4)
    req = consume_resize_request(d)
    assert req.devices == 2 and req.grad_sync_cadence == 4
    # the claim is a rename: a second consumer (or a relaunched child)
    # finds nothing, but the PAYLOAD survives at the honored path for the
    # supervisor's take() fallback
    assert consume_resize_request(d) is None
    honored = read_honored_request(d)
    assert honored is not None and honored.devices == 2
    assert consume_resize_request(str(tmp_path / "empty")) is None


def test_unparseable_request_is_claimed_and_ignored(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "resize.request"), "w") as f:
        f.write("device=2\n")  # typo
    assert consume_resize_request(d) is None
    # claimed anyway: a malformed request must not re-fire every poll
    assert not os.path.exists(os.path.join(d, "resize.request"))


# ---------------------------------------------------------------------------
# chaos fault + classification
# ---------------------------------------------------------------------------


def test_chaos_resize_spec_and_fire_once(tmp_path):
    plan = parse_chaos_spec("resize_at_step=6,devices=2")  # ISSUE 11 spelling
    assert plan.resize_at_step == 6 and plan.resize_devices == 2
    assert parse_chaos_spec("resize_at_step=3,resize_devices=4").resize_devices == 4
    assert plan.maybe_resize(5) is None
    assert plan.maybe_resize(6) == 2
    assert plan.maybe_resize(6) is None  # fire-once in-process
    # marker persistence (MOCO_TPU_CHAOS_STATE): the resized relaunch
    # re-polls every later step and must never be re-poisoned
    state = str(tmp_path / "chaos_state")
    first = ChaosPlan(resize_at_step=4, resize_devices=2, state_dir=state)
    assert first.maybe_resize(4) == 2
    second = ChaosPlan(resize_at_step=4, resize_devices=2, state_dir=state)
    assert second.maybe_resize(4) is None


def test_classify_resize_restartable_without_backoff():
    cls, detail = classify_exit(EXIT_RESIZE)
    assert cls == CLASS_RESIZE
    assert "resize" in detail
    assert CLASS_RESIZE not in FATAL_CLASSES
    policy = RestartPolicy()
    assert CLASS_RESIZE in policy.restart_on
    assert CLASS_RESIZE in policy.no_backoff


# ---------------------------------------------------------------------------
# argv rewrite + recorded-devices sidecar
# ---------------------------------------------------------------------------


def test_argv_device_count_last_wins_both_forms():
    assert argv_device_count(["x", "--num-devices", "4"]) == 4
    assert argv_device_count(["x", "--fake-devices=8"]) == 8
    # argparse last-wins is what the resize append relies on
    assert argv_device_count(["--num-devices", "4", "--num-devices", "2"]) == 2
    assert argv_device_count(["--fake-devices", "0"]) is None  # 0 = off
    assert argv_device_count(["x", "--batch-size", "16"]) is None
    assert pick_device_flag(["--fake-devices", "8"]) == "--fake-devices"
    assert pick_device_flag(["--num-devices=4"]) == "--num-devices"
    assert pick_device_flag(["x"]) == "--num-devices"


def test_read_recorded_devices_newest_stamped_step(tmp_path):
    ckpt = tmp_path / "ckpt"
    pos = ckpt / ".position"
    pos.mkdir(parents=True)
    (ckpt / "4").mkdir()
    (ckpt / "8").mkdir()
    (pos / "4.json").write_text('{"epoch": 1, "batch": 0, "devices": 4}')
    (pos / "8.json").write_text('{"epoch": 2, "batch": 0}')  # pre-elastic
    # newest step (8) has no devices stamp: fall back to the newest that does
    assert read_recorded_devices(str(ckpt)) == (4, 4)
    (pos / "8.json").write_text('{"epoch": 2, "batch": 0, "devices": 2}')
    assert read_recorded_devices(str(ckpt)) == (8, 2)
    assert read_recorded_devices(str(tmp_path / "missing")) is None


def test_controller_arms_once_and_applies(tmp_path, monkeypatch):
    monkeypatch.setenv("MOCO_TPU_CACHE_ROOT", str(tmp_path / "cache"))
    d = str(tmp_path)
    ctl = ResizeController(d, slow_cadence=8)
    assert ctl.poll() is None  # nothing pending
    write_resize_request(d, devices=2, slow=True)
    ctl._last_poll = float("-inf")  # bypass the poll gate for the test
    req = ctl.poll()
    assert req is not None and req.devices == 2 and req.slow
    assert ctl.poll() is None  # armed: no re-arm until taken
    taken = ctl.take()
    assert taken is req
    argv = ["python", "-m", "moco_tpu.train", "--fake-devices", "1"]
    env: dict = {}
    summary = ctl.apply(taken, argv, env)
    # appended, not edited (argparse last-wins): the operator argv stays
    # visible, the new count + the slow-link cadence override ride behind
    assert argv[-4:] == ["--fake-devices", "2", "--grad-sync-cadence", "8"]
    assert summary["devices_from"] == 1 and summary["devices_to"] == 2
    assert "per_run" in env["MOCO_TPU_CACHE_DIR"]
    # honored payload deleted after apply: a later payload-less resize
    # must not inherit this one's device count
    assert read_honored_request(d) is None
    # NO_CACHE suppresses the rotation
    env2: dict = {"MOCO_TPU_NO_CACHE": "1"}
    ctl.apply(ResizeRequest(), ["x"], env2)
    assert "MOCO_TPU_CACHE_DIR" not in env2


def test_sigusr2_to_controller_arms_empty_request(tmp_path):
    ctl = ResizeController(str(tmp_path))
    ctl.signal_resize()
    req = ctl.poll()
    assert req is not None and req.source == "sigusr2" and req.devices is None
    assert ctl.poll() is None


def test_sigusr2_recovers_payload_the_child_already_claimed(tmp_path):
    """Operator writes the request, the CHILD's listener claims the file,
    THEN the SIGUSR2 lands: the supervisor must recover the target count
    from the honored payload instead of resizing to 'visible'."""
    d = str(tmp_path)
    write_resize_request(d, devices=3)
    assert consume_resize_request(d) is not None  # the child's claim
    ctl = ResizeController(d)
    ctl.signal_resize()
    req = ctl.poll()
    assert req is not None and req.devices == 3 and req.source == "sigusr2"


def test_rotate_cache_opt_out_preserves_operator_cache(tmp_path):
    """--shared-compile-cache / operator-pinned MOCO_TPU_CACHE_DIR map to
    rotate_cache=False: a resize must not silently override an explicit
    cache choice."""
    ctl = ResizeController(str(tmp_path), rotate_cache=False)
    env = {"MOCO_TPU_CACHE_DIR": "/operator/pinned"}
    summary = ctl.apply(ResizeRequest(devices=2), ["x"], env)
    assert env["MOCO_TPU_CACHE_DIR"] == "/operator/pinned"
    assert "cache_dir" not in summary


def test_listener_file_trigger_and_sigusr2(tmp_path):
    d = str(tmp_path)
    with ResizeListener(d, poll_secs=0.0) as listener:
        assert not listener.poll()
        write_resize_request(d, devices=2)
        assert listener.poll()  # file trigger, consumed on claim
        assert not os.path.exists(os.path.join(d, "resize.request"))
    with ResizeListener("", poll_secs=0.0) as listener:
        assert not listener.poll()
        signal.raise_signal(signal.SIGUSR2)
        assert listener.triggered
    # a TRIGGERED listener leaves SIGUSR2 ignored on exit: the elastic
    # checkpoint is written AFTER the ExitStack closes, and a late
    # supervisor signal restored to the DEFAULT disposition would
    # terminate the child mid-save (the drill caught exactly this)
    assert signal.getsignal(signal.SIGUSR2) is signal.SIG_IGN
    signal.raise_signal(signal.SIGUSR2)  # must be harmless now
    # an UNtriggered listener restores the previous handler
    prev = signal.signal(signal.SIGUSR2, signal.SIG_DFL)
    try:
        with ResizeListener("", poll_secs=0.0):
            pass
        assert signal.getsignal(signal.SIGUSR2) == signal.SIG_DFL
    finally:
        signal.signal(signal.SIGUSR2, prev)


# ---------------------------------------------------------------------------
# guardrails: R5 covers the new exit path
# ---------------------------------------------------------------------------


def test_r5_covers_resize_exit_path(tmp_path):
    """The new exit path speaks the named constant: a literal 49 anywhere
    in the package would silently fork the supervisor's protocol (lint
    rule R5), and train.py's resize exit routes through EXIT_RESIZE."""
    from tools import lint_robustness as lint

    (tmp_path / "bad.py").write_text("import sys\nsys.exit(49)\n")
    found = lint.check_file(str(tmp_path / "bad.py"))
    assert len(found) == 1 and "named constants" in found[0]
    with open(os.path.join(REPO, "moco_tpu", "train.py")) as f:
        source = f.read()
    assert "sys.exit(EXIT_RESIZE)" in source
    assert lint.check_file(os.path.join(REPO, "moco_tpu", "train.py")) == []


# ---------------------------------------------------------------------------
# report folds
# ---------------------------------------------------------------------------


def _sup_record(event, **fields):
    rec = {"v": 1, "t": 0.0, "kind": "supervisor", "event": event}
    rec.update(fields)
    return rec


def test_report_resize_section_and_follow_lines():
    sys.path.insert(0, REPO)
    from tools.telemetry_report import render, render_record, summarize

    records = [
        _sup_record("launch", attempt=0),
        _sup_record("resize_request", source="request", devices=2),
        _sup_record("exit", classification="resize", returncode=49),
        _sup_record("resize_relaunch", source="request", devices_from=1,
                    devices_to=2, step=6, grad_sync_cadence=4),
        _sup_record("mesh_change", ckpt_step=6, devices_from=1,
                    devices_to=2),
        _sup_record("launch", attempt=1),
        _sup_record("exit", classification="clean", returncode=0),
        _sup_record("done", launches=2, restarts=1),
    ]
    summary = summarize(records)
    rsz = summary["resize"]
    assert rsz["requests"] == 1 and rsz["relaunches"] == 1
    assert rsz["mesh_changes"] == 1
    assert rsz["transitions"] == [{
        "devices_from": 1, "devices_to": 2, "step": 6,
        "grad_sync_cadence": 4, "source": "request",
    }]
    text = render(summary)
    assert "resize: 1 relaunch(es)" in text
    assert "1→2@6 (cadence 4)" in text
    assert "mesh changes observed at relaunch preflight: 1" in text
    # --follow: resize transitions get their own prefix, like fleet lines
    line = render_record(records[3])
    assert line.startswith("resize: resize_relaunch")
    assert render_record(records[4]).startswith("resize: mesh_change")
    assert render_record(records[0]).startswith("supervisor: launch")


# ---------------------------------------------------------------------------
# dialect shim: the restore every elastic relaunch performs
# ---------------------------------------------------------------------------


def test_dialect_shim_restores_across_mesh_size_change(tmp_path):
    """A quantized checkpoint saved under a 4-device mesh restored by a
    2-device run (the 1→2→1 drill's legs, one mesh hop): the shim detects
    the [n_dev, ...] accumulator mismatch, restores everything else
    exactly, and rebuilds the accumulators fresh-zero on the NEW mesh —
    with the saved mesh size recorded for the supervisor's preflight."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from moco_tpu.checkpoint import (
        checkpoint_manager,
        maybe_resume,
        save_checkpoint,
    )
    from moco_tpu.config import PretrainConfig
    from moco_tpu.parallel.gradsync import GradSync
    from moco_tpu.parallel.mesh import create_mesh, replicated
    from moco_tpu.train_state import create_train_state
    from moco_tpu.train_step import build_encoder, build_optimizer

    config = PretrainConfig(
        variant="v1", arch="resnet_tiny", cifar_stem=True, num_negatives=64,
        embed_dim=16, batch_size=16, epochs=2, lr=0.1,
        grad_sync="quantized", grad_sync_bucket_mb=0.05,
    )

    def build(mesh):
        model = build_encoder(config)
        tx, _sched = build_optimizer(config, 8)
        state = create_train_state(
            jax.random.key(0), model, tx, (16 // mesh.size, 16, 16, 3),
            64, 16,
        )
        return GradSync(config, mesh.size).attach(state, mesh)

    mesh4 = create_mesh(4)
    state4 = build(mesh4)
    # non-zero accumulators: the restore must DISCARD them, not carry them
    state4 = state4.replace(
        gradsync=jax.tree.map(jnp.ones_like, state4.gradsync))
    for leaf in jax.tree.leaves(state4.gradsync["acc"]):
        assert leaf.shape[0] == 4
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state4, 3, position=(0, 3), devices=mesh4.size)
    assert read_recorded_devices(str(tmp_path / "ckpt")) == (3, 4)

    mesh2 = create_mesh(2)
    fresh2 = build(mesh2)
    restored = maybe_resume(mgr, fresh2, "auto", sharding=replicated(mesh2))
    assert int(restored.step) == int(state4.step)
    for a, b in zip(jax.tree.leaves(restored.params_q),
                    jax.tree.leaves(state4.params_q), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for leaf in jax.tree.leaves(restored.gradsync["acc"]):
        assert leaf.shape[0] == 2          # the NEW mesh's accumulator
        assert float(jnp.max(jnp.abs(leaf))) == 0.0  # fresh zeros


# ---------------------------------------------------------------------------
# driver: the real train() honors a chaos resize
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_driver_chaos_resize_elastic_checkpoint(mesh8, tmp_path):
    from moco_tpu.config import get_preset
    from moco_tpu.train import train

    tdir = tmp_path / "telemetry"
    cfg = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=16,
        num_negatives=64, embed_dim=32, lr=0.1, epochs=3, steps_per_epoch=4,
        ckpt_dir=str(tmp_path / "ckpt"), tb_dir="", print_freq=1000,
        num_classes=10, knn_monitor=False, telemetry_dir=str(tdir),
        heartbeat_secs=0.0,
    )
    with chaos_context(ChaosPlan(resize_at_step=6, resize_devices=2)):
        _state, metrics = train(cfg, mesh8)
    assert metrics.get("resized") is True
    # elastic checkpoint at the fault step, mesh size recorded for the
    # supervisor's preflight
    assert read_recorded_devices(cfg.ckpt_dir) == (6, 8)
    # the chaos drill left the target count where the supervisor looks
    req = consume_resize_request(str(tdir))
    assert req is not None and req.devices == 2
    # the exit heartbeat says a resize relaunch is expected
    with open(tdir / "heartbeat.json") as f:
        hb = json.load(f)
    assert hb["phase"] == "resize_exit" and hb["step"] == 6


# ---------------------------------------------------------------------------
# stub-child e2e: the real Supervisor loop, seconds-cheap children
# ---------------------------------------------------------------------------

_STUB = textwrap.dedent("""\
    import json, os, signal, sys, time
    tdir, state_path, ckpt_dir = sys.argv[1], sys.argv[2], sys.argv[3]
    plan = sys.argv[4].split(",")
    extra = sys.argv[5:]
    n = 0
    if os.path.exists(state_path):
        n = int(open(state_path).read())
    open(state_path, "w").write(str(n + 1))
    with open(os.path.join(tdir, "argv_%d.json" % n), "w") as f:
        json.dump(extra, f)
    with open(os.path.join(tdir, "env_%d.json" % n), "w") as f:
        json.dump({"cache": os.environ.get("MOCO_TPU_CACHE_DIR", "")}, f)
    def beat(step, phase="step"):
        p = os.path.join(tdir, "heartbeat.json")
        with open(p + ".tmp", "w") as f:
            json.dump({"v": 1, "t": round(time.time(), 3), "step": step,
                       "pid": os.getpid(), "phase": phase}, f)
        os.replace(p + ".tmp", p)
    def ckpt(step, devices):
        d = os.path.join(ckpt_dir, str(step))
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "payload.bin"), "wb") as f:
            f.write(b"x" * 64)
        pd = os.path.join(ckpt_dir, ".position")
        os.makedirs(pd, exist_ok=True)
        with open(os.path.join(pd, "%d.json" % step), "w") as f:
            json.dump({"epoch": 0, "batch": step, "devices": devices}, f)
    behavior = plan[min(n, len(plan) - 1)]
    kind, _, arg = behavior.partition(":")
    if kind == "resize49":
        # beat, write an "elastic checkpoint" (step/devices from arg),
        # linger so the supervisor's poll can arm + signal, then exit 49
        step, devices = (int(x) for x in arg.split("/"))
        signal.signal(signal.SIGUSR2, signal.SIG_IGN)
        beat(step)
        ckpt(step, devices)
        time.sleep(0.6)
        sys.exit(49)
    elif kind == "usr2exit":
        # honor SIGUSR2 like the real driver's ResizeListener path
        signal.signal(signal.SIGUSR2, lambda *a: sys.exit(49))
        beat(int(arg or 2))
        time.sleep(30)
        sys.exit(1)
    elif kind == "exit":
        beat(2)
        sys.exit(int(arg))
    elif kind == "ok":
        beat(int(arg or 5))
        sys.exit(0)
    else:
        raise SystemExit("unknown stub behavior %r" % behavior)
""")


def _stub_supervisor(tmp_path, plan, argv_extra=(), **sup_kw):
    stub = tmp_path / "stub.py"
    stub.write_text(_STUB)
    tdir = tmp_path / "telemetry"
    tdir.mkdir(exist_ok=True)
    ckpt = tmp_path / "ckpt"
    policy = RestartPolicy(
        max_restarts=3, heartbeat_stale_secs=10.0, startup_grace_secs=10.0,
        term_grace_secs=1.0, backoff_base_secs=0.05, backoff_max_secs=0.2,
        backoff_jitter=0.0, poll_secs=0.1,
    )
    return Supervisor(
        [sys.executable, str(stub), str(tdir), str(tmp_path / "attempts"),
         str(ckpt), plan, *argv_extra],
        telemetry_dir=str(tdir),
        ckpt_dir=str(ckpt),
        policy=policy,
        seed=0,
        **sup_kw,
    ), tdir


def test_e2e_request_file_resize_rewrites_relaunch(tmp_path, monkeypatch):
    """The whole supervisor-side loop on a stub child: a pending
    resize.request is armed and consumed, the child's 49 relaunches with
    the device flag appended + a fresh per-resize cache dir, the
    mesh_change preflight fires (sidecar says 1, argv now says 2), and
    the incident lands as resize events + a `resize` span under the
    child span."""
    monkeypatch.setenv("MOCO_TPU_CACHE_ROOT", str(tmp_path / "cacheroot"))
    sup, tdir = _stub_supervisor(
        tmp_path, "resize49:4/1,ok:8", argv_extra=("--fake-devices", "1"),
    )
    write_resize_request(str(tdir), devices=2)
    result = sup.run()
    assert result.final_class == CLASS_CLEAN
    assert result.classifications == [CLASS_RESIZE, CLASS_CLEAN]
    assert result.restarts == 1 and not result.gave_up
    # no backoff: a resize exit is voluntary
    assert [r for r in sup.incidents if r["event"] == "backoff"] == []
    requests = [r for r in sup.incidents if r["event"] == "resize_request"]
    assert requests and requests[0]["devices"] == 2
    relaunches = [r for r in sup.incidents
                  if r["event"] == "resize_relaunch"]
    assert len(relaunches) == 1
    assert relaunches[0]["devices_from"] == 1
    assert relaunches[0]["devices_to"] == 2
    # preflight membership check: recorded mesh 1 vs relaunch argv 2
    changes = [r for r in sup.incidents if r["event"] == "mesh_change"]
    assert changes and (changes[0]["devices_from"],
                        changes[0]["devices_to"]) == (1, 2)
    # the relaunch argv carries the new count AND --resume auto
    with open(tdir / "argv_1.json") as f:
        argv1 = json.load(f)
    assert argv1[-4:] == ["--fake-devices", "2", "--resume", "auto"]
    # fresh per-resize compile cache, distinct from launch 0's
    with open(tdir / "env_1.json") as f:
        env1 = json.load(f)
    assert "resize0" in env1["cache"]
    with open(tdir / "env_0.json") as f:
        assert json.load(f)["cache"] != env1["cache"]
    # one traced incident: a `resize` span parented under a child span
    spans = read_events_tail(os.path.join(str(tdir), "spans.jsonl"))
    child_ids = {s["span"] for s in spans if s.get("name") == "child"}
    resize_spans = [s for s in spans if s.get("name") == "resize"]
    assert resize_spans and resize_spans[0]["parent"] in child_ids
    assert resize_spans[0]["attrs"]["devices_to"] == 2
    # the report folds the same stream
    from tools.telemetry_report import summarize

    records = read_events_tail(os.path.join(str(tdir), "events.jsonl"),
                               max_bytes=1 << 20)
    summary = summarize(records)
    assert summary["resize"]["relaunches"] == 1
    assert summary["supervisor"]["classifications"] == ["resize", "clean"]


def test_e2e_sigusr2_resize_without_payload(tmp_path, monkeypatch):
    """SIGUSR2 to the SUPERVISOR with no request file: the child is
    signaled (the stub exits 49 from its handler, like the driver's
    listener), and the relaunch keeps the argv's own device flags — only
    the compile cache rotates."""
    monkeypatch.setenv("MOCO_TPU_CACHE_ROOT", str(tmp_path / "cacheroot"))
    sup, tdir = _stub_supervisor(
        tmp_path, "usr2exit:2,ok:9", argv_extra=("--fake-devices", "1"),
    )
    runner = threading.Thread(target=lambda: setattr(
        sup, "_test_result", sup.run()))
    runner.start()
    time.sleep(0.5)  # child up and beating
    sup.resize.signal_resize()  # what the CLI's SIGUSR2 handler calls
    runner.join(timeout=30)
    assert not runner.is_alive()
    result = sup._test_result
    assert result.final_class == CLASS_CLEAN
    assert result.classifications == [CLASS_RESIZE, CLASS_CLEAN]
    relaunches = [r for r in sup.incidents
                  if r["event"] == "resize_relaunch"]
    assert relaunches and relaunches[0]["devices_to"] is None
    assert relaunches[0]["source"] == "sigusr2"
    with open(tdir / "argv_1.json") as f:
        argv1 = json.load(f)
    assert argv1.count("--fake-devices") == 1  # untouched: no target count
    with open(tdir / "env_1.json") as f:
        assert "resize0" in json.load(f)["cache"]


def test_e2e_unbootable_resize_reverts_instead_of_dying(tmp_path,
                                                        monkeypatch):
    """A typo'd device count (more devices than the hardware has) makes
    the resized argv exit config_error at boot. The supervisor must
    REVERT the appended flags and finish the run on the old mesh — a bad
    resize request must not take a healthy run down (and must not grind
    the restart budget on a fatal class either)."""
    monkeypatch.setenv("MOCO_TPU_CACHE_ROOT", str(tmp_path / "cacheroot"))
    # launch 0 resizes; launch 1 (the resized argv) dies 45; launch 2
    # (reverted argv) finishes clean
    sup, tdir = _stub_supervisor(
        tmp_path, "resize49:4/1,exit:45,ok:8",
        argv_extra=("--fake-devices", "1"),
    )
    write_resize_request(str(tdir), devices=100)
    base_len = len(sup.child_argv)
    result = sup.run()
    assert result.final_class == CLASS_CLEAN, result
    assert result.classifications == [CLASS_RESIZE, "config_error",
                                      CLASS_CLEAN]
    reverts = [r for r in sup.incidents if r["event"] == "resize_revert"]
    assert reverts and reverts[0]["dropped"] == ["--fake-devices", "100"]
    assert len(sup.child_argv) == base_len  # appended flags gone
    with open(tdir / "argv_2.json") as f:
        argv2 = json.load(f)
    assert "100" not in argv2
    # report folds the revert
    from tools.telemetry_report import render, summarize

    summary = summarize(sup.incidents)
    assert summary["resize"]["reverts"] == 1
    assert "1 reverted (unbootable argv)" in render(summary)


def test_take_path_still_records_the_request(tmp_path, monkeypatch):
    """A resize the child honored before the supervisor's poll armed it
    (the chaos drill shape: request written + exit 49 within one poll
    cycle) must still land a resize_request record — a report reading
    'relaunches from 0 requests' looks like resizes nobody asked for."""
    monkeypatch.setenv("MOCO_TPU_CACHE_ROOT", str(tmp_path / "cacheroot"))
    sup, tdir = _stub_supervisor(
        tmp_path, "exit:49,ok:8", argv_extra=("--fake-devices", "1"),
    )
    # freeze the controller's file poll: the monitor never arms, so only
    # take() can claim the request
    sup.resize._last_poll = float("inf")
    write_resize_request(str(tdir), devices=2)
    result = sup.run()
    assert result.classifications == [CLASS_RESIZE, CLASS_CLEAN]
    requests = [r for r in sup.incidents if r["event"] == "resize_request"]
    assert len(requests) == 1 and requests[0]["devices"] == 2
    from tools.telemetry_report import summarize

    summary = summarize(sup.incidents)
    assert summary["resize"]["requests"] == 1
    assert summary["resize"]["relaunches"] == 1


# ---------------------------------------------------------------------------
# the full drill: supervised 1→2→1 on the CPU proxy, zero manual steps
# ---------------------------------------------------------------------------


def _drill_argv(tdir, ckpt_dir):
    return [
        sys.executable, "-m", "moco_tpu.train",
        "--preset", "cifar10-moco-v1", "--fake-devices", "1",
        "--arch", "resnet_tiny", "--dataset", "synthetic",
        "--image-size", "16", "--batch-size", "16",
        "--num-negatives", "64", "--embed-dim", "32", "--lr", "0.1",
        "--epochs", "6", "--steps-per-epoch", "4", "--print-freq", "1",
        "--knn-monitor", "false", "--num-classes", "10",
        "--watchdog-secs", "0",
        # quantized gradsync: per-device error-feedback accumulators — the
        # state the dialect shim rebuilds fresh-zero at each mesh hop (the
        # bounded-divergence contract the continuity pin runs at);
        # sync_bn keeps the BN statistics mesh-size-invariant so the mesh
        # hops themselves are not a second, unbounded divergence source
        "--grad-sync", "quantized", "--sync-bn", "true",
        "--telemetry-dir", str(tdir), "--telemetry-flush-steps", "4",
        "--heartbeat-secs", "0.05", "--ckpt-dir", str(ckpt_dir),
    ]


def _drill_env(chaos="", chaos_state=""):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MOCO_TPU_NO_CACHE"] = "1"  # PR 4 finding: kill-risk runs + cache
    env.pop("MOCO_TPU_CACHE_DIR", None)
    if chaos:
        env["MOCO_TPU_CHAOS"] = chaos
        env["MOCO_TPU_CHAOS_STATE"] = chaos_state
    else:
        env.pop("MOCO_TPU_CHAOS", None)
        env.pop("MOCO_TPU_CHAOS_STATE", None)
    return env


def _step_losses(events_path):
    losses = {}
    for rec in read_events_tail(events_path, max_bytes=1 << 22):
        if rec.get("kind") == "step" and "loss" in rec:
            losses[int(rec["step"])] = float(rec["loss"])
    return losses


@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_resize_drill_1_2_1_loss_continuity(tmp_path):
    """ISSUE 11 acceptance: a supervised CPU run resizes 1→2 (chaos
    `resize_at_step`, the deterministic drill) and back 2→1 (an operator
    resize.request — the file-trigger path) with ZERO manual steps: the
    supervisor consumes each request, the child exits 49 with a verified
    elastic checkpoint, the relaunch restores onto the new mesh via the
    dialect shim (fresh-zero accumulators, logged `ckpt-dialect` events),
    and the final loss matches an uninterrupted run within the gradsync
    shim's bounded-divergence tolerance (the EF state restarts from
    zeros at each hop). The whole story is one run_id of resize events,
    rendered by telemetry_report."""
    import numpy as np

    # uninterrupted 1-device reference, same subprocess environment
    ref_t = tmp_path / "ref_telemetry"
    ref_ckpt = tmp_path / "ref_ckpt"
    proc = subprocess.run(
        _drill_argv(ref_t, ref_ckpt), env=_drill_env(),
        capture_output=True, text=True, timeout=900, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    ref_losses = _step_losses(os.path.join(str(ref_t), "events.jsonl"))
    assert 24 in ref_losses

    # supervised drill: chaos fires the 1→2 resize at step 5; the slow
    # stall at step 9 (fire-once, so only the SECOND child hits it) holds
    # the 2-device leg open while the test drops the operator's 2→1
    # request — the supervisor does everything else
    sup_t = tmp_path / "sup_telemetry"
    sup_ckpt = tmp_path / "sup_ckpt"
    sup_t.mkdir()
    sup = Supervisor(
        _drill_argv(sup_t, sup_ckpt),
        telemetry_dir=str(sup_t),
        ckpt_dir=str(sup_ckpt),
        env=_drill_env(
            chaos="resize_at_step=5,devices=2,slow_at_step=9,slow_ms=8000",
            chaos_state=str(tmp_path / "chaos_state"),
        ),
        policy=RestartPolicy(
            max_restarts=4, heartbeat_stale_secs=60.0,
            startup_grace_secs=600.0, term_grace_secs=3.0,
            backoff_base_secs=0.1, backoff_max_secs=1.0, poll_secs=0.25,
        ),
        seed=0,
    )

    def drop_request_when_second_leg_runs():
        # wait for the 2-device child to be stepping (any beat past the
        # resize step), then file the operator's scale-back request; the
        # 8 s chaos stall at step 9 keeps the child alive while the
        # supervisor consumes the file and SIGUSR2s it
        deadline = time.monotonic() + 600
        hb_path = os.path.join(str(sup_t), "heartbeat.json")
        while time.monotonic() < deadline:
            try:
                with open(hb_path) as f:
                    hb = json.load(f)
                if hb.get("phase") == "step" and int(hb.get("step", 0)) > 5:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        write_resize_request(str(sup_t), devices=1)

    requester = threading.Thread(target=drop_request_when_second_leg_runs)
    requester.start()
    result = sup.run()
    requester.join(timeout=10)
    assert result.final_class == CLASS_CLEAN, result
    assert not result.gave_up
    assert result.classifications == [CLASS_RESIZE, CLASS_RESIZE,
                                      CLASS_CLEAN], result

    # both relaunches rewrote the argv: 1→2, then 2→1
    relaunches = [r for r in sup.incidents
                  if r["event"] == "resize_relaunch"]
    assert [(r["devices_from"], r["devices_to"]) for r in relaunches] == \
        [(1, 2), (2, 1)]

    events_path = os.path.join(str(sup_t), "events.jsonl")
    records = read_events_tail(events_path, max_bytes=1 << 22)
    # every record of the incident carries ONE run id
    run_ids = {r.get("run_id") for r in records if r.get("run_id")}
    assert run_ids == {sup.run_id}
    # the dialect shim fired at each mesh hop (fresh-zero accumulators)
    dialect = [r for r in records if r.get("kind") == "event"
               and r.get("event") == "ckpt-dialect"]
    assert len(dialect) >= 2, dialect

    # loss-curve continuity: the drill ends where the uninterrupted run
    # ends, within the bounded-divergence tolerance the gradsync dialect
    # shim promises (PR 6 pins quantized-vs-exact at <= 5%; each hop only
    # resets EF state to its cold-start zeros)
    sup_losses = _step_losses(events_path)
    assert 24 in sup_losses, sorted(sup_losses)
    # the 1-device leg before the first resize is the SAME program on the
    # same data: bitwise-equal losses, not merely close
    for step in range(1, 5):
        assert sup_losses[step] == ref_losses[step], step
    final_ref, final_sup = ref_losses[24], sup_losses[24]
    assert abs(final_sup - final_ref) <= 0.05 * abs(final_ref), (
        f"final loss diverged past the shim tolerance: "
        f"ref={final_ref} resized={final_sup}"
    )

    # the whole incident renders as one story
    report = os.path.join(REPO, "tools", "telemetry_report.py")
    out = subprocess.run([sys.executable, report, events_path],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert "resize: 2 relaunch(es)" in out.stdout, out.stdout
    as_json = subprocess.run([sys.executable, report, events_path, "--json"],
                             capture_output=True, text=True)
    summary = json.loads(as_json.stdout)
    assert summary["resize"]["relaunches"] == 2
    assert [t["devices_to"] for t in summary["resize"]["transitions"]] == \
        [2, 1]
    np.testing.assert_allclose(final_sup, final_ref, rtol=0.05)
