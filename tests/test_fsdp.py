"""FSDP sharding for the v3 step (ISSUE 15, parallel/fsdp.py).

Parity gates on the tiny-ViT CPU proxy over a 4-device single-process
mesh (the pod-math stand-in — the 2-proc multihost harness is dead at
seed in this container):

- `sharding=fsdp` with `grad_sync=fused|bucketed` is BITWISE-pinned
  against plain dp: the all-gather-on-use reconstructs the exact bits,
  the reduce is the same psum over the same device order, and the
  elementwise optimizer computes each shard identically;
- quantized (incl. the fsdp_tp multi-hop reduce) and demo extend their
  ISSUE-6 bounded-divergence gates to fsdp;
- per-device param+optimizer bytes measure ~1/N of dp (the acceptance
  inventory);
- dp→fsdp and 4→2-device restores land params exactly and gradsync EF
  state fresh-zero through the dialect-3 path (no silent slices).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.models.vit import ViT
from moco_tpu.parallel import fsdp
from moco_tpu.parallel.gradsync import GradSync
from moco_tpu.parallel.mesh import (
    FSDP_AXIS,
    create_mesh,
    create_mesh_2d,
    default_fsdp_size,
    mesh_for_config,
)
from moco_tpu.train_step import build_optimizer, build_train_step
from moco_tpu.v3_step import V3Model, create_v3_train_state

IMG, B = 16, 16
N_STEPS = 3


def tiny_config(**kw):
    base = dict(
        variant="v3", arch="vit_small", embed_dim=16, momentum_ema=0.99,
        momentum_ramp=True, temperature=0.2, optimizer="adamw", lr=1e-3,
        weight_decay=0.1, batch_size=B, epochs=2, warmup_epochs=0,
    )
    base.update(kw)
    return PretrainConfig(**base)


def _build(config, mesh):
    model = V3Model(
        ViT(patch_size=8, width=32, depth=2, num_heads=2, num_classes=None),
        embed_dim=16, hidden_dim=32,
    )
    tx, sched = build_optimizer(config, 4)
    state = create_v3_train_state(
        jax.random.key(0), model, tx, (B // mesh.size, IMG, IMG, 3)
    )
    state = GradSync(config, mesh.size).attach(state, mesh)
    state = fsdp.place_state(state, mesh, config)
    step = build_train_step(config, model, tx, mesh, 4, sched, state=state)
    return state, step


def _run(config, steps=N_STEPS):
    mesh = mesh_for_config(config, create_mesh(4))
    state, step = _build(config, mesh)
    losses = []
    for i in range(steps):
        x1 = jax.random.normal(jax.random.key(100 + i), (B, IMG, IMG, 3))
        x2 = jax.random.normal(jax.random.key(200 + i), (B, IMG, IMG, 3))
        state, m = step(state, x1, x2)
        losses.append(float(m["loss"]))
    return state, losses


@pytest.fixture(scope="module")
def dp_run():
    return _run(tiny_config())


@pytest.fixture(scope="module")
def fsdp_run():
    return _run(tiny_config(sharding="fsdp"))


# ---------------------------------------------------------------------------
# mesh / config surface
# ---------------------------------------------------------------------------


def test_mesh_for_config_shapes():
    m_dp = mesh_for_config(tiny_config(), create_mesh(4))
    assert tuple(m_dp.axis_names) == ("data",)
    m_f = mesh_for_config(tiny_config(sharding="fsdp"), create_mesh(4))
    assert tuple(m_f.axis_names) == ("data", FSDP_AXIS)
    assert m_f.devices.shape == (1, 4)
    m_t = mesh_for_config(tiny_config(sharding="fsdp_tp"), create_mesh(4))
    assert m_t.devices.shape == (2, 2)
    m_t3 = mesh_for_config(
        tiny_config(sharding="fsdp_tp", sharding_axis_size=4), create_mesh(8))
    assert m_t3.devices.shape == (2, 4)
    # device ORDER is preserved (the bitwise-parity anchor)
    assert list(m_f.devices.flat) == list(create_mesh(4).devices.flat)
    assert default_fsdp_size("fsdp", 8) == 8
    assert default_fsdp_size("fsdp_tp", 8) == 4


def test_config_rejects_bad_sharding():
    with pytest.raises(ValueError, match="sharding"):
        tiny_config(sharding="zero3")
    with pytest.raises(ValueError, match="variant"):
        PretrainConfig(variant="v2", sharding="fsdp")
    with pytest.raises(ValueError, match="collective_chunks"):
        tiny_config(collective_chunks=0)
    with pytest.raises(ValueError, match="zero_sharding"):
        tiny_config(sharding="fsdp", zero_sharding=True)
    with pytest.raises(ValueError, match="divide"):
        mesh_for_config(tiny_config(sharding="fsdp_tp", sharding_axis_size=3),
                        create_mesh(4))


# ---------------------------------------------------------------------------
# parity: fused/bucketed bitwise, quantized/demo bounded
# ---------------------------------------------------------------------------


def test_fsdp_fused_bitwise_parity_with_dp(dp_run, fsdp_run):
    sd, ld = dp_run
    sf, lf = fsdp_run
    assert ld == lf
    for a, b in zip(jax.tree.leaves(sd.params_q), jax.tree.leaves(sf.params_q),
                    strict=True):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(sd.opt_state), jax.tree.leaves(sf.opt_state),
                    strict=True):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_params_actually_sharded(fsdp_run):
    sf, _ = fsdp_run
    sharded = [
        leaf for leaf in jax.tree.leaves(sf.params_q)
        if hasattr(leaf, "sharding") and FSDP_AXIS in
        jax.tree.leaves(tuple(leaf.sharding.spec))
    ]
    assert sharded, "no param leaf is sharded over the fsdp axis"
    # a sharded leaf's per-device shard really is 1/4 of the logical array
    leaf = sharded[0]
    shard = leaf.addressable_shards[0]
    assert np.prod(shard.data.shape) == np.prod(leaf.shape) // 4


def test_fsdp_state_bytes_quarter_of_dp(dp_run, fsdp_run):
    sd, _ = dp_run
    sf, _ = fsdp_run
    inv_d = fsdp.state_bytes_per_device(sd)
    inv_f = fsdp.state_bytes_per_device(sf)
    # ~1/N with slack only for the replicated small leaves (biases, LN
    # scales, cls token, opt scalars)
    ratio = inv_f["state_bytes_per_device"] / inv_d["state_bytes_per_device"]
    assert ratio < 0.35, (inv_d, inv_f)
    assert inv_f["param_bytes_per_device"] < 0.35 * inv_d["param_bytes_per_device"]


def test_fsdp_bucketed_bitwise_parity_with_dp(dp_run):
    _, ld = dp_run
    sb, lb = _run(tiny_config(sharding="fsdp", grad_sync="bucketed",
                              grad_sync_bucket_mb=0.05))
    assert ld == lb


def test_fsdp_tp_fused_bitwise_parity_with_dp(dp_run):
    _, ld = dp_run
    st, lt = _run(tiny_config(sharding="fsdp_tp"))
    assert ld == lt
    # the hybrid 2x2 mesh shards params over fsdp=2 only
    inv = fsdp.state_bytes_per_device(st)
    assert inv["param_bytes_per_device"] > 0


def test_fsdp_quantized_bounded_divergence(dp_run):
    _, ld = dp_run
    sq, lq = _run(tiny_config(sharding="fsdp", grad_sync="quantized",
                              grad_sync_bucket_mb=0.05))
    assert all(np.isfinite(lq))
    for a, b in zip(ld, lq):
        assert abs(a - b) <= 0.05 * max(abs(a), 1.0), (ld, lq)
    # error feedback lives: [n_dev, ...] leading axis, nonzero residual
    acc = jax.tree.leaves(sq.gradsync["acc"])
    assert acc and all(a.shape[0] == 4 for a in acc)
    assert any(float(jnp.max(jnp.abs(a))) > 0 for a in acc)


def test_fsdp_tp_multihop_quantized_bounded_divergence(dp_run):
    """fsdp_tp + quantized = the DynamiQ-style two-hop reduce (exact
    intra-axis psum, int8 inter-axis hop): still inside the single-hop
    quantized band vs exact DP."""
    _, ld = dp_run
    _, lq = _run(tiny_config(sharding="fsdp_tp", grad_sync="quantized",
                             grad_sync_bucket_mb=0.05))
    assert all(np.isfinite(lq))
    for a, b in zip(ld, lq):
        assert abs(a - b) <= 0.05 * max(abs(a), 1.0), (ld, lq)


def test_fsdp_demo_bounded_divergence(dp_run):
    _, ld = dp_run
    sd_, ldm = _run(tiny_config(sharding="fsdp", grad_sync="demo",
                                grad_sync_topk=0.25))
    assert all(np.isfinite(ldm))
    for a, b in zip(ld, ldm):
        assert abs(a - b) <= 0.5 * max(abs(a), 1.0), (ld, ldm)
    acc = jax.tree.leaves(sd_.gradsync["acc"])
    assert any(float(jnp.max(jnp.abs(a))) > 0 for a in acc)


@pytest.mark.slow
def test_fsdp_chunked_gather_bitwise(dp_run):
    """FAST-style chunked key-gather scheduling is pure scheduling: the
    fsdp+chunks program reproduces the dp trajectory bit-for-bit. (The
    collective-level bitwise restitch pin is tier-1 in
    tests/test_collectives.py; this whole-step soak rides the slow
    suite for the tier-1 budget.)"""
    _, ld = dp_run
    _, lc = _run(tiny_config(sharding="fsdp", collective_chunks=2))
    assert ld == lc


# ---------------------------------------------------------------------------
# multihop reduce unit (region-level)
# ---------------------------------------------------------------------------


def test_gradsync_for_mesh_reports_multihop_bytes(mesh8):
    """GradSync.for_mesh binds the strategy to the mesh's own axes: on a
    2-D mesh with both axes > 1, quantized describe() carries the
    multihop block and counts BOTH hops — a hand-rolled
    GradSync(config, mesh.size) would under-report the wire bytes ~5x
    (the drift the driver's telemetry emits to BENCH)."""
    params = {"w": jnp.zeros((256,), jnp.float32)}
    config = tiny_config(sharding="fsdp_tp", grad_sync="quantized")
    mesh2d = create_mesh_2d(4, devices=list(mesh8.devices.flat))
    gs = GradSync.for_mesh(config, mesh2d)
    assert gs.multihop
    info = gs.describe(params)
    assert info["multihop"]["intra_size"] == 4
    assert info["multihop"]["inter_size"] == 2
    # int8 inter payload + f32 intra hop + one scale
    assert info["sync_bytes_per_step"] == 256 * 1 + 256 * 4 + 4
    assert info["multihop"]["intra_bytes_per_step"] == 256 * 4
    assert info["multihop"]["inter_bytes_per_step"] == 256 * 1 + 4
    # the (1, N) fsdp mesh has a size-1 outer axis: single-hop, same
    # accounting as plain dp quantized
    mesh_f = mesh_for_config(tiny_config(sharding="fsdp"), create_mesh(4))
    gs_f = GradSync.for_mesh(tiny_config(sharding="fsdp",
                                         grad_sync="quantized"), mesh_f)
    assert not gs_f.multihop
    assert gs_f.describe(params)["sync_bytes_per_step"] == 256 * 1 + 4


def test_multihop_reduce_means_match_single_hop(mesh8):
    """The two-hop quantized mean equals the single-hop quantized mean to
    int8 tolerance, and the per-device EF residuals reassemble to the full
    group residual exactly once (the /n_intra bookkeeping)."""
    from jax.sharding import PartitionSpec as P

    from moco_tpu.parallel.collectives import (
        multihop_quantized_psum_mean,
        quantized_psum_mean,
    )
    from moco_tpu.utils.compat import shard_map

    mesh2d = create_mesh_2d(4, devices=list(mesh8.devices.flat))
    x = jax.random.normal(jax.random.key(0), (8, 64))

    def multi(v):
        means, errs = multihop_quantized_psum_mean(
            [v.reshape(-1)], "data", "fsdp", 2, 4, "int8")
        return means[0], errs[0]

    def single(v):
        means, errs = quantized_psum_mean(
            [v.reshape(-1)], ("data", "fsdp"), 8, "int8")
        return means[0]

    fm = jax.jit(shard_map(
        multi, mesh=mesh2d,
        in_specs=(P(("data", "fsdp")),),
        out_specs=(P(), P(("data", "fsdp"))),
    ))
    fs = jax.jit(shard_map(
        single, mesh=mesh2d,
        in_specs=(P(("data", "fsdp")),), out_specs=P(),
    ))
    mean_m, errs = fm(x)
    mean_s = fs(x)
    true_mean = np.asarray(x).reshape(8, -1).mean(axis=0)
    # the multihop quantum is scale(intra SUM)/127/n_intra ≈ 0.006 on this
    # draw — both reduces must land within one quantum of the true mean
    np.testing.assert_allclose(np.asarray(mean_m), true_mean,
                               rtol=0.2, atol=0.01)
    np.testing.assert_allclose(np.asarray(mean_s), true_mean,
                               rtol=0.2, atol=0.01)
    # EF bookkeeping: summing every device's stored residual over an
    # intra group recovers the group residual once (stored as /n_intra)
    errs = np.asarray(errs)  # [8, 64] — one row per device
    group_sum = np.asarray(x).reshape(2, 4, -1).sum(axis=1)
    per_group_err = errs.reshape(2, 4, -1).sum(axis=1)
    # residual == intra_sum - dequantized wire value; bounded by one
    # quantum of the shared scale
    scale = np.abs(group_sum).max() / 127.0
    assert np.abs(per_group_err).max() <= scale * 1.01


# ---------------------------------------------------------------------------
# checkpoint: dp→fsdp, fsdp→dp, 4→2 — dialect 3
# ---------------------------------------------------------------------------


def test_dp_to_fsdp_restore_lands_sharded(tmp_path, dp_run):
    """A dp checkpoint restores straight into the fsdp placement (same
    logical tree, different NamedShardings): params bitwise, leaves
    sharded."""
    from moco_tpu.checkpoint import (
        checkpoint_manager,
        restore_checkpoint,
        save_checkpoint,
    )

    sd, _ = dp_run
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, sd, 3, position=(0, 3), devices=4, sharding="dp")
    config = tiny_config(sharding="fsdp")
    mesh = mesh_for_config(config, create_mesh(4))
    fresh, _ = _build(config, mesh)
    target = fsdp.state_shardings(fresh, mesh, config)
    restored = restore_checkpoint(mgr, fresh, 3, sharding=target)
    assert int(restored.step) == int(sd.step)
    for a, b in zip(jax.tree.leaves(restored.params_q),
                    jax.tree.leaves(sd.params_q), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    sharded = [
        leaf for leaf in jax.tree.leaves(restored.params_q)
        if hasattr(leaf, "sharding") and FSDP_AXIS in
        jax.tree.leaves(tuple(leaf.sharding.spec))
    ]
    assert sharded, "restore dropped the fsdp placement"
    from moco_tpu.checkpoint import read_recorded_sharding

    assert read_recorded_sharding(str(tmp_path / "ckpt"), 3) == "dp"


def test_fsdp_4_to_2_restore_rebuilds_ef_fresh_zero(tmp_path):
    """The elastic 4→2 leg under sharding=fsdp: a quantized 4-device fsdp
    checkpoint restored by a 2-device fsdp run — params exact, the
    [4, ...] accumulators rebuilt fresh-zero on the new mesh (the PR 11
    silent-slice guard, now exercised with the sharded layout)."""
    from moco_tpu.checkpoint import (
        checkpoint_manager,
        maybe_resume,
        save_checkpoint,
    )

    config = tiny_config(sharding="fsdp", grad_sync="quantized",
                         grad_sync_bucket_mb=0.05)
    mesh4 = mesh_for_config(config, create_mesh(4))
    state4, _ = _build(config, mesh4)
    # non-zero accumulators: the restore must DISCARD them, not slice them
    state4 = state4.replace(
        gradsync=jax.tree.map(jnp.ones_like, state4.gradsync))
    mgr = checkpoint_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state4, 5, position=(0, 5), devices=4,
                    sharding="fsdp")
    mesh2 = mesh_for_config(config, create_mesh(2))
    fresh2, _ = _build(config, mesh2)
    target = fsdp.state_shardings(fresh2, mesh2, config)
    restored = maybe_resume(mgr, fresh2, "auto", sharding=target)
    assert int(restored.step) == int(state4.step)
    for a, b in zip(jax.tree.leaves(restored.params_q),
                    jax.tree.leaves(state4.params_q), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for leaf in jax.tree.leaves(restored.gradsync["acc"]):
        assert leaf.shape[0] == 2              # the NEW mesh's accumulator
        assert float(jnp.max(jnp.abs(leaf))) == 0.0  # fresh zeros, no slice


# ---------------------------------------------------------------------------
# telemetry: the sharding event renders, MFU is labeled per mode
# ---------------------------------------------------------------------------


def test_report_renders_sharding_line_and_mfu_label(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "telemetry_report.py"),
    )
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)

    records = [
        {"kind": "run_start", "name": "t", "variant": "v3", "arch": "vit_s",
         "batch_size": 256, "n_chips": 8, "n_procs": 1, "sharding": "fsdp"},
        {"kind": "event", "event": "sharding", "mode": "fsdp",
         "mesh_shape": {"data": 1, "fsdp": 8},
         "param_bytes_per_device": 4 * 2**20,
         "opt_bytes_per_device": 8 * 2**20,
         "state_bytes_per_device": 12 * 2**20},
    ]
    for s in range(1, 5):
        records.append({"kind": "step", "step": s, "step_s": 0.1,
                        "data_s": 0.01, "host_s": 0.005, "mfu": 0.3})
    summary = report.summarize(records)
    assert summary["sharding"]["mode"] == "fsdp"
    assert summary["sharding"]["param_bytes_per_device"] == 4 * 2**20
    text = report.render(summary)
    assert "sharding: fsdp" in text
    assert "params 4.00 MiB/device" in text
    assert "MFU [fsdp]:" in text
    # sharding is a routine event, not an incident (the grad_sync rule)
    assert summary["incidents_total"] == 0


def test_mfu_estimator_carries_sharding_mode():
    from moco_tpu.telemetry.mfu import MFUEstimator

    est = MFUEstimator.for_config(tiny_config(sharding="fsdp"), 8, "v5e")
    assert est.sharding == "fsdp"
    est_dp = MFUEstimator.for_config(tiny_config(), 8, "v5e")
    assert est_dp.sharding == "dp"
    # the analytic FLOPs basis is layout-invariant
    assert est.flops_per_step == est_dp.flops_per_step


# ---------------------------------------------------------------------------
# driver: fsdp through train(), elastic resize drill with sharding=fsdp
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fsdp_through_driver_and_resume(mesh8, tmp_path):
    """End-to-end: a short fsdp driver run lands the `sharding` telemetry
    event + sidecar stamp, and `--resume auto` restores into the sharded
    placement (dialect 3) bit-faithfully."""
    import json

    from moco_tpu.config import get_preset
    from moco_tpu.train import train

    tel = str(tmp_path / "tel")
    os.makedirs(tel, exist_ok=True)
    cfg = get_preset("imagenet-moco-v3-vits").replace(
        arch="vit_tiny", compute_dtype="float32", image_size=32,
        batch_size=16, embed_dim=16, dataset="synthetic", warmup_epochs=0,
        lr=1e-3, base_lr=0.0, epochs=2, steps_per_epoch=3, sharding="fsdp",
        knn_monitor=False, ckpt_dir=str(tmp_path / "ckpt"), print_freq=2,
        telemetry_dir=tel, telemetry_stride=2, telemetry_flush_steps=2,
    )
    state_a, _ = train(cfg.replace(ckpt_dir=""), mesh8)       # 6 straight
    state_mid, _ = train(cfg, mesh8, max_steps=3)             # 3 + save
    assert int(state_mid.step) == 3
    state_b, _ = train(cfg.replace(resume="auto"), mesh8)     # resume to 6
    assert int(state_a.step) == int(state_b.step) == 6
    for a, b in zip(jax.tree.leaves(state_a.params_q),
                    jax.tree.leaves(state_b.params_q), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    events = [json.loads(line) for line in
              open(os.path.join(tel, "events.jsonl"))]
    sh = [e for e in events if e.get("event") == "sharding"]
    assert sh and sh[0]["mode"] == "fsdp"
    assert sh[0]["param_bytes_per_device"] > 0
    gs = [e for e in events if e.get("event") == "grad_sync"]
    assert gs and gs[0]["sharding"] == "fsdp"
    from moco_tpu.checkpoint import read_recorded_sharding

    assert read_recorded_sharding(cfg.ckpt_dir, 3) == "fsdp"


def _fsdp_drill_argv(tdir, ckpt_dir, devices):
    import sys

    return [
        sys.executable, "-m", "moco_tpu.train",
        "--preset", "imagenet-moco-v3-vits", "--fake-devices", str(devices),
        "--arch", "vit_tiny", "--dataset", "synthetic",
        "--compute-dtype", "float32", "--image-size", "32",
        "--batch-size", "16", "--embed-dim", "16", "--lr", "1e-3",
        "--base-lr", "0", "--warmup-epochs", "0",
        "--epochs", "4", "--steps-per-epoch", "4", "--print-freq", "1",
        "--knn-monitor", "false", "--watchdog-secs", "0",
        "--sharding", "fsdp", "--grad-sync", "quantized",
        "--telemetry-dir", str(tdir), "--telemetry-flush-steps", "4",
        "--heartbeat-secs", "0.05", "--ckpt-dir", str(ckpt_dir),
    ]


@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_resize_drill_4_to_2_with_fsdp(tmp_path):
    """The PR 11 resize drill under sharding=fsdp: a supervised 4-device
    fsdp run resizes to 2 devices mid-run (chaos `resize_at_step`) with
    zero manual steps — the relaunch restores the SHARDED state onto the
    new mesh through the dialect-3 tree restore, quantized EF restarts
    fresh-zero, and the final loss matches an uninterrupted 4-device run
    within the gradsync shim's bounded-divergence tolerance (the v3 step
    math is mesh-size-invariant at fixed global batch)."""
    import json
    import subprocess

    from moco_tpu.resilience.supervisor import (
        CLASS_CLEAN,
        CLASS_RESIZE,
        RestartPolicy,
        Supervisor,
        read_events_tail,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MOCO_TPU_NO_CACHE"] = "1"
    env.pop("MOCO_TPU_CACHE_DIR", None)
    env.pop("MOCO_TPU_CHAOS", None)
    env.pop("MOCO_TPU_CHAOS_STATE", None)

    def losses_of(events_path):
        out = {}
        for rec in read_events_tail(events_path, max_bytes=1 << 22):
            if rec.get("kind") == "step" and "loss" in rec:
                out[int(rec["step"])] = float(rec["loss"])
        return out

    # uninterrupted 4-device reference
    ref_t, ref_ckpt = tmp_path / "ref_t", tmp_path / "ref_ckpt"
    proc = subprocess.run(
        _fsdp_drill_argv(ref_t, ref_ckpt, 4), env=env,
        capture_output=True, text=True, timeout=900, cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    ref_losses = losses_of(os.path.join(str(ref_t), "events.jsonl"))
    assert 16 in ref_losses

    sup_t, sup_ckpt = tmp_path / "sup_t", tmp_path / "sup_ckpt"
    sup_t.mkdir()
    chaos_env = dict(env, MOCO_TPU_CHAOS="resize_at_step=5,devices=2",
                     MOCO_TPU_CHAOS_STATE=str(tmp_path / "chaos_state"))
    sup = Supervisor(
        _fsdp_drill_argv(sup_t, sup_ckpt, 4),
        telemetry_dir=str(sup_t), ckpt_dir=str(sup_ckpt), env=chaos_env,
        policy=RestartPolicy(
            max_restarts=3, heartbeat_stale_secs=60.0,
            startup_grace_secs=600.0, term_grace_secs=3.0,
            backoff_base_secs=0.1, backoff_max_secs=1.0, poll_secs=0.25,
        ),
        seed=0,
    )
    result = sup.run()
    assert result.final_class == CLASS_CLEAN, result
    assert result.classifications == [CLASS_RESIZE, CLASS_CLEAN], result
    relaunches = [r for r in sup.incidents if r["event"] == "resize_relaunch"]
    assert [(r["devices_from"], r["devices_to"]) for r in relaunches] == \
        [(4, 2)]
    events_path = os.path.join(str(sup_t), "events.jsonl")
    records = read_events_tail(events_path, max_bytes=1 << 22)
    # the EF state restarted fresh-zero at the mesh hop
    dialect = [r for r in records if r.get("kind") == "event"
               and r.get("event") == "ckpt-dialect"]
    assert dialect, "no ckpt-dialect event at the mesh hop"
    sup_losses = losses_of(events_path)
    assert 16 in sup_losses, sorted(sup_losses)
    # pre-resize leg: same program, same data — bitwise
    for step in range(1, 5):
        assert sup_losses[step] == ref_losses[step], step
    final_ref, final_sup = ref_losses[16], sup_losses[16]
    assert abs(final_sup - final_ref) <= 0.05 * max(abs(final_ref), 1.0), (
        f"final loss diverged past the shim tolerance: "
        f"ref={final_ref} resized={final_sup}"
    )
    # the resized leg really ran fsdp on the 2-device mesh
    sh_events = [r for r in records if r.get("event") == "sharding"]
    assert sh_events[-1]["mode"] == "fsdp"
    assert sh_events[-1]["mesh_shape"] == {"data": 1, "fsdp": 2}
    with open(os.path.join(str(sup_t), "heartbeat.json")) as f:
        assert json.load(f)["phase"] == "run_end"


@pytest.mark.chaos
def test_driver_chaos_resize_with_fsdp(mesh8, tmp_path):
    """The PR 11 resize drill under sharding=fsdp: a chaos resize request
    mid-run writes the elastic checkpoint with the sharding stamp and
    exits through the resized path."""
    import json

    from moco_tpu.config import get_preset
    from moco_tpu.resilience.chaos import ChaosPlan, chaos_context
    from moco_tpu.resilience.resize import consume_resize_request
    from moco_tpu.train import train

    tdir = tmp_path / "telemetry"
    cfg = get_preset("imagenet-moco-v3-vits").replace(
        arch="vit_tiny", compute_dtype="float32", image_size=32,
        batch_size=16, embed_dim=16, dataset="synthetic", warmup_epochs=0,
        lr=1e-3, base_lr=0.0, epochs=3, steps_per_epoch=3, sharding="fsdp",
        knn_monitor=False, ckpt_dir=str(tmp_path / "ckpt"), print_freq=1000,
        telemetry_dir=str(tdir), heartbeat_secs=0.0,
    )
    with chaos_context(ChaosPlan(resize_at_step=4, resize_devices=2)):
        _state, metrics = train(cfg, mesh8)
    assert metrics.get("resized") is True
    from moco_tpu.checkpoint import read_recorded_sharding
    from moco_tpu.resilience.resize import read_recorded_devices

    assert read_recorded_devices(cfg.ckpt_dir) == (4, 8)
    assert read_recorded_sharding(cfg.ckpt_dir, 4) == "fsdp"
    req = consume_resize_request(str(tdir))
    assert req is not None and req.devices == 2
    with open(tdir / "heartbeat.json") as f:
        hb = json.load(f)
    assert hb["phase"] == "resize_exit" and hb["step"] == 4
