"""bench.py orchestrator logic (VERDICT r2 #1): retry env plumbing, JSON
extraction, degradation record, and the always-one-JSON-line guarantee —
unit-tested with a stubbed child so no backend (or 25-minute timeout) is
involved. The live paths are exercised against the real dead/alive backend
separately (BENCH artifacts)."""

import json
import sys
import unittest.mock as mock

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench


def _parse_only_line(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, out
    return json.loads(out[0])


def test_orchestrate_passes_through_first_success(capsys):
    ok = {"metric": "moco_v2_r50_pretrain_throughput_per_chip",
          "value": 2000.0, "unit": "imgs/sec/chip", "vs_baseline": 11.9}
    with mock.patch.object(bench, "_run_child", return_value=(ok, None)) as rc:
        bench.orchestrate("step")
    rec = _parse_only_line(capsys)
    assert rec == ok  # no degraded_from on a clean first attempt
    (mode, timeout, env), _ = rc.call_args
    assert mode == "step" and "MOCO_TPU_DISABLE_FUSED" not in env


def test_orchestrate_retry_disables_fused_then_degrades(capsys):
    calls = []

    def fake(mode, timeout_s, env):
        calls.append(dict(env))
        if len(calls) < 3:
            return None, f"rc=1: boom{len(calls)}"
        return ({"metric": "moco_v2_tiny_cpu_proxy_throughput_per_chip",
                 "value": 350.0, "unit": "imgs/sec/chip",
                 "vs_baseline": 2.08}, None)

    with mock.patch.object(bench, "_run_child", side_effect=fake), \
         mock.patch.object(bench.time, "sleep"):
        bench.orchestrate("step")
    rec = _parse_only_line(capsys)
    assert rec["value"] == 350.0
    assert len(rec["degraded_from"]) == 2
    # attempt 2 rules out the Pallas path; attempt 3 forces CPU in-process
    assert "MOCO_TPU_DISABLE_FUSED" not in calls[0]
    assert calls[1].get("MOCO_TPU_DISABLE_FUSED") == "1"
    assert calls[2].get("MOCO_TPU_FORCE_CPU") == "1"


def test_orchestrate_total_failure_emits_error_record(capsys):
    with mock.patch.object(bench, "_run_child",
                           return_value=(None, "timeout after 900s")), \
         mock.patch.object(bench.time, "sleep"):
        bench.orchestrate("e2e")
    rec = _parse_only_line(capsys)
    assert rec["metric"] == "moco_v2_r50_e2e_input_fed_throughput_per_chip"
    assert rec["value"] == 0.0 and "error" in rec


def test_run_child_extracts_last_json_line(tmp_path):
    """The child may print progress lines; only the LAST metric-bearing JSON
    line counts."""
    proc = mock.Mock(returncode=0, stderr="", stdout=(
        "warming up\n"
        '{"not_a_metric": 1}\n'
        '{"metric": "m", "value": 1.0}\n'
        "trailing noise\n"
    ))
    with mock.patch.object(bench.subprocess, "run", return_value=proc):
        parsed, err = bench._run_child("step", 10.0, {})
    assert err is None and parsed["metric"] == "m"


def test_run_child_reports_rc_and_tail():
    proc = mock.Mock(returncode=1, stdout="", stderr="line1\nBOOM: died\n")
    with mock.patch.object(bench.subprocess, "run", return_value=proc):
        parsed, err = bench._run_child("step", 10.0, {})
    assert parsed is None and "rc=1" in err and "BOOM" in err
