"""bench.py orchestrator logic (VERDICT r2 #1, r3 #1/#6): cheap-first
ordering, provisional-then-upgrade printing, retry env plumbing, JSON
extraction, the hard total budget, and the SIGTERM flush — unit-tested with
a stubbed child so no backend (or multi-minute timeout) is involved. The
live paths are exercised against the real dead/alive backend separately
(BENCH artifacts)."""

import json
import sys
import unittest.mock as mock

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench


def _lines(capsys):
    return [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]


class FakeClock:
    """time.monotonic stub; _run_child stubs advance it by the timeout they
    were granted (simulating a child that burns its whole cap)."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _patch_clock(clock):
    return (mock.patch.object(bench.time, "monotonic", clock),
            mock.patch.object(bench.time, "sleep",
                              lambda s: setattr(clock, "t", clock.t + s)))


PROXY = {"metric": "moco_v2_tiny_cpu_proxy_throughput_per_chip",
         "value": 316.0, "unit": "imgs/sec/chip", "vs_baseline": 1.88}
TPU = {"metric": "moco_v2_r50_pretrain_throughput_per_chip",
       "value": 2000.0, "unit": "imgs/sec/chip", "vs_baseline": 11.9}
INPUT = {"metric": "host_staging_throughput", "value": 482.1,
         "unit": "imgs/sec", "vs_baseline": 0.36,
         "detail": {"native_s512_2t": 482.1},
         "cores_per_8x1650imgs_chip_host": 28.5}
E2E = {"metric": "moco_v2_r50_e2e_input_fed_throughput_per_chip",
       "value": 1500.0, "unit": "imgs/sec/chip", "vs_baseline": 8.9}
PROBE = {"metric": "tpu_liveness", "value": 1.0, "unit": "devices",
         "vs_baseline": 0.0, "platform": "tpu"}
SERVE = {"metric": "serve_tiny_cpu_embed_p95_latency_ms", "value": 159.3,
         "unit": "ms", "vs_baseline": 0.0,
         "detail": {"occupancy_mean": 0.57, "throughput_rps": 441.7}}


def _fake_child(clock, outcomes):
    """outcomes: {mode or (mode, 'pallas_off'): result|None}.
    Burns 45 s on success, the full granted timeout on failure/hang."""
    calls = []

    def fake(mode, timeout_s, env):
        env = env or {}
        calls.append((mode, timeout_s, dict(env)))
        key = (mode, "pallas_off") if env.get("MOCO_TPU_DISABLE_PALLAS") else mode
        forced_cpu = env.get("MOCO_TPU_FORCE_CPU")
        result = outcomes.get(key if key in outcomes else mode)
        if callable(result):
            result = result(forced_cpu)
        if result is None:
            clock.t += timeout_s
            return None, f"timeout after {timeout_s:.0f}s"
        clock.t += 45.0
        return dict(result), None

    return fake, calls


def test_tpu_up_prints_provisional_then_upgraded_line(capsys):
    clock = FakeClock()
    fake, calls = _fake_child(clock, {"step": lambda cpu: PROXY if cpu else TPU,
                                      "input": INPUT, "e2e": E2E,
                                      "probe": PROBE, "serve": SERVE})
    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("step")
    out = _lines(capsys)
    assert len(out) == 2  # provisional first, upgrade LAST (driver takes last)
    assert out[0]["metric"] == PROXY["metric"]
    assert out[-1]["metric"] == TPU["metric"] and out[-1]["value"] == 2000.0
    assert out[-1]["input"]["value"] == 482.1
    assert out[-1]["e2e"]["value"] == 1500.0
    # the serving trajectory row (ISSUE 5) folded in, always on CPU
    assert out[-1]["serve"]["value"] == SERVE["value"]
    serve_calls = [c for c in calls if c[0] == "serve"]
    assert len(serve_calls) == 1 and serve_calls[0][2].get("MOCO_TPU_FORCE_CPU")
    # cpu proxy ran FIRST; e2e ran on the TPU (no FORCE_CPU) since TPU worked
    assert calls[0][0] == "step" and calls[0][2].get("MOCO_TPU_FORCE_CPU")
    e2e_calls = [c for c in calls if c[0] == "e2e"]
    assert e2e_calls and not e2e_calls[-1][2].get("MOCO_TPU_FORCE_CPU")


def test_tpu_hang_keeps_proxy_and_stays_inside_budget(capsys):
    clock = FakeClock()
    t_start = clock.t
    fake, calls = _fake_child(
        clock, {"step": lambda cpu: PROXY if cpu else None,
                "input": INPUT, "e2e": lambda cpu: E2E if cpu else None,
                "probe": PROBE})
    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("step")
    out = _lines(capsys)
    assert out[-1]["metric"] == PROXY["metric"] and out[-1]["value"] == 316.0
    assert any("timeout" in e for e in out[-1]["degraded_from"])
    assert out[-1]["input"]["value"] == 482.1
    # THE budget property (VERDICT r3 weak #1): wall time consumed by all
    # children + sleeps stays under the hard cap even when the TPU hangs
    assert clock.t - t_start <= bench.BENCH_TOTAL_BUDGET_S
    # the hung step attempt rightfully consumed the live-chip budget; e2e
    # must neither run on the suspect relay nor eat into the flush margin
    assert not [c for c in calls
                if c[0] == "e2e" and not c[2].get("MOCO_TPU_FORCE_CPU")]
    assert any("e2e: skipped" in e for e in out[-1]["degraded_from"])


def test_dead_probe_skips_tpu_attempt_entirely(capsys):
    """A dead liveness probe means NO expensive TPU child runs (the r4
    design burned 330 s hanging the full attempt on every dead day); the
    freed budget funds the CPU e2e proxy instead."""
    clock = FakeClock()
    t_start = clock.t
    fake, calls = _fake_child(
        clock, {"step": lambda cpu: PROXY if cpu else None,
                "input": INPUT, "e2e": lambda cpu: E2E if cpu else None,
                "probe": None,  # probe hangs to its cap
                "serve": SERVE})
    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("step")
    out = _lines(capsys)
    # no step child ever ran without FORCE_CPU (c[0] is the child MODE —
    # orch.run names like "tpu"/"tpu-retry" never reach _run_child)
    assert not [c for c in calls
                if c[0] == "step" and not c[2].get("MOCO_TPU_FORCE_CPU")]
    assert any("liveness probe" in e for e in out[-1]["degraded_from"])
    assert out[-1]["e2e"]["value"] == E2E["value"]
    assert out[-1]["serve"]["value"] == SERVE["value"]
    # dead day completes fast: proxy + input + probe cap + e2e + serve
    assert clock.t - t_start <= 45 + 45 + bench.TPU_PROBE_CAP_S + 45 + 45 + 1


def test_live_probe_gives_step_the_remaining_budget(capsys):
    """The success path's cap (VERDICT r4 weak #1): with a live probe the
    step child gets remaining-minus-flush-margin, not a fixed 330 s."""
    clock = FakeClock()
    fake, calls = _fake_child(clock, {"step": lambda cpu: PROXY if cpu else TPU,
                                      "input": INPUT, "e2e": E2E,
                                      "probe": PROBE, "serve": SERVE})
    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("step")
    tpu_calls = [c for c in calls
                 if c[0] == "step" and not c[2].get("MOCO_TPU_FORCE_CPU")]
    assert len(tpu_calls) == 1
    # proxy 45 + input 45 + probe 45 burned; 465 left; minus 25 flush margin
    assert tpu_calls[0][1] == 600.0 - 3 * 45.0 - bench.FLUSH_MARGIN_S


def test_plan_tpu_attempt_cap_arithmetic():
    # dead probe → skip, whatever the budget
    cap, why = bench.plan_tpu_attempt(500.0, 0.0)
    assert cap == 0.0 and "probe" in why
    # live but too thin → skip
    cap, why = bench.plan_tpu_attempt(
        bench.MIN_TPU_ATTEMPT_S + bench.FLUSH_MARGIN_S - 1.0, 1.0)
    assert cap == 0.0 and "thin" in why
    # live and fat → everything minus the flush margin
    cap, why = bench.plan_tpu_attempt(465.0, 1.0)
    assert cap == 465.0 - bench.FLUSH_MARGIN_S and why == "live"


def test_fast_tpu_failure_retries_with_pallas_disabled(capsys):
    clock = FakeClock()

    def fake(mode, timeout_s, env):
        env = env or {}
        if env.get("MOCO_TPU_FORCE_CPU"):
            clock.t += 45.0
            return dict(PROXY) if mode != "input" else dict(INPUT), None
        if mode == "probe":
            clock.t += 20.0
            return dict(PROBE), None
        if env.get("MOCO_TPU_DISABLE_PALLAS"):
            clock.t += 60.0
            return dict(TPU), None
        clock.t += 30.0  # fast rc=1 (Mosaic compile error shape)
        return None, "rc=1: Mosaic lowering failed"

    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("step")
    out = _lines(capsys)
    assert out[-1]["value"] == 2000.0
    assert any("Mosaic" in e for e in out[-1]["degraded_from"])


def test_everything_fails_emits_error_record(capsys):
    clock = FakeClock()
    p1, p2 = _patch_clock(clock)

    def fake(mode, timeout_s, env):
        clock.t += min(timeout_s, 30.0)
        return None, "rc=1: boom"

    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("e2e")
    out = _lines(capsys)
    assert len(out) == 1
    rec = out[0]
    assert rec["metric"] == "moco_v2_r50_e2e_input_fed_throughput_per_chip"
    assert rec["value"] == 0.0 and rec["degraded_from"]


def test_input_mode_single_cpu_child(capsys):
    clock = FakeClock()
    fake, calls = _fake_child(clock, {"input": INPUT})
    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake):
        bench.orchestrate("input")
    out = _lines(capsys)
    assert len(out) == 1 and out[0]["value"] == 482.1
    assert len(calls) == 1 and calls[0][2].get("MOCO_TPU_FORCE_CPU")


def test_sigterm_flushes_best_so_far(capsys):
    """The handler must emit the provisional record + evidence trail."""
    import signal

    clock = FakeClock()
    handler = {}

    def fake_signal(sig, fn):
        handler[sig] = fn

    def fake(mode, timeout_s, env):
        if (env or {}).get("MOCO_TPU_FORCE_CPU") and mode == "step":
            clock.t += 45.0
            return dict(PROXY), None
        # simulate the driver SIGTERMing us mid-TPU-attempt
        with mock.patch.object(bench.os, "_exit", side_effect=SystemExit):
            try:
                handler[signal.SIGTERM](signal.SIGTERM, None)
            except SystemExit:
                pass
        raise KeyboardInterrupt  # stop the orchestration like a real kill

    p1, p2 = _patch_clock(clock)
    with p1, p2, mock.patch.object(bench, "_run_child", side_effect=fake), \
         mock.patch.object(bench.signal, "signal", fake_signal):
        try:
            bench.orchestrate("step")
        except KeyboardInterrupt:
            pass
    out = _lines(capsys)
    # provisional line + the SIGTERM flush, both carrying the proxy number
    assert out[0]["value"] == 316.0
    assert out[-1]["value"] == 316.0
    assert any("signal" in e for e in out[-1]["degraded_from"])


def test_budget_exhaustion_skips_children():
    clock = FakeClock()
    with mock.patch.object(bench.time, "monotonic", clock):
        orch = bench._Orchestrator("step", 0.0)
        result = orch.run("tpu", "step", 100.0, {})
    assert result is None and "budget exhausted" in orch.errors[0]


def test_run_child_extracts_last_json_line(tmp_path):
    """The child may print progress lines; only the LAST metric-bearing JSON
    line counts."""
    proc = mock.Mock(returncode=0, stderr="", stdout=(
        "warming up\n"
        '{"not_a_metric": 1}\n'
        '{"metric": "m", "value": 1.0}\n'
        "trailing noise\n"
    ))
    with mock.patch.object(bench.subprocess, "run", return_value=proc):
        parsed, err = bench._run_child("step", 10.0, {})
    assert err is None and parsed["metric"] == "m"


def test_run_child_reports_rc_and_tail():
    proc = mock.Mock(returncode=1, stdout="", stderr="line1\nBOOM: died\n")
    with mock.patch.object(bench.subprocess, "run", return_value=proc):
        parsed, err = bench._run_child("step", 10.0, {})
    assert parsed is None and "rc=1" in err and "BOOM" in err


def test_probe_child_reports_no_tpu_on_cpu(capsys):
    """The real probe child under the test backend (8 fake CPU devices):
    metric shape is what the orchestrator keys on, and a CPU-only backend
    must report value 0.0 (dead) so plan_tpu_attempt skips the attempt."""
    bench.bench_probe()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "tpu_liveness" and rec["value"] == 0.0
    assert rec["platform"] == "cpu" and rec["unit"] == "devices"
