"""tools/mocolint in tier-1: the pluggable analysis engine (ISSUE 7).

Covers: per-rule positive+negative fixtures for the new rules R8-R11,
suppression + unused-suppression reporting, baseline round-trip, the
--json schema, and the repo gate — `python -m tools.mocolint moco_tpu
tools bench.py` must be CLEAN (zero unsuppressed findings) and fast
(single parse per file; the whole-repo budget is 5 s).

R1-R7 behavior parity is pinned by tests/test_lint_robustness.py, which
runs unmodified against the legacy shim.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.mocolint import baseline as baseline_mod  # noqa: E402
from tools.mocolint.config import DEFAULT_CONFIG  # noqa: E402
from tools.mocolint.engine import Engine, module_name_for  # noqa: E402


def run_on(tmp_path, rel, body, select=None):
    """Write `body` at tmp_path/rel and run the default config on it."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(body)
    return Engine(DEFAULT_CONFIG, select=select).run([str(path)]).findings


def rules_of(findings):
    return [f.rule for f in findings]


# -- R7: the fsdp extension (ISSUE 15) --------------------------------------


def test_r7_flags_param_gather_scatter_outside_parallel(tmp_path):
    """ISSUE 15: inline all_gather/psum_scatter on param-named operands
    outside parallel/ bypasses the ShardingPlan's per-leaf bookkeeping;
    gathers on non-param values (keys, batches) stay legal."""
    findings = run_on(
        tmp_path, "moco_tpu/stepish.py",
        "from jax import lax\n"
        "def region(params_q, k2, grads):\n"
        "    full = lax.all_gather(params_q, 'fsdp')\n"      # violation
        "    shard = lax.psum_scatter(grads, 'fsdp')\n"      # violation
        "    keys = lax.all_gather(k2, 'data')\n"            # legal
        "    return full, shard, keys\n",
        select=("R7",),
    )
    assert rules_of(findings) == ["R7", "R7"]
    assert any("ShardingPlan" in f.message for f in findings)
    assert any("gradsync API" in f.message for f in findings)


def test_r7_allows_param_gather_under_parallel(tmp_path):
    findings = run_on(
        tmp_path, "moco_tpu/parallel/fsdpish.py",
        "from jax import lax\n"
        "def gather(params):\n"
        "    return lax.all_gather(params, 'fsdp')\n",
        select=("R7",),
    )
    assert findings == []


# -- R8: host syncs in traced step code -------------------------------------

R8_POSITIVE = """\
import jax
import numpy as np

def build_step(tx):
    def train_step(state, batch):
        loss = compute(state, batch)
        metrics = {"loss": loss.item()}          # sync
        arr = np.asarray(loss)                   # host materialization
        scale = float(loss)                      # scalar coercion
        jax.block_until_ready(loss)              # fence
        if batch.shape[0] > 4:                   # shape branch
            loss = loss * 2
        return state, metrics
    return jax.jit(train_step, donate_argnums=(0,))
"""


def test_r8_flags_host_syncs_inside_traced_functions(tmp_path):
    found = run_on(tmp_path, "moco_tpu/train_step.py", R8_POSITIVE,
                   select=("R8",))
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 5, msgs
    assert ".item()" in msgs and "np.asarray" in msgs
    assert "`float(...)`" in msgs and "block_until_ready" in msgs
    assert "branch on `.shape`" in msgs


def test_r8_ignores_host_code_outside_traced_functions(tmp_path):
    # the SAME calls in build-time (host) code are legal: R8 is scoped to
    # traced bodies, not to the module
    body = """\
import jax
import numpy as np

def build_step(cfg, arrs):
    dim = int(np.asarray(arrs[0]).shape[-1])     # host setup: fine
    jax.block_until_ready(arrs)                  # host setup: fine
    def train_step(state, batch):
        return state
    return jax.jit(train_step)
"""
    assert run_on(tmp_path, "moco_tpu/train_step.py", body,
                  select=("R8",)) == []


def test_r8_sees_through_shard_map_and_nesting(tmp_path):
    body = """\
from moco_tpu.utils.compat import shard_map

def build(mesh):
    def region(x):
        def inner(y):
            return y.item()                      # nested: still traced
        return inner(x)
    return shard_map(region, mesh=mesh, in_specs=None, out_specs=None)
"""
    found = run_on(tmp_path, "moco_tpu/v3_step.py", body, select=("R8",))
    assert len(found) == 1 and ".item()" in found[0].message


def test_r8_scoped_to_step_builder_modules(tmp_path):
    # a traced .item() in a NON-step-builder module is not R8's business
    assert run_on(tmp_path, "moco_tpu/evals/lincls.py", R8_POSITIVE,
                  select=("R8",)) == []


def test_r8_clean_on_real_step_builders():
    for rel in ("moco_tpu/train_step.py", "moco_tpu/v3_step.py",
                "moco_tpu/serve/engine.py"):
        found = Engine(DEFAULT_CONFIG, select=("R8",)).run(
            [os.path.join(REPO, rel)]).findings
        assert found == [], [f.human() for f in found]


# -- R9: Python-side nondeterminism -----------------------------------------

def test_r9_flags_global_rng_and_wall_clock(tmp_path):
    body = """\
import random
import time
import numpy as np

def pick(xs):
    k = random.choice(xs)                        # global RNG
    jitter = np.random.rand()                    # numpy global RNG
    stamp = time.time()                          # wall clock as a value
    return k, jitter, stamp
"""
    found = run_on(tmp_path, "moco_tpu/data/augment.py", body,
                   select=("R9",))
    msgs = " | ".join(f.message for f in found)
    assert len(found) == 3, msgs
    assert "random.choice" in msgs and "np.random.rand" in msgs
    assert "time.time()" in msgs


def test_r9_allows_seeded_generators_and_perf_counter(tmp_path):
    body = """\
import time
import numpy as np

def shuffle(n, seed, epoch):
    rng = np.random.RandomState(seed * 100003 + epoch)
    g = np.random.default_rng(seed)
    t0 = time.perf_counter()                     # telemetry: fine
    return rng.permutation(n), g, time.perf_counter() - t0
"""
    assert run_on(tmp_path, "moco_tpu/data/loader.py", body,
                  select=("R9",)) == []


def test_r9_keyword_seed_counts_as_seeded(tmp_path):
    body = """\
import numpy as np

def make(seed):
    return np.random.default_rng(seed=seed), np.random.RandomState(seed=seed)
"""
    assert run_on(tmp_path, "moco_tpu/data/loader.py", body,
                  select=("R9",)) == []


def test_r9_flags_set_iteration(tmp_path):
    body = """\
def order(tags):
    out = []
    for t in set(tags):                          # hash-order iteration
        out.append(t)
    return out, [x for x in {1, 2, 3}]           # set-literal comprehension
"""
    found = run_on(tmp_path, "moco_tpu/ops/queue.py", body, select=("R9",))
    assert len(found) == 2
    assert all("iteration over a set" in f.message for f in found)


def test_r9_scoped_to_bit_identity_modules(tmp_path):
    # the supervisor's restart jitter legitimately uses random: out of scope
    body = "import random\ndelay = random.uniform(0, 1)\n"
    assert run_on(tmp_path, "moco_tpu/resilience/supervisor.py", body,
                  select=("R9",)) == []


# -- R10: thread-safety audit ------------------------------------------------

R10_RACY = """\
import threading

class Racy:
    def __init__(self):
        self.count = 0                           # init: before the thread
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        while True:
            self.count += 1                      # worker write, no lock

    def reset(self):
        self.count = 0                           # public write, no lock
"""


def test_r10_flags_unlocked_shared_writes(tmp_path):
    found = run_on(tmp_path, "mod.py", R10_RACY, select=("R10",))
    assert len(found) == 2
    assert {"_work", "reset"} <= {
        m for f in found for m in ("_work", "reset") if m in f.message
    }


def test_r10_accepts_locked_writes_and_worker_only_state(tmp_path):
    body = """\
import threading

class Locked:
    def __init__(self):
        self.count = 0
        self.progress = 0
        self._cond = threading.Condition()
        self._t = threading.Thread(target=self._work, daemon=True)
        self._t.start()

    def _work(self):
        with self._cond:
            self.count += 1                      # locked: fine
        self.progress += 1                       # worker-ONLY attr: fine

    def reset(self):
        with self._cond:
            self.count = 0                       # locked: fine
"""
    assert run_on(tmp_path, "mod.py", body, select=("R10",)) == []


def test_r10_tracks_worker_reachability_through_helpers(tmp_path):
    body = """\
import threading

class Indirect:
    def __init__(self):
        self.n = 0
        self._lock = threading.Lock()
        threading.Thread(target=self._loop).start()

    def _loop(self):
        self._step()                             # helper reached from worker

    def _step(self):
        self.n += 1                              # effectively a worker write

    def reset(self):
        self.n = 0
"""
    found = run_on(tmp_path, "mod.py", body, select=("R10",))
    assert len(found) == 2, [f.message for f in found]


def test_r10_ignores_classes_without_threads(tmp_path):
    body = """\
class Plain:
    def a(self):
        self.x = 1

    def b(self):
        self.x = 2
"""
    assert run_on(tmp_path, "mod.py", body, select=("R10",)) == []


def test_r10_clean_on_real_threaded_classes():
    for rel in ("moco_tpu/serve/batcher.py", "moco_tpu/data/loader.py",
                "moco_tpu/resilience/watchdog.py", "moco_tpu/serve/http.py"):
        found = Engine(DEFAULT_CONFIG, select=("R10",)).run(
            [os.path.join(REPO, rel)]).findings
        assert found == [], [f.human() for f in found]


# -- R11: import boundaries --------------------------------------------------

def test_r11_transitive_serve_chain(tmp_path):
    (tmp_path / "moco_tpu" / "serve").mkdir(parents=True)
    (tmp_path / "moco_tpu" / "__init__.py").write_text("")
    (tmp_path / "moco_tpu" / "serve" / "__init__.py").write_text("")
    (tmp_path / "moco_tpu" / "helper.py").write_text("import optax\n")
    (tmp_path / "moco_tpu" / "serve" / "svc.py").write_text(
        "from moco_tpu.helper import thing\n"
    )
    found = Engine(DEFAULT_CONFIG, select=("R11",)).run(
        [str(tmp_path / "moco_tpu")]).findings
    assert len(found) == 1
    assert "import chain reaches 'optax'" in found[0].message
    assert found[0].path.endswith("svc.py")


def test_r11_stdlib_only_supervisor(tmp_path):
    found = run_on(tmp_path, "moco_tpu/resilience/supervisor.py",
                   "import os\nimport numpy as np\n", select=("R11",))
    assert len(found) == 1
    assert "stdlib-only" in found[0].message and "numpy" in found[0].message


def test_r11_stdlib_only_transitive_through_package(tmp_path):
    (tmp_path / "moco_tpu" / "resilience").mkdir(parents=True)
    (tmp_path / "moco_tpu" / "__init__.py").write_text("")
    (tmp_path / "moco_tpu" / "resilience" / "__init__.py").write_text("")
    (tmp_path / "moco_tpu" / "heavy.py").write_text("import jax\n")
    (tmp_path / "moco_tpu" / "resilience" / "supervisor.py").write_text(
        "from moco_tpu.heavy import thing\n"
    )
    found = Engine(DEFAULT_CONFIG, select=("R11",)).run(
        [str(tmp_path / "moco_tpu")]).findings
    assert len(found) == 1
    assert "non-stdlib 'jax'" in found[0].message


def test_r11_orbax_must_stay_lazy(tmp_path):
    body = """\
import orbax.checkpoint as ocp                   # module level: flagged

def save(tree):
    import orbax.checkpoint as lazy_ocp          # lazy: fine
    return lazy_ocp, ocp
"""
    found = run_on(tmp_path, "moco_tpu/checkpoint.py", body,
                   select=("R11",))
    assert len(found) == 1 and found[0].line == 1
    assert "imported lazily" in found[0].message


def test_r11_type_checking_imports_are_exempt(tmp_path):
    body = """\
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import orbax.checkpoint as ocp               # annotations only: fine
"""
    assert run_on(tmp_path, "moco_tpu/checkpoint.py", body,
                  select=("R11",)) == []


def test_r11_clean_on_real_boundary_files():
    paths = [os.path.join(REPO, p) for p in
             ("moco_tpu", "tools/supervise.py")]
    found = Engine(DEFAULT_CONFIG, select=("R11",)).run(paths).findings
    assert found == [], [f.human() for f in found]


# -- suppression -------------------------------------------------------------

def test_suppression_trailing_and_standalone(tmp_path):
    body = """\
def f():
    try:
        pass
    except:  # mocolint: disable=R1 -- fixture exercises the syntax
        pass
    # mocolint: disable=R1
    try:
        pass
    except Exception:
        raise
"""
    # NB the second suppression covers line 7 (`try:`) where nothing
    # fires -> reported as unused
    result = Engine(DEFAULT_CONFIG, select=("R1",)).run(
        [_write(tmp_path, "mod.py", body)])
    assert rules_of(result.findings) == ["SUP"]
    assert len(result.suppressed) == 1


def test_suppression_is_rule_specific(tmp_path):
    body = """\
try:
    pass
except:  # mocolint: disable=R3 -- wrong id: does NOT cover R1
    pass
"""
    result = Engine(DEFAULT_CONFIG, select=("R1", "R3")).run(
        [_write(tmp_path, "mod.py", body)])
    assert rules_of(result.findings) == ["R1", "SUP"]


def test_select_subset_does_not_flag_other_rules_suppressions(tmp_path):
    """A valid R8 suppression must not read as 'unused' just because a
    --select run never gave R8 the chance to fire."""
    body = """\
import jax

def build():
    def step(x):
        return x.item()  # mocolint: disable=R8 -- fixture: deliberate
    return jax.jit(step)
"""
    path = _write(tmp_path, "moco_tpu/train_step.py", body)
    full = Engine(DEFAULT_CONFIG).run([path])
    assert full.findings == [] and len(full.suppressed) == 1
    subset = Engine(DEFAULT_CONFIG, select=("R1",)).run([path])
    assert subset.findings == []


def test_suppression_all_and_docstring_mentions_ignored(tmp_path):
    body = '''\
"""Docs quoting the syntax: # mocolint: disable=R1 — not a suppression."""
try:
    pass
except:  # mocolint: disable=all -- chaos fixture
    pass
'''
    result = Engine(DEFAULT_CONFIG, select=("R1",)).run(
        [_write(tmp_path, "mod.py", body)])
    assert result.findings == [] and len(result.suppressed) == 1


def _write(tmp_path, rel, body):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(body)
    return str(path)


# -- baseline ----------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    dirty = _write(tmp_path, "mod.py", "try:\n    x=1\nexcept:\n    pass\n")
    engine = Engine(DEFAULT_CONFIG, select=("R1",))
    first = engine.run([dirty])
    assert rules_of(first.findings) == ["R1"]
    bl = tmp_path / "baseline.json"
    baseline_mod.write(str(bl), first.findings)
    second = engine.run([dirty], baseline_path=str(bl))
    assert second.findings == [] and rules_of(second.baselined) == ["R1"]


def test_baseline_catches_new_occurrences(tmp_path):
    dirty = _write(tmp_path, "mod.py", "try:\n    x=1\nexcept:\n    pass\n")
    engine = Engine(DEFAULT_CONFIG, select=("R1",))
    bl = tmp_path / "baseline.json"
    baseline_mod.write(str(bl), engine.run([dirty]).findings)
    # a SECOND identical violation exceeds the grandfathered count
    (tmp_path / "mod.py").write_text(
        "try:\n    x=1\nexcept:\n    pass\n"
        "try:\n    y=2\nexcept:\n    pass\n"
    )
    result = engine.run([dirty], baseline_path=str(bl))
    assert rules_of(result.findings) == ["R1"]
    assert rules_of(result.baselined) == ["R1"]


def test_overlapping_paths_scan_each_file_once(tmp_path):
    """A dir plus a file inside it must not double findings — doubled
    occurrences would exceed their baseline budget."""
    dirty = _write(tmp_path, "pkg/mod.py",
                   "try:\n    x=1\nexcept:\n    pass\n")
    engine = Engine(DEFAULT_CONFIG, select=("R1",))
    result = engine.run([str(tmp_path / "pkg"), dirty, dirty])
    assert result.files_scanned == 1
    assert rules_of(result.findings) == ["R1"]
    bl = tmp_path / "baseline.json"
    baseline_mod.write(str(bl), result.findings)
    again = engine.run([str(tmp_path / "pkg"), dirty],
                       baseline_path=str(bl))
    assert again.findings == []


def test_baseline_survives_path_respelling(tmp_path, monkeypatch):
    """`moco_tpu` vs `./moco_tpu` vs absolute must fingerprint the same:
    a committed baseline can't depend on how the CI invocation spells
    the root."""
    monkeypatch.chdir(tmp_path)
    dirty = _write(tmp_path, "pkg/mod.py",
                   "try:\n    x=1\nexcept:\n    pass\n")
    engine = Engine(DEFAULT_CONFIG, select=("R1",))
    bl = tmp_path / "baseline.json"
    baseline_mod.write(str(bl), engine.run(["pkg"]).findings)
    for spelling in ("pkg", "./pkg", dirty, os.path.join(".", "pkg")):
        result = engine.run([spelling], baseline_path=str(bl))
        assert result.findings == [], (spelling,
                                       [f.human() for f in result.findings])


def test_committed_baseline_is_empty():
    """The repo carries NO grandfathered findings: the baseline file
    exists to exercise the mechanism, not to hide debt."""
    assert baseline_mod.load(
        os.path.join(REPO, "tools", "mocolint", "baseline.json")) == {}


# -- CLI: json schema + the tier-1 repo gate ---------------------------------

def _cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.mocolint", *args],
        capture_output=True, text=True, cwd=cwd,
    )


def test_cli_json_schema(tmp_path):
    dirty = _write(tmp_path, "mod.py", "try:\n    x=1\nexcept:\n    pass\n")
    proc = _cli(["--json", "--select", "R1", dirty])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == 1 and payload["tool"] == "mocolint"
    assert payload["files_scanned"] == 1
    (finding,) = payload["findings"]
    assert set(finding) == {"path", "line", "col", "rule", "severity",
                            "message"}
    assert finding["rule"] == "R1" and finding["line"] == 3


def test_cli_unknown_rule_is_usage_error():
    assert _cli(["--select", "R99", "moco_tpu"]).returncode == 2


@pytest.mark.parametrize("extra", [[], ["--baseline",
                                        "tools/mocolint/baseline.json"]])
def test_repo_gate_zero_unsuppressed_findings(extra):
    """THE tier-1 gate: the whole repo is clean under every rule, with
    and without the committed (empty) baseline, inside the ~5 s budget
    the single-parse engine promises."""
    t0 = time.monotonic()
    proc = _cli([*extra, "moco_tpu", "tools", "bench.py"])
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mocolint clean" in proc.stdout
    # generous CI headroom over the observed ~1.2 s; the contract is
    # "one parse per file", not a loaded-runner microbenchmark
    assert elapsed < 20.0, f"mocolint took {elapsed:.1f}s"


# -- incremental cache (ISSUE 9 satellite) ----------------------------------


R1_BODY = "try:\n    x = 1\nexcept:\n    pass\n"


def test_cache_warm_run_replays_findings_without_parsing(tmp_path):
    cache = str(tmp_path / "cache")
    a = _write(tmp_path, "tree/a.py", R1_BODY)
    _write(tmp_path, "tree/b.py", "x = 1\n")
    eng = Engine(DEFAULT_CONFIG)
    cold = eng.run([str(tmp_path / "tree")], cache_dir=cache)
    assert cold.files_cached == 0 and cold.files_scanned == 2
    warm = Engine(DEFAULT_CONFIG).run([str(tmp_path / "tree")],
                                      cache_dir=cache)
    assert warm.files_cached == 2
    assert [(f.path, f.line, f.rule, f.message) for f in warm.findings] == \
           [(f.path, f.line, f.rule, f.message) for f in cold.findings]
    assert any(f.rule == "R1" and f.path == a for f in warm.findings)


def test_cache_invalidates_only_the_edited_file(tmp_path):
    cache = str(tmp_path / "cache")
    _write(tmp_path, "tree/a.py", R1_BODY)
    b = _write(tmp_path, "tree/b.py", "x = 1\n")
    Engine(DEFAULT_CONFIG).run([str(tmp_path / "tree")], cache_dir=cache)
    _write(tmp_path, "tree/b.py", R1_BODY)  # b now violates R1 too
    warm = Engine(DEFAULT_CONFIG).run([str(tmp_path / "tree")],
                                      cache_dir=cache)
    assert warm.files_cached == 1  # a served from cache, b re-parsed
    assert sum(1 for f in warm.findings if f.rule == "R1") == 2
    assert any(f.path == b and f.rule == "R1" for f in warm.findings)


def test_cache_cross_file_chains_recompute_on_warm_runs(tmp_path):
    """The R11 transitive boundary walk must see a NEW violation in an
    UNCHANGED file: serve/a.py (cached) imports helper.py; when helper
    grows a module-level optax import, the chain finding lands in a.py
    on the warm run — proof that finalize() is never served from cache."""
    cache = str(tmp_path / "cache")
    a = _write(tmp_path, "moco_tpu/serve/a.py", "import moco_tpu.helper\n")
    _write(tmp_path, "moco_tpu/helper.py", "import os\n")
    cold = Engine(DEFAULT_CONFIG).run([str(tmp_path / "moco_tpu")],
                                      cache_dir=cache)
    assert not any(f.rule == "R11" for f in cold.findings)
    _write(tmp_path, "moco_tpu/helper.py", "import optax\n")
    warm = Engine(DEFAULT_CONFIG).run([str(tmp_path / "moco_tpu")],
                                      cache_dir=cache)
    assert warm.files_cached == 1  # a.py unchanged, helper re-parsed
    chains = [f for f in warm.findings if f.rule == "R11" and f.path == a]
    assert chains and "optax" in chains[0].message


def test_cache_keyed_on_rule_selection(tmp_path):
    """A --select subset must not poison the full-run cache: the engine
    fingerprint folds in the active rule set."""
    cache = str(tmp_path / "cache")
    _write(tmp_path, "tree/a.py", R1_BODY)
    r = Engine(DEFAULT_CONFIG, select=("R9",)).run(
        [str(tmp_path / "tree")], cache_dir=cache)
    assert r.files_cached == 0
    full = Engine(DEFAULT_CONFIG).run([str(tmp_path / "tree")],
                                      cache_dir=cache)
    assert full.files_cached == 0  # different fingerprint: cache miss
    assert any(f.rule == "R1" for f in full.findings)


def test_cache_cold_warm_timing(tmp_path):
    """The satellite's pin: the warm path must stay cheaper than the
    cold parse+walk as the tree grows (here: 60 files of real-ish code,
    warm run serves all of them from cache and beats the cold run)."""
    cache = str(tmp_path / "cache")
    body = "import os\n" + "\n".join(
        f"def f{i}(x):\n"
        f"    y = x + {i}\n"
        f"    for j in range(10):\n"
        f"        y += j * {i}\n"
        f"    return y\n"
        for i in range(40)
    )
    for n in range(60):
        _write(tmp_path, f"tree/m{n:02d}.py", body)
    t0 = time.monotonic()
    cold = Engine(DEFAULT_CONFIG).run([str(tmp_path / "tree")],
                                      cache_dir=cache)
    cold_s = time.monotonic() - t0
    t0 = time.monotonic()
    warm = Engine(DEFAULT_CONFIG).run([str(tmp_path / "tree")],
                                      cache_dir=cache)
    warm_s = time.monotonic() - t0
    assert cold.files_cached == 0 and warm.files_cached == 60
    assert warm_s < cold_s, (
        f"warm {warm_s:.3f}s not faster than cold {cold_s:.3f}s"
    )


def test_repo_gate_warm_cache(tmp_path):
    """The tier-1 gate with the cache: cold run populates, warm run
    serves every file and stays clean — the 'gate stays ~1 s as the tree
    grows' contract."""
    cache = str(tmp_path / "cache")
    cold = _cli(["--cache", cache, "moco_tpu", "tools", "bench.py"])
    assert cold.returncode == 0, cold.stdout + cold.stderr
    t0 = time.monotonic()
    warm = _cli(["--cache", cache, "moco_tpu", "tools", "bench.py"])
    elapsed = time.monotonic() - t0
    assert warm.returncode == 0, warm.stdout + warm.stderr
    assert "cached" in warm.stdout
    assert elapsed < 10.0, f"warm gate took {elapsed:.1f}s"


# -- R13: bank artifact writes are atomic (ISSUE 16) -------------------------


def test_r13_flags_in_place_artifact_writes(tmp_path):
    """A bare np.savez / json.dump / open-for-write inside the bank
    builder reintroduces the torn-artifact window the atomic helpers
    close — a crash mid-write leaves a promotable-looking file."""
    body = """\
import json
import numpy as np


def merge(path, feats, manifest):
    np.savez(path, features=feats)              # in place: flagged
    with open(path + ".json", "w") as f:        # in place: flagged
        json.dump(manifest, f)                  # in place: flagged
"""
    findings = run_on(tmp_path, "moco_tpu/serve/bankbuild.py", body,
                      select=("R13",))
    assert rules_of(findings) == ["R13", "R13", "R13"]
    assert "temp+rename" in findings[0].message


def test_r13_atomic_helpers_and_reads_are_exempt(tmp_path):
    """The atomic_* helpers ARE the temp+rename machinery (their inner
    writes are the point); reads, default-mode opens, and undotted
    calls never trip the rule."""
    body = """\
import json
import os

import numpy as np


def atomic_write_json(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:                   # inside the helper: fine
        json.dump(obj, f)
    os.replace(tmp, path)


def _atomic_save(path, arrays):
    np.savez(path + ".tmp", **arrays)           # inside the helper: fine
    os.replace(path + ".tmp", path)


def load(path):
    with open(path) as f:                       # a read: fine
        return json.load(f)


def dump(x):
    return x


def passthrough(x):
    return dump(x)                              # undotted call: fine
"""
    assert run_on(tmp_path, "moco_tpu/serve/bankbuild.py", body,
                  select=("R13",)) == []


def test_r13_scope_is_the_bank_builder_only(tmp_path):
    """R13 guards the bank artifacts, not every npz in the repo — a
    checkpoint writer outside the builder scope stays unflagged."""
    body = """\
import numpy as np


def save(path, arrays):
    np.savez(path, **arrays)
"""
    assert run_on(tmp_path, "moco_tpu/checkpoint.py", body,
                  select=("R13",)) == []


def test_bank_build_cli_is_train_free_boundary(tmp_path):
    """The R6 boundary pins tools/bank_build.py out of the train stack:
    a bank builder that imports the training loop would drag jax + the
    optimizer into the (lint-enforced jax-free) batch lane."""
    body = """\
from moco_tpu.train import train_loop
"""
    findings = run_on(tmp_path, "tools/bank_build.py", body,
                      select=("R6",))
    assert "R6" in rules_of(findings)


# -- ISSUE 20: the sharded-ANN lint surface ----------------------------------


def test_ann_module_is_jax_free_boundary(tmp_path):
    """The ann-jax-free boundary (R6): the IVF index builder runs inside
    bank_build's batch lane and inside serve replicas — a jax import
    there would drag the train runtime into both."""
    findings = run_on(tmp_path, "moco_tpu/serve/ann.py",
                      "import jax\n", select=("R6",))
    assert "R6" in rules_of(findings)


def test_ann_module_numpy_is_fine(tmp_path):
    # numpy IS the index's substrate; only jax/flax/train are banned
    body = """\
import json
import numpy as np


def centroids(x):
    return np.zeros((4, x.shape[1]), dtype=np.float32)
"""
    assert run_on(tmp_path, "moco_tpu/serve/ann.py", body,
                  select=("R6",)) == []


def test_r13_covers_ann_index_writes(tmp_path):
    """R13's scope now includes serve/ann.py: a bare np.savez of
    ann.npz reopens the torn-artifact window next to a good bank —
    index writes must go through the atomic_* helpers, manifest last."""
    body = """\
import numpy as np


def write_index(path, centroids):
    np.savez(path, centroids=centroids)          # in place: flagged


def atomic_save_npz(path, arrays):
    import os
    np.savez(path + ".tmp", **arrays)            # inside helper: fine
    os.replace(path + ".tmp", path)
"""
    findings = run_on(tmp_path, "moco_tpu/serve/ann.py", body,
                      select=("R13",))
    assert rules_of(findings) == ["R13"]
    assert findings[0].line == 5


def test_r9_covers_ann_kmeans_determinism(tmp_path):
    """ann.py is a bit-identity module (R9): an unseeded RNG in the
    k-means init would make the 1-shard and N-shard index builds
    diverge — the byte-identical artifact contract."""
    body = """\
import numpy as np


def init(x, k):
    return x[np.random.permutation(len(x))[:k]]  # global rng: flagged
"""
    findings = run_on(tmp_path, "moco_tpu/serve/ann.py", body,
                      select=("R9",))
    assert "R9" in rules_of(findings)


def test_fleet_router_cannot_import_the_ann_module(tmp_path):
    """The router merges fan-out candidates in pure python BECAUSE the
    fleet is stdlib-only (R11): reaching into serve/ann.py would pull
    numpy into the last process standing."""
    (tmp_path / "moco_tpu" / "serve").mkdir(parents=True)
    (tmp_path / "moco_tpu" / "__init__.py").write_text("")
    (tmp_path / "moco_tpu" / "serve" / "__init__.py").write_text("")
    (tmp_path / "moco_tpu" / "serve" / "ann.py").write_text(
        "import numpy as np\n"
    )
    (tmp_path / "moco_tpu" / "serve" / "fleet.py").write_text(
        "from moco_tpu.serve.ann import vote\n"
    )
    found = Engine(DEFAULT_CONFIG, select=("R11",)).run(
        [str(tmp_path / "moco_tpu")]).findings
    assert any(f.path.endswith("fleet.py") and "numpy" in f.message
               for f in found), [f.human() for f in found]
