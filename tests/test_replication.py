"""Multi-replica invariants (SURVEY §4 item 4): after N steps of the SPMD
program, the replicated state — queue, pointer, params — must be
BIT-IDENTICAL on every device (the property the reference gets from DDP
`broadcast_buffers` and we get from deterministic replicated arithmetic).
Also covers the opt-in SyncBN (cross-replica axis) path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.config import PretrainConfig
from moco_tpu.models.resnet import BasicBlock, ResNet
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step

GLOBAL_B, IMG, DIM, K = 16, 8, 16, 64


def _per_device_copies(arr):
    """All device shards of a (replicated) array as host arrays."""
    return [np.asarray(s.data) for s in arr.addressable_shards]


def test_state_identical_across_replicas_after_steps(mesh8):
    config = PretrainConfig(
        variant="v1", arch="resnet_tiny", cifar_stem=True, num_negatives=K,
        embed_dim=DIM, batch_size=GLOBAL_B, epochs=2, lr=0.1,
    )
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_train_state(
        jax.random.key(0), model, tx, (GLOBAL_B // 8, IMG, IMG, 3), K, DIM
    )
    step_fn = build_train_step(config, model, tx, mesh8, 8, sched)
    for i in range(3):
        im_q = jax.random.normal(jax.random.key(10 + i), (GLOBAL_B, IMG, IMG, 3))
        im_k = jax.random.normal(jax.random.key(20 + i), (GLOBAL_B, IMG, IMG, 3))
        state, _ = step_fn(state, im_q, im_k)
    for name, arr in [
        ("queue", state.queue),
        ("queue_ptr", state.queue_ptr),
        ("conv1", state.params_q["conv1"]["kernel"]),
        ("k_conv1", state.params_k["conv1"]["kernel"]),
        ("bn_mean", state.batch_stats_q["bn1"]["mean"]),
    ]:
        copies = _per_device_copies(arr)
        assert len(copies) == 8, f"{name} not present on all 8 devices"
        for c in copies[1:]:
            np.testing.assert_array_equal(copies[0], c, err_msg=name)


def test_sync_bn_step_runs(mesh8):
    """Opt-in cross-replica BN (SURVEY §2.11 SyncBN note for detection
    transfer): the BatchNorm axis_name must resolve inside the shard_map
    region and produce a finite step."""
    config = PretrainConfig(
        variant="v1", arch="resnet_tiny", cifar_stem=True, sync_bn=True,
        num_negatives=K, embed_dim=DIM, batch_size=GLOBAL_B, epochs=2, lr=0.1,
    )
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    state = create_train_state(
        jax.random.key(0), model, tx, (GLOBAL_B // 8, IMG, IMG, 3), K, DIM
    )
    step_fn = build_train_step(config, model, tx, mesh8, 8, sched)
    im_q = jax.random.normal(jax.random.key(1), (GLOBAL_B, IMG, IMG, 3))
    im_k = jax.random.normal(jax.random.key(2), (GLOBAL_B, IMG, IMG, 3))
    state, metrics = step_fn(state, im_q, im_k)
    assert np.isfinite(float(metrics["loss"]))
