"""ISSUE 3 input pipeline: parallel sharded staging, decode-once canvas
cache, overlapped H2D.

The load-bearing properties:
  - multi-worker staging is BIT-IDENTICAL to single-worker staging (the
    acceptance criterion: parallelism must never change the data);
  - cache-hit epochs are bit-identical to decoded epochs;
  - a transient read fault inside ONE staging worker retries that
    sub-slice without reordering or duplicating batches (chaos-marked);
  - `prefetch_depth` is honored end to end and validated at config build;
  - extent-trimmed H2D ships exactly the canvas prefix the extents cover.
"""

import os
import threading
import time

import numpy as np
import pytest

from moco_tpu.data.canvas_cache import CachedDataset
from moco_tpu.data.datasets import SyntheticDataset
from moco_tpu.data.loader import Prefetcher, epoch_loader, stage_eval_batch
from moco_tpu.data.stats import InputPipelineStats


def _collect(dataset, mesh, global_batch=16, epoch=0, **kw):
    loader = epoch_loader(dataset, epoch=epoch, seed=0,
                          global_batch=global_batch, mesh=mesh, **kw)
    try:
        return [tuple(np.asarray(a) for a in item) for item in loader]
    finally:
        loader.close_quietly()


def _assert_batches_equal(ref, got):
    assert len(ref) == len(got)
    for batch_ref, batch_got in zip(ref, got):
        for a, b in zip(batch_ref, batch_got):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# bit-identity: multi-worker vs single-worker
# ---------------------------------------------------------------------------


def test_multiworker_bit_identical_to_single(mesh8):
    ds = SyntheticDataset(num_samples=80, image_size=16, num_classes=4)
    ref = _collect(ds, mesh8)
    for workers in (2, 3, 5, 8):
        _assert_batches_equal(ref, _collect(ds, mesh8, workers=workers))


def test_multiworker_bit_identical_across_epochs_and_depth(mesh8):
    ds = SyntheticDataset(num_samples=96, image_size=16, num_classes=4)
    for epoch in (0, 1):
        ref = _collect(ds, mesh8, epoch=epoch)
        got = _collect(ds, mesh8, epoch=epoch, workers=4, depth=4)
        _assert_batches_equal(ref, got)


def test_multiworker_imagefolder_native_path(jpeg_tree_256, mesh8):
    """The zero-copy `get_batch_into` fan-out (native C++ decode straight
    into pooled canvas rows) must equal the single-call staging path."""
    from moco_tpu.data.datasets import ImageFolder

    ds = ImageFolder(jpeg_tree_256, stage_size=64)
    ref = _collect(ds, mesh8)
    got = _collect(ds, mesh8, workers=4)
    _assert_batches_equal(ref, got)


def test_multiworker_requires_three_tuple_protocol(mesh8):
    class TwoTuple:
        def __len__(self):
            return 64

        def get_batch(self, indices):
            return (np.zeros((len(indices), 8, 8, 3), np.uint8),
                    np.zeros((len(indices),), np.int32))

    loader = epoch_loader(TwoTuple(), epoch=0, seed=0, global_batch=16,
                          mesh=mesh8, workers=4)
    try:
        with pytest.raises(TypeError, match="3|protocol|extents"):
            list(loader)
    finally:
        loader.close_quietly()


# ---------------------------------------------------------------------------
# decode-once canvas cache
# ---------------------------------------------------------------------------


def test_cached_epoch_bit_identical_to_decoded(jpeg_tree_256, mesh8):
    from moco_tpu.data.datasets import ImageFolder

    ds = ImageFolder(jpeg_tree_256, stage_size=64)
    cached = CachedDataset(ds, cache_mb=128)
    decoded = _collect(ds, mesh8, workers=2)
    first_pass = _collect(cached, mesh8, workers=2)   # fills the cache
    assert cached.misses > 0
    hits_before = cached.hits
    second_pass = _collect(cached, mesh8, workers=2)  # served from cache
    assert cached.hits > hits_before
    _assert_batches_equal(decoded, first_pass)
    _assert_batches_equal(decoded, second_pass)


def test_cache_lru_respects_byte_budget():
    # 128 entries x (64*64*3 + 12) bytes ≈ 1.5 MiB > the 1 MiB budget
    ds = SyntheticDataset(num_samples=128, image_size=64, num_classes=4)
    per_entry = 64 * 64 * 3 + 3 * 4  # canvas + extents
    budget_mb = 1
    cached = CachedDataset(ds, cache_mb=budget_mb)
    cached.get_batch(np.arange(128))
    assert cached.cached_bytes <= budget_mb * 2**20
    max_entries = (budget_mb * 2**20) // per_entry
    assert 0 < cached.cached_entries <= max_entries < 128  # evicted some
    # LRU: the most recently inserted indices survived
    hits_before = cached.hits
    cached.get_batch(np.arange(128 - cached.cached_entries, 128))
    assert cached.hits == hits_before + cached.cached_entries


def test_cache_skips_batches_with_decode_failures():
    class Flaky:
        decode_failures = 0

        def __len__(self):
            return 16

        def get_batch(self, indices):
            self.decode_failures += 1  # every call "fails" one image
            n = len(indices)
            return (np.zeros((n, 8, 8, 3), np.uint8),
                    np.zeros((n,), np.int32),
                    np.tile(np.asarray([8, 8, 0], np.int32), (n, 1)))

    cached = CachedDataset(Flaky(), cache_mb=64)
    cached.get_batch(np.arange(8))
    assert cached.cached_entries == 0  # a transient blip is never frozen


def test_cache_delegates_dataset_attributes():
    ds = SyntheticDataset(num_samples=32, image_size=16, num_classes=4)
    cached = CachedDataset(ds, cache_mb=16)
    assert len(cached) == 32
    assert cached.num_classes == 4
    np.testing.assert_array_equal(cached.labels, ds.labels)


def test_cache_interacts_with_skip_batches(mesh8):
    """Resume fast-forward (`skip_batches`) over a cache-backed dataset:
    the skipped window is simply never requested, and the yielded batches
    equal the uncached loader's at the same positions."""
    ds = SyntheticDataset(num_samples=96, image_size=16, num_classes=4)
    cached = CachedDataset(ds, cache_mb=64)
    _collect(cached, mesh8, workers=2)  # epoch 0 fills the cache
    ref = _collect(ds, mesh8, skip_batches=2)
    got = _collect(cached, mesh8, workers=2, skip_batches=2)
    _assert_batches_equal(ref, got)


# ---------------------------------------------------------------------------
# chaos: transient fault inside one staging worker
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_worker_fault_retries_without_reorder_or_dup(jpeg_tree_256, mesh8):
    from moco_tpu.data.datasets import ImageFolder
    from moco_tpu.resilience.chaos import ChaosPlan, chaos_context

    ds = ImageFolder(jpeg_tree_256, stage_size=64)
    ref = _collect(ds, mesh8, workers=4)
    with chaos_context(ChaosPlan(loader_error_at_batch=1,
                                 loader_error_count=2)):
        got = _collect(ds, mesh8, workers=4, retries=3, backoff_secs=0.01)
    _assert_batches_equal(ref, got)


@pytest.mark.chaos
def test_worker_fault_exhausts_retries_and_surfaces(mesh8):
    from moco_tpu.resilience.chaos import ChaosPlan, chaos_context
    from moco_tpu.resilience.errors import TransientDataError

    ds = SyntheticDataset(num_samples=64, image_size=16, num_classes=4)
    loader = None
    with chaos_context(ChaosPlan(loader_error_at_batch=1,
                                 loader_error_count=10)):
        loader = epoch_loader(ds, epoch=0, seed=0, global_batch=16,
                              mesh=mesh8, workers=4, retries=2,
                              backoff_secs=0.01)
        try:
            with pytest.raises(TransientDataError):
                list(loader)
        finally:
            loader.close_quietly()


# ---------------------------------------------------------------------------
# prefetch depth + config validation
# ---------------------------------------------------------------------------


def test_prefetch_depth_honored(mesh8):
    ds = SyntheticDataset(num_samples=160, image_size=16, num_classes=4)
    loader = epoch_loader(ds, epoch=0, seed=0, global_batch=16, mesh=mesh8,
                          depth=3, workers=2)
    try:
        assert loader._q.maxsize == 3
        deadline = time.time() + 5.0
        while loader.qsize() < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert loader.qsize() == 3  # staged ahead up to depth, then blocked
    finally:
        loader.close_quietly()


def test_config_validates_pipeline_fields_at_build_time():
    from moco_tpu.config import EvalConfig, PretrainConfig

    with pytest.raises(ValueError, match="prefetch_depth"):
        PretrainConfig(prefetch_depth=0)
    with pytest.raises(ValueError, match="staging_workers"):
        PretrainConfig(staging_workers=0)
    with pytest.raises(ValueError, match="input_cache_mb"):
        PretrainConfig(input_cache_mb=-1)
    # replace() re-validates: the flag surface cannot smuggle a bad value
    good = PretrainConfig()
    with pytest.raises(ValueError, match="prefetch_depth"):
        good.replace(prefetch_depth=0)
    with pytest.raises(ValueError, match="prefetch_depth"):
        EvalConfig(prefetch_depth=0)


def test_driver_plumbs_prefetch_depth(monkeypatch, mesh8):
    """`epoch_loader` must receive config.prefetch_depth (the satellite:
    it used to hardcode the constructor default)."""
    import inspect

    from moco_tpu.data.loader import epoch_loader as real

    assert inspect.signature(real).parameters["depth"].default == 2
    seen = {}
    import moco_tpu.train as train_mod

    def spy(*args, **kw):
        seen["depth"] = kw.get("depth")
        seen["workers"] = kw.get("workers")
        return real(*args, **kw)

    monkeypatch.setattr(train_mod, "epoch_loader", spy)
    from moco_tpu.config import get_preset

    config = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=16,
        num_negatives=64, embed_dim=32, lr=0.1, epochs=1, steps_per_epoch=2,
        ckpt_dir="", knn_monitor=False, num_classes=10,
        prefetch_depth=4, staging_workers=3,
    )
    train_mod.train(config, mesh8, max_steps=2)
    assert seen == {"depth": 4, "workers": 3}


# ---------------------------------------------------------------------------
# overlapped H2D + trim
# ---------------------------------------------------------------------------


def test_iterated_batches_are_device_resident(mesh8):
    """The ready queue holds DEVICE arrays (H2D happened on the staging
    side), sharded over the data axis like before."""
    import jax

    ds = SyntheticDataset(num_samples=64, image_size=16, num_classes=4)
    loader = epoch_loader(ds, epoch=0, seed=0, global_batch=16, mesh=mesh8,
                          workers=4)
    try:
        imgs, labels, extents = next(iter(loader))
        assert isinstance(imgs, jax.Array)
        assert len(imgs.sharding.device_set) == 8
        assert isinstance(labels, jax.Array) and isinstance(extents, jax.Array)
    finally:
        loader.close_quietly()


def test_trim_h2d_ships_extent_prefix(jpeg_tree_256, mesh8):
    """Trimmed batches are exactly the untrimmed canvas prefix (rounded up
    to 64) with unchanged extents — content and crop semantics identical."""
    from moco_tpu.data.datasets import ImageFolder

    ds = ImageFolder(jpeg_tree_256, stage_size=128)
    ref = _collect(ds, mesh8)
    trimmed = _collect(ds, mesh8, workers=2, trim_h2d=True)
    assert len(ref) == len(trimmed)
    saw_trim = False
    for (imgs, labels, extents), (t_imgs, t_labels, t_extents) in zip(
        ref, trimmed
    ):
        th, tw = t_imgs.shape[1], t_imgs.shape[2]
        assert th % 64 == 0 or th == imgs.shape[1]
        assert tw % 64 == 0 or tw == imgs.shape[2]
        assert th >= extents[:, 0].max() and tw >= extents[:, 1].max()
        saw_trim |= (th, tw) != imgs.shape[1:3]
        np.testing.assert_array_equal(imgs[:, :th, :tw], t_imgs)
        np.testing.assert_array_equal(labels, t_labels)
        np.testing.assert_array_equal(extents, t_extents)
    assert saw_trim  # the 40-90 px tree underfills the 128x256 canvas


def test_trim_noop_for_full_extent_datasets(mesh8):
    ds = SyntheticDataset(num_samples=32, image_size=16, num_classes=4)
    ref = _collect(ds, mesh8)
    got = _collect(ds, mesh8, trim_h2d=True)
    _assert_batches_equal(ref, got)


# ---------------------------------------------------------------------------
# stats + eval staging
# ---------------------------------------------------------------------------


def test_input_stats_populated(mesh8):
    ds = SyntheticDataset(num_samples=64, image_size=16, num_classes=4)
    stats = InputPipelineStats()
    cached = CachedDataset(ds, cache_mb=16, stats=stats)
    _collect(cached, mesh8, workers=3, stats=stats)
    snap = stats.snapshot()
    assert snap["staged_batches"] == 4
    assert snap["workers"] == 3
    assert snap["staged_batch_s_p50"] > 0
    assert snap["staged_batch_s_p95"] >= snap["staged_batch_s_p50"]
    assert snap["queue_depth_mean"] >= 0
    assert 0 <= snap["worker_busy_frac"] <= 1
    assert snap["cache_misses"] > 0 and "cache_hit_rate" in snap


def test_stage_eval_batch_broadcast_padding():
    """Short batches pad with copies of the last row (broadcast-backed —
    no intermediate duplicate-image block) and the values are unchanged."""
    imgs = np.arange(3 * 4 * 4 * 3, dtype=np.uint8).reshape(3, 4, 4, 3)
    labels = np.asarray([5, 6, 7], np.int32)
    extents = np.asarray([[4, 4, 0]] * 3, np.int32)
    out_imgs, out_labels, out_extents = stage_eval_batch(
        (imgs, labels, extents), batch=8, pad_label=-1
    )
    out_imgs = np.asarray(out_imgs)
    assert out_imgs.shape == (8, 4, 4, 3)
    np.testing.assert_array_equal(out_imgs[:3], imgs)
    for row in range(3, 8):
        np.testing.assert_array_equal(out_imgs[row], imgs[-1])
    np.testing.assert_array_equal(out_labels, [5, 6, 7, -1, -1, -1, -1, -1])
    np.testing.assert_array_equal(
        np.asarray(out_extents)[3:], np.tile(extents[-1:], (5, 1))
    )


def test_close_joins_all_staging_threads(mesh8):
    before = threading.active_count()
    ds = SyntheticDataset(num_samples=160, image_size=16, num_classes=4)
    loader = epoch_loader(ds, epoch=0, seed=0, global_batch=16, mesh=mesh8,
                          workers=4, depth=2)
    try:
        next(iter(loader))
    finally:
        loader.close_quietly()
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def jpeg_tree_256(tmp_path_factory):
    PIL = pytest.importorskip("PIL")  # noqa: F841
    from PIL import Image

    root = tmp_path_factory.mktemp("pipe_imgs")
    rng = np.random.RandomState(7)
    for cls in ("a", "b"):
        d = root / cls
        d.mkdir()
        for i in range(24):
            h, w = rng.randint(40, 90), rng.randint(40, 90)
            img = rng.randint(0, 256, (h, w, 3)).astype(np.uint8)
            Image.fromarray(img).save(str(d / f"{i}.jpg"), quality=92)
    return str(root)
