#!/usr/bin/env python
"""Serve MoCo embeddings over HTTP (ISSUE 5).

    python tools/serve.py --pretrained runs/encoder.safetensors \
        --arch resnet50 --port 8080 --telemetry-dir runs/serve/telemetry

Loads the checkpoint's encoder through the shared surgery loader
(`checkpoint.load_for_inference` — both dialects), pre-compiles the
bucket ladder, and mounts the stdlib front end (moco_tpu/serve/http.py):
POST /v1/embed, POST /v1/knn (with --knn-bank), POST /admin/reload (hot
weight swap — the fleet supervisor's roll target, ISSUE 10),
GET /healthz, /stats.

SIGTERM/SIGINT drains gracefully — in-flight requests complete, new work
gets a structured 503 `draining` — via the resilience/preemption.py
handler (second signal: immediate exit, exactly like the train driver).

By default the process compiles into a PER-RUN XLA cache dir
(utils/cache.per_run_cache_dir): a served process lives under external
orchestrators that SIGKILL on eviction, and a kill mid-write must not
poison the shared compile cache (PR 4 finding). An explicit
MOCO_TPU_CACHE_DIR or MOCO_TPU_NO_CACHE=1 wins.

Exit codes (README table): 0 clean drain · 45 bad config/checkpoint ·
47 could not bind host:port (see resilience/exitcodes.py).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.config import ServeConfig, add_config_flags, collect_overrides  # noqa: E402
from moco_tpu.resilience.exitcodes import (  # noqa: E402
    EXIT_CONFIG_ERROR,
    EXIT_OK,
    EXIT_SERVE_BIND,
)
from moco_tpu.utils.logging import info, log_event  # noqa: E402


def build_service(config: ServeConfig):
    """Engine + service from a ServeConfig (shared with bench/tests)."""
    from moco_tpu.serve import EmbeddingEngine, EmbedService

    def engine_factory(path: str) -> "EmbeddingEngine":
        # hot reload (ISSUE 10): POST /admin/reload builds the new engine
        # through the SAME loader + config as the boot-time one, so a
        # reloaded replica is indistinguishable from a cold start on that
        # checkpoint (bit-identity test-pinned)
        return EmbeddingEngine.from_checkpoint(
            path,
            config.arch,
            image_size=config.image_size,
            cifar_stem=config.cifar_stem,
            buckets=config.buckets,
        )

    engine = engine_factory(config.pretrained)
    registry = None
    tracer = None
    if config.telemetry_dir:
        from moco_tpu.telemetry.registry import EVENTS_FILENAME, MetricsRegistry
        from moco_tpu.telemetry.trace import Tracer

        # span layer (ISSUE 8): serve spans (request/flush/engine) +
        # SIGUSR1 / trigger-file / shed-spike capture windows land in the
        # same telemetry dir; the registry stamps the tracer's run_id so
        # serve snapshots join the merged timeline
        tracer = Tracer(
            config.telemetry_dir, config.trace_mode, proc="serve",
            capture_steps=config.trace_capture_steps,
            capture_budget=config.trace_capture_budget,
        )
        registry = MetricsRegistry(
            os.path.join(config.telemetry_dir, EVENTS_FILENAME),
            stamp={"run_id": tracer.run_id, "trace_id": tracer.trace_id},
        )
    knn_bank = knn_labels = knn_bank_meta = None
    if config.knn_bank:
        from moco_tpu.serve.bankbuild import load_bank

        # versioned banks (ISSUE 16) come back with their manifest
        # metadata (checkpoint binding + probe rows) so the service can
        # dual-swap (engine, bank) pairs; a plain npz gets meta=None and
        # behaves exactly as before
        knn_bank, knn_labels, knn_bank_meta = load_bank(config.knn_bank)
    ann_shard = None
    if config.ann_cells:
        # sharded ANN (ISSUE 20): a verified paired index must sit next
        # to the versioned bank; a missing/torn index is a config error
        # (exit 45), never a silent fall-back to exact
        from moco_tpu.serve import ann as annmod

        loaded = annmod.load_ann(config.knn_bank)  # AnnIndexError -> 45
        if loaded is None:
            raise ValueError(
                f"--ann-cells {config.ann_cells} but bank "
                f"{config.knn_bank!r} has no ANN index manifest — build "
                "it with tools/bank_build.py --ann-cells"
            )
        arrays, _manifest = loaded
        ann_shard = annmod.AnnShard(
            knn_bank, knn_labels, arrays,
            shard=config.ann_shard, shards=config.ann_shards,
            nprobe=config.ann_nprobe,
            rerank=config.ann_rerank or config.knn_k,
            temperature=config.knn_temperature,
            num_classes=config.num_classes,
        )
    service = EmbedService(
        engine,
        flush_ms=config.flush_ms,
        max_queue=config.max_queue,
        request_deadline_ms=config.request_deadline_ms,
        cache_mb=config.embed_cache_mb,
        registry=registry,
        snapshot_every=config.snapshot_every,
        tracer=tracer,
        shed_spike_min=config.trace_shed_spike,
        knn_bank=knn_bank,
        knn_labels=knn_labels,
        num_classes=config.num_classes,
        knn_k=config.knn_k,
        knn_temperature=config.knn_temperature,
        reload_probe=config.reload_probe,
        reload_min_spread=config.reload_min_spread,
        knn_bank_meta=knn_bank_meta,
        bank_agreement_min=config.bank_agreement_min,
        ann=ann_shard,
        admission_tiers=config.admission_tiers,
        batch_max_queue=config.batch_max_queue,
        batch_deadline_ms=config.batch_deadline_ms,
    )
    service.set_engine_factory(engine_factory)
    return service, registry


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    add_config_flags(parser, ServeConfig)
    args = parser.parse_args(argv)
    try:
        config = ServeConfig().replace(**collect_overrides(args, ServeConfig))
        if not config.pretrained:
            raise ValueError("--pretrained <exported encoder> is required")
    except ValueError as e:
        info(f"config error: {e}")
        return EXIT_CONFIG_ERROR

    from moco_tpu.utils.cache import enable_persistent_cache, per_run_cache_dir

    if os.environ.get("MOCO_TPU_CACHE_DIR") or os.environ.get("MOCO_TPU_NO_CACHE"):
        enable_persistent_cache()  # explicit operator choice wins
    else:
        enable_persistent_cache(per_run_cache_dir(tag="serve"))

    try:
        service, registry = build_service(config)
    except (ValueError, OSError) as e:
        info(f"cannot build the service: {e}")
        return EXIT_CONFIG_ERROR

    from moco_tpu.serve import ServeFrontend

    try:
        frontend = ServeFrontend(service, config.host, config.port)
    except OSError as e:
        info(f"cannot bind {config.host}:{config.port}: {e}")
        return EXIT_SERVE_BIND

    from moco_tpu.resilience.preemption import PreemptionHandler

    if service.tracer is not None:
        service.tracer.install_signal()  # SIGUSR1 arms a capture window
    with PreemptionHandler() as pre:
        frontend.start()
        info(
            f"serving {config.arch} embeddings on {frontend.url} "
            f"(buckets {list(config.buckets)}, flush {config.flush_ms} ms, "
            f"queue {config.max_queue}, deadline "
            f"{config.request_deadline_ms:.0f} ms)"
        )
        while not pre.triggered:
            time.sleep(0.2)
    log_event(
        "serve",
        "signal received: draining — finishing in-flight batches, "
        "rejecting new work",
    )
    service.drain(config.drain_timeout_s)
    frontend.shutdown()
    if service.tracer is not None:
        service.tracer.close()
    if registry is not None:
        registry.close()
    info("drained cleanly")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
