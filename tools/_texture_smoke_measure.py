"""Derive the CI learning-detection thresholds (VERDICT r4 #5).

First r5 measurement (320 steps, resnet_tiny, 3 seeds): the trained-vs-
untrained VAL kNN delta at CI scale is NEGATIVE on every seed (-0.5 to
-5.7 pts) — the class-clustering dip phase the r5 horizon sweep also
shows at 320 steps. So class-level kNN is NOT a usable frozen-encoder
detector at CI cost; it only becomes one at horizon scale.

What IS separable at CI scale is positive-pair alignment
(`metrics["pos_sim"]`, the mean q·k⁺ cosine): only aug-invariance
optimization moves it, so this tool measures it for a LIVE run vs a
FROZEN null (lr ≈ 0 — same program, optimizer steps that move nothing)
over 3 seeds each, and the CI test asserts a margin between the two
populations. The frozen null is the exact regression CI must catch.

Usage: python tools/_texture_smoke_measure.py [steps] [lr]
"""
import json, os, sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from moco_tpu.parallel.mesh import force_cpu_devices

force_cpu_devices(8)  # mirror the CI conftest topology
from moco_tpu.config import get_preset
from moco_tpu.data.datasets import SyntheticTextureDataset
from moco_tpu.train import train

steps = int(sys.argv[1]) if len(sys.argv) > 1 else 256
lr = float(sys.argv[2]) if len(sys.argv) > 2 else 0.12
SPE = 32  # 1024 samples / B32


def run(seed, use_lr):
    cfg = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", cifar_stem=True, dataset="synthetic_texture",
        image_size=32, batch_size=32, num_negatives=512, embed_dim=64,
        lr=use_lr, momentum_ema=0.99, cos=True, epochs=max(steps // SPE, 1),
        knn_monitor=True, knn_every_epochs=max(steps // SPE, 1),
        knn_bank_size=768, num_classes=16, ckpt_dir="", tb_dir="",
        print_freq=SPE - 1, seed=seed,
    )
    data = SyntheticTextureDataset(num_samples=1024, image_size=32,
                                   num_classes=16, seed=seed)
    state, metrics = train(cfg, dataset=data)
    return {
        "seed": seed, "lr": use_lr,
        "untrained_knn": round(metrics["knn_val_top1_untrained"], 4),
        "trained_knn": round(metrics["knn_val_top1"], 4),
        "pos_sim": round(metrics["pos_sim"], 4),
        "loss": round(metrics["loss"], 3), "steps": int(state.step),
    }


live, frozen = [], []
for seed in (0, 1, 2):
    row = run(seed, lr)
    live.append(row)
    print(json.dumps({"live": row}), flush=True)
    row = run(seed, 1e-9)  # frozen null: _effective_lr rejects exactly 0
    frozen.append(row)
    print(json.dumps({"frozen": row}), flush=True)
print(json.dumps({
    # executed count: epochs floor to a multiple of SPE, so a non-multiple
    # request runs fewer steps than asked — report what actually ran
    "lr": lr, "steps": max(steps // SPE, 1) * SPE,
    "live_pos_sim_min": min(r["pos_sim"] for r in live),
    "frozen_pos_sim_max": max(r["pos_sim"] for r in frozen),
    "live_knn_delta": [round(r["trained_knn"] - r["untrained_knn"], 4)
                       for r in live],
    "frozen_knn_delta": [round(r["trained_knn"] - r["untrained_knn"], 4)
                         for r in frozen],
}))
