"""Derive the CI learning-detection threshold (VERDICT r4 #5).

The horizon tool's methodology — untrained-baseline kNN vs trained kNN on
`SyntheticTextureDataset` — lives in a manual tool; CI's smoke tests ran on
the old separable dataset and could not detect a frozen encoder. This tool
measures, over 3 seeds, what a CI-scale run (resnet_tiny, a few hundred
steps) actually achieves, so `tests/test_smoke_train.py` can assert a
MEASURED margin (threshold = roughly half the worst seed's delta, see the
test's docstring for the final number).

Usage: python tools/_texture_smoke_measure.py [steps] [lr]
"""
import json, os, sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from moco_tpu.parallel.mesh import force_cpu_devices

force_cpu_devices(8)  # mirror the CI conftest topology
from moco_tpu.config import get_preset
from moco_tpu.data.datasets import SyntheticTextureDataset
from moco_tpu.train import train

steps = int(sys.argv[1]) if len(sys.argv) > 1 else 320
lr = float(sys.argv[2]) if len(sys.argv) > 2 else 0.12
rows = []
for seed in (0, 1, 2):
    spe = 32  # 1024 samples / B32
    cfg = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", cifar_stem=True, dataset="synthetic_texture",
        image_size=32, batch_size=32, num_negatives=512, embed_dim=64,
        lr=lr, momentum_ema=0.99, cos=True, epochs=max(steps // spe, 1),
        knn_monitor=True, knn_every_epochs=max(steps // spe, 1),
        knn_bank_size=768, num_classes=16, ckpt_dir="", tb_dir="",
        print_freq=9999, seed=seed,
    )
    data = SyntheticTextureDataset(num_samples=1024, image_size=32,
                                   num_classes=16, seed=seed)
    state, metrics = train(cfg, dataset=data)
    row = {
        "seed": seed,
        "untrained": round(metrics["knn_val_top1_untrained"], 4),
        "trained": round(metrics["knn_val_top1"], 4),
        "delta": round(metrics["knn_val_top1"]
                       - metrics["knn_val_top1_untrained"], 4),
        "loss": round(metrics["loss"], 3),
        "steps": int(state.step),
    }
    rows.append(row)
    print(json.dumps(row), flush=True)
print(json.dumps({"lr": lr, "steps": steps,
                  "worst_delta": min(r["delta"] for r in rows),
                  "mean_delta": sum(r["delta"] for r in rows) / len(rows)}))
