import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import json, sys
from moco_tpu.parallel.mesh import force_cpu_devices
force_cpu_devices(8)
from moco_tpu.config import get_preset
from moco_tpu.train import train
res = []
for seed in (0, 1, 2):
    cfg = get_preset("cifar10-moco-v1").replace(
        arch="resnet_tiny", dataset="synthetic", image_size=16, batch_size=32,
        num_negatives=128, embed_dim=32, lr=0.12, epochs=3, steps_per_epoch=16,
        knn_monitor=True, num_classes=10, ckpt_dir="", tb_dir="",
        print_freq=9999, seed=seed,
    )
    state, metrics = train(cfg)
    res.append(round(metrics["knn_train_top1"], 4))
    print("seed", seed, "knn", metrics["knn_train_top1"], flush=True)
print(json.dumps(res))
