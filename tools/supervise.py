#!/usr/bin/env python
"""Run the training driver under the out-of-process supervisor (ISSUE 4).

    python tools/supervise.py --telemetry-dir runs/r1/telemetry \
        --ckpt-dir runs/r1/ckpt -- \
        python -m moco_tpu.train --preset imagenet-moco-v2 \
            --telemetry-dir runs/r1/telemetry --ckpt-dir runs/r1/ckpt

Everything after `--` is the child command, launched verbatim (plus
`--resume auto` on restarts unless the command already carries a
`--resume`). The supervisor detects hangs from heartbeat.json staleness,
classifies every death (exit-code protocol, death signal, events-tail
forensics), restarts within a progress-refunded budget with exponential
backoff, and quarantines integrity-failing checkpoints before each
relaunch. Lifecycle events land as `kind: "supervisor"` records in the
child's events.jsonl — `tools/telemetry_report.py` renders them.

Exit code: 0 when the child finished cleanly; the child's final exit code
when the supervisor gave up (fatal class or exhausted budget), so one
level further up (cron, systemd) still sees the structured code.

See README "Run supervision" for the exit-code table and policy knobs.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.resilience.supervisor import (  # noqa: E402
    RestartPolicy,
    Supervisor,
)
from moco_tpu.utils.logging import info  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--telemetry-dir", required=True,
                   help="the child's telemetry dir (heartbeat.json + "
                        "events.jsonl live here; must match the child's "
                        "--telemetry-dir)")
    p.add_argument("--ckpt-dir", default="",
                   help="the child's checkpoint dir: enables the resume-"
                        "integrity preflight and the checkpoint-step "
                        "progress fallback")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="consecutive no-progress restarts before giving up "
                        "(any step progress refunds the full budget)")
    p.add_argument("--heartbeat-stale-secs", type=float, default=120.0,
                   help="kill the child when its newest step-phase beat is "
                        "older than this; 0 disables hang detection — "
                        "required on non-main pod hosts, which never write "
                        "a heartbeat")
    p.add_argument("--startup-grace-secs", type=float, default=900.0,
                   help="staleness allowance before each launch's first "
                        "step beat (cold compile / restore)")
    p.add_argument("--term-grace-secs", type=float, default=30.0,
                   help="SIGTERM -> grace -> SIGKILL escalation window")
    p.add_argument("--backoff-base-secs", type=float, default=1.0)
    p.add_argument("--backoff-max-secs", type=float, default=60.0)
    p.add_argument("--backoff-jitter", type=float, default=0.2)
    p.add_argument("--poll-secs", type=float, default=2.0)
    p.add_argument("--oom-rss-bytes", type=float, default=0.0,
                   help="classify an external SIGKILL as OOM when the "
                        "events-tail RSS is >= this (0 = never)")
    p.add_argument("--no-force-resume", action="store_true",
                   help="do NOT append `--resume auto` to the child on "
                        "restarts")
    p.add_argument("--resize-device-flag", default="",
                   help="flag used to pin the device count on a resize "
                        "relaunch (ISSUE 11). Default: whichever of "
                        "--num-devices/--fake-devices the child argv "
                        "already uses, else --num-devices")
    p.add_argument("--resize-slow-cadence", type=int, default=0,
                   help="grad_sync_cadence override appended when a resize "
                        "request flags the new mesh slow-linked (`slow=1` "
                        "in resize.request); 0 = never override")
    p.add_argument("--shared-compile-cache", action="store_true",
                   help="let the child use the SHARED persistent XLA "
                        "compile cache. Default is a per-run "
                        "MOCO_TPU_CACHE_DIR (utils/cache.per_run_cache_dir)"
                        ": a SIGKILL'd child can poison a shared cache "
                        "into a native-crash loop for every later process "
                        "(PR 4 finding). An explicit MOCO_TPU_CACHE_DIR / "
                        "MOCO_TPU_NO_CACHE in the environment also wins")
    p.add_argument("--child-log", default="",
                   help="child stdout/stderr log path (default "
                        "<telemetry-dir>/child.log)")
    p.add_argument("child", nargs=argparse.REMAINDER,
                   help="-- then the child command")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    child = args.child
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        build_parser().error("no child command given (append `-- python -m "
                             "moco_tpu.train ...`)")
    owns_cache_dir = (not args.shared_compile_cache
                      and not os.environ.get("MOCO_TPU_CACHE_DIR")
                      and not os.environ.get("MOCO_TPU_NO_CACHE"))
    if owns_cache_dir:
        # supervised runs are kill-risk BY DESIGN (hang-kill escalation,
        # chaos drills): isolate their compile cache so a SIGKILL mid-write
        # can't poison the shared one for every later process on this host.
        # Set once for the whole supervision (children inherit the env):
        # a poisoned per-run dir is contained by the restart budget.
        from moco_tpu.utils.cache import per_run_cache_dir  # stdlib-only

        os.environ["MOCO_TPU_CACHE_DIR"] = per_run_cache_dir(tag="supervised")
        info(f"per-run compile cache: {os.environ['MOCO_TPU_CACHE_DIR']} "
             "(--shared-compile-cache opts out)")
    policy = RestartPolicy(
        max_restarts=args.max_restarts,
        heartbeat_stale_secs=args.heartbeat_stale_secs,
        startup_grace_secs=args.startup_grace_secs,
        term_grace_secs=args.term_grace_secs,
        backoff_base_secs=args.backoff_base_secs,
        backoff_max_secs=args.backoff_max_secs,
        backoff_jitter=args.backoff_jitter,
        poll_secs=args.poll_secs,
        oom_rss_bytes=args.oom_rss_bytes,
    )
    sup = Supervisor(
        child,
        telemetry_dir=args.telemetry_dir,
        ckpt_dir=args.ckpt_dir,
        policy=policy,
        force_resume=not args.no_force_resume,
        child_log_path=args.child_log,
        resize_device_flag=args.resize_device_flag,
        resize_slow_cadence=args.resize_slow_cadence,
        # rotate the compile cache per resize only when the supervisor
        # derived the cache dir itself: --shared-compile-cache and an
        # operator-pinned MOCO_TPU_CACHE_DIR are explicit choices a
        # resize must not silently override
        resize_rotate_cache=owns_cache_dir,
    )
    # SIGUSR2 to the SUPERVISOR requests an elastic resize (ISSUE 11): the
    # next monitor cycle claims any pending resize.request payload (or an
    # empty "resize to what's visible" request) and signals the child
    import signal

    signal.signal(signal.SIGUSR2, lambda *_: sup.resize.signal_resize())
    result = sup.run()
    info(
        f"supervisor: {result.final_class} after {result.launches} launch(es)"
        f" ({result.restarts} restart(s)"
        f"{', budget exhausted' if result.gave_up else ''})"
    )
    if result.final_class == "clean":
        return 0
    return result.exit_code if result.exit_code and result.exit_code > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
