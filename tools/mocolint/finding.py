"""The one record every rule produces and every consumer reads."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation at one source location.

    `path` is the path exactly as the caller spelled it (tests and
    editors match on it verbatim); scoping normalizes it on the
    FileContext, and baselines fingerprint a spelling-independent form
    (baseline._canon_path). `message` must NOT embed the location or the
    rule id: formatting is the consumer's choice.
    """

    path: str
    line: int
    rule: str
    message: str
    col: int = 0
    severity: str = "error"

    def legacy(self) -> str:
        """The pre-mocolint `path:line: message` string (lint_robustness
        shim contract — no rule id in the text)."""
        return f"{self.path}:{self.line}: {self.message}"

    def human(self) -> str:
        """`path:line: RULE message` — the mocolint CLI format."""
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def json_obj(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
        }


def sort_findings(findings: list[Finding]) -> list[Finding]:
    """Stable order: by file path, then line/col, then rule id. (The
    monolith emitted R4 findings before the node-walk rules and grouped
    files in os.walk order; every per-file count and line the pinned
    tests assert survives the resort, but raw output order on a dirty
    tree can differ.)"""
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
