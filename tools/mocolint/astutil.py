"""Small AST helpers shared by the rule plugins."""

from __future__ import annotations

import ast


def call_name(func: ast.expr) -> str | None:
    """Tail name of a call target: `pmean`, `lax.pmean` -> "pmean"."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def dotted(expr: ast.expr) -> str | None:
    """Full dotted spelling of a Name/Attribute chain, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    parts.append(expr.id)
    return ".".join(reversed(parts))


# Call tails that trace the function passed to them into an XLA program.
TRACERS = {
    "jit", "pmap", "shard_map", "vmap", "grad", "value_and_grad",
    "remat", "checkpoint", "scan", "custom_vjp", "custom_jvp",
}


def traced_functions(tree: ast.AST, parents: dict) -> set[ast.AST]:
    """Function defs whose bodies run under a jax trace.

    A function is traced when (a) its name is passed to a tracer call
    (`jax.jit(train_step, ...)`, `shard_map(spmd_region, ...)`,
    `value_and_grad(loss_fn)`), (b) it is decorated with a tracer
    (`@jax.jit`, `@functools.partial(jax.jit, ...)`), or (c) it is
    lexically nested inside a traced function. Name-based on purpose:
    the lint guards the obvious hazard, not adversarial aliasing.
    """
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    traced: set[ast.AST] = set()

    def mark_by_name(name: str):
        for fn in defs.get(name, ()):
            traced.add(fn)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node.func) in TRACERS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    mark_by_name(arg.id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if _decorator_traces(deco):
                    traced.add(node)

    # closure: anything lexically inside a traced function is traced
    out = set(traced)
    for fns in defs.values():
        for fn in fns:
            cur = parents.get(fn)
            while cur is not None:
                if cur in traced:
                    out.add(fn)
                    break
                cur = parents.get(cur)
    return out


def _decorator_traces(deco: ast.expr) -> bool:
    if call_name(deco) in TRACERS:
        return True
    if isinstance(deco, ast.Call):
        if call_name(deco.func) in TRACERS:
            return True
        # functools.partial(jax.jit, donate_argnums=...)
        if call_name(deco.func) == "partial":
            for arg in deco.args:
                if call_name(arg) in TRACERS or (
                    isinstance(arg, ast.Attribute) and arg.attr in TRACERS
                ):
                    return True
    return False


def enclosing_function(node: ast.AST, parents: dict):
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def in_traced_scope(node: ast.AST, parents: dict, traced: set) -> bool:
    fn = enclosing_function(node, parents)
    while fn is not None:
        if fn in traced:
            return True
        fn = enclosing_function(fn, parents)
    return False
