"""The analysis engine: one parse per file, one walk, all rules.

The monolithic linter re-walked the AST once per rule family; this engine
parses each file exactly once, annotates parents, and dispatches every
node to the rules that registered for its type during a single shared
walk. File-level and cross-file hooks run after. On this repo (~110
files) a full run is well under a second — the tier-1 budget is 5 s.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from tools.mocolint import baseline as baseline_mod
from tools.mocolint import suppress
from tools.mocolint.config import DEFAULT_CONFIG, LintConfig, norm
from tools.mocolint.finding import Finding, sort_findings
from tools.mocolint.registry import all_rules


@dataclasses.dataclass
class ImportEdge:
    """One import statement: the dotted module it names, where, and
    whether it executes at module import time (lazy = inside a function)."""

    module: str
    line: int
    lazy: bool
    type_checking: bool  # inside `if TYPE_CHECKING:` — never executes


class FileContext:
    """Everything the rules may want about one parsed file."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path              # as the caller spelled it
        self.norm = norm(path)
        self.source = source
        self.tree = tree
        self.parents: dict = {}
        self.suppressions = suppress.scan(source)
        self.module = module_name_for(self.norm)
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.imports = _collect_imports(tree, self.parents)

    def parent(self, node):
        return self.parents.get(node)

    def ancestors(self, node):
        node = self.parents.get(node)
        while node is not None:
            yield node
            node = self.parents.get(node)


def module_name_for(norm_path: str) -> str | None:
    """Dotted module name for in-package files, from the LAST `moco_tpu`
    path segment ("/tmp/x/moco_tpu/serve/http.py" -> "moco_tpu.serve.http").
    Files outside a moco_tpu tree get None: they are not import targets
    of the package graph."""
    parts = norm_path.split("/")
    if "moco_tpu" not in parts:
        return None
    i = len(parts) - 1 - parts[::-1].index("moco_tpu")
    rel = parts[i:]
    if not rel[-1].endswith(".py"):
        return None
    rel[-1] = rel[-1][:-3]
    if rel[-1] == "__init__":
        rel = rel[:-1]
    return ".".join(rel)


def _in_type_checking(node, parents) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If):
            t = cur.test
            if (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
                isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
            ):
                return True
        cur = parents.get(cur)
    return False


def _collect_imports(tree, parents) -> list[ImportEdge]:
    out: list[ImportEdge] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        lazy = any(
            isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
            for a in _ancestors(node, parents)
        )
        tc = _in_type_checking(node, parents)
        if isinstance(node, ast.Import):
            for alias in node.names:
                out.append(ImportEdge(alias.name, node.lineno, lazy, tc))
        else:
            if node.level:  # relative: resolved by the boundary rule if
                continue    # needed; every current contract is absolute
            base = node.module or ""
            out.append(ImportEdge(base, node.lineno, lazy, tc))
            # `from pkg import sub` may name a submodule: record both
            for alias in node.names:
                if base:
                    out.append(
                        ImportEdge(f"{base}.{alias.name}", node.lineno,
                                   lazy, tc)
                    )
    return out


def _ancestors(node, parents):
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


class Project:
    """Cross-file view handed to finalize(): all parsed contexts plus the
    module-level import graph keyed by dotted module name."""

    def __init__(self, contexts: list[FileContext]):
        self.contexts = contexts
        self.by_module: dict[str, FileContext] = {}
        for ctx in contexts:
            if ctx.module:
                self.by_module[ctx.module] = ctx

    def resolve(self, module: str) -> FileContext | None:
        """Context for `module`, tolerating package-vs-module spelling."""
        if module in self.by_module:
            return self.by_module[module]
        return None


@dataclasses.dataclass
class Result:
    findings: list          # what the caller should fail on
    suppressed: list        # dropped by inline suppressions
    baselined: list         # dropped by the baseline file
    files_scanned: int
    files_cached: int = 0   # served from the incremental cache (no parse)

    @property
    def clean(self) -> bool:
        return not self.findings


def collect_files(paths) -> list[str]:
    """Expand dirs to sorted .py files, deduplicating overlapping inputs
    (a dir plus a file inside it must not scan the file twice: doubled
    findings would blow past their baseline budget)."""
    out, seen = [], set()

    def add(p):
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            out.append(p)

    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        add(os.path.join(dirpath, fname))
        else:
            add(path)
    return out


class Engine:
    def __init__(self, config: LintConfig = DEFAULT_CONFIG,
                 select: tuple[str, ...] | None = None):
        self.config = config
        classes = all_rules()
        ids = [
            rid for rid in classes
            if config.rule_enabled(rid) and (select is None or rid in select)
        ]
        self.rules = [classes[rid]() for rid in ids]
        for rule in self.rules:
            rule.config = config
        # whether a --select subset is running: unused-suppression
        # reporting must not flag suppressions of rules that never ran
        self._subset = select is not None

    def run(self, paths, baseline_path: str | None = None,
            cache_dir: str | None = None) -> Result:
        """`cache_dir` enables the incremental per-file cache (ISSUE 9
        satellite; tools/mocolint/cache.py): unchanged files skip parse +
        walk and replay their cached per-file findings; cross-file
        analysis (finalize) always re-runs over the full context set."""
        cache = None
        engine_fp = ""
        if cache_dir:
            from tools.mocolint import cache as cache_mod

            cache = cache_mod.ResultCache(cache_dir)
            engine_fp = cache_mod.engine_fingerprint(
                self.config, [r.id for r in self.rules]
            )
        contexts: list = []          # FileContext | cache.SlimContext
        findings: list[Finding] = []
        files_cached = 0
        for path in collect_files(paths):
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            except OSError as e:
                findings.append(Finding(path, 0, "PARSE",
                                        f"unreadable ({e})"))
                continue
            if cache is not None:
                content_hash = cache.content_hash(source)
                hit = cache.load(path, norm(path), content_hash, engine_fp)
                if hit is not None:
                    ctx, cached_findings = hit
                    contexts.append(ctx)
                    findings.extend(cached_findings)
                    files_cached += 1
                    continue
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                findings.append(Finding(path, e.lineno or 0, "PARSE",
                                        f"unparseable ({e.msg})"))
                continue
            ctx = FileContext(path, source, tree)
            contexts.append(ctx)
            file_findings = list(self._check_file(ctx))
            findings.extend(file_findings)
            if cache is not None:
                cache.store(ctx, file_findings, content_hash, engine_fp)
        project = Project(contexts)
        for rule in self.rules:
            findings.extend(rule.finalize(project))
        # suppressions are per-file; group findings back to their context
        supp_by_path = {c.path: c.suppressions for c in contexts}
        kept, suppressed = [], []
        for path, sups in supp_by_path.items():
            mine = [f for f in findings if f.path == path]
            k, s = suppress.apply(mine, sups)
            kept.extend(k)
            suppressed.extend(s)
        kept.extend(f for f in findings if f.path not in supp_by_path)
        if self.config.report_unused_suppressions:
            active = {r.id for r in self.rules}
            for ctx in contexts:
                for s in ctx.suppressions:
                    if s.used:
                        continue
                    # under --select, a suppression of an unselected rule
                    # (or of "all") cannot prove itself used — skip it; a
                    # full run still reports every unused one, typos
                    # included
                    if self._subset and not (s.rules & active):
                        continue
                    kept.append(Finding(
                        ctx.path, s.line, "SUP",
                        "unused suppression "
                        f"({', '.join(sorted(s.rules))}) — nothing it "
                        "covers fires any more; delete it so a "
                        "regression cannot hide behind it",
                    ))
        baselined: list[Finding] = []
        if baseline_path:
            counts = baseline_mod.load(baseline_path)
            kept, baselined = baseline_mod.apply(sort_findings(kept), counts)
        return Result(
            findings=sort_findings(kept),
            suppressed=suppressed,
            baselined=baselined,
            files_scanned=len(contexts),
            files_cached=files_cached,
        )

    def _check_file(self, ctx: FileContext):
        scoped = [r for r in self.rules
                  if self.config.scope_for(r.id).contains(ctx.path)]
        if not scoped:
            return
        by_type = {}
        for rule in scoped:
            for node_type in rule.node_types:
                by_type.setdefault(node_type, []).append(rule)
        if by_type:
            for node in ast.walk(ctx.tree):
                for rule in by_type.get(type(node), ()):
                    yield from rule.visit(node, ctx)
        for rule in scoped:
            yield from rule.check_file(ctx)
