"""R3: bare print() bypasses the structured telemetry channel."""

from __future__ import annotations

import ast

from tools.mocolint.registry import Rule, register


@register
class BarePrint(Rule):
    id = "R3"
    title = "no bare print() outside the sanctioned channels"
    rationale = ("an event printed anywhere else bypasses log_event -> "
                 "telemetry events.jsonl, so an external monitor can never "
                 "consume it")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            yield self.finding(
                ctx, node.lineno,
                "bare `print(...)` — route through utils.logging (log_event "
                "for events, info for plain lines) so the structured "
                "telemetry sinks see it",
            )
