"""R4: every loader/service construction must be closed.

Prefetcher/epoch_loader leak staging threads and `depth` device batches
otherwise; the input-service constructions (ISSUE 14) additionally leak
sockets and decode-worker SUBPROCESSES — a ServiceClient left open keeps
its credit window pinned on every server, and an unclosed StagingServer/
LocalServerPool leaves orphan worker processes decoding for nobody. A
construction returned directly is the factory pattern and exempt: the
caller owns the close.
"""

from __future__ import annotations

import ast

from tools.mocolint.astutil import call_name
from tools.mocolint.registry import Rule, register

LOADER_FACTORIES = {"Prefetcher", "epoch_loader",
                    "ServiceClient", "service_epoch_loader",
                    "StagingServer", "LocalServerPool"}


def _walk_shallow(node):
    """Children of `node`, not descending into nested function/class
    scopes (each has its own finally obligations)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield child
        yield from _walk_shallow(child)


@register
class UnclosedLoader(Rule):
    id = "R4"
    title = "loader constructions need a close() in a finally"
    rationale = ("an early break leaks the staging threads and the staged "
                 "device batches for the life of the process")

    def check_file(self, ctx):
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            yield from self._scope(scope, ctx)

    def _scope(self, scope, ctx):
        constructions: list[tuple[str | None, int]] = []
        closed_in_finally: set[str] = set()
        for node in _walk_shallow(scope):
            if (isinstance(node, ast.Call)
                    and call_name(node.func) in LOADER_FACTORIES):
                parent = ctx.parent(node)
                if isinstance(parent, ast.Return):
                    continue  # factory pattern: the caller owns the close
                if (isinstance(parent, ast.Assign)
                        and len(parent.targets) == 1
                        and isinstance(parent.targets[0], ast.Name)):
                    constructions.append(
                        (parent.targets[0].id, node.lineno)
                    )
                else:
                    constructions.append((None, node.lineno))
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for call in ast.walk(stmt):
                        if (isinstance(call, ast.Call)
                                and isinstance(call.func, ast.Attribute)
                                and call.func.attr in ("close",
                                                       "close_quietly")
                                and isinstance(call.func.value, ast.Name)):
                            closed_in_finally.add(call.func.value.id)
        for var, lineno in constructions:
            if var is None:
                yield self.finding(
                    ctx, lineno,
                    "loader/service constructed without binding a "
                    "name — the staging threads can never be close()d; bind "
                    "it and close in a finally",
                )
            elif var not in closed_in_finally:
                yield self.finding(
                    ctx, lineno,
                    f"`{var} = ...` builds a loader/service but no `finally` "
                    f"in this function calls `{var}.close()`/"
                    f"`{var}.close_quietly()` — an early break leaks the "
                    "staging threads/sockets and the staged batches",
                )
