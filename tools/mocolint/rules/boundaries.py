"""R6/R11: the config-driven import-boundary graph.

R6 is the original serve-is-train-free check, now one `Boundary` entry
instead of a hand-rolled walker (behavior and message pinned by
tests/test_lint_robustness.py). R11 generalizes it three ways:

  - transitive forbids: an import CHAIN that reaches a forbidden module
    through module-level imports of in-repo modules is flagged at the
    originating import, with the chain in the message;
  - stdlib-only scopes: the supervisor processes must import nothing
    outside the standard library except moco_tpu modules that are
    themselves (transitively, at module level) stdlib-only;
  - lazy-only modules: heavy optional deps (orbax) may be imported only
    inside functions, never at module level.

Lazy (function-body) imports count for DIRECT forbids — a lazy train
import still drags the stack in when the function runs — but transitive
walks follow only module-level edges: a lazy import inside a reached
module is a deliberately deferred dependency (the exact pattern
checkpoint.py uses to keep orbax off the serve path).
"""

from __future__ import annotations

import ast
import sys

from tools.mocolint.registry import Rule, register

_STDLIB = frozenset(getattr(sys, "stdlib_module_names", ())) | {"__future__"}


def _root(module: str) -> str:
    return module.split(".", 1)[0]


def _is_stdlib(module: str) -> bool:
    return _root(module) in _STDLIB


def _forbidden_by(module: str, forbid) -> str | None:
    for f in forbid:
        if module == f or module.startswith(f + "."):
            return f
    return None


def _resolve(project, module: str):
    """FileContext for `module`, falling back one level (an edge like
    `pkg.mod.symbol` from `from pkg.mod import symbol` resolves to
    `pkg.mod`)."""
    ctx = project.resolve(module)
    if ctx is None and "." in module:
        ctx = project.resolve(module.rsplit(".", 1)[0])
    return ctx


def _with_ancestors(module: str):
    """`a.b.c` -> [a, a.b, a.b.c]: importing a submodule executes every
    ancestor package's __init__, so the walk must include them."""
    parts = module.split(".")
    return [".".join(parts[: i + 1]) for i in range(len(parts))]


def _seed(project, module: str):
    """Initial BFS frontier for `module`: itself plus every resolvable
    ancestor package (their __init__ bodies execute on import too)."""
    frontier, visited = [], set()
    for anc in _with_ancestors(module):
        if anc not in visited and project.resolve(anc):
            visited.add(anc)
            frontier.append((anc, [anc]))
    return frontier, visited


@register
class ServeTrainFree(Rule):
    """R6 — direct forbidden imports inside a boundary scope."""

    id = "R6"
    title = "serve/ never imports the train stack"
    rationale = ("a server that CAN touch training state eventually will; "
                 "the optimizer stack also bloats every serving process")
    node_types = (ast.Import, ast.ImportFrom)

    def _boundaries(self, ctx):
        return [b for b in self.config.boundaries
                if b.rule_id == self.id and b.forbid and not b.transitive
                and b.in_scope(ctx.path)]

    def visit(self, node, ctx):
        for b in self._boundaries(ctx):
            parents = {f.rsplit(".", 1)[0] for f in b.forbid if "." in f}
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _forbidden_by(alias.name, b.forbid):
                        yield self._flag(ctx, node, alias.name, b)
            else:
                if node.level:  # relative import inside the scope: fine
                    continue
                if _forbidden_by(node.module, b.forbid):
                    yield self._flag(ctx, node, node.module, b)
                elif node.module in parents:
                    for alias in node.names:
                        full = f"{node.module}.{alias.name}"
                        if _forbidden_by(full, b.forbid):
                            yield self._flag(ctx, node, full, b)

    # the historical serve-scope wording, kept verbatim for the shim's
    # pinned-parity tests; other boundaries (ISSUE 14 input service) name
    # themselves instead of claiming to be serve/
    _SERVE_NAMES = ("serve-train-free", "fleet-cli-train-free")

    def _flag(self, ctx, node, module, b):
        if b.name in self._SERVE_NAMES:
            return self.finding(
                ctx, node.lineno,
                f"serve/ imports {module!r} — the serving runtime must "
                "stay train-free (lint R6): no train, train_step, "
                "v3_step, train_state, or optimizer modules",
            )
        # every other boundary explains itself: its own name, rule id
        # and rationale — not serve/'s
        return self.finding(
            ctx, node.lineno,
            f"[{b.name}] imports {module!r} — forbidden by this "
            f"boundary (lint {b.rule_id}): {b.why}",
        )


@register
class ImportBoundary(Rule):
    """R11 — transitive forbids, stdlib-only scopes, lazy-only modules."""

    id = "R11"
    title = "config-driven cross-file import boundaries"
    rationale = ("single-purpose import checks don't scale; every boundary "
                 "is one config entry against the same graph walker")

    def check_file(self, ctx):
        for b in self.config.boundaries:
            if b.rule_id != self.id or not b.in_scope(ctx.path):
                continue
            if b.lazy_only:
                yield from self._check_lazy_only(ctx, b)
            if b.stdlib_only:
                yield from self._check_stdlib_direct(ctx, b)

    def _check_lazy_only(self, ctx, b):
        seen = set()
        for edge in ctx.imports:
            if edge.lazy or edge.type_checking:
                continue
            hit = _forbidden_by(edge.module, b.lazy_only)
            if hit and (edge.line, hit) not in seen:
                seen.add((edge.line, hit))
                yield self.finding(
                    ctx, edge.line,
                    f"module-level import of {edge.module!r} — "
                    f"[{b.name}] {hit} must be imported lazily (inside the "
                    f"function that needs it): {b.why}",
                )

    def _check_stdlib_direct(self, ctx, b):
        seen = set()
        for edge in ctx.imports:
            if edge.type_checking:
                continue
            if _is_stdlib(edge.module):
                continue
            if any(_root(edge.module) == p or edge.module.startswith(p + ".")
                   or edge.module == p for p in b.allow_prefixes):
                continue
            if (edge.line, _root(edge.module)) in seen:
                continue
            seen.add((edge.line, _root(edge.module)))
            yield self.finding(
                ctx, edge.line,
                f"imports {edge.module!r} — [{b.name}] this file is "
                f"stdlib-only: {b.why}",
            )

    def finalize(self, project):
        for b in self.config.boundaries:
            if b.rule_id != self.id or not b.transitive:
                continue
            for ctx in project.contexts:
                if not b.in_scope(ctx.path):
                    continue
                if b.forbid:
                    yield from self._walk_forbid(project, ctx, b)
                if b.stdlib_only:
                    yield from self._walk_stdlib(project, ctx, b)

    def _module_edges(self, ctx):
        """Module-level (non-lazy, non-TYPE_CHECKING) imports of a file."""
        return [e for e in ctx.imports if not e.lazy and not e.type_checking]

    def _walk_forbid(self, project, ctx, b):
        reported = set()
        for edge in ctx.imports:
            if edge.type_checking:
                continue
            if _forbidden_by(edge.module, b.forbid):
                continue  # direct violation: R6's finding, not a chain
            chain = self._find_chain(project, edge.module, b.forbid)
            if chain and (edge.line, chain[-1]) not in reported:
                reported.add((edge.line, chain[-1]))
                yield self.finding(
                    ctx, edge.line,
                    f"import chain reaches {chain[-1]!r}: "
                    f"{' -> '.join([edge.module] + chain[1:])} — "
                    f"[{b.name}] {b.why}",
                )

    def _find_chain(self, project, module, forbid):
        """BFS over module-level edges from `module`; returns the module
        chain ending at a forbidden import, or None. Terminates without a
        budget: the visited set admits each project module once."""
        start = _resolve(project, module)
        if start is None or start.module is None:
            return None
        frontier, visited = _seed(project, start.module)
        while frontier:
            name, chain = frontier.pop(0)
            ctx = project.resolve(name)
            if ctx is None:
                continue
            for edge in self._module_edges(ctx):
                if _forbidden_by(edge.module, forbid):
                    return chain + [edge.module]
                for anc in _with_ancestors(edge.module):
                    if anc not in visited and project.resolve(anc):
                        visited.add(anc)
                        frontier.append((anc, chain + [anc]))
        return None

    def _walk_stdlib(self, project, ctx, b):
        reported = set()
        for edge in ctx.imports:
            if edge.type_checking or _is_stdlib(edge.module):
                continue
            if not any(edge.module == p or edge.module.startswith(p + ".")
                       for p in b.allow_prefixes):
                continue  # direct non-allowed imports: _check_stdlib_direct
            bad = self._stdlib_chain(project, edge.module, b)
            if bad and (edge.line, bad[-1]) not in reported:
                reported.add((edge.line, bad[-1]))
                yield self.finding(
                    ctx, edge.line,
                    f"import chain reaches non-stdlib {bad[-1]!r}: "
                    f"{' -> '.join([edge.module] + bad[1:])} — "
                    f"[{b.name}] {b.why}",
                )

    def _stdlib_chain(self, project, module, b):
        start = _resolve(project, module)
        if start is None or start.module is None:
            return None
        frontier, visited = _seed(project, start.module)
        while frontier:
            name, chain = frontier.pop(0)
            ctx = project.resolve(name)
            if ctx is None:
                continue
            for edge in self._module_edges(ctx):
                if _is_stdlib(edge.module):
                    continue
                allowed = any(
                    edge.module == p or edge.module.startswith(p + ".")
                    for p in b.allow_prefixes
                )
                if not allowed:
                    return chain + [edge.module]
                for anc in _with_ancestors(edge.module):
                    if anc not in visited and project.resolve(anc):
                        visited.add(anc)
                        frontier.append((anc, chain + [anc]))
        return None
