"""R7: gradient/parameter collectives live in moco_tpu/parallel/ only.

An inline `lax.pmean(grads, ...)` in a step builder silently reverts the
step to the fused end-of-step reduce, bypassing the configured
bucketing/quantization/sparsification AND the comm telemetry measuring
it. ISSUE 15 widens the same contract to the FSDP primitives: an inline
`all_gather(params, ...)` / `psum_scatter(grads, ...)` outside parallel/
bypasses the ShardingPlan's per-leaf axis bookkeeping (gather and scatter
MUST agree leaf-by-leaf) and the multihop/chunked scheduling layered on
top. Name-based on purpose: the lint guards the obvious regression, not
adversarial renaming.
"""

from __future__ import annotations

import ast

from tools.mocolint.astutil import call_name
from tools.mocolint.registry import Rule, register

# collective spellings × the operand-name fragments that bind them to the
# gradsync/fsdp contract
_GRAD_COLLECTIVES = ("pmean", "psum", "psum_scatter", "reduce_scatter",
                     "all_gather")
_PARAM_COLLECTIVES = ("all_gather", "psum_scatter", "reduce_scatter")


@register
class GradCollective(Rule):
    id = "R7"
    title = "gradient/param collectives only under moco_tpu/parallel/"
    rationale = ("grads must route through the gradsync API and param "
                 "gathers/scatters through the fsdp ShardingPlan, so the "
                 "configured sync/sharding mode and its telemetry stay "
                 "in effect")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        fn = call_name(node.func)
        if fn not in _GRAD_COLLECTIVES or not node.args:
            return
        first = node.args[0]
        if isinstance(first, ast.Name):
            opname = first.id.lower()
        elif isinstance(first, ast.Attribute):
            opname = first.attr.lower()
        else:
            opname = ""
        graddy = "grad" in opname
        paramy = fn in _PARAM_COLLECTIVES and "param" in opname
        if graddy:
            yield self.finding(
                ctx, node.lineno,
                "gradient collective outside moco_tpu/parallel/ — route "
                "grads through the gradsync API (parallel/gradsync.GradSync)"
                "; an inline pmean/psum on grads bypasses the configured "
                "sync mode and its telemetry",
            )
        elif paramy:
            yield self.finding(
                ctx, node.lineno,
                "parameter gather/scatter outside moco_tpu/parallel/ — "
                "route param sharding through the fsdp ShardingPlan "
                "(parallel/fsdp.py); an inline all_gather/psum_scatter on "
                "params forks the per-leaf shard-axis bookkeeping the "
                "plan's gather and scatter share",
            )
