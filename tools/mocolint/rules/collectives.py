"""R7: gradient collectives live in moco_tpu/parallel/ only.

An inline `lax.pmean(grads, ...)` in a step builder silently reverts the
step to the fused end-of-step reduce, bypassing the configured
bucketing/quantization/sparsification AND the comm telemetry measuring
it. Name-based on purpose: the lint guards the obvious regression, not
adversarial renaming.
"""

from __future__ import annotations

import ast

from tools.mocolint.astutil import call_name
from tools.mocolint.registry import Rule, register


@register
class GradCollective(Rule):
    id = "R7"
    title = "gradient pmean/psum only under moco_tpu/parallel/"
    rationale = ("grads must route through the gradsync API so the "
                 "configured sync mode and its telemetry stay in effect")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        if call_name(node.func) not in ("pmean", "psum") or not node.args:
            return
        first = node.args[0]
        if isinstance(first, ast.Name):
            graddy = "grad" in first.id.lower()
        elif isinstance(first, ast.Attribute):
            graddy = "grad" in first.attr.lower()
        else:
            graddy = False
        if graddy:
            yield self.finding(
                ctx, node.lineno,
                "gradient collective outside moco_tpu/parallel/ — route "
                "grads through the gradsync API (parallel/gradsync.GradSync)"
                "; an inline pmean/psum on grads bypasses the configured "
                "sync mode and its telemetry",
            )
