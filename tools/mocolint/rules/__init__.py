"""Built-in rule plugins. Importing this package registers every rule."""

from tools.mocolint.rules import (  # noqa: F401
    atomicwrite,
    boundaries,
    collectives,
    determinism,
    exceptions,
    exits,
    hostsync,
    loaders,
    printing,
    threadsafety,
    tracing,
)
