"""R8: host-sync / recompile hazards inside jitted step-builder code.

The bit-identical resume and serve contracts (PRs 1/3/5) assume the
compiled step is ONE program with no host round-trips: a `.item()`,
`float(arr)`, `np.asarray(...)`, `jax.device_get(...)` or
`block_until_ready(...)` inside a traced function forces a device sync
per step (and usually a silent constant-folding of a traced value), and
Python branching on `.shape` of a traced value recompiles per shape.

Scope: the step-builder modules (config `STEP_BUILDER_MODULES`), and
within them only the bodies of functions that are actually traced — a
`build_*` function's setup code runs on the host by design and may do
all of the above freely. Traced-ness is the closure computed by
`astutil.traced_functions` (passed to jit/shard_map/grad/..., decorated,
or lexically nested inside such a function).
"""

from __future__ import annotations

import ast

from tools.mocolint.astutil import (
    call_name,
    dotted,
    in_traced_scope,
    traced_functions,
)
from tools.mocolint.registry import Rule, register

# numpy calls that materialize a traced value on the host
_NP_HOST = {"asarray", "array", "copy", "save", "frombuffer"}
_NP_BASES = {"np", "numpy", "onp"}


@register
class HostSyncInTracedCode(Rule):
    id = "R8"
    title = "no host syncs / recompile hazards in jitted step code"
    rationale = ("a host round-trip inside the compiled step stalls the "
                 "device pipeline every step and silently constant-folds "
                 "traced values; shape-dependent Python branching "
                 "recompiles per shape")

    def check_file(self, ctx):
        traced = traced_functions(ctx.tree, ctx.parents)
        if not traced:
            return
        for node in ast.walk(ctx.tree):
            if not in_traced_scope(node, ctx.parents, traced):
                continue
            if isinstance(node, ast.Call):
                yield from self._check_call(node, ctx)
            elif isinstance(node, (ast.If, ast.While)):
                yield from self._check_branch(node, ctx)

    def _check_call(self, node, ctx):
        func = node.func
        name = call_name(func)
        if name == "item" and isinstance(func, ast.Attribute):
            yield self.finding(
                ctx, node.lineno,
                "`.item()` inside a traced function — a per-step device "
                "sync; keep the value on device (or move this to the "
                "driver after the step returns)",
            )
            return
        if name in ("device_get", "block_until_ready"):
            yield self.finding(
                ctx, node.lineno,
                f"`{dotted(func) or name}(...)` inside a traced function — "
                "host materialization stalls the step pipeline; traced "
                "code must stay on device",
            )
            return
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in _NP_BASES
                and func.attr in _NP_HOST):
            yield self.finding(
                ctx, node.lineno,
                f"`{func.value.id}.{func.attr}(...)` inside a traced "
                "function — numpy materializes the traced value on the "
                "host (silent constant-fold + per-step sync); use jnp",
            )
            return
        if (isinstance(func, ast.Name) and func.id in ("float", "int")
                and node.args
                and not isinstance(node.args[0], ast.Constant)):
            yield self.finding(
                ctx, node.lineno,
                f"`{func.id}(...)` on a non-literal inside a traced "
                "function — coercing a traced array to a Python scalar "
                "forces a host sync (TracerConversionError at best, a "
                "silent constant-fold at worst); use jnp casts",
            )

    def _check_branch(self, node, ctx):
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                yield self.finding(
                    ctx, node.lineno,
                    "Python branch on `.shape` inside a traced function — "
                    "each distinct shape compiles a new program (the serve "
                    "bucket ladder exists precisely to bound this); branch "
                    "with lax.cond or hoist the shape decision to build "
                    "time",
                )
                return
