"""R5: no numeric-literal process exits — the supervisor classifies
deaths by exit code, so codes must come from the named constants in
resilience/exitcodes.py (one source of truth)."""

from __future__ import annotations

import ast

from tools.mocolint.registry import Rule, register


def _is_exit_call(func: ast.expr) -> bool:
    """Exactly the process-exit spellings: `sys.exit`, `os._exit`, the
    bare builtins `exit`/`SystemExit`. NOT any method that happens to be
    named exit (`parser.exit(2)` is argparse's API, not the protocol)."""
    if isinstance(func, ast.Name):
        return func.id in ("exit", "SystemExit")
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id == "sys" and func.attr == "exit") or \
            (func.value.id == "os" and func.attr == "_exit")
    return False


@register
class NumericExit(Rule):
    id = "R5"
    title = "no numeric-literal process exits"
    rationale = ("a magic number silently forks the supervisor's exit-code "
                 "classification protocol")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        if not _is_exit_call(node.func) or not node.args:
            return
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, int):
            yield self.finding(
                ctx, node.lineno,
                "numeric-literal process exit — use the named constants in "
                "resilience/exitcodes.py (the supervisor classifies deaths "
                "by these codes; a magic number here silently forks the "
                "protocol)",
            )
