"""R12: span discipline + trace.py's import diet (ISSUE 8 satellite).

Two contracts, one rule:

  1. Spans are opened ONLY through the context-manager API: every call
     to `.span(...)` / `.begin_span(...)` must be the context expression
     of a `with` statement. A span held outside `with` either never
     records (no __enter__/__exit__) or — entered by hand without a
     guaranteed exit — leaks on the opening thread's span stack and
     corrupts parenting for everything after it. Retroactive recording
     (`record_span`/`record_step`/`instant`) is the sanctioned escape
     hatch for intervals that end on another thread.

  2. `moco_tpu/telemetry/trace.py` imports NOTHING outside the standard
     library — module-level or lazy. The out-of-process supervisor
     imports it (and calls into it at runtime), and the supervisor's
     contract is surviving exactly the failures that kill the jax/numpy
     stack; one lazy `import jax` inside a method the supervisor calls
     would couple their fates.
"""

from __future__ import annotations

import ast

from tools.mocolint.registry import Rule, register
from tools.mocolint.rules.boundaries import _is_stdlib

_OPENERS = ("span", "begin_span")
_TRACE_MODULE_SUFFIX = "telemetry/trace.py"


def _call_attr(node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


@register
class SpanDiscipline(Rule):
    id = "R12"
    title = "spans open via `with`; trace.py stays stdlib-only"
    rationale = ("a span opened outside `with` never records or leaks on "
                 "the thread span stack; a non-stdlib import in trace.py "
                 "breaks the supervisor that must import it")
    node_types = (ast.Call,)

    def visit(self, node, ctx):
        name = _call_attr(node)
        if name not in _OPENERS:
            return
        if ctx.norm.endswith(_TRACE_MODULE_SUFFIX):
            return  # the implementation itself constructs Span objects
        parent = ctx.parent(node)
        if isinstance(parent, ast.withitem):
            return
        yield self.finding(
            ctx, node.lineno,
            f"`{name}(...)` outside a `with` statement — spans may only "
            "be opened via the context-manager API (use `with "
            f"tracer.{name}(...) as sp:`; for intervals that end "
            "elsewhere, record retroactively with record_span)",
        )

    def check_file(self, ctx):
        if not ctx.norm.endswith(_TRACE_MODULE_SUFFIX):
            return
        for edge in ctx.imports:
            if edge.type_checking or _is_stdlib(edge.module):
                continue
            yield self.finding(
                ctx, edge.line,
                f"trace.py imports non-stdlib module {edge.module!r}"
                f"{' (lazy)' if edge.lazy else ''} — it must stay "
                "importable and callable without jax/numpy: the "
                "out-of-process supervisor depends on it",
            )
