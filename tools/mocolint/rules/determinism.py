"""R9: Python-side nondeterminism in bit-identity-contracted code.

The resume and serve contracts promise BIT-IDENTICAL replays: the same
seed and step index must reproduce the same batch, augmentation, and
embedding. Python's global RNGs (`random.*`, `np.random.<fn>` on the
global state), wall-clock values flowing into computation, and
hash-order iteration silently break that — the run still "works", it
just can never be replayed, and pod replicas quietly diverge.

Allowed by design: explicitly seeded generator CONSTRUCTION
(`np.random.RandomState(seed)`, `np.random.default_rng(seed)`) — that is
the sanctioned deterministic pattern the loaders use; `time.perf_counter`
for telemetry (it measures, it never feeds values).
"""

from __future__ import annotations

import ast

from tools.mocolint.registry import Rule, register

_SEEDED_CTORS = {"RandomState", "default_rng", "Generator", "PCG64",
                 "SeedSequence"}
_TIME_VALUES = {"time", "time_ns"}


@register
class PythonNondeterminism(Rule):
    id = "R9"
    title = "no Python-side nondeterminism in bit-identity code"
    rationale = ("global-RNG draws, wall-clock values, and hash-order "
                 "iteration silently break the bit-identical resume/serve "
                 "replay guarantees")
    node_types = (ast.Call, ast.For, ast.comprehension)

    def visit(self, node, ctx):
        if isinstance(node, ast.Call):
            yield from self._check_call(node, ctx)
        else:
            iter_expr = node.iter
            yield from self._check_iteration(iter_expr, node, ctx)

    def _check_call(self, node, ctx):
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        base = func.value
        # random.<fn> on the module's hidden global state
        seeded = bool(node.args or node.keywords)  # seed=... counts too
        if isinstance(base, ast.Name) and base.id == "random":
            if func.attr == "Random" and seeded:
                return  # random.Random(seed): explicit, deterministic
            yield self.finding(
                ctx, node.lineno,
                f"`random.{func.attr}(...)` — the process-global RNG is "
                "unseeded, unshared across hosts, and consumed in "
                "whatever order threads race to it; derive values from "
                "the run seed (np.random.RandomState(seed) / "
                "jax.random.fold_in)",
            )
            return
        # np.random.<fn> on numpy's global state
        if (isinstance(base, ast.Attribute) and base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id in ("np", "numpy")):
            if func.attr in _SEEDED_CTORS and seeded:
                return  # explicitly seeded constructor: the sanctioned path
            yield self.finding(
                ctx, node.lineno,
                f"`np.random.{func.attr}(...)` — numpy's GLOBAL rng state; "
                "replays and pod replicas diverge. Construct "
                "np.random.RandomState(seed)/default_rng(seed) from the "
                "run seed instead",
            )
            return
        # time.time()/time_ns() producing a VALUE in contracted code
        if (isinstance(base, ast.Name) and base.id == "time"
                and func.attr in _TIME_VALUES):
            yield self.finding(
                ctx, node.lineno,
                f"`time.{func.attr}()` in bit-identity-contracted code — "
                "a wall-clock value can never replay; use the step index "
                "or the run seed for values (time.perf_counter is fine "
                "for telemetry durations)",
            )

    def _check_iteration(self, iter_expr, node, ctx):
        hazard = isinstance(iter_expr, ast.Set) or (
            isinstance(iter_expr, ast.Call)
            and isinstance(iter_expr.func, ast.Name)
            and iter_expr.func.id in ("set", "frozenset")
        )
        if hazard:
            yield self.finding(
                ctx, node.lineno if hasattr(node, "lineno")
                else iter_expr.lineno,
                "iteration over a set — order is hash-seed-dependent, so "
                "any value built from it differs across processes "
                "(PYTHONHASHSEED) and replays; sort it first "
                "(`sorted(...)`)",
            )
