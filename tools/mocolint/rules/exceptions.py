"""R1/R2: silent exception swallowing (migrated from the monolith).

The fault-tolerance subsystem only works if faults are VISIBLE: a bare
`except:` eats KeyboardInterrupt/SystemExit and hides the preemption
path; an `except Exception: pass` discards the very errors the
retry/rollback machinery routes on.
"""

from __future__ import annotations

import ast

from tools.mocolint.registry import Rule, register

BROAD = {"Exception", "BaseException"}


def _names(node: ast.expr | None):
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        return [n for elt in node.elts for n in _names(elt)]
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    return []


def _silent(body: list[ast.stmt]) -> bool:
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        for stmt in body
    )


@register
class BareExcept(Rule):
    id = "R1"
    title = "no bare `except:` handlers"
    rationale = ("a bare handler eats KeyboardInterrupt/SystemExit and "
                 "hides the preemption path")
    node_types = (ast.ExceptHandler,)

    def visit(self, node, ctx):
        if node.type is None:
            yield self.finding(
                ctx, node.lineno,
                "bare `except:` — name the exception types (a bare handler "
                "hides SIGINT and the preemption path)",
            )


@register
class BroadSilentSwallow(Rule):
    id = "R2"
    title = "no pass-only handlers over Exception/BaseException"
    rationale = ("swallowing EVERYTHING silently is never a policy; narrow "
                 "named exceptions stay legal")
    node_types = (ast.ExceptHandler,)

    def visit(self, node, ctx):
        if node.type is None:
            return
        caught = BROAD & set(_names(node.type))
        if caught and _silent(node.body):
            yield self.finding(
                ctx, node.lineno,
                f"`except {'/'.join(sorted(caught))}` with a pass-only body "
                "silently swallows every error — narrow the type or "
                "handle/log it",
            )
