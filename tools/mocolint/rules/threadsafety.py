"""R10: heuristic write-write race detector for thread-owning classes.

Scope: any class that spawns `threading.Thread(target=self.<method>)`.
Within it, the worker side is that target method plus everything it
reaches through `self.<m>()` calls; the public side is every other
method except `__init__` (which runs before the thread exists). An
attribute ASSIGNED on both sides is shared mutable state: every one of
its write sites must be lexically inside `with self.<lock>` (any self
attribute whose name contains lock/cond/mutex/sem) or it is a lost-update
race — exactly the convention serve/batcher.py pins with `self._cond`.

Deliberate limits (it is a heuristic, not an alias analysis): reads are
not tracked (torn reads of counters are tolerated by the telemetry
consumers), container mutation via method calls (`self._q.append`) is
not tracked (the stdlib deque/Queue are internally locked), and only
lexical `with` blocks count as holding the lock.
"""

from __future__ import annotations

import ast
import re

from tools.mocolint.astutil import call_name
from tools.mocolint.registry import Rule, register

_LOCKISH = re.compile(r"(lock|cond|mutex|sem)", re.IGNORECASE)


def _self_attr(expr) -> str | None:
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def _attr_writes(fn):
    """(attr, lineno, node) for every `self.X = ...` / `self.X += ...`
    in `fn`, including tuple-unpacking targets."""
    out = []
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                attr = _self_attr(e)
                if attr is not None:
                    out.append((attr, node.lineno, node))
    return out


def _locked(node, fn, parents) -> bool:
    """Is `node` lexically inside `with self.<lock-ish>` within `fn`?"""
    cur = parents.get(node)
    while cur is not None and cur is not fn:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                attr = _self_attr(expr)
                if attr is None and isinstance(expr, ast.Call):
                    attr = _self_attr(expr.func)
                if attr is not None and _LOCKISH.search(attr):
                    return True
        cur = parents.get(cur)
    return False


@register
class ThreadSharedWrites(Rule):
    id = "R10"
    title = "shared attributes of thread-owning classes write under a lock"
    rationale = ("an attribute assigned from both the worker thread and a "
                 "public method without the lock is a lost-update race "
                 "that only load reveals")
    node_types = (ast.ClassDef,)

    def visit(self, node, ctx):
        yield from self._check_class(node, ctx)

    def _check_class(self, cls, ctx):
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not methods:
            return
        roots = self._worker_roots(cls, methods)
        if not roots:
            return
        # closure over self.<m>() calls
        edges = {
            name: {
                call_name(c.func)
                for c in ast.walk(fn)
                if isinstance(c, ast.Call)
                and _self_attr(c.func) in methods
            }
            for name, fn in methods.items()
        }
        worker = set()
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            if m in worker:
                continue
            worker.add(m)
            frontier.extend(edges.get(m, set()) & set(methods) - worker)
        public = set(methods) - worker - {"__init__"}
        writes = {name: _attr_writes(fn) for name, fn in methods.items()}
        worker_attrs = {a for m in worker for a, _, _ in writes[m]}
        public_attrs = {a for m in public for a, _, _ in writes[m]}
        shared = worker_attrs & public_attrs
        if not shared:
            return
        for side, names in (("worker", worker), ("public", public)):
            for m in sorted(names):
                fn = methods[m]
                for attr, lineno, node in writes[m]:
                    if attr in shared and not _locked(node, fn, ctx.parents):
                        other = "a public method" if side == "worker" \
                            else "the worker thread"
                        yield self.finding(
                            ctx, lineno,
                            f"`self.{attr}` is written here ({side} method "
                            f"`{cls.name}.{m}`) and from {other} — both "
                            "sides race without `with self.<lock>` around "
                            "the write (lost updates under load)",
                        )

    def _worker_roots(self, cls, methods):
        roots = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call)
                    and call_name(node.func) == "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr in methods:
                        roots.add(attr)
        return roots
