"""R13: bank artifact writes go through the atomic temp+rename helpers.

The bank lifecycle (ISSUE 16) promises that a partially written artifact
is NEVER eligible for promotion: shard files, the merged bank npz, and
the manifest all land via temp-file + `os.replace`, with the manifest
written last. A bare `np.savez(...)`, `json.dump(...)`, or
`open(path, "w")` in the builder would reintroduce the torn-artifact
window the whole design exists to close — a watcher or a swapping
replica could read half a bank and promote it.

Scope (config): `moco_tpu/serve/bankbuild.py` + `tools/bank_build.py`.
Exempt: code inside the atomic helpers themselves (any function whose
name starts with `atomic_` or `_atomic`) — they ARE the temp+rename
machinery.
"""

from __future__ import annotations

import ast

from tools.mocolint.astutil import call_name, dotted
from tools.mocolint.registry import Rule, register

# call tails that write an artifact directly
_BANNED_TAILS = {"savez", "savez_compressed", "save", "dump"}
_WRITE_MODES = ("w", "a", "x")


def _opens_for_write(node: ast.Call) -> bool:
    if call_name(node.func) != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default "r": a read
    return (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
            and mode.value.startswith(_WRITE_MODES))


@register
class NonAtomicBankWrite(Rule):
    id = "R13"
    title = "bank artifact writes must use the atomic temp+rename helpers"
    rationale = ("a torn shard/bank/manifest written in place is a "
                 "promotable-looking artifact with wrong bytes; the "
                 "builder's whole crash-safety story is temp + os.replace "
                 "with the manifest last")

    def check_file(self, ctx):
        exempt_spans: list[tuple[int, int]] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name.lstrip("_").startswith("atomic_")):
                exempt_spans.append(
                    (node.lineno, getattr(node, "end_lineno", node.lineno))
                )
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            line = node.lineno
            if any(lo <= line <= hi for lo, hi in exempt_spans):
                continue
            name = dotted(node.func) or call_name(node.func) or ""
            tail = call_name(node.func)
            if tail in _BANNED_TAILS and "." in name:
                # np.savez / np.save / json.dump / pickle.dump — a direct
                # in-place artifact write (os.replace et al have no
                # banned tail, so plain calls pass untouched)
                yield self.finding(
                    ctx, line,
                    f"`{name}(...)` writes an artifact in place — a "
                    "crash mid-write leaves a torn file that looks "
                    "promotable; route it through the atomic_* "
                    "temp+rename helpers (manifest last)",
                )
            elif _opens_for_write(node):
                yield self.finding(
                    ctx, line,
                    "`open(..., \"w\"/\"a\"/\"x\")` writes in place in "
                    "the bank builder — use the atomic_* temp+rename "
                    "helpers so a partial artifact is never eligible "
                    "for promotion",
                )
