"""Committed baseline: grandfathered findings that don't fail the build.

A baseline entry is a FINGERPRINT, not a location: `path::rule::hash(message)`
with an occurrence count. Line numbers churn on every edit, so they are
deliberately absent — a finding is baselined if its file still contains no
MORE occurrences of that exact (rule, message) pair than the baseline
recorded. Fixing one occurrence shrinks the debt silently; introducing a
new one fails the build even in a file with grandfathered findings.

Format (JSON, stable key order so diffs are reviewable):

    {"version": 1, "findings": {"<fingerprint>": <count>, ...}}
"""

from __future__ import annotations

import hashlib
import json
import os


VERSION = 1


def _canon_path(path: str) -> str:
    """Spelling-independent path key: `moco_tpu/x.py`, `./moco_tpu/x.py`
    and the absolute form (from the working directory the baseline is
    used from — the repo root, for the committed one) all fingerprint
    identically."""
    return os.path.relpath(path).replace(os.sep, "/")


def fingerprint(finding) -> str:
    digest = hashlib.sha1(finding.message.encode("utf-8")).hexdigest()[:16]
    return f"{_canon_path(finding.path)}::{finding.rule}::{digest}"


def load(path: str) -> dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {VERSION}"
        )
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def write(path: str, findings) -> int:
    counts: dict[str, int] = {}
    for f in findings:
        key = fingerprint(f)
        counts[key] = counts.get(key, 0) + 1
    with open(path, "w", encoding="utf-8") as out:
        json.dump({"version": VERSION, "findings": dict(sorted(counts.items()))},
                  out, indent=2, sort_keys=False)
        out.write("\n")
    return len(findings)


def apply(findings, counts: dict[str, int]):
    """Split findings into (kept, baselined), consuming baseline budget in
    finding order."""
    budget = dict(counts)
    kept, baselined = [], []
    for f in findings:
        key = fingerprint(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            baselined.append(f)
        else:
            kept.append(f)
    return kept, baselined
