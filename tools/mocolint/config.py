"""Lint configuration: which rules run where, and the boundary graph.

Scoping is PATH-BASED with two pattern shapes, matching the conventions
the monolithic linter already used (so the shim is bit-compatible):

  - a pattern ending in "/" is a directory: it matches any file whose
    normalized path contains that directory segment
    ("moco_tpu/serve/" matches "/tmp/x/moco_tpu/serve/mod.py");
  - any other pattern is a file suffix: it matches a path that equals it
    or ends with "/" + it ("utils/logging.py").

Two stock configs ship:

  DEFAULT_CONFIG — what `python -m tools.mocolint` runs: all rules, with
    package-only scoping for the rules that guard package conventions
    (R3 print-discipline and R5 exit-codes are moco_tpu/ contracts; the
    CLI scripts under tools/ print and exit by design).
  LEGACY_CONFIG — exactly the monolithic tools/lint_robustness.py
    behavior: rules R1–R7 with their historical scoping, everywhere the
    caller points it. The shim and its pinned tests run this.
"""

from __future__ import annotations

import dataclasses
import os


def norm(path: str) -> str:
    return os.path.normpath(path).replace(os.sep, "/")


def path_matches(path: str, pattern: str) -> bool:
    p = norm(path)
    if pattern.endswith("/"):
        return ("/" + pattern) in ("/" + p)
    return p == pattern or p.endswith("/" + pattern)


@dataclasses.dataclass(frozen=True)
class RuleScope:
    """Empty include = everywhere; exclude always wins."""

    include: tuple[str, ...] = ()
    exclude: tuple[str, ...] = ()

    def contains(self, path: str) -> bool:
        if any(path_matches(path, pat) for pat in self.exclude):
            return False
        if not self.include:
            return True
        return any(path_matches(path, pat) for pat in self.include)


@dataclasses.dataclass(frozen=True)
class Boundary:
    """One entry of the import-boundary graph (rules R6/R11).

    `forbid` bans direct imports (module-level AND lazy) of the listed
    module prefixes from files in `scope`. With `transitive=True` the ban
    extends through module-level imports of in-repo modules: importing A
    which imports B which imports a forbidden module is a violation AT
    the original import site, reported with the chain.

    `stdlib_only=True` instead requires every direct import to be stdlib
    or begin with an `allow_prefixes` entry — and, transitively, every
    module-level import reachable through allowed in-repo modules too.

    `lazy_only` lists modules that must never be imported at module
    level in `scope` (function-local imports stay legal) — the orbax
    contract: the import cost/dependency is paid only on the code path
    that needs it.
    """

    name: str
    rule_id: str
    scope: tuple[str, ...]
    why: str
    forbid: tuple[str, ...] = ()
    transitive: bool = False
    stdlib_only: bool = False
    allow_prefixes: tuple[str, ...] = ()
    lazy_only: tuple[str, ...] = ()

    def in_scope(self, path: str) -> bool:
        return any(path_matches(path, pat) for pat in self.scope)


@dataclasses.dataclass(frozen=True)
class LintConfig:
    enabled: tuple[str, ...]
    scopes: dict          # rule id -> RuleScope (missing = everywhere)
    boundaries: tuple[Boundary, ...] = ()
    report_unused_suppressions: bool = True

    def rule_enabled(self, rule_id: str) -> bool:
        return rule_id in self.enabled

    def scope_for(self, rule_id: str) -> RuleScope:
        return self.scopes.get(rule_id, _EVERYWHERE)


_EVERYWHERE = RuleScope()

# R6's forbidden-module list: the serving runtime must stay train-free.
SERVE_FORBIDDEN = (
    "moco_tpu.train",
    "moco_tpu.train_step",
    "moco_tpu.train_state",
    "moco_tpu.v3_step",
    "optax",
    "moco_tpu.ops.schedules",
)

# Historical scoping of the monolithic linter, shared by both configs.
_R1_R7_SCOPES = {
    "R3": RuleScope(exclude=("utils/logging.py", "utils/meters.py")),
    # R4 (ISSUE 14): the input-service constructions must close in a
    # finally exactly like Prefetcher constructions; the implementation
    # modules themselves are excluded like data/loader.py always was —
    # they ARE the close machinery
    "R4": RuleScope(exclude=("data/loader.py",
                             "data/service/client.py",
                             "data/service/fleet.py")),
    "R6": RuleScope(include=("moco_tpu/serve/",)),
    "R7": RuleScope(exclude=("moco_tpu/parallel/",)),
}

_SERVE_BOUNDARY = Boundary(
    name="serve-train-free",
    rule_id="R6",
    scope=("moco_tpu/serve/",),
    forbid=SERVE_FORBIDDEN,
    why=("the serving runtime must stay import-light and train-free: a "
         "train dependency drags the optimizer stack into every serving "
         "process"),
)

LEGACY_CONFIG = LintConfig(
    enabled=("R1", "R2", "R3", "R4", "R5", "R6", "R7"),
    scopes=dict(_R1_R7_SCOPES),
    boundaries=(_SERVE_BOUNDARY,),
    report_unused_suppressions=False,
)

# Modules whose values are covered by a bit-identity contract (resume /
# staging / serve parity) — R9's scope. Python-side nondeterminism here
# breaks guarantees tests elsewhere pin. ISSUE 9 satellite: the scope
# covers the whole serve ENGINE SIDE (engine + content-hash cache +
# batcher + service), not just engine.py — batch composition and cache
# keys decide which program pads which rows, and the served-embedding
# bit-identity test only holds if none of it consults a global RNG or a
# wall clock. (parallel/ already covers gradsync.py.)
BIT_IDENTITY_MODULES = (
    "moco_tpu/train_step.py",
    "moco_tpu/v3_step.py",
    # ISSUE 13: the in-graph health diagnostics trace INTO the step
    # program — nondeterminism here would break the health-on == health-
    # off bitwise-trajectory contract the step tests pin
    "moco_tpu/telemetry/health.py",
    "moco_tpu/data/augment.py",
    "moco_tpu/data/loader.py",
    "moco_tpu/data/canvas_cache.py",
    "moco_tpu/data/datasets.py",
    "moco_tpu/serve/engine.py",
    "moco_tpu/serve/cache.py",
    "moco_tpu/serve/batcher.py",
    "moco_tpu/serve/service.py",
    # ISSUE 16: the bank builder's shard→merge output is test-pinned
    # bit-identical for any shard count — a global-RNG draw or wall-clock
    # value in the build path would break the 1-vs-3-shard byte equality
    "moco_tpu/serve/bankbuild.py",
    # ISSUE 20: the IVF index build is test-pinned byte-identical for
    # the same (bank, cells, seed) — the seeded k-means and the shard
    # search/vote must never consult a global RNG or wall clock
    "moco_tpu/serve/ann.py",
    "moco_tpu/ops/",
    "moco_tpu/parallel/",
)

# Modules that build jitted step programs — R8's scope (within which only
# traced-function bodies are checked).
STEP_BUILDER_MODULES = (
    "moco_tpu/train_step.py",
    "moco_tpu/v3_step.py",
    "moco_tpu/serve/engine.py",
    "moco_tpu/ops/",
    "moco_tpu/data/augment.py",
    "moco_tpu/telemetry/health.py",  # ISSUE 13: traced into the step —
                                     # a host sync here stalls EVERY step
    "moco_tpu/parallel/fsdp.py",     # ISSUE 15: gather/scatter trace into
                                     # the sharded step (R9 already covers
                                     # it via the parallel/ dir pattern)
)

DEFAULT_CONFIG = LintConfig(
    enabled=("R1", "R2", "R3", "R4", "R5", "R6", "R7",
             "R8", "R9", "R10", "R11", "R12", "R13"),
    scopes={
        **_R1_R7_SCOPES,
        # R13 (ISSUE 16): bank artifact writes go through the atomic
        # temp+rename helpers — torn artifacts must never look promotable
        # (ISSUE 20 extends the scope to the ANN index writer: a torn
        # ann.npz next to a good bank must never look loadable)
        "R13": RuleScope(include=("moco_tpu/serve/bankbuild.py",
                                  "moco_tpu/serve/ann.py",
                                  "tools/bank_build.py")),
        # R12 (ISSUE 8): span context-manager discipline package-wide +
        # the stdlib-only import diet of telemetry/trace.py (which the
        # rule applies only to that file)
        "R12": RuleScope(include=("moco_tpu/", "tools/", "bench.py")),
        # package contracts: the CLI scripts in tools/ print and exit(N)
        # by design, so the package-convention rules scope to moco_tpu/
        "R3": RuleScope(include=("moco_tpu/",),
                        exclude=("utils/logging.py", "utils/meters.py")),
        "R5": RuleScope(include=("moco_tpu/", "tools/supervise.py",
                                 "tools/serve.py", "tools/serve_fleet.py",
                                 "tools/bank_build.py")),
        # R6's historical scope is moco_tpu/serve/ (fleet.py rides along);
        # the fleet CLI lives in tools/ and must honor the same boundary
        "R6": RuleScope(include=("moco_tpu/serve/", "tools/serve_fleet.py",
                                 "tools/bank_build.py",
                                 "moco_tpu/data/service/",
                                 "tools/staging_server.py",
                                 "tools/prestage.py")),
        "R8": RuleScope(include=STEP_BUILDER_MODULES),
        "R9": RuleScope(include=BIT_IDENTITY_MODULES),
    },
    boundaries=(
        _SERVE_BOUNDARY,
        # ISSUE 10: the fleet CLI is serve-side code outside moco_tpu/serve/
        Boundary(
            name="fleet-cli-train-free",
            rule_id="R6",
            scope=("tools/serve_fleet.py",),
            forbid=SERVE_FORBIDDEN,
            why=("the fleet front end routes traffic for N serving "
                 "processes; a train dependency here couples the whole "
                 "fleet's availability to the training stack"),
        ),
        # ISSUE 16: the bank builder CLI re-embeds corpora for SERVING —
        # its orchestration must stay train-free like the serve stack
        # (the engine-import path is the only jax it may reach)
        Boundary(
            name="bank-build-train-free",
            rule_id="R6",
            scope=("tools/bank_build.py",),
            forbid=SERVE_FORBIDDEN,
            why=("the bank builder produces SERVING artifacts; a train "
                 "dependency would drag the optimizer stack into every "
                 "promotion job (and its batch-lane mode into fleets)"),
        ),
        Boundary(
            name="serve-train-free-transitive",
            rule_id="R11",
            scope=("moco_tpu/serve/", "tools/serve_fleet.py",
                   "tools/bank_build.py"),
            forbid=SERVE_FORBIDDEN,
            transitive=True,
            why=("an import CHAIN from serve/ to the train stack defeats "
                 "R6 exactly as a direct import would — the optimizer "
                 "lands in the serving process either way"),
        ),
        Boundary(
            name="fleet-stdlib-only",
            rule_id="R11",
            scope=("moco_tpu/serve/fleet.py", "tools/serve_fleet.py"),
            stdlib_only=True,
            allow_prefixes=("moco_tpu",),
            transitive=True,
            why=("the fleet supervisor+router is the LAST process standing "
                 "when replicas die — the supervisor contract (PR 4): it "
                 "must never import jax/numpy, directly or through a "
                 "moco_tpu module, so a poisoned compile cache or an OOM'd "
                 "runtime cannot take the routing tier down with the "
                 "replicas"),
        ),
        # ISSUE 12: obsd watches the fleet from outside — it must keep
        # answering /metrics while the runtimes it observes OOM or
        # crash-loop, so it obeys the same import diet as the supervisor
        Boundary(
            name="obsd-stdlib-only",
            rule_id="R11",
            scope=("moco_tpu/telemetry/aggregate.py", "tools/obsd.py"),
            stdlib_only=True,
            allow_prefixes=("moco_tpu",),
            transitive=True,
            why=("the metrics aggregator + SLO engine is the layer an "
                 "operator trusts DURING an incident: importing jax/numpy "
                 "(directly or through a moco_tpu module) would couple "
                 "its liveness to the exact runtimes whose failures it "
                 "exists to report"),
        ),
        Boundary(
            name="supervisor-stdlib-only",
            rule_id="R11",
            scope=("moco_tpu/resilience/supervisor.py", "tools/supervise.py"),
            stdlib_only=True,
            allow_prefixes=("moco_tpu",),
            transitive=True,
            why=("the out-of-process supervisor must survive exactly the "
                 "failures that kill jax (poisoned compile cache, OOM'd "
                 "runtime) — importing the stack it supervises couples "
                 "their fates"),
        ),
        # ISSUE 14: direct train-stack imports in the input service are
        # R6 findings (the transitive chains are the R11 twin below)
        Boundary(
            name="input-service-train-free-direct",
            rule_id="R6",
            scope=("moco_tpu/data/service/", "tools/staging_server.py",
                   "tools/prestage.py"),
            forbid=SERVE_FORBIDDEN,
            why=("the input service feeds training but must not import "
                 "it — N staging servers dragging the optimizer stack "
                 "would couple the input tier to the train stack"),
        ),
        # ISSUE 14: the staging-server control plane supervises numpy
        # decode workers from OUTSIDE their process — the PR 4 contract
        Boundary(
            name="staging-server-stdlib-only",
            rule_id="R11",
            scope=("moco_tpu/data/service/server.py",
                   "moco_tpu/data/service/fleet.py",
                   "moco_tpu/data/service/protocol.py",
                   "tools/staging_server.py"),
            stdlib_only=True,
            allow_prefixes=("moco_tpu",),
            transitive=True,
            why=("the staging-server supervisor half must outlive a "
                 "wedged or OOM'd decode runtime — it answers /healthz "
                 "503, classifies the death and relaunches; importing "
                 "jax/numpy (directly or through a moco_tpu module) "
                 "couples its fate to the worker it exists to restart"),
        ),
        # ISSUE 14: decode workers may import numpy, but never the train
        # stack — a staging fleet's availability must not depend on it
        Boundary(
            name="input-service-train-free",
            rule_id="R11",
            scope=("moco_tpu/data/service/", "tools/staging_server.py",
                   "tools/prestage.py"),
            forbid=SERVE_FORBIDDEN,
            transitive=True,
            why=("the input service feeds training but must not import "
                 "it: the optimizer stack in every staging server would "
                 "bloat N decode processes and couple their restarts to "
                 "the train stack (the R6 serve rule, applied to the "
                 "input side)"),
        ),
        # ISSUE 20: the ANN index layer is pure numpy by contract — a
        # jax import there would drag the runtime (and a compile cache)
        # into every shard-serving process and the index builder
        Boundary(
            name="ann-jax-free",
            rule_id="R6",
            scope=("moco_tpu/serve/ann.py",),
            forbid=SERVE_FORBIDDEN + ("jax", "flax"),
            why=("the IVF index builds from and serves numpy bank "
                 "artifacts; importing jax (let alone the train stack) "
                 "would couple every ANN shard replica and promotion "
                 "job to the runtime whose failures the serving tier "
                 "must survive"),
        ),
        Boundary(
            name="checkpoint-orbax-lazy",
            rule_id="R11",
            scope=("moco_tpu/checkpoint.py",),
            lazy_only=("orbax", "optax", "moco_tpu.train_state"),
            why=("checkpoint.py is also the inference-side loader (the "
                 "serve/ path): a module-level orbax/optax import drags "
                 "the training stack into every serving process"),
        ),
    ),
)
