"""Inline suppression comments.

Syntax, anywhere in a line:

    # mocolint: disable=R8            one rule
    # mocolint: disable=R8,R10        several
    # mocolint: disable=all           everything on the covered line

Coverage: a trailing comment (code before the `#`) covers findings on ITS
OWN line; a standalone comment line covers the NEXT line. That is the
whole contract — no block/file scopes, so every suppression sits beside
the code it excuses and carries its rationale in the same comment.

Suppressions that cover no finding are themselves reported (rule `SUP`):
a stale suppression is how a regressing rule goes quiet.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize

# rule list stops at the first token that is not `id` or `,` — trailing
# prose in the same comment is the rationale, not more ids
_PATTERN = re.compile(
    r"#\s*mocolint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclasses.dataclass
class Suppression:
    line: int            # line the comment sits on (1-based)
    covers: int          # line whose findings it suppresses
    rules: frozenset[str]  # rule ids, or {"all"}
    used: bool = False

    def matches(self, rule_id: str, line: int) -> bool:
        return line == self.covers and ("all" in self.rules
                                        or rule_id in self.rules)


def scan(source: str) -> list[Suppression]:
    """Real COMMENT tokens only (tokenize, not line regex): the syntax
    quoted inside a docstring — this package documents itself — must not
    create suppressions."""
    out: list[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # the engine reports the file as unparseable anyway
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PATTERN.search(tok.string)
        if not m:
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        if not rules:
            continue
        line = tok.start[0]
        standalone = tok.line.lstrip().startswith("#")
        out.append(Suppression(line=line, covers=line + 1 if standalone
                               else line, rules=rules))
    return out


def apply(findings, suppressions):
    """Split findings into (kept, suppressed), marking used suppressions."""
    kept, suppressed = [], []
    for f in findings:
        hit = None
        for s in suppressions:
            if s.matches(f.rule, f.line):
                hit = s
                break
        if hit is None:
            kept.append(f)
        else:
            hit.used = True
            suppressed.append(f)
    return kept, suppressed
