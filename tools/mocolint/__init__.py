"""mocolint — the repo's pluggable AST analysis engine (ISSUE 7).

One parse per file feeds every rule through a shared visitor dispatch;
rules are plugin classes in `tools/mocolint/rules/` registered by id.
Inline suppression (`# mocolint: disable=R8` — with unused-suppression
reporting), a committed baseline for grandfathered findings, and `--json`
machine output ride on top.

Entry points:

    python -m tools.mocolint moco_tpu tools bench.py      # CI gate
    python -m tools.mocolint --list-rules
    tools/lint_robustness.py                              # legacy shim

Rule ids: R1–R7 are the migrated robustness rules (behavior pinned by
tests/test_lint_robustness.py); R8–R11 are the JAX-aware hot-path,
nondeterminism, thread-safety, and import-boundary rules; PARSE marks
unparseable files; SUP marks unused suppressions.
"""

from tools.mocolint.config import DEFAULT_CONFIG, LEGACY_CONFIG  # noqa: F401
from tools.mocolint.engine import Engine, Result  # noqa: F401
from tools.mocolint.finding import Finding  # noqa: F401
from tools.mocolint.registry import all_rules  # noqa: F401

__version__ = "1.0.0"
