"""Rule registry: every rule is a plugin class registered by id.

A rule declares WHAT it checks (metadata: id, title, severity, rationale)
and implements up to three hooks, all generators of `Finding`:

  visit(node, ctx)      — called once per AST node whose type appears in
                          `node_types`, during the engine's single shared
                          walk of the file. The cheap common case.
  check_file(ctx)       — called once per in-scope file, after the walk.
                          For rules that need whole-file structure
                          (scopes, class shapes, traced-function closure).
  finalize(project)     — called once per RUN, after every file was
                          parsed. For cross-file rules (import graphs).

Rules are instantiated fresh per Engine run, so a rule may accumulate
state across visit()/check_file() calls and flush it in finalize().
"""

from __future__ import annotations

from tools.mocolint.finding import Finding


class Rule:
    """Base class; subclasses override the metadata and any hooks."""

    id: str = ""
    title: str = ""
    severity: str = "error"
    rationale: str = ""
    node_types: tuple = ()

    def visit(self, node, ctx):
        return ()

    def check_file(self, ctx):
        return ()

    def finalize(self, project):
        return ()

    # helper so rule bodies stay terse
    def finding(self, ctx, line: int, message: str, col: int = 0) -> Finding:
        return Finding(path=ctx.path, line=line, rule=self.id,
                       message=message, col=col, severity=self.severity)


_RULES: dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator: adds the rule to the global registry."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _RULES:
        raise ValueError(f"duplicate rule id {cls.id}")
    _RULES[cls.id] = cls
    return cls


def all_rules() -> dict[str, type]:
    """id -> class, after ensuring the built-in rule modules loaded."""
    import tools.mocolint.rules  # noqa: F401  (registration side effect)

    return dict(_RULES)
