import sys

from tools.mocolint.cli import main

sys.exit(main())
