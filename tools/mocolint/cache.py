"""Incremental per-file result cache (ISSUE 9 satellite).

The tier-1 repo gate runs mocolint over the whole tree; parsing and
walking ~120 files dominates its ~1 s. As the tree grows that cost grows
linearly — the cache keeps the warm path flat: each file's PER-FILE
results (visit/check_file findings, import edges, suppressions, module
name) are stored under its CONTENT hash, so an unchanged file skips
parse + walk entirely. Cross-file analysis (the R6/R11 boundary walks)
always re-runs, over slim contexts rebuilt from the cached import edges
— a change in module B must still surface a chain finding in untouched
module A, so chain findings are never cached.

Invalidation is hash-of-everything: the cache key folds in the content
hash AND an engine fingerprint covering the mocolint SOURCE itself plus
the active config/rule selection — editing any rule, the config, or the
engine silently invalidates every entry; no version constant to forget
to bump. Entries are one JSON file per source path under
`<cache_dir>/mocolint/` (the per-run cache dir convention:
utils/cache.per_run_cache_dir or any directory the caller owns).
"""

from __future__ import annotations

import hashlib
import json
import os

from tools.mocolint.finding import Finding
from tools.mocolint.suppress import Suppression

CACHE_SCHEMA = 1

_FP_CACHE: dict[str, str] = {}


def engine_fingerprint(config, rule_ids) -> str:
    """Hash of everything that can change a per-file verdict besides the
    file itself: the mocolint source tree, the config (scopes, boundaries,
    enabled set), and the active rule selection."""
    key = repr((sorted(rule_ids), config))
    if key in _FP_CACHE:
        return _FP_CACHE[key]
    h = hashlib.sha1()
    h.update(str(CACHE_SCHEMA).encode())
    root = os.path.dirname(os.path.abspath(__file__))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                with open(os.path.join(dirpath, fname), "rb") as f:
                    h.update(f.read())
    h.update(key.encode("utf-8", errors="replace"))
    fp = h.hexdigest()
    _FP_CACHE[key] = fp
    return fp


class SlimContext:
    """A cached file's stand-in for FileContext in cross-file analysis:
    everything finalize()-stage rules read (path/norm/module/imports/
    suppressions), nothing that needs a parse (tree/parents/source)."""

    def __init__(self, path, norm, module, imports, suppressions):
        self.path = path
        self.norm = norm
        self.module = module
        self.imports = imports
        self.suppressions = suppressions


class ResultCache:
    def __init__(self, cache_dir: str):
        self.dir = os.path.join(cache_dir, "mocolint")
        os.makedirs(self.dir, exist_ok=True)

    def _entry_path(self, norm_path: str) -> str:
        name = hashlib.sha1(norm_path.encode("utf-8",
                                             errors="replace")).hexdigest()
        return os.path.join(self.dir, f"{name}.json")

    @staticmethod
    def content_hash(source: str) -> str:
        return hashlib.sha1(source.encode("utf-8",
                                          errors="replace")).hexdigest()

    def load(self, path: str, norm: str, content_hash: str,
             engine_fp: str):
        """(SlimContext, findings) for an unchanged file, else None."""
        try:
            with open(self._entry_path(norm), encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError):
            return None
        if (data.get("schema") != CACHE_SCHEMA
                or data.get("hash") != content_hash
                or data.get("engine") != engine_fp):
            return None
        try:
            from tools.mocolint.engine import ImportEdge

            imports = [ImportEdge(**e) for e in data["imports"]]
            sups = [Suppression(line=s["line"], covers=s["covers"],
                                rules=frozenset(s["rules"]))
                    for s in data["suppressions"]]
            findings = [Finding(path=path, **{k: v for k, v in f.items()})
                        for f in data["findings"]]
        except (KeyError, TypeError):
            return None
        ctx = SlimContext(path, norm, data.get("module"), imports, sups)
        return ctx, findings

    def store(self, ctx, findings, content_hash: str,
              engine_fp: str) -> None:
        """Persist one parsed file's per-file results. Findings drop their
        `path` (re-attached at load with the caller's spelling, which the
        shim contract preserves verbatim)."""
        data = {
            "schema": CACHE_SCHEMA,
            "hash": content_hash,
            "engine": engine_fp,
            "module": ctx.module,
            "imports": [
                {"module": e.module, "line": e.line, "lazy": e.lazy,
                 "type_checking": e.type_checking}
                for e in ctx.imports
            ],
            "suppressions": [
                {"line": s.line, "covers": s.covers,
                 "rules": sorted(s.rules)}
                for s in ctx.suppressions
            ],
            "findings": [
                {"line": f.line, "rule": f.rule, "message": f.message,
                 "col": f.col, "severity": f.severity}
                for f in findings
            ],
        }
        tmp = self._entry_path(ctx.norm) + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f)
            os.replace(tmp, self._entry_path(ctx.norm))
        except OSError:
            # a read-only or full cache dir silently degrades to cold runs
            try:
                os.remove(tmp)
            except OSError:
                pass
