"""mocolint CLI.

    python -m tools.mocolint [paths...]            # default: moco_tpu
        --json              machine output (schema below)
        --baseline PATH     subtract grandfathered findings
        --write-baseline PATH   snapshot current findings and exit 0
        --select R8,R10     run only these rules
        --list-rules        print the rule table and exit

Exit codes: 0 clean, 1 findings, 2 usage/config error.

JSON schema (version 1):
    {"version": 1, "tool": "mocolint", "files_scanned": N,
     "findings": [{"path","line","col","rule","severity","message"}...],
     "suppressed": N, "baselined": N}
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _bootstrap_path() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo not in sys.path:
        sys.path.insert(0, repo)


def main(argv: list[str] | None = None) -> int:
    _bootstrap_path()
    from tools.mocolint.config import DEFAULT_CONFIG
    from tools.mocolint.engine import Engine
    from tools.mocolint.registry import all_rules

    parser = argparse.ArgumentParser(
        prog="mocolint", description="moco_tpu static analysis")
    parser.add_argument("paths", nargs="*", default=None)
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--baseline", default=None)
    parser.add_argument("--write-baseline", default=None)
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids")
    parser.add_argument("--cache", default=None, metavar="DIR",
                        help="incremental per-file result cache dir "
                             "(content-hash keyed; unchanged files skip "
                             "parse+walk)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, cls in sorted(all_rules().items(),
                               key=lambda kv: (len(kv[0]), kv[0])):
            print(f"{rid:<4} [{cls.severity}] {cls.title}")
            print(f"     why: {cls.rationale}")
        return 0

    paths = args.paths or ["moco_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"mocolint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    select = None
    if args.select:
        select = tuple(s.strip() for s in args.select.split(",") if s.strip())
        unknown = [s for s in select if s not in all_rules()]
        if unknown:
            print(f"mocolint: unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    engine = Engine(DEFAULT_CONFIG, select=select)
    if args.write_baseline:
        result = engine.run(paths, baseline_path=None)
        from tools.mocolint import baseline as baseline_mod
        n = baseline_mod.write(args.write_baseline, result.findings)
        print(f"wrote baseline of {n} finding(s) to {args.write_baseline}")
        return 0

    try:
        result = engine.run(paths, baseline_path=args.baseline,
                            cache_dir=args.cache)
    except (OSError, ValueError) as e:
        print(f"mocolint: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "tool": "mocolint",
            "files_scanned": result.files_scanned,
            "findings": [f.json_obj() for f in result.findings],
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
        }, indent=2))
        return 1 if result.findings else 0

    for f in result.findings:
        print(f.human())
    tail = []
    if result.suppressed:
        tail.append(f"{len(result.suppressed)} suppressed")
    if result.baselined:
        tail.append(f"{len(result.baselined)} baselined")
    if result.files_cached:
        tail.append(f"{result.files_cached} cached")
    suffix = f" ({', '.join(tail)})" if tail else ""
    if result.findings:
        print(f"{len(result.findings)} finding(s) in "
              f"{result.files_scanned} file(s){suffix}")
        return 1
    print(f"mocolint clean: {result.files_scanned} file(s){suffix}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
