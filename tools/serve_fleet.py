#!/usr/bin/env python
"""Run a replicated serve fleet: N serve.py replicas behind one router
(ISSUE 10).

    python tools/serve_fleet.py --replicas 2 --port 8080 \
        --telemetry-dir runs/fleet --watch-dir runs/export -- \
        python tools/serve.py --pretrained runs/encoder.npz --arch resnet50

Everything after `--` is ONE replica's base command; the fleet appends
`--port <p>` and `--telemetry-dir <dir>/replica<i>` per replica (and,
after a hot reload, `--pretrained <newest verified payload>` so a
relaunched replica boots on the deployed weights). The front-end router
serves `POST /v1/embed` / `POST /v1/knn` (health-routed least-outstanding
with single-retry), `GET /healthz`, `GET /stats`; replica `/admin/*`
stays on the replicas' own ports, never proxied.

Signals: SIGTERM/SIGINT drain the whole fleet (replicas finish accepted
work) and exit 0; a second signal exits immediately. SIGHUP triggers a
drain-aware ROLLING restart that never takes capacity below N−1.

`--watch-dir` arms the hot-reload watcher: new integrity-manifested
steps are verified, corrupt ones quarantined to `.quarantine/`, and
verified ones rolled across the fleet via each replica's
`POST /admin/reload` — zero dropped requests.

`--ann-shards N` (ISSUE 20) partitions a bank's IVF index across the
fleet: replica i serves cell partition i%N (`--ann-shard`/`--ann-shards`
appended to its command) and the router scatter-gathers `/v1/knn`
across one healthy owner per shard, merging top-k under the request's
deadline — shards that miss it are dropped and the answer is flagged
`partial: true`. `--autoscale-max > 0` arms the telemetry-driven
autoscaler: sustained shed/depth/p99 breaches in the router_stats
stream spawn replicas up to the budget, sustained idle drains-then-
reaps down to max(--autoscale-min, shard cover).

`--chaos`/`--chaos-replica` install a drill fault (e.g.
`kill_at_request=200`, `wedge_at_request=200`) on ONE replica via
MOCO_TPU_CHAOS, with fire-once state persisted per replica dir so the
restarted replica doesn't re-fire the drill.

Pure stdlib — this process must outlive replicas that OOM or segfault
(mocolint R11 pins the import diet, transitively).

Exit codes (README table): 0 clean drain · 45 bad flags · 48 could not
bind the router host:port · 1 every replica abandoned.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.resilience.exitcodes import (  # noqa: E402
    EXIT_CONFIG_ERROR,
    EXIT_FLEET_BIND,
    EXIT_OK,
)
from moco_tpu.serve.fleet import (  # noqa: E402
    FleetLaunchError,
    FleetPolicy,
    FleetSupervisor,
)
from moco_tpu.utils.logging import info  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="front-end router port (0 = ephemeral, printed)")
    p.add_argument("--base-port", type=int, default=0,
                   help="replica i binds base-port+i; 0 picks free "
                        "ephemeral ports")
    p.add_argument("--telemetry-dir", required=True,
                   help="fleet events.jsonl + per-replica dirs live here")
    p.add_argument("--watch-dir", default="",
                   help="checkpoint export dir to watch for hot reloads "
                        "(PR 1 step layout + integrity manifests)")
    p.add_argument("--bank-dir", default="",
                   help="versioned kNN-bank dir (tools/bank_build.py "
                        "layout): a watched step deploys ONLY with its "
                        "verifying paired bank, rolled as an atomic "
                        "(engine, bank) dual swap (ISSUE 16)")
    p.add_argument("--probe-secs", type=float, default=1.0)
    p.add_argument("--probe-timeout-s", type=float, default=2.0)
    p.add_argument("--health-stale-secs", type=float, default=10.0,
                   help="kill a replica whose newest probe answer is "
                        "older than this (accepting-but-not-answering "
                        "wedge)")
    p.add_argument("--startup-grace-secs", type=float, default=300.0,
                   help="launch -> first healthy probe allowance (cold "
                        "jax import + bucket-ladder compile)")
    p.add_argument("--term-grace-secs", type=float, default=15.0)
    p.add_argument("--max-restarts", type=int, default=5,
                   help="consecutive never-healthy deaths per replica "
                        "before abandoning it (a healthy life refunds)")
    p.add_argument("--backoff-base-secs", type=float, default=0.5)
    p.add_argument("--backoff-max-secs", type=float, default=30.0)
    p.add_argument("--backoff-jitter", type=float, default=0.2)
    p.add_argument("--request-timeout-s", type=float, default=30.0,
                   help="router default per-request deadline (a body "
                        "deadline_ms wins)")
    p.add_argument("--watch-poll-secs", type=float, default=1.0)
    p.add_argument("--reload-timeout-s", type=float, default=300.0)
    p.add_argument("--stats-every-secs", type=float, default=30.0,
                   help="router_stats emit cadence — the autoscaler/obsd "
                        "input stream (cumulative per-code sheds, "
                        "outstanding depth, latency p50/p95/p99)")
    p.add_argument("--ann-shards", type=int, default=0,
                   help="ANN cell partitions (ISSUE 20): replica i "
                        "serves shard i%%N of the bank's IVF index and "
                        "the router scatter-gathers /v1/knn; 0 = every "
                        "replica answers exact/full-index kNN alone. "
                        "Requires --replicas >= this and replica "
                        "commands with --ann-cells")
    p.add_argument("--autoscale-max", type=int, default=0,
                   help="replica budget for telemetry-driven "
                        "autoscaling; 0 disables the autoscaler")
    p.add_argument("--autoscale-min", type=int, default=1,
                   help="never reap below this many replicas (ANN "
                        "shard cover raises the effective floor)")
    p.add_argument("--autoscale-cooldown-s", type=float, default=60.0,
                   help="minimum gap between scale actions")
    p.add_argument("--autoscale-up-after", type=int, default=2,
                   help="consecutive breached stats windows before a "
                        "scale-up")
    p.add_argument("--autoscale-down-after", type=int, default=6,
                   help="consecutive idle stats windows before a "
                        "drain-then-reap")
    p.add_argument("--autoscale-shed-high", type=float, default=0.02,
                   help="windowed shed-rate breach threshold")
    p.add_argument("--autoscale-outstanding-high", type=float,
                   default=4.0,
                   help="in-flight depth per healthy replica breach "
                        "threshold")
    p.add_argument("--autoscale-p99-high-ms", type=float, default=0.0,
                   help="p99 latency breach threshold in ms; 0 disables "
                        "the latency trigger")
    p.add_argument("--autoscale-idle-low", type=float, default=0.25,
                   help="depth per healthy replica below this (with "
                        "zero sheds) counts as an idle window")
    p.add_argument("--chaos", default="",
                   help="drill fault spec for ONE replica, e.g. "
                        "kill_at_request=200 (see resilience/chaos.py)")
    p.add_argument("--chaos-replica", type=int, default=0,
                   help="which replica gets --chaos")
    p.add_argument("replica_cmd", nargs=argparse.REMAINDER,
                   help="-- then one replica's base command")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    cmd = args.replica_cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        info("config error: no replica command given (append `-- python "
             "tools/serve.py --pretrained ...`)")
        return EXIT_CONFIG_ERROR
    if args.replicas < 1:
        info(f"config error: --replicas must be >= 1, got {args.replicas}")
        return EXIT_CONFIG_ERROR
    if args.ann_shards < 0:
        info(f"config error: --ann-shards must be >= 0, "
             f"got {args.ann_shards}")
        return EXIT_CONFIG_ERROR
    if args.ann_shards and args.replicas < args.ann_shards:
        info(f"config error: --ann-shards {args.ann_shards} needs at "
             f"least that many replicas to cover every cell partition, "
             f"got --replicas {args.replicas}")
        return EXIT_CONFIG_ERROR
    if args.autoscale_max:
        if args.autoscale_min < 1:
            info(f"config error: --autoscale-min must be >= 1, "
                 f"got {args.autoscale_min}")
            return EXIT_CONFIG_ERROR
        if args.autoscale_max < max(args.autoscale_min, args.replicas):
            info(f"config error: --autoscale-max {args.autoscale_max} "
                 f"below max(--autoscale-min {args.autoscale_min}, "
                 f"--replicas {args.replicas})")
            return EXIT_CONFIG_ERROR
        if args.autoscale_cooldown_s < 0:
            info("config error: --autoscale-cooldown-s must be >= 0")
            return EXIT_CONFIG_ERROR
        if args.autoscale_up_after < 1 or args.autoscale_down_after < 1:
            info("config error: --autoscale-up-after and "
                 "--autoscale-down-after must be >= 1")
            return EXIT_CONFIG_ERROR

    def child_argv(index: int, port: int, telemetry_dir: str,
                   pretrained: str | None,
                   bank: str | None = None,
                   shard: int | None = None) -> list:
        out = list(cmd) + ["--port", str(port),
                           "--telemetry-dir", telemetry_dir]
        if pretrained:
            # argparse last-wins: this overrides the base command's
            # --pretrained so a relaunch boots on the deployed weights
            out += ["--pretrained", pretrained]
        if bank:
            # dual-swap fleets (ISSUE 16): pin the deployed bank too —
            # a relaunch must boot on the (weights, bank) PAIR, never
            # new weights over the boot-time bank
            out += ["--knn-bank", bank]
        if shard is not None and args.ann_shards:
            # sharded ANN (ISSUE 20): pin the replica's cell partition
            # so a relaunch comes back serving ITS shard
            out += ["--ann-shard", str(shard),
                    "--ann-shards", str(args.ann_shards)]
        return out

    replica_env = {}
    if args.chaos:
        replica_env[args.chaos_replica] = {
            "MOCO_TPU_CHAOS": args.chaos,
            "MOCO_TPU_CHAOS_STATE": os.path.join(
                args.telemetry_dir, f"replica{args.chaos_replica}",
                "chaos_state",
            ),
        }

    policy = FleetPolicy(
        probe_secs=args.probe_secs,
        probe_timeout_s=args.probe_timeout_s,
        health_stale_secs=args.health_stale_secs,
        startup_grace_secs=args.startup_grace_secs,
        term_grace_secs=args.term_grace_secs,
        max_restarts=args.max_restarts,
        backoff_base_secs=args.backoff_base_secs,
        backoff_max_secs=args.backoff_max_secs,
        backoff_jitter=args.backoff_jitter,
        request_timeout_s=args.request_timeout_s,
        watch_poll_secs=args.watch_poll_secs,
        reload_timeout_s=args.reload_timeout_s,
        stats_every_secs=args.stats_every_secs,
        autoscale_min=args.autoscale_min,
        autoscale_max=args.autoscale_max,
        autoscale_cooldown_s=args.autoscale_cooldown_s,
        autoscale_up_after=args.autoscale_up_after,
        autoscale_down_after=args.autoscale_down_after,
        autoscale_shed_high=args.autoscale_shed_high,
        autoscale_outstanding_high=args.autoscale_outstanding_high,
        autoscale_p99_high_ms=args.autoscale_p99_high_ms,
        autoscale_idle_low=args.autoscale_idle_low,
    )
    fleet = FleetSupervisor(
        child_argv,
        replicas=args.replicas,
        telemetry_dir=args.telemetry_dir,
        host=args.host,
        router_port=args.port,
        base_port=args.base_port,
        policy=policy,
        watch_dir=args.watch_dir,
        bank_dir=args.bank_dir,
        replica_env=replica_env,
        ann_shards=args.ann_shards,
    )
    try:
        fleet.start()
    except FleetLaunchError as e:
        # the replica COMMAND can't spawn: the same argv can never
        # succeed — config error, NOT the reschedule-semantics 48
        info(f"config error: {e}")
        return EXIT_CONFIG_ERROR
    except OSError as e:
        info(f"cannot bind the fleet router {args.host}:{args.port}: {e}")
        return EXIT_FLEET_BIND

    if hasattr(signal, "SIGHUP"):
        signal.signal(
            signal.SIGHUP,
            lambda signum, frame: fleet.request_rolling_restart(),
        )

    from moco_tpu.resilience.preemption import PreemptionHandler

    with PreemptionHandler() as pre:
        info(
            f"fleet serving on {fleet.router.url} "
            f"({args.replicas} replicas on ports "
            f"{[r.port for r in fleet.replicas]}; SIGHUP = rolling "
            f"restart)"
        )
        while not pre.triggered and not fleet.failed:
            time.sleep(0.2)
    fleet.stop()
    if fleet.failed:
        info("fleet failed: every replica abandoned")
        return 1
    info("fleet drained cleanly")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
