#!/usr/bin/env python
"""bench_gate — fail loudly when a fresh BENCH record regresses the
committed trajectory (ISSUE 12).

    python bench.py | tee bench_out.txt
    python tools/bench_gate.py bench_out.txt          # vs BENCH_r*.json
    python tools/bench_gate.py --self-test            # replay r01..r05

The repo commits one `BENCH_r<k>.json` per round (`{"n", "cmd", "rc",
"tail", "parsed"}` — the driver's wrapper around bench.py's stdout).
Until now nothing COMPARED consecutive rounds: a 20% throughput drop
lands as just another number and drifts silently. This gate:

  - flattens every metric-bearing JSON line of a record's output into
    `{metric_key: value}` (the headline `value`, folded `input.value` as
    `<metric>/input`, the `e2e` record under its own metric name, and
    `final_loss` as `<metric>/final_loss`). Noisy per-thread `detail`
    rows are deliberately NOT gated (PR 3 measured them swinging 2× with
    container core allocation) — they are counted and noted.
  - for each fresh key, finds the NEWEST committed record carrying the
    same key (rounds change metric names when the environment degrades —
    a tiny-CPU-proxy number must never be compared against an 8-chip
    one) and applies a per-metric tolerance: throughput-like keys may
    drop at most `--tolerance` (default 25% — sandbox container variance
    is real; see BENCH_r04 vs r01), `final_loss` may rise at most
    `--loss-tolerance` (default 10%).
  - rounds whose `parsed` is null (rc!=0 — an infra failure, e.g. r02's
    dead TPU backend, r03's rc=124 timeout) contribute no baselines and,
    in the self-test, are skipped: an infra-failed round records an
    outage, not a perf claim. A FRESH record that failed is still a gate
    FAILURE (`--allow-failed` opts out for degraded environments).

`--self-test` replays the committed trajectory in order (each round
gated against all earlier ones) and exits 1 on any false regression —
the tier-1 pin that keeps the default tolerances honest against real
history.

Exit codes: 0 pass · 1 regression (or failed fresh bench) · 2 usage.
Pure stdlib; also importable (`gate_record`) by bench.py's `--gate`.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAJECTORY_GLOB = "BENCH_r*.json"

# metric-key suffixes that are LOWER-better; everything else is a
# throughput-like higher-better number
_LOWER_BETTER = ("/final_loss",)

DEFAULT_TOLERANCE = 0.25       # allowed relative drop (higher-better)
DEFAULT_LOSS_TOLERANCE = 0.10  # allowed relative rise (lower-better)

# metric-key suffixes gated against an ABSOLUTE cap instead of the
# trajectory: overhead shares hover near zero, where a relative
# tolerance would flap on measurement noise (0.02% vs 0.04% is "2×")
# while the contract is the absolute bound. health_overhead (ISSUE 13):
# amortized in-graph diagnostics cost must stay under 1% of step p50 at
# the default stride.
_ABSOLUTE_CAPS = {"/health_overhead_pct": 1.0}


def _iter_metric_records(source) -> list[dict]:
    """Every metric-bearing JSON object in a bench output. `source` is a
    BENCH wrapper dict, a bare parsed record, or raw stdout text."""
    if isinstance(source, dict):
        if "metric" in source:
            return [source]
        records = []
        tail = source.get("tail")
        if isinstance(tail, str):
            records.extend(_iter_metric_records(tail))
        parsed = source.get("parsed")
        if (isinstance(parsed, dict) and "metric" in parsed
                and parsed["metric"] not in
                {r["metric"] for r in records}):
            # the wrapper's parsed IS the tail's last line; include it
            # only when a truncated tail lost that line
            records.append(parsed)
        return records
    records = []
    for line in str(source).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            records.append(rec)
    return records


def _fold_service_rows(container: dict, fallback_name: str,
                       flat: dict) -> int:
    """Fold the ISSUE 14 service/prestage e2e rows of one record (or of
    its nested `e2e` dict) into `flat`; they gate under their own metric
    names once a round carries them. Returns the number of per-server
    `detail` rows excluded (the same rule as per-thread rows)."""
    details = 0
    for sub in ("service", "prestage"):
        s = container.get(sub)
        if not isinstance(s, dict):
            continue
        sv = s.get("value")
        sname = str(s.get("metric", f"{fallback_name}/{sub}"))
        if isinstance(sv, (int, float)) and sv > 0:
            flat[sname] = float(sv)
        details += len(s.get("detail") or ())
    return details


def flatten(source) -> tuple[dict, int]:
    """(metric_key -> value, skipped_detail_rows). Later records win on
    key collision (bench.py prints provisional lines first and the
    consolidated record LAST — the same convention every consumer
    applies)."""
    flat: dict[str, float] = {}
    details = 0
    for rec in _iter_metric_records(source):
        name = str(rec["metric"])
        value = rec.get("value")
        if isinstance(value, (int, float)) and value > 0:
            flat[name] = float(value)
        loss = rec.get("final_loss")
        if isinstance(loss, (int, float)):
            flat[f"{name}/final_loss"] = float(loss)
        inp = rec.get("input")
        if isinstance(inp, dict):
            v = inp.get("value")
            if isinstance(v, (int, float)) and v > 0:
                flat[f"{name}/input"] = float(v)
            details += len(inp.get("detail") or ())
        e2e = rec.get("e2e")
        if isinstance(e2e, dict):
            v = e2e.get("value")
            ename = str(e2e.get("metric", f"{name}/e2e"))
            if isinstance(v, (int, float)) and v > 0:
                flat[ename] = float(v)
            details += _fold_service_rows(e2e, ename, flat)
        # same rows when flatten is fed the e2e CHILD's own record (the
        # consolidated BENCH wrapper nests them under "e2e" instead)
        details += _fold_service_rows(rec, name, flat)
        ho = rec.get("health_overhead")
        if isinstance(ho, dict):
            v = ho.get("overhead_pct_of_step_p50")
            if isinstance(v, (int, float)) and v >= 0:
                flat[f"{name}/health_overhead_pct"] = float(v)
        sh = rec.get("sharding")
        if isinstance(sh, dict):
            # per-sharding-mode v3 rows (ISSUE 15): each mode gates under
            # its own metric name once a round carries it — skipped/error
            # rows (degraded sweep) carry no number and fold to nothing
            for mode, row in sorted(sh.items()):
                if not isinstance(row, dict):
                    continue
                v = row.get("imgs_per_sec_per_chip")
                if isinstance(v, (int, float)) and v > 0:
                    flat[f"{name}/sharding/{mode}"] = float(v)
    return flat, details


def load_trajectory(pattern: str | None = None) -> list[tuple[str, dict]]:
    """[(round_name, wrapper_dict)] sorted by round number then name —
    oldest first. Unreadable files are skipped (a gate must judge perf,
    not the repo's file hygiene)."""
    pattern = pattern or os.path.join(REPO_ROOT, TRAJECTORY_GLOB)
    entries = []
    for path in globlib.glob(pattern):
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(data, dict):
            entries.append((os.path.basename(path), data))

    def key(entry):
        m = re.search(r"(\d+)", entry[0])
        return (int(m.group(1)) if m else 0, entry[0])

    return sorted(entries, key=key)


def load_trajectory_flats(pattern: str | None = None) -> list[tuple[str, dict]]:
    """The trajectory as gate_record wants it: [(round_name, flat)]
    oldest first, infra-failed (metric-less) rounds dropped — ONE place
    for that rule, shared by this CLI and `bench.py --gate`."""
    flats = [(name, flatten(wrapper)[0])
             for name, wrapper in load_trajectory(pattern)]
    return [(name, flat) for name, flat in flats if flat]


def gate_record(fresh_flat: dict, trajectory_flats: list[tuple[str, dict]],
                *, tolerance: float = DEFAULT_TOLERANCE,
                loss_tolerance: float = DEFAULT_LOSS_TOLERANCE,
                overrides: dict | None = None) -> dict:
    """Compare one flattened record against the flattened trajectory
    (oldest first). Returns the verdict dict (the --json payload):
    `regressions` non-empty == gate failure. `overrides` maps metric_key
    -> tolerance fraction."""
    overrides = overrides or {}
    regressions, improvements, passes, new_metrics = [], [], [], []
    for key, value in sorted(fresh_flat.items()):
        cap = next((c for suffix, c in _ABSOLUTE_CAPS.items()
                    if key.endswith(suffix)), None)
        if cap is not None:
            # absolute-cap metric: the bound IS the contract — no
            # trajectory baseline needed (and the cap never loosens just
            # because a committed round measured close to it)
            cap = overrides.get(key, cap)
            entry = {"metric": key, "value": value, "cap": cap}
            (regressions if value > cap else passes).append(entry)
            continue
        baseline = None
        for round_name, flat in reversed(trajectory_flats):
            if key in flat:
                baseline = (round_name, flat[key])
                break
        if baseline is None:
            new_metrics.append(key)
            continue
        round_name, base = baseline
        lower_better = key.endswith(_LOWER_BETTER)
        tol = overrides.get(
            key, loss_tolerance if lower_better else tolerance)
        entry = {
            "metric": key,
            "value": value,
            "baseline": base,
            "baseline_round": round_name,
            "tolerance": tol,
            "ratio": round(value / base, 4) if base else None,
        }
        if lower_better:
            if value > base * (1.0 + tol):
                regressions.append(entry)
            elif value < base:
                improvements.append(entry)
            else:
                passes.append(entry)
        else:
            if value < base * (1.0 - tol):
                regressions.append(entry)
            elif value > base:
                improvements.append(entry)
            else:
                passes.append(entry)
    return {
        "compared": len(regressions) + len(improvements) + len(passes),
        "regressions": regressions,
        "improvements": improvements,
        "passes": passes,
        "new_metrics": new_metrics,
    }


def self_test(pattern: str | None = None, *,
              tolerance: float = DEFAULT_TOLERANCE,
              loss_tolerance: float = DEFAULT_LOSS_TOLERANCE) -> dict:
    """Replay the committed trajectory: every non-null round gated
    against all earlier rounds. Returns {"rounds": [...], "regressions":
    N, "compared": N, "skipped": [names]} — regressions must be 0 for
    the committed history (the tier-1 pin)."""
    trajectory = load_trajectory(pattern)
    if not trajectory:
        raise FileNotFoundError(
            f"no trajectory records match "
            f"{pattern or os.path.join(REPO_ROOT, TRAJECTORY_GLOB)}"
        )
    flats: list[tuple[str, dict]] = []
    rounds, skipped = [], []
    compared = regressions = 0
    for name, wrapper in trajectory:
        flat, _ = flatten(wrapper)
        if not flat:
            skipped.append(name)  # infra-failed round: an outage record,
            continue              # not a perf claim — never a baseline
        if flats:
            verdict = gate_record(flat, flats, tolerance=tolerance,
                                  loss_tolerance=loss_tolerance)
            rounds.append({"round": name, **{
                k: verdict[k] for k in ("compared", "regressions",
                                        "improvements", "new_metrics")
            }})
            compared += verdict["compared"]
            regressions += len(verdict["regressions"])
        flats.append((name, flat))
    return {"rounds": rounds, "compared": compared,
            "regressions": regressions, "skipped": skipped,
            "usable_rounds": len(flats)}


def _parse_overrides(pairs) -> dict:
    overrides = {}
    for pair in pairs or ():
        key, sep, frac = pair.partition("=")
        if not sep:
            raise ValueError(f"--tolerance-for needs KEY=FRACTION, "
                             f"got {pair!r}")
        overrides[key] = float(frac)
    return overrides


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("fresh", nargs="?",
                        help="fresh bench evidence: bench.py stdout "
                             "(text), a BENCH_r*.json wrapper, or '-' "
                             "for stdin")
    parser.add_argument("--trajectory", default="",
                        help="baseline glob (default: repo BENCH_r*.json)")
    parser.add_argument("--tolerance", type=float,
                        default=DEFAULT_TOLERANCE,
                        help="allowed relative DROP for throughput-like "
                             "metrics")
    parser.add_argument("--loss-tolerance", type=float,
                        default=DEFAULT_LOSS_TOLERANCE,
                        help="allowed relative RISE for final_loss")
    parser.add_argument("--tolerance-for", action="append", metavar="K=F",
                        help="per-metric override, e.g. "
                             "moco_v2_r50_pretrain_throughput_per_chip=0.1")
    parser.add_argument("--allow-failed", action="store_true",
                        help="do not fail the gate when the fresh bench "
                             "itself produced no metrics")
    parser.add_argument("--self-test", action="store_true",
                        help="replay the committed trajectory; exit 1 on "
                             "any false regression")
    parser.add_argument("--json", action="store_true",
                        help="emit the verdict as one JSON object")
    args = parser.parse_args(argv)
    try:
        overrides = _parse_overrides(args.tolerance_for)
    except ValueError as e:
        print(f"usage error: {e}", file=sys.stderr)
        return 2

    if args.self_test:
        try:
            verdict = self_test(args.trajectory or None,
                                tolerance=args.tolerance,
                                loss_tolerance=args.loss_tolerance)
        except (FileNotFoundError, OSError) as e:
            print(f"usage error: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(verdict))
        else:
            print(f"bench_gate self-test: {verdict['usable_rounds']} "
                  f"usable round(s), {verdict['compared']} comparison(s), "
                  f"{verdict['regressions']} regression(s), skipped "
                  f"{verdict['skipped']}")
        return 1 if verdict["regressions"] else 0

    if not args.fresh:
        parser.print_usage(sys.stderr)
        print("usage error: need a fresh bench record (or --self-test)",
              file=sys.stderr)
        return 2
    if args.fresh == "-":
        source: object = sys.stdin.read()
    else:
        try:
            with open(args.fresh, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            print(f"usage error: cannot read {args.fresh}: {e}",
                  file=sys.stderr)
            return 2
        try:
            source = json.loads(text)
        except json.JSONDecodeError:
            source = text  # raw bench stdout
    fresh_flat, details = flatten(source)
    if not fresh_flat:
        msg = "fresh bench produced no metric-bearing records"
        if args.allow_failed:
            print(f"bench_gate: PASS (degraded: {msg})")
            return 0
        print(f"bench_gate: FAIL — {msg}", file=sys.stderr)
        return 1
    verdict = gate_record(fresh_flat,
                          load_trajectory_flats(args.trajectory or None),
                          tolerance=args.tolerance,
                          loss_tolerance=args.loss_tolerance,
                          overrides=overrides)
    verdict["detail_rows_ignored"] = details
    if args.json:
        print(json.dumps(verdict))
    else:
        for r in verdict["regressions"]:
            if "cap" in r:
                print(f"REGRESSION {r['metric']}: {r['value']} over "
                      f"absolute cap {r['cap']}")
                continue
            print(f"REGRESSION {r['metric']}: {r['value']} vs "
                  f"{r['baseline']} ({r['baseline_round']}) — "
                  f"×{r['ratio']} beyond tolerance {r['tolerance']}")
        for r in verdict["improvements"]:
            print(f"improved   {r['metric']}: {r['value']} vs "
                  f"{r['baseline']} ({r['baseline_round']}) ×{r['ratio']}")
        for r in verdict["passes"]:
            if "cap" in r:
                print(f"ok         {r['metric']}: {r['value']} within "
                      f"absolute cap {r['cap']}")
                continue
            print(f"ok         {r['metric']}: {r['value']} vs "
                  f"{r['baseline']} ({r['baseline_round']}) ×{r['ratio']}")
        for name in verdict["new_metrics"]:
            print(f"new        {name}: no baseline in the trajectory")
        state = "FAIL" if verdict["regressions"] else "PASS"
        print(f"bench_gate: {state} ({verdict['compared']} compared, "
              f"{len(verdict['new_metrics'])} new, {details} detail "
              f"row(s) not gated)")
    return 1 if verdict["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
