#!/bin/bash
# First-chip-contact runbook as ONE command (VERDICT r4 #3): when the TPU
# tunnel comes back, run the full staged validation stack in priority
# order without spending the window deciding what to run.
#
#   bash tools/first_chip.sh [runs_dir]
#
# Order (each stage timeboxed; a hang in one stage must not eat the rest):
#   1. tools/_fused_validate.py  — numerics + fusedxremat A/B for all six
#      Pallas kernel families; ITS DATA decides the fused_bn_conv default
#   2. tools/_tpu_validate.py    — step semantics on the real chip
#   3. tools/_horizon_run.py     — config-1 B=256 horizon (minutes on-chip)
#   4. python bench.py           — the headline number, warm compile cache
#
# Every stage tees to $runs_dir/<stage>_tpu.log so a mid-run tunnel drop
# still leaves committed evidence. The persistent compile cache
# (.jax_cache/) carries compiles across stages and across reruns.
set -u
cd "$(dirname "$0")/.."
RUNS="${1:-runs}"
mkdir -p "$RUNS"
overall_rc=0

stage() { # name timeout_s cmd...
  local name="$1" cap="$2"; shift 2
  local log="$RUNS/${name}_tpu.log"
  echo "=== [$name] (cap ${cap}s) $* -> $log"
  # own process GROUP (setsid) + log-file redirect, no pipe: bench.py and
  # the tools spawn children; killing only the direct python would leave
  # orphans holding a tee pipe open and the stage would block past its cap
  setsid "$@" > "$log" 2>&1 &
  local pid=$! rc=0 waited=0
  while kill -0 "$pid" 2>/dev/null; do
    sleep 5; waited=$((waited + 5))
    if [ "$waited" -ge "$cap" ]; then
      kill -TERM -- "-$pid" 2>/dev/null; sleep 10
      kill -KILL -- "-$pid" 2>/dev/null
      rc=124; break
    fi
  done
  if [ "$rc" -ne 124 ]; then wait "$pid"; rc=$?; fi
  tail -25 "$log"
  echo "=== [$name] rc=$rc" | tee -a "$log"
  [ "$rc" -ne 0 ] && overall_rc=1
  return 0
}

# cheap liveness gate first: don't burn the stage caps on a dead tunnel
timeout -k 15 120 python bench.py --child --mode probe > "$RUNS/probe_tpu.log" 2>&1
cat "$RUNS/probe_tpu.log"
if ! grep -q '"value": [1-9]' "$RUNS/probe_tpu.log"; then
  echo "no live TPU (probe) — aborting first-chip stack" | tee -a "$RUNS/probe_tpu.log"
  exit 2
fi

stage fused_validate 1200 python tools/_fused_validate.py
stage tpu_validate    900 python tools/_tpu_validate.py
stage horizon        1800 python tools/_horizon_run.py
stage bench          1200 python bench.py
echo "first_chip stack done (rc=$overall_rc); commit $RUNS/*_tpu.log"
exit $overall_rc
