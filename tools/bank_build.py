#!/usr/bin/env python
"""Build a versioned kNN bank paired to one checkpoint step (ISSUE 16).

    # offline: load the encoder in-process and bulk re-embed
    python tools/bank_build.py --checkpoint runs/export/7000/encoder.npz \
        --bank-dir runs/bank --corpus runs/corpus.npz \
        --arch resnet_tiny --cifar-stem --image-size 32 \
        --shards 4 --workers 2

    # batch-lane: embed through a serve fleet ALREADY on the checkpoint
    python tools/bank_build.py --checkpoint runs/export/7000/encoder.npz \
        --bank-dir runs/bank --corpus runs/corpus.npz \
        --fleet-url http://127.0.0.1:8080

Output (the moco_tpu/serve/bankbuild.py layout): `<bank-dir>/<step>/
bank.npz` + `<bank-dir>/.integrity/<step>.json`, the manifest binding
the bank to the checkpoint's content hash and recording seeded probe
rows — what a dual-swapping replica verifies before rolling (engine,
bank) together. Shard files land atomically under `.build/` and a
re-run after a crash resumes from completed shards; the merge is in
dataset-index order, so the bytes are identical for any --shards value.

The corpus npz needs `images` [N,S,S,3] uint8 + `labels` [N]. --step
defaults to the checkpoint's parent directory name when that is a step
number (the PR 1 export layout).

With --telemetry-dir, build progress lands as `kind:"bank"` events
(build_start / shard_done / build_done) in events.jsonl for obsd and
telemetry_report.

Train-free by lint (mocolint R6/R11): the engine import happens only on
the offline path; batch-lane builds never load jax.

Exit codes (README table): 0 built · 45 bad flags/corpus/checkpoint.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.resilience.exitcodes import EXIT_CONFIG_ERROR, EXIT_OK  # noqa: E402
from moco_tpu.utils.logging import info  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--checkpoint", required=True,
                   help="exported encoder payload the corpus is embedded "
                        "with (the bank binds to its content hash)")
    p.add_argument("--step", type=int, default=-1,
                   help="checkpoint step the bank versions under; -1 "
                        "derives it from the checkpoint's parent dir "
                        "name (the PR 1 export layout)")
    p.add_argument("--bank-dir", required=True,
                   help="bank root: <bank-dir>/<step>/bank.npz + "
                        ".integrity/<step>.json")
    p.add_argument("--corpus", required=True,
                   help="npz with `images` [N,S,S,3] uint8 + `labels` [N]")
    p.add_argument("--fleet-url", default="",
                   help="batch-lane mode: embed via this serve fleet's "
                        "POST /v1/embed (it must already SERVE "
                        "--checkpoint); empty = offline in-process engine")
    p.add_argument("--arch", default="resnet50")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--cifar-stem", action="store_true")
    p.add_argument("--buckets", default="1,8,32,128",
                   help="offline engine's padded compile shapes")
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--workers", type=int, default=1)
    p.add_argument("--probe-rows", type=int, default=8,
                   help="seeded probe rows recorded in the manifest — "
                        "the swap-time space-agreement check")
    p.add_argument("--batch-rows", type=int, default=64,
                   help="rows per embed call inside one shard")
    p.add_argument("--telemetry-dir", default="",
                   help="emit kind:\"bank\" build events here")
    p.add_argument("--ann-cells", type=int, default=0,
                   help="also build the paired IVF ANN index (ISSUE 20): "
                        "a deterministic k-means coarse quantizer with "
                        "this many cells, written atomically next to "
                        "the bank with its own manifest binding "
                        "index -> bank -> checkpoint; 0 = no index")
    p.add_argument("--ann-kmeans-iters", type=int, default=10,
                   help="Lloyd iterations for the --ann-cells quantizer")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import numpy as np

    from moco_tpu.serve import bankbuild

    step = args.step
    if step < 0:
        parent = os.path.basename(os.path.dirname(
            os.path.abspath(args.checkpoint)))
        if not parent.isdigit():
            info("config error: --step not given and the checkpoint's "
                 f"parent dir {parent!r} is not a step number")
            return EXIT_CONFIG_ERROR
        step = int(parent)
    if not os.path.isfile(args.checkpoint):
        info(f"config error: no checkpoint at {args.checkpoint!r}")
        return EXIT_CONFIG_ERROR
    try:
        corpus = np.load(args.corpus)
        if "images" not in corpus or "labels" not in corpus:
            raise ValueError(
                f"--corpus {args.corpus!r} needs `images` [N,S,S,3] "
                "uint8 and `labels` [N] arrays"
            )
        images, labels = corpus["images"], corpus["labels"]
    except (OSError, ValueError, KeyError) as e:
        info(f"config error: {e}")
        return EXIT_CONFIG_ERROR

    if args.fleet_url:
        # batch-lane: the fleet's replicas do the embedding; this
        # process stays jax-free and a dead replica just retries the
        # shard through the router
        embed_fn = bankbuild.http_embed_fn(args.fleet_url)
        image_size = int(images.shape[1])
    else:
        try:
            buckets = tuple(
                int(b) for b in str(args.buckets).split(",") if b.strip()
            )
        except ValueError:
            info(f"config error: bad --buckets {args.buckets!r}")
            return EXIT_CONFIG_ERROR
        from moco_tpu.serve import EmbeddingEngine

        try:
            engine = EmbeddingEngine.from_checkpoint(
                args.checkpoint, args.arch, image_size=args.image_size,
                cifar_stem=args.cifar_stem, buckets=buckets,
            )
            engine.warmup()
        except (ValueError, OSError, KeyError) as e:
            info(f"config error: cannot load {args.checkpoint!r}: {e}")
            return EXIT_CONFIG_ERROR

        cap = buckets[-1]

        def embed_fn(batch):
            out = []
            for lo in range(0, len(batch), cap):
                out.append(engine.embed(batch[lo:lo + cap]))
            return np.concatenate(out, axis=0)

        image_size = args.image_size

    registry = None
    emit = None
    if args.telemetry_dir:
        from moco_tpu.telemetry.registry import (
            EVENTS_FILENAME,
            MetricsRegistry,
        )
        from moco_tpu.telemetry.trace import Tracer

        tracer = Tracer(args.telemetry_dir, "off", proc="bank_build")
        registry = MetricsRegistry(
            os.path.join(args.telemetry_dir, EVENTS_FILENAME),
            stamp={"run_id": tracer.run_id, "trace_id": tracer.trace_id},
            flush_every=1,
        )

        def emit(event, **fields):
            registry.emit("bank", event=event, **fields)

    try:
        manifest = bankbuild.build_bank(
            args.bank_dir, step, images, labels, embed_fn,
            checkpoint_path=args.checkpoint, image_size=image_size,
            shards=args.shards, workers=args.workers,
            probe_rows=args.probe_rows, batch_rows=args.batch_rows,
            emit=emit,
        )
    except (bankbuild.BankBuildError, OSError, ValueError) as e:
        info(f"bank build failed: {e}")
        if registry is not None:
            registry.close()
        return EXIT_CONFIG_ERROR
    if args.ann_cells:
        # the index is built AFTER (and bound to) the finished bank: a
        # fleet seeing a bank manifest without an index manifest knows
        # the build is still in flight and retries, never mispairs
        from moco_tpu.serve import ann as annmod

        try:
            ann_manifest = annmod.build_ann_index(
                args.bank_dir, step, cells=args.ann_cells,
                kmeans_iters=args.ann_kmeans_iters, emit=emit,
            )
        except (annmod.AnnIndexError, OSError, ValueError) as e:
            info(f"ann index build failed: {e}")
            if registry is not None:
                registry.close()
            return EXIT_CONFIG_ERROR
        info(
            f"ann index step {step}: {ann_manifest['cells']} cells over "
            f"{ann_manifest['rows']} rows -> "
            f"{annmod.ann_index_path(args.bank_dir, step)}"
        )
    if registry is not None:
        registry.close()
    info(
        f"bank step {step}: {manifest['rows']} rows x "
        f"{manifest['feat_dim']} dims in {manifest['shards']} shard(s) "
        f"-> {os.path.join(args.bank_dir, str(step), 'bank.npz')} "
        f"(manifest binds checkpoint "
        f"{manifest['checkpoint']['sha256'][:12]}...)"
    )
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
