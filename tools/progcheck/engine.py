"""progcheck's run loop: every check over every record, plus baseline.

Mirrors mocolint's Engine shape (instantiate checks fresh, run per-item
hooks then finalize, subtract the committed baseline) so adding a check
feels identical to adding a lint rule — the difference is only what the
hooks receive: a traced ProgramRecord instead of a parsed file.
"""

from __future__ import annotations

import dataclasses

from tools.mocolint import baseline as baseline_mod
from tools.progcheck.finding import Finding, sort_findings
from tools.progcheck.registry import all_checks


@dataclasses.dataclass
class Result:
    findings: list
    baselined: list
    programs_audited: int
    # check id -> programs it actually examined; a SELECTED check that
    # applied to zero programs is a silently-vacuous audit the caller
    # should surface (the CLI warns)
    checks_applied: dict = dataclasses.field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.findings


class Engine:
    def __init__(self, select: tuple[str, ...] | None = None):
        classes = all_checks()
        self._ids = [cid for cid in sorted(classes)
                     if select is None or cid in select]
        self._classes = classes

    def run(self, records, baseline_path: str | None = None) -> Result:
        # fresh instances per run (the registry contract): a check may
        # accumulate state across check_program() calls and flush it in
        # finalize() without leaking into the next run
        checks = [self._classes[cid]() for cid in self._ids]
        findings: list[Finding] = []
        applied: dict[str, int] = {}
        for check in checks:
            applied[check.id] = 0
            for rec in records:
                if check.applies(rec):
                    applied[check.id] += 1
                    findings.extend(check.check_program(rec))
            findings.extend(check.finalize(records))
        baselined: list[Finding] = []
        if baseline_path:
            counts = baseline_mod.load(baseline_path)
            kept, baselined = baseline_mod.apply(sort_findings(findings),
                                                 counts)
            findings = kept
        return Result(
            findings=sort_findings(findings),
            baselined=baselined,
            programs_audited=len(records),
            checks_applied=applied,
        )
