"""Jaxpr graph analysis: walking, data-flow reachability, taint.

Everything progcheck knows about a program it learns here, from the
pre-lowering jaxpr (collectives are still explicit named primitives at
this level; after SPMD partitioning they dissolve into HLO channels).
Three analyses, each recursive over sub-jaxprs (pjit bodies, shard_map
regions, cond branches, custom-vjp calls, remat):

  walk_eqns          — every equation with the set of mesh axes bound at
                       its position (shard_map pushes its mesh's axes).
  input_dependence   — for each program output, WHICH inputs it
                       transitively data-depends on. A gradient that is
                       structurally zero (the stop_gradient contract)
                       depends on NO input — that is the machine-checkable
                       form of "no differentiable path" (check P1).
  double_sum_reduces — sum-reduces (psum/pmean) whose operand derives,
                       through value-preserving ops only, from another
                       sum-reduce over the same axis: the double-reduced-
                       gradient hazard (check P3).

Positional primitives (`optimization_barrier`) map outputs to inputs
1:1 — treating them conservatively would make every chained-psum bucket
look double-reduced, since bucket i+1's input is barrier-tied to bucket
i's OUTPUT purely as a scheduling hint.
"""

from __future__ import annotations

import dataclasses
import warnings

with warnings.catch_warnings():
    warnings.simplefilter("ignore", DeprecationWarning)
    from jax import core as jax_core

# collectives whose payload crosses the interconnect (named-axis prims at
# the jaxpr level; psum appears as psum2 inside shard_map regions on this
# jax version)
SUM_REDUCE_PRIMS = frozenset({"psum", "psum2"})
COLLECTIVE_PRIMS = SUM_REDUCE_PRIMS | frozenset({
    "pmax", "pmin", "all_gather", "ppermute", "all_to_all",
    "reduce_scatter",
})
# host-boundary primitives that must never appear in a step program
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call",
})
# outputs depend only on the same-position input. The collectives matter:
# a tree-wide pmean is ONE multi-operand psum equation, and treating it
# conservatively would fuse the dependence of every gradient leaf in the
# tree — a structurally-zero key-encoder grad would inherit the query
# grads' inputs through the shared reduce.
POSITIONAL_PRIMS = frozenset({
    "optimization_barrier", "psum", "psum2", "pmax", "pmin", "all_gather",
    "ppermute", "pbroadcast", "pvary",
})
# ops through which a value stays "the same quantity" for taint purposes:
# elementwise arithmetic, dtype casts, and layout moves. A dot_general or
# reduction produces a NEW quantity and clears the taint — without this
# restriction, a forward-pass psum would taint every gradient computed
# from its outputs and the gradsync reduce would always look double.
VALUE_PRESERVING_PRIMS = frozenset({
    "add", "add_any", "sub", "mul", "div", "neg", "sign", "abs", "max",
    "min", "select_n", "clamp", "convert_element_type", "reshape",
    "transpose", "squeeze", "broadcast_in_dim", "slice", "dynamic_slice",
    "concatenate", "copy", "stop_gradient", "integer_pow", "pow",
    "optimization_barrier", "rev", "expand_dims", "pad",
    # shard_map's check_rep rewrite inserts identity replication
    # adjustments between collectives — values pass through unchanged
    "pbroadcast", "pvary",
})


def _sub_jaxprs(eqn):
    """Every jaxpr hiding in an equation's params, as plain Jaxprs."""
    out = []
    for sub in jax_core.jaxprs_in_params(eqn.params):
        out.append(sub.jaxpr if hasattr(sub, "jaxpr") else sub)
    return out


def _shard_map_axes(eqn) -> frozenset[str]:
    mesh = eqn.params.get("mesh")
    names = getattr(mesh, "axis_names", None)
    return frozenset(str(a) for a in names) if names else frozenset()


def walk_eqns(closed_jaxpr):
    """Yield `(eqn, bound_axes)` for every equation, depth-first through
    sub-jaxprs; `bound_axes` is the frozenset of mesh axis names in scope
    (pushed by enclosing shard_map equations)."""
    def walk(jaxpr, bound):
        for eqn in jaxpr.eqns:
            yield eqn, bound
            inner = bound
            if eqn.primitive.name == "shard_map":
                inner = bound | _shard_map_axes(eqn)
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub, inner)

    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    yield from walk(jaxpr, frozenset())


def named_axes(eqn) -> tuple[str, ...]:
    """The named mesh axes a collective reduces/gathers over (positional
    axis ints are filtered out)."""
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name", ())
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes if isinstance(a, str))


@dataclasses.dataclass
class CollectiveOp:
    prim: str
    axes: tuple[str, ...]
    operand_dtypes: tuple[str, ...]
    operand_elems: int          # total elements across operands
    operand_bytes: int          # total bytes across operands (native dtype)

    def json_obj(self) -> dict:
        return dataclasses.asdict(self)


def collect_collectives(closed_jaxpr) -> list[CollectiveOp]:
    """Every collective equation in the program, with its native operand
    payload (what the wire would carry at the operand's own dtype)."""
    out = []
    for eqn, _bound in walk_eqns(closed_jaxpr):
        if eqn.primitive.name not in COLLECTIVE_PRIMS:
            continue
        avals = [v.aval for v in eqn.invars
                 if not isinstance(v, jax_core.Literal)]
        elems = sum(int(_size(a)) for a in avals)
        nbytes = sum(int(_size(a)) * _itemsize(a) for a in avals)
        out.append(CollectiveOp(
            prim=eqn.primitive.name,
            axes=named_axes(eqn),
            operand_dtypes=tuple(sorted({str(a.dtype) for a in avals})),
            operand_elems=elems,
            operand_bytes=nbytes,
        ))
    return out


def _size(aval) -> int:
    size = 1
    for d in getattr(aval, "shape", ()):
        size *= int(d)
    return size


def _itemsize(aval) -> int:
    try:
        return int(aval.dtype.itemsize)
    except (AttributeError, TypeError):
        return 4  # extended dtypes (PRNG keys): irrelevant to wire math


# ---------------------------------------------------------------------------
# input dependence
# ---------------------------------------------------------------------------


def input_dependence(closed_jaxpr) -> list[set[int]]:
    """For each flat output of the program, the set of flat-input indices
    it transitively data-depends on. Literals and consts contribute
    nothing, so a materialized zero-gradient (symbolic zero from a
    stop_gradient cotangent) yields an empty set.

    Call-like equations (one sub-jaxpr, arity-matched) map positionally;
    `cond` unions its branches plus the predicate; anything else —
    including `scan`/`while`, which none of the audited invariants need
    to see through precisely — is treated conservatively (every output
    depends on every input), which can only over-report dependence,
    never hide it."""
    memo: dict[int, list[set[int]]] = {}

    def deps_of(jaxpr) -> list[set[int]]:
        key = id(jaxpr)
        if key in memo:
            return memo[key]
        env: dict = {}
        for i, v in enumerate(jaxpr.invars):
            env[v] = {i}
        for v in jaxpr.constvars:
            env[v] = set()

        def read(v) -> set[int]:
            if isinstance(v, jax_core.Literal):
                return set()
            return env.get(v, set())

        for eqn in jaxpr.eqns:
            in_sets = [read(v) for v in eqn.invars]
            name = eqn.primitive.name
            subs = _sub_jaxprs(eqn)
            if name in POSITIONAL_PRIMS and len(eqn.outvars) == len(eqn.invars):
                outs = list(in_sets)
            elif name == "cond" and len(subs) >= 1:
                pred, ops = in_sets[0], in_sets[1:]
                outs = None
                for sub in subs:
                    mapped = _map_through(deps_of(sub), ops)
                    outs = mapped if outs is None else [
                        a | b for a, b in zip(outs, mapped)
                    ]
                outs = [o | pred for o in outs]
            elif (len(subs) == 1 and len(subs[0].invars) == len(eqn.invars)
                  and len(subs[0].outvars) == len(eqn.outvars)):
                outs = _map_through(deps_of(subs[0]), in_sets)
            else:
                union: set[int] = set()
                for s in in_sets:
                    union |= s
                outs = [set(union) for _ in eqn.outvars]
            for v, s in zip(eqn.outvars, outs):
                env[v] = s
        result = [read(v) for v in jaxpr.outvars]
        memo[key] = result
        return result

    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    return deps_of(jaxpr)


def _map_through(inner: list[set[int]], in_sets: list[set[int]]) -> list[set[int]]:
    out = []
    for dep in inner:
        s: set[int] = set()
        for i in dep:
            if i < len(in_sets):
                s |= in_sets[i]
        out.append(s)
    return out


# ---------------------------------------------------------------------------
# double sum-reduce taint
# ---------------------------------------------------------------------------


def double_sum_reduces(closed_jaxpr) -> list[tuple[str, str]]:
    """`(prim, axis)` for every sum-reduce whose operand is, through
    value-preserving ops only, derived from another sum-reduce over the
    same named axis — reducing an already-reduced quantity again (the
    double-reduced gradient: grads end up scaled by n²... or by n, twice).

    Taint = set of axis names the value has already been sum-reduced
    over. It survives elementwise arithmetic, casts, and layout moves
    (`pmean`'s trailing div, bucket slicing/concat) and dies at anything
    that builds a NEW quantity (dot_general, reductions, forwards), so a
    loss that legitimately contains a psum does not taint the gradients
    computed from it."""
    violations: list[tuple[str, str]] = []

    def run(jaxpr, in_taints: list[frozenset]) -> list[frozenset]:
        env: dict = {}
        for v, t in zip(jaxpr.invars, in_taints):
            env[v] = t
        for v in jaxpr.constvars:
            env[v] = frozenset()

        def read(v) -> frozenset:
            if isinstance(v, jax_core.Literal):
                return frozenset()
            return env.get(v, frozenset())

        for eqn in jaxpr.eqns:
            in_ts = [read(v) for v in eqn.invars]
            name = eqn.primitive.name
            subs = _sub_jaxprs(eqn)
            if name in SUM_REDUCE_PRIMS:
                axes = frozenset(named_axes(eqn))
                # operands map to outputs 1:1 — taint per operand, so one
                # already-reduced leaf cannot smear its siblings
                if len(in_ts) == len(eqn.outvars):
                    per_operand = in_ts
                else:
                    union = frozenset().union(*in_ts) if in_ts else frozenset()
                    per_operand = [union for _ in eqn.outvars]
                outs = []
                for t in per_operand:
                    for ax in axes:
                        if ax in t:
                            violations.append((name, ax))
                    outs.append(t | axes)
            elif name in POSITIONAL_PRIMS and len(eqn.outvars) == len(eqn.invars):
                outs = list(in_ts)
            elif name == "cond" and subs:
                ops = in_ts[1:]
                outs = None
                for sub in subs:
                    mapped = run(sub, list(ops) + [frozenset()] * max(
                        0, len(sub.invars) - len(ops)))
                    outs = mapped if outs is None else [
                        a | b for a, b in zip(outs, mapped)
                    ]
            elif (len(subs) == 1 and len(subs[0].invars) == len(eqn.invars)
                  and len(subs[0].outvars) == len(eqn.outvars)):
                outs = run(subs[0], in_ts)
            elif name in VALUE_PRESERVING_PRIMS:
                union = frozenset().union(*in_ts) if in_ts else frozenset()
                outs = [union for _ in eqn.outvars]
            else:
                # a new quantity: taint does not survive
                for sub in subs:  # still scan inner programs for violations
                    run(sub, [frozenset()] * len(sub.invars))
                outs = [frozenset() for _ in eqn.outvars]
            for v, t in zip(eqn.outvars, outs):
                env[v] = t
        return [read(v) for v in jaxpr.outvars]

    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    run(jaxpr, [frozenset() for _ in jaxpr.invars])
    return violations


# ---------------------------------------------------------------------------
# producer tracing (dtype-policy checks)
# ---------------------------------------------------------------------------


def build_producers(jaxpr) -> dict:
    """var -> producing eqn, for ONE jaxpr level (no recursion — callers
    walk levels via walk_eqns and inspect each level's local graph)."""
    producers: dict = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            producers[v] = eqn
    return producers


def trace_back(var, producers, through=("reshape", "concatenate",
                                        "transpose", "squeeze", "copy")):
    """Follow `var` backwards through pure layout ops; returns the first
    producing eqn that is NOT a layout op (None for inputs/literals)."""
    seen = 0
    while seen < 1000:
        seen += 1
        eqn = producers.get(var)
        if eqn is None:
            return None
        if eqn.primitive.name in through:
            nonlit = [v for v in eqn.invars
                      if not isinstance(v, jax_core.Literal)]
            if len(nonlit) != 1:
                return eqn  # concat of several: stop here, caller inspects
            var = nonlit[0]
            continue
        return eqn
    return None


def iter_jaxprs(closed_jaxpr):
    """Yield every (sub)jaxpr level, outermost first."""
    def walk(jaxpr):
        yield jaxpr
        for eqn in jaxpr.eqns:
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub)

    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    yield from walk(jaxpr)
