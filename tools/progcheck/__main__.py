import sys

from tools.progcheck.cli import main

if __name__ == "__main__":
    sys.exit(main())
