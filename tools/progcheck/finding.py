"""progcheck's finding record.

Field-compatible with mocolint's Finding (path/line/rule/message) so the
baseline machinery (tools/mocolint/baseline.py) fingerprints both — but a
progcheck finding anchors to a PROGRAM, not a source line: `path` holds
the program name (e.g. "train/quantized") and `line` is always 0.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str            # program name ("family/mode" or "family/variant")
    line: int            # always 0 — programs have no lines
    rule: str            # check id (P1..P9)
    message: str
    col: int = 0
    severity: str = "error"

    @property
    def program(self) -> str:
        return self.path

    def human(self) -> str:
        return f"{self.path}: {self.rule} {self.message}"

    def json_obj(self) -> dict:
        return {
            "program": self.path,
            "check": self.rule,
            "severity": self.severity,
            "message": self.message,
        }


def sort_findings(findings: list[Finding]) -> list[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.rule, f.message))
