"""progcheck: jaxpr-level program auditor (ISSUE 9 tentpole).

mocolint (tools/mocolint) guards SOURCE-level contracts; the invariants
that actually define MoCo correctness live in the traced program, where
the AST cannot see them: no gradient flows into the key encoder (He et
al.), the queue/EMA updates are non-differentiable, the configured
gradient sync moves exactly the payload its telemetry claims, step
programs host no callbacks, donated state really aliases.

progcheck enumerates the repo's full compiled-program surface (train/v3
steps under every grad_sync mode, the serve bucket ladder, h2d_trim
shape variants, eval programs — tools/progcheck/surface.py) via abstract
tracing (`jax.make_jaxpr` over `eval_shape`-built states: no weights are
initialized, no program runs), then runs pluggable semantic checks over
every jaxpr (tools/progcheck/checks/). The per-program inventory (shape
signature, `cost_analysis` FLOPs, collective payload bytes) doubles as
the seed data for the planned CompiledRegistry (ROADMAP item 5).

Structured like mocolint on purpose: check registry with metadata,
`--list-checks`, `--select`, committed baseline, `--json`, exit 0/1/2.
"""
