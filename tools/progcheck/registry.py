"""Check registry: every program check is a plugin class registered by id.

A check declares WHAT invariant it verifies (metadata: id, title,
severity, rationale — `--list-checks` renders them) and implements up to
two hooks, both generators of `Finding`:

  check_program(record)  — called once per ProgramRecord whose family
                           appears in `families` (empty = every program).
                           The common case: one jaxpr, one verdict.
  finalize(inventory)    — called once per run with every record, for
                           cross-program invariants (the bounded
                           compile-set check).

Checks are instantiated fresh per Engine run (mirroring mocolint's rule
contract), so a check may accumulate state across check_program() calls
and flush it in finalize().
"""

from __future__ import annotations

from tools.progcheck.finding import Finding


class Check:
    """Base class; subclasses override the metadata and hooks."""

    id: str = ""
    title: str = ""
    severity: str = "error"
    rationale: str = ""
    families: tuple = ()   # empty = audit every program

    def applies(self, record) -> bool:
        return not self.families or record.family in self.families

    def check_program(self, record):
        return ()

    def finalize(self, inventory):
        return ()

    def finding(self, record_or_name, message: str) -> Finding:
        name = getattr(record_or_name, "name", record_or_name)
        return Finding(path=name, line=0, rule=self.id, message=message,
                       severity=self.severity)


_CHECKS: dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator: adds the check to the global registry."""
    if not cls.id:
        raise ValueError(f"check {cls.__name__} has no id")
    if cls.id in _CHECKS:
        raise ValueError(f"duplicate check id {cls.id}")
    _CHECKS[cls.id] = cls
    return cls


def all_checks() -> dict[str, type]:
    """id -> class, after ensuring the built-in check modules loaded."""
    import tools.progcheck.checks  # noqa: F401  (registration side effect)

    return dict(_CHECKS)
