"""progcheck CLI.

    python -m tools.progcheck                      # audit the full surface
        --json                 machine output (schema below)
        --families train,v3    limit the traced surface
        --select P1,P3         run only these checks
        --list-checks          print the check table and exit
        --baseline PATH        subtract grandfathered findings
        --write-baseline PATH  snapshot current findings and exit 0
        --inventory PATH       also write the program inventory JSON
        --write-golden PATH    write the train/v3 invariant-summary golden
        --fake-devices N       mesh size (default 8 fake CPU devices)
        --no-flops             skip XLA cost_analysis (faster)

Exit codes: 0 clean, 1 findings, 2 usage/config error.

JSON schema (version 1):
    {"version": 1, "tool": "progcheck", "programs_audited": N,
     "findings": [{"program","check","severity","message"}...],
     "baselined": N, "inventory": {...}}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _bootstrap_path() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if repo not in sys.path:
        sys.path.insert(0, repo)


def main(argv: list[str] | None = None) -> int:
    _bootstrap_path()
    from tools.progcheck.registry import all_checks

    parser = argparse.ArgumentParser(
        prog="progcheck", description="moco_tpu jaxpr-level program auditor")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--families", default=None,
                        help="comma-separated program families")
    parser.add_argument("--select", default=None,
                        help="comma-separated check ids")
    parser.add_argument("--list-checks", action="store_true")
    parser.add_argument("--baseline", default=None)
    parser.add_argument("--write-baseline", default=None)
    parser.add_argument("--inventory", default=None)
    parser.add_argument("--write-golden", default=None)
    parser.add_argument("--fake-devices", type=int, default=8)
    parser.add_argument("--no-flops", action="store_true")
    args = parser.parse_args(argv)

    if args.list_checks:
        for cid, cls in sorted(all_checks().items(),
                               key=lambda kv: (len(kv[0]), kv[0])):
            scope = ",".join(cls.families) if cls.families else "all programs"
            print(f"{cid:<4} [{cls.severity}] {cls.title}  ({scope})")
            print(f"     why: {cls.rationale}")
        return 0

    select = None
    if args.select:
        select = tuple(s.strip() for s in args.select.split(",") if s.strip())
        unknown = [s for s in select if s not in all_checks()]
        if unknown:
            print(f"progcheck: unknown check id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
    families = None
    if args.families:
        families = tuple(f.strip() for f in args.families.split(",")
                         if f.strip())

    # the program surface needs a multi-device mesh; fake CPU devices give
    # real collective semantics (the test-suite convention). Must happen
    # before the first backend query, so before build_surface imports land.
    if args.fake_devices:
        from moco_tpu.parallel.mesh import force_cpu_devices

        force_cpu_devices(args.fake_devices)

    from moco_tpu.parallel.mesh import create_mesh
    from tools.progcheck.engine import Engine
    from tools.progcheck.inventory import (
        golden_json,
        inventory_json,
        write_inventory,
    )
    from tools.progcheck.surface import build_surface

    t0 = time.perf_counter()
    try:
        mesh = create_mesh()
        records = build_surface(mesh=mesh, families=families,
                                with_cost=not args.no_flops)
    except ValueError as e:
        print(f"progcheck: {e}", file=sys.stderr)
        return 2
    trace_s = time.perf_counter() - t0

    if args.inventory:
        write_inventory(args.inventory, records, mesh.size)
    if args.write_golden:
        with open(args.write_golden, "w", encoding="utf-8") as f:
            json.dump(golden_json(records, mesh.size), f, indent=2,
                      sort_keys=True)
            f.write("\n")

    engine = Engine(select=select)
    if args.write_baseline:
        result = engine.run(records, baseline_path=None)
        from tools.mocolint import baseline as baseline_mod

        n = baseline_mod.write(args.write_baseline, result.findings)
        print(f"wrote baseline of {n} finding(s) to {args.write_baseline}")
        return 0
    try:
        result = engine.run(records, baseline_path=args.baseline)
    except (OSError, ValueError) as e:
        print(f"progcheck: {e}", file=sys.stderr)
        return 2
    audit_s = time.perf_counter() - t0 - trace_s

    if select:
        # an explicitly-selected check that examined zero programs is a
        # vacuous audit, not a pass — say so (family-scoped checks need
        # their family in --families; P1 needs "probe", P8 "gradsync")
        vacuous = [cid for cid in select
                   if result.checks_applied.get(cid, 0) == 0]
        if vacuous:
            print(
                f"progcheck: warning: selected check(s) "
                f"{', '.join(vacuous)} matched no program in the traced "
                "surface — nothing was verified by them (add their "
                "family to --families)",
                file=sys.stderr,
            )

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "tool": "progcheck",
            "programs_audited": result.programs_audited,
            "trace_s": round(trace_s, 3),
            "audit_s": round(audit_s, 3),
            "findings": [f.json_obj() for f in result.findings],
            "baselined": len(result.baselined),
            "inventory": inventory_json(records, mesh.size),
        }, indent=2))
        return 1 if result.findings else 0

    for f in result.findings:
        print(f.human())
    tail = f" ({len(result.baselined)} baselined)" if result.baselined else ""
    if result.findings:
        print(f"{len(result.findings)} finding(s) over "
              f"{result.programs_audited} program(s){tail}")
        return 1
    print(f"progcheck clean: {result.programs_audited} program(s) audited "
          f"in {trace_s + audit_s:.1f} s (trace {trace_s:.1f} s){tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
